"""Async buffered aggregation (core/async_agg.py): staleness-discount
math against numpy references, merge linearity (out-of-order == in-order),
the K=1/M=1 sync-equivalence bit-identity contract, the unsound-mode
fail-fast guard, buffer checkpoint/resume semantics (loud restart, never
a silent double-count; cross-vintage explanatory errors), the schema-v4
``async_round`` event + health rules, and the teleview staleness gates."""

import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.checkpoint import CheckpointManager, load_state, \
    save_state
from commefficient_tpu.config import FedConfig
from commefficient_tpu.core import (AsyncAggregator, FedRuntime,
                                    staleness_weight, validate_async_combo)
from commefficient_tpu.core.async_agg import (commit_loss,
                                              reconcile_resumed_state)
from commefficient_tpu.data.fed_sampler import Round
from commefficient_tpu.data.scenarios import CohortFate
from tests.test_parallel import make_batch, quad_loss

W, B = 4, 4


def make_cfg(**kw):
    base = dict(mode="sketch", error_type="virtual", k=5, num_rows=3,
                num_cols=32, num_blocks=2, sketch_impl="hash",
                local_momentum=0.0, virtual_momentum=0.9,
                weight_decay=0.0, num_workers=W, local_batch_size=B,
                track_bytes=True, num_clients=16)
    base.update(kw)
    return FedConfig(**base)


def make_params(seed=0):
    return {"w": jnp.asarray(np.random.RandomState(seed).randn(6, 3),
                             jnp.float32)}


def make_round(seed):
    batch, mask, ids = make_batch(seed, W=W, B=B)
    return Round(np.asarray(ids, np.int64),
                 np.zeros((W, B), np.int64), np.asarray(mask)), batch


class FixedScenario:
    """Prescribed per-cohort fates, keyed by cohort index (test stub)."""

    def __init__(self, latencies=(), dropped=(), masks=None):
        self.latencies = dict(latencies)
        self.dropped = set(dropped)
        self.masks = masks or {}

    def fate(self, cohort_idx, mask, client_ids=None):
        return CohortFate(float(self.latencies.get(cohort_idx, 0.0)),
                          cohort_idx in self.dropped,
                          self.masks.get(cohort_idx, mask))


# ------------------------------------------------------------ staleness math


def test_staleness_weight_numpy_reference():
    for s in (0, 1, 2, 5, 17):
        assert staleness_weight("none", s) == 1.0
        for alpha in (0.25, 0.5, 2.0):
            np.testing.assert_allclose(
                staleness_weight("poly", s, alpha),
                (1.0 + s) ** (-alpha), rtol=1e-12)
            np.testing.assert_allclose(
                staleness_weight("exp", s, alpha),
                math.exp(-alpha * s), rtol=1e-12)


def test_staleness_weight_one_at_zero_and_monotone():
    """Weight EXACTLY 1.0 at s=0 (the sync-equivalence contract) and
    strictly decreasing in s for the discounting rules."""
    for rule in ("none", "poly", "exp"):
        assert staleness_weight(rule, 0) == 1.0
    for rule in ("poly", "exp"):
        ws = [staleness_weight(rule, s, 0.5) for s in range(8)]
        assert all(a > b for a, b in zip(ws, ws[1:]))
    with pytest.raises(ValueError):
        staleness_weight("linear", 1)
    with pytest.raises(ValueError):
        staleness_weight("poly", -1)


# -------------------------------------------------------------- merge algebra


def test_out_of_order_merge_equals_in_order_numpy():
    """Sketch linearity at the merge level: the buffer arithmetic
    (buffer + w*S, exactly what FedRuntime._merge_step computes) is
    order-independent for exactly-representable values — merging the
    same cohort sums in any arrival order commits the same aggregate."""
    rng = np.random.RandomState(0)
    sums = [rng.randint(-8, 8, (3, 32)).astype(np.float32)
            for _ in range(4)]
    weights = [1.0, 0.5, 0.25, 1.0]   # exact binary fractions

    def merge_all(order):
        buf = np.zeros((3, 32), np.float32)
        for i in order:
            buf = buf + np.float32(weights[i]) * sums[i]
        return buf

    ref = merge_all([0, 1, 2, 3])
    for order in ([3, 2, 1, 0], [1, 3, 0, 2], [2, 0, 3, 1]):
        np.testing.assert_array_equal(ref, merge_all(order))


def test_out_of_order_merge_matches_runtime():
    """End-to-end: the SAME three cohorts landing in different arrival
    orders (no commit between — M=3 — so staleness is 0 either way)
    commit the same weights up to float summation order."""
    params = make_params()

    def run(latencies):
        cfg = make_cfg(async_agg=True, max_inflight=3, buffer_goal=3,
                       staleness_discount="none")
        rt = FedRuntime(cfg, params, quad_loss, num_clients=16)
        agg = AsyncAggregator(rt, scenario=FixedScenario(latencies))
        state = rt.init_state()
        all_commits = []
        for g in range(1, 4):
            rnd, batch = make_round(g)
            state, _, commits = agg.step(state, rnd, g, batch, 0.1)
            all_commits.extend(commits)
        state, commits = agg.flush(state, 0.1)
        all_commits.extend(commits)
        assert len(all_commits) == 1 and all_commits[0]["n_cohorts"] == 3
        return np.asarray(rt.flat_weights(state)), all_commits[0]

    w_inorder, c_a = run({})                       # arrival 1, 2, 3
    w_reorder, c_b = run({1: 5.0, 2: 3.0})         # arrival 3, 2, 1
    assert c_a["cohorts"] == [1, 2, 3]
    assert c_b["cohorts"] == [3, 2, 1]
    np.testing.assert_allclose(w_inorder, w_reorder, rtol=2e-5, atol=1e-7)


# ------------------------------------------------------------ sync equivalence


@pytest.mark.parametrize("mode,extra", [
    ("sketch", {}),
    ("uncompressed", {"error_type": "none"}),
    ("true_topk", {"error_type": "virtual"}),
])
def test_sync_equivalence_bit_identical(mode, extra):
    """K=1, M=1, no scenario: every cohort lands and commits in its own
    tick with staleness 0 — losses and final weights must be BITWISE
    equal to the inline fused round (all discount rules give weight
    exactly 1.0 at s=0; the first-merge path adds no arithmetic)."""
    params = make_params()
    cfg = make_cfg(mode=mode, **extra)
    rt_sync = FedRuntime(cfg, params, quad_loss, num_clients=16)
    st_sync = rt_sync.init_state()
    sync_losses = []
    for g in range(1, 6):
        rnd, batch = make_round(g)
        st_sync, m = rt_sync.round(st_sync, rnd.client_ids, batch,
                                   rnd.mask, 0.1)
        sync_losses.append(np.asarray(m["results"][0]))

    rt_a = FedRuntime(cfg.replace(async_agg=True, max_inflight=1,
                                  buffer_goal=1),
                      params, quad_loss, num_clients=16)
    st_a = rt_a.init_state()
    agg = AsyncAggregator(rt_a)
    async_losses = []
    for g in range(1, 6):
        rnd, batch = make_round(g)
        st_a, m, commits = agg.step(st_a, rnd, g, batch, 0.1)
        async_losses.append(np.asarray(m["results"][0]))
        assert len(commits) == 1
        assert commits[0]["staleness_max"] == 0
        assert commits[0]["discount_min"] == 1.0
    st_a, leftover = agg.flush(st_a, 0.1)
    assert not leftover
    assert (np.stack(sync_losses) == np.stack(async_losses)).all()
    np.testing.assert_array_equal(
        np.asarray(rt_sync.flat_weights(st_sync)),
        np.asarray(rt_a.flat_weights(st_a)))


# ------------------------------------------------------- discounting dynamics


def test_staleness_discount_attenuates_stale_cohorts():
    """A cohort landing 2 commits stale under exp(-50*s) contributes
    ~nothing: its commit's update norm collapses vs discount none, and
    the denominator stays the RAW datum count (the discount must not
    cancel between numerator and denominator). Momentum-free
    uncompressed mode isolates the commit to THIS cohort's aggregate —
    with EF/momentum the server state legitimately carries residual
    mass across commits and the norm would not vanish."""
    params = make_params()

    def run(discount, alpha=50.0):
        cfg = make_cfg(mode="uncompressed", error_type="none",
                       virtual_momentum=0.0, async_agg=True,
                       max_inflight=2, buffer_goal=1,
                       staleness_discount=discount,
                       staleness_alpha=alpha)
        rt = FedRuntime(cfg, params, quad_loss, num_clients=16)
        # cohort 1 is slow (arrival tick 4); cohorts 2 and 3 land and
        # commit immediately, so cohort 1 merges 2 commits stale
        agg = AsyncAggregator(rt, scenario=FixedScenario({1: 3.0}))
        state = rt.init_state()
        all_commits = []
        for g in range(1, 4):
            rnd, batch = make_round(g)
            state, _, cms = agg.step(state, rnd, g, batch, 0.1)
            all_commits.extend(cms)
        state, cms = agg.flush(state, 0.1)
        all_commits.extend(cms)
        stale = [c for c in all_commits if c["staleness_max"] > 0]
        assert len(stale) == 1 and stale[0]["cohorts"] == [1]
        return float(np.asarray(stale[0]["update_norm"])), stale[0]

    norm_plain, rec_plain = run("none")
    norm_exp, rec_exp = run("exp")
    assert rec_plain["discount_min"] == 1.0
    assert rec_exp["discount_min"] == pytest.approx(math.exp(-100.0))
    assert norm_exp < norm_plain * 1e-3, (norm_exp, norm_plain)


def test_inflight_pool_bound_and_dropout():
    """The pool never exceeds K (dispatching past it forces the
    earliest arrival to land first), and a dropped cohort computes
    nothing: metrics is None, nothing merges, weights stay put."""
    params = make_params()
    cfg = make_cfg(async_agg=True, max_inflight=2, buffer_goal=4)
    rt = FedRuntime(cfg, params, quad_loss, num_clients=16)
    agg = AsyncAggregator(rt,
                          scenario=FixedScenario({g: 100.0
                                                  for g in range(1, 9)}))
    state = rt.init_state()
    for g in range(1, 7):
        rnd, batch = make_round(g)
        state, m, _ = agg.step(state, rnd, g, batch, 0.1)
        assert m is not None
        assert agg.inflight <= 2
    assert agg.merged == 4  # 6 dispatched, pool of 2 forced 4 landings

    cfg2 = make_cfg(async_agg=True, max_inflight=1, buffer_goal=1)
    rt2 = FedRuntime(cfg2, params, quad_loss, num_clients=16)
    agg2 = AsyncAggregator(rt2, scenario=FixedScenario(dropped={1, 2}))
    st = rt2.init_state()
    w0 = np.asarray(rt2.flat_weights(st))
    for g in (1, 2):
        rnd, batch = make_round(g)
        st, m, commits = agg2.step(st, rnd, g, batch, 0.1)
        assert m is None and commits == []
    assert agg2.dropped == 2 and agg2.dispatched == 0
    np.testing.assert_array_equal(w0, np.asarray(rt2.flat_weights(st)))


def test_dropped_cohort_never_evicts_pool_slot():
    """A dropped cohort needs no pool slot, so it must not force the
    earliest in-flight cohort to land early (which would skew the
    measured staleness/merge order) — the fate check runs BEFORE the
    pool-full wait."""
    params = make_params()
    cfg = make_cfg(async_agg=True, max_inflight=1, buffer_goal=8)
    rt = FedRuntime(cfg, params, quad_loss, num_clients=16)
    # cohort 1 is slow (arrival tick 11); cohort 2 is dropped; cohort 3
    # genuinely needs the slot and forces cohort 1 to land
    agg = AsyncAggregator(rt, scenario=FixedScenario({1: 10.0, 3: 10.0},
                                                     dropped={2}))
    state = rt.init_state()
    rnd, batch = make_round(1)
    state, _, _ = agg.step(state, rnd, 1, batch, 0.1)
    assert agg.inflight == 1
    rnd, batch = make_round(2)
    state, m, _ = agg.step(state, rnd, 2, batch, 0.1)
    assert m is None
    assert agg.inflight == 1 and agg.merged == 0  # slot NOT evicted
    rnd, batch = make_round(3)
    state, m, _ = agg.step(state, rnd, 3, batch, 0.1)
    assert m is not None
    assert agg.merged == 1      # now cohort 1 had to land...
    assert agg.inflight == 1    # ...making room for cohort 3


def test_signals_loudly_off_under_async(capsys):
    """--signals under --async_agg is not silently ignored: the runtime
    compiles the signal sites out AND says so on stderr (the async_round
    EF norms are the async health channel)."""
    cfg = make_cfg(async_agg=True, signals=True, telemetry=True)
    rt = FedRuntime(cfg, make_params(), quad_loss, num_clients=16)
    assert rt._signals is False
    assert "disables the per-round `signals`" in capsys.readouterr().err
    # sync runtime from the same flags keeps them on
    rt2 = FedRuntime(make_cfg(signals=True), make_params(), quad_loss,
                     num_clients=16)
    assert rt2._signals is True


def test_flush_commits_partial_buffer():
    params = make_params()
    cfg = make_cfg(async_agg=True, max_inflight=4, buffer_goal=3)
    rt = FedRuntime(cfg, params, quad_loss, num_clients=16)
    agg = AsyncAggregator(rt)
    state = rt.init_state()
    for g in (1, 2):
        rnd, batch = make_round(g)
        state, _, commits = agg.step(state, rnd, g, batch, 0.1)
        assert not commits  # below the goal
    state, commits = agg.flush(state, 0.1)
    assert len(commits) == 1
    assert commits[0]["partial"] is True
    assert commits[0]["n_cohorts"] == 2
    assert commit_loss(commits[0]) is not None
    # the buffer is empty after the flush — nothing left to double-count
    assert float(np.asarray(state.async_buffer_n)) == 0.0
    assert agg.pending == 0 and agg.inflight == 0


# ------------------------------------------------------------ fail-fast guard


def test_unsound_modes_fail_fast():
    for kw in (dict(mode="local_topk", error_type="local",
                    local_momentum=0.9),
               dict(mode="uncompressed", error_type="none",
                    local_momentum=0.9),
               dict(mode="true_topk", error_type="virtual",
                    do_topk_down=True)):
        with pytest.raises(ValueError, match="buffered merge is unsound"):
            validate_async_combo(make_cfg(async_agg=True, **kw))
    # sound combinations pass
    validate_async_combo(make_cfg(async_agg=True))
    validate_async_combo(make_cfg(async_agg=True, mode="local_topk",
                                  error_type="none"))
    # and the guard runs at runtime construction too
    with pytest.raises(ValueError, match="buffered merge is unsound"):
        FedRuntime(make_cfg(async_agg=True, mode="local_topk",
                            error_type="local", local_momentum=0.9),
                   make_params(), quad_loss, num_clients=16)


# -------------------------------------------------------- checkpoint / resume


def _mid_buffer_state(rt, agg, n_rounds=2):
    state = rt.init_state()
    for g in range(1, n_rounds + 1):
        rnd, batch = make_round(g)
        state, _, _ = agg.step(state, rnd, g, batch, 0.1)
    return state


def test_buffer_roundtrips_through_checkpoint(tmp_path):
    """A mid-buffer FedState (e.g. a flight-recorder postmortem) saves
    and loads the buffer losslessly — the state is never silently
    truncated on disk."""
    params = make_params()
    cfg = make_cfg(async_agg=True, max_inflight=4, buffer_goal=4)
    rt = FedRuntime(cfg, params, quad_loss, num_clients=16)
    state = _mid_buffer_state(rt, AsyncAggregator(rt))
    assert float(np.asarray(state.async_buffer_n)) > 0
    path = str(tmp_path / "ck")
    save_state(path, state)
    loaded = load_state(path)
    np.testing.assert_array_equal(np.asarray(state.async_buffer),
                                  np.asarray(loaded.async_buffer))
    np.testing.assert_array_equal(np.asarray(state.async_buffer_n),
                                  np.asarray(loaded.async_buffer_n))


def test_resume_mid_buffer_loudly_restarts():
    """reconcile_resumed_state: a restored NON-EMPTY buffer is zeroed
    with a message naming the double-count hazard — the epoch replays
    from its boundary, so its cohorts will be recomputed."""
    params = make_params()
    cfg = make_cfg(async_agg=True, max_inflight=4, buffer_goal=4)
    rt = FedRuntime(cfg, params, quad_loss, num_clients=16)
    state = _mid_buffer_state(rt, AsyncAggregator(rt))
    state2, msgs = reconcile_resumed_state(state, rt)
    assert len(msgs) == 1 and "double-count" in msgs[0]
    assert float(np.asarray(state2.async_buffer_n)) == 0.0
    assert not np.asarray(state2.async_buffer).any()
    # an EMPTY restored buffer reconciles silently
    state3, msgs3 = reconcile_resumed_state(state2, rt)
    assert msgs3 == []


def test_resume_cross_vintage_explanatory_error(tmp_path):
    """Pre-async checkpoint into an --async_agg run: the meta guard
    raises the explanatory error BEFORE any state is materialized
    (the PR-1 sketch_gen pattern); --resume_unverified opts into a
    fresh, empty buffer via reconcile_resumed_state."""
    sync_cfg = make_cfg()
    rt_sync = FedRuntime(sync_cfg, make_params(), quad_loss,
                         num_clients=16)
    mgr = CheckpointManager(str(tmp_path / "ck"))
    mgr.default_meta = {"sketch_gen": None}  # pre-async vintage: no marker
    mgr.save(rt_sync.init_state(), epoch=1)

    with pytest.raises(ValueError) as e:
        mgr.restore_latest(expect_async_gen="v1-poly-a0.5-M2-K4")
    assert "predates async buffered aggregation" in str(e.value)
    assert "--resume_unverified" in str(e.value)

    # the opt-in loads; the async runtime then starts with a fresh buffer
    restored, _ = mgr.restore_latest(expect_async_gen="v1-poly-a0.5-M2-K4",
                                     async_mismatch_ok=True)
    assert restored.async_buffer is None
    rt_async = FedRuntime(make_cfg(async_agg=True), make_params(),
                          quad_loss, num_clients=16)
    restored, msgs = reconcile_resumed_state(restored, rt_async)
    assert restored.async_buffer is not None
    assert float(np.asarray(restored.async_buffer_n)) == 0.0
    assert any("EMPTY" in m for m in msgs)

    # changed async parameters only warn (commits are atomic)
    mgr.default_meta = {"async_gen": "v1-none-a0.5-M1-K1"}
    mgr.save(rt_sync.init_state(), epoch=2)
    restored, _ = mgr.restore_latest(expect_async_gen="v1-exp-a2.0-M4-K8")
    assert restored is not None

    # a sync run resuming an async checkpoint drops the buffer fields
    rt_a = FedRuntime(make_cfg(async_agg=True, max_inflight=4,
                               buffer_goal=4), make_params(), quad_loss,
                      num_clients=16)
    st = _mid_buffer_state(rt_a, AsyncAggregator(rt_a))
    st2, msgs2 = reconcile_resumed_state(st, rt_sync)
    assert st2.async_buffer is None and st2.async_buffer_n is None
    assert any("resumed synchronously" in m for m in msgs2)


# ------------------------------------------------------- telemetry integration


def _fake_commit_rec(rnd=1, error_norm=1.0, staleness=0.0):
    return {"round": rnd, "n_cohorts": 2, "cohorts": [rnd, rnd + 1],
            "staleness_mean": staleness, "staleness_max": staleness,
            "discount_mean": 1.0, "discount_min": 1.0, "partial": False,
            "buffer_n": np.float32(8.0),
            "update_norm": np.float32(0.5),
            "error_norm": np.float32(error_norm),
            "velocity_norm": np.float32(0.25),
            "loss_refs": [(np.full((W,), 2.0, np.float32),
                           np.full((W,), float(B), np.float32))]}


def test_async_round_event_schema_roundtrip(tmp_path):
    from commefficient_tpu.telemetry import RunTelemetry
    from commefficient_tpu.telemetry.schema import validate_file
    tel = RunTelemetry(str(tmp_path), "test", cfg=make_cfg())
    tel.async_round_event(rec=_fake_commit_rec(), lr=0.1, loss=2.0,
                          with_device=True)
    # off the record cadence: device fields stay null, never fake zeros
    tel.async_round_event(rec=_fake_commit_rec(rnd=2), lr=0.1, loss=None,
                          with_device=False)
    tel.write_summary(aborted=False, n_rounds=2)
    tel.close()
    assert validate_file(tel.path) == []
    evs = [json.loads(l) for l in open(tel.path)]
    ars = [e for e in evs if e["event"] == "async_round"]
    assert len(ars) == 2
    assert ars[0]["error_norm"] == pytest.approx(1.0)
    assert ars[1]["error_norm"] is None and ars[1]["buffer_n"] is None


def test_commit_loss_weighted_mean_and_nonfinite():
    rec = _fake_commit_rec()
    assert commit_loss(rec) == pytest.approx(2.0)
    rec["loss_refs"] = [(np.full((W,), np.nan, np.float32),
                         np.full((W,), 1.0, np.float32))]
    assert commit_loss(rec) is None
    assert commit_loss({"loss_refs": []}) is None


def test_async_ef_blowup_rule_fires(tmp_path):
    """The staleness-EF-divergence monitor rule: a flat error_norm
    history followed by a blowup on the async_round stream fires
    async_ef_blowup (critical) exactly once."""
    from commefficient_tpu.telemetry import AnomalyMonitor, RunTelemetry
    tel = RunTelemetry(str(tmp_path), "test", cfg=make_cfg())
    mon = AnomalyMonitor(tel, action="log", window=16, min_points=8)
    tel.set_monitor(mon)
    rng = np.random.RandomState(0)
    for r in range(1, 20):
        blow = 500.0 if r == 16 else 1.0 + 0.01 * rng.rand()
        tel.async_round_event(rec=_fake_commit_rec(rnd=r, error_norm=blow),
                              lr=0.1, loss=2.0, with_device=True)
    tel.close()
    fired = [a for a in mon.alerts if a["rule"] == "async_ef_blowup"]
    assert len(fired) == 1
    assert fired[0]["severity"] == "critical"
    assert fired[0]["metric"] == "async_round.error_norm"


# ------------------------------------------------------------ teleview gates


def _load_teleview():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "teleview", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts", "teleview.py"))
    tv = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tv)
    return tv


def test_teleview_async_keys_pinned_against_schema():
    """teleview must run jax-free, so its async_round field names are
    literals — pin them against the canonical schema vocabulary."""
    from commefficient_tpu.telemetry.schema import EVENT_FIELDS
    tv = _load_teleview()
    assert set(tv.ASYNC_ROUND_KEYS) <= set(EVENT_FIELDS["async_round"])


def _write_stream(path, staleness_mean, error_norm=1.0):
    events = [
        {"event": "manifest", "t": 0.0, "seq": 0, "schema": 4,
         "run_type": "cv_train", "jax_version": "0", "backend": "cpu",
         "device_kind": "cpu", "device_count": 1, "mesh_shape": [],
         "mesh_axes": [], "grad_size": 10, "sketch": None, "config": {}},
        {"event": "async_round", "t": 1.0, "seq": 1, "round": 1,
         "n_cohorts": 2, "cohorts": [1, 2],
         "staleness_mean": staleness_mean,
         "staleness_max": staleness_mean * 2, "discount_mean": 0.9,
         "discount_min": 0.8, "partial": False, "buffer_n": 8.0,
         "loss": 2.0, "update_norm": 0.5, "error_norm": error_norm,
         "velocity_norm": 0.2, "lr": 0.1},
        {"event": "summary", "t": 2.0, "seq": 2, "run_type": "cv_train",
         "aborted": False, "n_rounds": 1, "total_download_mib": None,
         "total_upload_mib": None, "wall_time_s": 1.0,
         "event_counts": {}, "final": None},
    ]
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    return str(path)


def test_teleview_staleness_rise_gate_and_summarize(tmp_path, capsys):
    tv = _load_teleview()
    a = _write_stream(tmp_path / "a.jsonl", staleness_mean=0.5)
    b = _write_stream(tmp_path / "b.jsonl", staleness_mean=3.0)
    assert tv.main(["diff", a, a]) == 0
    assert tv.main(["diff", a, b]) == 1
    out = capsys.readouterr().out
    assert "staleness_mean" in out
    # the summarize staleness line
    tv.main(["summarize", a])
    out = capsys.readouterr().out
    assert "-- async: 1 commits" in out
    # the EF-divergence ratio gate on the async stream
    c = _write_stream(tmp_path / "c.jsonl", staleness_mean=0.5,
                      error_norm=50.0)
    assert tv.main(["diff", a, c]) == 1
    assert "error_norm" in capsys.readouterr().out


# --------------------------------------------------------- driver integration


def test_driver_end_to_end_async(tmp_path, monkeypatch):
    """One cv_train.train epoch over synthetic CIFAR with async
    aggregation + a straggler scenario: schema-valid stream with
    async_round events carrying measured staleness, ledger staleness
    tracked in client_stats, finite summary, empty buffer at the end."""
    from commefficient_tpu import cv_train, models
    from commefficient_tpu.data import FedCIFAR10, transforms_for
    from commefficient_tpu.losses import make_cv_loss
    from commefficient_tpu.telemetry import RunTelemetry
    from commefficient_tpu.telemetry.schema import validate_file

    ds = FedCIFAR10(str(tmp_path / "d"), synthetic=True,
                    synthetic_per_class=8,
                    transform=transforms_for("CIFAR10", True, seed=0))
    cfg = FedConfig(mode="sketch", error_type="virtual", k=10, num_rows=2,
                    num_cols=64, num_blocks=2, sketch_impl="hash",
                    local_momentum=0.0, virtual_momentum=0.9,
                    num_workers=4, local_batch_size=4,
                    num_clients=ds.num_clients, num_epochs=1.0,
                    track_bytes=True, compute_dtype="float32",
                    telemetry=True, telemetry_every=1,
                    async_agg=True, max_inflight=3, buffer_goal=2,
                    scenario="stragglers", scenario_latency=1.0,
                    scenario_straggler_frac=0.25,
                    scenario_straggler_mult=5.0, scenario_dropout=0.1)
    model = models.ResNet9(num_classes=10,
                           channels={"prep": 2, "layer1": 2,
                                     "layer2": 2, "layer3": 2})
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 32, 32, 3)))
    rt = FedRuntime(cfg, params, make_cv_loss(model, "float32"),
                    num_clients=ds.num_clients)
    tel = RunTelemetry(str(tmp_path / "log"), "cv_train", cfg=rt.cfg)
    tel.instrument(rt)
    state, summary = cv_train.train(cfg, rt, rt.init_state(), ds, ds,
                                    telemetry=tel)
    tel.write_summary(aborted=False, n_rounds=1)
    tel.close()
    assert summary is not None and np.isfinite(summary["train_loss"])
    assert validate_file(tel.path) == []
    evs = [json.loads(l) for l in open(tel.path)]
    ars = [e for e in evs if e["event"] == "async_round"]
    assert ars, "no async_round events emitted"
    assert max(e["staleness_max"] for e in ars) > 0
    assert all(e["lr"] >= 0 for e in ars)
    cstats = [e for e in evs if e["event"] == "client_stats"]
    assert cstats and cstats[-1]["staleness_max"] is not None
    # the epoch-boundary flush left no open buffer behind
    assert float(np.asarray(state.async_buffer_n)) == 0.0
