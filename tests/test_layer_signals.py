"""Layer-wise compression attribution (telemetry/layer_signals.py +
ops/segments.py): group partition against a numpy reference on a small
pytree (conservation, range tiling, boundary and padding coordinates),
the in-round per-group signals across modes and topologies (null —
never fake-zero — contracts on the fused-encode and mesh paths), HLO
byte-identity with the groups off, the schema-v10 round-trip, the
group_starvation monitor rule, and the teleview layers/diff surface
(literal fallbacks pinned against the package)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.config import FedConfig
from commefficient_tpu.core import FedRuntime
from commefficient_tpu.telemetry import (LAYER_SIGNAL_KEYS, AnomalyMonitor,
                                         RunTelemetry,
                                         layer_signals_to_host,
                                         make_group_spec, signals_to_host,
                                         starved_groups, validate_event,
                                         validate_file)
from commefficient_tpu.telemetry.layer_signals import (STARVATION_MASS_SHARE,
                                                       STARVATION_WIN_SHARE,
                                                       STARVATION_WINDOW)

W, B, D_IN, D_OUT = 4, 4, 6, 3
D = D_IN * D_OUT + D_OUT            # w kernel + b bias


def loss_fn(params, batch, mask):
    pred = batch["x"] @ params["w"] + params["b"]
    m = mask.astype(jnp.float32)
    denom = jnp.maximum(m.sum(), 1.0)
    err = ((pred - batch["y"]) ** 2).sum(axis=1)
    loss = (err * m).sum() / denom
    return loss, (loss,)


def make_params(seed=0):
    return {"w": jnp.asarray(
        np.random.RandomState(seed).randn(D_IN, D_OUT), jnp.float32),
        "b": jnp.zeros((D_OUT,), jnp.float32)}


def make_runtime(**kw):
    cfg_kw = dict(mode="sketch", error_type="virtual", local_momentum=0.0,
                  virtual_momentum=0.9, weight_decay=0.0, num_workers=W,
                  local_batch_size=B, track_bytes=True, num_clients=8,
                  num_results_train=2, num_results_val=2,
                  k=5, num_rows=2, num_cols=32, exact_num_cols=True)
    cfg_kw.update(kw)
    return FedRuntime(FedConfig(**cfg_kw), make_params(), loss_fn,
                      num_clients=8)


def make_batch(seed=1):
    rng = np.random.RandomState(seed)
    batch = {"x": jnp.asarray(rng.randn(W, B, D_IN), jnp.float32),
             "y": jnp.asarray(rng.randn(W, B, D_OUT), jnp.float32)}
    return batch, jnp.ones((W, B), bool), jnp.arange(W, dtype=jnp.int32)


def fetch(metrics):
    return layer_signals_to_host(metrics["layer_signals"])


# --------------------------------------------------- partition vs numpy


def test_group_spec_tiles_ravel_order_exactly():
    """Ranges tile [0, d) with no gap/overlap, sizes sum to d, every
    boundary coordinate between adjacent leaf ranges lands in exactly
    one group, and the gid map agrees with a numpy re-derivation from
    the ravel layout."""
    params = make_params()
    spec = make_group_spec(params, "coarse")
    assert spec.d == D and sum(spec.sizes) == D
    covered = np.zeros(D, np.int32)
    for start, end, g in spec.ranges:
        assert 0 <= start < end <= D and 0 <= g < spec.n_groups
        covered[start:end] += 1
    assert (covered == 1).all()          # exactly-one-group tiling
    # ravel order is tree_leaves order: 'b' (3 coords) then 'w' (18)
    gid = spec.gid()
    names = [spec.names[g] for g in gid]
    assert names[:D_OUT] == ["b/norm-bias"] * D_OUT
    assert names[D_OUT:] == ["w"] * (D_IN * D_OUT)
    # the boundary pair straddles the b/w leaf edge: adjacent
    # coordinates, different (single) groups
    assert gid[D_OUT - 1] != gid[D_OUT]


def test_gid_padding_lands_in_no_group():
    """Mesh d_pad coordinates map to n_groups (out of bounds) and the
    scatter drops them: padded mass never leaks into a real group."""
    from commefficient_tpu.ops.segments import group_sq_mass
    spec = make_group_spec(make_params(), "coarse")
    d_pad = D + 11
    gid = spec.gid(d_pad)
    assert (gid[D:] == spec.n_groups).all()
    x = jnp.ones((d_pad,), jnp.float32) * 2.0   # padding coords NONZERO
    masses = np.asarray(group_sq_mass(x, jnp.asarray(gid), spec.n_groups))
    np.testing.assert_allclose(masses.sum(), 4.0 * D, rtol=1e-6)
    np.testing.assert_allclose(masses, [4.0 * s for s in spec.sizes],
                               rtol=1e-6)


def test_segment_reductions_match_numpy_reference():
    rng = np.random.RandomState(3)
    d, G = 97, 5
    gid_np = rng.randint(0, G + 1, size=d).astype(np.int32)  # incl. drop
    x_np = rng.randn(d).astype(np.float32)
    from commefficient_tpu.ops.segments import (group_count, group_sq_mass,
                                                group_sum_at, group_sum_cols)
    gid, x = jnp.asarray(gid_np), jnp.asarray(x_np)
    ref_sq = np.zeros(G)
    ref_ct = np.zeros(G)
    for i in range(d):
        if gid_np[i] < G:
            ref_sq[gid_np[i]] += x_np[i] ** 2
            ref_ct[gid_np[i]] += float(x_np[i] != 0)
    np.testing.assert_allclose(np.asarray(group_sq_mass(x, gid, G)),
                               ref_sq, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(group_count(x != 0, gid, G)),
                               ref_ct, rtol=1e-6)
    cols = jnp.stack([x * x, (x != 0).astype(jnp.float32)], axis=-1)
    got = np.asarray(group_sum_cols(cols, gid, G))
    np.testing.assert_allclose(got[:, 0], ref_sq, rtol=1e-5)
    np.testing.assert_allclose(got[:, 1], ref_ct, rtol=1e-6)
    idx = jnp.asarray([0, 5, 5, 96], jnp.int32)
    ref_at = np.zeros(G)
    for j in idx:
        if gid_np[int(j)] < G:
            ref_at[gid_np[int(j)]] += 1.0
    np.testing.assert_allclose(
        np.asarray(group_sum_at(jnp.ones(4), idx, gid, G)), ref_at)


def test_gpt2_scanned_blocks_split_per_block():
    """The scan-stacked h/block leaves split along their leading block
    dim into per-block coarse groups (embed/attn/mlp/norm-bias per
    block + head), and the ranges still tile [0, d)."""
    from commefficient_tpu.models.gpt2 import GPT2Config, GPT2DoubleHeads
    gcfg = GPT2Config.small(compute_dtype=jnp.float32)
    ids0 = jnp.zeros((1, 2, 16), jnp.int32)
    params = GPT2DoubleHeads(gcfg).init(
        jax.random.PRNGKey(0), ids0, jnp.zeros((1, 2), jnp.int32), ids0)
    spec = make_group_spec(params, "coarse")
    names = set(spec.names)
    assert "embed" in names and "head" in names
    for b in range(gcfg.n_layer):
        for sub in ("attn", "mlp", "norm-bias"):
            assert f"h{b}/{sub}" in names, (b, sub, sorted(names))
    covered = np.zeros(spec.d, np.int32)
    for start, end, g in spec.ranges:
        covered[start:end] += 1
    assert (covered == 1).all()
    assert sum(spec.sizes) == spec.d


def test_leaf_mode_one_group_per_leaf():
    spec = make_group_spec(make_params(), "leaf")
    assert spec.n_groups == 2 and set(spec.sizes) == {3, 18}


# --------------------------------------------------- in-round signals


def test_conservation_masses_and_counts():
    """Per-group masses sum to the whole-vector signal norms squared;
    support counts sum to exactly k (sketch top-k support)."""
    rt = make_runtime(signals_exact=True, sketch_fused_encode="off")
    batch, mask, ids = make_batch()
    state = rt.init_state()
    for _ in range(3):
        state, metrics = rt.round(state, ids, batch, mask, 0.05)
    sig = signals_to_host(metrics["signals"])
    ls = fetch(metrics)
    assert set(ls) == set(LAYER_SIGNAL_KEYS)
    assert sum(ls["update_mass"]) == pytest.approx(
        sig["update_norm"] ** 2, rel=1e-4)
    assert sum(ls["grad_mass"]) == pytest.approx(
        sig["grad_true_norm"] ** 2, rel=1e-4)
    assert sum(ls["error_mass"]) == pytest.approx(
        float(np.linalg.norm(np.asarray(state.sig_Verror))) ** 2, rel=1e-3)
    assert sum(ls["topk_count"]) == rt.cfg.k
    # lossless regime (c >= d): every group's winners recover (NaN =
    # the group owned no winner this round; serialized null)
    assert all(v == 1.0 or np.isnan(v) for v in ls["hh_overlap"])


def test_dense_mode_counts_are_group_sizes():
    rt = make_runtime(mode="uncompressed", error_type="none")
    batch, mask, ids = make_batch()
    _, metrics = rt.round(rt.init_state(), ids, batch, mask, 0.05)
    ls = fetch(metrics)
    assert ls["topk_count"] == [float(s) for s in rt.group_spec.sizes]
    assert ls["grad_mass"] is not None and ls["error_mass"] is not None


def test_fused_encode_reports_null_grad_mass_not_zero():
    """The PR-4 NaN contract applied to groups: the fused-encode round
    holds no dense aggregated gradient, so grad_mass/error_mass are
    NULL while the update-side fields stay live."""
    rt = make_runtime()                       # fused encode auto-engages
    assert rt._fused_encode and not rt._layer_grad_mass
    batch, mask, ids = make_batch()
    _, metrics = rt.round(rt.init_state(), ids, batch, mask, 0.05)
    ls = fetch(metrics)
    assert ls["grad_mass"] is None and ls["error_mass"] is None
    assert ls["hh_overlap"] is None
    assert sum(ls["topk_count"]) == rt.cfg.k
    assert sum(ls["update_mass"]) > 0


def test_mesh_sketch_reports_null_grad_mass_counts_live(devices):
    """Sharded (mesh) sketch round — the seq-sharded/fused-clients
    class: no dense aggregate ever materializes (per-shard encode), so
    grad_mass is null; support counts and update mass come from the
    update side and stay live, and conservation holds across shards."""
    from commefficient_tpu.parallel import make_mesh
    mesh = make_mesh((8,), ("clients",), devices=devices)
    params = make_params()
    cfg = FedConfig(mode="sketch", error_type="virtual",
                    local_momentum=0.0, virtual_momentum=0.9,
                    weight_decay=0.0, num_workers=8, local_batch_size=B,
                    track_bytes=True, num_clients=16,
                    num_results_train=2, num_results_val=2,
                    k=5, num_rows=2, num_cols=32, exact_num_cols=True)
    rt = FedRuntime(cfg, params, loss_fn, num_clients=16, mesh=mesh)
    rng = np.random.RandomState(1)
    batch = {"x": jnp.asarray(rng.randn(8, B, D_IN), jnp.float32),
             "y": jnp.asarray(rng.randn(8, B, D_OUT), jnp.float32)}
    mask = jnp.ones((8, B), bool)
    _, metrics = rt.round(rt.init_state(), jnp.arange(8, dtype=jnp.int32),
                          batch, mask, 0.05)
    sig = signals_to_host(metrics["signals"])
    ls = fetch(metrics)
    assert ls["grad_mass"] is None and ls["error_mass"] is None
    assert sum(ls["topk_count"]) == cfg.k
    assert sum(ls["update_mass"]) == pytest.approx(
        sig["update_norm"] ** 2, rel=1e-4)


@pytest.mark.slow
def test_seq_sharded_sketch_reports_null_grad_mass_counts_live():
    """The seq-sharded half of the null contract: a ("clients","seq")
    sketch round holds only per-shard partial gradients and a
    replicated table — grad_mass/error_mass null, update-side fields
    live and conserved."""
    from commefficient_tpu.gpt2_train import PERSONA_SEQ_SPEC
    from commefficient_tpu.losses import make_gpt2_train_loss
    from commefficient_tpu.models.gpt2 import GPT2Config, GPT2DoubleHeads
    from commefficient_tpu.parallel import make_mesh
    Wg, Bg, C, S = 2, 2, 2, 32
    gcfg = GPT2Config.small(compute_dtype=jnp.float32, n_positions=128)
    ids0 = jnp.zeros((1, C, S), jnp.int32)
    params = GPT2DoubleHeads(gcfg).init(
        jax.random.PRNGKey(0), ids0, jnp.zeros((1, C), jnp.int32), ids0)
    mesh = make_mesh((2, 4), ("clients", "seq"))
    seq_model = GPT2DoubleHeads(gcfg, seq_axis="seq", seq_shards=4)
    cfg = FedConfig(mode="sketch", error_type="virtual",
                    local_momentum=0.0, virtual_momentum=0.9,
                    weight_decay=0.0, num_workers=Wg, local_batch_size=Bg,
                    num_clients=4, track_bytes=False, num_results_train=2,
                    k=8, num_rows=3, num_cols=256, num_blocks=2)
    rt = FedRuntime(cfg, params,
                    make_gpt2_train_loss(seq_model, seq_axis="seq",
                                         seq_shards=4),
                    num_clients=4, mesh=mesh, seq_spec=PERSONA_SEQ_SPEC)
    assert rt._layer_signals and not rt._layer_grad_mass
    rng = np.random.RandomState(0)
    batch = {
        "input_ids": jnp.asarray(rng.randint(0, 256, (Wg, Bg, C, S)),
                                 jnp.int32),
        "token_type_ids": jnp.asarray(rng.randint(0, 256, (Wg, Bg, C, S)),
                                      jnp.int32),
        "mc_token_ids": jnp.asarray(rng.randint(0, S, (Wg, Bg, C)),
                                    jnp.int32),
        "lm_labels": jnp.asarray(
            np.where(rng.rand(Wg, Bg, C, S) < 0.5,
                     rng.randint(0, 256, (Wg, Bg, C, S)), -100),
            jnp.int32),
        "mc_label": jnp.asarray(rng.randint(0, C, (Wg, Bg)), jnp.int32),
    }
    _, metrics = rt.round(rt.init_state(), jnp.arange(Wg, dtype=jnp.int32),
                          batch, jnp.ones((Wg, Bg), bool), 0.05)
    sig = signals_to_host(metrics["signals"])
    ls = fetch(metrics)
    assert ls["grad_mass"] is None and ls["error_mass"] is None
    assert sum(ls["topk_count"]) == cfg.k
    assert sum(ls["update_mass"]) == pytest.approx(
        sig["update_norm"] ** 2, rel=1e-4)
    # per-block groups exist for the scanned GPT-2 layout
    assert any(n.startswith("h0/") for n in rt.group_spec.names)


def test_groups_do_not_change_numerics():
    states = []
    for kw in ({"signal_groups": "coarse"}, {"signal_groups": "leaf"},
               {"signal_groups": "off"}):
        rt = make_runtime(**kw)
        batch, mask, ids = make_batch()
        s = rt.init_state()
        for _ in range(3):
            s, _ = rt.round(s, ids, batch, mask, 0.05)
        states.append(np.asarray(s.ps_weights))
    np.testing.assert_array_equal(states[0], states[2])
    np.testing.assert_array_equal(states[1], states[2])


def test_off_and_no_telemetry_hlo_byte_identity():
    """--signal_groups off compiles the group machinery out entirely:
    byte-identical HLO to a no-signals / no-telemetry round regardless
    of the groups setting, and the off round carries no gid argument."""
    batch, mask, ids = make_batch()

    def hlo(**kw):
        rt = make_runtime(**kw)
        return rt._round.lower(
            rt.init_state(), ids, batch, mask,
            jnp.asarray(0.05, jnp.float32), rt.cs, rt._gid).as_text()

    assert hlo(telemetry=False, signal_groups="coarse") == \
        hlo(telemetry=False, signal_groups="off")
    assert hlo(signals=False, signal_groups="coarse") == \
        hlo(signals=False, signal_groups="off")
    # sanity: with signals live the groups DO change the lowering
    assert hlo(signal_groups="coarse") != hlo(signal_groups="off")
    rt_off = make_runtime(signal_groups="off")
    assert rt_off._gid is None and rt_off.group_spec is None
    _, metrics = rt_off.round(rt_off.init_state(), ids, batch, mask, 0.05)
    assert metrics["layer_signals"] is None


# ------------------------------------------------- schema + emission


def test_layer_signals_event_roundtrip(tmp_path):
    rt = make_runtime(signals_exact=True, sketch_fused_encode="off")
    tel = RunTelemetry(str(tmp_path), "test", cfg=rt.cfg)
    batch, mask, ids = make_batch()
    _, metrics = rt.round(rt.init_state(), ids, batch, mask, 0.05)
    tel.layer_signals_event(rnd=1, mode=rt.cfg.mode,
                            signal_groups=rt.cfg.signal_groups,
                            groups=rt.group_spec.names,
                            sizes=rt.group_spec.sizes,
                            values=fetch(metrics))
    tel.write_summary(aborted=False, n_rounds=1)
    tel.close()
    assert validate_file(tel.path) == []
    ev = [json.loads(line) for line in open(tel.path)
          if '"event": "layer_signals"' in line][0]
    assert ev["groups"] == list(rt.group_spec.names)
    assert ev["sizes"] == list(rt.group_spec.sizes)
    assert len(ev["update_mass"]) == rt.group_spec.n_groups
    assert "NaN" not in open(tel.path).read()


def test_schema_rejects_malformed_layer_signals():
    assert validate_event({"event": "layer_signals", "t": 0.0, "seq": 0})
    ok = {"event": "layer_signals", "t": 0.0, "seq": 0, "round": 1,
          "mode": "sketch", "signal_groups": "coarse",
          "groups": ["w"], "sizes": [18], "grad_mass": None,
          "update_mass": [1.0], "topk_count": [5.0],
          "error_mass": None, "hh_overlap": None}
    assert validate_event(ok) == []
    assert validate_event(dict(ok, update_mass="nope"))


def test_driver_loop_emits_layer_signals_events(tmp_path):
    from commefficient_tpu import cv_train
    from test_telemetry import StubDS

    rt = make_runtime(dataset_name="SYNTH", telemetry_every=1,
                      sketch_fused_encode="off")
    tel = RunTelemetry(str(tmp_path), "cv_train", cfg=rt.cfg)
    tel.instrument(rt)
    cfg = rt.cfg.replace(num_epochs=1.0, pivot_epoch=0.5)
    _, summary = cv_train.train(cfg, rt, rt.init_state(),
                                StubDS(), StubDS(), telemetry=tel)
    tel.close()
    assert summary is not None
    assert validate_file(tel.path) == []
    events = [json.loads(line) for line in open(tel.path)]
    lsigs = [e for e in events if e["event"] == "layer_signals"]
    sigs = [e for e in events if e["event"] == "signals"]
    assert len(lsigs) == len(sigs) >= 1      # same cadence
    assert lsigs[0]["signal_groups"] == "coarse"
    assert sum(lsigs[0]["topk_count"]) == rt.cfg.k


# ------------------------------------------------------- starvation rule


def _ls_fields(groups, grad_mass, topk_count):
    return {"round": 1, "groups": list(groups),
            "grad_mass": list(grad_mass), "topk_count": list(topk_count)}


def test_starved_groups_predicate():
    # group 0 holds 30% of mass, wins 0 of k -> starved; group 1 fine
    out = starved_groups(["a", "b"], [3.0, 7.0], [0.0, 8.0])
    assert [g for g, _, _ in out] == ["a"]
    _, ms, ws = out[0]
    assert ms == pytest.approx(0.3) and ws == 0.0
    # null grad_mass: starvation is never guessed
    assert starved_groups(["a", "b"], None, [0.0, 8.0]) == []
    # below the mass floor: small groups losing k is EXPECTED
    assert starved_groups(["a", "b"], [0.1, 9.9], [0.0, 8.0]) == []


def test_group_starvation_rule_fires_after_window():
    mon = AnomalyMonitor(None)
    fields = _ls_fields(["conv", "bias"], [5.0, 5.0], [8.0, 0.0])
    fired = []
    for i in range(STARVATION_WINDOW - 1):
        fired += mon.observe("layer_signals", fields)
    assert fired == []                       # streak not ripe yet
    fired = mon.observe("layer_signals", fields)
    assert [f["rule"] for f in fired] == ["group_starvation"]
    a = fired[0]
    assert a["metric"] == "layer_signals.starvation[bias]"
    assert a["severity"] == "warn" and a["window"] == STARVATION_WINDOW
    # cooldown: the next ripe observation stays quiet
    assert mon.observe("layer_signals", fields) == []


def test_group_starvation_streak_breaks_on_recovery():
    mon = AnomalyMonitor(None)
    hungry = _ls_fields(["conv", "bias"], [5.0, 5.0], [8.0, 0.0])
    fed = _ls_fields(["conv", "bias"], [5.0, 5.0], [6.0, 2.0])
    for _ in range(STARVATION_WINDOW - 1):
        assert mon.observe("layer_signals", hungry) == []
    assert mon.observe("layer_signals", fed) == []     # streak broken
    for _ in range(STARVATION_WINDOW - 1):
        assert mon.observe("layer_signals", hungry) == []


def test_group_starvation_silent_on_null_grad_mass():
    mon = AnomalyMonitor(None)
    fields = {"round": 1, "groups": ["a", "b"], "grad_mass": None,
              "topk_count": [8.0, 0.0]}
    for _ in range(3 * STARVATION_WINDOW):
        assert mon.observe("layer_signals", fields) == []


def test_starvation_streak_survives_state_dict_roundtrip():
    mon = AnomalyMonitor(None)
    fields = _ls_fields(["conv", "bias"], [5.0, 5.0], [8.0, 0.0])
    for _ in range(STARVATION_WINDOW - 1):
        mon.observe("layer_signals", fields)
    mon2 = AnomalyMonitor(None)
    mon2.load_state_dict(mon.state_dict())
    fired = mon2.observe("layer_signals", fields)
    assert [f["rule"] for f in fired] == ["group_starvation"]


def test_committed_high_compression_arm_replays_starvation():
    """The evidence artifact's contract (runs/BREAKDOWN_layers.md):
    replaying the committed 10x hard-v2 attribution stream through the
    monitor fires group_starvation on the head group — the measured
    mechanism the adaptive-compression controller consumes. The 2.6x
    flagship arm flags too (later, once): starvation is present at the
    flagship compression and worsens with the ratio."""
    fired_by_arm = {}
    for arm in ("c26x", "c10x"):
        path = os.path.join(os.path.dirname(__file__), os.pardir, "runs",
                            "layer_attrib", arm, "telemetry.jsonl")
        mon = AnomalyMonitor(None)
        fired = []
        with open(path) as f:
            for line in f:
                try:
                    e = json.loads(line)
                except ValueError:
                    continue
                if e.get("event") == "layer_signals":
                    fired += mon.observe("layer_signals", e)
        fired_by_arm[arm] = [(a["metric"], a["round"]) for a in fired]
    assert any("head" in m for m, _ in fired_by_arm["c10x"]), fired_by_arm
    # dose response: the high arm fires no later and no less often
    assert len(fired_by_arm["c10x"]) >= len(fired_by_arm["c26x"]) >= 1, \
        fired_by_arm
    assert fired_by_arm["c10x"][0][1] <= fired_by_arm["c26x"][0][1], \
        fired_by_arm


# ---------------------------------------------------------------- teleview


def _teleview():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "teleview", os.path.join(os.path.dirname(__file__), os.pardir,
                                 "scripts", "teleview.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_teleview_fallback_constants_match_package():
    """teleview must run jax-free, so it carries literal twins of the
    layer-signal vocabulary and the starvation thresholds — pin them
    (and the fallback predicate's behavior) to the canonical values."""
    import re
    src = open(os.path.join(os.path.dirname(__file__), os.pardir,
                            "scripts", "teleview.py")).read()
    block = re.search(r"LAYER_SIGNAL_KEYS = \((.*?)\)", src, re.S).group(1)
    assert tuple(re.findall(r'"([a-z_0-9]+)"', block)) == LAYER_SIGNAL_KEYS
    m = re.search(r"STARVATION_MASS_SHARE = ([0-9.]+)", src)
    assert float(m.group(1)) == STARVATION_MASS_SHARE
    m = re.search(r"STARVATION_WIN_SHARE = ([0-9.]+)", src)
    assert float(m.group(1)) == STARVATION_WIN_SHARE
    # the literal fallback predicate agrees with the package's on a
    # starving sample (exercised by deleting the package import)
    tv = _teleview()
    sample = (["a", "b"], [3.0, 7.0], [0.0, 8.0])
    assert tv.starved_groups(*sample) == starved_groups(*sample)


def _write_stream(path, rounds=2, win_bias=0.0):
    tel = RunTelemetry(str(path), "test", cfg=None)
    for r in range(1, rounds + 1):
        tel.event("layer_signals", round=r, mode="sketch",
                  signal_groups="coarse",
                  groups=["conv", "bias"], sizes=[900, 100],
                  grad_mass=[6.0, 4.0], update_mass=[1.0, 0.1],
                  topk_count=[8.0 - win_bias, 0.0 + win_bias],
                  error_mass=[1.0, 9.0], hh_overlap=[1.0, None])
    tel.write_summary(aborted=False, n_rounds=rounds)
    tel.close()
    assert validate_file(tel.path) == []
    return tel.path


def test_teleview_layers_renders_table_and_flags_starved(tmp_path, capsys):
    tv = _teleview()
    p = _write_stream(tmp_path / "a")
    assert tv.main(["layers", p]) == 0
    out = capsys.readouterr().out
    assert "bias" in out and "STARVED" in out
    assert tv.main(["summarize", p]) == 0
    assert "STARVED" in capsys.readouterr().out


def test_teleview_diff_starvation_rise_gate(tmp_path, capsys):
    tv = _teleview()
    a = _write_stream(tmp_path / "a", win_bias=2.0)   # bias wins some k
    b = _write_stream(tmp_path / "b", win_bias=0.0)   # bias starves
    assert tv.main(["diff", a, b]) == 1
    assert "starvation gap" in capsys.readouterr().out
    assert tv.main(["diff", a, b, "--starvation_rise", "0.9"]) == 0
    # the input-wait gate keeps its own primary spelling
    assert tv.main(["diff", a, b, "--starvation_rise", "0.9",
                    "--input_wait_rise", "0.5"]) == 0
