"""Span tracer + utilization accounting (telemetry/tracing.py,
telemetry/utilization.py): nesting/reentrancy/thread-safety of the
tracer, the zero-overhead null path, the MFU math against synthetic
cost dicts and a fake peak table, the schema round-trip of the new
``span``/``utilization`` events (incl. the v1 backward-compat read),
the driver wiring, and the structural validity of the perfetto
``trace.json`` that ``teleview timeline`` renders."""

import importlib.util
import json
import os
import threading
import time

import numpy as np
import pytest

from commefficient_tpu.config import FedConfig
from commefficient_tpu.telemetry import (RunTelemetry, SpanTracer, tracing,
                                         validate_file, validate_lines)
from commefficient_tpu.telemetry.schema import (SCHEMA_VERSION,
                                                TELEMETRY_BASENAME)
from commefficient_tpu.telemetry.utilization import (UtilizationTracker,
                                                     emit_from_totals,
                                                     peak_flops_for,
                                                     straggler_spread,
                                                     utilization_fields)
from tests.test_telemetry import (StubDS, make_batch, make_runtime,
                                  read_events)


def load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(os.path.dirname(__file__), os.pardir,
                           "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------------ tracer


def test_span_nesting_and_drain():
    tr = SpanTracer()
    with tr.span("outer"):
        with tr.span("inner"):
            time.sleep(0.002)
        with tr.span("inner2"):
            pass
    spans = tr.drain()
    by_name = {s["name"]: s for s in spans}
    assert set(by_name) == {"outer", "inner", "inner2"}
    assert by_name["outer"]["depth"] == 0
    assert by_name["inner"]["depth"] == by_name["inner2"]["depth"] == 1
    # children close before the parent and start after it
    assert by_name["inner"]["ts"] >= by_name["outer"]["ts"]
    assert by_name["outer"]["dur_s"] >= by_name["inner"]["dur_s"] >= 0.002
    # drain cleared the buffer; re-entering after a drain works
    assert tr.drain() == []
    with tr.span("again"):
        pass
    assert [s["name"] for s in tr.drain()] == ["again"]


def test_span_records_on_exception():
    tr = SpanTracer()
    with pytest.raises(RuntimeError):
        with tr.span("dies"):
            raise RuntimeError("boom")
    spans = tr.drain()
    assert [s["name"] for s in spans] == ["dies"]
    # the depth counter unwound: a following span is top-level again
    with tr.span("next"):
        pass
    assert tr.drain()[0]["depth"] == 0


def test_span_thread_safety():
    tr = SpanTracer()
    # hold every thread at the gate until all are alive: a thread that
    # finishes before another starts can hand its (reused) OS ident to
    # the newcomer, merging their tids
    gate = threading.Barrier(4)

    def work():
        gate.wait()
        for _ in range(50):
            with tr.span("a"):
                with tr.span("b"):
                    pass

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    spans = tr.drain()
    assert len(spans) == 4 * 50 * 2
    assert {s["tid"] for s in spans} == {0, 1, 2, 3}
    for s in spans:
        # per-thread nesting survived concurrency
        assert s["depth"] == (1 if s["name"] == "b" else 0)


def test_span_buffer_cap_counts_drops():
    tr = SpanTracer(max_spans=3)
    for i in range(5):
        with tr.span(f"s{i}"):
            pass
    assert len(tr.drain()) == 3
    # per-window semantics: pop returns the drops once, then resets —
    # each span event's n_dropped covers its own window only
    assert tr.pop_dropped() == 2
    assert tr.pop_dropped() == 0


def test_null_tracer_is_free_and_default():
    """With no tracer installed (the --no_telemetry state), span() must
    return one shared no-op object — no allocation, no clock reads —
    and install/uninstall must restore that state."""
    assert isinstance(tracing.current(), tracing.NullTracer)
    assert tracing.span("x") is tracing.span("y") is tracing.NULL_SPAN
    tr = tracing.install()
    try:
        assert tracing.current() is tr
        with tracing.span("live"):
            pass
        assert [s["name"] for s in tr.drain()] == ["live"]
    finally:
        tracing.uninstall()
    assert isinstance(tracing.current(), tracing.NullTracer)
    assert tracing.current().drain() == []


# ------------------------------------------------------------------ MFU math


def test_peak_flops_table_and_override():
    assert peak_flops_for("TPU v5 lite chip") == 197e12
    assert peak_flops_for("TPU v4 (whatever)") == 275e12
    assert peak_flops_for("cpu") is None          # unknown => null, not 0
    assert peak_flops_for("cpu", override=3e12) == 3e12
    assert peak_flops_for("TPU v4", override=1e12) == 1e12  # override wins


def test_utilization_fields_math():
    """Synthetic cost-analysis numbers through the pure math: the exact
    MFU/starvation identities, and nulls (never fake zeros) where the
    inputs are unknown."""
    f = utilization_fields(rounds=10, wall_s=2.0, host_s=0.5,
                           dispatch_s=0.3, device_s=1.0,
                           flops_per_round=1e11,
                           flops_source="cost_analysis",
                           device_kind="TPU v5e", peak_flops=197e12)
    assert f["achieved_flops"] == pytest.approx(10 * 1e11 / 2.0)
    assert f["mfu"] == pytest.approx(10 * 1e11 / 2.0 / 197e12, rel=1e-3)
    assert f["input_wait_frac"] == pytest.approx(0.25)
    assert f["dispatch_frac"] == pytest.approx(0.15)
    assert f["device_wait_frac"] == pytest.approx(0.5)
    assert f["flops_source"] == "cost_analysis"
    # no FLOPs count => null achieved/mfu/source
    f = utilization_fields(rounds=1, wall_s=1.0, host_s=0, dispatch_s=0,
                           device_s=0, flops_per_round=None,
                           flops_source="cost_analysis",
                           device_kind="TPU v5e", peak_flops=197e12)
    assert f["mfu"] is None and f["achieved_flops"] is None
    assert f["flops_source"] is None
    # no peak => achieved computes, mfu stays null
    f = utilization_fields(rounds=1, wall_s=1.0, host_s=0, dispatch_s=0,
                           device_s=0, flops_per_round=5e9,
                           flops_source="analytic", device_kind="cpu",
                           peak_flops=None)
    assert f["achieved_flops"] == pytest.approx(5e9)
    assert f["mfu"] is None


def test_straggler_spread():
    assert straggler_spread([]) is None
    assert straggler_spread([1.0]) is None          # one host can't straggle
    assert straggler_spread([1.0, 1.0]) == 0.0
    assert straggler_spread([1.0, 3.0]) == pytest.approx(1.0)  # (3-1)/2


class CaptureTelemetry:
    """RunTelemetry stand-in recording event() calls."""

    def __init__(self):
        self.events = []

    def event(self, kind, **fields):
        self.events.append({"event": kind, **fields})


def test_utilization_tracker_windows():
    tel = CaptureTelemetry()
    util = UtilizationTracker(tel, device_kind="TPU v5e", peak_flops=1e12)
    assert util.emit(0) is None and tel.events == []  # empty window no-ops
    util.set_flops_per_round(2e9, source="analytic")
    util.observe_round(host_s=0.01, dispatch_s=0.02, device_s=0.03)
    util.observe_round(host_s=0.01, dispatch_s=0.02)   # unsynced round
    f = util.emit(7)
    assert f is not None and tel.events[-1]["event"] == "utilization"
    assert tel.events[-1]["round"] == 7
    assert f["rounds"] == 2
    assert f["wall_s"] >= 0.03                 # window spans both rounds
    assert f["flops_per_round"] == 2e9 and f["flops_source"] == "analytic"
    assert f["mfu"] == pytest.approx(2 * 2e9 / (f["wall_s"] * 1e12),
                                     rel=1e-2)
    # the window reset: a second emit with no rounds observed is a no-op
    assert util.emit(8) is None


def test_utilization_tracker_reads_watcher_flops():
    class FakeWatcher:
        flops = {"round_step": 3e9}

    tel = CaptureTelemetry()
    util = UtilizationTracker(tel, device_kind="TPU v5e",
                              watcher=FakeWatcher())
    util.observe_round(host_s=0.0, dispatch_s=0.001, device_s=0.0)
    f = util.emit(1)
    assert f["flops_per_round"] == 3e9
    assert f["flops_source"] == "cost_analysis"


# ------------------------------------------------------------------- schema


def test_span_and_utilization_schema_roundtrip(tmp_path):
    tel = RunTelemetry(str(tmp_path), "test", cfg=None)
    tr = SpanTracer()
    with tr.span("data_fetch"):
        with tr.span("host_gather"):
            pass
    tel.span_event(tr)
    tel.span_event(tr)   # drained buffer => no empty event written
    emit_from_totals(tel, rnd=1, rounds=1, wall_s=0.5, host_s=0.1,
                     dispatch_s=0.2, device_s=0.1, flops_per_round=1e9,
                     flops_source="analytic", device_kind="TPU v5e",
                     per_host_device_s=[0.1, 0.3])
    tel.write_summary(aborted=False, n_rounds=1)
    tel.close()
    assert validate_file(tel.path) == []
    events = read_events(tel.path)
    kinds = [e["event"] for e in events]
    assert kinds.count("span") == 1
    assert kinds.count("utilization") == 1
    sp = next(e for e in events if e["event"] == "span")
    assert {s["name"] for s in sp["spans"]} == {"data_fetch", "host_gather"}
    assert sp["t0_wall"] > 0
    ut = next(e for e in events if e["event"] == "utilization")
    assert ut["straggler_spread"] == pytest.approx(1.0)
    man = events[0]
    assert man["schema"] == SCHEMA_VERSION == 11


def test_v1_streams_stay_readable():
    """Backward-compat read: a manifest written under schema 1 (pre
    span/utilization) must still validate."""
    man = {"event": "manifest", "t": 0.0, "seq": 0, "schema": 1,
           "run_type": "t", "jax_version": "x", "backend": "cpu",
           "device_kind": "cpu", "device_count": 1, "mesh_shape": [],
           "mesh_axes": [], "grad_size": 1, "sketch": None, "config": {}}
    assert validate_lines([json.dumps(man)]) == []
    # an unknown FUTURE version is still rejected
    man["schema"] = 99
    assert any("schema" in p for _, p in validate_lines([json.dumps(man)]))


def test_selftest_covers_new_event_types():
    mod = load_script("check_telemetry_schema")
    lines = mod.sample_stream()
    kinds = [json.loads(l)["event"] for l in lines]
    assert "span" in kinds and "utilization" in kinds
    assert "client_stats" in kinds and "alert" in kinds
    # the client_stats sample carries realistic ordered quantiles — the
    # selftest is the cheap CI proof the generator and validator agree
    cs = next(json.loads(l) for l in lines
              if json.loads(l)["event"] == "client_stats")
    q = cs["quantiles"]["loss"]
    assert q["p5"] <= q["p50"] <= q["p95"] <= q["max"]
    assert mod.main(["--selftest"]) == 0


# ------------------------------------------------------------ driver wiring


def run_driver(tmp_path, **cfg_kw):
    from commefficient_tpu import cv_train
    from commefficient_tpu.utils import TableLogger

    rt = make_runtime(dataset_name="SYNTH", telemetry_every=1,
                      peak_flops=1e12, **cfg_kw)
    tel = RunTelemetry(str(tmp_path), "cv_train", cfg=rt.cfg)
    tel.instrument(rt)
    cfg = rt.cfg.replace(num_epochs=1.0, pivot_epoch=0.5)
    state, summary = cv_train.train(cfg, rt, rt.init_state(), StubDS(),
                                    StubDS(), loggers=(TableLogger(),),
                                    telemetry=tel)
    tel.close()
    assert summary is not None
    return tel.path


def test_driver_emits_spans_and_utilization(tmp_path, capsys):
    path = run_driver(tmp_path)
    assert validate_file(path) == []
    events = read_events(path)
    kinds = [e["event"] for e in events]
    assert "span" in kinds and "utilization" in kinds
    names = {s["name"] for e in events if e["event"] == "span"
             for s in e["spans"]}
    # the full vertical slice: driver loop phases, runtime dispatch,
    # the validation sweep, the emission tail (the data-layer spans are
    # covered by test_data_layer_spans — StubDS is not a FedDataset)
    for expected in ("data_fetch", "round_dispatch", "device_wait",
                     "telemetry_emit", "validation", "val_dispatch"):
        assert expected in names, (expected, names)
    ut = [e for e in events if e["event"] == "utilization"]
    # cadence=1 emits per round, plus the epoch-boundary flush no-ops
    assert all(e["rounds"] >= 1 for e in ut)
    assert sum(e["rounds"] for e in ut) == 2     # StubDS: 2 rounds/epoch
    # the watcher's cost-analysis FLOPs reached the MFU join, and the
    # --peak_flops override made mfu computable on CPU
    assert all(e["flops_source"] == "cost_analysis" for e in ut)
    assert all(e["mfu"] is not None and e["mfu"] > 0 for e in ut)
    assert all(0 <= e["input_wait_frac"] <= 1 for e in ut)
    # the tracer was uninstalled on the way out
    assert isinstance(tracing.current(), tracing.NullTracer)


def test_data_layer_spans():
    """The loader waits are instrumented at the layer that owns them:
    FedDataset.gather (host pipeline) and DeviceStore.round_batch
    (device gather dispatch) each open their span."""
    from commefficient_tpu.data.device_store import DeviceStore
    from commefficient_tpu.data.fed_dataset import FedDataset

    ds = FedDataset.__new__(FedDataset)   # bypass the on-disk prepare
    ds.train, ds.do_iid, ds.transform = True, False, None
    ds.arrays = {"x": np.arange(12).reshape(6, 2)}
    store = DeviceStore({"x": np.zeros((6, 2), np.float32)})
    tr = tracing.install()
    try:
        out = ds.gather(np.array([1, 3]))
        assert out["x"].shape == (2, 2)
        batch = store.round_batch(np.array([0, 1]), None)
        assert batch["x"].shape == (2, 2)
    finally:
        tracing.uninstall()
    names = [s["name"] for s in tr.drain()]
    assert names == ["host_gather", "data_gather"]


def test_no_telemetry_leaves_null_tracer(capsys):
    """--no_telemetry: train() must never install a recording tracer —
    span sites stay the shared no-op (the zero-overhead contract)."""
    from commefficient_tpu import cv_train

    rt = make_runtime(dataset_name="SYNTH", telemetry=False)
    cfg = rt.cfg.replace(num_epochs=1.0, pivot_epoch=0.5)
    state, summary = cv_train.train(cfg, rt, rt.init_state(), StubDS(),
                                    StubDS(), telemetry=None)
    assert summary is not None
    assert isinstance(tracing.current(), tracing.NullTracer)
    assert tracing.span("anything") is tracing.NULL_SPAN


def test_round_record_excludes_emission_from_phases(tmp_path):
    """The telemetry_emit span must sit OUTSIDE the recorded
    host/dispatch/device phases: the round record's phase sum never
    includes the JSONL flush that follows it."""
    path = run_driver(tmp_path)
    events = read_events(path)
    spans = [s for e in events if e["event"] == "span"
             for s in e["spans"]]
    emits = [s for s in spans if s["name"] == "telemetry_emit"]
    waits = [s for s in spans if s["name"] == "device_wait"]
    assert emits and waits
    # emission starts only after the device wait of the same round ended
    assert emits[0]["ts"] >= waits[0]["ts"] + waits[0]["dur_s"] - 1e-6


# ----------------------------------------------------------- teleview views


def test_teleview_timeline_perfetto_structure(tmp_path):
    path = run_driver(tmp_path / "run")
    mod = load_script("teleview")
    out = str(tmp_path / "trace.json")
    assert mod.main(["timeline", path, "-o", out]) == 0
    with open(out) as f:
        trace = json.load(f)          # valid JSON
    evs = trace["traceEvents"]
    assert evs, "empty trace"
    # complete ("X") / counter ("C") / metadata ("M") events only — no
    # B/E pairs to mismatch
    assert {e["ph"] for e in evs} <= {"X", "C", "M"}
    assert any(e["ph"] == "X" for e in evs)
    assert any(e["ph"] == "C" and e["name"] == "MFU" for e in evs)
    ts = [e["ts"] for e in evs]
    assert ts == sorted(ts), "timestamps not monotonic"
    assert all(t >= 0 for t in ts)
    for e in evs:
        if e["ph"] == "X":
            assert e["dur"] >= 0
            assert isinstance(e["name"], str) and "tid" in e


def test_teleview_summarize_has_utilization_line(tmp_path, capsys):
    path = run_driver(tmp_path)
    mod = load_script("teleview")
    assert mod.main(["summarize", path]) == 0
    out = capsys.readouterr().out
    assert "utilization" in out and "mfu" in out


def _stream_with_util(tmp_path, name, mfu, wait):
    d = tmp_path / name
    d.mkdir()
    lines = [
        {"event": "manifest", "t": 0.0, "seq": 0, "schema": SCHEMA_VERSION,
         "run_type": "t", "jax_version": "x", "backend": "cpu",
         "device_kind": "cpu", "device_count": 1, "mesh_shape": [],
         "mesh_axes": [], "grad_size": 1, "sketch": None, "config": {}},
        {"event": "utilization", "t": 1.0, "seq": 1, "round": 1,
         "rounds": 1, "wall_s": 1.0, "device_kind": "cpu",
         "peak_flops": 1e12, "flops_per_round": 1e9,
         "flops_source": "analytic", "achieved_flops": 1e9, "mfu": mfu,
         "input_wait_frac": wait, "dispatch_frac": 0.1,
         "device_wait_frac": 0.1, "straggler_spread": None},
    ]
    p = d / TELEMETRY_BASENAME
    p.write_text("\n".join(json.dumps(l) for l in lines) + "\n")
    return str(p)


def test_teleview_diff_flags_mfu_and_starvation(tmp_path, capsys):
    mod = load_script("teleview")
    base = _stream_with_util(tmp_path, "base", mfu=0.50, wait=0.10)
    slow = _stream_with_util(tmp_path, "slow", mfu=0.20, wait=0.10)
    starved = _stream_with_util(tmp_path, "starved", mfu=0.50, wait=0.40)
    same = _stream_with_util(tmp_path, "same", mfu=0.49, wait=0.12)
    assert mod.main(["diff", base, slow]) == 1
    assert "mfu" in capsys.readouterr().out
    assert mod.main(["diff", base, starved]) == 1
    assert "input_wait_frac" in capsys.readouterr().out
    # within thresholds: clean
    assert mod.main(["diff", base, same]) == 0


def test_bench_phase_split_and_utilization_event(tmp_path):
    """bench_common's phase split + the bench-side utilization event:
    one event per timed stage, schema-valid, MFU from the given FLOPs."""
    import bench_common

    rt = make_runtime()
    batch, mask, ids = make_batch()
    dt, metrics, phases = bench_common.timed_rounds(
        rt, (ids, batch, mask, 0.05), warmup=1, rounds=2, desc="t")
    tel = RunTelemetry(str(tmp_path), "bench", cfg=None)
    fields = emit_from_totals(
        tel, rnd=2, rounds=2, wall_s=dt, host_s=phases["host_s"],
        dispatch_s=phases["dispatch_s"], device_s=phases["device_wait_s"],
        flops_per_round=1e9, flops_source="cost_analysis",
        device_kind="TPU v5e")
    tel.write_summary(aborted=False, n_rounds=2)
    tel.close()
    assert validate_file(tel.path) == []
    assert fields["mfu"] == pytest.approx(2e9 / (dt * 197e12), rel=1e-2)
