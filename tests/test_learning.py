"""End-to-end learning check: a small ResNet-9 on synthetic federated CIFAR
must actually learn under both sketch (FetchSGD) and uncompressed modes.

This is the "loss decreasing" criterion of SURVEY.md §7's minimum
end-to-end slice, kept CPU-fast via a narrow model.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu import models
from commefficient_tpu.config import FedConfig
from commefficient_tpu.core import FedRuntime
from commefficient_tpu.data import FedCIFAR10, FedSampler, transforms_for
from commefficient_tpu.losses import make_cv_loss

SMALL = {"prep": 8, "layer1": 16, "layer2": 16, "layer3": 32}


def run_training(mode, extra, tmp_path, epochs=10, lr=0.15):
    # normalize-only transform: random-crop augmentation would scramble the
    # synthetic per-pixel class prototypes (no translation structure), which
    # masks learning; real CIFAR uses the train transform
    ds = FedCIFAR10(str(tmp_path / mode), synthetic=True,
                    synthetic_per_class=8,
                    transform=transforms_for("CIFAR10", False))
    cfg = FedConfig(mode=mode, local_momentum=0.0, virtual_momentum=0.9,
                    weight_decay=0.0, num_workers=2, local_batch_size=8,
                    num_clients=ds.num_clients, track_bytes=False,
                    compute_dtype="float32", **extra)
    # batch-stat norm: the norm-free net optimizes too slowly for a short
    # test (verified: plain centralized SGD barely moves it either; the
    # reference's norm-free default relies on its 24-epoch tuned schedule)
    model = models.ResNet9(num_classes=10, channels=SMALL,
                           do_batchnorm=True)
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 32, 32, 3)))
    runtime = FedRuntime(cfg, params, make_cv_loss(model, "float32"),
                         num_clients=ds.num_clients)
    state = runtime.init_state()

    losses = []
    for epoch in range(epochs):
        sampler = FedSampler(ds.data_per_client, cfg.num_workers,
                             cfg.local_batch_size, seed=epoch)
        ep, w = 0.0, 0.0
        for rnd in sampler:
            batch = ds.gather(rnd.idx)
            batch = {k: jnp.asarray(v) for k, v in batch.items()}
            state, m = runtime.round(state, rnd.client_ids, batch,
                                     rnd.mask, lr)
            n = np.asarray(m["n_valid"])
            ep += float((np.asarray(m["results"][0]) * n).sum())
            w += float(n.sum())
        losses.append(ep / w)
    return losses


@pytest.mark.parametrize("mode,extra", [
    ("uncompressed", {"error_type": "none"}),
    ("sketch", {"error_type": "virtual", "k": 2000, "num_rows": 3,
                "num_cols": 20000, "num_blocks": 2}),
])
@pytest.mark.slow
def test_training_learns(mode, extra, tmp_path):
    losses = run_training(mode, extra, tmp_path)
    assert np.isfinite(losses).all(), losses
    # synthetic classes are near-separable: loss must drop markedly
    assert losses[-1] < losses[0] * 0.7, losses


# --------------------------------------------------------------------------
# Compressing-regime (r*c << d) sketch study — ADVICE r1 medium #1.
# The default (hash) impl must train at real compression ratios, and its
# update dynamics must be IDENTICAL on a mesh and on a single device (the
# cell-zeroing rule is pure table-space math, so topology cannot change it).

def _quad_loss(params, batch, mask):
    pred = batch["x"] @ params["w"]
    err = ((pred - batch["y"]) ** 2).sum(axis=1)
    m = mask.astype(jnp.float32)
    loss = (err * m).sum() / jnp.maximum(m.sum(), 1.0)
    return loss, (loss,)


def _run_compressing(impl, use_mesh, rounds=80, lr=0.02):
    from commefficient_tpu.parallel import make_mesh

    din, dout, W, B = 40, 15, 8, 8          # d = 600
    rng = np.random.RandomState(0)
    w_true = rng.randn(din, dout)
    params = {"w": jnp.asarray(rng.randn(din, dout) * 0.1, jnp.float32)}
    cfg = FedConfig(mode="sketch", error_type="virtual", local_momentum=0.0,
                    virtual_momentum=0.9, weight_decay=0.0, num_workers=W,
                    local_batch_size=B, k=30, num_rows=4, num_cols=80,
                    num_blocks=1, track_bytes=False, num_clients=16,
                    sketch_impl=impl)
    rt = FedRuntime(cfg, params, _quad_loss, num_clients=16,
                    mesh=make_mesh((8,), ("clients",)) if use_mesh else None)
    s = rt.init_state()
    losses = []
    ids = jnp.arange(W, dtype=jnp.int32)
    for t in range(rounds):
        r = np.random.RandomState(t)
        x = r.randn(W, B, din).astype(np.float32)
        y = (x @ w_true + 0.01 * r.randn(W, B, dout)).astype(np.float32)
        s, met = rt.round(s, ids, {"x": jnp.asarray(x), "y": jnp.asarray(y)},
                          jnp.ones((W, B), bool), lr)
        losses.append(float(np.asarray(met["results"][0]).mean()))
    return losses


@pytest.mark.parametrize("impl", ["circ", "hash"])
def test_sketch_trains_at_real_compression(impl):
    """r*c = 320 << d = 600: the cell-zeroing rule must contract the error
    and the loss must come down (the SRHT impl demonstrably diverges here,
    which is why circ/hash are the supported compressing impls — see
    ops/rht.py 'Regime of validity')."""
    single = _run_compressing(impl, use_mesh=False)
    assert np.isfinite(single).all(), single[-5:]
    assert single[-1] < single[0] * 0.8, (single[0], single[-1])

    mesh = _run_compressing(impl, use_mesh=True)
    # topology reproducibility: identical dynamics at real compression,
    # not just in the lossless limit
    np.testing.assert_allclose(single, mesh, rtol=1e-4)


def test_rht_compressing_regime_is_rejected(capsys):
    """sketch_impl=rht sized compressing is known-divergent: runtime
    construction must REFUSE it (fail-fast), and --allow_divergent_rht
    must opt back in with a warning on STDERR (stdout is the bench/driver
    machine-readable channel)."""
    params = {"w": jnp.zeros((40, 15), jnp.float32)}
    cfg = FedConfig(mode="sketch", error_type="virtual", local_momentum=0.0,
                    num_workers=2, local_batch_size=4, num_clients=4,
                    k=30, num_rows=4, num_cols=80, num_blocks=1,
                    sketch_impl="rht", track_bytes=False)
    with pytest.raises(ValueError, match="diverges under error feedback"):
        FedRuntime(cfg, params, _quad_loss, num_clients=4)
    FedRuntime(cfg.replace(allow_divergent_rht=True), params, _quad_loss,
               num_clients=4)
    captured = capsys.readouterr()
    assert "diverges under error feedback" in captured.err
    assert "diverges" not in captured.out


@pytest.mark.slow
def test_imagenet_pipeline_end_to_end_rounds(tmp_path):
    """FedImageNet's synthetic path through real federated rounds (not
    just prepare/ingest): per-wnid natural clients, sampler, sketch
    round, and validation all compose — the CPU-sized stand-in for the
    ImageNet recipe (scripts/imagenet.sh)."""
    from commefficient_tpu.data import FedSampler
    from commefficient_tpu.data.fed_imagenet import FedImageNet

    ds = FedImageNet(str(tmp_path), train=True, synthetic=True,
                     image_size=32, synthetic_num_classes=4,
                     synthetic_per_class=8,
                     transform=transforms_for("CIFAR10", False))
    val = FedImageNet(str(tmp_path), train=False, synthetic=True,
                      image_size=32, synthetic_num_classes=4,
                      synthetic_per_class=8,
                      transform=transforms_for("CIFAR10", False))
    cfg = FedConfig(mode="sketch", error_type="virtual", local_momentum=0.0,
                    virtual_momentum=0.9, weight_decay=0.0, num_workers=2,
                    local_batch_size=4, k=50, num_rows=3, num_cols=512,
                    num_blocks=2, num_clients=ds.num_clients,
                    track_bytes=False, compute_dtype="float32")
    model = models.ResNet9(num_classes=4, channels=SMALL,
                           do_batchnorm=True)
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 32, 32, 3)))
    rt = FedRuntime(cfg, params, make_cv_loss(model, "float32"),
                    num_clients=ds.num_clients)
    state = rt.init_state()
    sampler = FedSampler(ds.data_per_client, cfg.num_workers,
                         cfg.local_batch_size, seed=0)
    for rnd in sampler:
        batch = {k: jnp.asarray(v) for k, v in ds.gather(rnd.idx).items()}
        state, m = rt.round(state, rnd.client_ids, batch, rnd.mask, 0.05)
        break
    assert np.isfinite(np.asarray(m["results"][0])).all()
    vb = {k: jnp.asarray(v)
          for k, v in val.gather(np.arange(8)).items()}
    res, n = rt.val(state, vb, jnp.ones((8,), bool))
    assert np.isfinite(float(res[0]))


@pytest.mark.slow
def test_flagship_model_trains_at_real_compression(tmp_path):
    """VERDICT r2 item 7: the compressing-regime stability claim must
    cover the flagship PATH, not just a quadratic toy — the small
    ResNet-9 trains with the default circ sketch at r·c ≪ d."""
    losses = run_training(
        "sketch",
        {"error_type": "virtual", "k": 1500, "num_rows": 3,
         "num_cols": 5000, "num_blocks": 2},
        tmp_path, epochs=8)
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0] * 0.8, losses
