"""End-to-end learning check: a small ResNet-9 on synthetic federated CIFAR
must actually learn under both sketch (FetchSGD) and uncompressed modes.

This is the "loss decreasing" criterion of SURVEY.md §7's minimum
end-to-end slice, kept CPU-fast via a narrow model.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu import models
from commefficient_tpu.config import FedConfig
from commefficient_tpu.core import FedRuntime
from commefficient_tpu.data import FedCIFAR10, FedSampler, transforms_for
from commefficient_tpu.losses import make_cv_loss

SMALL = {"prep": 8, "layer1": 16, "layer2": 16, "layer3": 32}


def run_training(mode, extra, tmp_path, epochs=10, lr=0.15):
    # normalize-only transform: random-crop augmentation would scramble the
    # synthetic per-pixel class prototypes (no translation structure), which
    # masks learning; real CIFAR uses the train transform
    ds = FedCIFAR10(str(tmp_path / mode), synthetic=True,
                    synthetic_per_class=8,
                    transform=transforms_for("CIFAR10", False))
    cfg = FedConfig(mode=mode, local_momentum=0.0, virtual_momentum=0.9,
                    weight_decay=0.0, num_workers=2, local_batch_size=8,
                    num_clients=ds.num_clients, track_bytes=False,
                    compute_dtype="float32", **extra)
    # batch-stat norm: the norm-free net optimizes too slowly for a short
    # test (verified: plain centralized SGD barely moves it either; the
    # reference's norm-free default relies on its 24-epoch tuned schedule)
    model = models.ResNet9(num_classes=10, channels=SMALL,
                           do_batchnorm=True)
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 32, 32, 3)))
    runtime = FedRuntime(cfg, params, make_cv_loss(model, "float32"),
                         num_clients=ds.num_clients)
    state = runtime.init_state()

    losses = []
    for epoch in range(epochs):
        sampler = FedSampler(ds.data_per_client, cfg.num_workers,
                             cfg.local_batch_size, seed=epoch)
        ep, w = 0.0, 0.0
        for rnd in sampler:
            batch = ds.gather(rnd.idx)
            batch = {k: jnp.asarray(v) for k, v in batch.items()}
            state, m = runtime.round(state, rnd.client_ids, batch,
                                     rnd.mask, lr)
            n = np.asarray(m["n_valid"])
            ep += float((np.asarray(m["results"][0]) * n).sum())
            w += float(n.sum())
        losses.append(ep / w)
    return losses


@pytest.mark.parametrize("mode,extra", [
    ("uncompressed", {"error_type": "none"}),
    ("sketch", {"error_type": "virtual", "k": 2000, "num_rows": 3,
                "num_cols": 20000, "num_blocks": 2}),
])
def test_training_learns(mode, extra, tmp_path):
    losses = run_training(mode, extra, tmp_path)
    assert np.isfinite(losses).all(), losses
    # synthetic classes are near-separable: loss must drop markedly
    assert losses[-1] < losses[0] * 0.7, losses
