"""Real-format data fixtures (VERDICT r1 next #4): every non-synthetic
ingest branch — CIFAR pickle dirs, the PersonaChat corpus json + real GPT-2
BPE tokenizer, and the ImageNet image tree + driver recipe — exercised
against tiny fixtures in the reference's exact on-disk formats."""

import json
import os
import pickle
import subprocess
import sys

import numpy as np
import pytest

# ---------------------------------------------------------------- CIFAR


def _write_cifar_pickles(root, num_classes=10, per_batch=20):
    """Tiny cifar-10-batches-py/ in the standard python-pickle schema:
    dicts with b'data' (N, 3072) uint8 row-major CHW and b'labels'."""
    d = os.path.join(root, "cifar-10-batches-py")
    os.makedirs(d, exist_ok=True)

    def batch(seed):
        r = np.random.RandomState(seed)
        data = r.randint(0, 255, (per_batch, 3072), dtype=np.uint8)
        labels = [int(x) for x in r.randint(0, num_classes, per_batch)]
        return {b"data": data, b"labels": labels}

    for i in range(1, 6):
        with open(os.path.join(d, f"data_batch_{i}"), "wb") as f:
            pickle.dump(batch(i), f)
    with open(os.path.join(d, "test_batch"), "wb") as f:
        pickle.dump(batch(99), f)
    return d


def test_cifar_pickle_ingest(tmp_path):
    """The real-pickle branch (reference fed_cifar.py layout): images are
    split by label into per-class clients and round-trip exactly."""
    from commefficient_tpu.data.fed_cifar import FedCIFAR10

    _write_cifar_pickles(str(tmp_path))
    ds = FedCIFAR10(str(tmp_path))        # synthetic=None, real data found
    assert ds.num_clients == 10
    assert len(ds) == 100                 # 5 batches x 20
    # reconstruct the expected class partition from the raw pickles
    raw_imgs, raw_labels = [], []
    for i in range(1, 6):
        with open(str(tmp_path / "cifar-10-batches-py" / f"data_batch_{i}"),
                  "rb") as f:
            d = pickle.load(f, encoding="bytes")
        raw_imgs.append(d[b"data"].reshape(-1, 3, 32, 32)
                        .transpose(0, 2, 3, 1))
        raw_labels.append(np.asarray(d[b"labels"]))
    raw_imgs = np.concatenate(raw_imgs)
    raw_labels = np.concatenate(raw_labels)
    counts = np.bincount(raw_labels, minlength=10)
    np.testing.assert_array_equal(ds.images_per_client, counts)
    # flat order is class-sorted; client id == class (reference
    # fed_cifar.py:78-84)
    b = ds.gather(np.arange(len(ds)))
    np.testing.assert_array_equal(
        b["target"], np.repeat(np.arange(10), counts))
    # every class-0 image from the raw batches appears in client 0's slab
    class0 = raw_imgs[raw_labels == 0]
    np.testing.assert_array_equal(
        np.sort(b["image"][: counts[0]].reshape(counts[0], -1), axis=0),
        np.sort(class0.reshape(counts[0], -1), axis=0))
    # val split loads too
    val = FedCIFAR10(str(tmp_path), train=False)
    assert len(val) == 20


# -------------------------------------------------------------- Persona


PERSONA_FIXTURE = {
    "train": [
        {"personality": ["i love cats .", "i am a chef ."],
         "utterances": [
             {"history": ["hello how are you ?"],
              "candidates": ["bad answer here .",
                             "great , cooking dinner now ."]},
             {"history": ["hello how are you ?",
                          "great , cooking dinner now .",
                          "what do you cook ?"],
              "candidates": ["i have no idea .",
                             "mostly fish for my cats ."]},
         ]},
        {"personality": ["i run marathons .", "i live in ohio ."],
         "utterances": [
             {"history": ["hi there !"],
              "candidates": ["wrong reply .",
                             "hi , just back from a run ."]},
         ]},
    ],
    "valid": [
        {"personality": ["i play guitar ."],
         "utterances": [
             {"history": ["what are your hobbies ?"],
              "candidates": ["none of that .", "music , mostly guitar ."]},
         ]},
    ],
}


def _write_bpe_fixture(d):
    """Minimal on-disk GPT-2 BPE: full byte-level alphabet vocab + no
    merges — a valid tokenizer the real `GPT2Tokenizer.from_pretrained`
    branch loads offline."""
    from transformers.models.gpt2.tokenization_gpt2 import bytes_to_unicode

    os.makedirs(d, exist_ok=True)
    alphabet = list(bytes_to_unicode().values())
    vocab = {ch: i for i, ch in enumerate(alphabet)}
    vocab["<|endoftext|>"] = len(vocab)
    with open(os.path.join(d, "vocab.json"), "w") as f:
        json.dump(vocab, f)
    with open(os.path.join(d, "merges.txt"), "w") as f:
        f.write("#version: 0.2\n")
    return d


@pytest.mark.slow
def test_persona_real_corpus_with_real_bpe(tmp_path):
    """The real-corpus branch (reference fed_persona.py:23-28, 31-392) +
    the real GPT-2 BPE tokenizer branch (get_tokenizer, reference
    fed_persona.py:63-75), end to end from files on disk."""
    from commefficient_tpu.data.fed_persona import FedPERSONA, get_tokenizer

    tok_dir = _write_bpe_fixture(str(tmp_path / "bpe"))
    tok = get_tokenizer(tok_dir)
    from transformers import GPT2Tokenizer
    assert isinstance(tok, GPT2Tokenizer)      # NOT the Hash fallback
    # the 5 reference special tokens were added (gpt2_train.py:101-112):
    # each resolves to a REAL id (convert_tokens_to_ids returns unk for
    # unknown tokens, so compare against it), all distinct
    ids = [tok.convert_tokens_to_ids(t) for t in
           ("<bos>", "<eos>", "<speaker1>", "<speaker2>", "<pad>")]
    assert tok.unk_token_id not in ids
    assert len(set(ids)) == 5

    data_dir = str(tmp_path / "persona")
    os.makedirs(data_dir)
    with open(os.path.join(data_dir, "personachat_self_original.json"),
              "w") as f:
        json.dump(PERSONA_FIXTURE, f)

    ds = FedPERSONA(data_dir, tokenizer=tok, max_seq_len=96)
    # clients = distinct personalities
    assert ds.num_clients == 2
    assert ds.images_per_client.tolist() == [2, 1]
    b = ds.gather(np.arange(3))
    assert b["input_ids"].shape == (3, 2, 96)
    # gold candidate is last (reference convention)
    np.testing.assert_array_equal(b["mc_label"], [1, 1, 1])
    # the packed tokens decode back to the corpus text: find the gold
    # reply of the first utterance inside candidate 1's sequence
    seq = tok.decode([t for t in b["input_ids"][0, 1]
                      if t != tok.convert_tokens_to_ids("<pad>")])
    assert "great , cooking dinner now ." in seq
    assert "i love cats ." in seq              # persona prefix
    # prep config records the real corpus + tokenizer identity
    with open(os.path.join(data_dir, "FedPERSONA_persona_prep.json")) as f:
        prep = json.load(f)
    assert prep["corpus"] == "real"
    val = FedPERSONA(data_dir, train=False, tokenizer=tok, max_seq_len=96)
    assert len(val) == 1                       # one valid-split utterance


# ----------------------------------------------------------- LEAF EMNIST


def _write_leaf_femnist(root, seed=3):
    """Tiny LEAF FEMNIST tree in the reference's exact on-disk format
    (reference fed_emnist.py:95-123 reads train/ and test/ directories of
    ``all_data_*.json`` files, each ``{"users": [...], "num_samples":
    [...], "user_data": {user: {"x": [784-float lists], "y": [ints]}}}``).
    Train data is spread over TWO json files to exercise the multi-file
    concatenation."""
    rng = np.random.RandomState(seed)

    def blob(users, per):
        user_data = {}
        for u, n in zip(users, per):
            user_data[u] = {
                "x": rng.rand(n, 784).round(4).tolist(),
                "y": [int(t) for t in rng.randint(0, 62, n)],
            }
        return {"users": users, "num_samples": per, "user_data": user_data}

    os.makedirs(os.path.join(root, "train"), exist_ok=True)
    os.makedirs(os.path.join(root, "test"), exist_ok=True)
    train_blobs = [blob(["f0000_01", "f0001_02"], [6, 4]),
                   blob(["f0002_03"], [5])]
    for i, b in enumerate(train_blobs):
        with open(os.path.join(root, "train", f"all_data_{i}.json"),
                  "w") as f:
            json.dump(b, f)
    with open(os.path.join(root, "test", "all_data_0.json"), "w") as f:
        json.dump(blob(["f0000_01", "f0002_03"], [3, 2]), f)
    return train_blobs


def test_leaf_emnist_ingest_and_round(tmp_path):
    """The real LEAF json branch (_read_leaf) end to end: per-writer
    natural clients with exact pixel round-trip, then a federated sketch
    round + validation over the ingested data (VERDICT r3 item 5)."""
    import jax
    import jax.numpy as jnp

    from commefficient_tpu import models
    from commefficient_tpu.config import FedConfig
    from commefficient_tpu.core import FedRuntime
    from commefficient_tpu.data import FedSampler, transforms_for
    from commefficient_tpu.data.fed_emnist import FedEMNIST
    from commefficient_tpu.losses import make_cv_loss

    train_blobs = _write_leaf_femnist(str(tmp_path))
    ds = FedEMNIST(str(tmp_path))            # synthetic=None, LEAF found
    assert ds.num_clients == 3               # writers across both files
    assert ds.images_per_client.tolist() == [6, 4, 5]
    # exact round-trip of the first writer's pixels and labels, in order
    b = ds.gather(np.arange(6))
    ud = train_blobs[0]["user_data"]["f0000_01"]
    np.testing.assert_allclose(
        b["image"].reshape(6, -1), np.asarray(ud["x"], np.float32),
        rtol=0, atol=1e-6)
    np.testing.assert_array_equal(b["target"], ud["y"])
    val = FedEMNIST(str(tmp_path), train=False)
    assert len(val) == 5                     # test-split samples pooled

    # a real federated round over the ingested clients
    tf = transforms_for("EMNIST", train=False)
    cfg = FedConfig(mode="sketch", error_type="virtual", local_momentum=0.0,
                    virtual_momentum=0.9, weight_decay=0.0, num_workers=2,
                    local_batch_size=4, k=50, num_rows=3, num_cols=512,
                    num_blocks=2, num_clients=ds.num_clients,
                    dataset_name="EMNIST", track_bytes=False,
                    compute_dtype="float32")
    model = models.ResNet9(num_classes=62,
                           channels={"prep": 2, "layer1": 2, "layer2": 2,
                                     "layer3": 2}, do_batchnorm=True)
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 28, 28, 1)))
    rt = FedRuntime(cfg, params, make_cv_loss(model, "float32"),
                    num_clients=ds.num_clients)
    state = rt.init_state()
    for rnd in FedSampler(ds.data_per_client, cfg.num_workers,
                          cfg.local_batch_size, seed=0):
        batch = {k: jnp.asarray(v) for k, v in tf(ds.gather(rnd.idx)).items()}
        state, m = rt.round(state, rnd.client_ids, batch, rnd.mask, 0.05)
        break
    assert np.isfinite(np.asarray(m["results"][0])).all()
    vb = {k: jnp.asarray(v) for k, v in tf(val.gather(np.arange(5))).items()}
    res, _ = rt.val(state, vb, jnp.ones((5,), bool))
    assert np.isfinite(float(res[0]))


def test_leaf_emnist_missing_test_split(tmp_path):
    """A train split without its test split must fail loudly, not fall
    back to synthetic validation data."""
    from commefficient_tpu.data.fed_emnist import FedEMNIST

    _write_leaf_femnist(str(tmp_path))
    os.unlink(str(tmp_path / "test" / "all_data_0.json"))
    with pytest.raises(FileNotFoundError, match="test split is missing"):
        FedEMNIST(str(tmp_path))


# ------------------------------------------------------------- ImageNet


def _write_imagenet_tree(root, wnids=("n01440764", "n01443537"), per=3,
                         size=48):
    from PIL import Image
    rng = np.random.RandomState(0)
    for split, n in (("train", per), ("val", 1)):
        for wnid in wnids:
            d = os.path.join(root, split, wnid)
            os.makedirs(d, exist_ok=True)
            for i in range(n):
                arr = rng.randint(0, 255, (size + 10, size, 3),
                                  dtype=np.uint8)
                Image.fromarray(arr).save(
                    os.path.join(d, f"{wnid}_{i}.JPEG"), "JPEG")


def test_imagenet_tree_ingest(tmp_path):
    """The real image-tree branch (reference fed_imagenet.py:12-76): one
    wnid class per client, decoded + resized at prepare time."""
    from commefficient_tpu.data.fed_imagenet import FedImageNet

    _write_imagenet_tree(str(tmp_path))
    ds = FedImageNet(str(tmp_path), image_size=32)
    assert ds.num_clients == 2
    assert ds.images_per_client.tolist() == [3, 3]
    b = ds.gather(np.arange(6))
    assert b["image"].shape == (6, 32, 32, 3)
    assert b["image"].dtype == np.uint8
    np.testing.assert_array_equal(b["target"], [0, 0, 0, 1, 1, 1])
    val = FedImageNet(str(tmp_path), train=False, image_size=32)
    assert len(val) == 2


# ----------------------------------------------------------- multi-host


@pytest.mark.slow
def test_multihost_two_process_round():
    """scripts/multihost_dryrun.py: two real jax.distributed processes
    execute one sharded federated round over a global 8-device mesh and
    match the single-process golden checksum (PARITY §2.8 multi-host
    claim, executed — VERDICT r3 item 7)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts",
                                      "multihost_dryrun.py")],
        cwd=repo, capture_output=True, text=True, timeout=900)
    assert out.returncode == 0, out.stdout[-3000:] + out.stderr[-2000:]
    assert "2-process round == single-process round" in out.stdout


@pytest.mark.slow
def test_imagenet_recipe_smoke(tmp_path):
    """scripts/imagenet.sh --test: the FixupResNet50 recipe executes one
    real federated round end to end (tiny synthetic tree, single device)."""
    _write_imagenet_tree(str(tmp_path), per=2, size=40)
    env = dict(os.environ,
               DATASET_DIR=str(tmp_path),
               JAX_PLATFORMS="cpu")
    out = subprocess.run(
        ["bash", "scripts/imagenet.sh", "--test",
         "--num_workers", "2", "--num_clients", "2",
         "--local_batch_size", "2", "--valid_batch_size", "2",
         "--checkpoint_every", "0", "--checkpoint_path",
         str(tmp_path / "ck"), "--mesh_shape", ""],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env, capture_output=True, text=True, timeout=1800)
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    assert "Total Upload" in out.stdout
