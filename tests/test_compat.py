"""Reference-API facade: train/val calls, client splitting, LR wiring."""

import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.compat import FedModel, FedOptimizer, split_by_client
from commefficient_tpu.config import FedConfig
from tests.test_parallel import quad_loss


def make_model(**kw):
    cfg_kw = dict(mode="uncompressed", error_type="none", local_momentum=0.0,
                  virtual_momentum=0.0, weight_decay=0.0, num_workers=2,
                  local_batch_size=4, num_clients=6, track_bytes=True)
    cfg_kw.update(kw)
    cfg = FedConfig(**cfg_kw)
    params = {"w": jnp.asarray(
        np.random.RandomState(0).randn(5, 2), jnp.float32)}
    fm = FedModel(None, params, quad_loss, cfg, num_clients=6)
    opt = fm.attach_optimizer(FedOptimizer(cfg, lr=0.1))
    return fm, opt


def flat_batch(rng, n, clients):
    return {
        "client_id": np.asarray(clients),
        "x": rng.randn(n, 5).astype(np.float32),
        "y": rng.randn(n, 2).astype(np.float32),
    }


def test_split_by_client():
    rng = np.random.RandomState(0)
    clients = np.array([3, 1, 3, 1, 3])
    b = flat_batch(rng, 5, clients)
    ids, gathered, masks = split_by_client(
        clients, {k: v for k, v in b.items() if k != "client_id"}, 2, 4)
    np.testing.assert_array_equal(sorted(ids), [1, 3])
    assert masks.sum() == 5
    slot3 = list(ids).index(3)
    np.testing.assert_allclose(gathered["x"][slot3][:3],
                               b["x"][clients == 3])


def test_split_underfull_raises():
    with pytest.raises(ValueError):
        split_by_client(np.array([2, 2]), {"x": np.zeros((2, 1))}, 2, 4)


def test_train_step_updates_weights():
    fm, opt = make_model()
    rng = np.random.RandomState(1)
    w0 = np.asarray(fm.state.ps_weights).copy()
    b = flat_batch(rng, 8, np.array([0, 0, 0, 0, 2, 2, 2, 2]))
    loss, acc, down, up = fm(b)
    opt.step()
    assert loss.shape == (2,) and np.isfinite(loss).all()
    w1 = np.asarray(fm.state.ps_weights)
    assert np.abs(w1 - w0).max() > 0
    assert int(fm.state.step) == 1
    # byte accounting: exactly the two participating clients uploaded
    assert (up > 0).sum() == 2


def test_lr_flows_from_optimizer():
    fm, opt = make_model()
    rng = np.random.RandomState(1)
    b = flat_batch(rng, 8, np.array([0, 0, 0, 0, 2, 2, 2, 2]))
    w0 = np.asarray(fm.state.ps_weights).copy()
    fm(b)
    d1 = np.abs(np.asarray(fm.state.ps_weights) - w0).max()

    fm2, opt2 = make_model()
    opt2.set_lr(0.2)
    fm2(b)
    d2 = np.abs(np.asarray(fm2.state.ps_weights) - w0).max()
    np.testing.assert_allclose(d2, 2 * d1, rtol=1e-5)


def test_val_call():
    fm, _ = make_model()
    fm.train(False)
    rng = np.random.RandomState(2)
    b = flat_batch(rng, 10, np.full(10, -1))
    loss, acc = fm(b)
    assert loss.shape == (1,) and np.isfinite(loss).all()


def test_get_params_roundtrip():
    fm, _ = make_model()
    p = fm.get_params()
    assert p["w"].shape == (5, 2)
