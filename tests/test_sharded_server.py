"""Sharded sketch SERVER tail (core/server.sharded_sketch_server_update).

The round's server half — table momentum+EF, decode, top-k, error
feedback — runs reduce-scattered over the mesh: each device owns c/n
table columns and decodes only its d_pad/n coordinate range, and a tiny
(n, k) candidate all-gather + order-stable merge yields the global
top-k. Sharding must never change numerics: the round-level gates here
assert parity against the replicated tail (bitwise on this backend —
the merge is order-stable and the scattered reduce sums in device
order), and the op-level tests pin the range decode and the merge
against numpy references / the unsharded ``topk_with_idx``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.config import FedConfig
from commefficient_tpu.core import DecodeOverlapRound, FedRuntime
from commefficient_tpu.ops.circulant import make_circulant_sketch
from commefficient_tpu.ops.sketch import make_sketch
from commefficient_tpu.ops.topk import (local_topk_candidates,
                                        merge_topk_candidates,
                                        topk_with_idx)
from commefficient_tpu.parallel import make_mesh
from commefficient_tpu.utils.jax_compat import shard_map


def _sketches(d, c=64, r=3):
    return [make_sketch(d, c, r, num_blocks=4),
            make_circulant_sketch(d, c, r)]


# ------------------------------------------------------- range decode


@pytest.mark.parametrize("impl", ["hash", "circ"])
def test_decode_range_matches_full_decode(impl):
    """decode_range(table, s, n) == decode(table)[s:s+n] — numpy-level
    parity for both estimator implementations, at several offsets
    including a non-block-aligned one."""
    d = 1000
    rng = np.random.RandomState(0)
    v = jnp.asarray(rng.randn(d), jnp.float32)
    cs = _sketches(d)[0 if impl == "hash" else 1]
    table = cs.encode(v)
    full = np.asarray(cs.decode(table))
    for start, length in ((0, d), (100, 300), (437, 129), (999, 1)):
        got = np.asarray(cs.decode_range(table, start, length))
        assert np.array_equal(got, full[start:start + length]), (
            impl, start, length)


@pytest.mark.parametrize("impl", ["hash", "circ"])
def test_decode_range_traced_start_under_jit(impl):
    """A traced start (the shard_map axis_index case) must produce the
    same estimates as the static-start call."""
    d = 777
    rng = np.random.RandomState(1)
    v = jnp.asarray(rng.randn(d), jnp.float32)
    cs = _sketches(d)[0 if impl == "hash" else 1]
    table = cs.encode(v)
    full = np.asarray(cs.decode(table))
    f = jax.jit(lambda t, s: cs.decode_range(t, s, 250))
    for start in (0, 13, 500):
        got = np.asarray(f(table, jnp.int32(start)))
        assert np.array_equal(got, full[start:start + 250]), (impl, start)


@pytest.mark.parametrize("impl", ["hash", "circ"])
def test_decode_range_zero_beyond_d(impl):
    """Coordinates >= d (mesh padding) decode to EXACTLY 0 — a padding
    coordinate must never win a top-k against real estimates."""
    d = 100
    rng = np.random.RandomState(2)
    v = jnp.asarray(rng.randn(d), jnp.float32)
    cs = _sketches(d, c=32)[0 if impl == "hash" else 1]
    table = cs.encode(v)
    full = np.asarray(cs.decode(table))
    got = np.asarray(cs.decode_range(table, d - 8, 40))
    assert np.array_equal(got[:8], full[-8:]), impl
    assert (got[8:] == 0).all(), impl


@pytest.mark.parametrize("impl", ["hash", "circ"])
def test_decode_range_inside_shard_map(impl):
    """The sharded tail's exact usage: each device decodes its
    axis_index-dependent slice of the padded range; the concatenated
    shards equal the full decode (plus zero padding)."""
    d = 1000
    n = 8
    d_pad = -(-d // n) * n
    blk = d_pad // n
    rng = np.random.RandomState(3)
    v = jnp.asarray(rng.randn(d), jnp.float32)
    cs = _sketches(d)[0 if impl == "hash" else 1]
    table = cs.encode(v)
    mesh = make_mesh((n,), ("clients",))
    from jax.sharding import PartitionSpec as P

    def block(t, cs):
        i = jax.lax.axis_index("clients")
        return cs.decode_range(t, i * blk, blk)

    out = shard_map(block, mesh=mesh,
                    in_specs=(P(), jax.tree.map(lambda _: P(), cs)),
                    out_specs=P("clients"), check_vma=False)(table, cs)
    full = np.asarray(cs.decode(table))
    got = np.asarray(out)
    assert got.shape == (d_pad,)
    assert np.array_equal(got[:d], full), impl
    assert (got[d:] == 0).all(), impl


@pytest.mark.parametrize("impl", ["hash", "circ"])
def test_decode_range_bf16_wire_table(impl):
    """Range decode of a table that went through the bf16 wire rounding
    (the --sketch_dtype bfloat16 collective payload) still matches the
    full decode of the SAME rounded table — the wire dtype changes what
    the server sees, never how the two decode paths see it."""
    d = 600
    rng = np.random.RandomState(4)
    v = jnp.asarray(rng.randn(d), jnp.float32)
    cs = _sketches(d)[0 if impl == "hash" else 1]
    table = cs.encode(v).astype(jnp.bfloat16).astype(jnp.float32)
    full = np.asarray(cs.decode(table))
    got = np.asarray(cs.decode_range(table, 64, 400))
    assert np.array_equal(got, full[64:464]), impl


# ------------------------------------------------------- top-k merge


def _sharded_select(x, k, n_shards):
    """Reference pipeline: per-shard candidates + merge over contiguous
    slices of ``x`` (len divisible by n_shards)."""
    blk = x.shape[0] // n_shards
    cv, ci = [], []
    for i in range(n_shards):
        lv, li = local_topk_candidates(x[i * blk:(i + 1) * blk], k, i * blk)
        cv.append(lv)
        ci.append(li)
    return merge_topk_candidates(jnp.stack(cv), jnp.stack(ci), k)


@pytest.mark.parametrize("k,n", [(7, 4), (8, 8), (13, 8), (1, 8)])
def test_merge_matches_unsharded_topk(k, n):
    """k not divisible by n, k == shards, k == 1: the merged selection
    (values AND index order) equals topk_with_idx on the full vector."""
    rng = np.random.RandomState(k * 31 + n)
    x = jnp.asarray(rng.randn(128), jnp.float32)
    ref_dense, ref_idx = topk_with_idx(x, k)
    mv, mi = _sharded_select(x, k, n)
    assert np.array_equal(np.asarray(mi), np.asarray(ref_idx)), (k, n)
    dense = np.zeros(128, np.float32)
    dense[np.asarray(mi)] = np.asarray(mv)
    assert np.array_equal(dense, np.asarray(ref_dense)), (k, n)


def test_merge_ties_straddling_shard_boundaries():
    """Equal magnitudes placed on both sides of shard boundaries (and
    a sign flip, which squares to the same key) must resolve exactly
    like the unsharded top-k: ascending index among equals."""
    n, k = 8, 6
    x = np.zeros(128, np.float32)
    x[15], x[16] = 2.0, 2.0          # straddles the 0|1 boundary
    x[31], x[32] = -2.0, 2.0         # sign flip straddling 1|2
    x[64], x[127] = 2.0, 2.0         # far shards
    x[40] = 5.0                      # one clear winner
    xv = jnp.asarray(x)
    ref_dense, ref_idx = topk_with_idx(xv, k)
    mv, mi = _sharded_select(xv, k, n)
    assert np.array_equal(np.asarray(mi), np.asarray(ref_idx))
    dense = np.zeros(128, np.float32)
    dense[np.asarray(mi)] = np.asarray(mv)
    assert np.array_equal(dense, np.asarray(ref_dense))


def test_merge_k_exceeds_shard_length():
    """k > per-shard candidate pool (k > d/n): every shard contributes
    its whole slice and the merge degenerates to the exact top-k."""
    n = 8
    d = 64                            # blk = 8 < k = 24
    k = 24
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(d), jnp.float32)
    ref_dense, ref_idx = topk_with_idx(x, k)
    mv, mi = _sharded_select(x, k, n)
    assert np.array_equal(np.asarray(mi), np.asarray(ref_idx))
    dense = np.zeros(d, np.float32)
    dense[np.asarray(mi)] = np.asarray(mv)
    assert np.array_equal(dense, np.asarray(ref_dense))


def test_merge_rejects_insufficient_candidates():
    """A candidate stack that cannot cover k is a caller bug, not a
    silent truncation."""
    with pytest.raises(AssertionError):
        merge_topk_candidates(jnp.zeros((2, 3)), jnp.zeros((2, 3),
                                                           jnp.int32), 8)


# ------------------------------------------------- round-level parity


def _params_and_loss():
    key = jax.random.PRNGKey(0xABCD)
    D, C = 24, 10
    P_mat = jax.random.normal(jax.random.fold_in(key, 1), (D, C),
                              jnp.float32)

    def loss_fn(params, batch, mask):
        logits = batch["x"] @ params["w"]
        m = mask.astype(jnp.float32)
        denom = jnp.maximum(m.sum(), 1.0)
        lp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(lp, batch["target"][:, None],
                                   axis=1)[:, 0]
        loss = (nll * m).sum() / denom
        return loss, (loss,)

    def batch_for(W, B, g):
        k1 = jax.random.fold_in(key, 1000 + g)
        x = jax.random.normal(k1, (W, B, D), jnp.float32)
        t = jnp.argmax(x @ P_mat, axis=-1).astype(jnp.int32)
        return {"x": x, "target": t}

    return {"w": jnp.zeros((D, C), jnp.float32)}, loss_fn, batch_for


def _sketch_cfg(**kw):
    base = dict(mode="sketch", error_type="virtual", local_momentum=0.0,
                virtual_momentum=0.9, weight_decay=0.0, num_workers=8,
                local_batch_size=4, k=8, num_rows=3, num_cols=64,
                num_blocks=2, num_clients=16, track_bytes=True)
    base.update(kw)
    return FedConfig(**base)


def _run_rounds(cfg, n_rounds=4, lr=0.1, adapter=None):
    params, loss_fn, batch_for = _params_and_loss()
    mesh = make_mesh((8,), ("clients",))
    rt = FedRuntime(cfg, params, loss_fn, num_clients=cfg.num_clients,
                    mesh=mesh)
    obj = adapter(rt) if adapter is not None else rt
    st = obj.init_state() if adapter is not None else rt.init_state()
    ids = jnp.arange(8, dtype=jnp.int32)
    mask = jnp.ones((8, 4), bool)
    losses = []
    for g in range(1, n_rounds + 1):
        st, m = obj.round(st, ids, batch_for(8, 4, g), mask, lr)
        losses.append(np.asarray(m["results"][0]))
    return rt, np.stack(losses), np.asarray(rt.flat_weights(st))


@pytest.mark.parametrize("variant", [
    {},                                   # circ, zero-EF, f32
    {"sketch_impl": "hash"},
    {"sketch_ef": "subtract"},
    {"sketch_dtype": "bfloat16"},         # wire covers the scattered reduce
])
def test_sharded_round_matches_replicated(variant):
    """The tentpole parity gate at test granularity: a sharded-server
    sketch round must train identically to the replicated tail on this
    backend (the merge is order-stable and the scattered reduce sums in
    device order, so the rounds are BITWISE equal here; on other
    toolchains the committed contract is the dryrun's tolerance gate)."""
    rt_s, losses_s, w_s = _run_rounds(_sketch_cfg(**variant))
    assert rt_s._sharded_server, variant
    rt_r, losses_r, w_r = _run_rounds(
        _sketch_cfg(sketch_sharded_server="off", **variant))
    assert not rt_r._sharded_server
    assert np.all(np.isfinite(losses_s)), variant
    assert (losses_s == losses_r).all(), (variant, losses_s, losses_r)
    assert (w_s == w_r).all(), variant


def test_sharded_round_per_param_lr_vector():
    """The per-parameter LR vector path (Fixup groups): the sharded tail
    multiplies d_pad-length shards, the replicated tail a true-d slice
    — same trained weights."""
    params, loss_fn, batch_for = _params_and_loss()
    mesh = make_mesh((8,), ("clients",))
    d = 24 * 10
    lr_vec = np.linspace(0.01, 0.2, d).astype(np.float32)
    outs = {}
    for ss in ("auto", "off"):
        cfg = _sketch_cfg(sketch_sharded_server=ss)
        rt = FedRuntime(cfg, params, loss_fn, num_clients=cfg.num_clients,
                        mesh=mesh)
        st = rt.init_state()
        ids = jnp.arange(8, dtype=jnp.int32)
        mask = jnp.ones((8, 4), bool)
        for g in range(1, 4):
            st, m = rt.round(st, ids, batch_for(8, 4, g), mask, lr_vec)
        outs[ss] = np.asarray(rt.flat_weights(st))
    assert (outs["auto"] == outs["off"]).all()


def test_decode_overlap_composes_with_sharded_server():
    """--decode_overlap + sharded server: the cohort ends at the LOCAL
    partial tables (no collective), the decode executable runs the
    deferred reduce-scatter + sharded tail — bit-identical to the
    monolithic sharded round (the PR-9 gate pattern, extended)."""
    _, losses_mono, w_mono = _run_rounds(_sketch_cfg())
    rt, losses_split, w_split = _run_rounds(
        _sketch_cfg(decode_overlap=True), adapter=DecodeOverlapRound)
    assert rt._reduce_in_decode
    assert (losses_split == losses_mono).all()
    assert (w_split == w_mono).all()


# ------------------------------------------- eligibility + ledger


def test_sharded_server_on_requires_mesh():
    params, loss_fn, _ = _params_and_loss()
    with pytest.raises(ValueError, match="no mesh"):
        FedRuntime(_sketch_cfg(sketch_sharded_server="on", num_workers=2,
                               num_clients=4),
                   params, loss_fn, num_clients=4)


def test_sharded_server_on_requires_divisible_cols():
    params, loss_fn, _ = _params_and_loss()
    mesh = make_mesh((8,), ("clients",))
    with pytest.raises(ValueError, match="num_cols"):
        FedRuntime(_sketch_cfg(sketch_sharded_server="on", num_cols=60,
                               exact_num_cols=True),
                   params, loss_fn, num_clients=16, mesh=mesh)


def test_sharded_server_on_requires_sketch_mode():
    with pytest.raises(ValueError, match="mode sketch"):
        FedConfig(mode="uncompressed", error_type="none",
                  sketch_sharded_server="on")


def test_ineligible_auto_falls_back_to_replicated_hlo():
    """auto with an ineligible geometry (c % n != 0) must trace the
    SAME program as the explicit off — the fallback IS the replicated
    round, byte for byte."""
    params, loss_fn, batch_for = _params_and_loss()
    mesh = make_mesh((8,), ("clients",))
    cfgs = [_sketch_cfg(num_cols=60, exact_num_cols=True,
                        sketch_sharded_server=ss) for ss in ("auto", "off")]
    texts = []
    for cfg in cfgs:
        rt = FedRuntime(cfg, params, loss_fn, num_clients=cfg.num_clients,
                        mesh=mesh)
        assert not rt._sharded_server
        st = rt.init_state()
        texts.append(rt._round.lower(
            st, jnp.arange(8, dtype=jnp.int32), batch_for(8, 4, 1),
            jnp.ones((8, 4), bool), jnp.asarray(0.1, jnp.float32),
            rt.cs, rt._gid).as_text())
    assert texts[0] == texts[1]


def test_teleview_perchip_drop_gate(tmp_path):
    """The scaling harness's regression gate: teleview diff exits 1
    when the candidate stream's last bench per_chip_items_per_s drops
    more than --perchip_drop relative to the baseline's, and 0 within
    the threshold (jax-free, like every teleview gate)."""
    import importlib.util
    import json
    import os

    spec = importlib.util.spec_from_file_location(
        "teleview",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts", "teleview.py"))
    tv = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tv)

    def stream(path, per_chip):
        evs = [
            {"event": "manifest", "t": 0.0, "seq": 0},
            {"event": "bench", "t": 1.0, "seq": 1, "metric": "scaling",
             "result": {"items_per_s": per_chip * 8,
                        "per_chip_items_per_s": per_chip}},
        ]
        with open(path, "w") as f:
            for e in evs:
                f.write(json.dumps(e) + "\n")
        return str(path)

    base = stream(tmp_path / "a.jsonl", 100.0)
    bad = stream(tmp_path / "b.jsonl", 50.0)     # 50% drop
    ok = stream(tmp_path / "c.jsonl", 80.0)      # 20% drop
    assert tv.main(["diff", base, bad]) == 1
    assert tv.main(["diff", base, ok]) == 0
    # the threshold is the knob the virtual-device dryrun tunes
    assert tv.main(["diff", base, bad, "--perchip_drop", "0.6"]) == 0


def test_sharded_round_ledger_kinds():
    """The collective story the dryrun commits, at test granularity:
    the sharded sketch round's ledger holds a reduce-scatter (the table
    aggregation) and the ~n*k*8-byte candidate all-gathers, and NO
    table-sized (or larger) all-reduce — the replicated psum is gone."""
    from commefficient_tpu.telemetry.collectives import (round_ledger,
                                                         summarize_ledger)
    params, loss_fn, batch_for = _params_and_loss()
    mesh = make_mesh((8,), ("clients",))
    cfg = _sketch_cfg()
    rt = FedRuntime(cfg, params, loss_fn, num_clients=cfg.num_clients,
                    mesh=mesh)
    assert rt._sharded_server
    st = rt.init_state()
    led = round_ledger(rt, st, jnp.arange(8, dtype=jnp.int32),
                       batch_for(8, 4, 1), jnp.ones((8, 4), bool))
    counts = summarize_ledger(led)["counts"]
    assert counts.get("reduce-scatter", 0) >= 1, counts
    table = cfg.num_rows * cfg.num_cols
    big_ar = [e for e in led
              if e["kind"] == "all-reduce" and e["n_elements"] >= table]
    assert not big_ar, big_ar
    k_loc = min(cfg.k, rt.d_pad // 8)
    cand = [e for e in led if e["kind"] == "all-gather"
            and e["n_elements"] == 8 * k_loc]
    assert sum(e["bytes"] for e in cand) == 8 * k_loc * 8, cand
