"""Test harness: run everything on a simulated 8-device CPU mesh.

Mirrors the survey's test strategy (SURVEY.md §4): the reference had no
working automated tests; here all "distributed" behavior is validated on
virtual CPU devices via ``--xla_force_host_platform_device_count`` so the
suite runs anywhere, including CI without TPUs.

Must set the env vars BEFORE jax is imported anywhere.
"""

import os

# overwrite, not setdefault: the shell presets JAX_PLATFORMS=axon (real TPU)
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The env var alone is NOT enough: an axon/TPU sitecustomize may have run
# ``jax.config.update("jax_platforms", "axon,cpu")`` at interpreter start,
# which overrides JAX_PLATFORMS and makes the first ``jax.devices()`` block
# on the TPU tunnel. Re-assert CPU at the config layer (backends are not
# initialized yet, so this wins).
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    return jax.devices()


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running end-to-end smokes (driver recipes)")
