"""Ring attention vs dense causal attention: exact numerical parity.

The ring implementation (parallel/ring.py) must produce the same output as
single-device dense causal attention for any sharding of the sequence axis —
this is the correctness contract that lets GPT-2 swap ``attn_impl``
transparently.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.models.gpt2 import dense_causal_attention
from commefficient_tpu.parallel.mesh import make_mesh
from commefficient_tpu.parallel.ring import make_ring_attention


@pytest.mark.parametrize("B,S,H,D", [(2, 32, 4, 8), (1, 64, 2, 16)])
def test_ring_matches_dense(B, S, H, D):
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)

    dense = dense_causal_attention(q, k, v)

    mesh = make_mesh((8,), ("seq",))
    ring = make_ring_attention(mesh, "seq")
    out = jax.jit(ring)(q, k, v)

    np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                               rtol=2e-5, atol=2e-5)


def test_ring_in_gpt2_block():
    from commefficient_tpu.models.gpt2 import GPT2Config, GPT2LMHead

    mesh = make_mesh((4,), ("seq",))
    cfg = GPT2Config.small(compute_dtype=jnp.float32)
    dense_model = GPT2LMHead(cfg)
    ring_model = GPT2LMHead(cfg, attn_impl=make_ring_attention(mesh, "seq"))

    ids = jnp.asarray(np.random.RandomState(1).randint(0, 256, (2, 64)))
    params = dense_model.init(jax.random.PRNGKey(0), ids)
    y_dense = dense_model.apply(params, ids)
    y_ring = ring_model.apply(params, ids)
    np.testing.assert_allclose(np.asarray(y_ring), np.asarray(y_dense),
                               rtol=2e-4, atol=2e-4)
