"""Online anomaly monitor + flight recorder (telemetry/health.py):
robust-z math, each rule firing exactly once with the right severity on
injected anomalies, the 200-round healthy-stream false-positive gate,
nonfinite-precursor semantics (null-after-numeric fires, always-null
stays silent), alert-event schema round-trips, action side effects, the
one-shot postmortem bundle, and the driver wiring (nan-abort emits a
final alert and the stream survives fsync'd)."""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.checkpoint import load_state
from commefficient_tpu.core.state import FedState
from commefficient_tpu.telemetry import (AnomalyMonitor, FlightRecorder,
                                         RunTelemetry, robust_z,
                                         validate_event, validate_file)
from tests.test_telemetry import StubDS, make_runtime, read_events


def observe_rounds(mon, losses, start=1):
    fired = []
    for i, loss in enumerate(losses, start=start):
        fired += mon.observe("round", {"round": i, "loss": loss})
    return fired


# ------------------------------------------------------------- robust z


def test_robust_z_math():
    hist = [1.0, 1.1, 0.9, 1.05, 0.95, 1.0, 1.02, 0.98]
    z = robust_z(1.0, hist)
    assert abs(z["zscore"]) < 1.0
    assert z["median"] == pytest.approx(1.0, abs=0.02)
    spike = robust_z(10.0, hist)
    assert spike["zscore"] > 50
    # constant history: the MAD floor (2% of |median|) keeps z finite
    # and keeps a 1% wiggle from firing
    flat = robust_z(1.01, [1.0] * 20)
    assert abs(flat["zscore"]) < 1.0
    assert robust_z(2.0, [1.0] * 20)["zscore"] > 6


def test_robust_z_constant_zero_history_mad_floor():
    """The satellite regression: a metric whose rolling median is ZERO
    (staleness on a no-latency run, quarantine counts on a healthy
    fleet) has a zero relative MAD floor, and without an absolute
    epsilon the FIRST nonzero tick fired with an astronomical z. With
    the per-rule ``mad_floor_abs`` a single-unit tick stays far below
    the default threshold 6 while a multi-unit jump still breaches."""
    zeros = [0.0] * 20
    # the old behavior (no absolute floor): any tick is "infinitely"
    # surprising — this is the bug, kept visible as the default so
    # continuous metrics keep full sensitivity
    assert robust_z(1.0, zeros)["zscore"] > 1e6
    # the fix, applied by the monitor for count-like rules
    tick = robust_z(1.0, zeros, mad_floor_abs=0.5)
    assert abs(tick["zscore"]) < 2.0
    assert tick["mad"] == 0.5
    jump = robust_z(10.0, zeros, mad_floor_abs=0.5)
    assert jump["zscore"] > 6
    # the absolute floor composes with (never weakens) the relative one
    assert robust_z(2.0, [1.0] * 20,
                    mad_floor_abs=1e-9)["zscore"] > 6


def test_staleness_spike_quiet_on_first_tick_after_zero_history():
    """Monitor-level regression for the same satellite: a no-latency
    async run keeps staleness_max at 0; the first cohort that lands one
    commit late must NOT fire staleness_spike (it used to)."""
    mon = AnomalyMonitor(None, window=16, min_points=8)
    fired = []
    for i in range(1, 21):
        fired += mon.observe("async_round",
                             {"round": i, "staleness_max": 0.0,
                              "staleness_mean": 0.0, "error_norm": 1.0,
                              "loss": 2.0})
    fired += mon.observe("async_round",
                         {"round": 21, "staleness_max": 1.0,
                          "staleness_mean": 0.2, "error_norm": 1.0,
                          "loss": 2.0})
    assert fired == [], fired
    # a genuine staleness blowout still fires
    fired = mon.observe("async_round",
                        {"round": 22, "staleness_max": 25.0,
                         "staleness_mean": 9.0, "error_norm": 1.0,
                         "loss": 2.0})
    assert [f["rule"] for f in fired] == ["staleness_spike"]


def test_update_norm_outlier_rule():
    """PR-7 rule: the round's max per-client transmitted-update norm
    leaving the population envelope (the boosted-client signature)."""
    mon = AnomalyMonitor(None, window=16, min_points=8)
    rng = np.random.RandomState(3)
    fired = []
    for i in range(1, 21):
        q = {"tx_norm": {"max": 5.0 + 0.1 * rng.randn()},
             "loss": {"p5": 1.0, "p95": 1.2}}
        fired += mon.observe("client_stats", {"round": i, "quantiles": q})
    assert fired == []
    fired = mon.observe("client_stats", {
        "round": 21, "quantiles": {"tx_norm": {"max": 500.0},
                                   "loss": {"p5": 1.0, "p95": 1.2}}})
    assert [f["rule"] for f in fired] == ["update_norm_outlier"]
    assert fired[0]["metric"] == "client_stats.tx_norm_max"
    assert fired[0]["severity"] == "warn"


def test_quarantine_growth_rule_single_bench_quiet_jump_fires():
    """One benched client above an all-zero history is the system
    WORKING (absolute MAD floor keeps it quiet); a multi-client jump is
    the broken-fleet signature and fires."""
    mon = AnomalyMonitor(None, window=16, min_points=8)
    fired = []
    for i in range(1, 21):
        fired += mon.observe("defense", {"round": i, "quarantined": 0})
    fired += mon.observe("defense", {"round": 21, "quarantined": 1})
    assert fired == [], fired             # a single bench: quiet
    fired = mon.observe("defense", {"round": 22, "quarantined": 8})
    assert [f["rule"] for f in fired] == ["quarantine_growth"]


def test_new_rules_healthy_stream_false_positive_gate():
    """200 rounds of realistic healthy defense/client_stats streams must
    fire NEITHER new rule (mirrors the main healthy-stream gate)."""
    mon = AnomalyMonitor(None, window=32, min_points=8)
    rng = np.random.RandomState(11)
    for i in range(1, 201):
        fired = mon.observe("client_stats", {
            "round": i, "quantiles": {
                "tx_norm": {"max": 4.0 + 0.5 * abs(rng.randn())},
                "loss": {"p5": 1.5 + 0.05 * rng.randn(),
                         "p95": 2.5 + 0.05 * rng.randn()}}})
        # a healthy quarantine stream: count sits at 0 with the odd
        # transient bench that recovers
        q = 1 if i % 97 == 0 else 0
        fired += mon.observe("defense", {"round": i, "quarantined": q,
                                         "clip_frac": 0.0})
        assert fired == [], (i, fired)
    assert mon.n_observed == 400


# ------------------------------------------------------------ the rules


def test_loss_spike_fires_exactly_once_warn():
    mon = AnomalyMonitor(None, window=16, min_points=8)
    rng = np.random.RandomState(0)
    losses = list(2.0 + 0.05 * rng.randn(30)) + [40.0] + \
        list(2.0 + 0.05 * rng.randn(20))
    fired = observe_rounds(mon, losses)
    assert len(fired) == 1, fired
    assert fired[0]["rule"] == "loss_spike"
    assert fired[0]["severity"] == "warn"
    assert fired[0]["round"] == 31
    assert fired[0]["zscore"] > 6


def test_error_norm_blowup_fires_once_critical():
    """A sustained EF blowup (the round-5 subtract-EF class): the jump
    fires once; the plateau afterwards must NOT re-fire — the value
    enters the history and becomes the new normal, and the cooldown
    covers the transition."""
    mon = AnomalyMonitor(None, window=16, min_points=8)
    vals = [1.0 + 0.01 * (i % 5) for i in range(30)] + [1e6] * 30
    fired = []
    for i, v in enumerate(vals, start=1):
        fired += mon.observe("signals", {"round": i, "error_norm": v})
    assert [f["rule"] for f in fired] == ["error_norm_blowup"]
    assert fired[0]["severity"] == "critical"


def test_mfu_cliff_low_direction():
    mon = AnomalyMonitor(None, window=16, min_points=8)
    fired = []
    rng = np.random.RandomState(1)
    for i, m in enumerate(list(0.4 + 0.005 * rng.randn(20)) + [0.02],
                          start=1):
        fired += mon.observe("utilization",
                             {"round": i, "mfu": m,
                              "input_wait_frac": 0.05})
    assert [f["rule"] for f in fired] == ["mfu_cliff"]
    assert fired[0]["severity"] == "warn"
    assert fired[0]["zscore"] < -6


def test_client_loss_spread_rule():
    mon = AnomalyMonitor(None, window=16, min_points=8)
    fired = []
    rng = np.random.RandomState(2)
    spreads = list(1.0 + 0.02 * rng.randn(20)) + [50.0]
    for i, s in enumerate(spreads, start=1):
        q = {"loss": {"p5": 1.0, "p95": 1.0 + s}}
        fired += mon.observe("client_stats",
                             {"round": i, "quantiles": q})
    assert [f["rule"] for f in fired] == ["client_loss_spread"]
    assert fired[0]["metric"] == "client_stats.loss_spread"


def test_shared_metric_history_appends_once_per_event():
    """round.loss is watched by TWO rules (spike + nonfinite); one
    observed event must enter the shared history once, not per rule —
    double-appending would halve the effective rolling window."""
    mon = AnomalyMonitor(None, window=32, min_points=8)
    observe_rounds(mon, [2.0] * 10)
    assert len(mon._hist["round.loss"]) == 10


def test_tiny_alert_window_still_fires():
    """--alert_window below the default min_points must clamp
    min_points, not silently disarm every statistical rule (the deque
    could otherwise never hold enough history)."""
    mon = AnomalyMonitor(None, window=4)
    assert mon.min_points == 4
    fired = observe_rounds(mon, [2.0] * 6 + [50.0])
    assert [f["rule"] for f in fired] == ["loss_spike"]


def test_nonfinite_precursor_semantics():
    """null AFTER numeric history fires critical; a field that was
    always null (N/A for the mode) never fires."""
    mon = AnomalyMonitor(None, window=16, min_points=8)
    fired = observe_rounds(mon, [2.0] * 10 + [None])
    assert [f["rule"] for f in fired] == ["loss_nonfinite"]
    assert fired[0]["severity"] == "critical"
    assert mon.nonfinite_counts["round.loss"] == 1
    # always-null: e.g. sketch-mode topk_overlap without --signals_exact
    mon2 = AnomalyMonitor(None, window=16, min_points=8)
    for i in range(40):
        assert mon2.observe("signals",
                            {"round": i, "error_norm": 1.0,
                             "update_norm": None,
                             "topk_overlap": None}) == []


def test_healthy_stream_stays_silent_200_rounds():
    """The false-positive gate: 200 rounds of realistic noisy-but-
    healthy streams across every monitored kind must fire nothing."""
    mon = AnomalyMonitor(None, window=32, min_points=8)
    rng = np.random.RandomState(7)
    for i in range(1, 201):
        fired = mon.observe("round", {"round": i,
                                      "loss": 2.0 * np.exp(-i / 400)
                                      + 0.05 * rng.randn()})
        fired += mon.observe("signals", {
            "round": i, "grad_norm": 5.0 + 0.3 * rng.randn(),
            "error_norm": 3.0 + i / 100 + 0.1 * rng.randn(),
            "velocity_norm": 4.0 + 0.2 * rng.randn(),
            "update_norm": 1.0 + 0.05 * rng.randn(),
            "topk_overlap": min(1.0, 0.8 + 0.05 * rng.randn())})
        fired += mon.observe("utilization", {
            "round": i, "mfu": 0.42 + 0.01 * rng.randn(),
            "input_wait_frac": abs(0.05 + 0.01 * rng.randn())})
        fired += mon.observe("client_stats", {
            "round": i, "quantiles": {"loss": {
                "p5": 1.5 + 0.05 * rng.randn(),
                "p95": 2.5 + 0.05 * rng.randn()}}})
        assert fired == [], (i, fired)
    assert mon.n_observed == 800


# ------------------------------------------------ events, actions, bundle


def test_alert_events_written_and_schema_valid(tmp_path):
    tel = RunTelemetry(str(tmp_path), "test", cfg=None)
    mon = AnomalyMonitor(tel, window=16, min_points=8)
    tel.set_monitor(mon)
    assert mon.armed
    # feed THROUGH the stream (the driver wiring): monitored events
    # forwarded by event(), alert written back into the same stream
    for i, loss in enumerate([2.0] * 12 + [50.0], start=1):
        tel.event("round", round=i, epoch=1, lr=0.1, loss=loss, acc=0.5,
                  n_valid=4.0, download_bytes=None, upload_bytes=None,
                  host_s=0.0, dispatch_s=0.0, device_s=0.0)
    tel.write_summary(aborted=False, n_rounds=13)
    tel.close()
    assert validate_file(tel.path) == []
    events = read_events(tel.path)
    alerts = [e for e in events if e["event"] == "alert"]
    assert len(alerts) == 1 and alerts[0]["rule"] == "loss_spike"
    assert validate_event(alerts[0]) == []
    # the alert lands immediately after the round that fired it
    rounds = [e for e in events if e["event"] == "round"]
    assert alerts[0]["seq"] == rounds[-1]["seq"] + 1


def test_actions_warn_checkpoint_abort(capsys):
    warn = AnomalyMonitor(None, action="warn", window=16, min_points=8)
    observe_rounds(warn, [2.0] * 12 + [50.0])
    assert "ALERT [warn] loss_spike" in capsys.readouterr().err
    assert warn.pop_snapshot_request() is None
    assert not warn.abort_requested

    chk = AnomalyMonitor(None, action="checkpoint", window=16,
                         min_points=8)
    observe_rounds(chk, [2.0] * 12 + [50.0, 2.0] + [None])
    req = chk.pop_snapshot_request()
    assert req is not None and req["rule"] == "loss_spike"
    assert chk.pop_snapshot_request() is None   # one-shot
    assert not chk.abort_requested

    ab = AnomalyMonitor(None, action="abort", window=16, min_points=8)
    observe_rounds(ab, [2.0] * 12 + [50.0])
    assert ab.abort_requested


def _tiny_state():
    return FedState(ps_weights=jnp.arange(6, dtype=jnp.float32),
                    Vvelocity=jnp.zeros(6), Verror=jnp.zeros(6),
                    step=jnp.asarray(3, jnp.int32),
                    rng=jnp.zeros(2, jnp.uint32))


def test_flight_recorder_bundle(tmp_path):
    tel = RunTelemetry(str(tmp_path), "test", cfg=None)
    tel.event("round", round=1, epoch=1, lr=0.1, loss=2.0, acc=0.5,
              n_valid=4.0, download_bytes=None, upload_bytes=None,
              host_s=0.0, dispatch_s=0.0, device_s=0.0)
    rec = FlightRecorder(str(tmp_path), tel)
    out = rec.record(_tiny_state(), {"rule": "loss_spike", "round": 9})
    assert out == rec.path and rec.written
    for fn in ("state.npz", "state.meta.json", "events.jsonl",
               "alert.json"):
        assert os.path.exists(os.path.join(rec.path, fn)), fn
    # one-shot: a second alert must NOT overwrite the first bundle
    mtime = os.path.getmtime(os.path.join(rec.path, "state.npz"))
    assert rec.record(_tiny_state(), {"rule": "other"}) == out
    assert os.path.getmtime(
        os.path.join(rec.path, "state.npz")) == mtime
    # the bundle replays: state round-trips through the checkpoint
    # layer, events.jsonl holds the ring buffer, alert.json the context
    restored = load_state(os.path.join(rec.path, "state"))
    np.testing.assert_array_equal(np.asarray(restored.ps_weights),
                                  np.arange(6, dtype=np.float32))
    assert int(restored.step) == 3
    lines = open(os.path.join(rec.path, "events.jsonl")).read()
    assert '"event": "round"' in lines
    ctx = json.load(open(os.path.join(rec.path, "alert.json")))
    assert ctx["rule"] == "loss_spike"
    tel.close()


# --------------------------------------------------------- driver wiring


def test_driver_attaches_monitor_and_stream_valid(tmp_path):
    from commefficient_tpu import cv_train
    from commefficient_tpu.utils import TableLogger

    rt = make_runtime(dataset_name="SYNTH", telemetry_every=1,
                      alert_action="checkpoint")
    tel = RunTelemetry(str(tmp_path), "cv_train", cfg=rt.cfg)
    tel.instrument(rt)
    cfg = rt.cfg.replace(num_epochs=1.0, pivot_epoch=0.5)
    state, summary = cv_train.train(cfg, rt, rt.init_state(), StubDS(),
                                    StubDS(), loggers=(TableLogger(),),
                                    telemetry=tel)
    assert summary is not None
    assert tel._monitor is not None and tel._monitor.n_observed > 0
    tel.close()
    assert validate_file(tel.path) == []
    kinds = [e["event"] for e in read_events(tel.path)]
    assert "client_stats" in kinds
    # healthy 2-round smoke run: no alerts, no postmortem
    assert "alert" not in kinds
    assert not os.path.exists(os.path.join(str(tmp_path), "postmortem"))


def test_nan_abort_emits_final_alert_and_bundle(tmp_path):
    """The satellite contract: the divergence abort path writes a final
    critical alert BEFORE the nan_abort record, the flight recorder
    (armed via --alert_action checkpoint) captures the bundle, and the
    stream validates end to end (flushed+fsynced, never truncated)."""
    from commefficient_tpu import cv_train
    from commefficient_tpu.utils import TableLogger

    rt = make_runtime(dataset_name="SYNTH", telemetry_every=1,
                      alert_action="checkpoint")
    tel = RunTelemetry(str(tmp_path), "cv_train", cfg=rt.cfg)
    tel.instrument(rt)
    cfg = rt.cfg.replace(num_epochs=1.0, pivot_epoch=0.5, lr_scale=1e30)
    state, summary = cv_train.train(
        cfg, rt, rt.init_state(), StubDS(scale=1e25), StubDS(scale=1e25),
        loggers=(TableLogger(),), telemetry=tel)
    assert summary is None   # diverged
    tel.close()
    assert validate_file(tel.path) == []
    events = read_events(tel.path)
    kinds = [e["event"] for e in events]
    assert "nan_abort" in kinds
    alerts = [e for e in events if e["event"] == "alert"]
    assert any(a["rule"] == "nonfinite_abort"
               and a["severity"] == "critical" for a in alerts)
    abort_seq = next(e["seq"] for e in events
                     if e["event"] == "nan_abort")
    final = next(a for a in alerts if a["rule"] == "nonfinite_abort")
    assert final["seq"] < abort_seq
    assert events[-1]["event"] == "summary" and events[-1]["aborted"]
    # the flight recorder captured the poisoned run for replay
    bundle = os.path.join(str(tmp_path), "postmortem")
    assert os.path.exists(os.path.join(bundle, "state.npz"))
    assert os.path.exists(os.path.join(bundle, "alert.json"))
