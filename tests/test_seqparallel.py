"""Sequence/context parallelism: the federated GPT-2 round with the model
seq-sharded over a ("clients", "seq") mesh (ring attention) must match the
dense single-device round, and must cut per-device attention memory for
long sequences. New scope beyond the reference (SURVEY.md §5: no sequence
parallelism anywhere)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.config import FedConfig
from commefficient_tpu.core import FedRuntime
from commefficient_tpu.gpt2_train import PERSONA_SEQ_SPEC
from commefficient_tpu.losses import make_gpt2_train_loss
from commefficient_tpu.models.gpt2 import GPT2Config, GPT2DoubleHeads
from commefficient_tpu.parallel import make_mesh

W, B, C = 2, 2, 2


def _batch(S, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "input_ids": jnp.asarray(rng.randint(0, 256, (W, B, C, S)),
                                 jnp.int32),
        "token_type_ids": jnp.asarray(rng.randint(0, 256, (W, B, C, S)),
                                      jnp.int32),
        "mc_token_ids": jnp.asarray(rng.randint(0, S, (W, B, C)),
                                    jnp.int32),
        "lm_labels": jnp.asarray(
            np.where(rng.rand(W, B, C, S) < 0.5,
                     rng.randint(0, 256, (W, B, C, S)), -100), jnp.int32),
        "mc_label": jnp.asarray(rng.randint(0, C, (W, B)), jnp.int32),
    }


def _runtimes(S, mode="uncompressed", extra=None):
    gcfg = GPT2Config.small(compute_dtype=jnp.float32,
                            n_positions=max(128, S))
    dense_model = GPT2DoubleHeads(gcfg)
    ids = jnp.zeros((1, C, S), jnp.int32)
    params = dense_model.init(jax.random.PRNGKey(0), ids,
                              jnp.zeros((1, C), jnp.int32), ids)

    cfg = FedConfig(mode=mode, local_momentum=0.0, virtual_momentum=0.9,
                    weight_decay=0.01, num_workers=W, local_batch_size=B,
                    num_clients=4, track_bytes=False, num_results_train=2,
                    error_type=("virtual" if mode in ("sketch", "true_topk")
                                else "none"), **(extra or {}))

    rt_dense = FedRuntime(cfg, params, make_gpt2_train_loss(dense_model),
                          num_clients=4)

    mesh = make_mesh((2, 4), ("clients", "seq"))
    seq_model = GPT2DoubleHeads(gcfg, seq_axis="seq", seq_shards=4)
    loss_seq = make_gpt2_train_loss(seq_model, seq_axis="seq",
                                    seq_shards=4)
    rt_seq = FedRuntime(cfg, params, loss_seq, num_clients=4, mesh=mesh,
                        seq_spec=PERSONA_SEQ_SPEC)
    return rt_dense, rt_seq


@pytest.mark.parametrize("mode,extra", [
    ("uncompressed", {}),
    ("sketch", {"k": 20, "num_rows": 3, "num_cols": 64, "num_blocks": 2}),
    ("true_topk", {"k": 20}),
])
@pytest.mark.slow
def test_seq_sharded_round_matches_dense(mode, extra):
    rt_dense, rt_seq = _runtimes(S=32, mode=mode, extra=extra)
    ids = jnp.arange(W, dtype=jnp.int32)
    mask = jnp.ones((W, B), bool)
    s1, s2 = rt_dense.init_state(), rt_seq.init_state()
    for step in range(2):
        batch = _batch(32, seed=step)
        s1, m1 = rt_dense.round(s1, ids, batch, mask, 0.05)
        s2, m2 = rt_seq.round(s2, ids, batch, mask, 0.05)
        np.testing.assert_allclose(np.asarray(m1["results"][0]),
                                   np.asarray(m2["results"][0]),
                                   rtol=2e-4, atol=1e-5)
    d = rt_dense.cfg.grad_size
    np.testing.assert_allclose(np.asarray(s1.ps_weights),
                               np.asarray(s2.ps_weights[:d]),
                               rtol=2e-3, atol=2e-5)


@pytest.mark.slow
def test_seq_shard_boundary_mc_tokens_and_full_length():
    """Edge coverage (VERDICT r2 item 9): mc_token_ids pinned EXACTLY at
    every seq-shard boundary (first/last position of each shard — the MC
    head's hidden-state select must pick from the right shard), and a
    full n_positions-length sequence, both match the dense round."""
    S = 128  # == n_positions for GPT2Config.small(n_positions=max(128, S))
    rt_dense, rt_seq = _runtimes(S=S)
    assert rt_seq._seq_shards == 4 and S % 4 == 0
    ids = jnp.arange(W, dtype=jnp.int32)
    mask = jnp.ones((W, B), bool)
    batch = _batch(S, seed=7)
    # shard edges: 0, 31, 32, 63, 64, 95, 96, 127 — cycle them through
    # every (worker, dialogue, candidate) slot
    edges = np.array([0, 31, 32, 63, 64, 95, 96, 127], np.int32)
    mc = np.resize(edges, (W, B, C)).astype(np.int32)
    batch["mc_token_ids"] = jnp.asarray(mc)
    s1, m1 = rt_dense.round(rt_dense.init_state(), ids, batch, mask, 0.05)
    s2, m2 = rt_seq.round(rt_seq.init_state(), ids, batch, mask, 0.05)
    np.testing.assert_allclose(np.asarray(m1["results"][0]),
                               np.asarray(m2["results"][0]),
                               rtol=2e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(m1["results"][1]),
                               np.asarray(m2["results"][1]),
                               rtol=2e-4, atol=1e-5)
    d = rt_dense.cfg.grad_size
    np.testing.assert_allclose(np.asarray(s1.ps_weights),
                               np.asarray(s2.ps_weights[:d]),
                               rtol=2e-3, atol=2e-5)


@pytest.mark.slow
def test_long_seq_cuts_attention_memory():
    """The point of the seq axis: a long-S round's per-device temp memory
    must be far below the dense round's (the dense S x S score tensor and
    full-S activations shrink by the shard count)."""
    S = 512
    rt_dense, rt_seq = _runtimes(S=S)
    ids = jnp.arange(W, dtype=jnp.int32)
    mask = jnp.ones((W, B), bool)
    batch = _batch(S)

    def temp_bytes(rt):
        lowered = rt._round.lower(rt.init_state(), ids, batch, mask,
                                  jnp.asarray(0.05, jnp.float32), rt.cs,
                                  rt._gid)
        ma = lowered.compile().memory_analysis()
        return ma.temp_size_in_bytes

    dense_b, seq_b = temp_bytes(rt_dense), temp_bytes(rt_seq)
    # 8 devices, seq=4: expect a large cut; assert a conservative 2x
    assert seq_b * 2 < dense_b, (dense_b, seq_b)
