"""Fused sketch encode (core/client.py + ops/sketch.py + ops/circulant.py)
and decode overlap (core/pipeline.DecodeOverlapRound):

- the streaming/accumulating encode entry points against dense-encode
  references (sketch linearity: ``table + encode(v)``, range offsets,
  scale folding, the loop-token contract);
- ``encode_grad_tree`` leaf coalescing/splitting against ``encode(ravel)``;
- StreamMLP's hand-written ``streaming_grad`` against ``jax.grad`` of the
  same loss (the manual-VJP contract of models/stream_mlp.py);
- fused-encode rounds == unfused rounds within fp tolerance on the
  fused-clients scan AND the vmap path, incl. masked/zero-datum clients
  and update-space adversary injection (which acts on the table);
- HLO byte-identity where the fused encode must be invisible (non-sketch
  modes; auto-with-blocker == explicit off);
- the --sketch_fused_encode on fail-fast and --decode_overlap
  validation guards, and the split round's bit-identity to the
  monolithic round (the PR-5 pipeline-gate pattern, server-side);
- the blocked-scan download-byte accounting against the numpy reference
  (the (W, d) broadcast it replaced was the round's largest temp).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.flatten_util import ravel_pytree

from commefficient_tpu.config import FedConfig
from commefficient_tpu.core import (DecodeOverlapRound, FedRuntime,
                                    validate_overlap_combo)
from commefficient_tpu.core.client import (encode_grad_tree,
                                           fused_encode_blockers)
from commefficient_tpu.models.stream_mlp import (init_stream_mlp,
                                                 make_stream_mlp_loss)
from commefficient_tpu.ops.sketch import (loop_token_zero, make_sketch_impl,
                                          sketch_encode_accum)
from tests.test_parallel import make_batch, quad_loss

W, B = 4, 4


def make_cfg(**kw):
    base = dict(mode="sketch", error_type="virtual", k=5, num_rows=3,
                num_cols=32, num_blocks=2, sketch_impl="hash",
                local_momentum=0.0, virtual_momentum=0.9,
                weight_decay=0.0, num_workers=W, local_batch_size=B,
                track_bytes=True, num_clients=16, microbatch_size=2)
    base.update(kw)
    return FedConfig(**base)


def make_params(seed=0):
    return {"w": jnp.asarray(np.random.RandomState(seed).randn(6, 3),
                             jnp.float32)}


def run_rounds(cfg, n=3, params=None, loss_fn=quad_loss, seed=0):
    rt = FedRuntime(cfg, params or make_params(), loss_fn, num_clients=16)
    state = rt.init_state()
    batch, mask, ids = make_batch(seed, W=W, B=B)
    losses = []
    for _ in range(n):
        state, m = rt.round(state, ids, batch, mask, 0.1)
        losses.append(np.asarray(m["results"][0]))
    return rt, np.stack(losses), state


# --------------------------------------------------------- streaming encodes


@pytest.mark.parametrize("impl", ["hash", "circ"])
def test_encode_accum_matches_dense_encode(impl):
    """``table + encode_accum(vals @ start)`` == ``table + encode(v)``
    for v zero outside the range — for interior ranges, the full vector,
    and with a scale folded in (sketch linearity)."""
    d = 1000
    cs = make_sketch_impl(impl, d=d, c=64, r=3, num_blocks=4)
    rng = np.random.RandomState(3)
    table0 = jnp.asarray(rng.randn(3, 64), jnp.float32)
    for start, n in ((0, d), (0, 17), (128, 300), (d - 33, 33)):
        vals = jnp.asarray(rng.randn(n), jnp.float32)
        dense = jnp.zeros(d).at[start:start + n].set(vals)
        ref = table0 + cs.encode(dense)
        got = cs.encode_accum(table0, vals, start)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
        got_s = cs.encode_accum(table0, vals, start,
                                scale=jnp.asarray(2.5, jnp.float32),
                                token=jnp.asarray(1.7, jnp.float32))
        ref_s = table0 + 2.5 * cs.encode(dense)
        np.testing.assert_allclose(np.asarray(got_s), np.asarray(ref_s),
                                   rtol=1e-5, atol=1e-4)


def test_encode_accum_under_jit_and_scan():
    """The streaming encode composes with jit + lax.scan (the fused
    client path's actual shape: per-step encodes into a carried table)
    and the result equals the one-shot encode of the summed vector."""
    d = 257
    cs = make_sketch_impl("hash", d=d, c=32, r=3, num_blocks=2)
    rng = np.random.RandomState(0)
    vs = jnp.asarray(rng.randn(5, d), jnp.float32)

    @jax.jit
    def stream(vs):
        def body(tbl, v):
            return sketch_encode_accum(cs, tbl, v, 0, token=v[0]), None
        tbl, _ = jax.lax.scan(body, jnp.zeros((3, 32)), vs)
        return tbl

    ref = cs.encode(vs.sum(axis=0))
    np.testing.assert_allclose(np.asarray(stream(vs)), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_loop_token_zero_contract():
    """The opaque zero is EXACTLY zero for every token — finite, inf,
    nan (a diverging loss must never scramble bucket indices) — and
    None degrades to a plain zero."""
    for tok in (0.0, 3.7, -1e30, np.inf, -np.inf, np.nan):
        z = jax.jit(loop_token_zero)(jnp.asarray(tok, jnp.float32))
        assert int(z) == 0, (tok, z)
        assert z.dtype == jnp.uint32
    assert int(loop_token_zero(None)) == 0


@pytest.mark.parametrize("impl", ["hash", "circ"])
def test_encode_grad_tree_matches_ravel_encode(impl):
    """Leaf-range streaming over a mixed pytree (tiny bias leaves that
    coalesce, a large kernel that splits) equals the one-shot encode of
    the raveled tree; a scale folds in linearly."""
    rng = np.random.RandomState(1)
    gtree = {
        "a_bias": jnp.asarray(rng.randn(7), jnp.float32),
        "b_kernel": jnp.asarray(rng.randn(90, 30), jnp.float32),
        "c_bias": jnp.asarray(rng.randn(11), jnp.float32),
        "d_kernel": jnp.asarray(rng.randn(40, 10), jnp.float32),
    }
    flat, _ = ravel_pytree(gtree)
    d = flat.shape[0]
    cs = make_sketch_impl(impl, d=d, c=128, r=3, num_blocks=4)
    table0 = jnp.zeros((3, 128))
    ref = cs.encode(flat)
    # min/max chunk sizes chosen to force BOTH the coalesce path (7- and
    # 11-element biases) and the split path (the 2700-element kernel)
    got = encode_grad_tree(cs, table0, gtree, min_chunk=64, max_chunk=512)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-4)
    got_s = encode_grad_tree(cs, table0, gtree,
                             scale=jnp.asarray(0.5, jnp.float32),
                             token=jnp.asarray(2.0, jnp.float32),
                             min_chunk=64, max_chunk=512)
    np.testing.assert_allclose(np.asarray(got_s), 0.5 * np.asarray(ref),
                               rtol=1e-5, atol=1e-4)


def test_streaming_grad_matches_jax_grad():
    """models/stream_mlp.py's manual VJP: the streamed table equals
    encode(jax.grad) of the same loss in ravel layout, the loss matches
    the pytree forward, and the client datum-count scale folds in."""
    params = init_stream_mlp(jax.random.PRNGKey(0), d_in=16, hidden=32,
                             n_layers=6, n_classes=5)
    loss_fn = make_stream_mlp_loss(params)
    pv, unravel = ravel_pytree(params)
    d = pv.shape[0]
    rng = np.random.RandomState(2)
    batch = {"x": jnp.asarray(rng.randn(8, 16), jnp.float32),
             "target": jnp.asarray(rng.randint(0, 5, (8,)), jnp.int32)}
    mask = jnp.asarray([1, 1, 1, 0, 1, 1, 0, 1], bool)

    def loss_vec(v):
        loss, _ = loss_fn(unravel(v), batch, mask)
        return loss

    g = jax.grad(loss_vec)(pv)
    for impl in ("hash", "circ"):
        cs = make_sketch_impl(impl, d=d, c=128, r=3, num_blocks=4)
        t, loss_s, (acc_s,) = loss_fn.streaming_grad(
            pv, batch, mask, cs, jnp.zeros((3, 128)))
        np.testing.assert_allclose(float(loss_s), float(loss_vec(pv)),
                                   rtol=1e-6)
        ref = np.asarray(cs.encode(g))
        np.testing.assert_allclose(np.asarray(t), ref, rtol=1e-4,
                                   atol=1e-5)
        t2, _, _ = loss_fn.streaming_grad(
            pv, batch, mask, cs, jnp.zeros((3, 128)),
            scale=jnp.asarray(3.0, jnp.float32))
        np.testing.assert_allclose(np.asarray(t2), 3.0 * ref, rtol=1e-4,
                                   atol=1e-4)


# ------------------------------------------------------- runtime equivalence


FUSED_LOSS_RTOL, FUSED_LOSS_ATOL = 1e-4, 1e-5


def test_fused_round_matches_unfused_fused_clients_path():
    rt_f, lf, sf = run_rounds(make_cfg(sketch_fused_encode="auto"))
    rt_u, lu, su = run_rounds(make_cfg(sketch_fused_encode="off"))
    assert rt_f._fused_encode and rt_f._fused
    assert not rt_u._fused_encode
    np.testing.assert_allclose(lf, lu, rtol=FUSED_LOSS_RTOL,
                               atol=FUSED_LOSS_ATOL)
    np.testing.assert_allclose(np.asarray(sf.ps_weights),
                               np.asarray(su.ps_weights),
                               rtol=1e-4, atol=1e-5)


def test_fused_round_matches_unfused_vmap_path():
    """The per-client table-carry scan (make_client_step): per-client
    grad stats are a blocker by design, so they are off here."""
    kw = dict(fused_clients=False, client_stats=False)
    rt_f, lf, sf = run_rounds(make_cfg(sketch_fused_encode="auto", **kw))
    rt_u, lu, su = run_rounds(make_cfg(sketch_fused_encode="off", **kw))
    assert rt_f._fused_encode and not rt_f._fused
    np.testing.assert_allclose(lf, lu, rtol=FUSED_LOSS_RTOL,
                               atol=FUSED_LOSS_ATOL)
    np.testing.assert_allclose(np.asarray(sf.ps_weights),
                               np.asarray(su.ps_weights),
                               rtol=1e-4, atol=1e-5)


def test_fused_round_zero_datum_client():
    """A fully-masked (zero-datum) client contributes NOTHING to the
    table in both paths — fused == unfused with a benched slot, and the
    benched slot's n_valid stays zero."""
    batch, mask, ids = make_batch(5, W=W, B=B)
    mask = jnp.asarray(np.asarray(mask)).at[1].set(False)

    def run(fe, fused_clients):
        cfg = make_cfg(sketch_fused_encode=fe, fused_clients=fused_clients,
                       client_stats=False)
        rt = FedRuntime(cfg, make_params(), quad_loss, num_clients=16)
        state, m = rt.round(rt.init_state(), ids, batch, mask, 0.1)
        return np.asarray(state.ps_weights), np.asarray(m["n_valid"])

    for fc in (True, False):
        wf, nf = run("auto", fc)
        wu, nu = run("off", fc)
        assert nf[1] == 0 and (nf == nu).all()
        np.testing.assert_allclose(wf, wu, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("kind", ["signflip", "scale"])
def test_fused_encode_with_adversary_injection(kind):
    """Update-space injection acts on the TABLE under the fused encode
    (the per-client transmitted quantity) — and because signflip/scale
    commute with the linear encode, the attacked fused round still
    matches the attacked unfused round within fp tolerance."""
    kw = dict(fused_clients=False, client_stats=False, adversary=kind,
              adversary_frac=0.6, adversary_scale=5.0)
    rt_f, lf, sf = run_rounds(make_cfg(sketch_fused_encode="auto", **kw))
    rt_u, lu, su = run_rounds(make_cfg(sketch_fused_encode="off", **kw))
    assert rt_f._fused_encode and rt_f._adv_inject
    np.testing.assert_allclose(lf, lu, rtol=FUSED_LOSS_RTOL,
                               atol=FUSED_LOSS_ATOL)
    np.testing.assert_allclose(np.asarray(sf.ps_weights),
                               np.asarray(su.ps_weights),
                               rtol=1e-4, atol=1e-5)


def test_fused_encode_table_frobenius_clip_stays_available():
    """--max_grad_norm WITHOUT --sketch_dense_clip is the per-client
    table-Frobenius clip — a per-table op the fused path keeps (the
    reference semantics, fed_worker.py:318)."""
    kw = dict(max_grad_norm=0.05, fused_clients=False, client_stats=False)
    rt_f, lf, _ = run_rounds(make_cfg(sketch_fused_encode="auto", **kw))
    rt_u, lu, _ = run_rounds(make_cfg(sketch_fused_encode="off", **kw))
    assert rt_f._fused_encode
    np.testing.assert_allclose(lf, lu, rtol=FUSED_LOSS_RTOL,
                               atol=FUSED_LOSS_ATOL)


# ----------------------------------------------------- soundness / fail-fast


def test_fused_encode_blockers_unit():
    assert fused_encode_blockers(make_cfg()) == []
    assert fused_encode_blockers(make_cfg(mode="uncompressed",
                                          error_type="none"))
    assert any("sketch_dense_clip" in p for p in fused_encode_blockers(
        make_cfg(sketch_dense_clip=True, max_grad_norm=1.0)))
    assert any("privacy" in p for p in fused_encode_blockers(
        make_cfg(do_dp=True, noise_multiplier=0.1)))
    # --signals_exact blocks only when the signal diagnostics are LIVE
    assert any("signals_exact" in p for p in fused_encode_blockers(
        make_cfg(signals_exact=True), signals=True))
    assert fused_encode_blockers(make_cfg(signals_exact=True),
                                 signals=False) == []


def test_fused_encode_on_fails_fast_with_explanation():
    for kw, needle in ((dict(sketch_dense_clip=True, max_grad_norm=1.0),
                        "sketch_dense_clip"),
                       (dict(do_dp=True, noise_multiplier=0.1),
                        "privacy"),
                       (dict(signals_exact=True), "signals_exact")):
        with pytest.raises(ValueError, match=needle):
            FedRuntime(make_cfg(sketch_fused_encode="on", **kw),
                       make_params(), quad_loss, num_clients=16)
    # ... and auto with the same blockers silently falls back (the
    # fallback IS the pre-fusion path)
    rt = FedRuntime(make_cfg(sketch_fused_encode="auto",
                             sketch_dense_clip=True, max_grad_norm=1.0),
                    make_params(), quad_loss, num_clients=16)
    assert not rt._fused_encode


def test_fused_encode_on_requires_sketch_mode():
    with pytest.raises(ValueError, match="mode sketch"):
        make_cfg(mode="uncompressed", error_type="none",
                 sketch_fused_encode="on")


def test_fused_encode_auto_with_blocker_hlo_identical_to_off():
    """auto's fallback must BE the old round: byte-identical HLO to the
    explicit off spelling (numerics never change silently), and the
    fused encode must be invisible to non-sketch modes entirely."""
    batch, mask, ids = make_batch(0, W=W, B=B)
    for kw in (dict(sketch_dense_clip=True, max_grad_norm=1.0),
               dict(mode="uncompressed", error_type="none")):
        rt_a = FedRuntime(make_cfg(sketch_fused_encode="auto", **kw),
                          make_params(), quad_loss, num_clients=16)
        rt_o = FedRuntime(make_cfg(sketch_fused_encode="off", **kw),
                          make_params(), quad_loss, num_clients=16)
        args = (rt_a.init_state(), ids, batch, mask,
                jnp.asarray(0.1, jnp.float32), rt_a.cs)
        assert (rt_a._round.lower(*args).as_text()
                == rt_o._round.lower(*args).as_text()), kw
    # sanity: where the fused encode ENGAGES, the lowering does change
    rt_on = FedRuntime(make_cfg(sketch_fused_encode="auto"),
                       make_params(), quad_loss, num_clients=16)
    rt_off = FedRuntime(make_cfg(sketch_fused_encode="off"),
                        make_params(), quad_loss, num_clients=16)
    args = (rt_on.init_state(), ids, batch, mask,
            jnp.asarray(0.1, jnp.float32), rt_on.cs)
    assert (rt_on._round.lower(*args).as_text()
            != rt_off._round.lower(*args).as_text())


# ------------------------------------------------------------ decode overlap


def test_decode_overlap_bitwise_vs_inline():
    """The PR-5 gate pattern, server side: split cohort+decode rounds
    are BIT-identical to the monolithic round — losses and weights."""
    cfg_s = make_cfg(decode_overlap=True)
    rt_s = FedRuntime(cfg_s, make_params(), quad_loss, num_clients=16)
    ov = DecodeOverlapRound(rt_s)
    rt_m = FedRuntime(make_cfg(), make_params(), quad_loss, num_clients=16)
    ss, sm = rt_s.init_state(), rt_m.init_state()
    batch, mask, ids = make_batch(1, W=W, B=B)
    for r in range(4):
        ss, mo = ov.round(ss, ids, batch, mask, 0.1)
        sm, mi = rt_m.round(sm, ids, batch, mask, 0.1)
        assert (np.asarray(mo["results"][0])
                == np.asarray(mi["results"][0])).all(), r
        assert (np.asarray(mo["n_valid"])
                == np.asarray(mi["n_valid"])).all(), r
    assert (np.asarray(ss.ps_weights) == np.asarray(sm.ps_weights)).all()


def test_decode_overlap_metrics_contract():
    """The adapter's metrics dict matches FedRuntime.round's contract
    keys; signals is None (the split decouples what they compare)."""
    cfg = make_cfg(decode_overlap=True)
    rt = FedRuntime(cfg, make_params(), quad_loss, num_clients=16)
    ov = DecodeOverlapRound(rt)
    batch, mask, ids = make_batch(2, W=W, B=B)
    _, m = ov.round(rt.init_state(), ids, batch, mask, 0.1)
    rt_m = FedRuntime(make_cfg(signals=False), make_params(), quad_loss,
                      num_clients=16)
    _, mm = rt_m.round(rt_m.init_state(), ids, batch, mask, 0.1)
    assert set(m) == set(mm), (sorted(m), sorted(mm))
    assert m["signals"] is None
    assert m["download_bytes"] is not None


def test_decode_overlap_validation():
    with pytest.raises(ValueError, match="mutually exclusive"):
        make_cfg(decode_overlap=True, async_agg=True)
    with pytest.raises(ValueError, match="--decode_overlap"):
        validate_overlap_combo(make_cfg(
            decode_overlap=True, mode="local_topk", error_type="local",
            local_momentum=0.9, k=5))
    # the adapter refuses a runtime built without the split executables
    rt = FedRuntime(make_cfg(), make_params(), quad_loss, num_clients=16)
    with pytest.raises(ValueError, match="decode_overlap"):
        DecodeOverlapRound(rt)


def test_decode_overlap_driver_end_to_end(tmp_path):
    """The driver loop's --decode_overlap branch (cv_train.train):
    one synthetic-CIFAR epoch split vs monolithic, identical data order
    (same seed), train losses bit-identical — the PR-5 gate pattern at
    driver granularity."""
    from commefficient_tpu import cv_train, models
    from commefficient_tpu.data import FedCIFAR10, transforms_for
    from commefficient_tpu.losses import make_cv_loss

    def run(decode_overlap):
        ds = FedCIFAR10(str(tmp_path / f"d{int(decode_overlap)}"),
                        synthetic=True, synthetic_per_class=8,
                        transform=transforms_for("CIFAR10", True, seed=0))
        cfg = FedConfig(mode="sketch", error_type="virtual", k=10,
                        num_rows=2, num_cols=64, num_blocks=2,
                        sketch_impl="hash", local_momentum=0.0,
                        virtual_momentum=0.9, num_workers=4,
                        local_batch_size=4, num_clients=ds.num_clients,
                        num_epochs=1.0, track_bytes=True,
                        compute_dtype="float32", telemetry=False,
                        decode_overlap=decode_overlap)
        model = models.ResNet9(num_classes=10,
                               channels={"prep": 2, "layer1": 2,
                                         "layer2": 2, "layer3": 2})
        params = model.init(jax.random.PRNGKey(0),
                            jnp.ones((1, 32, 32, 3)))
        rt = FedRuntime(cfg, params, make_cv_loss(model, "float32"),
                        num_clients=ds.num_clients)
        state, summary = cv_train.train(cfg, rt, rt.init_state(), ds, ds)
        return summary

    s_split = run(True)
    s_mono = run(False)
    assert s_split is not None and np.isfinite(s_split["train_loss"])
    assert s_split["train_loss"] == s_mono["train_loss"], (
        s_split["train_loss"], s_mono["train_loss"])


# ----------------------------------------------------- byte-count accounting


def test_download_coord_counts_blocked_scan_matches_numpy():
    """The blocked-scan byte accounting (which replaced the (W, d)
    broadcast-compare-reduce — the fused round's largest temp buffer)
    against the obvious numpy reference, incl. a d that does not divide
    the block and thresholds the padding would satisfy if mis-padded."""
    rt = FedRuntime(make_cfg(), make_params(), quad_loss, num_clients=16)
    rng = np.random.RandomState(0)
    for d in (100, 512 * 3 + 17, 2048):
        clu = jnp.asarray(rng.randint(-1, 40, (d,)), jnp.int32)
        # include the minimum threshold present in real states (0 after
        # init, possibly -1-ish sentinels) — padding must never count
        thr = jnp.asarray([0, 3, -1, 39], jnp.int32)
        got = np.asarray(jax.jit(rt._download_coord_counts)(clu, thr))
        ref = (np.asarray(clu)[None, :]
               >= np.asarray(thr)[:, None]).sum(axis=1)
        np.testing.assert_array_equal(got, ref, err_msg=str(d))
