"""Compression-signal health + HLO collective ledger + teleview analyzer
(telemetry/signals.py, telemetry/collectives.py, scripts/teleview.py):
on-device diagnostics against a numpy reference on a tiny model, schema
round-trips for the two new event types, ledger parsing/launch counting,
the driver-loop signals wiring, regime guardrails, and the analyzer's
summarize/diff contract."""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.config import FedConfig
from commefficient_tpu.core import FedRuntime
from commefficient_tpu.core.server import (check_regime_health,
                                           validate_regimes)
from commefficient_tpu.telemetry import (RunTelemetry, SIGNAL_KEYS,
                                         ledger_from_hlo, round_ledger,
                                         summarize_ledger, validate_event,
                                         validate_file)
from commefficient_tpu.telemetry.schema import TELEMETRY_BASENAME

W, B, D_IN, D_OUT = 4, 4, 6, 3
D = D_IN * D_OUT


def loss_fn(params, batch, mask):
    pred = batch["x"] @ params["w"]
    m = mask.astype(jnp.float32)
    denom = jnp.maximum(m.sum(), 1.0)
    err = ((pred - batch["y"]) ** 2).sum(axis=1)
    loss = (err * m).sum() / denom
    return loss, (loss,)


def make_runtime(**kw):
    cfg_kw = dict(mode="sketch", error_type="virtual", local_momentum=0.0,
                  virtual_momentum=0.9, weight_decay=0.0, num_workers=W,
                  local_batch_size=B, track_bytes=True, num_clients=8,
                  num_results_train=2, num_results_val=2,
                  k=5, num_rows=2, num_cols=32, exact_num_cols=True)
    cfg_kw.update(kw)
    params = {"w": jnp.asarray(
        np.random.RandomState(0).randn(D_IN, D_OUT), jnp.float32)}
    return FedRuntime(FedConfig(**cfg_kw), params, loss_fn, num_clients=8)


def make_batch(seed=1):
    rng = np.random.RandomState(seed)
    batch = {"x": jnp.asarray(rng.randn(W, B, D_IN), jnp.float32),
             "y": jnp.asarray(rng.randn(W, B, D_OUT), jnp.float32)}
    return batch, jnp.ones((W, B), bool), jnp.arange(W, dtype=jnp.int32)


def fetch_signals(metrics):
    return {k: float(np.asarray(v)) for k, v in metrics["signals"].items()}


# ------------------------------------------------------- on-device signals


def test_signals_present_and_keys_complete():
    rt = make_runtime()
    batch, mask, ids = make_batch()
    state, metrics = rt.round(rt.init_state(), ids, batch, mask, 0.05)
    sig = fetch_signals(metrics)
    assert set(sig) == set(SIGNAL_KEYS)


def test_no_signals_flag_drops_them():
    rt = make_runtime(signals=False)
    batch, mask, ids = make_batch()
    state, metrics = rt.round(rt.init_state(), ids, batch, mask, 0.05)
    assert metrics["signals"] is None
    assert state.sig_Verror is None


def test_no_telemetry_drops_signals_too():
    """--no_telemetry leaves no consumer for the signals — they must
    not cost hot-path work (in mesh sketch mode the l2estimates are
    table-sized all-gathers; --signals_exact adds 2 x O(d) shadow
    state) for a stream nobody reads."""
    rt = make_runtime(telemetry=False, signals_exact=True)
    assert not rt._signals and not rt._signals_shadow
    batch, mask, ids = make_batch()
    state, metrics = rt.round(rt.init_state(), ids, batch, mask, 0.05)
    assert metrics["signals"] is None
    assert state.sig_Verror is None       # no dead shadow allocation


def test_resume_rezeros_missing_shadow(tmp_path):
    """A checkpoint written WITHOUT the --signals_exact shadow fields
    must resume with them re-zeroed (not None) when the resuming
    runtime expects a shadow — otherwise topk_overlap silently goes
    dead for the whole resumed run."""
    from commefficient_tpu.cv_train import setup_checkpointing
    plain = make_runtime(do_resume=True, checkpoint_every=1,
                         checkpoint_path=str(tmp_path))
    batch, mask, ids = make_batch()
    state, _ = plain.round(plain.init_state(), ids, batch, mask, 0.05)
    mgr, _, _, _ = setup_checkpointing(plain.cfg, plain, "quad")
    mgr.save(state, 1)
    exact = make_runtime(do_resume=True, checkpoint_every=1,
                         checkpoint_path=str(tmp_path),
                         signals_exact=True)
    assert exact._signals_shadow
    _, start, restored, _ = setup_checkpointing(exact.cfg, exact,
                                                 "quad")
    assert start == 1 and restored is not None
    assert restored.sig_Verror is not None
    np.testing.assert_array_equal(np.asarray(restored.sig_Verror),
                                  np.zeros(D, np.float32))
    # and the resumed state runs through the shadowed round
    s2, metrics = exact.round(restored, ids, batch, mask, 0.05)
    assert np.isfinite(fetch_signals(metrics)["topk_overlap"])


def test_uncompressed_signals_match_numpy_reference():
    """First round, momentum 0: the aggregated gradient is the
    datum-weighted mean of per-client mean gradients of the quadratic
    loss — computable exactly in numpy — and update = lr * agg."""
    lr = 0.05
    rt = make_runtime(mode="uncompressed", error_type="none",
                      virtual_momentum=0.0)
    batch, mask, ids = make_batch()
    w0 = np.asarray(rt.initial_weights).reshape(D_IN, D_OUT)
    state, metrics = rt.round(rt.init_state(), ids, batch, mask, lr)
    sig = fetch_signals(metrics)

    x = np.asarray(batch["x"], np.float64)
    y = np.asarray(batch["y"], np.float64)
    # per-client mean grad of sum-over-outputs squared error, x n_c,
    # summed over clients, / total datums (core/client.py weighting)
    g = np.zeros((D_IN, D_OUT))
    for c in range(W):
        res = x[c] @ w0 - y[c]                      # (B, D_OUT)
        g += (2.0 * x[c].T @ res / B) * B
    g /= W * B
    expect = float(np.linalg.norm(g))
    assert sig["grad_norm"] == pytest.approx(expect, rel=1e-4)
    assert sig["grad_true_norm"] == pytest.approx(expect, rel=1e-4)
    assert sig["update_norm"] == pytest.approx(lr * expect, rel=1e-4)
    assert sig["support_density"] == pytest.approx(1.0)
    # momentum 0: Vvelocity == agg
    assert sig["velocity_norm"] == pytest.approx(expect, rel=1e-4)
    assert np.isnan(sig["grad_l2estimate"])
    assert np.isnan(sig["topk_overlap"])  # needs --signals_exact
    # state norms agree with the signal (the fetched state IS the source)
    assert float(np.linalg.norm(np.asarray(state.Vvelocity))) == \
        pytest.approx(sig["velocity_norm"], rel=1e-5)


def test_sketch_signals_lossless_regime():
    """c >= d: the sketch round-trip is exact, so the l2estimate matches
    the true dense norm and the recovered top-k is the exact top-k."""
    rt = make_runtime(signals_exact=True)          # c=32 >= d=18
    assert rt._signals_shadow
    batch, mask, ids = make_batch()
    state = rt.init_state()
    assert state.sig_Verror is not None and state.sig_Verror.shape == (D,)
    for _ in range(3):
        state, metrics = rt.round(state, ids, batch, mask, 0.05)
    sig = fetch_signals(metrics)
    assert sig["grad_l2estimate"] == pytest.approx(sig["grad_true_norm"],
                                                   rel=1e-4)
    assert sig["topk_overlap"] == pytest.approx(1.0)
    assert sig["support_density"] == pytest.approx(rt.cfg.k / D)
    assert sig["error_norm"] > 0          # EF accumulator is accumulating
    # the lossless shadow tracks the table state exactly: its error's
    # norm estimate equals the dense shadow error norm
    assert float(np.linalg.norm(np.asarray(state.sig_Verror))) == \
        pytest.approx(sig["error_l2estimate"], rel=1e-3)


def test_sketch_compressing_overlap_below_one():
    """At real compression (c << d) with a few accumulation rounds the
    recovered support must remain a VALID fraction in [0, 1] — and the
    collision-noise proxy (l2estimate vs true norm) must diverge from
    the lossless identity."""
    rt = make_runtime(signals_exact=True, num_cols=4, num_rows=1, k=3)
    batch, mask, ids = make_batch()
    state = rt.init_state()
    overlaps = []
    for _ in range(4):
        state, metrics = rt.round(state, ids, batch, mask, 0.05)
        sig = fetch_signals(metrics)
        overlaps.append(sig["topk_overlap"])
    assert all(0.0 <= o <= 1.0 for o in overlaps)
    assert sig["grad_l2estimate"] != pytest.approx(sig["grad_true_norm"],
                                                   rel=1e-6)


def test_true_topk_exact_overlap_is_one():
    rt = make_runtime(mode="true_topk", error_type="virtual",
                      signals_exact=True)
    assert not rt._signals_shadow          # dense error needs no shadow
    batch, mask, ids = make_batch()
    state = rt.init_state()
    for _ in range(2):
        state, metrics = rt.round(state, ids, batch, mask, 0.05)
    sig = fetch_signals(metrics)
    assert sig["topk_overlap"] == pytest.approx(1.0)
    assert state.sig_Verror is None


def test_signals_do_not_change_numerics():
    """The diagnostics are observers: weights after N rounds are
    bit-identical with signals on, off, and exact."""
    states = []
    for kw in ({}, {"signals": False}, {"signals_exact": True}):
        rt = make_runtime(**kw)
        batch, mask, ids = make_batch()
        s = rt.init_state()
        for _ in range(3):
            s, _ = rt.round(s, ids, batch, mask, 0.05)
        states.append(np.asarray(s.ps_weights))
    np.testing.assert_array_equal(states[0], states[1])
    np.testing.assert_array_equal(states[0], states[2])


# ------------------------------------------------------- schema round-trip


def test_signals_and_collectives_events_validate(tmp_path):
    rt = make_runtime()
    tel = RunTelemetry(str(tmp_path), "test", cfg=rt.cfg)
    tel.instrument(rt)
    batch, mask, ids = make_batch()
    state, metrics = rt.round(rt.init_state(), ids, batch, mask, 0.05)
    from commefficient_tpu.telemetry import signals_to_host
    tel.signals_event(rnd=1, mode=rt.cfg.mode,
                      signals=signals_to_host(metrics["signals"]),
                      download_bytes=1.0, upload_bytes=2.0,
                      client_download_bytes=[1.0] * W,
                      client_upload_bytes=[0.5] * W)
    tel.write_summary(aborted=False, n_rounds=1)
    tel.close()
    assert validate_file(tel.path) == []
    events = [json.loads(l) for l in open(tel.path)]
    kinds = [e["event"] for e in events]
    # the JitWatcher emits a collectives inventory next to each compile
    assert "compile" in kinds and "collectives" in kinds
    coll = [e for e in events if e["event"] == "collectives"][0]
    assert coll["name"] == "round_step"
    assert isinstance(coll["counts"], dict)
    assert coll["n_collectives"] == 0       # single device: no collectives
    sig = [e for e in events if e["event"] == "signals"][0]
    assert sig["mode"] == "sketch" and sig["round"] == 1
    assert len(sig["client_download_bytes"]) == W
    # NaN signals must have landed as null, never the NaN token
    raw = open(tel.path).read()
    assert "NaN" not in raw


def test_schema_rejects_malformed_new_events():
    assert validate_event({"event": "signals", "t": 0.0, "seq": 0})
    assert validate_event({"event": "collectives", "t": 0.0, "seq": 0})
    ok = {"event": "collectives", "t": 0.0, "seq": 0, "name": "round_step",
          "n_collectives": 2, "counts": {"all-reduce": 2},
          "total_bytes": 128, "ops": [],
          "wire_dtype": None, "table_reduce_bytes": None}
    assert validate_event(ok) == []
    bad = dict(ok, counts=["all-reduce"])
    assert validate_event(bad)


def test_check_schema_script_selftest(tmp_path):
    """Satellite: scripts/check_telemetry_schema.py --selftest generates
    a sample stream containing EVERY event type (the two new ones
    included) and validates it with the same code CI runs."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(os.path.dirname(__file__), os.pardir, "scripts",
                     "check_telemetry_schema.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main(["--selftest"]) == 0
    from commefficient_tpu.telemetry.schema import EVENT_FIELDS
    stream = mod.sample_stream()
    kinds = {json.loads(l)["event"] for l in stream}
    assert kinds == set(EVENT_FIELDS), "selftest must cover every type"
    # the flag composes with lint roots (any order) instead of being
    # misread as a filesystem path
    empty = tmp_path / "empty"
    empty.mkdir()
    assert mod.main(["--selftest", str(empty)]) == 0
    assert mod.main([str(empty), "--selftest"]) == 0


# ------------------------------------------------------------- driver loop


def test_driver_loop_emits_signals_events(tmp_path):
    from commefficient_tpu import cv_train
    from test_telemetry import StubDS

    rt = make_runtime(dataset_name="SYNTH", telemetry_every=1)
    tel = RunTelemetry(str(tmp_path), "cv_train", cfg=rt.cfg)
    tel.instrument(rt)
    cfg = rt.cfg.replace(num_epochs=1.0, pivot_epoch=0.5)
    state, summary = cv_train.train(cfg, rt, rt.init_state(),
                                    StubDS(), StubDS(), telemetry=tel)
    tel.close()
    assert summary is not None
    assert validate_file(tel.path) == []
    events = [json.loads(l) for l in open(tel.path)]
    sigs = [e for e in events if e["event"] == "signals"]
    rounds = [e for e in events if e["event"] == "round"]
    assert len(sigs) == len(rounds) >= 1     # same cadence
    s = sigs[0]
    assert s["upload_bytes"] == rounds[0]["upload_bytes"]
    # exact per-client bytes: W participating clients, uniform uploads
    assert len(s["client_upload_bytes"]) == W
    assert sum(s["client_upload_bytes"]) == pytest.approx(s["upload_bytes"])
    assert s["error_norm"] is not None and s["error_norm"] >= 0


# ------------------------------------------------------- collective ledger


SAMPLE_HLO = """
HloModule jit_round
  %x1 = f32[492]{0} all-to-all(f32[492]{0} %p0), replica_groups={}
  %x2 = f32[492]{0} all-to-all(f32[492]{0} %x1), replica_groups={}
  %ar = (f32[]{/*index=0*/}, f32[3,64]{1,0}) all-reduce-start(f32[] %a, f32[3,64] %b)
  %ad = (f32[], f32[3,64]) all-reduce-done((f32[], f32[3,64]) %ar)
  %rs = bf16[492]{0} reduce-scatter(bf16[3936]{0} %big), dimensions={0}
  %ag = f32[3936]{0} all-gather(f32[492]{0} %rs2), dimensions={0}
"""


def test_ledger_parses_kinds_sizes_dtypes_and_launches():
    ledger = ledger_from_hlo(SAMPLE_HLO)
    s = summarize_ledger(ledger)
    # -done lines must not double-count; the combined all-reduce tuple is
    # ONE launch with two payload elements
    assert s["counts"] == {"all-to-all": 2, "all-reduce": 1,
                           "reduce-scatter": 1, "all-gather": 1}
    ar = [e for e in ledger if e["kind"] == "all-reduce"]
    assert len(ar) == 2
    assert {e["n_elements"] for e in ar} == {1, 192}
    assert len({e["combined_in"] for e in ar}) == 1
    rs = [e for e in ledger if e["kind"] == "reduce-scatter"][0]
    assert rs["dtype"] == "bf16" and rs["bytes"] == 492 * 2
    assert s["total_bytes"] == (492 * 4 * 2 + 4 + 192 * 4 + 492 * 2
                                + 3936 * 4)


@pytest.mark.parametrize("mode,extra", [
    ("sketch", {"error_type": "virtual"}),
    ("local_topk", {"error_type": "local", "local_momentum": 0.9,
                    "lr_scale": 0.01}),
])
def test_mesh_round_ledger_counts(devices, mode, extra):
    """The compiled mesh round's ledger must stay within the dryrun's
    count bounds — the in-tree guard for the 32x unroll class (the same
    bounds __graft_entry__.dryrun_multichip asserts on all 5 modes)."""
    from commefficient_tpu.parallel import make_mesh
    from commefficient_tpu.telemetry.collectives import \
        ROUND_COLLECTIVE_LAUNCH_BOUNDS as _COLLECTIVE_COUNT_BOUNDS
    mesh = make_mesh((8,), ("clients",), devices=devices)
    rt = make_runtime(mode=mode, num_workers=8, **extra)
    params = {"w": jnp.asarray(
        np.random.RandomState(0).randn(D_IN, D_OUT), jnp.float32)}
    rt = FedRuntime(rt.cfg.replace(grad_size=0), params, loss_fn,
                    num_clients=8, mesh=mesh)
    rng = np.random.RandomState(1)
    batch = {"x": jnp.asarray(rng.randn(8, B, D_IN), jnp.float32),
             "y": jnp.asarray(rng.randn(8, B, D_OUT), jnp.float32)}
    mask = jnp.ones((8, B), bool)
    ids = jnp.arange(8, dtype=jnp.int32)
    state = rt.init_state()
    ledger = round_ledger(rt, state, ids, batch, mask)
    assert ledger, "a mesh round must contain collectives"
    counts = summarize_ledger(ledger)["counts"]
    for kind, limit in _COLLECTIVE_COUNT_BOUNDS.items():
        assert counts.get(kind, 0) <= limit, (mode, counts)


# --------------------------------------------------------- regime guards


def test_regime_guardrails_fire_and_strict_raises(capsys):
    # measured-divergent: local_topk + local EF at dense-stable lr
    bad = FedConfig(mode="local_topk", error_type="local", lr_scale=0.1,
                    local_momentum=0.0)
    assert check_regime_health(bad)
    validate_regimes(bad)
    assert "MEASURED divergent" in capsys.readouterr().err
    with pytest.raises(ValueError, match="strict_regimes"):
        validate_regimes(bad.replace(strict_regimes=True))
    # inside the envelope: no warning
    ok = bad.replace(lr_scale=0.01)
    assert check_regime_health(ok) == []
    # measured-divergent: subtract-EF at high collision load (d/c >= 100)
    sub = FedConfig(mode="sketch", error_type="virtual", local_momentum=0.0,
                    sketch_ef="subtract", num_cols=1000, grad_size=200_000)
    assert check_regime_health(sub)
    with pytest.raises(ValueError, match="collision load"):
        validate_regimes(sub.replace(strict_regimes=True))
    # the stable loads / the dense-state rescue are NOT flagged
    assert check_regime_health(sub.replace(num_cols=20_000)) == []
    assert check_regime_health(
        sub.replace(sketch_server_state="dense")) == []


def test_strict_regimes_wired_through_runtime():
    params = {"w": jnp.zeros((D_IN, D_OUT), jnp.float32)}
    cfg = FedConfig(mode="local_topk", error_type="local", lr_scale=0.4,
                    local_momentum=0.0, num_workers=W, local_batch_size=B,
                    strict_regimes=True)
    with pytest.raises(ValueError, match="strict_regimes"):
        FedRuntime(cfg, params, loss_fn, num_clients=8)


# ---------------------------------------------------------------- teleview


def _write_stream(path, error_norm=1.0, a2a_count=2, loss=2.0):
    tel = RunTelemetry(str(path), "test", cfg=None)
    tel.event("collectives", name="round_step", n_collectives=3 + a2a_count,
              counts={"all-reduce": 3, "all-to-all": a2a_count},
              total_bytes=4096, ops=[],
              # schema v9 wire fields (hand-rolled event; the real
              # emitter is RunTelemetry.collectives_event)
              wire_dtype=None, table_reduce_bytes=None)
    sig = {k: 1.0 for k in SIGNAL_KEYS}
    sig["error_norm"] = error_norm
    tel.signals_event(rnd=1, mode="sketch", signals=sig,
                      download_bytes=8.0, upload_bytes=8.0,
                      client_download_bytes=[4.0, 4.0],
                      client_upload_bytes=[4.0, 4.0])
    tel.round_event(rnd=1, epoch=1, lr=0.1, loss=loss, acc=0.5, n_valid=8,
                    download_bytes=8.0, upload_bytes=8.0,
                    host_s=0.01, dispatch_s=0.01, device_s=0.01)
    tel.write_summary(aborted=False, n_rounds=1)
    tel.close()
    assert validate_file(tel.path) == []
    return tel.path


def _teleview():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "teleview", os.path.join(os.path.dirname(__file__), os.pardir,
                                 "scripts", "teleview.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_teleview_fallback_constants_match_package():
    """teleview must run on machines without jax, so it carries literal
    fallbacks of the two schema constants — pin them to the canonical
    values so they cannot drift."""
    import re
    src = open(os.path.join(os.path.dirname(__file__), os.pardir,
                            "scripts", "teleview.py")).read()
    m = re.search(r'TELEMETRY_BASENAME = "([^"]+)"', src)
    assert m and m.group(1) == TELEMETRY_BASENAME
    block = re.search(r"SIGNAL_KEYS = \((.*?)\)", src, re.S).group(1)
    assert tuple(re.findall(r'"([a-z_0-9]+)"', block)) == SIGNAL_KEYS


def test_teleview_summarize_and_clean_diff(tmp_path, capsys):
    tv = _teleview()
    a = _write_stream(tmp_path / "a")
    assert tv.main(["summarize", a]) == 0
    out = capsys.readouterr().out
    assert "collectives" in out and "signals" in out and "error_norm" in out
    b = _write_stream(tmp_path / "b")
    assert tv.main(["diff", a, b]) == 0


def test_teleview_diff_fails_on_collective_count_growth(tmp_path, capsys):
    tv = _teleview()
    a = _write_stream(tmp_path / "a", a2a_count=2)
    b = _write_stream(tmp_path / "b", a2a_count=32)   # the r5 unroll class
    assert tv.main(["diff", a, b]) == 1
    assert "all-to-all launch count 2 -> 32" in capsys.readouterr().out
    # slack makes it pass again (opt-in tolerance)
    assert tv.main(["diff", a, b, "--count_slack", "30",
                    "--bytes_ratio", "100"]) == 0


def test_teleview_diff_fails_on_signal_norm_blowup(tmp_path, capsys):
    tv = _teleview()
    a = _write_stream(tmp_path / "a", error_norm=10.0)
    b = _write_stream(tmp_path / "b", error_norm=100.0)  # EF divergence
    assert tv.main(["diff", a, b]) == 1
    assert "error_norm" in capsys.readouterr().out
    assert tv.main(["diff", a, b, "--signal_ratio", "20"]) == 0


def test_teleview_diff_fails_on_loss_regression(tmp_path):
    tv = _teleview()
    a = _write_stream(tmp_path / "a", loss=2.0)
    b = _write_stream(tmp_path / "b", loss=3.0)
    assert tv.main(["diff", a, b]) == 1
    assert tv.main(["diff", a, b, "--loss_ratio", "2.0"]) == 0
