"""Data layer: partition math, sampler coverage, transform shapes.

The sampler tests are property tests of the semantics preserved from the
reference FedSampler (data_utils/fed_sampler.py:19-68): within-epoch
permutation per client, sampling without replacement, exhaustion semantics.
"""

import numpy as np
import pytest

from commefficient_tpu.data import (
    FedCIFAR10,
    FedEMNIST,
    FedSampler,
    ValSampler,
    transforms_for,
)


@pytest.fixture(scope="module")
def cifar_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("cifar")
    ds = FedCIFAR10(str(d), synthetic=True, synthetic_per_class=16)
    return str(d), ds


def test_cifar_partition(cifar_dir):
    _, ds = cifar_dir
    assert ds.num_clients == 10
    assert len(ds) == 160
    np.testing.assert_array_equal(ds.images_per_client, [16] * 10)
    # train target == natural client id
    batch = ds.gather(np.arange(len(ds)))
    expected = np.repeat(np.arange(10), 16)
    np.testing.assert_array_equal(batch["target"], expected)
    assert batch["image"].shape == (160, 32, 32, 3)


def test_cifar_reload_from_disk(cifar_dir):
    d, _ = cifar_dir
    ds2 = FedCIFAR10(d, synthetic_per_class=16)  # prepared stats reused
    assert len(ds2) == 160


def test_cifar_val(cifar_dir):
    d, _ = cifar_dir
    val = FedCIFAR10(d, train=False, synthetic_per_class=16)
    assert len(val) == val.num_val_images > 0
    b = val.gather(np.arange(4))
    assert b["image"].shape == (4, 32, 32, 3)


def test_data_per_client_sharding(cifar_dir):
    _, _ = cifar_dir
    ds = FedCIFAR10(cifar_dir[0], num_clients=20, synthetic_per_class=16)
    per = ds.data_per_client
    assert len(per) == 20 and per.sum() == 160
    # each class split across 2 synthetic clients (reference
    # fed_dataset.py:41-48)
    np.testing.assert_array_equal(per, [8] * 20)


def test_iid_partition(cifar_dir):
    ds = FedCIFAR10(cifar_dir[0], do_iid=True, num_clients=7,
                    synthetic_per_class=16)
    per = ds.data_per_client
    assert per.sum() == 160 and len(per) == 7
    assert per.max() - per.min() <= 1


def test_sampler_covers_epoch_exactly_once():
    per_client = np.array([10, 7, 13, 10])
    s = FedSampler(per_client, num_workers=2, local_batch_size=4, seed=0,
                   drop_underfull=False)
    seen = []
    for rnd in s:
        assert rnd.idx.shape == (2, 4) and rnd.mask.shape == (2, 4)
        seen.extend(rnd.idx[rnd.mask].tolist())
        # valid indices must belong to the claimed client
        offsets = np.concatenate([[0], np.cumsum(per_client)])
        for slot in range(2):
            c = rnd.client_ids[slot]
            vals = rnd.idx[slot][rnd.mask[slot]]
            if len(vals):
                assert (vals >= offsets[c]).all()
                assert (vals < offsets[c + 1]).all()
    assert sorted(seen) == list(range(per_client.sum()))


def test_sampler_drop_underfull_stops_early():
    per_client = np.array([100, 1])
    s = FedSampler(per_client, num_workers=2, local_batch_size=8, seed=0)
    rounds = list(s)
    # client 1 exhausts after its first appearance; afterwards only client 0
    # remains and rounds must stop (reference driver skip, cv_train.py:205-219)
    for rnd in rounds:
        assert len(np.unique(rnd.client_ids)) == 2


def test_sampler_whole_client_batches():
    per_client = np.array([5, 3, 4])
    s = FedSampler(per_client, num_workers=3, local_batch_size=-1,
                   max_client_batch=8, seed=1, drop_underfull=False)
    rounds = list(s)
    # every client's whole dataset fits in one round here
    assert len(rounds) == 1
    np.testing.assert_array_equal(np.sort(rounds[0].mask.sum(axis=1)),
                                  [3, 4, 5])


def test_val_sampler():
    chunks = list(ValSampler(num_items=10, batch_size=4))
    assert len(chunks) == 3
    total = sum(m.sum() for _, m in chunks)
    assert total == 10


def test_transforms_cifar():
    t = transforms_for("CIFAR10", train=True, seed=0)
    batch = {"image": np.random.randint(0, 255, (3, 5, 32, 32, 3),
                                        dtype=np.uint8),
             "target": np.zeros((3, 5), np.int64)}
    out = t(batch)
    assert out["image"].shape == (3, 5, 32, 32, 3)
    assert out["image"].dtype == np.float32
    # normalized: roughly centered
    assert abs(float(out["image"].mean())) < 3.0


def test_emnist_synthetic(tmp_path):
    ds = FedEMNIST(str(tmp_path), synthetic=True)
    assert ds.num_clients == 20
    b = ds.gather(np.arange(6))
    assert b["image"].shape == (6, 28, 28, 1)
    t = transforms_for("EMNIST", train=True)
    out = t(b)
    assert out["image"].shape == (6, 28, 28, 1)


def test_synthetic_prep_invalidation(tmp_path):
    """Changing --synthetic_per_class (or the generator version) must
    re-prepare a synthetic dir instead of silently reusing stale arrays;
    marker-less (real-data era) stats are preserved."""
    from commefficient_tpu.data.fed_cifar import FedCIFAR10

    ds = FedCIFAR10(str(tmp_path), synthetic=True, synthetic_per_class=8)
    assert len(ds) == 80
    # same size: reused
    again = FedCIFAR10(str(tmp_path), synthetic=True, synthetic_per_class=8)
    assert len(again) == 80
    # different size: re-prepared
    bigger = FedCIFAR10(str(tmp_path), synthetic=True,
                        synthetic_per_class=16)
    assert len(bigger) == 160


def test_synthetic_val_shares_prototypes():
    """Train and val synthetic splits must describe the SAME classes
    (different noise only) — otherwise validation accuracy is capped at
    chance by construction (the r1 artifact-run bug)."""
    from commefficient_tpu.data.fed_cifar import _synthetic_cifar

    tr_img, tr_t = _synthetic_cifar(4, 8, seed=1234)
    va_img, va_t = _synthetic_cifar(4, 8, seed=4321)
    # per-class means across splits are close (same prototype)...
    for c in range(4):
        m_tr = tr_img[tr_t == c].astype(float).mean(axis=0)
        m_va = va_img[va_t == c].astype(float).mean(axis=0)
        assert np.abs(m_tr - m_va).mean() < 20
    # ...but the images themselves differ (fresh noise)
    assert np.abs(tr_img.astype(float) - va_img.astype(float)).mean() > 10


def test_emnist_synthetic_splits_share_prototypes(tmp_path):
    """Regression (round-4 bug): the synthetic train and val splits must
    describe the SAME classes — prototypes from a fixed proto_seed, only
    noise from the split seed. (They used to draw prototypes from the
    split seed, making every synthetic-EMNIST val accuracy chance by
    construction.) Pinned structurally: each class's train-mean image is
    closest to ITS OWN val-mean image."""
    ds = FedEMNIST(str(tmp_path), synthetic=True)
    val = FedEMNIST(str(tmp_path), train=False, synthetic=True)
    tb = ds.gather(np.arange(len(ds)))
    vb = val.gather(np.arange(len(val)))

    def class_means(b):
        xs, ys = b["image"][..., 0], b["target"]
        return np.stack([xs[ys == c].mean(axis=0) for c in range(62)
                         if (ys == c).any()]), sorted(set(ys.tolist()))

    tm, tc = class_means(tb)
    vm, vc = class_means(vb)
    common = sorted(set(tc) & set(vc))
    assert len(common) >= 10
    ti = [tc.index(c) for c in common]
    vi = [vc.index(c) for c in common]
    d = ((tm[ti][:, None] - vm[vi][None]) ** 2).sum(axis=(-1, -2))
    # own-class distance must be the row minimum for every common class
    assert (d.argmin(axis=1) == np.arange(len(common))).all()


def test_premarker_backup_not_clobbered(tmp_path):
    """Re-preparing a marker-less synthetic dir twice must keep the FIRST
    .pre-marker.bak (the one that could hold a real-data prep) instead of
    os.replace-ing over it; later backups get a counter suffix."""
    import glob
    import json
    import os

    d = str(tmp_path)
    FedCIFAR10(d, synthetic=True, synthetic_per_class=8)
    pref = os.path.join(d, "stats_FedCIFAR10.json")

    def strip_marker():
        with open(pref) as f:
            meta = json.load(f)
        meta.pop("synthetic", None)
        with open(pref, "w") as f:
            json.dump(meta, f)

    strip_marker()
    FedCIFAR10(d, synthetic=True, synthetic_per_class=8)
    first = sorted(glob.glob(os.path.join(d, "*.pre-marker.bak")))
    assert first, "expected pre-marker backups after re-preparation"
    sentinel = first[0]
    with open(sentinel, "w") as f:
        f.write("FIRST-GENERATION-BACKUP")

    strip_marker()
    FedCIFAR10(d, synthetic=True, synthetic_per_class=8)
    # the first-generation backup survived byte-for-byte...
    with open(sentinel) as f:
        assert f.read() == "FIRST-GENERATION-BACKUP"
    # ...and the second generation landed under a counter suffix
    assert glob.glob(os.path.join(d, "*.pre-marker.bak.1"))
    # backup files themselves are never re-backed-up
    assert not glob.glob(os.path.join(d, "*.pre-marker.bak.bak*"))
    assert not glob.glob(os.path.join(d, "*.pre-marker.bak.pre-marker*"))
