"""Differential privacy (clip + noise) and top-k download compression.

Reference behavior pinned: DP worker mode clips each client gradient to
l2_norm_clip and adds sqrt(num_workers)-scaled gaussian noise
(fed_worker.py:304-309); DP server mode adds noise once to the aggregated
update (fed_aggregator.py:497-509); --topk_down keeps stale per-client
weights that advance by the top-k of their lag (fed_worker.py:232-247).
"""

import jax.numpy as jnp
import numpy as np

from commefficient_tpu.config import FedConfig
from commefficient_tpu.core import FedRuntime
from tests.test_parallel import make_batch, make_cfg, quad_loss


def make_rt(**kw):
    params = {"w": jnp.asarray(
        np.random.RandomState(0).randn(6, 3), jnp.float32)}
    cfg = make_cfg(**kw)
    return FedRuntime(cfg, params, quad_loss, num_clients=16)


def test_dp_clip_bounds_update():
    """With noise 0, DP reduces to per-client L2 clipping: the aggregated
    gradient norm is bounded by num_workers * clip / total_datums."""
    clip = 0.01
    rt = make_rt(mode="uncompressed", do_dp=True, dp_mode="worker",
                 l2_norm_clip=clip, noise_multiplier=0.0,
                 virtual_momentum=0.0, track_bytes=False)
    batch, mask, cids = make_batch(1)
    s = rt.init_state()
    w0 = np.asarray(s.ps_weights)
    s, _ = rt.round(s, cids, batch, mask, 1.0)
    total = float(np.asarray(mask).sum())
    bound = 8 * clip * np.asarray(mask.sum(1)).max() / total + 1e-6
    assert np.linalg.norm(np.asarray(s.ps_weights) - w0) <= bound


def test_dp_worker_noise_changes_update_deterministically():
    kw = dict(mode="uncompressed", do_dp=True, dp_mode="worker",
              l2_norm_clip=1.0, virtual_momentum=0.0, track_bytes=False)
    batch, mask, cids = make_batch(1)

    rt0 = make_rt(noise_multiplier=0.0, **kw)
    s0, _ = rt0.round(rt0.init_state(), cids, batch, mask, 0.1)
    rt1 = make_rt(noise_multiplier=0.5, **kw)
    s1, _ = rt1.round(rt1.init_state(), cids, batch, mask, 0.1)
    s1b, _ = rt1.round(rt1.init_state(), cids, batch, mask, 0.1)

    assert np.abs(np.asarray(s1.ps_weights)
                  - np.asarray(s0.ps_weights)).max() > 1e-6
    # same seed => same noise (JAX PRNG determinism; the reference relies
    # on cuDNN determinism flags instead, cv_train.py:325-326)
    np.testing.assert_array_equal(np.asarray(s1.ps_weights),
                                  np.asarray(s1b.ps_weights))


def test_dp_server_noise():
    kw = dict(mode="uncompressed", do_dp=True, dp_mode="server",
              l2_norm_clip=1e9, virtual_momentum=0.0, track_bytes=False)
    batch, mask, cids = make_batch(1)
    rt0 = make_rt(noise_multiplier=0.0, **kw)
    rt1 = make_rt(noise_multiplier=1.0, **kw)
    s0, _ = rt0.round(rt0.init_state(), cids, batch, mask, 0.1)
    s1, _ = rt1.round(rt1.init_state(), cids, batch, mask, 0.1)
    assert np.abs(np.asarray(s1.ps_weights)
                  - np.asarray(s0.ps_weights)).max() > 1e-6


def test_topk_down_client_weights_lag():
    rt = make_rt(mode="true_topk", error_type="virtual", k=4,
                 do_topk_down=True, virtual_momentum=0.0, track_bytes=False)
    batch, mask, cids = make_batch(2)
    s = rt.init_state()
    assert s.client_weights is not None
    w_init = np.asarray(s.client_weights).copy()
    for _ in range(2):
        s, _ = rt.round(s, cids, batch, mask, 0.1)
    cw = np.asarray(s.client_weights)
    participating = np.asarray(cids)
    others = [c for c in range(16) if c not in set(participating.tolist())]
    # participating clients' stale weights moved; others untouched
    assert np.abs(cw[participating] - w_init[participating]).max() > 0
    np.testing.assert_array_equal(cw[others], w_init[others])
    # each participant's weights differ from PS weights only at <= d coords
    # moved by top-k increments (k per round => at most 2k coords changed)
    changed = (np.abs(cw[participating] - w_init[participating]) > 0)
    assert changed.sum(axis=1).max() <= 2 * rt.cfg.k


def test_sketch_dense_clip_wiring():
    """--sketch_dense_clip (TPU-native extension): clips the DENSE worker
    gradient before encode instead of the reference's post-encode table
    clip. Pinned: (a) deferred encode survives (sketch linearity holds
    for summed clipped gradients); (b) a non-binding threshold reproduces
    the unclipped round exactly; (c) at a BINDING threshold the two
    placements nearly coincide — l2 clipping is a rescaling and encode is
    linear, so clip-then-encode = (t/||g||)·encode(g) while
    encode-then-clip = (t/median_row_norm)·encode(g), and the count
    sketch preserves norms in expectation (E||row||² = ||g||²). The flag
    therefore matters for threshold SEMANTICS (the dense placement
    scales with num_iters like the other modes; the reference's table
    clip is bare), not for the operation applied."""
    kw = dict(mode="sketch", error_type="virtual", local_momentum=0.0,
              weight_decay=0.0, k=5, num_rows=3, num_cols=32, num_blocks=2,
              track_bytes=False)
    batch, mask, cids = make_batch(1)

    rt_plain = make_rt(**kw)
    rt_loose = make_rt(max_grad_norm=1e9, sketch_dense_clip=True, **kw)
    rt_tight = make_rt(max_grad_norm=0.01, sketch_dense_clip=True, **kw)
    rt_table = make_rt(max_grad_norm=0.01, **kw)
    # dense clip keeps encode deferral; table clip kills it
    assert rt_plain._defer_encode and rt_loose._defer_encode
    assert rt_tight._defer_encode and not rt_table._defer_encode
    # per-client clip disables the fused path
    assert rt_plain._fused and not rt_loose._fused

    outs = {}
    for name, rt in (("plain", rt_plain), ("loose", rt_loose),
                     ("tight", rt_tight), ("table", rt_table)):
        s = rt.init_state()
        for _ in range(2):
            s, _ = rt.round(s, cids, batch, mask, 0.1)
        outs[name] = np.asarray(s.ps_weights)
    np.testing.assert_allclose(outs["plain"], outs["loose"],
                               rtol=1e-5, atol=1e-7)
    assert not np.allclose(outs["plain"], outs["tight"], rtol=1e-3)
    # linearity equivalence of the two placements at a binding threshold
    np.testing.assert_allclose(outs["tight"], outs["table"],
                               rtol=0.1, atol=1e-4)
    assert np.all(np.isfinite(outs["tight"]))
