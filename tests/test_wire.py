"""Int8 quantized sketch wire (--wire_dtype int8; ops/wire.py).

Numpy-reference checks of the quantizer (bit-exact hash + rounding),
stochastic-rounding determinism incl. across a resume, unbiasedness,
EF absorption, int8==f32 trajectory parity, the exact wire byte
accounting, the eligibility fail-fasts, the schema-v9 wire fields and
the teleview --wire_bytes_growth gate.
"""

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.config import FedConfig, parse_args
from commefficient_tpu.core import FedRuntime
from commefficient_tpu.ops.wire import (INT8_MAX, dequantize_accum,
                                        dequantize_table, quantize_table,
                                        wire_round_trip, wire_uniform)

# ---------------------------------------------------------------- numpy ref

_M32 = np.uint32(0xFFFFFFFF)


def _np_mix32(h):
    h = h.astype(np.uint64)
    h ^= h >> np.uint64(16)
    h = (h * np.uint64(0x85EBCA6B)) & np.uint64(0xFFFFFFFF)
    h ^= h >> np.uint64(13)
    h = (h * np.uint64(0xC2B2AE35)) & np.uint64(0xFFFFFFFF)
    h ^= h >> np.uint64(16)
    return h.astype(np.uint32)


def _np_wire_uniform(r, c, seed, round_idx, salt):
    rows = np.arange(r, dtype=np.uint64)
    cols = np.arange(c, dtype=np.uint64)
    base = ((rows[:, None] * np.uint64(0x01000193) + cols[None, :])
            & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    seed_mix = np.uint32((seed * 0x9E3779B1 + 0x7F4A7C15) & 0xFFFFFFFF)
    h = _np_mix32(base ^ seed_mix)
    rs = _np_mix32(np.uint32((round_idx * 0x85EBCA77
                              + salt * 0xC2B2AE3D) & 0xFFFFFFFF))
    h = _np_mix32((h.astype(np.uint64) + np.uint64(rs))
                  .astype(np.uint32) & _M32)
    return (h >> np.uint32(8)).astype(np.float32) * np.float32(2.0 ** -24)


def _np_quantize(table, block, seed, round_idx, salt):
    r, c = table.shape
    g = table.astype(np.float32).reshape(r, c // block, block)
    absmax = np.max(np.abs(g), axis=2)
    scale = (absmax / np.float32(INT8_MAX)).astype(np.float32)
    safe = np.where(scale > 0, scale, np.float32(1.0)).astype(np.float32)
    x = (g / safe[:, :, None]).astype(np.float32)
    u = _np_wire_uniform(r, c, seed, round_idx, salt)
    q = np.floor((x + u.reshape(r, c // block, block))
                 .astype(np.float32))
    q = np.clip(q, -INT8_MAX, INT8_MAX)
    return q.reshape(r, c).astype(np.int8), scale


def test_uniform_matches_numpy_reference():
    u = np.asarray(wire_uniform(7, 96, seed=21, round_idx=jnp.int32(5),
                                salt=jnp.int32(3)))
    ref = _np_wire_uniform(7, 96, 21, 5, 3)
    assert (u == ref).all()
    assert 0.0 <= u.min() and u.max() < 1.0
    # well spread (a broken mixer collapses toward constants)
    assert abs(u.mean() - 0.5) < 0.05


def test_quantize_matches_numpy_reference():
    rng = np.random.RandomState(0)
    t = rng.randn(3, 256).astype(np.float32)
    q, s = quantize_table(jnp.asarray(t), 64, seed=21,
                          round_idx=jnp.int32(7), salt=jnp.int32(1))
    qn, sn = _np_quantize(t, 64, 21, 7, 1)
    assert (np.asarray(s) == sn).all()
    assert (np.asarray(q) == qn).all()
    # dequantize round-trips within one quantization step per cell
    d = np.asarray(dequantize_table(q, s, 64))
    per_block_scale = np.repeat(sn, 64, axis=1)
    assert (np.abs(d - t) <= per_block_scale + 1e-7).all()
    assert np.abs(d - t).max() > 0  # the wire genuinely quantizes


def test_stochastic_rounding_deterministic_and_round_keyed():
    rng = np.random.RandomState(1)
    t = jnp.asarray(rng.randn(2, 128).astype(np.float32))
    q1, _ = quantize_table(t, 64, seed=3, round_idx=jnp.int32(9),
                           salt=jnp.int32(0))
    q2, _ = quantize_table(t, 64, seed=3, round_idx=jnp.int32(9),
                           salt=jnp.int32(0))
    assert (np.asarray(q1) == np.asarray(q2)).all()
    q3, _ = quantize_table(t, 64, seed=3, round_idx=jnp.int32(10),
                           salt=jnp.int32(0))
    q4, _ = quantize_table(t, 64, seed=3, round_idx=jnp.int32(9),
                           salt=jnp.int32(1))
    assert (np.asarray(q1) != np.asarray(q3)).any()
    assert (np.asarray(q1) != np.asarray(q4)).any()


def test_stochastic_rounding_unbiased():
    rng = np.random.RandomState(2)
    t = jnp.asarray(rng.randn(2, 128).astype(np.float32))
    f = jax.jit(lambda r: wire_round_trip(t, 64, seed=5, round_idx=r,
                                          salt=jnp.int32(0)))
    N = 2000
    acc = np.zeros((2, 128), np.float64)
    for r in range(N):
        acc += np.asarray(f(jnp.int32(r)))
    bias = acc / N - np.asarray(t)
    _, s = quantize_table(t, 64, seed=5, round_idx=jnp.int32(0),
                          salt=jnp.int32(0))
    # per-cell bias of an unbiased rounder is N(0, scale^2/12N)-ish;
    # 6 sigma over 256 cells with headroom
    bound = 6 * float(np.max(np.asarray(s))) / np.sqrt(12 * N)
    assert np.abs(bias).max() < max(bound, 1e-3), (np.abs(bias).max(),
                                                   bound)


def test_zero_and_nan_blocks():
    t = jnp.zeros((2, 128), jnp.float32)
    out = wire_round_trip(t, 64, seed=1, round_idx=jnp.int32(1), salt=0)
    assert (np.asarray(out) == 0).all()
    tn = t.at[1, 70].set(jnp.nan)
    outn = np.asarray(wire_round_trip(tn, 64, seed=1,
                                      round_idx=jnp.int32(1), salt=0))
    # the NaN poisons exactly its own block — the wire never launders a
    # non-finite upload into finite int8 cells
    assert np.isnan(outn[1, 64:]).all()
    assert np.isfinite(outn[0]).all() and np.isfinite(outn[1, :64]).all()


def test_dequantize_accum_matches_per_source_sum():
    rng = np.random.RandomState(3)
    qs, ss, ref = [], [], np.zeros((3, 128), np.float32)
    for i in range(4):
        t = rng.randn(3, 128).astype(np.float32)
        q, s = quantize_table(jnp.asarray(t), 32, seed=9,
                              round_idx=jnp.int32(2), salt=jnp.int32(i))
        qs.append(np.asarray(q))
        ss.append(np.asarray(s))
        ref += np.asarray(dequantize_table(q, s, 32))
    out = dequantize_accum(jnp.asarray(np.stack(qs)),
                           jnp.asarray(np.stack(ss)), 32)
    assert np.allclose(np.asarray(out), ref, rtol=1e-6, atol=1e-6)


def test_sketch_class_wire_entry_points():
    """The impl-agnostic quantize_wire/dequantize_wire methods on both
    sketch classes are thin delegates to ops/wire.py — pinned here so
    the convenience surface can never drift from the real quantizer."""
    from commefficient_tpu.ops.circulant import make_circulant_sketch
    from commefficient_tpu.ops.sketch import make_sketch
    rng = np.random.RandomState(4)
    t = jnp.asarray(rng.randn(3, 256).astype(np.float32))
    for cs in (make_sketch(1000, 256, 3),
               make_circulant_sketch(1000, 256, 3)):
        q, s = cs.quantize_wire(t, 64, seed=7, round_idx=jnp.int32(2),
                                salt=jnp.int32(1))
        qr, sr = quantize_table(t, 64, seed=7, round_idx=jnp.int32(2),
                                salt=jnp.int32(1))
        assert (np.asarray(q) == np.asarray(qr)).all()
        assert (np.asarray(s) == np.asarray(sr)).all()
        d = cs.dequantize_wire(q, s, 64)
        assert (np.asarray(d)
                == np.asarray(dequantize_table(qr, sr, 64))).all()


# --------------------------------------------------- config + accounting


def test_upload_wire_bytes_accounting():
    base = dict(mode="sketch", error_type="virtual", num_rows=3,
                num_cols=512, grad_size=4096)
    f32 = FedConfig(**base)
    assert f32.wire_dtype == "float32"
    assert f32.upload_wire_bytes() == 4.0 * 3 * 512
    bf16 = FedConfig(wire_dtype="bfloat16", **base)
    assert bf16.upload_wire_bytes() == 2.0 * 3 * 512
    int8 = FedConfig(wire_dtype="int8", wire_block=64, **base)
    # 1 byte/cell + 4 bytes of f32 scale per 64-cell block
    assert int8.upload_wire_bytes() == 3 * 512 + 4 * 3 * (512 // 64)
    # the runtime passes its resolved effective block
    assert int8.upload_wire_bytes(block=128) == 3 * 512 + 4 * 3 * 4
    # dense modes keep the 4-byte float wire
    unc = FedConfig(mode="uncompressed", error_type="none",
                    grad_size=1000)
    assert unc.upload_wire_bytes() == 4.0 * 1000


def test_sketch_dtype_alias_resolution():
    # direct construction: wire inherits the legacy field
    cfg = FedConfig(mode="sketch", error_type="virtual",
                    sketch_dtype="bfloat16")
    assert cfg.wire_dtype == "bfloat16"
    # an explicit bf16 wire syncs the rht transform compute dtype
    cfg2 = FedConfig(mode="sketch", error_type="virtual",
                     wire_dtype="bfloat16")
    assert cfg2.sketch_dtype == "bfloat16"
    # int8 wire leaves sketch_dtype f32 (no bf16 transform implied)
    cfg3 = FedConfig(mode="sketch", error_type="virtual",
                     wire_dtype="int8")
    assert cfg3.sketch_dtype == "float32"
    # an explicit int8 wire WINS over the bf16 alias: sketch_dtype is
    # forced back to f32 so the runtime's bf16 rounding branch can
    # never shadow the int8 wire (and byte accounting stays truthful)
    cfg4 = FedConfig(mode="sketch", error_type="virtual",
                     sketch_dtype="bfloat16", wire_dtype="int8")
    assert cfg4.sketch_dtype == "float32"
    assert cfg4.wire_dtype == "int8"
    cfg5 = parse_args(["--mode", "sketch", "--sketch_dtype", "bfloat16",
                       "--wire_dtype", "int8"])
    assert cfg5.sketch_dtype == "float32" and cfg5.wire_dtype == "int8"
    # ... and an explicit f32 wire wins too: the runtime's bf16 branch
    # keys off sketch_dtype, so leaving it bf16 would arm a wire the
    # config claims is f32
    cfg6 = parse_args(["--mode", "sketch", "--sketch_dtype", "bfloat16",
                       "--wire_dtype", "float32"])
    assert cfg6.sketch_dtype == "float32" and cfg6.wire_dtype == "float32"
    assert cfg6.upload_wire_bytes() == 4.0 * cfg6.upload_floats


def test_sketch_dtype_parse_time_deprecation(capsys):
    cfg = parse_args(["--mode", "sketch", "--sketch_dtype", "bfloat16"])
    err = capsys.readouterr().err
    assert "deprecated" in err and "--wire_dtype" in err
    assert cfg.wire_dtype == "bfloat16"
    # explicit --wire_dtype wins over the alias
    cfg2 = parse_args(["--mode", "sketch", "--sketch_dtype", "bfloat16",
                       "--wire_dtype", "int8"])
    assert cfg2.wire_dtype == "int8"
    # no alias, no warning
    capsys.readouterr()
    cfg3 = parse_args(["--mode", "sketch"])
    assert "deprecated" not in capsys.readouterr().err
    assert cfg3.wire_dtype == "float32"


def test_int8_fail_fasts():
    with pytest.raises(ValueError, match="mode sketch"):
        FedConfig(mode="uncompressed", error_type="none",
                  wire_dtype="int8")
    with pytest.raises(ValueError, match="rht"):
        FedConfig(mode="sketch", error_type="virtual", sketch_impl="rht",
                  wire_dtype="int8")
    with pytest.raises(ValueError, match="dense"):
        FedConfig(mode="sketch", error_type="virtual",
                  sketch_server_state="dense", wire_dtype="int8")
    with pytest.raises(ValueError, match="wire_block"):
        FedConfig(mode="sketch", error_type="virtual", wire_block=4)
    with pytest.raises(ValueError, match="wire_dtype"):
        FedConfig(mode="sketch", error_type="virtual", wire_dtype="fp8")


# ------------------------------------------------------- runtime trajectory

_D, _C = 12, 10


def _linear_loss():
    key = jax.random.PRNGKey(0xDEF)
    P = jax.random.normal(jax.random.fold_in(key, 1), (_D, _C),
                          jnp.float32)

    def loss_fn(params, batch, mask):
        logits = batch["x"] @ params["w"]
        m = mask.astype(jnp.float32)
        denom = jnp.maximum(m.sum(), 1.0)
        lp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(lp, batch["target"][:, None],
                                   axis=1)[:, 0]
        loss = (nll * m).sum() / denom
        acc = ((logits.argmax(1) == batch["target"]) * m).sum() / denom
        return loss, (acc,)

    def batch_for(W, B, g):
        k1 = jax.random.fold_in(key, 1000 + g)
        x = jax.random.normal(k1, (W, B, _D), jnp.float32)
        t = jnp.argmax(x @ P, axis=-1).astype(jnp.int32)
        return {"x": x, "target": t}

    return loss_fn, batch_for


def _wire_cfg(**kw):
    base = dict(mode="sketch", error_type="virtual", local_momentum=0.0,
                virtual_momentum=0.9, weight_decay=0.0, num_workers=4,
                local_batch_size=8, k=8, num_rows=3, num_cols=64,
                num_blocks=2, num_clients=4, track_bytes=True,
                num_results_train=2)
    base.update(kw)
    return FedConfig(**base)


def _run_rounds(cfg, n_rounds, state=None, start=1):
    loss_fn, batch_for = _linear_loss()
    rt = FedRuntime(cfg, {"w": jnp.zeros((_D, _C), jnp.float32)},
                    loss_fn, num_clients=cfg.num_workers)
    if state is None:
        state = rt.init_state()
    ids = jnp.arange(cfg.num_workers, dtype=jnp.int32)
    mask = jnp.ones((cfg.num_workers, 8), bool)
    losses, err_norms = [], []
    for g in range(start, start + n_rounds):
        state, m = rt.round(state, ids, batch_for(cfg.num_workers, 8, g),
                            mask, 0.3)
        losses.append(float(np.asarray(m["results"][0]).mean()))
        err_norms.append(float(np.linalg.norm(np.asarray(state.Verror))))
    return rt, state, np.asarray(losses), np.asarray(err_norms)


def test_int8_trajectory_parity_and_ef_absorption():
    """int8 == f32 within the committed band on a short learning curve
    (the hard-v2-style dryrun contract), and the quantized run's EF
    accumulator stays bounded relative to f32 — the rounding residual
    is ABSORBED, not accumulated (it is zero-mean by construction)."""
    _, _, l32, e32 = _run_rounds(_wire_cfg(), 16)
    _, _, l8, e8 = _run_rounds(_wire_cfg(wire_dtype="int8"), 16)
    assert np.all(np.isfinite(l8))
    # learning happened in both arms and the curves track each other
    assert l8[-1] < l8[0]
    assert abs(l8[-1] - l32[-1]) <= 0.10 * abs(l32[-1]) + 1e-3, (l8, l32)
    # EF absorption: bounded vs the f32 run's accumulator trajectory
    assert e8[-1] <= 2.0 * e32[-1] + 1e-3, (e8, e32)
    assert np.all(e8 <= 2.0 * np.maximum(e32, e32.max()) + 1e-3)


def test_int8_bitwise_replay_across_resume():
    """The rounding draws key off the CHECKPOINTED round counter: a run
    split at round 3 and continued from a state snapshot in a FRESH
    runtime replays rounds 4..6 bitwise."""
    cfg = _wire_cfg(wire_dtype="int8")
    loss_fn, batch_for = _linear_loss()
    ids = jnp.arange(4, dtype=jnp.int32)
    mask = jnp.ones((4, 8), bool)

    def rounds(rt, state, lo, hi):
        ls = []
        for g in range(lo, hi + 1):
            state, m = rt.round(state, ids, batch_for(4, 8, g), mask, 0.3)
            ls.append(np.asarray(m["results"][0]))
        return state, np.stack(ls)

    rt_a = FedRuntime(cfg, {"w": jnp.zeros((_D, _C), jnp.float32)},
                      loss_fn, num_clients=4)
    _, la = rounds(rt_a, rt_a.init_state(), 1, 6)

    rt_b = FedRuntime(cfg, {"w": jnp.zeros((_D, _C), jnp.float32)},
                      loss_fn, num_clients=4)
    sb, lb_head = rounds(rt_b, rt_b.init_state(), 1, 3)
    snap = jax.tree.map(lambda x: None if x is None else np.asarray(x),
                        sb)
    del rt_b, sb
    rt_c = FedRuntime(cfg, {"w": jnp.zeros((_D, _C), jnp.float32)},
                      loss_fn, num_clients=4)
    sc = jax.tree.map(lambda x: None if x is None else jnp.asarray(x),
                      snap)
    _, lb_tail = rounds(rt_c, sc, 4, 6)
    lb = np.concatenate([lb_head, lb_tail])
    assert (la == lb).all(), (la, lb)


def test_int8_upload_bytes_in_round_metrics():
    cfg = _wire_cfg(wire_dtype="int8")
    loss_fn, batch_for = _linear_loss()
    rt = FedRuntime(cfg, {"w": jnp.zeros((_D, _C), jnp.float32)},
                    loss_fn, num_clients=4)
    state = rt.init_state()
    ids = jnp.arange(4, dtype=jnp.int32)
    _, m = rt.round(state, ids, batch_for(4, 8, 1),
                    jnp.ones((4, 8), bool), 0.3)
    up = float(np.asarray(m["upload_bytes"]).sum())
    # effective block on one device: min(wire_block, c) = 64
    expected = 4 * cfg.upload_wire_bytes(block=rt._wire_block)
    assert up == expected
    assert up < 4 * 4.0 * cfg.upload_floats  # genuinely below f32


def test_int8_mesh_reduce_matches_numpy_reference(devices):
    """The quantized all_to_all reduce (ops/wire.int8_reduce_scatter
    under shard_map) equals the numpy reference: per-device quantize
    (salt = device index) -> dequantize -> sum, column-shard layout."""
    if len(devices) < 8:
        pytest.skip("needs 8 virtual devices")
    from jax.sharding import Mesh, PartitionSpec as P

    from commefficient_tpu.ops.wire import REDUCE_SALT, int8_reduce_scatter
    from commefficient_tpu.utils.jax_compat import shard_map

    n, r, c, blk = 8, 3, 512, 64
    mesh = Mesh(np.array(devices[:8]), ("clients",))
    rng = np.random.RandomState(11)
    partials = rng.randn(n, r, c).astype(np.float32)

    def blk_fn(part, step):
        return int8_reduce_scatter(part[0], axis="clients", n_shards=n,
                                   block=blk, seed=21, round_idx=step)

    out = shard_map(blk_fn, mesh=mesh,
                    in_specs=(P("clients", None, None), P()),
                    out_specs=P(None, "clients"),
                    check_vma=False)(jnp.asarray(partials),
                                     jnp.int32(5))
    out = np.asarray(out)
    assert out.shape == (r, c)
    ref = np.zeros((r, c), np.float32)
    for i in range(n):
        # the reduce quantizer salts in its own namespace (REDUCE_SALT
        # offset) so it can never share a draw stream with a slot-
        # salted per-client upload in the same round
        q, s = _np_quantize(partials[i], blk, 21, 5, REDUCE_SALT + i)
        ref += np.repeat(s, blk, axis=1) * q.astype(np.float32)
    assert np.allclose(out, ref, rtol=1e-5, atol=1e-5), (
        np.abs(out - ref).max())


# ------------------------------------------------- telemetry + tooling


def test_collective_wire_bytes_model():
    from commefficient_tpu.telemetry.collectives import (
        collective_wire_bytes, table_reduce_wire_bytes)
    rs = {"kind": "reduce-scatter", "bytes": 768, "n_elements": 192}
    a2a = {"kind": "all-to-all", "bytes": 1536, "n_elements": 1536}
    ar = {"kind": "all-reduce", "bytes": 100, "n_elements": 25}
    ag = {"kind": "all-gather", "bytes": 800, "n_elements": 200}
    n = 8
    assert collective_wire_bytes(rs, n) == 768 * 7
    assert collective_wire_bytes(a2a, n) == 1536 * 7 / 8
    assert collective_wire_bytes(ar, n) == 2 * 100 * 7 / 8
    assert collective_wire_bytes(ag, n) == 800 * 7 / 8
    assert collective_wire_bytes(rs, 1) == 0.0
    # only the table-REDUCE kinds count
    assert table_reduce_wire_bytes([rs, a2a, ar, ag], n) == \
        768 * 7 + 1536 * 7 / 8
    # the ISSUE-14 ratio at the gate geometry: int8 cells + f32 scales
    # vs the f32 reduce-scatter of the same (3, 512) table
    scales = {"kind": "all-to-all", "bytes": 96, "n_elements": 24}
    f32_bytes = table_reduce_wire_bytes([rs], n)
    int8_bytes = table_reduce_wire_bytes([a2a, scales], n)
    assert int8_bytes <= 0.30 * f32_bytes


def test_schema_v9_wire_fields():
    from commefficient_tpu.telemetry.schema import validate_event
    ev = {"event": "collectives", "t": 0.0, "seq": 1, "name": "round_step",
          "n_collectives": 3, "counts": {"all-to-all": 2},
          "total_bytes": 2000, "ops": []}
    # a v8 stream legitimately omits the wire fields...
    assert validate_event(ev, version=8) == []
    # ...a v9 stream must carry them...
    problems = validate_event(ev, version=9)
    assert any("wire_dtype" in p for p in problems)
    assert any("table_reduce_bytes" in p for p in problems)
    # ...and they type-check (null allowed — single-device runs)
    ev.update(wire_dtype="int8", table_reduce_bytes=1428.0)
    assert validate_event(ev, version=9) == []
    ev.update(wire_dtype=None, table_reduce_bytes=None)
    assert validate_event(ev, version=9) == []
    sig = {"event": "signals", "t": 0.0, "seq": 2, "round": 1,
           "mode": "sketch"}
    for k in ("grad_norm", "grad_true_norm", "grad_l2estimate",
              "velocity_norm", "error_norm", "error_l2estimate",
              "update_norm", "support_density", "topk_overlap",
              "download_bytes", "upload_bytes", "client_download_bytes",
              "client_upload_bytes"):
        sig[k] = None
    assert any("wire_dtype" in p for p in validate_event(sig, version=9))
    sig["wire_dtype"] = "bfloat16"
    assert validate_event(sig, version=9) == []
    bench = {"event": "bench", "t": 0.0, "seq": 3, "metric": "x",
             "result": {}}
    assert any("wire_dtype" in p
               for p in validate_event(bench, version=9))
    bench["wire_dtype"] = "float32"
    assert validate_event(bench, version=9) == []


def test_telemetry_events_carry_wire_dtype(tmp_path):
    from commefficient_tpu.telemetry import RunTelemetry
    from commefficient_tpu.telemetry.schema import validate_file
    cfg = _wire_cfg(wire_dtype="int8")
    tel = RunTelemetry(str(tmp_path), "test", cfg=cfg)
    tel.bench_event("m", {"value": 1.0})
    tel.collectives_event("round_step", [
        {"kind": "all-to-all", "n_elements": 1536, "dtype": "s8",
         "bytes": 1536, "combined_in": 0}])
    tel.write_summary(aborted=False, n_rounds=0)
    tel.close()
    assert validate_file(tel.path) == []
    events = [json.loads(ln) for ln in open(tel.path)]
    bench = next(e for e in events if e["event"] == "bench")
    assert bench["wire_dtype"] == "int8"
    coll = next(e for e in events if e["event"] == "collectives")
    assert coll["wire_dtype"] == "int8"
    # manifest sketch geometry names the wire too
    man = events[0]
    assert man["sketch"]["wire_dtype"] == "int8"


def _load_teleview():
    import importlib.util
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "teleview.py")
    spec = importlib.util.spec_from_file_location("teleview_wire", path)
    tv = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tv)
    return tv


def _mini_stream(path, table_reduce_bytes):
    events = [
        {"event": "manifest", "t": 0.0, "seq": 0, "schema": 9,
         "run_type": "t", "jax_version": "0", "backend": "cpu",
         "device_kind": "cpu", "device_count": 8, "mesh_shape": [8],
         "mesh_axes": ["clients"], "grad_size": 10, "sketch": None,
         "config": {}, "stream_id": "t-0-0"},
        {"event": "collectives", "t": 1.0, "seq": 1, "name": "round_step",
         "n_collectives": 1, "counts": {"all-to-all": 2},
         "total_bytes": 2000, "ops": [], "wire_dtype": "int8",
         "table_reduce_bytes": table_reduce_bytes},
        {"event": "summary", "t": 2.0, "seq": 2, "run_type": "t",
         "aborted": False, "n_rounds": 1, "total_download_mib": None,
         "total_upload_mib": None, "wall_time_s": 1.0,
         "event_counts": {}, "final": None},
    ]
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    return str(path)


def test_teleview_wire_bytes_growth_gate(tmp_path):
    tv = _load_teleview()
    a = _mini_stream(tmp_path / "a.jsonl", 1428.0)
    b_ok = _mini_stream(tmp_path / "b.jsonl", 1450.0)     # +1.5%
    b_bad = _mini_stream(tmp_path / "c.jsonl", 5376.0)    # re-widened
    assert tv.main(["diff", a, b_ok]) == 0
    assert tv.main(["diff", a, b_bad]) == 1
    # explicit threshold slackening passes
    assert tv.main(["diff", a, b_bad, "--wire_bytes_growth", "10"]) == 0
