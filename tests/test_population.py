"""Population-scale observability (telemetry/population.py +
telemetry/clients.py make_ledger + the schema-v11 ``population`` event):
every estimator against a numpy reference with its documented bound
asserted on an adversarially skewed stream, seeded determinism and the
bitwise checkpoint-sidecar round-trip, sketch/exact snapshot parity,
the PR-13 sidecar size guard in both directions, the coverage_stall /
hh_churn monitor rules, and the teleview ``population`` / ``trend`` /
``diff --coverage_stall`` surfaces with their jax-free literal pins."""

import json
import os
import re

import numpy as np
import pytest

from commefficient_tpu.telemetry.clients import (ParticipationLedger,
                                                 make_ledger)
from commefficient_tpu.telemetry.population import (AUTO_SKETCH_THRESHOLD,
                                                    MEMORY_BUDGET_BYTES,
                                                    POPULATION_KEYS,
                                                    CountMinSketch,
                                                    KMVSample,
                                                    P2Quantile,
                                                    PopulationLedger,
                                                    SpaceSaving)

ROOT = os.path.join(os.path.dirname(__file__), os.pardir)


def zipf_stream(rs, num_clients, slots):
    """Adversarially skewed draw: a zipf head hammering a few hot ids
    (the worst case for count-min row pollution) plus a uniform tail."""
    hot = rs.zipf(1.5, slots // 2) % num_clients
    cold = rs.randint(0, num_clients, slots - slots // 2)
    ids = np.concatenate([hot, cold]).astype(np.int64)
    return ids, rs.randint(1, 9, slots).astype(np.int64)


# ------------------------------------------------------------- count-min


def test_count_min_bounds_on_skewed_stream():
    rs = np.random.RandomState(11)
    cms = CountMinSketch(seed=3)
    true = np.zeros(50_000, np.float64)
    for _ in range(200):
        ids, w = zipf_stream(rs, 50_000, 256)
        cms.add(ids, w)
        np.add.at(true, ids, w.astype(np.float64))
    n = float(true.sum())
    est = cms.query(np.arange(50_000, dtype=np.int64))
    # one-sided estimator: NEVER undercounts...
    assert np.all(est >= true - 1e-9)
    # ...and overcounts <= eps*N with probability >= 1 - delta
    frac_ok = np.mean(est - true <= cms.epsilon * n)
    assert frac_ok >= 1.0 - cms.delta, (frac_ok, cms.delta)


def test_count_min_deterministic_and_roundtrip():
    streams = [zipf_stream(np.random.RandomState(5), 1000, 64)
               for _ in range(20)]
    a, b = CountMinSketch(seed=9), CountMinSketch(seed=9)
    for ids, w in streams:
        a.add(ids, w)
        b.add(ids, w)
    assert json.dumps(a.state_dict()) == json.dumps(b.state_dict())
    c = CountMinSketch(seed=9)
    c.load_state_dict(json.loads(json.dumps(a.state_dict())))
    ids = np.arange(1000, dtype=np.int64)
    assert np.array_equal(a.query(ids), c.query(ids))


# ---------------------------------------------------------- space-saving


def test_space_saving_holds_guaranteed_heavy_hitters():
    rs = np.random.RandomState(7)
    ss = SpaceSaving(k=64)
    true = np.zeros(10_000, np.float64)
    for _ in range(100):
        ids, w = zipf_stream(rs, 10_000, 256)
        uniq, inv = np.unique(ids, return_inverse=True)
        sums = np.zeros(uniq.size, np.float64)
        np.add.at(sums, inv, w.astype(np.float64))
        ss.offer(uniq, sums)
        np.add.at(true, ids, w.astype(np.float64))
    n = float(true.sum())
    heavy = np.nonzero(true > n / ss.k)[0]
    assert heavy.size > 0, "stream not skewed enough to test anything"
    stored = {int(c): v for c, v in ss._counts.items()}
    for c in heavy.tolist():
        # any id with weight > N/K is guaranteed present, and its
        # reported count brackets the truth: count - err <= true <= count
        assert c in stored, c
        err = ss._errs.get(c, 0.0)
        assert stored[c] - err <= true[c] + 1e-9 <= stored[c] + 1e-9
    # top(n) is (count desc, id asc) ordered [id, count, err] triples
    top = ss.top(10)
    assert all(top[i][1] >= top[i + 1][1] for i in range(len(top) - 1))


def test_space_saving_exact_below_capacity_and_deterministic():
    ss = SpaceSaving(k=8)
    ss.offer(np.asarray([3, 1, 5]), np.asarray([2.0, 1.0, 4.0]))
    ss.offer(np.asarray([3]), np.asarray([1.0]))
    assert {int(c): v for c, v in ss._counts.items()} == {
        3: 3.0, 1: 1.0, 5: 4.0}
    assert all(e == 0.0 for e in ss._errs.values())
    a, b = SpaceSaving(k=4), SpaceSaving(k=4)
    rs = np.random.RandomState(2)
    for _ in range(50):
        ids, w = zipf_stream(rs, 100, 16)
        uniq, inv = np.unique(ids, return_inverse=True)
        sums = np.zeros(uniq.size, np.float64)
        np.add.at(sums, inv, w.astype(np.float64))
        a.offer(uniq, sums)
        b.offer(uniq, sums)
    assert json.dumps(a.state_dict()) == json.dumps(b.state_dict())


# ------------------------------------------------------------------- P2


def test_p2_quantiles_vs_numpy():
    rs = np.random.RandomState(13)
    vals = rs.lognormal(2.0, 0.4, 5000)
    for p in (0.5, 0.95):
        q = P2Quantile(p)
        for v in vals:
            q.add(float(v))
        ref = float(np.percentile(vals, p * 100))
        assert abs(q.value() - ref) <= 0.05 * ref, (p, q.value(), ref)


def test_p2_exact_small_and_roundtrip():
    q = P2Quantile(0.5)
    for v in (3.0, 1.0, 2.0):
        q.add(v)
    assert q.value() == 2.0  # exact until the 5-marker regime
    rs = np.random.RandomState(1)
    for v in rs.rand(100):
        q.add(float(v))
    r = P2Quantile(0.5)
    r.load_state_dict(json.loads(json.dumps(q.state_dict())))
    assert r.value() == q.value()
    r.add(0.5)
    q.add(0.5)
    assert r.value() == q.value()


# ------------------------------------------------------------------ KMV


def test_kmv_distinct_exact_below_capacity():
    kmv = KMVSample(size=128, seed=0)
    kmv.observe(1, np.arange(50, dtype=np.int64),
                np.ones(50, np.float64))
    assert kmv.distinct() == 50.0


def test_kmv_distinct_estimate_within_bound():
    rs = np.random.RandomState(3)
    kmv = KMVSample(size=1024, seed=4)
    seen = set()
    for rnd in range(1, 120):
        ids = rs.randint(0, 80_000, 512).astype(np.int64)
        uniq = np.unique(ids)
        kmv.observe(rnd, uniq, np.ones(uniq.size, np.float64))
        seen.update(uniq.tolist())
    rel = abs(kmv.distinct() - len(seen)) / len(seen)
    assert rel <= 5.0 / np.sqrt(kmv.size), (kmv.distinct(), len(seen))


def test_kmv_roundtrip_bitwise_and_exact_member_counts():
    rs = np.random.RandomState(6)
    a = KMVSample(size=64, seed=8)
    for rnd in range(1, 40):
        ids = np.unique(rs.randint(0, 500, 32).astype(np.int64))
        a.observe(rnd, ids, np.full(ids.size, 2.0))
    b = KMVSample(size=64, seed=8)
    b.load_state_dict(json.loads(json.dumps(a.state_dict())))
    assert json.dumps(a.state_dict()) == json.dumps(b.state_dict())
    ids = np.unique(rs.randint(0, 500, 32).astype(np.int64))
    a.observe(40, ids, np.full(ids.size, 2.0))
    b.observe(40, ids, np.full(ids.size, 2.0))
    # the heap rebuilt on load must evict identically forever after
    assert json.dumps(a.state_dict()) == json.dumps(b.state_dict())
    # tracked members carry EXACT cumulative weight (every observation
    # here weighs 2.0, so every sampled count is a multiple of it)
    assert np.all(np.mod(a.counts(), 2.0) == 0.0)


# ------------------------------------------------- ledger parity + resume


def small_streams(n_rounds=60, num_clients=400, slots=32, seed=21):
    rs = np.random.RandomState(seed)
    return [zipf_stream(rs, num_clients, slots) for _ in range(n_rounds)]


def test_sketch_and_exact_snapshots_agree_on_small_population():
    streams = small_streams()
    sk = PopulationLedger(400, seed=2)
    ex = ParticipationLedger(400)
    for rnd, (ids, w) in enumerate(streams, start=1):
        sk.observe(rnd, ids, w)
        ex.observe(rnd, ids, w)
    ssnap = sk.population_snapshot(len(streams))
    esnap = ex.population_snapshot(len(streams))
    assert tuple(ssnap) == tuple(esnap) == POPULATION_KEYS
    assert ssnap["estimated"] is True and esnap["estimated"] is False
    # 400 clients fit the KMV sample entirely: distinct/coverage exact
    assert ssnap["distinct"] == esnap["distinct"]
    assert ssnap["coverage"] == pytest.approx(esnap["coverage"])
    assert ssnap["counts_p50"] == pytest.approx(esnap["counts_p50"])
    assert ssnap["staleness_p50"] == pytest.approx(esnap["staleness_p50"])
    # per-round snapshot (the client_stats participation fields) agrees
    # too, and both carry their mode's `estimated` flag
    s, e = sk.snapshot(len(streams)), ex.snapshot(len(streams))
    assert s["estimated"] is True and e["estimated"] is False
    assert s["coverage"] == pytest.approx(e["coverage"])


def test_sketch_ledger_bitwise_resume_at_half():
    streams = small_streams(seed=22)
    half = len(streams) // 2
    full = PopulationLedger(400, seed=5)
    resumed = None
    for rnd, (ids, w) in enumerate(streams, start=1):
        full.observe(rnd, ids, w)
        full.observe_loss_argmax(int(ids[0]))
        if rnd % 7 == 0:
            full.observe_strikes(ids[:2])
        if resumed is not None:
            resumed.observe(rnd, ids, w)
            resumed.observe_loss_argmax(int(ids[0]))
            if rnd % 7 == 0:
                resumed.observe_strikes(ids[:2])
        if rnd == half:
            resumed = PopulationLedger(400, seed=5)
            resumed.load_state_dict(
                json.loads(json.dumps(full.state_dict())))
    assert json.dumps(full.state_dict()) == json.dumps(
        resumed.state_dict())


def test_mode_mismatch_sidecars_refuse_to_load():
    ex = ParticipationLedger(10)
    ex.observe(1, np.asarray([1, 2]), np.asarray([3, 4]))
    sk = PopulationLedger(10)
    sk.observe(1, np.asarray([1, 2]), np.asarray([3, 4]))
    with pytest.raises(ValueError, match="population_sketch"):
        sk.load_state_dict(json.loads(json.dumps(ex.state_dict())))
    with pytest.raises(ValueError, match="exact ledger"):
        ex.load_state_dict(json.loads(json.dumps(sk.state_dict())))


def test_make_ledger_policy():
    assert isinstance(make_ledger(50, "off"), ParticipationLedger)
    assert isinstance(make_ledger(50, "on"), PopulationLedger)
    assert isinstance(make_ledger(AUTO_SKETCH_THRESHOLD - 1, "auto"),
                      ParticipationLedger)
    assert isinstance(make_ledger(AUTO_SKETCH_THRESHOLD, "auto"),
                      PopulationLedger)
    with pytest.raises(ValueError, match="population_sketch"):
        make_ledger(50, "maybe")


def test_memory_budget_is_population_independent():
    small = PopulationLedger(1000)
    big = PopulationLedger(10**6)
    assert small.memory_bytes() == big.memory_bytes()
    assert big.memory_bytes() <= MEMORY_BUDGET_BYTES


# --------------------------------------- vectorized observe (satellite 1)


def test_vectorized_observe_matches_per_slot_reference_loop():
    streams = small_streams(n_rounds=30, seed=23)
    led = ParticipationLedger(400)
    ref_samples, ref_last = {}, {}
    for rnd, (ids, w) in enumerate(streams, start=1):
        led.observe(rnd, ids, w)
        for c, n in zip(ids.tolist(), w.tolist()):
            if n <= 0:
                continue
            ref_samples[int(c)] = ref_samples.get(int(c), 0) + int(n)
            ref_last[int(c)] = rnd
    st = led.state_dict()
    assert {int(c): n for c, n in st["samples"].items()} == ref_samples
    assert {int(c): r for c, r in st["last_round"].items()} == ref_last
    snap = led.snapshot(len(streams))
    counts = np.asarray(sorted(ref_samples.values()), np.float64)
    assert snap["counts_max"] == counts.max()
    assert snap["distinct_clients"] == len(ref_samples)


def test_observe_drops_nonpositive_slots():
    led = ParticipationLedger(10)
    led.observe(1, np.asarray([1, 2, 3]), np.asarray([2, 0, -1]))
    assert {int(c) for c in led.state_dict()["samples"]} == {1}


# ------------------------------------------- PR-13 sidecar + size guard


def test_sidecar_guard_passes_under_cap_and_fails_over(monkeypatch):
    from commefficient_tpu.core import preempt

    sk = PopulationLedger(10**6, seed=1)
    rs = np.random.RandomState(9)
    for rnd in range(1, 40):
        ids, w = zipf_stream(rs, 10**6, 256)
        sk.observe(rnd, ids, w)
    out = preempt.collect_ledger_state(participation=sk)
    assert len(json.dumps(out["participation"]).encode()) \
        <= preempt.LEDGER_SIDECAR_MAX_BYTES
    # restoring through the sidecar into a fresh runtime's ledger is
    # bitwise — the PR-13 contract the gate replays at full scale
    fresh = PopulationLedger(10**6, seed=1)
    preempt.restore_ledger_state(json.loads(json.dumps(out)),
                                 participation=fresh)
    assert json.dumps(fresh.state_dict()) == json.dumps(sk.state_dict())

    ex = ParticipationLedger(1000)
    ex.observe(1, np.arange(1000, dtype=np.int64),
               np.ones(1000, np.int64))
    monkeypatch.setattr(preempt, "LEDGER_SIDECAR_MAX_BYTES", 4096)
    with pytest.raises(ValueError, match="--population_sketch on"):
        preempt.collect_ledger_state(participation=ex)
    # the sketch ledger's bounded state still fits the tightened cap?
    # no — 4 KiB is below its ~3 MiB floor: the guard applies to BOTH
    # ledgers (it caps the sidecar, not a ledger kind)
    with pytest.raises(ValueError):
        preempt.collect_ledger_state(participation=sk)


# ------------------------------------------------------- schema (v11)


def _checker():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(ROOT, "scripts", "check_telemetry_schema.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_population_event_validates_in_both_modes():
    from commefficient_tpu.telemetry import validate_event

    streams = small_streams(n_rounds=5, seed=24)
    for led in (PopulationLedger(400), ParticipationLedger(400)):
        for rnd, (ids, w) in enumerate(streams, start=1):
            led.observe(rnd, ids, w)
        ev = {"event": "population", "t": 0.0, "seq": 0,
              **led.population_snapshot(len(streams))}
        assert validate_event(json.loads(json.dumps(ev))) == [], ev


def test_client_stats_estimated_is_v11_vintage_gated():
    from commefficient_tpu.telemetry import validate_event
    from commefficient_tpu.telemetry.schema import FIELDS_SINCE_V11

    assert FIELDS_SINCE_V11 == {"client_stats": ("estimated",)}
    chk = _checker()
    ev = json.loads([ln for ln in chk.sample_stream()
                     if '"event": "client_stats"' in ln][0])
    assert validate_event(dict(ev)) == []
    pre = dict(ev)
    del pre["estimated"]
    # a v10 stream legitimately lacks the flag; a v11 stream must not
    assert validate_event(dict(pre), version=10) == []
    assert any("estimated" in p for p in validate_event(dict(pre)))


def test_schema_selftest_covers_population():
    from commefficient_tpu.telemetry import validate_lines

    chk = _checker()
    lines = chk.sample_stream()
    assert validate_lines(lines) == []
    assert any('"event": "population"' in ln for ln in lines)


# ------------------------------------------------------ monitor rules


def _pop_fields(rnd, distinct, coverage=0.5, top=None):
    return {"round": rnd, "distinct": float(distinct),
            "coverage": coverage,
            "top_sampled": top if top is not None
            else [[1, 9.0], [2, 8.0], [3, 7.0]]}


def test_coverage_stall_rule_fires_after_window():
    from commefficient_tpu.telemetry.health import (AnomalyMonitor,
                                                    COVERAGE_STALL_WINDOW)

    mon = AnomalyMonitor(None, action="log")
    fired = mon.observe("population", _pop_fields(1, 100))
    for rnd in range(2, 2 + COVERAGE_STALL_WINDOW):
        assert not [a for a in fired if a["rule"] == "coverage_stall"]
        fired = mon.observe("population", _pop_fields(rnd, 100))
    stall = [a for a in fired if a["rule"] == "coverage_stall"]
    assert len(stall) == 1
    assert stall[0]["metric"] == "population.coverage_stall"
    assert stall[0]["window"] == COVERAGE_STALL_WINDOW


def test_coverage_stall_silent_at_saturation_or_growth():
    from commefficient_tpu.telemetry.health import AnomalyMonitor

    mon = AnomalyMonitor(None, action="log")
    for rnd in range(1, 30):  # saturated universe: flat is fine
        fired = mon.observe("population",
                            _pop_fields(rnd, 400, coverage=1.0))
        assert not [a for a in fired if a["rule"] == "coverage_stall"]
    mon2 = AnomalyMonitor(None, action="log")
    for rnd in range(1, 30):  # still discovering: never stalls
        fired = mon2.observe("population", _pop_fields(rnd, 100 + rnd))
        assert not [a for a in fired if a["rule"] == "coverage_stall"]


def test_coverage_stall_streak_survives_monitor_roundtrip():
    from commefficient_tpu.telemetry.health import (AnomalyMonitor,
                                                    COVERAGE_STALL_WINDOW)

    mon = AnomalyMonitor(None, action="log")
    for rnd in range(1, COVERAGE_STALL_WINDOW):  # streak = WINDOW - 2
        mon.observe("population", _pop_fields(rnd, 100))
    mon2 = AnomalyMonitor(None, action="log")
    mon2.load_state_dict(json.loads(json.dumps(mon.state_dict())))
    fired = mon2.observe("population",
                         _pop_fields(COVERAGE_STALL_WINDOW, 100))
    assert not [a for a in fired if a["rule"] == "coverage_stall"]
    fired = mon2.observe("population",
                         _pop_fields(COVERAGE_STALL_WINDOW + 1, 100))
    assert [a for a in fired if a["rule"] == "coverage_stall"], (
        "restored streak lost — a stall straddling a resume must still "
        "fire on schedule")


def test_hh_churn_rule_fires_on_turnover_burst():
    from commefficient_tpu.telemetry.health import AnomalyMonitor

    mon = AnomalyMonitor(None, action="log")
    stable = [[i, 10.0 - i] for i in range(5)]
    for rnd in range(1, 12):  # build a quiet turnover history
        fired = mon.observe("population",
                            _pop_fields(rnd, 100 + rnd, top=stable))
        assert not [a for a in fired if a["rule"] == "hh_churn"]
    burst = [[100 + i, 10.0 - i] for i in range(5)]
    fired = mon.observe("population",
                        _pop_fields(12, 112, top=burst))
    churn = [a for a in fired if a["rule"] == "hh_churn"]
    assert len(churn) == 1
    assert churn[0]["metric"] == "population.hh_turnover"
    assert churn[0]["value"] == pytest.approx(1.0)


# ------------------------------------------------------------ teleview


def _teleview():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "teleview", os.path.join(ROOT, "scripts", "teleview.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_teleview_fallback_literals_match_package():
    from commefficient_tpu.telemetry.health import COVERAGE_STALL_WINDOW

    src = open(os.path.join(ROOT, "scripts", "teleview.py")).read()
    block = re.search(r"POPULATION_KEYS = \((.*?)\)", src, re.S).group(1)
    assert tuple(re.findall(r'"([a-z_0-9]+)"', block)) == POPULATION_KEYS
    lit = re.search(r"COVERAGE_STALL_WINDOW = (\d+)", src).group(1)
    assert int(lit) == COVERAGE_STALL_WINDOW


def _write_population_stream(path, rounds, distinct_fn, registered=1000):
    with open(path, "w") as f:
        for rnd in range(rounds):
            d = float(distinct_fn(rnd))
            f.write(json.dumps({
                "event": "population", "round": rnd, "estimated": True,
                "registered": registered, "distinct": d,
                "coverage": d / registered, "counts_p50": 2.0,
                "counts_p95": 6.0, "counts_max": 11.0,
                "staleness_p50": 3.0, "staleness_p95": 9.0,
                "staleness_max": 20.0, "obs_count_p50": 8.0,
                "obs_count_p95": 12.0, "gap_p50": 4.0, "gap_p95": 10.0,
                "top_sampled": [[7, 9.0]], "top_loss": [[7, 3.0]],
                "top_strikes": [], "memory_bytes": 3468800.0,
                "cm_epsilon": 4.15e-05, "cm_delta": 0.0183,
                "hh_k": 256, "sample_size": 4096}) + "\n")


def test_teleview_population_view(tmp_path, capsys):
    p = str(tmp_path / "telemetry.jsonl")
    _write_population_stream(p, 10, lambda r: 100 + 10 * r)
    tv = _teleview()
    assert tv.main(["population", p]) == 0
    out = capsys.readouterr().out
    assert "SKETCH-ESTIMATED" in out
    assert "most-sampled clients: #7x9" in out
    assert "count-min bound" in out
    assert "COVERAGE STALL" not in out


def test_teleview_population_view_flags_terminal_stall(tmp_path, capsys):
    p = str(tmp_path / "telemetry.jsonl")
    _write_population_stream(p, 12, lambda r: min(100 + 10 * r, 120))
    tv = _teleview()
    assert tv.main(["population", p]) == 0
    assert "COVERAGE STALL" in capsys.readouterr().out


def test_teleview_diff_coverage_stall_gate(tmp_path, capsys):
    a = str(tmp_path / "a.jsonl")
    b = str(tmp_path / "b.jsonl")
    c = str(tmp_path / "c.jsonl")
    _write_population_stream(a, 10, lambda r: 100 + 10 * r)
    _write_population_stream(b, 10, lambda r: min(100 + 10 * r, 120))
    _write_population_stream(c, 10, lambda r: 100 + 9 * r)
    tv = _teleview()
    assert tv.main(["diff", a, b]) == 1
    out = capsys.readouterr().out
    assert "distinct-coverage stall" in out
    assert "final coverage" in out
    assert tv.main(["diff", a, c]) == 0  # within the 0.05 default


def test_teleview_trend_tolerates_every_vintage(tmp_path, capsys):
    # r01: pre-mfu vintage; r02: crashed bench (parsed null); r03: the
    # full shape with the nested gpt2 arm and a parseable warmup tail
    (tmp_path / "BENCH_r01.json").write_text(json.dumps({
        "n": 1, "rc": 0, "tail": "warmup done in 75.4s\nok",
        "parsed": {"metric": "m", "value": 9387.0, "unit": "images/sec",
                   "vs_baseline": 4.7}}))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps({
        "n": 2, "rc": 1, "tail": "Traceback ...", "parsed": None}))
    (tmp_path / "BENCH_r03.json").write_text(json.dumps({
        "n": 3, "rc": 0,
        "tail": "warmup done in 34.6s\nwarmup done in 106.2s",
        "parsed": {"metric": "m", "value": 17441.3, "unit": "images/sec",
                   "vs_baseline": 8.7, "mfu": 0.1748,
                   "gpt2": {"metric": "g", "value": 67326.4,
                            "unit": "tokens/sec", "vs_baseline": 15.0,
                            "mfu": 0.263, "tokens_per_round": 32768}}}))
    tv = _teleview()
    assert tv.main(["trend", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    lines = {ln.split()[0]: ln for ln in out.splitlines()
             if "BENCH_" in ln}
    assert "9387" in lines["BENCH_r01.json"]
    assert "rc=1" in lines["BENCH_r02.json"]
    assert "67326" in lines["BENCH_r03.json"]
    assert "106.2" in lines["BENCH_r03.json"]  # slowest warmup wins
    assert tv.main(["trend", str(tmp_path / "nothing_here")]) == 1


# ----------------------------------------------------- config + driver


def test_fedconfig_validates_population_sketch():
    from commefficient_tpu.config import FedConfig

    base = dict(mode="uncompressed", error_type="none",
                local_momentum=0.0, virtual_momentum=0.9,
                weight_decay=0.0, num_workers=2, local_batch_size=2,
                track_bytes=False, num_clients=2, num_results_train=2)
    assert FedConfig(**base).population_sketch == "auto"
    assert FedConfig(**base,
                     population_sketch="on").population_sketch == "on"
    with pytest.raises(ValueError, match="population_sketch"):
        FedConfig(**base, population_sketch="sometimes")
