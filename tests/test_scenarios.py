"""Straggler scenario engine (data/scenarios.py): fate determinism off
(seed, cohort_idx), latency distributions, dropout rates, participation
masking invariants, and the config factory's trivial-scenario elision."""

import numpy as np
import pytest

from commefficient_tpu.config import FedConfig
from commefficient_tpu.data.scenarios import (StragglerScenario,
                                              make_scenario)

MASK = np.ones((4, 3), bool)


def test_fate_deterministic_across_instances_and_order():
    """Same (seed, cohort_idx) -> identical fate, regardless of which
    instance produced it or in what order cohorts were asked about —
    the replay contract async resumes and prefetch interleavings need."""
    kw = dict(seed=7, latency=2.0, spread=0.5, dropout=0.2,
              participation=0.7)
    a = StragglerScenario("lognormal", **kw)
    b = StragglerScenario("lognormal", **kw)
    fates_fwd = [a.fate(i, MASK) for i in range(20)]
    fates_rev = [b.fate(i, MASK) for i in reversed(range(20))][::-1]
    for fa, fb in zip(fates_fwd, fates_rev):
        assert fa.latency == fb.latency
        assert fa.dropped == fb.dropped
        np.testing.assert_array_equal(fa.mask, fb.mask)


def test_different_seed_or_cohort_changes_fate():
    a = StragglerScenario("lognormal", seed=1, latency=2.0, spread=1.0)
    b = StragglerScenario("lognormal", seed=2, latency=2.0, spread=1.0)
    lat_a = [a.fate(i, MASK).latency for i in range(32)]
    lat_b = [b.fate(i, MASK).latency for i in range(32)]
    assert lat_a != lat_b
    assert len(set(lat_a)) > 1  # per-cohort variation, not a constant


def test_kind_none_zero_latency_but_dropout_applies():
    s = StragglerScenario("none", seed=3, dropout=0.5)
    fates = [s.fate(i, MASK) for i in range(200)]
    assert all(f.latency == 0.0 for f in fates)
    drop_rate = np.mean([f.dropped for f in fates])
    assert 0.3 < drop_rate < 0.7


def test_uniform_latency_bounds():
    s = StragglerScenario("uniform", seed=0, latency=3.0, spread=1.0)
    lats = [s.fate(i, MASK).latency for i in range(100)]
    assert all(2.0 <= lt <= 4.0 for lt in lats)
    # spread wider than the mean clamps at zero, never negative
    s2 = StragglerScenario("uniform", seed=0, latency=0.5, spread=2.0)
    assert all(s2.fate(i, MASK).latency >= 0.0 for i in range(100))


def test_straggler_mixture_two_point():
    s = StragglerScenario("stragglers", seed=5, latency=1.0,
                          straggler_frac=0.25, straggler_mult=10.0)
    lats = np.asarray([s.fate(i, MASK).latency for i in range(400)])
    assert set(np.unique(lats)) == {1.0, 10.0}
    frac = (lats == 10.0).mean()
    assert 0.15 < frac < 0.35


def test_participation_masks_slots_never_adds_keeps_one():
    s = StragglerScenario("none", seed=9, participation=0.5)
    base = np.ones((6, 4), bool)
    base[5, 1:] = False  # an already-partial slot stays partial
    saw_reduction = False
    for i in range(50):
        f = s.fate(i, base)
        # only ever REMOVES: mask & keep
        assert not (f.mask & ~base).any()
        # at least one slot still participates
        assert f.mask.any()
        if f.mask.sum() < base.sum():
            saw_reduction = True
    assert saw_reduction
    # participation=1.0 leaves the mask untouched (same object semantics)
    s_full = StragglerScenario("none", seed=9, participation=1.0)
    np.testing.assert_array_equal(s_full.fate(0, base).mask, base)


def test_invalid_parameters_raise():
    with pytest.raises(ValueError, match="unknown scenario kind"):
        StragglerScenario("gaussian")
    with pytest.raises(ValueError):
        StragglerScenario("none", dropout=1.0)
    with pytest.raises(ValueError):
        StragglerScenario("none", participation=0.0)
    with pytest.raises(ValueError):
        StragglerScenario("uniform", latency=-1.0)


def test_constructor_numerics_validated_exhaustively():
    """The PR-7 satellite: every numeric field refuses its degenerate
    range with a clear message — bad values must never silently produce
    degenerate fates."""
    with pytest.raises(ValueError, match="latency/spread"):
        StragglerScenario("uniform", spread=-0.5)
    with pytest.raises(ValueError, match="dropout"):
        StragglerScenario("none", dropout=-0.1)
    with pytest.raises(ValueError, match="participation"):
        StragglerScenario("none", participation=1.5)
    with pytest.raises(ValueError, match="participation"):
        StragglerScenario("none", participation=-0.2)
    with pytest.raises(ValueError, match="straggler_frac"):
        StragglerScenario("stragglers", straggler_frac=1.2)
    with pytest.raises(ValueError, match="straggler_frac"):
        StragglerScenario("stragglers", straggler_frac=-0.1)
    # straggler_mult < 1 makes the "stragglers" FASTER than the rest —
    # a silently-inverted two-point mixture (the named regression)
    with pytest.raises(ValueError, match="straggler_mult"):
        StragglerScenario("stragglers", straggler_mult=0.5)
    with pytest.raises(ValueError, match="straggler_mult"):
        StragglerScenario("none", straggler_mult=0.0)
    # the boundary values remain legal
    StragglerScenario("stragglers", straggler_mult=1.0,
                      straggler_frac=0.0, dropout=0.0, participation=1.0,
                      latency=0.0, spread=0.0)


def test_make_scenario_elides_trivial_and_builds_configured():
    cfg = FedConfig(async_agg=True)
    assert make_scenario(cfg) is None
    cfg2 = FedConfig(async_agg=True, scenario="stragglers",
                     scenario_latency=2.0, scenario_dropout=0.1)
    s = make_scenario(cfg2)
    assert isinstance(s, StragglerScenario)
    assert s.kind == "stragglers" and s.latency == 2.0
    assert s.seed == cfg2.seed
    # dropout alone (kind none) is NOT trivial
    assert make_scenario(FedConfig(async_agg=True,
                                   scenario_dropout=0.1)) is not None


def test_scenario_without_async_agg_fails_fast():
    """A scenario the lockstep loop would silently ignore must refuse
    at config time (the repo's silently-ignored-flag contract)."""
    with pytest.raises(ValueError, match="require --async_agg"):
        FedConfig(scenario="stragglers")
    with pytest.raises(ValueError, match="require --async_agg"):
        FedConfig(scenario_dropout=0.2)
    with pytest.raises(ValueError, match="require --async_agg"):
        FedConfig(scenario_participation=0.5)
