"""Device-resident data store (data/device_store.py): eval-path numeric
equality with the host transforms, train-path shape/range sanity, iid
routing, and the host-fallback gating."""

import jax
import jax.numpy as jnp
import numpy as np

from commefficient_tpu.data import transforms as T
from commefficient_tpu.data.device_store import DeviceStore, make_device_store


def _fake_cifar(n=40):
    rng = np.random.RandomState(0)
    return {"image": rng.randint(0, 255, (n, 32, 32, 3), dtype=np.uint8),
            "target": rng.randint(0, 10, n).astype(np.int64)}


def test_eval_path_matches_host_normalize():
    arrays = _fake_cifar()
    store = DeviceStore(arrays, augment="normalize",
                        mean=T.CIFAR10_MEAN, std=T.CIFAR10_STD)
    idx = np.array([3, 7, 1])
    got = store.round_batch(idx, None)
    host = T.CifarEval()( {k: v[idx] for k, v in arrays.items()} )
    np.testing.assert_allclose(np.asarray(got["image"]), host["image"],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(got["target"]),
                                  arrays["target"][idx])


def test_train_augment_shape_and_stats():
    arrays = _fake_cifar()
    store = DeviceStore(arrays, augment="cifar_train",
                        mean=T.CIFAR10_MEAN, std=T.CIFAR10_STD)
    idx = np.arange(16).reshape(2, 8)   # (W, B) round shape
    out = store.round_batch(idx, jax.random.PRNGKey(0))
    assert out["image"].shape == (2, 8, 32, 32, 3)
    assert out["image"].dtype == jnp.float32
    # normalized data: roughly centered
    assert abs(float(out["image"].mean())) < 2.0
    # different rng keys give different crops/flips
    out2 = store.round_batch(idx, jax.random.PRNGKey(1))
    assert float(jnp.abs(out["image"] - out2["image"]).max()) > 0


def test_iid_shuffle_applied_on_device():
    arrays = _fake_cifar()
    perm = np.random.RandomState(1).permutation(40)
    store = DeviceStore(arrays, iid_shuffle=perm)
    idx = np.array([0, 5])
    got = store.round_batch(idx, None)
    np.testing.assert_array_equal(np.asarray(got["target"]),
                                  arrays["target"][perm[idx]])


def test_factory_gating(tmp_path):
    from commefficient_tpu.data.fed_cifar import FedCIFAR10

    ds = FedCIFAR10(str(tmp_path), train=True, synthetic=True)
    assert make_device_store(ds, "CIFAR10", train=True) is not None
    # EMNIST train augmentation has no device equivalent => host fallback
    assert make_device_store(ds, "EMNIST", train=True) is None
    # unknown dataset => host fallback
    assert make_device_store(ds, "NOPE", train=True) is None
    # too big => host fallback
    assert make_device_store(ds, "CIFAR10", train=True, max_bytes=10) is None
