"""Device-resident data store (data/device_store.py): eval-path numeric
equality with the host transforms, train-path shape/range sanity, iid
routing, and the host-fallback gating."""

import jax
import jax.numpy as jnp
import numpy as np

from commefficient_tpu.data import transforms as T
from commefficient_tpu.data.device_store import DeviceStore, make_device_store


def _fake_cifar(n=40):
    rng = np.random.RandomState(0)
    return {"image": rng.randint(0, 255, (n, 32, 32, 3), dtype=np.uint8),
            "target": rng.randint(0, 10, n).astype(np.int64)}


def test_eval_path_matches_host_normalize():
    arrays = _fake_cifar()
    store = DeviceStore(arrays, augment="normalize",
                        mean=T.CIFAR10_MEAN, std=T.CIFAR10_STD)
    idx = np.array([3, 7, 1])
    got = store.round_batch(idx, None)
    host = T.CifarEval()( {k: v[idx] for k, v in arrays.items()} )
    np.testing.assert_allclose(np.asarray(got["image"]), host["image"],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(got["target"]),
                                  arrays["target"][idx])


def test_train_augment_shape_and_stats():
    arrays = _fake_cifar()
    store = DeviceStore(arrays, augment="cifar_train",
                        mean=T.CIFAR10_MEAN, std=T.CIFAR10_STD)
    idx = np.arange(16).reshape(2, 8)   # (W, B) round shape
    out = store.round_batch(idx, jax.random.PRNGKey(0))
    assert out["image"].shape == (2, 8, 32, 32, 3)
    assert out["image"].dtype == jnp.float32
    # normalized data: roughly centered
    assert abs(float(out["image"].mean())) < 2.0
    # different rng keys give different crops/flips
    out2 = store.round_batch(idx, jax.random.PRNGKey(1))
    assert float(jnp.abs(out["image"] - out2["image"]).max()) > 0


def test_iid_shuffle_applied_on_device():
    arrays = _fake_cifar()
    perm = np.random.RandomState(1).permutation(40)
    store = DeviceStore(arrays, iid_shuffle=perm)
    idx = np.array([0, 5])
    got = store.round_batch(idx, None)
    np.testing.assert_array_equal(np.asarray(got["target"]),
                                  arrays["target"][perm[idx]])


def test_factory_gating(tmp_path):
    from commefficient_tpu.data.fed_cifar import FedCIFAR10

    ds = FedCIFAR10(str(tmp_path), train=True, synthetic=True)
    assert make_device_store(ds, "CIFAR10", train=True) is not None
    # ImageNet train now has a device equivalent (flip + normalize on
    # pre-sized crops — the PR-5 uint8 input fix); the resident array
    # must stay raw uint8
    st = make_device_store(ds, "ImageNet", train=True)
    assert st is not None and st.augment == "imagenet_train"
    assert str(st.arrays["image"].dtype) == "uint8"
    # unknown dataset => host fallback
    assert make_device_store(ds, "NOPE", train=True) is None
    # too big => host fallback
    assert make_device_store(ds, "CIFAR10", train=True, max_bytes=10) is None
    # hard synthetic regime: train batches must be normalize-only (crop/
    # flip scrambles the per-pixel class evidence — cv_train passes
    # no_augment=cfg.synthetic_hard)
    st = make_device_store(ds, "CIFAR10", train=True, no_augment=True)
    assert st is not None and st.augment == "normalize"


def test_mesh_store_shards_round_batches():
    """On a mesh, train batches come out sharded over the round's client
    axis with values identical to the single-device store, and eval stores
    emit replicated (VERDICT r1 weak #3: no more host-streaming fallback on
    the mesh path)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from commefficient_tpu.parallel import make_mesh

    mesh = make_mesh((8,), ("clients",))
    arrays = _fake_cifar(64)
    idx = np.arange(32).reshape(8, 4)          # (W=8, B=4) round shape
    single = DeviceStore(arrays, augment="normalize",
                         mean=T.CIFAR10_MEAN, std=T.CIFAR10_STD)
    sharded = DeviceStore(arrays, augment="normalize",
                          mean=T.CIFAR10_MEAN, std=T.CIFAR10_STD,
                          mesh=mesh, shard_axis="clients")
    got = sharded.round_batch(idx, None)
    assert got["image"].sharding.is_equivalent_to(
        NamedSharding(mesh, P("clients")), got["image"].ndim)
    np.testing.assert_allclose(
        np.asarray(got["image"]),
        np.asarray(single.round_batch(idx, None)["image"]),
        rtol=1e-6)
    # val flavor: replicated output
    val = DeviceStore(arrays, augment="normalize", mean=T.CIFAR10_MEAN,
                      std=T.CIFAR10_STD, mesh=mesh)
    out = val.round_batch(np.array([1, 2, 3]), None)
    assert out["image"].sharding.is_equivalent_to(
        NamedSharding(mesh, P()), out["image"].ndim)


def test_mesh_train_loop_uses_store(tmp_path):
    """cv_train.train on a mesh keeps the device-resident path and the
    sharded round executes end to end."""
    import jax.numpy as jnp

    from commefficient_tpu import models
    from commefficient_tpu.config import FedConfig
    from commefficient_tpu.core import FedRuntime
    from commefficient_tpu.data import FedCIFAR10, transforms_for
    from commefficient_tpu.data.device_store import make_device_store
    from commefficient_tpu.losses import make_cv_loss
    from commefficient_tpu.cv_train import train
    from commefficient_tpu.parallel import make_mesh

    mesh = make_mesh((8,), ("clients",))
    ds = FedCIFAR10(str(tmp_path / "d"), synthetic=True,
                    synthetic_per_class=8,
                    transform=transforms_for("CIFAR10", True, seed=0))
    assert make_device_store(ds, "CIFAR10", True, mesh=mesh) is not None
    cfg = FedConfig(mode="uncompressed", error_type="none",
                    local_momentum=0.0, virtual_momentum=0.9,
                    num_workers=8, local_batch_size=4,
                    num_clients=ds.num_clients, num_epochs=1.0,
                    track_bytes=False, compute_dtype="float32")
    model = models.ResNet9(num_classes=10,
                           channels={"prep": 2, "layer1": 2,
                                     "layer2": 2, "layer3": 2})
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 32, 32, 3)))
    rt = FedRuntime(cfg, params, make_cv_loss(model, "float32"),
                    num_clients=ds.num_clients, mesh=mesh)
    state, summary = train(cfg, rt, rt.init_state(), ds, ds)
    assert summary is not None and np.isfinite(summary["train_loss"])


def _fake_imagenet(n=6, hw=224):
    rng = np.random.RandomState(3)
    return {"image": rng.randint(0, 255, (n, hw, hw, 3), dtype=np.uint8),
            "target": rng.randint(0, 8, n).astype(np.int64)}


def test_imagenet_uint8_matches_float_path():
    """The uint8 ImageNet store (raw bytes resident, /255 + flip +
    normalize fused on device) matches a float-resident store numerically
    under the same rng key — the uint8 residency changes the storage and
    the transfer, never the values."""
    arrays = _fake_imagenet()
    u8 = DeviceStore(arrays, augment="imagenet_train",
                     mean=T.IMAGENET_MEAN, std=T.IMAGENET_STD)
    fl = DeviceStore({"image": arrays["image"].astype(np.float32) / 255.0,
                      "target": arrays["target"]},
                     augment="imagenet_train",
                     mean=T.IMAGENET_MEAN, std=T.IMAGENET_STD)
    assert str(u8.arrays["image"].dtype) == "uint8"
    # uint8 image residency is 4x smaller than float32
    assert u8.arrays["image"].nbytes * 4 == fl.arrays["image"].nbytes
    idx = np.arange(4).reshape(2, 2)            # (W, B) round shape
    a = u8.round_batch(idx, jax.random.PRNGKey(0))
    b = fl.round_batch(idx, jax.random.PRNGKey(0))
    assert a["image"].shape == (2, 2, 224, 224, 3)
    assert a["image"].dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(a["image"]),
                               np.asarray(b["image"]),
                               rtol=1e-5, atol=1e-5)


def test_imagenet_train_flip_semantics_and_eval_equality():
    """Each 224^2 train output equals the host normalize of the image or
    of its horizontal mirror (the ImagenetTrain augmentation family);
    different keys flip differently; the eval store equals the host
    ImagenetEval exactly."""
    arrays = _fake_imagenet()
    st = DeviceStore(arrays, augment="imagenet_train",
                     mean=T.IMAGENET_MEAN, std=T.IMAGENET_STD)
    idx = np.arange(4)
    got = np.asarray(st.round_batch(idx, jax.random.PRNGKey(0))["image"])
    host = T.ImagenetEval()({"image": arrays["image"][idx]})["image"]
    for i in range(4):
        plain = np.allclose(got[i], host[i], atol=1e-4)
        mirror = np.allclose(got[i], host[i][:, ::-1], atol=1e-4)
        assert plain or mirror, i
    got2 = np.asarray(st.round_batch(idx, jax.random.PRNGKey(3))["image"])
    assert float(np.abs(got - got2).max()) > 0    # keys flip differently
    ev = DeviceStore(arrays, augment="normalize",
                     mean=T.IMAGENET_MEAN, std=T.IMAGENET_STD)
    np.testing.assert_allclose(
        np.asarray(ev.round_batch(idx, None)["image"]), host,
        rtol=1e-5, atol=1e-6)


def test_imagenet_factory_and_no_augment():
    """make_device_store wires ImageNet train to the device path (uint8
    resident) and still honors no_augment -> normalize-only."""
    arrays = _fake_imagenet(n=4, hw=32)         # small: gating only

    class FakeDs:
        def __init__(self):
            self.arrays = arrays
            self.do_iid = False

    st = make_device_store(FakeDs(), "ImageNet", train=True)
    assert st is not None and st.augment == "imagenet_train"
    st2 = make_device_store(FakeDs(), "ImageNet", train=True,
                            no_augment=True)
    assert st2 is not None and st2.augment == "normalize"
    ev = make_device_store(FakeDs(), "ImageNet", train=False)
    assert ev is not None and ev.augment == "normalize"


def test_emnist_train_augment_on_device():
    """FEMNIST train path no longer falls back to the host pipeline: the
    edge-pad-2 shift crop (no flip) runs on device, eval path equals the
    host normalize."""
    rng = np.random.RandomState(2)
    arrays = {"image": rng.randint(0, 255, (30, 28, 28, 1), dtype=np.uint8),
              "target": rng.randint(0, 62, 30).astype(np.int64)}

    class FakeDs:
        def __init__(self):
            self.arrays = arrays
            self.do_iid = False

    store = make_device_store(FakeDs(), "EMNIST", train=True)
    assert store is not None                 # was a host fallback before
    out = store.round_batch(np.arange(8), jax.random.PRNGKey(0))
    assert out["image"].shape == (8, 28, 28, 1)
    assert out["image"].dtype == jnp.float32
    # crops differ across keys; values stay in the normalized range
    out2 = store.round_batch(np.arange(8), jax.random.PRNGKey(1))
    assert float(jnp.abs(out["image"] - out2["image"]).max()) > 0
    # eval store still equals the host normalize exactly
    ev = make_device_store(FakeDs(), "EMNIST", train=False)
    got = np.asarray(ev.round_batch(np.array([0, 3]), None)["image"])
    host = T.FemnistEval()({k: v[[0, 3]] for k, v in arrays.items()})
    np.testing.assert_allclose(got, host["image"], rtol=1e-5, atol=1e-6)
