"""Device-resident data store (data/device_store.py): eval-path numeric
equality with the host transforms, train-path shape/range sanity, iid
routing, and the host-fallback gating."""

import jax
import jax.numpy as jnp
import numpy as np

from commefficient_tpu.data import transforms as T
from commefficient_tpu.data.device_store import DeviceStore, make_device_store


def _fake_cifar(n=40):
    rng = np.random.RandomState(0)
    return {"image": rng.randint(0, 255, (n, 32, 32, 3), dtype=np.uint8),
            "target": rng.randint(0, 10, n).astype(np.int64)}


def test_eval_path_matches_host_normalize():
    arrays = _fake_cifar()
    store = DeviceStore(arrays, augment="normalize",
                        mean=T.CIFAR10_MEAN, std=T.CIFAR10_STD)
    idx = np.array([3, 7, 1])
    got = store.round_batch(idx, None)
    host = T.CifarEval()( {k: v[idx] for k, v in arrays.items()} )
    np.testing.assert_allclose(np.asarray(got["image"]), host["image"],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(got["target"]),
                                  arrays["target"][idx])


def test_train_augment_shape_and_stats():
    arrays = _fake_cifar()
    store = DeviceStore(arrays, augment="cifar_train",
                        mean=T.CIFAR10_MEAN, std=T.CIFAR10_STD)
    idx = np.arange(16).reshape(2, 8)   # (W, B) round shape
    out = store.round_batch(idx, jax.random.PRNGKey(0))
    assert out["image"].shape == (2, 8, 32, 32, 3)
    assert out["image"].dtype == jnp.float32
    # normalized data: roughly centered
    assert abs(float(out["image"].mean())) < 2.0
    # different rng keys give different crops/flips
    out2 = store.round_batch(idx, jax.random.PRNGKey(1))
    assert float(jnp.abs(out["image"] - out2["image"]).max()) > 0


def test_iid_shuffle_applied_on_device():
    arrays = _fake_cifar()
    perm = np.random.RandomState(1).permutation(40)
    store = DeviceStore(arrays, iid_shuffle=perm)
    idx = np.array([0, 5])
    got = store.round_batch(idx, None)
    np.testing.assert_array_equal(np.asarray(got["target"]),
                                  arrays["target"][perm[idx]])


def test_factory_gating(tmp_path):
    from commefficient_tpu.data.fed_cifar import FedCIFAR10

    ds = FedCIFAR10(str(tmp_path), train=True, synthetic=True)
    assert make_device_store(ds, "CIFAR10", train=True) is not None
    # ImageNet train augmentation has no device equivalent => host fallback
    assert make_device_store(ds, "ImageNet", train=True) is None
    # unknown dataset => host fallback
    assert make_device_store(ds, "NOPE", train=True) is None
    # too big => host fallback
    assert make_device_store(ds, "CIFAR10", train=True, max_bytes=10) is None
    # hard synthetic regime: train batches must be normalize-only (crop/
    # flip scrambles the per-pixel class evidence — cv_train passes
    # no_augment=cfg.synthetic_hard)
    st = make_device_store(ds, "CIFAR10", train=True, no_augment=True)
    assert st is not None and st.augment == "normalize"


def test_mesh_store_shards_round_batches():
    """On a mesh, train batches come out sharded over the round's client
    axis with values identical to the single-device store, and eval stores
    emit replicated (VERDICT r1 weak #3: no more host-streaming fallback on
    the mesh path)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from commefficient_tpu.parallel import make_mesh

    mesh = make_mesh((8,), ("clients",))
    arrays = _fake_cifar(64)
    idx = np.arange(32).reshape(8, 4)          # (W=8, B=4) round shape
    single = DeviceStore(arrays, augment="normalize",
                         mean=T.CIFAR10_MEAN, std=T.CIFAR10_STD)
    sharded = DeviceStore(arrays, augment="normalize",
                          mean=T.CIFAR10_MEAN, std=T.CIFAR10_STD,
                          mesh=mesh, shard_axis="clients")
    got = sharded.round_batch(idx, None)
    assert got["image"].sharding.is_equivalent_to(
        NamedSharding(mesh, P("clients")), got["image"].ndim)
    np.testing.assert_allclose(
        np.asarray(got["image"]),
        np.asarray(single.round_batch(idx, None)["image"]),
        rtol=1e-6)
    # val flavor: replicated output
    val = DeviceStore(arrays, augment="normalize", mean=T.CIFAR10_MEAN,
                      std=T.CIFAR10_STD, mesh=mesh)
    out = val.round_batch(np.array([1, 2, 3]), None)
    assert out["image"].sharding.is_equivalent_to(
        NamedSharding(mesh, P()), out["image"].ndim)


def test_mesh_train_loop_uses_store(tmp_path):
    """cv_train.train on a mesh keeps the device-resident path and the
    sharded round executes end to end."""
    import jax.numpy as jnp

    from commefficient_tpu import models
    from commefficient_tpu.config import FedConfig
    from commefficient_tpu.core import FedRuntime
    from commefficient_tpu.data import FedCIFAR10, transforms_for
    from commefficient_tpu.data.device_store import make_device_store
    from commefficient_tpu.losses import make_cv_loss
    from commefficient_tpu.cv_train import train
    from commefficient_tpu.parallel import make_mesh

    mesh = make_mesh((8,), ("clients",))
    ds = FedCIFAR10(str(tmp_path / "d"), synthetic=True,
                    synthetic_per_class=8,
                    transform=transforms_for("CIFAR10", True, seed=0))
    assert make_device_store(ds, "CIFAR10", True, mesh=mesh) is not None
    cfg = FedConfig(mode="uncompressed", error_type="none",
                    local_momentum=0.0, virtual_momentum=0.9,
                    num_workers=8, local_batch_size=4,
                    num_clients=ds.num_clients, num_epochs=1.0,
                    track_bytes=False, compute_dtype="float32")
    model = models.ResNet9(num_classes=10,
                           channels={"prep": 2, "layer1": 2,
                                     "layer2": 2, "layer3": 2})
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 32, 32, 3)))
    rt = FedRuntime(cfg, params, make_cv_loss(model, "float32"),
                    num_clients=ds.num_clients, mesh=mesh)
    state, summary = train(cfg, rt, rt.init_state(), ds, ds)
    assert summary is not None and np.isfinite(summary["train_loss"])


def test_emnist_train_augment_on_device():
    """FEMNIST train path no longer falls back to the host pipeline: the
    edge-pad-2 shift crop (no flip) runs on device, eval path equals the
    host normalize."""
    rng = np.random.RandomState(2)
    arrays = {"image": rng.randint(0, 255, (30, 28, 28, 1), dtype=np.uint8),
              "target": rng.randint(0, 62, 30).astype(np.int64)}

    class FakeDs:
        def __init__(self):
            self.arrays = arrays
            self.do_iid = False

    store = make_device_store(FakeDs(), "EMNIST", train=True)
    assert store is not None                 # was a host fallback before
    out = store.round_batch(np.arange(8), jax.random.PRNGKey(0))
    assert out["image"].shape == (8, 28, 28, 1)
    assert out["image"].dtype == jnp.float32
    # crops differ across keys; values stay in the normalized range
    out2 = store.round_batch(np.arange(8), jax.random.PRNGKey(1))
    assert float(jnp.abs(out["image"] - out2["image"]).max()) > 0
    # eval store still equals the host normalize exactly
    ev = make_device_store(FakeDs(), "EMNIST", train=False)
    got = np.asarray(ev.round_batch(np.array([0, 3]), None)["image"])
    host = T.FemnistEval()({k: v[[0, 3]] for k, v in arrays.items()})
    np.testing.assert_allclose(got, host["image"], rtol=1e-5, atol=1e-6)
