"""Core round-step tests: golden SGD trajectories and mode equivalences.

Method ported from the reference's (broken) unit_test.py (SURVEY.md §4):
compare against closed-form/numpy SGD trajectories, and exploit the lossless
limits — top-k with k=d and a huge sketch must reproduce uncompressed SGD
exactly (to float tolerance).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.config import FedConfig
from commefficient_tpu.core import FedRuntime

D_FEAT = 6
NUM_CLIENTS = 10
W = 4          # clients per round
B = 8          # local batch size


def loss_fn(params, batch, mask):
    """Masked linear-regression MSE with mean-abs-error metric."""
    x, y = batch["x"], batch["y"]
    pred = x @ params["w"] + params["b"]
    mask = mask.astype(jnp.float32)
    denom = jnp.maximum(mask.sum(), 1.0)
    err = pred - y
    loss = ((err ** 2) * mask).sum() / denom
    mae = (jnp.abs(err) * mask).sum() / denom
    return loss, (mae,)


def init_params(seed=0):
    rng = np.random.RandomState(seed)
    return {"w": jnp.asarray(rng.randn(D_FEAT).astype(np.float32)),
            "b": jnp.zeros(())}


def make_data(seed=1):
    rng = np.random.RandomState(seed)
    w_true = rng.randn(D_FEAT).astype(np.float32)
    xs = rng.randn(NUM_CLIENTS, B, D_FEAT).astype(np.float32)
    ys = xs @ w_true + 0.01 * rng.randn(NUM_CLIENTS, B).astype(np.float32)
    return xs, ys


def base_cfg(**kw):
    defaults = dict(mode="uncompressed", local_momentum=0.0,
                    virtual_momentum=0.0, weight_decay=0.0,
                    error_type="none", local_batch_size=B,
                    num_workers=W, num_clients=NUM_CLIENTS,
                    num_results_train=2, track_bytes=True)
    defaults.update(kw)
    return FedConfig(**defaults)


def run_rounds(cfg, n_rounds, lr=0.05, seed=3):
    params = init_params()
    xs, ys = make_data()
    rt = FedRuntime(cfg, params, loss_fn, num_clients=NUM_CLIENTS)
    state = rt.init_state()
    rng = np.random.RandomState(seed)
    traj, metrics_hist = [], []
    for _ in range(n_rounds):
        ids = rng.choice(NUM_CLIENTS, W, replace=False).astype(np.int32)
        batch = {"x": jnp.asarray(xs[ids]), "y": jnp.asarray(ys[ids])}
        mask = jnp.ones((W, B))
        state, m = rt.round(state, ids, batch, mask, lr)
        traj.append(np.asarray(state.ps_weights))
        metrics_hist.append(jax.tree.map(np.asarray, m))
    return rt, state, traj, metrics_hist


def numpy_sgd(n_rounds, lr=0.05, seed=3, rho=0.0):
    """Host-side replica of uncompressed federated SGD with virtual momentum
    (reference _server_helper_uncompressed, fed_aggregator.py:497-509)."""
    p = init_params()
    w = np.concatenate([np.asarray(p["b"]).reshape(1), np.asarray(p["w"])])
    # note: ravel_pytree orders dict keys alphabetically: b then w
    xs, ys = make_data()
    rng = np.random.RandomState(seed)
    vel = np.zeros_like(w)
    traj = []
    for _ in range(n_rounds):
        ids = rng.choice(NUM_CLIENTS, W, replace=False)
        x = xs[ids].reshape(-1, D_FEAT)
        y = ys[ids].reshape(-1)
        pred = x @ w[1:] + w[0]
        err = pred - y
        gw = 2 * (x * err[:, None]).mean(0)
        gb = 2 * err.mean()
        g = np.concatenate([[gb], gw])
        vel = g + rho * vel
        w = w - lr * vel
        traj.append(w.copy())
    return traj


class TestGoldenTrajectories:
    def test_uncompressed_matches_numpy(self):
        _, _, traj, _ = run_rounds(base_cfg(), 5)
        expected = numpy_sgd(5)
        for got, want in zip(traj, expected):
            np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-5)

    def test_virtual_momentum_matches_numpy(self):
        _, _, traj, _ = run_rounds(base_cfg(virtual_momentum=0.9), 5)
        expected = numpy_sgd(5, rho=0.9)
        for got, want in zip(traj, expected):
            np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-5)

    def test_true_topk_lossless_matches_uncompressed(self):
        d = D_FEAT + 1
        _, _, traj_t, _ = run_rounds(
            base_cfg(mode="true_topk", error_type="virtual", k=d), 5)
        _, _, traj_u, _ = run_rounds(base_cfg(), 5)
        for got, want in zip(traj_t, traj_u):
            np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)

    def test_local_topk_lossless_matches_uncompressed(self):
        d = D_FEAT + 1
        _, _, traj_t, _ = run_rounds(
            base_cfg(mode="local_topk", error_type="none", k=d), 5)
        _, _, traj_u, _ = run_rounds(base_cfg(), 5)
        for got, want in zip(traj_t, traj_u):
            np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)

    @pytest.mark.parametrize("impl,server_state", [
        ("hash", "table"), ("rht", "table"),
        ("hash", "dense"), ("circ", "dense")])
    def test_sketch_lossless_matches_true_topk(self, impl, server_state):
        """Huge table => estimates are near-exact => FetchSGD reduces to
        true top-k (SURVEY.md §4 golden strategy). For the rht impl the
        lossless limit is exact by construction (c == padded size), which
        certifies the dense-preimage support-zeroing rule coincides with
        the reference's cell-masking there (core/server.py); the
        sketch_server_state=dense cases certify the same for the circ/hash
        opt-in pre-image path."""
        d = D_FEAT + 1
        cfg_s = base_cfg(mode="sketch", error_type="virtual", k=d,
                         num_rows=7, num_cols=4096, num_blocks=1,
                         sketch_impl=impl, sketch_server_state=server_state)
        _, _, traj_s, _ = run_rounds(cfg_s, 5)
        _, _, traj_u, _ = run_rounds(base_cfg(), 5)
        for got, want in zip(traj_s, traj_u):
            np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)

    def test_fedavg_single_step_matches_sgd(self):
        """One local epoch, whole-client batch => fedavg transmit is exactly
        lr * mean-grad, so the server step equals plain SGD."""
        cfg = FedConfig(mode="fedavg", local_momentum=0.0,
                        virtual_momentum=0.0, weight_decay=0.0,
                        error_type="none", local_batch_size=-1,
                        max_client_batch=B, fedavg_batch_size=-1,
                        num_fedavg_epochs=1, num_workers=W,
                        num_clients=NUM_CLIENTS, num_results_train=2)
        _, _, traj_f, _ = run_rounds(cfg, 3)
        expected = numpy_sgd(3)
        for got, want in zip(traj_f, expected):
            np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-5)


def test_local_topk_matches_reference_sim():
    """scripts/local_topk_sim.py --check: our local_topk trajectory must be
    identical to a straight numpy transcription of the reference's
    fed_worker.py:184-230 + fed_aggregator.py:544-566 dynamics (VERDICT r4
    missing #2 — proves measured local_topk behavior is the algorithm's,
    not a port artifact)."""
    import os
    import subprocess
    import sys
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(root, "scripts", "local_topk_sim.py"),
         "--check"], capture_output=True, text=True, cwd=root, timeout=300)
    assert "OK: framework local_topk == reference dynamics" in out.stdout, \
        out.stdout + out.stderr


class TestAutoNumCols:
    """VERDICT r4 weak #1: default circulant geometry must hit the Pallas
    fast path; the rounding is pinned here."""

    def test_rounding_values(self):
        from commefficient_tpu.config import auto_num_cols
        assert auto_num_cols(500_000) == 500_736      # reference default
        assert auto_num_cols(524_288) == 524_288      # already aligned
        assert auto_num_cols(500_736) == 500_736
        # tiny test geometries must NOT be inflated (budget bound 5%)
        assert auto_num_cols(320) == 320
        assert auto_num_cols(256) == 256
        assert auto_num_cols(100_000) == 100_352      # +0.35%

    def test_runtime_applies_and_pins(self):
        params = init_params()
        cfg = base_cfg(mode="sketch", error_type="virtual", k=4,
                       num_rows=3, num_cols=100_000, num_blocks=1,
                       sketch_impl="circ")
        rt = FedRuntime(cfg, params, loss_fn, num_clients=NUM_CLIENTS)
        assert rt.cfg.num_cols == 100_352
        assert rt.cfg.num_cols % 1024 == 0
        # byte accounting must reflect the real table
        assert rt.cfg.upload_floats == 3 * 100_352
        rt2 = FedRuntime(cfg.replace(exact_num_cols=True), params, loss_fn,
                         num_clients=NUM_CLIENTS)
        assert rt2.cfg.num_cols == 100_000


class TestSketchEFVariants:
    """The TPU-native error-feedback extensions (config.py sketch_ef /
    error_decay) against the reference zero rule."""

    @pytest.mark.parametrize("impl", ["hash", "circ"])
    def test_subtract_ef_lossless_matches_zero(self, impl):
        """In the lossless limit (no cell collisions for circ; c >> d for
        hash) 'subtract the extracted estimates' and 'zero the occupied
        cells' are the same rule, so the trajectories must coincide."""
        d = D_FEAT + 1
        common = dict(mode="sketch", error_type="virtual", k=d,
                      num_rows=7, num_cols=4096, num_blocks=1,
                      sketch_impl=impl)
        _, _, traj_z, _ = run_rounds(base_cfg(**common), 5)
        _, _, traj_s, _ = run_rounds(
            base_cfg(**common, sketch_ef="subtract"), 5)
        tol = 0 if impl == "circ" else 1e-3
        for got, want in zip(traj_s, traj_z):
            np.testing.assert_allclose(got, want, rtol=1e-4, atol=max(tol, 1e-6))

    def test_subtract_ef_preserves_colliding_error(self):
        """The point of the subtract rule: a coordinate whose cell collides
        with the update's keeps its accumulated error (the zero rule
        destroys it). Direct server_update check on a 1-block circulant
        sketch where collisions are by construction (c < d)."""
        from commefficient_tpu.core.server import server_update
        from commefficient_tpu.ops.circulant import make_circulant_sketch
        d, c, r, k = 64, 16, 3, 1
        cs = make_circulant_sketch(d=d, c=c, r=r, num_blocks=1, seed=3)
        rng = np.random.RandomState(0)
        g = jnp.asarray(0.01 * rng.randn(d).astype(np.float32))
        g = g.at[5].set(10.0)  # one dominant coordinate wins the top-1
        cfg_z = base_cfg(mode="sketch", error_type="virtual", k=k,
                         num_rows=r, num_cols=c, grad_size=d,
                         sketch_impl="circ")
        cfg_s = cfg_z.replace(sketch_ef="subtract")
        table = cs.encode(g)
        zeros = cs.empty_table()
        _, _, verr_z, _ = server_update(cfg_z, table, zeros, zeros,
                                        jnp.asarray(1.0), cs=cs)
        _, _, verr_s, _ = server_update(cfg_s, table, zeros, zeros,
                                        jnp.asarray(1.0), cs=cs)
        # zero rule wipes r cells entirely; subtract keeps the colliding
        # coordinates' mass: the surviving table mass must be strictly
        # larger under subtract
        assert float(jnp.abs(verr_s).sum()) > float(jnp.abs(verr_z).sum())
        # and the extracted coordinate's estimate is (near-)removed in both
        est_s = float(cs.decode_at(verr_s, jnp.asarray([5]))[0])
        assert abs(est_s) < 1.0  # was 10.0 before extraction

    def test_error_decay_scales_verror(self):
        from commefficient_tpu.core.server import server_update
        d, k = 16, 2
        cfg1 = base_cfg(mode="true_topk", error_type="virtual", k=k,
                        grad_size=d)
        cfg2 = cfg1.replace(error_decay=0.5)
        g = jnp.asarray(np.arange(1.0, d + 1, dtype=np.float32))
        zeros = jnp.zeros((d,), jnp.float32)
        u1, v1, e1, _ = server_update(cfg1, g, zeros, zeros, jnp.asarray(1.0))
        u2, v2, e2, _ = server_update(cfg2, g, zeros, zeros, jnp.asarray(1.0))
        np.testing.assert_allclose(np.asarray(u1), np.asarray(u2))
        np.testing.assert_allclose(np.asarray(e2), 0.5 * np.asarray(e1))


class TestErrorFeedback:
    def test_true_topk_error_accumulates_and_masks(self):
        cfg = base_cfg(mode="true_topk", error_type="virtual", k=2)
        _, state, _, _ = run_rounds(cfg, 4)
        verr = np.asarray(state.Verror)
        # after any round, Verror must be zero on exactly the coords that
        # were just updated (k of them) and generally nonzero elsewhere
        assert (verr == 0).sum() >= 2
        assert (verr != 0).sum() > 0

    @pytest.mark.parametrize("impl", ["hash", "rht"])
    def test_loss_decreases(self, impl):
        cfg = base_cfg(mode="sketch", error_type="virtual", k=4,
                       num_rows=5, num_cols=256, num_blocks=1,
                       sketch_impl=impl)
        _, _, _, hist = run_rounds(cfg, 20, lr=0.05)
        first = hist[0]["results"][0].mean()
        last = hist[-1]["results"][0].mean()
        assert last < first * 0.5, (first, last)


class TestByteAccounting:
    def test_first_round_download_is_zero(self):
        _, _, _, hist = run_rounds(base_cfg(), 3)
        assert hist[0]["download_bytes"].sum() == 0

    def test_dense_update_downloads_full_model(self):
        d = D_FEAT + 1
        _, _, _, hist = run_rounds(base_cfg(), 3, seed=5)
        # by round 2+, participants that sat out exactly one dense update
        # download the whole model: 4 bytes * d
        later = hist[1]["download_bytes"]
        nz = later[later > 0]
        assert np.all(nz == 4 * d), nz

    def test_upload_matches_mode_table(self):
        # reference upload table fed_aggregator.py:291-299
        d = D_FEAT + 1
        _, _, _, hist = run_rounds(base_cfg(), 1)
        up = hist[0]["upload_bytes"]
        assert np.all(up[up > 0] == 4 * d)
        _, _, _, hist = run_rounds(
            base_cfg(mode="local_topk", error_type="none", k=3), 1)
        up = hist[0]["upload_bytes"]
        assert np.all(up[up > 0] == 4 * 3)
        _, _, _, hist = run_rounds(
            base_cfg(mode="sketch", error_type="virtual", k=3,
                     num_rows=3, num_cols=64, num_blocks=1), 1)
        up = hist[0]["upload_bytes"]
        assert np.all(up[up > 0] == 4 * 3 * 64)

    def test_sparse_update_downloads_only_changed(self):
        cfg = base_cfg(mode="true_topk", error_type="virtual", k=2)
        _, _, _, hist = run_rounds(cfg, 4, seed=7)
        later = hist[1]["download_bytes"]
        nz = later[later > 0]
        # a client stale by exactly one top-k(k=2) update downloads 8 bytes
        assert nz.size > 0 and np.all(nz <= 4 * 2 * 2), nz


class TestLocalState:
    def test_local_momentum_rows_update_only_for_participants(self):
        cfg = base_cfg(mode="local_topk", error_type="local", k=3,
                       local_momentum=0.9)
        params = init_params()
        xs, ys = make_data()
        rt = FedRuntime(cfg, params, loss_fn, num_clients=NUM_CLIENTS)
        state = rt.init_state()
        ids = np.array([1, 3, 5, 7], np.int32)
        batch = {"x": jnp.asarray(xs[ids]), "y": jnp.asarray(ys[ids])}
        state, _ = rt.round(state, ids, batch, jnp.ones((W, B)), 0.05)
        vel = np.asarray(state.client_velocities)
        err = np.asarray(state.client_errors)
        for c in range(NUM_CLIENTS):
            if c in ids:
                assert np.abs(vel[c]).sum() > 0
            else:
                assert np.abs(vel[c]).sum() == 0
                assert np.abs(err[c]).sum() == 0

    def test_microbatching_equivalence(self):
        """microbatch_size splitting scales the accumulated grad by
        num_iters (reference semantics, fed_worker.py:266-287): with lr
        scaled down by the same factor the trajectory must match."""
        _, _, traj_a, _ = run_rounds(base_cfg(microbatch_size=B), 3, lr=0.05)
        _, _, traj_b, _ = run_rounds(base_cfg(microbatch_size=B // 2), 3,
                                     lr=0.025)
        for got, want in zip(traj_b, traj_a):
            np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-5)


class TestPerParamLR:
    def test_vector_lr_scales_update_per_coordinate(self):
        """The reference's Fixup param groups yield a per-parameter LR
        vector from FedOptimizer.get_lr (fed_aggregator.py:411-427); our
        round accepts a (d,) lr and must scale each coordinate's update by
        its own rate — equivalent to running with scalar lr and rescaling."""
        cfg = base_cfg()
        params = init_params()
        xs, ys = make_data()
        rt = FedRuntime(cfg, params, loss_fn, num_clients=NUM_CLIENTS)
        d = rt.cfg.grad_size
        mult = np.ones(d, np.float32)
        mult[: d // 2] = 0.1
        ids = np.arange(W, dtype=np.int32)
        batch = {"x": jnp.asarray(xs[ids]), "y": jnp.asarray(ys[ids])}
        mask = jnp.ones((W, B))

        s_vec = rt.init_state()
        s_vec, _ = rt.round(s_vec, ids, batch, mask, 0.05 * mult)
        s_ref = rt.init_state()
        s_ref, _ = rt.round(s_ref, ids, batch, mask, 0.05)

        w0 = np.asarray(rt.init_state().ps_weights)
        upd_vec = w0 - np.asarray(s_vec.ps_weights)
        upd_ref = w0 - np.asarray(s_ref.ps_weights)
        np.testing.assert_allclose(upd_vec, upd_ref * mult,
                                   rtol=1e-5, atol=1e-7)


class TestNanFlag:
    """Device-side divergence flag (VERDICT r1 next #8): nan_round records
    the FIRST round whose loss/gradient/update went non-finite, without any
    per-round host fetch."""

    def test_records_first_bad_round(self):
        cfg = base_cfg()
        rt = FedRuntime(cfg, init_params(), loss_fn,
                        num_clients=NUM_CLIENTS)
        state = rt.init_state()
        xs, ys = make_data()
        ids = np.arange(W, dtype=np.int32)
        good = {"x": jnp.asarray(xs[ids]), "y": jnp.asarray(ys[ids])}
        bad = {"x": good["x"].at[0, 0, 0].set(jnp.nan), "y": good["y"]}
        mask = jnp.ones((W, B))

        state, _ = rt.round(state, ids, good, mask, 0.05)
        assert int(state.nan_round) == -1
        state, _ = rt.round(state, ids, bad, mask, 0.05)
        assert int(state.nan_round) == 1
        # weights are now poisoned; later rounds stay flagged at round 1
        state, _ = rt.round(state, ids, good, mask, 0.05)
        assert int(state.nan_round) == 1

    def test_train_loop_aborts_without_checkpoint(self, tmp_path):
        """The driver epoch loop reports the offending round and refuses to
        write a checkpoint of poisoned state."""
        from commefficient_tpu import models
        from commefficient_tpu.checkpoint import CheckpointManager
        from commefficient_tpu.cv_train import train
        from commefficient_tpu.data import FedCIFAR10, transforms_for
        from commefficient_tpu.losses import make_cv_loss

        ds = FedCIFAR10(str(tmp_path / "d"), synthetic=True,
                        synthetic_per_class=4,
                        transform=transforms_for("CIFAR10", False))
        cfg = FedConfig(mode="uncompressed", error_type="none",
                        local_momentum=0.0, virtual_momentum=0.0,
                        num_workers=2, local_batch_size=4,
                        num_clients=ds.num_clients, num_epochs=1.0,
                        track_bytes=False, compute_dtype="float32",
                        checkpoint_every=1)
        model = models.ResNet9(num_classes=10,
                               channels={"prep": 2, "layer1": 2,
                                         "layer2": 2, "layer3": 2})
        params = model.init(jax.random.PRNGKey(0),
                            jnp.ones((1, 32, 32, 3)))
        # poison the initial weights: every round's update is non-finite
        params = jax.tree.map(lambda t: t * jnp.nan, params)
        rt = FedRuntime(cfg, params, make_cv_loss(model, "float32"),
                        num_clients=ds.num_clients)
        mgr = CheckpointManager(str(tmp_path / "ck"))
        state, summary = train(cfg, rt, rt.init_state(), ds, ds,
                               ckpt_mgr=mgr)
        assert summary is None            # aborted
        assert int(state.nan_round) == 0  # flagged on the very first round
        assert mgr.epochs() == []         # nothing persisted


def test_subtract_ef_rejected_on_dense_preimage_paths():
    """--sketch_ef subtract is a TABLE-space rule; the dense-preimage
    server paths (sketch_server_state=dense, and rht's dense transform)
    would silently ignore it — they must refuse instead (ADVICE.md)."""
    from commefficient_tpu.core.server import validate_mode_combo
    common = dict(mode="sketch", error_type="virtual", local_momentum=0.0,
                  k=5, num_rows=2, num_cols=32)
    # the legal study configurations still validate
    validate_mode_combo(FedConfig(**common, sketch_ef="subtract"))
    validate_mode_combo(FedConfig(**common, sketch_ef="subtract",
                                  sketch_impl="hash"))
    validate_mode_combo(FedConfig(**common, sketch_server_state="dense"))
    with pytest.raises(ValueError, match="sketch_ef subtract"):
        validate_mode_combo(FedConfig(**common, sketch_ef="subtract",
                                      sketch_server_state="dense"))
    with pytest.raises(ValueError, match="sketch_ef subtract"):
        validate_mode_combo(FedConfig(**common, sketch_ef="subtract",
                                      sketch_impl="rht"))
    # and the runtime constructor (both drivers' entry point) enforces it
    params = {"w": jnp.zeros((4, 2), jnp.float32)}
    with pytest.raises(ValueError, match="sketch_ef subtract"):
        FedRuntime(FedConfig(**common, num_workers=2, local_batch_size=2,
                             sketch_ef="subtract",
                             sketch_server_state="dense"),
                   params, loss_fn, num_clients=4)
