"""GPT-2 DoubleHeads model + PersonaChat pipeline tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.data.fed_persona import (
    FedPERSONA,
    HashTokenizer,
    build_input_from_segments,
)
from commefficient_tpu.losses import make_gpt2_train_loss, make_gpt2_val_loss
from commefficient_tpu.models.gpt2 import (
    GPT2Config,
    GPT2DoubleHeads,
    GPT2LMHead,
    load_state_dict,
)


@pytest.fixture(scope="module")
def tiny_model():
    cfg = GPT2Config.small(compute_dtype=jnp.float32)
    model = GPT2DoubleHeads(cfg)
    ids = jnp.zeros((2, 2, 16), jnp.int32)
    mc = jnp.full((2, 2), 15, jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids, mc, ids)
    return cfg, model, params


def test_shapes(tiny_model):
    cfg, model, params = tiny_model
    ids = jnp.zeros((2, 2, 16), jnp.int32)
    mc = jnp.full((2, 2), 15, jnp.int32)
    lm, mcl = model.apply(params, ids, mc, ids)
    assert lm.shape == (2, 2, 16, cfg.total_vocab)
    assert mcl.shape == (2, 2)


def test_causality(tiny_model):
    """Changing a future token must not change past logits."""
    cfg, model, params = tiny_model
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 256, (1, 1, 16))
    ids2 = ids.copy()
    ids2[..., 10:] = (ids2[..., 10:] + 1) % 256
    mc = jnp.full((1, 1), 15, jnp.int32)
    lm1, _ = model.apply(params, jnp.asarray(ids), mc, jnp.asarray(ids))
    lm2, _ = model.apply(params, jnp.asarray(ids2), mc, jnp.asarray(ids2))
    np.testing.assert_allclose(np.asarray(lm1[..., :10, :]),
                               np.asarray(lm2[..., :10, :]),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_losses_finite_and_trainable(tiny_model):
    cfg, model, params = tiny_model
    rng = np.random.RandomState(1)
    B, C, S = 3, 2, 16
    batch = {
        "input_ids": jnp.asarray(rng.randint(0, 256, (B, C, S))),
        "token_type_ids": jnp.asarray(rng.randint(0, 256, (B, C, S))),
        "mc_token_ids": jnp.full((B, C), S - 1, jnp.int32),
        "lm_labels": jnp.asarray(
            np.where(rng.rand(B, C, S) < 0.5, rng.randint(0, 256, (B, C, S)),
                     -100)),
        "mc_label": jnp.asarray(rng.randint(0, C, (B,))),
    }
    mask = jnp.asarray([1, 1, 0], jnp.float32)
    loss_fn = make_gpt2_train_loss(model)
    (loss, (acc,)), grads = jax.value_and_grad(
        loss_fn, has_aux=True)(params, batch, mask)
    assert np.isfinite(float(loss)) and 0 <= float(acc) <= 1
    gnorm = sum(float(jnp.abs(g).sum()) for g in jax.tree.leaves(grads))
    assert gnorm > 0

    val_fn = make_gpt2_val_loss(model)
    nll, (vacc,) = val_fn(params, batch, mask)
    assert np.isfinite(float(nll))


@pytest.mark.slow
def test_chunked_lm_loss_matches_dense(tiny_model):
    """lm_chunk (the memory-bounded CE that never materializes full-vocab
    logits — the microbatch-8 enabler) must reproduce the dense loss AND
    its gradients, including a chunk size that does not divide S-1."""
    cfg, model, params = tiny_model
    rng = np.random.RandomState(2)
    B, C, S = 3, 2, 16
    batch = {
        "input_ids": jnp.asarray(rng.randint(0, 256, (B, C, S))),
        "token_type_ids": jnp.asarray(rng.randint(0, 256, (B, C, S))),
        "mc_token_ids": jnp.full((B, C), S - 1, jnp.int32),
        "lm_labels": jnp.asarray(
            np.where(rng.rand(B, C, S) < 0.5, rng.randint(0, 256, (B, C, S)),
                     -100)),
        "mc_label": jnp.asarray(rng.randint(0, C, (B,))),
    }
    mask = jnp.asarray([1, 1, 0], jnp.float32)
    dense_fn = make_gpt2_train_loss(model)
    (l0, (a0,)), g0 = jax.value_and_grad(dense_fn, has_aux=True)(
        params, batch, mask)
    for chunk in (4, 7, 64):  # divides, doesn't divide, > S-1
        ck_fn = make_gpt2_train_loss(model, lm_chunk=chunk)
        (l1, (a1,)), g1 = jax.value_and_grad(ck_fn, has_aux=True)(
            params, batch, mask)
        np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
        np.testing.assert_allclose(float(a0), float(a1))
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6), g0, g1)


def test_build_input_from_segments():
    tok = HashTokenizer(64)
    persona = [tok.encode("i like cats"), tok.encode("i run")]
    history = [tok.encode("hello there"), tok.encode("hi you")]
    reply = tok.encode("good day")
    inst = build_input_from_segments(persona, history, reply, tok,
                                     lm_labels=True)
    n = len(inst["input_ids"])
    assert len(inst["token_type_ids"]) == n
    assert len(inst["lm_labels"]) == n
    # labels cover exactly the reply + <eos>
    labeled = [x for x in inst["lm_labels"] if x != -100]
    assert len(labeled) == len(reply) + 1
    # sequence starts with <bos>, ends with <eos>
    assert inst["input_ids"][0] == tok.convert_tokens_to_ids("<bos>")
    assert inst["input_ids"][-1] == tok.convert_tokens_to_ids("<eos>")


def test_fed_persona_synthetic(tmp_path):
    ds = FedPERSONA(str(tmp_path), synthetic=True, max_seq_len=48)
    assert ds.num_clients == 12
    b = ds.gather(np.arange(4))
    assert b["input_ids"].shape == (4, 2, 48)
    assert b["mc_token_ids"].shape == (4, 2)
    assert b["mc_label"].shape == (4,)
    val = FedPERSONA(str(tmp_path), train=False, synthetic=True,
                     max_seq_len=48)
    assert len(val) > 0
    # write policy: every persona artifact is class-prefixed
    # (fed_dataset.py data_fn write policy; VERDICT r1 weak #6)
    import os
    for fn in ("persona_train.npz", "persona_val.npz",
               "persona_prep.json"):
        assert os.path.exists(str(tmp_path / f"FedPERSONA_{fn}")), fn
        assert not os.path.exists(str(tmp_path / fn)), fn


def test_persona_legacy_cache_invalidation(tmp_path):
    """A packed cache with no prep-config sidecar predates the sidecar and
    its packing semantics — it must be re-prepared, not silently adopted
    (ADVICE r1 low #3)."""
    import os

    ds = FedPERSONA(str(tmp_path), synthetic=True, max_seq_len=48)
    n_items = len(ds)
    # forge a pre-sidecar legacy layout: unprefixed npz + plain stats.json,
    # no persona_prep.json anywhere
    for fn in ("persona_train.npz", "persona_val.npz"):
        os.rename(str(tmp_path / f"FedPERSONA_{fn}"), str(tmp_path / fn))
    os.rename(str(tmp_path / "stats_FedPERSONA.json"),
              str(tmp_path / "stats.json"))
    os.unlink(str(tmp_path / "FedPERSONA_persona_prep.json"))
    # sanity: a legacy layout WITH a matching sidecar is adopted as-is
    import json as _json
    with open(str(tmp_path / "persona_prep.json"), "w") as f:
        _json.dump(ds._prep_config, f)
    adopted = FedPERSONA(str(tmp_path), synthetic=True, max_seq_len=48)
    assert adopted._legacy_layout
    os.unlink(str(tmp_path / "persona_prep.json"))
    # no sidecar: stale by definition -> re-prepared under prefixed names
    fresh = FedPERSONA(str(tmp_path), synthetic=True, max_seq_len=48)
    assert not fresh._legacy_layout
    assert len(fresh) == n_items
    assert os.path.exists(str(tmp_path / "FedPERSONA_persona_train.npz"))
    # and the stale unprefixed pack was removed, not left adoptable
    assert not os.path.exists(str(tmp_path / "persona_train.npz"))


def test_persona_mixed_layout_adoption(tmp_path):
    """The immediately previous package version wrote prefixed stats but
    unprefixed npz + sidecar; a matching pack is adopted by rename instead
    of re-tokenizing the corpus."""
    import os

    ds = FedPERSONA(str(tmp_path), synthetic=True, max_seq_len=48)
    n_items = len(ds)
    # forge the mixed layout: prefixed stats stays, pack+sidecar unprefixed
    for fn in ("persona_train.npz", "persona_val.npz",
               "persona_prep.json"):
        os.rename(str(tmp_path / f"FedPERSONA_{fn}"), str(tmp_path / fn))
    # tag the pack so we can prove it was adopted, not regenerated
    mtime = os.path.getmtime(str(tmp_path / "persona_train.npz"))
    adopted = FedPERSONA(str(tmp_path), synthetic=True, max_seq_len=48)
    assert len(adopted) == n_items
    pref = str(tmp_path / "FedPERSONA_persona_train.npz")
    assert os.path.exists(pref)
    assert os.path.getmtime(pref) == mtime        # renamed, not re-packed
    assert not os.path.exists(str(tmp_path / "persona_train.npz"))


def test_lm_head_variant():
    cfg = GPT2Config.small(compute_dtype=jnp.float32)
    model = GPT2LMHead(cfg)
    ids = jnp.zeros((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)
    y = model.apply(params, ids)
    assert y.shape == (2, 16, cfg.total_vocab)


def test_persona_history_and_permutations(tmp_path):
    """--max_history truncates to the last 2*h+1 exchanges (reference
    fed_persona.py:255) and --personality_permutations multiplies items with
    rotated persona sentences (reference utils.py:204-207)."""
    from commefficient_tpu.data.fed_persona import FedPERSONA

    base = FedPERSONA(str(tmp_path / "p1"), train=True, synthetic=True,
                      max_history=2, personality_permutations=1)
    perm = FedPERSONA(str(tmp_path / "p2"), train=True, synthetic=True,
                      max_history=2, personality_permutations=3)
    assert len(perm) == 3 * len(base)
    # shorter history => sequences can only get shorter or equal
    short = FedPERSONA(str(tmp_path / "p3"), train=True, synthetic=True,
                       max_history=0, personality_permutations=1)
    lens_base = (base.arrays["input_ids"] !=
                 base.tokenizer.convert_tokens_to_ids("<pad>")).sum()
    lens_short = (short.arrays["input_ids"] !=
                  short.tokenizer.convert_tokens_to_ids("<pad>")).sum()
    assert lens_short <= lens_base
    assert len(short) == len(base)


def test_gpt2_lr_schedule_is_linear_to_zero():
    """Reference gpt2_train.py:302-307: LR decays LINEARLY from lr_scale at
    epoch 0 to 0 at num_epochs — distinct from the CV triangular ramp."""
    from commefficient_tpu.config import FedConfig
    from commefficient_tpu.gpt2_train import make_gpt2_schedule

    cfg = FedConfig(lr_scale=0.16, num_epochs=4.0, local_momentum=0.0)
    s = make_gpt2_schedule(cfg)
    assert s(0.0) == 0.16                 # full LR at step 0 (no warmup)
    assert abs(s(1.0) - 0.12) < 1e-9      # linear
    assert abs(s(2.0) - 0.08) < 1e-9
    assert s(4.0) == 0.0
    # --lr_warmup (TPU-native opt-in): triangular 0 -> lr -> 0
    w = make_gpt2_schedule(cfg.replace(lr_warmup=True, pivot_epoch=1.0))
    assert w(0.0) == 0.0
    assert abs(w(0.5) - 0.08) < 1e-9
    assert w(1.0) == 0.16
    assert abs(w(2.5) - 0.08) < 1e-9
    assert w(4.0) == 0.0


def test_save_pretrained_roundtrip(tmp_path):
    """save_pretrained emits weights + config + tokenizer together and
    load_pretrained rebuilds an equivalent model with no access to the
    writing run (reference fed_aggregator.py:208-211 parity)."""
    from commefficient_tpu.config import FedConfig
    from commefficient_tpu.core import FedRuntime
    from commefficient_tpu.data.fed_persona import HashTokenizer
    from commefficient_tpu.gpt2_train import load_pretrained, save_pretrained
    from commefficient_tpu.losses import make_gpt2_train_loss

    tok = HashTokenizer(128)
    gcfg = GPT2Config.small(vocab_size=len(tok) - 5,
                            compute_dtype=jnp.float32)
    model = GPT2DoubleHeads(gcfg)
    ids = jnp.zeros((1, 2, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(1), ids,
                        jnp.zeros((1, 2), jnp.int32), ids)
    cfg = FedConfig(mode="uncompressed", error_type="none",
                    local_momentum=0.0, num_workers=2, local_batch_size=2,
                    num_clients=4, track_bytes=False, num_results_train=3)
    rt = FedRuntime(cfg, params, make_gpt2_train_loss(model),
                    num_clients=4)
    state = rt.init_state()
    out = str(tmp_path / "pretrained")
    save_pretrained(out, rt, state, gcfg, tok)
    import os
    assert os.path.exists(os.path.join(out, "weights.npz"))
    assert os.path.exists(os.path.join(out, "config.json"))
    assert os.path.exists(os.path.join(out, "hash_tokenizer.json"))

    model2, params2, gcfg2, tok2 = load_pretrained(out)
    assert gcfg2 == gcfg
    assert isinstance(tok2, HashTokenizer) and len(tok2) == len(tok)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), params, params2)
    # the reloaded model runs
    lm, mc = model2.apply(params2, ids, jnp.zeros((1, 2), jnp.int32), ids)
    assert lm.shape == (1, 2, 8, gcfg.total_vocab)


def _synth_hf_state_dict(cfg: GPT2Config, seed=0):
    """A synthesized HuggingFace-GPT2Model-layout state dict (the exact key
    names/shapes GPT2Model.state_dict() emits) with random values — the
    fixture standing in for a real pretrained checkpoint in this
    zero-egress environment (VERDICT r4 missing #3)."""
    rng = np.random.RandomState(seed)
    E = cfg.n_embd
    sd = {
        "wte.weight": rng.randn(cfg.vocab_size, E).astype(np.float32) * 0.1,
        "wpe.weight": rng.randn(cfg.n_positions, E).astype(np.float32) * 0.1,
        "ln_f.weight": 1 + 0.1 * rng.randn(E).astype(np.float32),
        "ln_f.bias": 0.1 * rng.randn(E).astype(np.float32),
    }
    per_layer = {  # HF Conv1D layout: (in, out), matching flax Dense
        "attn.c_attn.weight": (E, 3 * E), "attn.c_attn.bias": (3 * E,),
        "attn.c_proj.weight": (E, E), "attn.c_proj.bias": (E,),
        "mlp.c_fc.weight": (E, 4 * E), "mlp.c_fc.bias": (4 * E,),
        "mlp.c_proj.weight": (4 * E, E), "mlp.c_proj.bias": (E,),
        "ln_1.weight": (E,), "ln_1.bias": (E,),
        "ln_2.weight": (E,), "ln_2.bias": (E,),
    }
    for i in range(cfg.n_layer):
        for name, shape in per_layer.items():
            scale = 0.02 if name.endswith("weight") and len(shape) == 2 \
                else 0.1
            sd[f"h.{i}.{name}"] = (
                scale * rng.randn(*shape)).astype(np.float32)
    return sd


def test_load_state_dict_mapping_and_parity():
    """The HF-checkpoint mapping end to end (VERDICT r4 missing #3):
    synthesized HF-layout arrays -> load_state_dict into BOTH layer
    layouts -> (a) leaves land where the hand-built placement says,
    (b) special-token rows are the mean-embedding pad, (c) the scan and
    no-scan models produce IDENTICAL forwards from the same checkpoint —
    the stacking is semantics-preserving."""
    base = dict(vocab_size=64, n_positions=32, n_embd=16, n_layer=3,
                n_head=4, compute_dtype=jnp.float32)
    cfg_scan = GPT2Config(**base, scan_layers=True)
    cfg_flat = GPT2Config(**base, scan_layers=False)
    sd = _synth_hf_state_dict(cfg_scan)

    ids = jnp.asarray(np.random.RandomState(1).randint(
        0, cfg_scan.total_vocab, (2, 2, 8)), jnp.int32)
    mc = jnp.full((2, 2), 7, jnp.int32)
    m_scan, m_flat = GPT2DoubleHeads(cfg_scan), GPT2DoubleHeads(cfg_flat)
    p_scan = m_scan.init(jax.random.PRNGKey(0), ids, mc, ids)
    p_flat = m_flat.init(jax.random.PRNGKey(1), ids, mc, ids)
    # the MC head is not part of the HF checkpoint: align it across the
    # two models so the forwards are comparable
    p_flat["params"]["mc_head"] = jax.tree.map(
        lambda t: t, p_scan["params"]["mc_head"])

    l_scan = load_state_dict(p_scan, cfg_scan, sd)
    l_flat = load_state_dict(p_flat, cfg_flat, sd)

    # (a) hand-checked placement: layer 2's c_fc kernel sits at stacked
    # index 2 in the scan layout and under h2 in the flat layout
    np.testing.assert_array_equal(
        np.asarray(l_scan["params"]["transformer"]["h"]["block"]["c_fc"]
                   ["kernel"])[2], sd["h.2.mlp.c_fc.weight"])
    np.testing.assert_array_equal(
        np.asarray(l_flat["params"]["transformer"]["h2"]["c_fc"]["kernel"]),
        sd["h.2.mlp.c_fc.weight"])

    # (b) special-token padding: rows vocab_size..total_vocab-1 all equal
    # the mean pretrained embedding
    wte = np.asarray(l_scan["params"]["transformer"]["wte"])
    mean = sd["wte.weight"].mean(0)
    for row in range(cfg_scan.vocab_size, cfg_scan.total_vocab):
        np.testing.assert_allclose(wte[row], mean, rtol=1e-6)

    # (c) forward parity between the two layouts from the same checkpoint
    lm_s, mc_s = m_scan.apply(l_scan, ids, mc, ids)
    lm_f, mc_f = m_flat.apply(l_flat, ids, mc, ids)
    np.testing.assert_allclose(np.asarray(lm_s), np.asarray(lm_f),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(mc_s), np.asarray(mc_f),
                               rtol=1e-5, atol=1e-5)


def test_load_state_dict_fails_loudly():
    """Mapping errors must raise, not silently ship a half-loaded model."""
    cfg = GPT2Config(vocab_size=64, n_positions=32, n_embd=16, n_layer=2,
                     n_head=4, compute_dtype=jnp.float32)
    model = GPT2DoubleHeads(cfg)
    ids = jnp.zeros((1, 2, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids,
                        jnp.zeros((1, 2), jnp.int32), ids)
    sd = _synth_hf_state_dict(cfg)
    missing = dict(sd)
    del missing["h.1.mlp.c_fc.weight"]
    with pytest.raises(KeyError):
        load_state_dict(params, cfg, missing)
    bad = dict(sd)
    bad["ln_f.weight"] = np.zeros((cfg.n_embd + 1,), np.float32)
    with pytest.raises(ValueError):
        load_state_dict(params, cfg, bad)


# ---------------------------------------------------- load_hf_weights


class _FakeTensor:
    """torch-tensor stand-in: the two methods load_hf_weights calls."""

    def __init__(self, arr):
        self._arr = arr

    def detach(self):
        return self

    def numpy(self):
        return self._arr


def _install_fake_transformers(monkeypatch, sd):
    """A ``transformers`` module whose GPT2Model.from_pretrained serves
    the given HF-layout state dict from 'local files'."""
    import sys
    import types

    class _FakeHF:
        def state_dict(self):
            return {k: _FakeTensor(v) for k, v in sd.items()}

    class GPT2Model:  # noqa: N801 - mirrors the transformers name
        @classmethod
        def from_pretrained(cls, checkpoint, **kw):
            assert kw.get("local_files_only"), \
                "load_hf_weights must never hit the network"
            return _FakeHF()

    fake = types.ModuleType("transformers")
    fake.GPT2Model = GPT2Model
    monkeypatch.setitem(sys.modules, "transformers", fake)


def _np_layernorm(x, w, b, eps):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) / np.sqrt(var + eps) * w + b


def _np_gelu_tanh(x):
    return 0.5 * x * (1 + np.tanh(
        np.sqrt(2 / np.pi) * (x + 0.044715 * x ** 3)))


def _np_gpt2_lm_forward(sd, cfg: GPT2Config, ids, tt_ids):
    """Independent numpy forward of the LM path straight from the HF
    state-dict arrays (resize included) — no jax, no flax, so a mapping
    bug cannot cancel out of the comparison."""
    wte = sd["wte.weight"]
    wte = np.concatenate(
        [wte, np.tile(wte.mean(0, keepdims=True),
                      (cfg.total_vocab - wte.shape[0], 1))])
    S = ids.shape[-1]
    H, Dh = cfg.n_head, cfg.n_embd // cfg.n_head
    x = wte[ids] + sd["wpe.weight"][np.arange(S)] + wte[tt_ids]
    eps = cfg.layer_norm_eps
    for i in range(cfg.n_layer):
        p = {k: sd[f"h.{i}.{k}"] for k in (
            "ln_1.weight", "ln_1.bias", "attn.c_attn.weight",
            "attn.c_attn.bias", "attn.c_proj.weight", "attn.c_proj.bias",
            "ln_2.weight", "ln_2.bias", "mlp.c_fc.weight",
            "mlp.c_fc.bias", "mlp.c_proj.weight", "mlp.c_proj.bias")}
        h = _np_layernorm(x, p["ln_1.weight"], p["ln_1.bias"], eps)
        qkv = h @ p["attn.c_attn.weight"] + p["attn.c_attn.bias"]
        q, k, v = np.split(qkv, 3, axis=-1)
        split = lambda t: t.reshape(t.shape[:-1] + (H, Dh))  # noqa: E731
        q, k, v = split(q), split(k), split(v)
        logits = np.einsum("...qhd,...khd->...hqk", q, k) / np.sqrt(Dh)
        mask = np.tril(np.ones((S, S), bool))
        logits = np.where(mask, logits, -1e30)
        probs = np.exp(logits - logits.max(-1, keepdims=True))
        probs = probs / probs.sum(-1, keepdims=True)
        a = np.einsum("...hqk,...khd->...qhd", probs, v)
        a = a.reshape(a.shape[:-2] + (cfg.n_embd,))
        x = x + a @ p["attn.c_proj.weight"] + p["attn.c_proj.bias"]
        h = _np_layernorm(x, p["ln_2.weight"], p["ln_2.bias"], eps)
        h = _np_gelu_tanh(h @ p["mlp.c_fc.weight"] + p["mlp.c_fc.bias"])
        x = x + h @ p["mlp.c_proj.weight"] + p["mlp.c_proj.bias"]
    x = _np_layernorm(x, sd["ln_f.weight"], sd["ln_f.bias"], eps)
    return x @ wte.T


def test_load_hf_weights_end_to_end(monkeypatch):
    """The full load_hf_weights path (VERDICT missing #1): an HF-layout
    fixture (true tensor names/shapes, Conv1D (in, out) convention,
    the mask buffers real checkpoints carry) served through a stubbed
    ``transformers`` -> load -> 5-special-token resize -> the loaded
    model matches an independent NUMPY forward, and one federated
    finetune round runs finite and actually moves the weights."""
    from commefficient_tpu.models.gpt2 import load_hf_weights

    cfg = GPT2Config(vocab_size=64, n_positions=32, n_embd=16, n_layer=2,
                     n_head=4, compute_dtype=jnp.float32)
    assert cfg.total_vocab == cfg.vocab_size + 5   # the resize contract
    sd = _synth_hf_state_dict(cfg)
    for i in range(cfg.n_layer):
        # buffers a real GPT2Model.state_dict() also contains: the
        # causal-mask constants — the mapping must ignore extras
        sd[f"h.{i}.attn.bias"] = np.tril(
            np.ones((cfg.n_positions, cfg.n_positions), np.float32))
        sd[f"h.{i}.attn.masked_bias"] = np.float32(-1e4)
    _install_fake_transformers(monkeypatch, sd)

    lm = GPT2LMHead(cfg)
    ids0 = jnp.zeros((1, 8), jnp.int32)
    params = lm.init(jax.random.PRNGKey(0), ids0, ids0)
    loaded = load_hf_weights(params, cfg, "gpt2")
    assert loaded is not None, "stubbed checkpoint must load"

    # resize: the 5 added special-token rows are the mean embedding
    wte = np.asarray(loaded["params"]["transformer"]["wte"])
    assert wte.shape == (cfg.total_vocab, cfg.n_embd)
    for row in range(cfg.vocab_size, cfg.total_vocab):
        np.testing.assert_allclose(wte[row], sd["wte.weight"].mean(0),
                                   rtol=1e-6)

    # numpy forward parity on tokens that EXERCISE the resize (special
    # ids above vocab_size appear in both ids and token types)
    rng = np.random.RandomState(5)
    ids = rng.randint(0, cfg.total_vocab, (2, 8)).astype(np.int32)
    tt = rng.randint(cfg.vocab_size, cfg.total_vocab,
                     (2, 8)).astype(np.int32)
    got = np.asarray(lm.apply(loaded, jnp.asarray(ids), jnp.asarray(tt)))
    want = _np_gpt2_lm_forward(sd, cfg, ids, tt)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    # one federated finetune round on the loaded weights
    from commefficient_tpu.config import FedConfig
    from commefficient_tpu.core import FedRuntime

    dbl = GPT2DoubleHeads(cfg)
    ids3 = jnp.zeros((1, 2, 8), jnp.int32)
    dparams = dbl.init(jax.random.PRNGKey(1), ids3,
                       jnp.zeros((1, 2), jnp.int32), ids3)
    dloaded = load_hf_weights(dparams, cfg, "gpt2")
    assert dloaded is not None
    fed = FedConfig(mode="uncompressed", error_type="none",
                    local_momentum=0.0, virtual_momentum=0.9,
                    weight_decay=0.0, num_workers=2, local_batch_size=2,
                    track_bytes=False, num_clients=4,
                    num_results_train=2)
    rt = FedRuntime(fed, dloaded, make_gpt2_train_loss(dbl),
                    num_clients=4)
    W, B, C, S = 2, 2, 2, 8
    batch = {
        "input_ids": jnp.asarray(
            rng.randint(0, cfg.total_vocab, (W, B, C, S)), jnp.int32),
        "token_type_ids": jnp.asarray(
            rng.randint(0, cfg.total_vocab, (W, B, C, S)), jnp.int32),
        "mc_token_ids": jnp.asarray(
            rng.randint(0, S, (W, B, C)), jnp.int32),
        "lm_labels": jnp.asarray(
            rng.randint(0, cfg.total_vocab, (W, B, C, S)), jnp.int32),
        "mc_label": jnp.asarray(rng.randint(0, C, (W, B)), jnp.int32),
    }
    state0 = rt.init_state()
    w_before = np.asarray(rt.flat_weights(state0))
    state, metrics = rt.round(state0, jnp.arange(W, dtype=jnp.int32),
                              batch, jnp.ones((W, B), bool), 0.01)
    losses = np.asarray(metrics["results"][0])
    assert np.all(np.isfinite(losses))
    assert not np.array_equal(w_before, np.asarray(rt.flat_weights(state)))


def test_load_hf_weights_soft_fails_without_transformers(monkeypatch):
    """Zero-egress environments: an unavailable transformers import (or
    missing local checkpoint) falls back to None — random init, never a
    crash."""
    import sys

    from commefficient_tpu.models.gpt2 import load_hf_weights
    monkeypatch.setitem(sys.modules, "transformers", None)
    cfg = GPT2Config.small(compute_dtype=jnp.float32)
    lm = GPT2LMHead(cfg)
    ids0 = jnp.zeros((1, 8), jnp.int32)
    params = lm.init(jax.random.PRNGKey(0), ids0, ids0)
    assert load_hf_weights(params, cfg, "gpt2") is None
