"""HBM memory ledger + roofline attribution (telemetry/memory_ledger.py
+ the utilization.py roofline fields + the schema-v6 events): numpy-
reference roofline math against synthetic cost dicts and fake peak
tables, ledger parsing from a stubbed ``memory_analysis()``, the
ceiling/dense-gradient gates in both directions, residency degradation
semantics (missing method / raising / empty dict -> null, never fake
zeros), schema round-trips incl. the v5-stream compatibility rule,
JitWatcher stream integration, HLO invisibility of the whole layer, the
``hbm_pressure`` monitor rule, the flight recorder's ``memory.json``,
and the jax-free teleview literals + ``memory``/``diff`` gates."""

import json
import os
import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.config import FedConfig
from commefficient_tpu.core import FedRuntime
from commefficient_tpu.telemetry import (AnomalyMonitor, FlightRecorder,
                                         RunTelemetry, validate_event,
                                         validate_file)
from commefficient_tpu.telemetry.memory_ledger import (MEMORY_KEYS,
                                                       MEMORY_LEDGER_KEYS,
                                                       ResidencyTracker,
                                                       check_ceilings,
                                                       check_dense_grad_floor,
                                                       ledger_from_compiled,
                                                       ledger_from_stats,
                                                       residency_fields,
                                                       round_memory_ceilings,
                                                       round_memory_ledger)
from commefficient_tpu.telemetry.utilization import (PEAK_HBM_GBPS_BY_KIND,
                                                     ROOFLINE_KEYS,
                                                     emit_from_totals,
                                                     peak_hbm_for,
                                                     roofline_fields,
                                                     utilization_fields)

W, B, D_IN, D_OUT = 4, 4, 6, 3
D = D_IN * D_OUT


def loss_fn(params, batch, mask):
    pred = batch["x"] @ params["w"]
    m = mask.astype(jnp.float32)
    denom = jnp.maximum(m.sum(), 1.0)
    err = ((pred - batch["y"]) ** 2).sum(axis=1)
    loss = (err * m).sum() / denom
    return loss, (loss,)


def make_runtime(**kw):
    cfg_kw = dict(mode="uncompressed", error_type="none",
                  local_momentum=0.0, virtual_momentum=0.9,
                  weight_decay=0.0, num_workers=W, local_batch_size=B,
                  track_bytes=True, num_clients=8, num_results_train=2,
                  num_results_val=2, k=5, num_rows=2, num_cols=32,
                  exact_num_cols=True)
    cfg_kw.update(kw)
    params = {"w": jnp.asarray(
        np.random.RandomState(0).randn(D_IN, D_OUT), jnp.float32)}
    return FedRuntime(FedConfig(**cfg_kw), params, loss_fn, num_clients=8)


def make_batch(seed=1):
    rng = np.random.RandomState(seed)
    batch = {"x": jnp.asarray(rng.randn(W, B, D_IN), jnp.float32),
             "y": jnp.asarray(rng.randn(W, B, D_OUT), jnp.float32)}
    return batch, jnp.ones((W, B), bool), jnp.arange(W, dtype=jnp.int32)


# ------------------------------------------------------------- roofline


def test_roofline_math_compute_bound():
    # fake peak pair: 1e14 FLOP/s over 1000 GB/s -> ridge 100 FLOP/B;
    # 1e12 FLOPs over 4e9 bytes -> AI 250, right of the ridge
    r = roofline_fields(rounds=10, wall_s=2.0, flops_per_round=1e12,
                        bytes_per_round=4e9, bytes_source="cost_analysis",
                        peak_flops=1e14, peak_hbm_gbps=1000.0)
    assert r["arithmetic_intensity"] == pytest.approx(250.0)
    assert r["ridge_intensity"] == pytest.approx(100.0)
    assert r["bound"] == "compute"
    # bytes throughput: 4e9 * 10 / 2 s = 2e10 B/s = 20 GB/s of 1000
    assert r["achieved_gbps"] == pytest.approx(20.0)
    assert r["bw_frac"] == pytest.approx(0.02)
    # two-term model: max(1e12/1e14, 4e9/1e12) = max(0.01, 0.004)
    assert r["expected_round_s"] == pytest.approx(0.01)
    assert r["bytes_source"] == "cost_analysis"


def test_roofline_math_bandwidth_bound():
    r = roofline_fields(rounds=1, wall_s=1.0, flops_per_round=1e11,
                        bytes_per_round=4e9, bytes_source="cost_analysis",
                        peak_flops=1e14, peak_hbm_gbps=1000.0)
    assert r["arithmetic_intensity"] == pytest.approx(25.0)
    assert r["bound"] == "bandwidth"
    assert r["expected_round_s"] == pytest.approx(4e-3)  # byte term binds


def test_roofline_null_contract_never_fake_zero():
    # no byte count: every byte-derived field null, bytes_source nulled
    r = roofline_fields(rounds=1, wall_s=1.0, flops_per_round=1e12,
                        bytes_per_round=None, bytes_source="cost_analysis",
                        peak_flops=1e14, peak_hbm_gbps=1000.0)
    for k in ("bytes_per_round", "bytes_source", "arithmetic_intensity",
              "bound", "achieved_gbps", "bw_frac", "expected_round_s"):
        assert r[k] is None, k
    assert r["ridge_intensity"] is not None  # peak pair alone defines it
    # no bandwidth peak: verdict/ridge/fraction null even with bytes
    r = roofline_fields(rounds=1, wall_s=1.0, flops_per_round=1e12,
                        bytes_per_round=4e9, bytes_source="cost_analysis",
                        peak_flops=1e14, peak_hbm_gbps=None)
    for k in ("ridge_intensity", "bound", "bw_frac", "expected_round_s"):
        assert r[k] is None, k
    assert r["arithmetic_intensity"] == pytest.approx(250.0)


def test_utilization_fields_joins_roofline():
    f = utilization_fields(rounds=2, wall_s=1.0, host_s=0.1,
                           dispatch_s=0.1, device_s=0.5,
                           flops_per_round=1e12,
                           flops_source="analytic", device_kind="fake",
                           peak_flops=1e14, bytes_per_round=4e9,
                           bytes_source="cost_analysis",
                           peak_hbm_gbps=1000.0)
    assert f["mfu"] == pytest.approx(0.02)
    assert f["bound"] == "compute" and f["bw_frac"] is not None
    # without bytes the roofline keys are still PRESENT (schema shape)
    # but null — a pre-roofline caller keeps producing valid v6 events
    f = utilization_fields(rounds=2, wall_s=1.0, host_s=0.1,
                           dispatch_s=0.1, device_s=0.5,
                           flops_per_round=1e12,
                           flops_source="analytic", device_kind="fake",
                           peak_flops=1e14)
    for k in ROOFLINE_KEYS:
        assert k in f
    assert f["bound"] is None and f["arithmetic_intensity"] is None


def test_peak_hbm_lookup_prefix_override_unknown():
    assert peak_hbm_for("TPU v5 lite") == PEAK_HBM_GBPS_BY_KIND["TPU v5 lite"]
    assert peak_hbm_for("TPU v4 (something)") == \
        PEAK_HBM_GBPS_BY_KIND["TPU v4"]
    assert peak_hbm_for("Grace Hopper") is None       # never a guess
    assert peak_hbm_for("Grace Hopper", 4000.0) == 4000.0


def test_emit_from_totals_roofline_event_round_trips(tmp_path):
    tel = RunTelemetry(str(tmp_path), "test", cfg=None)
    fields = emit_from_totals(
        tel, rnd=1, rounds=4, wall_s=1.0, host_s=0.1, dispatch_s=0.1,
        device_s=0.5, flops_per_round=1e12, flops_source="analytic",
        device_kind="fake", peak_flops=1e14,
        bytes_per_round=4e9, bytes_source="cost_analysis",
        peak_hbm_gbps=1000.0)
    tel.close()
    assert fields["bound"] == "compute"
    assert validate_file(tel.path) == []
    ev = [json.loads(l) for l in open(tel.path)
          if '"utilization"' in l][0]
    for k in ROOFLINE_KEYS:
        assert ev[k] == fields[k], k


# ------------------------------------------------------- ledger parsing


class _Stats:
    def __init__(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)


def test_ledger_from_stats_full():
    led = ledger_from_stats(_Stats(
        temp_size_in_bytes=1000, argument_size_in_bytes=200,
        output_size_in_bytes=300, alias_size_in_bytes=50,
        generated_code_size_in_bytes=7))
    assert led == {"temp_bytes": 1000, "argument_bytes": 200,
                   "output_bytes": 300, "alias_bytes": 50,
                   "generated_code_bytes": 7,
                   "total_bytes": 1000 + 200 + 300 + 7}


def test_ledger_from_stats_partial_keeps_nulls():
    led = ledger_from_stats(_Stats(temp_size_in_bytes=64))
    assert led["temp_bytes"] == 64 and led["argument_bytes"] is None
    assert led["total_bytes"] == 64   # sum over the PRESENT parts only


def test_ledger_from_stats_unknown_shape_is_none():
    assert ledger_from_stats(_Stats()) is None
    assert ledger_from_stats(_Stats(temp_size_in_bytes="big")) is None
    # bool is an int subclass — must not be read as a byte count
    assert ledger_from_stats(_Stats(temp_size_in_bytes=True)) is None


def test_ledger_from_compiled_degrades_to_none():
    class _Raises:
        def memory_analysis(self):
            raise NotImplementedError
    assert ledger_from_compiled(_Raises()) is None
    assert ledger_from_compiled(object()) is None   # no method at all


def test_round_memory_ledger_real_executable():
    """The CPU container's XLA exposes memory_analysis: the tiny round
    must yield a ledger with real temp/argument bytes (the same call
    the dryrun gate and the JitWatcher make)."""
    rt = make_runtime()
    batch, mask, ids = make_batch()
    led = round_memory_ledger(rt, rt.init_state(), ids, batch, mask)
    assert led is not None
    assert led["temp_bytes"] and led["temp_bytes"] > 0
    assert led["argument_bytes"] and led["argument_bytes"] > 0
    assert led["total_bytes"] >= led["temp_bytes"]


# ------------------------------------------------------------- ceilings


def test_check_ceilings_pass_and_fail():
    led = {"temp_bytes": 100, "argument_bytes": 50}
    assert check_ceilings(led, {"temp_bytes": 200}) == []
    assert check_ceilings(led, {"temp_bytes": 50}) != []
    # a NULL measured field fails the gate — absence of evidence must
    # not read as health (the collective-ledger lesson)
    assert check_ceilings({"temp_bytes": None}, {"temp_bytes": 200}) != []
    assert check_ceilings(None, {"temp_bytes": 200}) != []


def test_dense_grad_floor_both_directions():
    d = 100                        # floor = 400 bytes
    assert check_dense_grad_floor({"temp_bytes": 500}, d,
                                  fused=False) == []
    assert check_dense_grad_floor({"temp_bytes": 300}, d,
                                  fused=False) != []   # flip the flag!
    assert check_dense_grad_floor({"temp_bytes": 300}, d,
                                  fused=True) == []
    assert check_dense_grad_floor({"temp_bytes": 500}, d,
                                  fused=True) != []    # fusion regressed
    assert check_dense_grad_floor({"temp_bytes": None}, d) != []
    assert check_dense_grad_floor(None, d) != []


def test_round_ceilings_hold_on_real_round_and_sketch_floor():
    """Unit-scale pin of the dryrun gates: the tiny round sits under its
    own geometry-derived ceilings, and the sketch round's temp buffers
    contain the dense (d,) f32 gradient — the committed baseline
    ROADMAP item 1's encode-fusion must flip."""
    batch, mask, ids = make_batch()
    for kw in (dict(),
               dict(mode="sketch", error_type="virtual")):
        rt = make_runtime(**kw)
        state = rt.init_state()
        led = round_memory_ledger(rt, state, ids, batch, mask)
        assert check_ceilings(
            led, round_memory_ceilings(rt, state, batch)) == []
    # rt is the sketch runtime here
    assert check_dense_grad_floor(led, rt.cfg.grad_size, fused=False) == []


# ------------------------------------------------------------ residency


def test_residency_fields_max_over_devices_and_derivations():
    stats = [{"bytes_in_use": 100, "peak_bytes_in_use": 150,
              "bytes_limit": 1000},
             {"bytes_in_use": 300, "peak_bytes_in_use": 400,
              "bytes_limit": 1000},
             None]
    f = residency_fields(stats, prev_peak=350)
    assert f["live_bytes"] == 300 and f["peak_bytes"] == 400
    assert f["delta_peak_bytes"] == 50
    assert f["fragmentation_bytes"] == 100
    assert f["limit_bytes"] == 1000
    assert f["headroom_frac"] == pytest.approx(0.6)


def test_residency_fields_all_null_without_stats():
    f = residency_fields([None, {}, {"weird": 1}])
    assert all(f[k] is None for k in MEMORY_KEYS)
    # first snapshot: no previous peak -> delta null, not zero
    f = residency_fields([{"peak_bytes_in_use": 10}], prev_peak=None)
    assert f["delta_peak_bytes"] is None and f["peak_bytes"] == 10


def test_residency_fields_derive_per_device_before_aggregating():
    """Heterogeneous devices: fragmentation and headroom must describe a
    REAL device, not pair the max peak with an independently-maxed
    limit. Device 0 has twice the limit and the larger peak; device 1
    is the one about to OOM — its ~1% headroom must win."""
    stats = [{"bytes_in_use": 6 * 2**30, "peak_bytes_in_use": 8 * 2**30,
              "bytes_limit": 16 * 2**30},
             {"bytes_in_use": 7 * 2**30,
              "peak_bytes_in_use": int(7.9 * 2**30),
              "bytes_limit": 8 * 2**30}]
    f = residency_fields(stats)
    assert f["peak_bytes"] == 8 * 2**30          # worst absolute peak
    assert f["limit_bytes"] == 16 * 2**30
    # headroom: min over per-device (limit-peak)/limit = device 1's
    assert f["headroom_frac"] == pytest.approx((8 - 7.9) / 8, abs=1e-6)
    # fragmentation: max over per-device (peak-live), not max-peak minus
    # max-live across different devices (which would be 1 GiB here)
    assert f["fragmentation_bytes"] == 2 * 2**30


class _Dev:
    device_kind = "fake"

    def __init__(self, id=0, stats=None, raises=False, missing=False):
        self.id = id
        self._stats, self._raises = stats, raises
        if missing:
            del self.memory_stats   # type: ignore[attr-defined]

    def __getattr__(self, name):
        raise AttributeError(name)

    def memory_stats(self):
        if self._raises:
            raise RuntimeError("no allocator stats")
        return self._stats


def _dev_no_method(id=0):
    class _Bare:
        device_kind = "fake"
    d = _Bare()
    d.id = id
    return d


def test_residency_tracker_degrades_missing_method_and_empty(capsys):
    """The satellite regression: a backend whose devices lack
    ``memory_stats`` entirely, raise from it, or return an empty dict
    must yield null fields with ONE stderr note — never fake zeros,
    never a crash, never a per-snapshot nag."""
    tr = ResidencyTracker()
    for devs in ([_dev_no_method()],          # missing method
                 [_Dev(raises=True)],         # raising method
                 [_Dev(stats={})]):           # empty dict
        records, derived = tr.snapshot(devs)
        assert records[0]["stats"] is None
        assert all(derived[k] is None for k in MEMORY_KEYS)
    err = capsys.readouterr().err
    assert err.count("memory_stats() unavailable") == 1   # one-time


def test_residency_tracker_partial_stats_no_degradation_note(capsys):
    """A backend exposing memory_stats() WITHOUT peak_bytes_in_use (live
    only) keeps its non-null fields and must NOT be announced as
    'unavailable' — the note is reserved for full absence."""
    tr = ResidencyTracker()
    _, derived = tr.snapshot([_Dev(stats={"bytes_in_use": 123})])
    assert derived["live_bytes"] == 123
    assert derived["peak_bytes"] is None
    assert "memory_stats() unavailable" not in capsys.readouterr().err


def test_residency_tracker_delta_attribution_across_snapshots():
    tr = ResidencyTracker()
    _, d1 = tr.snapshot([_Dev(stats={"bytes_in_use": 50,
                                     "peak_bytes_in_use": 100})])
    assert d1["delta_peak_bytes"] is None     # nothing to diff yet
    _, d2 = tr.snapshot([_Dev(stats={"bytes_in_use": 60,
                                     "peak_bytes_in_use": 180})])
    assert d2["delta_peak_bytes"] == 80       # this phase grew the peak


# --------------------------------------------------------------- schema


def test_memory_ledger_event_schema_round_trip(tmp_path):
    tel = RunTelemetry(str(tmp_path), "test", cfg=None)
    tel.memory_ledger_event("round_step", {
        "temp_bytes": 2_900_000_000, "argument_bytes": 1_200_000_000,
        "output_bytes": 1_200_000_000, "alias_bytes": 1_100_000_000,
        "generated_code_bytes": 4_000_000, "total_bytes": 5_304_000_000})
    tel.memory_event("init")   # real devices; null-degrades on CPU
    tel.close()
    assert validate_file(tel.path) == []
    events = [json.loads(l) for l in open(tel.path)]
    ml = [e for e in events if e["event"] == "memory_ledger"]
    assert len(ml) == 1 and ml[0]["name"] == "round_step"
    assert ml[0]["temp_bytes"] == 2_900_000_000
    mem = [e for e in events if e["event"] == "memory"]
    assert len(mem) == 1
    for k in MEMORY_KEYS:        # enriched fields present (possibly null)
        assert k in mem[0], k


def test_v5_stream_memory_event_stays_valid():
    """FIELDS_SINCE_V6 compatibility: a pre-v6 memory/utilization event
    without the residency/roofline fields validates under its own
    vintage but NOT under v6 — old streams stay readable, new writers
    cannot silently drop the fields."""
    ev = {"event": "memory", "t": 0.0, "seq": 1, "phase": "init",
          "devices": [], "host_rss_bytes": None}
    assert validate_event(ev, version=5) == []
    assert any("live_bytes" in p for p in validate_event(ev, version=6))
    util = {"event": "utilization", "t": 0.0, "seq": 2, "round": 1,
            "rounds": 1, "wall_s": 1.0, "flops_per_round": None,
            "flops_source": None, "device_kind": "cpu",
            "peak_flops": None, "achieved_flops": None, "mfu": None,
            "input_wait_frac": 0.0, "dispatch_frac": 0.0,
            "device_wait_frac": 0.0, "straggler_spread": None}
    assert validate_event(util, version=5) == []
    assert any("bound" in p for p in validate_event(util, version=6))


def test_v6_stream_utilization_stays_valid_without_v7_fields():
    """FIELDS_SINCE_V7 compatibility: a v6 utilization event without
    the mesh-topology fields (n_devices/mesh_shape) validates under
    its own vintage but NOT under v7 — same contract as the v6
    roofline fields one version earlier."""
    from commefficient_tpu.telemetry.utilization import ROOFLINE_KEYS
    util = {"event": "utilization", "t": 0.0, "seq": 2, "round": 1,
            "rounds": 1, "wall_s": 1.0, "flops_per_round": None,
            "flops_source": None, "device_kind": "cpu",
            "peak_flops": None, "achieved_flops": None, "mfu": None,
            "input_wait_frac": 0.0, "dispatch_frac": 0.0,
            "device_wait_frac": 0.0, "straggler_spread": None,
            **{k: None for k in ROOFLINE_KEYS}}
    assert validate_event(util, version=6) == []
    v7_problems = validate_event(util, version=7)
    assert any("n_devices" in p for p in v7_problems)
    assert any("mesh_shape" in p for p in v7_problems)


# -------------------------------------------------- watcher integration


def test_jitwatcher_emits_memory_ledger_into_stream(tmp_path):
    rt = make_runtime()
    tel = RunTelemetry(str(tmp_path), "test", cfg=rt.cfg)
    tel.instrument(rt)
    batch, mask, ids = make_batch()
    rt.round(rt.init_state(), ids, batch, mask, 0.05)
    w = tel.watcher()
    tel.close()
    assert validate_file(tel.path) == []
    events = [json.loads(l) for l in open(tel.path)]
    ml = [e for e in events if e["event"] == "memory_ledger"]
    assert ml and ml[0]["name"] == "round_step"
    assert ml[0]["temp_bytes"] and ml[0]["temp_bytes"] > 0
    # the watcher keeps the latest ledger + cost-analysis bytes for the
    # roofline join and the flight recorder's memory.json
    assert "round_step" in w.memory
    assert w.bytes.get("round_step", 0) > 0


def test_memory_telemetry_is_hlo_invisible():
    """Zero hot-path cost: the whole layer observes compiled artifacts
    and allocator stats from the HOST — lowering the round after taking
    a residency snapshot, a ledger, and under a pinned --peak_hbm_gbps
    yields byte-identical HLO."""
    batch, mask, ids = make_batch()
    rt_a = make_runtime()
    args_a = (rt_a.init_state(), ids, batch, mask,
              jnp.asarray(0.05, jnp.float32), None)
    hlo_a = rt_a._round.lower(*args_a).as_text()
    rt_b = make_runtime(peak_hbm_gbps=819.0)
    ResidencyTracker().snapshot(jax.devices())
    round_memory_ledger(rt_b, rt_b.init_state(), ids, batch, mask)
    args_b = (rt_b.init_state(), ids, batch, mask,
              jnp.asarray(0.05, jnp.float32), None)
    assert rt_b._round.lower(*args_b).as_text() == hlo_a


# --------------------------------------------------------- hbm_pressure


def test_hbm_pressure_rule_fires_on_peak_growth():
    mon = AnomalyMonitor(None, window=16, min_points=8)
    fired = []
    for i in range(20):          # warm steady-state: ~8 GiB +- jitter
        fired += mon.observe("memory", {
            "phase": f"rounds_{i}",
            "peak_bytes": 8e9 + (i % 3) * 1e6})
    assert fired == []           # MiB-scale jitter is quiet
    fired = mon.observe("memory", {"phase": "rounds_20",
                                   "peak_bytes": 12e9})
    assert [f["rule"] for f in fired] == ["hbm_pressure"]
    assert fired[0]["severity"] == "warn"


def test_hbm_pressure_quiet_on_null_peaks_cpu_stream():
    mon = AnomalyMonitor(None, window=16, min_points=8)
    fired = []
    for i in range(30):          # the CPU container: every peak null
        fired += mon.observe("memory", {"phase": f"rounds_{i}",
                                        "peak_bytes": None})
    assert fired == []


# -------------------------------------------------- flight recorder


def _tiny_state():
    from commefficient_tpu.core.state import FedState
    return FedState(ps_weights=jnp.arange(6, dtype=jnp.float32),
                    Vvelocity=jnp.zeros(6), Verror=jnp.zeros(6),
                    step=jnp.asarray(3, jnp.int32),
                    rng=jnp.zeros(2, jnp.uint32))


def test_flight_recorder_bundle_includes_memory_json(tmp_path):
    """The satellite: the postmortem bundle ships the residency timeline
    + the per-executable ledgers as memory.json (the separately
    ring-buffered snapshots survive round/span traffic rotation)."""
    rt = make_runtime()
    tel = RunTelemetry(str(tmp_path), "test", cfg=rt.cfg)
    tel.instrument(rt)
    batch, mask, ids = make_batch()
    rt.round(rt.init_state(), ids, batch, mask, 0.05)
    tel.memory_event("rounds_1")
    tel.memory_event("checkpoint_1")
    rec = FlightRecorder(str(tmp_path), tel)
    out = rec.record(_tiny_state(), {"rule": "hbm_pressure", "round": 1})
    assert out is not None
    mem = json.load(open(os.path.join(rec.path, "memory.json")))
    assert [e["phase"] for e in mem["residency"]] == \
        ["rounds_1", "checkpoint_1"]
    assert "round_step" in mem["ledgers"]
    assert mem["ledgers"]["round_step"]["temp_bytes"] > 0
    tel.close()


def test_recent_memory_ring_survives_round_traffic(tmp_path):
    tel = RunTelemetry(str(tmp_path), "test", cfg=None)
    tel.memory_event("init")
    for i in range(300):         # rotate the MAIN ring completely
        tel.event("round", round=i, epoch=1, lr=0.1, loss=2.0, acc=0.5,
                  n_valid=4.0, download_bytes=None, upload_bytes=None,
                  host_s=0.0, dispatch_s=0.0, device_s=0.0)
    assert all(e["event"] != "memory" for e in tel.recent)
    assert [e["phase"] for e in tel.recent_memory] == ["init"]
    tel.close()


# ------------------------------------------------------------- teleview


def _teleview():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "teleview", os.path.join(os.path.dirname(__file__), os.pardir,
                                 "scripts", "teleview.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_teleview_memory_literals_match_package():
    """teleview runs jax-free off literal fallbacks of the key tuples —
    pin them to the canonical values so they cannot drift."""
    src = open(os.path.join(os.path.dirname(__file__), os.pardir,
                            "scripts", "teleview.py")).read()
    for name, canon in (("MEMORY_KEYS", MEMORY_KEYS),
                        ("MEMORY_LEDGER_KEYS", MEMORY_LEDGER_KEYS),
                        ("ROOFLINE_KEYS", ROOFLINE_KEYS)):
        block = re.search(rf"\n    {name} = \((.*?)\)", src,
                          re.S).group(1)
        assert tuple(re.findall(r'"([a-z_0-9]+)"', block)) == canon, name


def _write_mem_stream(path, temp_bytes=1000, bw_frac_bytes=5e8):
    tel = RunTelemetry(str(path), "test", cfg=None)
    tel.memory_ledger_event("round_step", {
        "temp_bytes": temp_bytes, "argument_bytes": 200,
        "output_bytes": 300, "alias_bytes": 50,
        "generated_code_bytes": 7, "total_bytes": temp_bytes + 507})
    tel.memory_event("init")
    emit_from_totals(
        tel, rnd=1, rounds=1, wall_s=1.0, host_s=0.1, dispatch_s=0.1,
        device_s=0.5, flops_per_round=1e10, flops_source="analytic",
        device_kind="fake", peak_flops=1e14,
        bytes_per_round=bw_frac_bytes, bytes_source="cost_analysis",
        peak_hbm_gbps=1.0)    # 1 GB/s peak: bw_frac = bytes / 1e9
    tel.write_summary(aborted=False, n_rounds=1)
    tel.close()
    assert validate_file(tel.path) == []
    return tel.path


def test_teleview_memory_subcommand(tmp_path, capsys):
    tv = _teleview()
    p = _write_mem_stream(tmp_path / "a")
    assert tv.main(["memory", p]) == 0
    out = capsys.readouterr().out
    assert "per-executable byte inventory" in out
    assert "round_step" in out
    assert "residency timeline" in out
    assert "roofline" in out and "bandwidth" in out


def test_teleview_diff_fails_on_temp_bytes_growth(tmp_path, capsys):
    tv = _teleview()
    a = _write_mem_stream(tmp_path / "a", temp_bytes=1000)
    b = _write_mem_stream(tmp_path / "b", temp_bytes=2000)
    assert tv.main(["diff", a, b]) == 1
    assert "temp bytes" in capsys.readouterr().out
    assert tv.main(["diff", a, b, "--temp_bytes_growth", "3.0"]) == 0


def test_teleview_diff_fails_on_bw_frac_drop(tmp_path, capsys):
    tv = _teleview()
    a = _write_mem_stream(tmp_path / "a", bw_frac_bytes=5e8)   # 0.5
    b = _write_mem_stream(tmp_path / "b", bw_frac_bytes=2e8)   # 0.2
    assert tv.main(["diff", a, b]) == 1
    assert "bw_frac" in capsys.readouterr().out
    assert tv.main(["diff", a, b, "--bw_frac_drop", "0.5"]) == 0


def test_teleview_timeline_hbm_counter_track(tmp_path):
    tv = _teleview()
    tel = RunTelemetry(str(tmp_path / "a"), "test", cfg=None)
    # synthetic residency snapshot with live numbers (the CPU container
    # reports none, so drive build_trace with a hand-built event)
    tel.event("memory", phase="rounds_1", devices=[],
              host_rss_bytes=None, live_bytes=2 * 2**30,
              peak_bytes=3 * 2**30, delta_peak_bytes=None,
              fragmentation_bytes=2**30, limit_bytes=16 * 2**30,
              headroom_frac=0.8125)
    tel.close()
    trace = tv.build_trace([json.loads(l) for l in open(tel.path)])
    counters = [e for e in trace["traceEvents"]
                if e.get("ph") == "C"]
    names = {e["name"] for e in counters}
    assert "hbm_live_gib" in names and "hbm_peak_gib" in names
    live = [e for e in counters if e["name"] == "hbm_live_gib"][0]
    assert live["args"]["hbm_live_gib"] == pytest.approx(2.0)
