"""Nonfinite recovery (PR 7, --nonfinite_action quarantine): ledger
strike/backoff/re-admission/ejection semantics, the sampler-side
blocked-slot masking, local-state row protection, and the end-to-end
driver contract — a NaN-injecting client under quarantine COMPLETES the
run (defense events in the stream, finite final loss) where the same
run under the default abort stops."""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu import cv_train
from commefficient_tpu.core.quarantine import QuarantineLedger
from commefficient_tpu.data.fed_sampler import Round, mask_blocked
from commefficient_tpu.telemetry import RunTelemetry, validate_file
from commefficient_tpu.utils import TableLogger
from tests.test_telemetry import StubDS, make_batch, make_runtime, \
    read_events

W = 4


# --------------------------------------------------------------- ledger


def test_ledger_strike_backoff_readmission_ejection():
    led = QuarantineLedger(backoff=3, strikes=2)
    # round 1: client 5 nonfinite -> strike 1, benched rounds 2..4
    struck = led.observe(1, [4, 5, 6, 7], [True, False, True, True])
    assert struck == [5]
    assert led.blocked(2) == {5} and led.blocked(4) == {5}
    assert led.blocked(5) == set()        # re-admitted after the backoff
    assert led.quarantined(2) == 1 and led.quarantined(5) == 0
    # round 5 retry fails -> strike 2 -> permanent ejection
    struck = led.observe(5, [5, 6], [False, True])
    assert struck == [5]
    assert 5 in led.ejected
    assert led.blocked(100) == {5}        # ejection never expires
    assert led.quarantined(6) == 0        # ejected, not "benched"
    # further strikes on an ejected client are not double-counted
    assert led.observe(6, [5], [False]) == []
    assert led.total_strikes == 2


def test_ledger_finite_rounds_never_strike():
    led = QuarantineLedger()
    for rnd in range(1, 50):
        assert led.observe(rnd, [0, 1, 2], [True, True, True]) == []
    assert led.total_strikes == 0 and not led.blocked(50)


def test_ledger_snapshot_and_digest():
    led = QuarantineLedger(backoff=8, strikes=3)
    snap = led.snapshot(1)
    assert snap == {"quarantined": 0, "ejected": 0,
                    "quarantine_ids_digest": None}
    led.observe(1, [3, 9], [False, False])
    snap = led.snapshot(2)
    assert snap["quarantined"] == 2 and snap["ejected"] == 0
    digest = snap["quarantine_ids_digest"]
    assert digest.startswith("2:") and len(digest) == 2 + 12
    # digest is stable for the same blocked set
    assert led.snapshot(3)["quarantine_ids_digest"] == digest


def test_ledger_validation():
    with pytest.raises(ValueError):
        QuarantineLedger(backoff=0)
    with pytest.raises(ValueError):
        QuarantineLedger(strikes=0)


def test_mask_blocked_masks_slots_never_mutates():
    rnd = Round(np.asarray([4, 5, 6, 7]),
                np.zeros((4, 3), np.int64), np.ones((4, 3), bool))
    out = mask_blocked(rnd, {5, 7})
    np.testing.assert_array_equal(out.mask[:, 0],
                                  [True, False, True, False])
    assert rnd.mask.all()                 # original untouched
    assert mask_blocked(rnd, set()) is rnd
    assert mask_blocked(rnd, {99}) is rnd


# ----------------------------------------------------- runtime semantics


def test_quarantine_preserves_local_state_rows():
    """local_topk with local error rows: a struck client's persistent
    row must keep its PREVIOUS value, not absorb the nonfinite round."""
    rt = make_runtime(mode="local_topk", error_type="local",
                      local_momentum=0.9, k=5,
                      adversary="nan", adversary_frac=0.4, seed=3,
                      nonfinite_action="quarantine", fused_clients=False)
    adv = np.asarray(rt._adv_universe)
    assert adv.any() and not adv.all()
    batch, mask, ids = make_batch()       # ids = arange(W)
    state = rt.init_state()
    err0 = np.asarray(state.client_errors)
    state, m = rt.round(state, ids, batch, mask, 0.05)
    fin = np.asarray(m["client_finite"])
    np.testing.assert_array_equal(fin, ~adv[:W])
    errs = np.asarray(state.client_errors)
    vels = np.asarray(state.client_velocities)
    assert np.isfinite(errs).all() and np.isfinite(vels).all()
    # struck clients kept their (zero-initialized) rows; finite clients
    # actually accumulated something
    for i in range(W):
        if not fin[i]:
            np.testing.assert_array_equal(errs[i], err0[i])
        else:
            assert np.abs(errs[i]).sum() > 0


def test_fully_nonfinite_round_still_aborts():
    rt = make_runtime(mode="uncompressed", error_type="none",
                      adversary="nan", adversary_frac=1.0,
                      nonfinite_action="quarantine", fused_clients=False)
    batch, mask, ids = make_batch()
    state, m = rt.round(rt.init_state(), ids, batch, mask, 0.05)
    assert int(state.nan_round) >= 0
    assert not np.asarray(m["client_finite"]).any()


def test_all_live_nonfinite_with_benched_slots_still_aborts():
    """A benched/masked placeholder slot uploads finite zeros — it must
    not vouch for a round whose every DATA-CARRYING upload diverged
    ("fully-nonfinite" counts live clients, not slots)."""
    rt = make_runtime(mode="uncompressed", error_type="none",
                      adversary="nan", adversary_frac=1.0,
                      nonfinite_action="quarantine", fused_clients=False)
    batch, mask, ids = make_batch()
    half = np.asarray(mask).copy()
    half[: W // 2] = False                 # half the slots carry no data
    state, m = rt.round(rt.init_state(), ids, batch,
                        jnp.asarray(half), 0.05)
    assert int(state.nan_round) >= 0
    # the inverse: a round of ONLY zero-data slots has no nonfinite
    # evidence — it must NOT abort as diverged (the driver's
    # quarantine_exhausted path owns the no-data-left case)
    state2, _ = rt.round(rt.init_state(), ids, batch,
                         jnp.zeros_like(mask), 0.05)
    assert int(state2.nan_round) < 0


# ------------------------------------------------------- driver contract


def _nan_runtime(**kw):
    """A runtime whose universe provably contains >= 1 nan adversary
    among StubDS's 8 clients."""
    rt = make_runtime(dataset_name="SYNTH", telemetry_every=1,
                      adversary="nan", adversary_frac=0.3, **kw)
    assert np.asarray(rt._adv_universe).any()
    return rt


def test_driver_quarantine_completes_where_abort_stops(tmp_path):
    """The acceptance criterion, end to end through cv_train.train: the
    same nan-injecting population under --nonfinite_action quarantine
    completes the run (schema-valid v5 defense events in the stream,
    finite final loss) where the default abort stops it."""
    rt = _nan_runtime(nonfinite_action="quarantine",
                      quarantine_backoff=2, quarantine_strikes=2)
    tel = RunTelemetry(str(tmp_path / "q"), "cv_train", cfg=rt.cfg)
    tel.instrument(rt)
    cfg = rt.cfg.replace(num_epochs=1.0, pivot_epoch=0.5)
    state, summary = cv_train.train(cfg, rt, rt.init_state(), StubDS(),
                                    StubDS(), loggers=(TableLogger(),),
                                    telemetry=tel)
    tel.close()
    assert summary is not None            # the run COMPLETED
    assert np.isfinite(summary["train_loss"])
    assert np.isfinite(np.asarray(state.ps_weights)).all()
    assert validate_file(tel.path) == []
    events = read_events(tel.path)
    defs = [e for e in events if e["event"] == "defense"]
    assert defs, "no defense events in the quarantine stream"
    assert any((e.get("nonfinite_clients") or 0) > 0 for e in defs)
    assert any(e.get("quarantined", 0) > 0 or e.get("ejected", 0) > 0
               for e in defs)
    assert events[-1]["event"] == "summary" and not events[-1]["aborted"]

    # the SAME population under the default abort stops the run
    rt2 = _nan_runtime()
    tel2 = RunTelemetry(str(tmp_path / "a"), "cv_train", cfg=rt2.cfg)
    tel2.instrument(rt2)
    cfg2 = rt2.cfg.replace(num_epochs=1.0, pivot_epoch=0.5)
    state2, summary2 = cv_train.train(cfg2, rt2, rt2.init_state(),
                                      StubDS(), StubDS(),
                                      loggers=(TableLogger(),),
                                      telemetry=tel2)
    tel2.close()
    assert summary2 is None               # aborted
    kinds2 = [e["event"] for e in read_events(tel2.path)]
    assert "nan_abort" in kinds2


def test_driver_aborts_when_every_client_ejected(tmp_path, capsys):
    """A fleet that ejects its ENTIRE universe has no data source left:
    the run must terminate (aborted summary, critical alert) instead of
    silently burning the budget on fully-masked rounds reporting
    loss 0."""
    rt = make_runtime(dataset_name="SYNTH", telemetry_every=1,
                      adversary="nan", adversary_frac=1.0,
                      nonfinite_action="quarantine",
                      quarantine_backoff=1, quarantine_strikes=1)
    assert np.asarray(rt._adv_universe).all()
    tel = RunTelemetry(str(tmp_path), "cv_train", cfg=rt.cfg)
    tel.instrument(rt)
    cfg = rt.cfg.replace(num_epochs=3.0, pivot_epoch=1.0, do_test=False)
    state, summary = cv_train.train(cfg, rt, rt.init_state(), StubDS(),
                                    StubDS(), loggers=(TableLogger(),),
                                    telemetry=tel)
    tel.close()
    assert summary is None                # terminated, not "successful"
    assert "QUARANTINE ABORT" in capsys.readouterr().out
    events = read_events(tel.path)
    alerts = [e for e in events if e["event"] == "alert"]
    assert any(a["rule"] == "quarantine_exhausted"
               and a["severity"] == "critical" for a in alerts)
    assert events[-1]["event"] == "summary" and events[-1]["aborted"]


def test_driver_benches_struck_client_next_rounds(tmp_path, capsys):
    """After a strike the client's later slots are masked out: its
    nonfinite count drops to zero while it sits out (the ledger is
    wired into the dispatch path, not just the stream)."""
    rt = _nan_runtime(nonfinite_action="quarantine",
                      quarantine_backoff=50, quarantine_strikes=3)
    tel = RunTelemetry(str(tmp_path), "cv_train", cfg=rt.cfg)
    tel.instrument(rt)
    # several epochs so the adversary is re-sampled after its strike
    cfg = rt.cfg.replace(num_epochs=3.0, pivot_epoch=1.0, do_test=False)
    state, summary = cv_train.train(cfg, rt, rt.init_state(), StubDS(),
                                    StubDS(), loggers=(TableLogger(),),
                                    telemetry=tel)
    tel.close()
    assert summary is not None
    assert "QUARANTINE: client" in capsys.readouterr().err
    events = read_events(tel.path)
    defs = [e for e in events if e["event"] == "defense"]
    struck_rounds = [e["round"] for e in defs
                     if (e.get("nonfinite_clients") or 0) > 0]
    benched = [e for e in defs if e.get("quarantined", 0) > 0]
    assert struck_rounds and benched
    # with a 50-round backoff the client strikes ONCE and stays benched:
    # every later defense record shows zero fresh nonfinites
    after = [e for e in defs if e["round"] > struck_rounds[0]]
    assert after and all((e.get("nonfinite_clients") or 0) == 0
                         for e in after)
    # the injected count reports ACTUAL injections: a benched hostile
    # client's slot is masked and uploads nothing, so once the hostile
    # population sits out the count must read zero — counting sampled
    # hostile ids would fake ongoing injection in the stream
    assert all(sum(e["injected"].values()) == 0 for e in after)
