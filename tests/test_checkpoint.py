"""Checkpoint round-trip and resume-exactness.

The resume contract: a run checkpointed at round t and restored must produce
bit-identical subsequent state to the uninterrupted run (the reference
cannot do this at all — SURVEY.md §5)."""

import jax.numpy as jnp
import numpy as np

from commefficient_tpu.checkpoint import CheckpointManager, load_state, save_state
from commefficient_tpu.config import FedConfig
from commefficient_tpu.core import FedRuntime
from tests.test_parallel import make_batch, make_cfg, quad_loss


def build_runtime(**kw):
    cfg = make_cfg(mode="true_topk", error_type="virtual", k=5, **kw)
    params = {"w": jnp.asarray(
        np.random.RandomState(0).randn(6, 3), jnp.float32)}
    return FedRuntime(cfg, params, quad_loss, num_clients=16)


def test_save_load_roundtrip(tmp_path):
    rt = build_runtime()
    state = rt.init_state()
    path = str(tmp_path / "ck")
    save_state(path, state, meta={"note": "x"})
    loaded = load_state(path)
    for name in ["ps_weights", "Vvelocity", "Verror", "step", "rng"]:
        np.testing.assert_array_equal(np.asarray(getattr(state, name)),
                                      np.asarray(getattr(loaded, name)))
    # optional leaves that were None stay None
    assert loaded.client_velocities is None


def test_resume_exactness(tmp_path):
    rt = build_runtime()
    batch, mask, cids = make_batch(3)
    lr = 0.05

    # uninterrupted: 4 rounds
    s = rt.init_state()
    for _ in range(4):
        s, _ = rt.round(s, cids, batch, mask, lr)

    # interrupted: 2 rounds, checkpoint, restore, 2 more
    s2 = rt.init_state()
    for _ in range(2):
        s2, _ = rt.round(s2, cids, batch, mask, lr)
    mgr = CheckpointManager(str(tmp_path / "ckpts"), keep_last=2)
    mgr.save(s2, epoch=1)
    restored, meta = mgr.restore_latest()
    assert meta["epoch"] == 1
    for _ in range(2):
        restored, _ = rt.round(restored, cids, batch, mask, lr)

    np.testing.assert_array_equal(np.asarray(s.ps_weights),
                                  np.asarray(restored.ps_weights))
    np.testing.assert_array_equal(np.asarray(s.Verror),
                                  np.asarray(restored.Verror))
    assert int(restored.step) == 4


def test_rotation(tmp_path):
    rt = build_runtime()
    state = rt.init_state()
    mgr = CheckpointManager(str(tmp_path / "r"), keep_last=2)
    for e in range(5):
        mgr.save(state, epoch=e)
    assert mgr.epochs() == [3, 4]
    assert mgr.latest() == 4


def test_fingerprint_guard(tmp_path):
    """A checkpoint written under one parameter layout must refuse to resume
    under another (the flat ps_weights vector would unravel into the wrong
    weights — e.g. flipping GPT-2's scan_layers)."""
    import jax.numpy as jnp
    import pytest
    from commefficient_tpu.checkpoint import params_fingerprint

    rt = build_runtime()
    state = rt.init_state()
    mgr = CheckpointManager(str(tmp_path / "fp"))
    layout_a = {"w": jnp.zeros((3, 4))}
    layout_b = {"w0": jnp.zeros((4,)), "w1": jnp.zeros((3, 4))}
    fp_a, fp_b = params_fingerprint(layout_a), params_fingerprint(layout_b)
    assert fp_a != fp_b
    mgr.default_meta = {"params_fingerprint": fp_a}
    mgr.save(state, epoch=0)
    # same layout: fine
    restored, _ = mgr.restore_latest(expect_fingerprint=fp_a)
    assert restored is not None
    # different layout: refused
    with pytest.raises(ValueError, match="different parameter layout"):
        mgr.restore_latest(expect_fingerprint=fp_b)
    # legacy checkpoints without a fingerprint are REFUSED by default (the
    # layout cannot be verified, and the guard exists precisely for
    # pre-fingerprint checkpoints) ...
    mgr2 = CheckpointManager(str(tmp_path / "fp2"))
    mgr2.save(state, epoch=0)
    with pytest.raises(ValueError, match="no params fingerprint"):
        mgr2.restore_latest(expect_fingerprint=fp_a)
    # ... unless the caller explicitly opts in (--resume_unverified)
    restored, _ = mgr2.restore_latest(expect_fingerprint=fp_a,
                                      allow_missing_fingerprint=True)
    assert restored is not None
    # callers that pass no expectation are unaffected
    restored, _ = mgr2.restore_latest()
    assert restored is not None


def test_legacy_checkpoint_migration(tmp_path):
    """Checkpoints written before the nan_round field / mesh padding exist
    must still resume: missing nan_round defaults to -1, and dense server
    leaves re-pad to the restoring runtime's d_pad (cross-topology
    resume)."""
    rt = build_runtime()
    state = rt.init_state()
    path = str(tmp_path / "old")
    save_state(path, state)
    # forge an old-format checkpoint: strip nan_round from the npz
    with np.load(path + ".npz") as z:
        arrays = {k: z[k] for k in z.files if k != "nan_round"}
    with open(path + ".npz", "wb") as f:
        np.savez(f, **arrays)

    loaded = load_state(path)
    assert int(loaded.nan_round) == -1

    # cross-topology: restore the single-device (d=19) state into a mesh
    # runtime whose d_pad=24
    from commefficient_tpu.parallel import make_mesh
    mesh = make_mesh((8,), ("clients",))
    cfg = make_cfg(mode="true_topk", error_type="virtual", k=5)
    params = {"w": jnp.asarray(
        np.random.RandomState(0).randn(6, 3), jnp.float32)}
    rt_mesh = FedRuntime(cfg, params, quad_loss, num_clients=16, mesh=mesh)
    migrated = load_state(path, sharding=rt_mesh._state_sharding,
                          d_pad=rt_mesh.d_pad)
    assert migrated.ps_weights.shape == (rt_mesh.d_pad,)
    assert int(migrated.nan_round) == -1
    np.testing.assert_array_equal(
        np.asarray(migrated.coord_last_update[rt_mesh.cfg.grad_size:]), -1)
    # and the migrated state actually runs a round
    batch, mask, cids = make_batch(3)
    s2, _ = rt_mesh.round(migrated, cids, batch, mask, 0.05)
    assert np.isfinite(np.asarray(s2.ps_weights)).all()
    # the reverse direction: mesh checkpoint restored at true d
    save_state(str(tmp_path / "mesh"), s2)
    back = load_state(str(tmp_path / "mesh"), d_pad=rt.cfg.grad_size)
    assert back.ps_weights.shape == (rt.cfg.grad_size,)


def test_client_row_migration(tmp_path):
    """Per-client rows pad/truncate to the restoring runtime's (possibly
    mesh-padded) client count: a single-device checkpoint with
    num_clients=18 resumes on an 8-device mesh that pads to 24."""
    from commefficient_tpu.parallel import make_mesh

    cfg = make_cfg(mode="local_topk", error_type="local", k=4,
                   local_momentum=0.9, do_topk_down=True)
    params = {"w": jnp.asarray(
        np.random.RandomState(0).randn(6, 3), jnp.float32)}
    rt18 = FedRuntime(cfg, params, quad_loss, num_clients=18)
    s = rt18.init_state()
    batch, mask, cids = make_batch(3)
    s, _ = rt18.round(s, cids, batch, mask, 0.05)
    save_state(str(tmp_path / "c18"), s)

    mesh = make_mesh((8,), ("clients",))
    rt_mesh = FedRuntime(cfg, params, quad_loss, num_clients=18, mesh=mesh)
    assert rt_mesh.num_clients == 24
    mig = load_state(str(tmp_path / "c18"),
                     sharding=rt_mesh._state_sharding,
                     d_pad=rt_mesh.d_pad, num_clients=24)
    assert mig.client_errors.shape[0] == 24
    # old rows preserved, new rows are fresh clients
    np.testing.assert_array_equal(np.asarray(mig.client_errors[:18]),
                                  np.asarray(s.client_errors))
    np.testing.assert_array_equal(np.asarray(mig.client_errors[18:]), 0.0)
    np.testing.assert_array_equal(
        np.asarray(mig.client_weights[18:]),
        np.broadcast_to(np.asarray(s.ps_weights[:18]), (6, 18)))
    s2, _ = rt_mesh.round(mig, cids, batch, mask, 0.05)
    assert np.isfinite(np.asarray(s2.ps_weights)).all()
