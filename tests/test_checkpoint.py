"""Checkpoint round-trip and resume-exactness.

The resume contract: a run checkpointed at round t and restored must produce
bit-identical subsequent state to the uninterrupted run (the reference
cannot do this at all — SURVEY.md §5)."""

import jax.numpy as jnp
import numpy as np

from commefficient_tpu.checkpoint import CheckpointManager, load_state, save_state
from commefficient_tpu.config import FedConfig
from commefficient_tpu.core import FedRuntime
from tests.test_parallel import make_batch, make_cfg, quad_loss


def build_runtime(**kw):
    cfg = make_cfg(mode="true_topk", error_type="virtual", k=5, **kw)
    params = {"w": jnp.asarray(
        np.random.RandomState(0).randn(6, 3), jnp.float32)}
    return FedRuntime(cfg, params, quad_loss, num_clients=16)


def test_save_load_roundtrip(tmp_path):
    rt = build_runtime()
    state = rt.init_state()
    path = str(tmp_path / "ck")
    save_state(path, state, meta={"note": "x"})
    loaded = load_state(path)
    for name in ["ps_weights", "Vvelocity", "Verror", "step", "rng"]:
        np.testing.assert_array_equal(np.asarray(getattr(state, name)),
                                      np.asarray(getattr(loaded, name)))
    # optional leaves that were None stay None
    assert loaded.client_velocities is None


def test_resume_exactness(tmp_path):
    rt = build_runtime()
    batch, mask, cids = make_batch(3)
    lr = 0.05

    # uninterrupted: 4 rounds
    s = rt.init_state()
    for _ in range(4):
        s, _ = rt.round(s, cids, batch, mask, lr)

    # interrupted: 2 rounds, checkpoint, restore, 2 more
    s2 = rt.init_state()
    for _ in range(2):
        s2, _ = rt.round(s2, cids, batch, mask, lr)
    mgr = CheckpointManager(str(tmp_path / "ckpts"), keep_last=2)
    mgr.save(s2, epoch=1)
    restored, meta = mgr.restore_latest()
    assert meta["epoch"] == 1
    for _ in range(2):
        restored, _ = rt.round(restored, cids, batch, mask, lr)

    np.testing.assert_array_equal(np.asarray(s.ps_weights),
                                  np.asarray(restored.ps_weights))
    np.testing.assert_array_equal(np.asarray(s.Verror),
                                  np.asarray(restored.Verror))
    assert int(restored.step) == 4


def test_rotation(tmp_path):
    rt = build_runtime()
    state = rt.init_state()
    mgr = CheckpointManager(str(tmp_path / "r"), keep_last=2)
    for e in range(5):
        mgr.save(state, epoch=e)
    assert mgr.epochs() == [3, 4]
    assert mgr.latest() == 4


def test_fingerprint_guard(tmp_path):
    """A checkpoint written under one parameter layout must refuse to resume
    under another (the flat ps_weights vector would unravel into the wrong
    weights — e.g. flipping GPT-2's scan_layers)."""
    import jax.numpy as jnp
    import pytest
    from commefficient_tpu.checkpoint import params_fingerprint

    rt = build_runtime()
    state = rt.init_state()
    mgr = CheckpointManager(str(tmp_path / "fp"))
    layout_a = {"w": jnp.zeros((3, 4))}
    layout_b = {"w0": jnp.zeros((4,)), "w1": jnp.zeros((3, 4))}
    fp_a, fp_b = params_fingerprint(layout_a), params_fingerprint(layout_b)
    assert fp_a != fp_b
    mgr.default_meta = {"params_fingerprint": fp_a}
    mgr.save(state, epoch=0)
    # same layout: fine
    restored, _ = mgr.restore_latest(expect_fingerprint=fp_a)
    assert restored is not None
    # different layout: refused
    with pytest.raises(ValueError, match="different parameter layout"):
        mgr.restore_latest(expect_fingerprint=fp_b)
    # legacy checkpoints without a fingerprint are REFUSED by default (the
    # layout cannot be verified, and the guard exists precisely for
    # pre-fingerprint checkpoints) ...
    mgr2 = CheckpointManager(str(tmp_path / "fp2"))
    mgr2.save(state, epoch=0)
    with pytest.raises(ValueError, match="no params fingerprint"):
        mgr2.restore_latest(expect_fingerprint=fp_a)
    # ... unless the caller explicitly opts in (--resume_unverified)
    restored, _ = mgr2.restore_latest(expect_fingerprint=fp_a,
                                      allow_missing_fingerprint=True)
    assert restored is not None
    # callers that pass no expectation are unaffected
    restored, _ = mgr2.restore_latest()
    assert restored is not None


def test_legacy_checkpoint_migration(tmp_path):
    """Checkpoints written before the nan_round field / mesh padding exist
    must still resume: missing nan_round defaults to -1, and dense server
    leaves re-pad to the restoring runtime's d_pad (cross-topology
    resume)."""
    rt = build_runtime()
    state = rt.init_state()
    path = str(tmp_path / "old")
    save_state(path, state)
    # forge an old-format checkpoint: strip nan_round from the npz
    with np.load(path + ".npz") as z:
        arrays = {k: z[k] for k in z.files if k != "nan_round"}
    with open(path + ".npz", "wb") as f:
        np.savez(f, **arrays)

    loaded = load_state(path)
    assert int(loaded.nan_round) == -1

    # cross-topology: restore the single-device (d=19) state into a mesh
    # runtime whose d_pad=24
    from commefficient_tpu.parallel import make_mesh
    mesh = make_mesh((8,), ("clients",))
    cfg = make_cfg(mode="true_topk", error_type="virtual", k=5)
    params = {"w": jnp.asarray(
        np.random.RandomState(0).randn(6, 3), jnp.float32)}
    rt_mesh = FedRuntime(cfg, params, quad_loss, num_clients=16, mesh=mesh)
    migrated = load_state(path, sharding=rt_mesh._state_sharding,
                          d_pad=rt_mesh.d_pad)
    assert migrated.ps_weights.shape == (rt_mesh.d_pad,)
    assert int(migrated.nan_round) == -1
    np.testing.assert_array_equal(
        np.asarray(migrated.coord_last_update[rt_mesh.cfg.grad_size:]), -1)
    # and the migrated state actually runs a round
    batch, mask, cids = make_batch(3)
    s2, _ = rt_mesh.round(migrated, cids, batch, mask, 0.05)
    assert np.isfinite(np.asarray(s2.ps_weights)).all()
    # the reverse direction: mesh checkpoint restored at true d
    save_state(str(tmp_path / "mesh"), s2)
    back = load_state(str(tmp_path / "mesh"), d_pad=rt.cfg.grad_size)
    assert back.ps_weights.shape == (rt.cfg.grad_size,)


def test_client_row_migration(tmp_path):
    """Per-client rows pad/truncate to the restoring runtime's (possibly
    mesh-padded) client count: a single-device checkpoint with
    num_clients=18 resumes on an 8-device mesh that pads to 24."""
    from commefficient_tpu.parallel import make_mesh

    cfg = make_cfg(mode="local_topk", error_type="local", k=4,
                   local_momentum=0.9, do_topk_down=True)
    params = {"w": jnp.asarray(
        np.random.RandomState(0).randn(6, 3), jnp.float32)}
    rt18 = FedRuntime(cfg, params, quad_loss, num_clients=18)
    s = rt18.init_state()
    batch, mask, cids = make_batch(3)
    s, _ = rt18.round(s, cids, batch, mask, 0.05)
    save_state(str(tmp_path / "c18"), s)

    mesh = make_mesh((8,), ("clients",))
    rt_mesh = FedRuntime(cfg, params, quad_loss, num_clients=18, mesh=mesh)
    assert rt_mesh.num_clients == 24
    mig = load_state(str(tmp_path / "c18"),
                     sharding=rt_mesh._state_sharding,
                     d_pad=rt_mesh.d_pad, num_clients=24,
                     d_row_pad=rt_mesh.d_row_pad)
    assert mig.client_errors.shape == (24, rt_mesh.d_row_pad)
    d = rt18.cfg.grad_size
    # old rows preserved (at true d; mesh rows carry zero column padding),
    # new rows are fresh clients
    np.testing.assert_array_equal(np.asarray(mig.client_errors[:18, :d]),
                                  np.asarray(s.client_errors))
    np.testing.assert_array_equal(np.asarray(mig.client_errors[:, d:]), 0.0)
    np.testing.assert_array_equal(np.asarray(mig.client_errors[18:]), 0.0)
    np.testing.assert_array_equal(
        np.asarray(mig.client_weights[18:]),
        np.broadcast_to(np.asarray(s.ps_weights[:18]), (6, 18)))
    s2, _ = rt_mesh.round(mig, cids, batch, mask, 0.05)
    assert np.isfinite(np.asarray(s2.ps_weights)).all()

    # and the reverse direction: the mesh checkpoint (rows at d_row_pad=24)
    # restores back into a single-device runtime at true d=18 — the
    # sliced-off columns are structural zero padding
    save_state(str(tmp_path / "mesh24"), s2)
    back = load_state(str(tmp_path / "mesh24"), d_pad=d, num_clients=18,
                      d_row_pad=d)
    assert back.client_errors.shape == (18, d)
    s3, _ = rt18.round(back, cids, batch, mask, 0.05)
    assert np.isfinite(np.asarray(s3.ps_weights)).all()


def test_truncation_guards(tmp_path):
    """Dropping LIVE client state must raise; dropping padding must not
    (ADVICE r2: load_state silently truncated per-client rows)."""
    import pytest

    cfg = make_cfg(mode="local_topk", error_type="local", k=4,
                   local_momentum=0.9)
    params = {"w": jnp.asarray(
        np.random.RandomState(0).randn(6, 3), jnp.float32)}
    rt = FedRuntime(cfg, params, quad_loss, num_clients=16)
    s = rt.init_state()
    batch, mask, cids = make_batch(3)
    s, _ = rt.round(s, cids, batch, mask, 0.05)  # clients 0..14 now live
    path = str(tmp_path / "live")
    save_state(path, s)
    # truncating below a participated client's row loses live error state
    with pytest.raises(ValueError, match="non-zero velocity/error"):
        load_state(path, num_clients=8)
    # narrowing rows to a shorter d loses live columns
    with pytest.raises(ValueError, match="sliced-off columns"):
        load_state(path, d_row_pad=10)
    # truncating only never-touched padding rows is fine
    ok = load_state(path, num_clients=15)
    assert ok.client_errors.shape[0] == 15


def test_scale_guard_and_sharded_save(tmp_path):
    """States above the host-materialization threshold refuse a plain save
    with a clear message (VERDICT r2 weak #6: no silent OOM path); the
    sharded escape hatch writes per-shard and round-trips exactly."""
    import pytest

    rt = build_runtime()
    state = rt.init_state()
    path = str(tmp_path / "big")
    with pytest.raises(ValueError, match="sharded=True"):
        save_state(path, state, max_host_bytes=16)
    # the sharded layout round-trips bit-exactly (single- or multi-shard)
    save_state(path, state, sharded=True)
    loaded = load_state(path)
    for name in ["ps_weights", "Vvelocity", "Verror", "step", "rng",
                 "nan_round"]:
        np.testing.assert_array_equal(np.asarray(getattr(state, name)),
                                      np.asarray(getattr(loaded, name)))

    # sharded save of a genuinely sharded mesh state
    from commefficient_tpu.parallel import make_mesh
    mesh = make_mesh((8,), ("clients",))
    cfg = make_cfg(mode="local_topk", error_type="local", k=4,
                   local_momentum=0.9)
    params = {"w": jnp.asarray(
        np.random.RandomState(0).randn(6, 3), jnp.float32)}
    rtm = FedRuntime(cfg, params, quad_loss, num_clients=16, mesh=mesh)
    sm = rtm.init_state()
    batch, mask, cids = make_batch(3)
    sm, _ = rtm.round(sm, cids, batch, mask, 0.05)
    pm = str(tmp_path / "mesh_sharded")
    save_state(pm, sm, sharded=True)
    plain = str(tmp_path / "mesh_plain")
    save_state(plain, sm)
    a = load_state(pm)
    b = load_state(plain)
    for name in ["ps_weights", "Vvelocity", "Verror", "client_errors",
                 "client_velocities", "step", "rng"]:
        np.testing.assert_array_equal(np.asarray(getattr(a, name)),
                                      np.asarray(getattr(b, name)))
    # same-topology sharded restore streams shard->device (no full-host
    # materialization); must be value-identical and correctly sharded
    c = load_state(pm, sharding=rtm._state_sharding,
                   d_pad=rtm.d_pad, num_clients=rtm.num_clients,
                   d_row_pad=rtm.d_row_pad)
    for name in ["ps_weights", "Vvelocity", "Verror", "client_errors",
                 "client_velocities", "step", "rng"]:
        np.testing.assert_array_equal(np.asarray(getattr(c, name)),
                                      np.asarray(getattr(b, name)))
    assert c.client_errors.sharding.is_equivalent_to(
        rtm._state_sharding.client_errors, c.client_errors.ndim)
    # and it must still run a round
    s3, _ = rtm.round(c, cids, batch, mask, 0.05)
    assert np.isfinite(np.asarray(s3.ps_weights)).all()


def test_sketch_gen_checked_before_materializing(tmp_path):
    """A sketch-generation mismatch must be diagnosed from the META alone
    — BEFORE load_state touches the (possibly shape-incompatible) arrays.
    Pinned by corrupting the npz: if the check ran after materialization,
    these restores would die on the corrupt file instead of raising the
    explanatory ValueError."""
    import pytest

    rt = build_runtime()
    mgr = CheckpointManager(str(tmp_path / "ck"))
    mgr.default_meta = {"sketch_gen": "circ-v1-2x32-42"}
    mgr.save(rt.init_state(), epoch=1)
    npz = mgr._path(1) + ".npz"
    with open(npz, "wb") as f:
        f.write(b"not an npz at all")

    # same-layout marker mismatch: explanatory refuse, no array touched
    with pytest.raises(ValueError, match="sketch generation"):
        mgr.restore_latest(expect_sketch_gen="circ-v1-2x64-42")
    # cross-layout (table checkpoint under sketch_server_state=dense):
    # the layout explanation, and --resume_unverified cannot override
    for ok in (False, True):
        with pytest.raises(ValueError, match="server state"):
            mgr.restore_latest(
                expect_sketch_gen="circ-v1-2x32-42-densestate",
                sketch_mismatch_ok=ok)
    # pre-marker checkpoint (no sketch_gen in meta): unverifiable wording
    mgr2 = CheckpointManager(str(tmp_path / "ck2"))
    mgr2.save(rt.init_state(), epoch=1)
    with open(mgr2._path(1) + ".npz", "wb") as f:
        f.write(b"junk")
    with pytest.raises(ValueError, match="predates sketch-generation"):
        mgr2.restore_latest(expect_sketch_gen="circ-v1-2x32-42")
    # non-sketch restoring runs (expect None) skip the check entirely and
    # only then hit the corrupt file
    with pytest.raises(Exception, match="(?i)(zip|pickle|magic|file)"):
        mgr2.restore_latest(expect_sketch_gen=None)


def test_sketch_gen_mismatch_ok_loads_state(tmp_path):
    """--resume_unverified (sketch_mismatch_ok) still LOADS a same-layout
    mismatched checkpoint; the driver then discards the tables."""
    rt = build_runtime()
    mgr = CheckpointManager(str(tmp_path / "ck"))
    mgr.default_meta = {"sketch_gen": "circ-v1-2x32-42"}
    s = rt.init_state()
    mgr.save(s, epoch=2)
    restored, meta = mgr.restore_latest(
        expect_sketch_gen="circ-aligned1024-2x32-43",
        sketch_mismatch_ok=True)
    assert restored is not None and meta["sketch_gen"] == "circ-v1-2x32-42"
    np.testing.assert_array_equal(np.asarray(restored.ps_weights),
                                  np.asarray(s.ps_weights))
