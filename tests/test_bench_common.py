"""The bench resilience contract (BENCH_r02 post-mortem): transient
remote-compile tunnel failures are retried; real bugs propagate
immediately."""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from bench_common import is_transient, with_retries


def test_r02_failure_message_is_transient():
    # the exact message that killed BENCH_r02
    e = RuntimeError(
        "INTERNAL: http://127.0.0.1:8093/remote_compile: read body: "
        "response body closed before all bytes were read")
    assert is_transient(e)


def test_real_bug_is_not_transient():
    assert not is_transient(TypeError("unsupported operand type(s)"))
    assert not is_transient(ValueError("mode 'sketch' requires num_cols"))


def test_with_retries_recovers_from_transient(monkeypatch):
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise RuntimeError("remote_compile: read body: response body "
                               "closed before all bytes were read")
        return "ok"

    monkeypatch.setattr("bench_common.time.sleep", lambda s: None)
    assert with_retries(flaky, desc="test", tries=4) == "ok"
    assert len(calls) == 3


def test_with_retries_propagates_real_bug_immediately(monkeypatch):
    calls = []

    def buggy():
        calls.append(1)
        raise TypeError("boom")

    monkeypatch.setattr("bench_common.time.sleep", lambda s: None)
    with pytest.raises(TypeError):
        with_retries(buggy, desc="test", tries=4)
    assert len(calls) == 1


def test_with_retries_exhausts_and_raises(monkeypatch):
    calls = []

    def always_flaky():
        calls.append(1)
        raise RuntimeError("UNAVAILABLE: connection reset by peer")

    monkeypatch.setattr("bench_common.time.sleep", lambda s: None)
    with pytest.raises(RuntimeError):
        with_retries(always_flaky, desc="test", tries=3)
    assert len(calls) == 3
