"""Per-client population observability (telemetry/clients.py + the
core/client.py stat outputs + the runtime threading): device-side
quantile summaries against numpy references, DP clip-saturation
visibility, the fused-path NaN contract, the zero-hot-path-cost gating
(HLO identity under --no_telemetry), the participation ledger, the
schema round-trip of the new ``client_stats`` event, and the teleview
``clients`` view."""

import json
import os
import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.config import FedConfig
from commefficient_tpu.core import FedRuntime
from commefficient_tpu.telemetry import (RunTelemetry, validate_event,
                                         validate_file)
from commefficient_tpu.telemetry.clients import (CLIENT_GRAD_KEYS,
                                                 CLIENT_STAT_KEYS,
                                                 ParticipationLedger,
                                                 client_stats_to_host,
                                                 quantiles_ordered,
                                                 summarize_per_client)

W, B, D_IN, D_OUT = 4, 4, 6, 3
D = D_IN * D_OUT


def loss_fn(params, batch, mask):
    pred = batch["x"] @ params["w"]
    m = mask.astype(jnp.float32)
    denom = jnp.maximum(m.sum(), 1.0)
    err = ((pred - batch["y"]) ** 2).sum(axis=1)
    loss = (err * m).sum() / denom
    return loss, (loss,)


def make_runtime(**kw):
    cfg_kw = dict(mode="uncompressed", error_type="none",
                  local_momentum=0.0, virtual_momentum=0.9,
                  weight_decay=0.0, num_workers=W, local_batch_size=B,
                  track_bytes=True, num_clients=8, num_results_train=2,
                  num_results_val=2, k=5, num_rows=2, num_cols=32,
                  exact_num_cols=True)
    cfg_kw.update(kw)
    params = {"w": jnp.asarray(
        np.random.RandomState(0).randn(D_IN, D_OUT), jnp.float32)}
    return FedRuntime(FedConfig(**cfg_kw), params, loss_fn, num_clients=8)


def make_batch(seed=1):
    rng = np.random.RandomState(seed)
    batch = {"x": jnp.asarray(rng.randn(W, B, D_IN), jnp.float32),
             "y": jnp.asarray(rng.randn(W, B, D_OUT), jnp.float32)}
    return batch, jnp.ones((W, B), bool), jnp.arange(W, dtype=jnp.int32)


def fetch(metrics, client_ids):
    return client_stats_to_host(metrics["client_stats"], client_ids)


# ------------------------------------------------- device-side quantiles


def test_summarize_matches_numpy_reference():
    rng = np.random.RandomState(3)
    vals = {"a": rng.randn(16).astype(np.float32),
            "b": rng.rand(16).astype(np.float32)}
    n_valid = np.ones(16, np.float32)
    out = jax.jit(lambda v, n: summarize_per_client(v, n))(
        {k: jnp.asarray(v) for k, v in vals.items()},
        jnp.asarray(n_valid))
    for key, v in vals.items():
        np.testing.assert_allclose(
            np.asarray(out[key]["q"]),
            np.percentile(v, [5, 25, 50, 75, 95]).astype(np.float32),
            rtol=1e-5)
        assert float(out[key]["max"]) == pytest.approx(float(v.max()))
        assert float(out[key]["mean"]) == pytest.approx(float(v.mean()),
                                                        rel=1e-5)
        assert int(out[key]["argmax"]) == int(v.argmax())


def test_summarize_masks_invalid_and_nan_slots():
    vals = {"a": jnp.asarray([1.0, 100.0, 2.0, jnp.nan])}
    n_valid = jnp.asarray([1.0, 0.0, 1.0, 1.0])   # slot 1 fully padded
    out = summarize_per_client(vals, n_valid)
    # the padded slot's 100.0 and the NaN slot are both excluded
    assert float(out["a"]["max"]) == pytest.approx(2.0)
    assert int(out["a"]["argmax"]) == 2
    host = client_stats_to_host({"a": out["a"]}, np.array([7, 8, 9, 10]))
    assert host["a"]["argmax_client"] == 9
    assert quantiles_ordered(host["a"])


def test_all_nan_stat_serializes_null():
    out = summarize_per_client({"a": jnp.full((4,), jnp.nan)},
                               jnp.ones((4,)))
    host = client_stats_to_host({"a": out["a"]}, np.arange(4))
    assert all(host["a"][f] is None
               for f in ("p5", "p50", "p95", "max", "mean"))
    assert host["a"]["argmax_client"] is None


# ----------------------------------------------------- runtime threading


def test_round_client_stats_match_per_client_results():
    """The vmap path: loss quantiles must be exactly the quantiles of
    the per-client results vector the metrics already carry, and the
    grad/tx stats must be finite and ordered."""
    rt = make_runtime(fused_clients=False)
    assert not rt._fused and rt._client_grad_stats
    batch, mask, ids = make_batch()
    ids = jnp.asarray([5, 2, 7, 0], jnp.int32)   # non-trivial id mapping
    state, metrics = rt.round(rt.init_state(), ids, batch, mask, 0.05)
    host = fetch(metrics, ids)
    assert set(host) == set(CLIENT_STAT_KEYS)
    losses = np.asarray(metrics["results"][0])
    np.testing.assert_allclose(
        [host["loss"]["p5"], host["loss"]["p50"], host["loss"]["p95"]],
        np.percentile(losses, [5, 50, 95]), rtol=1e-5)
    assert host["loss"]["argmax_client"] == int(
        np.asarray(ids)[losses.argmax()])
    for key in ("grad_norm_pre", "grad_norm_post", "tx_norm",
                "upload_bytes", "download_bytes"):
        assert host[key]["p50"] is not None, key
        assert quantiles_ordered(host[key]), (key, host[key])
    # uncompressed, no clip configured: saturation is NaN, not 0
    assert host["clip_frac"]["mean"] is None
    assert host["upload_bytes"]["p50"] == pytest.approx(4.0 * D)
    # round 1 downloads are 0 (nothing updated yet); after round 1's
    # dense update touched every coordinate, round 2's participants
    # each download the full vector
    assert host["download_bytes"]["max"] == 0.0
    state, metrics = rt.round(state, ids, batch, mask, 0.05)
    host2 = fetch(metrics, ids)
    assert host2["download_bytes"]["p50"] == pytest.approx(4.0 * D)


def test_fused_path_keeps_loss_stats_drops_grad_stats():
    """The fused fast path never materializes per-client gradients —
    its grad-stat quantiles are NaN (null), never fake zeros, while the
    loss/bytes population stats stay live."""
    rt = make_runtime(mode="sketch", error_type="virtual")
    assert rt._fused and rt._client_stats
    batch, mask, ids = make_batch()
    state, metrics = rt.round(rt.init_state(), ids, batch, mask, 0.05)
    host = fetch(metrics, ids)
    assert host["loss"]["p50"] is not None
    for key in CLIENT_GRAD_KEYS:
        assert host[key]["p50"] is None, key
        assert host[key]["mean"] is None, key


def test_dp_clip_saturation_visible():
    """A DP clip that binds for every client must read clip_frac mean
    1.0 with grad_norm_post == l2_norm_clip; a clip far above the
    gradient scale must read 0.0."""
    batch, mask, ids = make_batch()
    tight = make_runtime(do_dp=True, l2_norm_clip=1e-3,
                         noise_multiplier=0.0)
    _, metrics = tight.round(tight.init_state(), ids, batch, mask, 0.05)
    host = fetch(metrics, ids)
    assert host["clip_frac"]["mean"] == pytest.approx(1.0)
    assert host["grad_norm_post"]["max"] == pytest.approx(1e-3, rel=1e-3)
    assert host["grad_norm_pre"]["p50"] > 1e-2
    loose = make_runtime(do_dp=True, l2_norm_clip=1e6,
                         noise_multiplier=0.0)
    _, metrics = loose.round(loose.init_state(), ids, batch, mask, 0.05)
    host = fetch(metrics, ids)
    assert host["clip_frac"]["mean"] == pytest.approx(0.0)


def test_fedavg_tx_norm_only():
    rt = make_runtime(mode="fedavg", local_batch_size=-1,
                      max_client_batch=B, local_momentum=0.0)
    batch, mask, ids = make_batch()
    _, metrics = rt.round(rt.init_state(), ids, batch, mask, 0.05)
    host = fetch(metrics, ids)
    assert host["tx_norm"]["p50"] is not None
    assert host["grad_norm_pre"]["p50"] is None
    assert host["loss"]["p50"] is not None


# --------------------------------------------------- zero-hot-path cost


def test_no_client_stats_flag_drops_them():
    rt = make_runtime(client_stats=False)
    batch, mask, ids = make_batch()
    _, metrics = rt.round(rt.init_state(), ids, batch, mask, 0.05)
    assert metrics["client_stats"] is None


def test_no_telemetry_drops_client_stats_too():
    rt = make_runtime(telemetry=False)
    assert not rt._client_stats
    batch, mask, ids = make_batch()
    _, metrics = rt.round(rt.init_state(), ids, batch, mask, 0.05)
    assert metrics["client_stats"] is None


def test_no_telemetry_compiles_stats_out_identically():
    """--no_telemetry must leave the round's HLO byte-identical to a
    round that never had the per-client machinery — the no-op tracer
    identity argument, applied to the compiled graph."""
    rt_off = make_runtime(telemetry=False, fused_clients=False)
    rt_base = make_runtime(signals=False, client_stats=False,
                           fused_clients=False)
    batch, mask, ids = make_batch()
    args = (rt_off.init_state(), ids, batch, mask,
            jnp.asarray(0.05, jnp.float32), None)
    hlo_off = rt_off._round.lower(*args).as_text()
    hlo_base = rt_base._round.lower(*args).as_text()
    assert hlo_off == hlo_base


# ------------------------------------------------- participation ledger


def test_participation_ledger_counts_coverage_staleness():
    led = ParticipationLedger(8)
    assert led.snapshot(0)["coverage"] == 0.0
    led.observe(1, [0, 1, 2, 3], [4, 4, 2, 4])
    led.observe(2, [0, 1, 4, 5], [4, 4, 4, 4])
    snap = led.snapshot(4)
    assert snap["distinct_clients"] == 6
    assert snap["coverage"] == pytest.approx(6 / 8)
    # clients 0/1 saw 8 samples, 2/3 saw 2/4, 4/5 saw 4
    assert snap["counts_max"] == 8.0
    # last rounds: 0,1,4,5 -> 2 (stale 2); 2,3 -> 1 (stale 3)
    assert snap["staleness_max"] == 3.0
    assert snap["staleness_p50"] == 2.0
    ev = {"event": "client_stats", "t": 0.0, "seq": 0, "round": 4,
          "n_participants": 4, "quantiles": {}, **snap}
    assert validate_event(ev) == []


# ------------------------------------------------- schema + event wiring


def test_client_stats_event_roundtrip(tmp_path):
    rt = make_runtime(fused_clients=False)
    tel = RunTelemetry(str(tmp_path), "test", cfg=rt.cfg)
    batch, mask, ids = make_batch()
    _, metrics = rt.round(rt.init_state(), ids, batch, mask, 0.05)
    led = ParticipationLedger(8)
    led.observe(1, np.asarray(ids), np.asarray(mask).sum(axis=1))
    tel.client_stats_event(rnd=1, n_participants=W,
                           quantiles=fetch(metrics, ids),
                           participation=led.snapshot(1))
    tel.write_summary(aborted=False, n_rounds=1)
    tel.close()
    assert validate_file(tel.path) == []
    events = [json.loads(line) for line in open(tel.path)]
    cs = [e for e in events if e["event"] == "client_stats"]
    assert len(cs) == 1
    assert cs[0]["coverage"] == pytest.approx(0.5)
    assert quantiles_ordered(cs[0]["quantiles"]["loss"])


# ---------------------------------------------------------- teleview


def _teleview():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "teleview", os.path.join(os.path.dirname(__file__), os.pardir,
                                 "scripts", "teleview.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_teleview_fallback_client_keys_match_package():
    src = open(os.path.join(os.path.dirname(__file__), os.pardir,
                            "scripts", "teleview.py")).read()
    block = re.search(r"CLIENT_STAT_KEYS = \((.*?)\)", src, re.S).group(1)
    assert tuple(re.findall(r'"([a-z_0-9]+)"', block)) == CLIENT_STAT_KEYS


def test_teleview_clients_view(tmp_path, capsys):
    rt = make_runtime(fused_clients=False)
    tel = RunTelemetry(str(tmp_path), "test", cfg=rt.cfg)
    batch, mask, ids = make_batch()
    state = rt.init_state()
    led = ParticipationLedger(8)
    for rnd in (1, 2):
        state, metrics = rt.round(state, ids, batch, mask, 0.05)
        led.observe(rnd, np.asarray(ids), np.asarray(mask).sum(axis=1))
        tel.client_stats_event(rnd=rnd, n_participants=W,
                               quantiles=fetch(metrics, ids),
                               participation=led.snapshot(rnd))
    tel.close()
    tv = _teleview()
    assert tv.main(["clients", tel.path]) == 0
    out = capsys.readouterr().out
    assert "coverage" in out and "loss" in out
    assert "grad_norm_pre" in out
    # an empty stream (pre-PR-4 vintage) is a note, not an error
    empty = tmp_path / "old" / "telemetry.jsonl"
    os.makedirs(empty.parent, exist_ok=True)
    empty.write_text('{"event": "manifest", "t": 0, "seq": 0}\n')
    assert tv.main(["clients", str(empty)]) == 0


def test_teleview_truncated_trailing_line(tmp_path, capsys):
    """A crashed writer's stream ends mid-line: teleview must read the
    intact prefix and only note the truncation, never raise."""
    p = tmp_path / "telemetry.jsonl"
    p.write_text('{"event": "manifest", "t": 0, "seq": 0, "schema": 3}\n'
                 '{"event": "round", "t": 1, "seq": 1, "round": 1, "los')
    tv = _teleview()
    events = tv.load_events(str(p))
    assert [e["event"] for e in events] == ["manifest"]
    assert "truncated" in capsys.readouterr().err
