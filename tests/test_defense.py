"""Adversarial client injection + robust aggregation (PR 7):
numpy-reference norm-clip and trimmed-mean in dense AND table space,
clip-then-sketch == sketch-then-clip for the linear case, the rolling-
median threshold semantics, deterministic adversary fates, per-kind
injection effects, HLO byte-identity with the robustness flags off,
async-path parity, the schema-v5 defense event round-trip, and the
teleview DEFENSE_KEYS jax-free literal pin."""

import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.config import FedConfig
from commefficient_tpu.core import FedRuntime
from commefficient_tpu.core.client import (flip_labels, inject_adversary,
                                           quarantine_zero)
from commefficient_tpu.core.server import robust_aggregate
from commefficient_tpu.data.scenarios import (AdversaryPlan, CohortFate,
                                              StragglerScenario,
                                              make_adversary)
from commefficient_tpu.ops.sketch import make_sketch_impl
from commefficient_tpu.telemetry import RunTelemetry, validate_file
from commefficient_tpu.telemetry.schema import EVENT_FIELDS
from tests.test_telemetry import make_batch, make_runtime

W = 4


def _teleview():
    spec = importlib.util.spec_from_file_location(
        "teleview", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts", "teleview.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------- numpy references


def _np_normclip(tx, n_valid, mult, ref=np.nan):
    """Reference norm-clip: per-datum norms, median threshold, rescale."""
    tx = np.asarray(tx, np.float64)
    n = np.asarray(n_valid, np.float64)
    denom = np.maximum(n, 1.0)
    flat = tx.reshape(tx.shape[0], -1)
    norms = np.sqrt((flat * flat).sum(axis=1)) / denom
    usable = (n > 0) & np.isfinite(norms)
    med = np.nanmedian(np.where(usable, norms, np.nan))
    thresh = mult * (med if np.isnan(ref) else ref)
    factors = np.minimum(1.0, thresh / np.maximum(norms, 1e-12))
    factors = np.where(usable, factors, 1.0)
    agg = (tx * factors.reshape((-1,) + (1,) * (tx.ndim - 1))).sum(axis=0)
    return agg, med, thresh, factors


def _np_trim(tx, n_valid, trim_frac):
    tx = np.asarray(tx, np.float64)
    n = np.asarray(n_valid, np.float64)
    denom = np.maximum(n, 1.0)
    u = tx / denom.reshape((-1,) + (1,) * (tx.ndim - 1))
    t = int(trim_frac * tx.shape[0])
    s = np.sort(u, axis=0)
    core = s[t: tx.shape[0] - t] if t else s
    return core.mean(axis=0) * n.sum()


def test_normclip_matches_numpy_reference_dense():
    rng = np.random.RandomState(0)
    tx = rng.randn(6, 40).astype(np.float32) * rng.uniform(1, 5, (6, 1))
    n = np.asarray([8, 8, 4, 8, 8, 8], np.float32)
    cfg = FedConfig(defense="normclip", defense_clip_mult=2.0)
    agg, med, stats = robust_aggregate(cfg, jnp.asarray(tx),
                                       jnp.asarray(n),
                                       ref_thresh=jnp.float32(np.nan))
    ref_agg, ref_med, ref_thresh, factors = _np_normclip(tx, n, 2.0)
    np.testing.assert_allclose(np.asarray(agg), ref_agg, rtol=1e-5)
    assert float(med) == pytest.approx(ref_med, rel=1e-6)
    assert float(stats["clip_thresh"]) == pytest.approx(ref_thresh,
                                                        rel=1e-6)
    assert float(stats["clip_frac"]) == pytest.approx(
        ((factors < 1.0).sum()) / 6)
    # removed mass: l2 over the clipped clients' removed norms
    denom = np.maximum(n, 1.0)
    norms = np.sqrt((tx.reshape(6, -1) ** 2).sum(1)) / denom
    removed = np.sqrt((((1 - factors) * norms * denom) ** 2).sum())
    assert float(stats["clipped_mass"]) == pytest.approx(removed, rel=1e-4)


def test_normclip_table_space_matches_numpy_reference():
    """Norm-clip on per-client (r, c) sketch tables: Frobenius norms."""
    rng = np.random.RandomState(1)
    tx = rng.randn(5, 3, 16).astype(np.float32)
    n = np.full(5, 4.0, np.float32)
    cfg = FedConfig(defense="normclip", defense_clip_mult=1.5)
    agg, med, stats = robust_aggregate(cfg, jnp.asarray(tx),
                                       jnp.asarray(n),
                                       ref_thresh=jnp.float32(np.nan))
    ref_agg, _, _, _ = _np_normclip(tx, n, 1.5)
    assert agg.shape == (3, 16)
    np.testing.assert_allclose(np.asarray(agg), ref_agg, rtol=1e-5)


def test_normclip_uses_rolling_reference_not_current_round():
    """With a warm ref_thresh the boosted round's own (contaminated)
    median must NOT set the threshold — that is the whole point of the
    rolling reference."""
    rng = np.random.RandomState(2)
    tx = rng.randn(4, 30).astype(np.float32)
    tx[1] *= 1000.0                       # boosted client
    n = np.full(4, 8.0, np.float32)
    cfg = FedConfig(defense="normclip", defense_clip_mult=3.0)
    ref = jnp.float32(1.0)                # healthy historical median
    agg, med, stats = robust_aggregate(cfg, jnp.asarray(tx),
                                       jnp.asarray(n), ref_thresh=ref)
    assert float(stats["clip_thresh"]) == pytest.approx(3.0)
    ref_agg, _, _, _ = _np_normclip(tx, n, 3.0, ref=1.0)
    np.testing.assert_allclose(np.asarray(agg), ref_agg, rtol=1e-5)
    # the boosted client was crushed back to the threshold
    assert float(stats["clip_frac"]) >= 0.25


def test_trim_matches_numpy_reference_dense_and_table():
    rng = np.random.RandomState(3)
    for shape in ((8, 50), (8, 2, 12)):
        tx = rng.randn(*shape).astype(np.float32)
        n = rng.randint(1, 9, 8).astype(np.float32)
        cfg = FedConfig(defense="trim", defense_trim_frac=0.25)
        agg, med, stats = robust_aggregate(cfg, jnp.asarray(tx),
                                           jnp.asarray(n))
        assert med is None
        np.testing.assert_allclose(np.asarray(agg), _np_trim(tx, n, 0.25),
                                   rtol=1e-5)
        assert float(stats["trim_frac"]) == pytest.approx(2 * 2 / 8)


def test_trim_drops_coordinate_outliers():
    """Concentrated honest updates + sign-flipped minority: the trimmed
    mean recovers the honest mean while the plain mean is dragged."""
    rng = np.random.RandomState(4)
    honest = np.ones((6, 20), np.float32) + 0.01 * rng.randn(6, 20)
    flipped = -np.ones((2, 20), np.float32)
    tx = np.concatenate([honest, flipped]).astype(np.float32)
    n = np.ones(8, np.float32)
    cfg = FedConfig(defense="trim", defense_trim_frac=0.25)
    agg, _, _ = robust_aggregate(cfg, jnp.asarray(tx), jnp.asarray(n))
    trimmed_mean = np.asarray(agg) / 8.0
    assert np.all(np.abs(trimmed_mean - 1.0) < 0.05)
    plain_mean = tx.sum(0) / 8.0
    assert np.all(plain_mean < 0.6)       # the mean was dragged


def test_trim_excludes_zero_datum_slots():
    """A quarantine-benched / participation-masked slot carries NO vote:
    its 0/1 = 0 placeholder update must not dilute the trimmed mean
    (with 2 live clients in an 8-slot round the defended update would
    otherwise shrink 4x toward zero)."""
    tx = np.zeros((8, 10), np.float32)
    tx[0] = 1.0
    tx[1] = 1.0
    n = np.zeros(8, np.float32)
    n[:2] = 1.0                           # only two slots participated
    cfg = FedConfig(defense="trim", defense_trim_frac=0.25)
    agg, _, stats = robust_aggregate(cfg, jnp.asarray(tx), jnp.asarray(n))
    # agg / n_total must equal the live clients' trimmed mean, 1.0
    np.testing.assert_allclose(np.asarray(agg) / n.sum(),
                               np.ones(10), rtol=1e-6)
    # trim count follows the LIVE cohort: floor(0.25 * 2) = 0
    assert float(stats["trim_frac"]) == 0.0
    # and with enough live clients the trim still drops live extremes
    n2 = np.ones(8, np.float32)
    n2[6:] = 0.0                          # 6 live, 2 benched
    tx2 = np.ones((8, 4), np.float32)
    tx2[0] = 100.0                        # a live outlier
    tx2[6:] = 0.0
    agg2, _, stats2 = robust_aggregate(cfg, jnp.asarray(tx2),
                                       jnp.asarray(n2))
    np.testing.assert_allclose(np.asarray(agg2) / n2.sum(),
                               np.ones(4), rtol=1e-6)
    assert float(stats2["trim_frac"]) == pytest.approx(2 / 6)


def test_clip_commutes_with_linear_sketch():
    """An l2 clip is a rescaling, and the sketch is linear:
    encode(f * g) == f * encode(g) — clipping before the encode equals
    clipping the table by the same factor (the transmitted-space
    soundness claim of --defense normclip for sketch mode)."""
    d, c, r = 256, 64, 3
    cs = make_sketch_impl("circ", d, c, r, 2, seed=7)
    g = jnp.asarray(np.random.RandomState(5).randn(d), jnp.float32)
    norm = float(jnp.linalg.norm(g))
    f = min(1.0, 0.3 * norm / norm)       # a real clip factor < 1
    f = 0.37
    enc_clip = cs.encode(f * g)
    clip_enc = f * cs.encode(g)
    np.testing.assert_allclose(np.asarray(enc_clip), np.asarray(clip_enc),
                               rtol=1e-5, atol=1e-6)
    # and the table Frobenius norm scales by exactly the same factor,
    # so a threshold computed in table space clips the same clients
    assert float(jnp.linalg.norm(enc_clip)) == pytest.approx(
        f * float(jnp.linalg.norm(cs.encode(g))), rel=1e-5)


# ------------------------------------------------- adversary fates


def test_adversary_plan_deterministic_and_frac_bounded():
    a = AdversaryPlan("signflip", 0.25, seed=3)
    b = AdversaryPlan("signflip", 0.25, seed=3)
    u1, u2 = a.universe_mask(64), b.universe_mask(64)
    np.testing.assert_array_equal(u1, u2)
    # independent of universe size / query order (keyed per client)
    np.testing.assert_array_equal(u1[:16], a.universe_mask(16))
    np.testing.assert_array_equal(a.slot_mask([5, 3, 5]),
                                  u1[[5, 3, 5]])
    assert 0 < u1.mean() < 0.6            # roughly frac, never all/none
    assert AdversaryPlan("signflip", 0.25, seed=4).universe_mask(
        64).tolist() != u1.tolist()
    assert not AdversaryPlan("none", 0.5).universe_mask(8).any()


def test_adversary_plan_validation():
    with pytest.raises(ValueError, match="unknown adversary kind"):
        AdversaryPlan("backdoor", 0.1)
    with pytest.raises(ValueError, match="frac"):
        AdversaryPlan("scale", 1.5)
    with pytest.raises(ValueError, match="scale"):
        AdversaryPlan("scale", 0.5, scale=0.0)


def test_cohort_fate_carries_adversary_assignment():
    plan = AdversaryPlan("nan", 0.5, seed=9)
    sc = StragglerScenario("none", seed=9, dropout=0.0, adversary=plan)
    mask = np.ones((4, 2), bool)
    ids = np.asarray([1, 2, 3, 4])
    fate = sc.fate(0, mask, client_ids=ids)
    np.testing.assert_array_equal(fate.adversary, plan.slot_mask(ids))
    # without ids (or without a plan) the field stays None
    assert sc.fate(0, mask).adversary is None
    assert StragglerScenario("none", seed=9, dropout=0.1).fate(
        0, mask, client_ids=ids).adversary is None
    assert CohortFate(0.0, False, mask).adversary is None


def test_async_aggregator_rejects_mismatched_adversary_plans():
    """The scenario's CohortFate.adversary annotation and the runtime's
    baked universe mask must describe the SAME assignment — a seed
    mismatch fails fast instead of silently diverging."""
    from commefficient_tpu.core.async_agg import AsyncAggregator

    kw = dict(mode="uncompressed", error_type="none",
              adversary="signflip", adversary_frac=0.5,
              fused_clients=False, async_agg=True)
    rt = make_runtime(**kw)
    bad = StragglerScenario(
        "none", seed=rt.cfg.seed, dropout=0.1,
        adversary=AdversaryPlan("signflip", 0.5, seed=rt.cfg.seed + 1))
    with pytest.raises(ValueError, match="disagrees"):
        AsyncAggregator(rt, scenario=bad)
    good = StragglerScenario(
        "none", seed=rt.cfg.seed, dropout=0.1,
        adversary=make_adversary(rt.cfg))
    AsyncAggregator(rt, scenario=good)    # matching plans accepted


def test_make_adversary_from_config():
    assert make_adversary(FedConfig()) is None
    plan = make_adversary(FedConfig(adversary="scale", adversary_frac=0.3,
                                    adversary_scale=7.0, seed=11))
    assert plan.kind == "scale" and plan.scale == 7.0 and plan.seed == 11


def test_config_validation():
    with pytest.raises(ValueError, match="injects nothing"):
        FedConfig(adversary="scale")
    with pytest.raises(ValueError, match="adversary_frac"):
        FedConfig(adversary_frac=0.5)
    with pytest.raises(ValueError, match="adversary_frac"):
        FedConfig(adversary="scale", adversary_frac=-0.1)
    with pytest.raises(ValueError, match="adversary_scale"):
        FedConfig(adversary="scale", adversary_frac=0.5,
                  adversary_scale=-1.0)
    with pytest.raises(ValueError, match="defense_trim_frac"):
        FedConfig(defense="trim", defense_trim_frac=0.5)
    with pytest.raises(ValueError, match="defense_clip_mult"):
        FedConfig(defense="normclip", defense_clip_mult=0.0)
    with pytest.raises(ValueError, match="quarantine_backoff"):
        FedConfig(nonfinite_action="quarantine", quarantine_backoff=0)
    with pytest.raises(ValueError, match="quarantine_strikes"):
        FedConfig(nonfinite_action="quarantine", quarantine_strikes=0)


# ------------------------------------------------- injection helpers


def test_inject_adversary_kinds_numpy_reference():
    rng = np.random.RandomState(6)
    tx = jnp.asarray(rng.randn(4, 10), jnp.float32)
    adv = jnp.asarray([False, True, False, True])
    rngs = jax.random.split(jax.random.PRNGKey(0), 4)
    n = jnp.full((4,), 2.0)

    sf = inject_adversary(FedConfig(adversary="signflip",
                                    adversary_frac=0.5), tx, adv, rngs, n)
    np.testing.assert_array_equal(np.asarray(sf[0]), np.asarray(tx[0]))
    np.testing.assert_array_equal(np.asarray(sf[1]), -np.asarray(tx[1]))

    sc = inject_adversary(FedConfig(adversary="scale", adversary_frac=0.5,
                                    adversary_scale=5.0), tx, adv, rngs, n)
    np.testing.assert_allclose(np.asarray(sc[3]), 5.0 * np.asarray(tx[3]),
                               rtol=1e-6)

    nz = inject_adversary(FedConfig(adversary="noise", adversary_frac=0.5,
                                    adversary_scale=2.0), tx, adv, rngs, n)
    np.testing.assert_array_equal(np.asarray(nz[0]), np.asarray(tx[0]))
    assert not np.allclose(np.asarray(nz[1]), np.asarray(tx[1]))
    # deterministic: same keys -> same noise
    nz2 = inject_adversary(FedConfig(adversary="noise",
                                     adversary_frac=0.5,
                                     adversary_scale=2.0), tx, adv, rngs, n)
    np.testing.assert_array_equal(np.asarray(nz), np.asarray(nz2))

    na = inject_adversary(FedConfig(adversary="nan", adversary_frac=0.5),
                          tx, adv, rngs, n)
    assert np.isnan(np.asarray(na[1])).all()
    assert np.isfinite(np.asarray(na[2])).all()


def test_inject_skips_non_participating_slots():
    """A masked-out slot (zero valid datums) uploads nothing — injecting
    into its zero placeholder would fabricate quarantine strikes for a
    client that never participated."""
    tx = jnp.zeros((3, 8))
    adv = jnp.asarray([True, True, False])
    rngs = jax.random.split(jax.random.PRNGKey(1), 3)
    n = jnp.asarray([4.0, 0.0, 4.0])      # slot 1 is benched/masked
    out = inject_adversary(FedConfig(adversary="nan", adversary_frac=0.5),
                           tx, adv, rngs, n)
    assert np.isnan(np.asarray(out[0])).all()
    assert np.isfinite(np.asarray(out[1])).all()


def test_flip_labels():
    batch = {"x": jnp.zeros((3, 4, 2)),
             "target": jnp.asarray([[0, 1, 9, 4]] * 3)}
    adv = jnp.asarray([False, True, False])
    out = flip_labels(batch, adv, 10)
    np.testing.assert_array_equal(np.asarray(out["target"][0]),
                                  [0, 1, 9, 4])
    np.testing.assert_array_equal(np.asarray(out["target"][1]),
                                  [9, 8, 0, 5])
    with pytest.raises(ValueError, match="target"):
        flip_labels({"x": jnp.zeros((3, 4))}, adv, 10)


def test_quarantine_zero_semantics():
    tx = jnp.asarray([[1.0, 2.0], [np.nan, 1.0], [3.0, 4.0]])
    n = jnp.asarray([2.0, 2.0, 2.0])
    res = (jnp.asarray([0.5, 0.6, np.nan]),)
    tx2, n2, res2, fin = quarantine_zero(tx, n, res)
    np.testing.assert_array_equal(np.asarray(fin), [True, False, False])
    np.testing.assert_array_equal(np.asarray(n2), [2.0, 0.0, 0.0])
    assert np.isfinite(np.asarray(tx2)).all()
    assert np.isfinite(np.asarray(res2[0])).all()


# ------------------------------------------------- runtime integration


def test_round_defense_ring_rolls_and_protects():
    """The normclip threshold comes from PAST medians: a boosted round
    cannot raise its own threshold; the ring fills one slot per round."""
    rt = make_runtime(mode="uncompressed", error_type="none",
                      defense="normclip", defense_window=4)
    batch, mask, ids = make_batch()
    state = rt.init_state()
    assert np.isnan(np.asarray(state.defense_ref)).all()
    for i in range(3):
        state, m = rt.round(state, ids, batch, mask, 0.05)
    ring = np.asarray(state.defense_ref)
    assert np.isfinite(ring[:3]).all() and np.isnan(ring[3])
    assert float(m["defense"]["clip_frac"]) == 0.0   # clean cohort


def test_round_signflip_changes_weights_labelflip_needs_target():
    rt_clean = make_runtime(mode="uncompressed", error_type="none")
    rt_adv = make_runtime(mode="uncompressed", error_type="none",
                          adversary="signflip", adversary_frac=0.99,
                          fused_clients=False)
    batch, mask, ids = make_batch()
    s1, _ = rt_clean.round(rt_clean.init_state(), ids, batch, mask, 0.05)
    s2, _ = rt_adv.round(rt_adv.init_state(), ids, batch, mask, 0.05)
    assert not np.allclose(np.asarray(s1.ps_weights),
                           np.asarray(s2.ps_weights))
    # labelflip on a batch without integer labels fails with the
    # explanation at trace time, not with a shape error
    rt_lf = make_runtime(mode="uncompressed", error_type="none",
                         adversary="labelflip", adversary_frac=0.99)
    with pytest.raises(ValueError, match="labelflip"):
        rt_lf.round(rt_lf.init_state(), ids, batch, mask, 0.05)


def test_defense_flags_off_hlo_byte_identity():
    """The robustness flags at their off-values must leave the round's
    HLO byte-identical to a config that never names them — the
    signals/client_stats discipline applied to the defense subsystem."""
    rt_base = make_runtime(mode="uncompressed", error_type="none")
    rt_expl = make_runtime(mode="uncompressed", error_type="none",
                           adversary="none", adversary_frac=0.0,
                           defense="none", nonfinite_action="abort",
                           quarantine_backoff=16, quarantine_strikes=5)
    batch, mask, ids = make_batch()
    args = (rt_base.init_state(), ids, batch, mask,
            jnp.asarray(0.05, jnp.float32), None)
    assert (rt_base._round.lower(*args).as_text()
            == rt_expl._round.lower(*args).as_text())
    # sanity: turning a defense ON does change the lowering
    rt_on = make_runtime(mode="uncompressed", error_type="none",
                         defense="normclip")
    assert (rt_on._round.lower(rt_on.init_state(), ids, batch, mask,
                               jnp.asarray(0.05, jnp.float32),
                               None).as_text()
            != rt_base._round.lower(*args).as_text())


def test_defense_stats_gated_on_telemetry():
    rt = make_runtime(mode="uncompressed", error_type="none",
                      defense="normclip", telemetry=False)
    batch, mask, ids = make_batch()
    _, m = rt.round(rt.init_state(), ids, batch, mask, 0.05)
    assert m["defense"] is None           # observability off...
    rt2 = make_runtime(mode="uncompressed", error_type="none",
                       defense="normclip")
    _, m2 = rt2.round(rt2.init_state(), ids, batch, mask, 0.05)
    assert m2["defense"] is not None      # ...but the clip still ran
    # clean cohort: threshold finite either way (the ring advanced)
    assert float(m2["defense"]["clip_thresh"]) > 0


def test_async_cohort_injection_bit_identical_to_sync():
    """K=1/M=1 async with an update-space adversary must stay
    bit-identical to the sync round with the same flags — injection
    happens at cohort compute, which both paths share."""
    from commefficient_tpu.core.async_agg import AsyncAggregator
    from commefficient_tpu.data.fed_sampler import Round

    kw = dict(mode="uncompressed", error_type="none",
              adversary="signflip", adversary_frac=0.6,
              nonfinite_action="quarantine", fused_clients=False)
    rt_sync = make_runtime(**kw)
    rt_async = make_runtime(async_agg=True, max_inflight=1, buffer_goal=1,
                            **kw)
    batch, mask, ids = make_batch()
    s_state = rt_sync.init_state()
    a_state = rt_async.init_state()
    agg = AsyncAggregator(rt_async)
    rnd = Round(np.asarray(ids), np.zeros((W, 4), np.int64),
                np.ones((W, 4), bool))
    for g in range(1, 4):
        s_state, sm = rt_sync.round(s_state, ids, batch, mask, 0.05)
        a_state, am, cms = agg.step(a_state, rnd, g, batch, 0.05)
        np.testing.assert_array_equal(np.asarray(sm["results"][0]),
                                      np.asarray(am["results"][0]))
        np.testing.assert_array_equal(np.asarray(sm["client_finite"]),
                                      np.asarray(am["client_finite"]))
    np.testing.assert_array_equal(np.asarray(s_state.ps_weights),
                                  np.asarray(a_state.ps_weights))


# ------------------------------------------------- telemetry surface


def test_defense_event_schema_roundtrip(tmp_path):
    tel = RunTelemetry(str(tmp_path), "test", cfg=None)
    tel.defense_event(rnd=3, defense="normclip", adversary="scale",
                      nonfinite_action="quarantine",
                      device={"clip_frac": 0.25, "clip_thresh": 4.2,
                              "clipped_mass": 10.0,
                              "trim_frac": float("nan"),
                              "nonfinite_clients": 0.0},
                      quarantine={"quarantined": 1, "ejected": 0,
                                  "quarantine_ids_digest": "1:abc"},
                      injected={"scale": 2})
    tel.write_summary(aborted=False, n_rounds=1)
    tel.close()
    assert validate_file(tel.path) == []
    ev = [json.loads(l) for l in open(tel.path)
          if '"defense"' in l][0]
    assert ev["clip_frac"] == 0.25 and ev["trim_frac"] is None
    assert ev["quarantined"] == 1 and ev["injected"] == {"scale": 2}


def test_teleview_defense_keys_literal_matches_schema():
    """The jax-free DEFENSE_KEYS fallback in scripts/teleview.py must
    track the canonical schema (same pin as ASYNC_ROUND_KEYS)."""
    tv = _teleview()
    spec = set(EVENT_FIELDS["defense"])
    for key in tv.DEFENSE_KEYS:
        assert key in spec, key


def test_teleview_defense_subcommand(tmp_path, capsys):
    tel = RunTelemetry(str(tmp_path), "test", cfg=None)
    for i in range(3):
        tel.defense_event(rnd=i, defense="trim", adversary="labelflip",
                          nonfinite_action="abort",
                          device={"trim_frac": 0.25},
                          injected={"labelflip": 2})
    tel.write_summary(aborted=False, n_rounds=3)
    tel.close()
    tv = _teleview()
    rc = tv.main(["defense", tel.path])
    out = capsys.readouterr().out
    assert rc == 0                        # no ejections
    assert "trim_frac" in out and "labelflipx6" in out
    # an ejection turns the exit red
    tel2 = RunTelemetry(str(tmp_path / "b"), "test", cfg=None)
    tel2.defense_event(rnd=1, defense="none", adversary="nan",
                       nonfinite_action="quarantine",
                       quarantine={"quarantined": 0, "ejected": 2,
                                   "quarantine_ids_digest": "2:dead"})
    tel2.write_summary(aborted=False, n_rounds=1)
    tel2.close()
    assert tv.main(["defense", tel2.path]) == 1
    # summarize grows a defense line
    tv.main(["summarize", tel.path])
    assert "-- defense:" in capsys.readouterr().out


def test_teleview_diff_defense_gates(tmp_path):
    tv = _teleview()

    def stream(path, clip_frac, quarantined, ejected):
        tel = RunTelemetry(str(path), "test", cfg=None)
        tel.defense_event(rnd=1, defense="normclip", adversary="none",
                          nonfinite_action="quarantine",
                          device={"clip_frac": clip_frac},
                          quarantine={"quarantined": quarantined,
                                      "ejected": ejected,
                                      "quarantine_ids_digest": None})
        tel.write_summary(aborted=False, n_rounds=1)
        tel.close()
        return tel.path

    a = stream(tmp_path / "a", 0.1, 0, 0)
    b = stream(tmp_path / "b", 0.6, 2, 1)
    assert tv.main(["diff", a, b]) == 1       # both gates breach
    assert tv.main(["diff", a, a]) == 0
    assert tv.main(["diff", a, b, "--clip_frac_rise", "0.9",
                    "--quarantine_growth", "5"]) == 0
