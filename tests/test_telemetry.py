"""Run-telemetry subsystem (telemetry/): schema round-trip, per-round
records through a real federated round, compile observability, NaN-abort
diagnostics, profiler-window parsing, and the console-output golden
check (telemetry must never change what the TableLogger/TSVLogger
print)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.config import FedConfig
from commefficient_tpu.core import FedRuntime
from commefficient_tpu.telemetry import (ProfilerWindow, RunTelemetry,
                                         parse_profile_rounds,
                                         validate_event, validate_file,
                                         validate_lines)
from commefficient_tpu.telemetry.schema import TELEMETRY_BASENAME
from commefficient_tpu.utils import TableLogger, TSVLogger

W, B, D_IN, D_OUT = 4, 4, 6, 3


def loss_fn(params, batch, mask):
    pred = batch["x"] @ params["w"]
    m = mask.astype(jnp.float32)
    denom = jnp.maximum(m.sum(), 1.0)
    err = ((pred - batch["y"]) ** 2).sum(axis=1)
    loss = (err * m).sum() / denom
    return loss, (loss,)


def make_runtime(**kw):
    cfg_kw = dict(mode="sketch", error_type="virtual", local_momentum=0.0,
                  virtual_momentum=0.9, weight_decay=0.0, num_workers=W,
                  local_batch_size=B, track_bytes=True, num_clients=8,
                  num_results_train=2, num_results_val=2,
                  k=5, num_rows=2, num_cols=32, exact_num_cols=True)
    cfg_kw.update(kw)
    params = {"w": jnp.asarray(
        np.random.RandomState(0).randn(D_IN, D_OUT), jnp.float32)}
    return FedRuntime(FedConfig(**cfg_kw), params, loss_fn, num_clients=8)


def make_batch(seed=1):
    rng = np.random.RandomState(seed)
    batch = {"x": jnp.asarray(rng.randn(W, B, D_IN), jnp.float32),
             "y": jnp.asarray(rng.randn(W, B, D_OUT), jnp.float32)}
    return batch, jnp.ones((W, B), bool), jnp.arange(W, dtype=jnp.int32)


def run_instrumented(tmp_path, n_rounds=3, **cfg_kw):
    rt = make_runtime(**cfg_kw)
    tel = RunTelemetry(str(tmp_path), "test", cfg=rt.cfg)
    tel.instrument(rt)
    state = rt.init_state()
    batch, mask, ids = make_batch()
    for _ in range(n_rounds):
        state, metrics = rt.round(state, ids, batch, mask, 0.05)
    return rt, tel, state, metrics


def read_events(path):
    with open(path) as f:
        return [json.loads(line) for line in f]


# --------------------------------------------------------------- schema


def test_schema_roundtrip_full_stream(tmp_path):
    """Every event helper produces lines the validator accepts, and the
    stream as a whole (manifest first, contiguous seq) is valid."""
    rt, tel, state, metrics = run_instrumented(tmp_path)
    tel.memory_event("init")
    res = [np.asarray(r) for r in metrics["results"]]
    nv = np.asarray(metrics["n_valid"])
    tel.round_event(rnd=1, epoch=1, lr=0.05,
                    loss=float(res[0].mean()), acc=float(res[1].mean()),
                    n_valid=float(nv.sum()),
                    download_bytes=1.0, upload_bytes=2.0,
                    host_s=0.1, dispatch_s=0.2, device_s=0.3)
    tel.epoch_event({"epoch": 1, "lr": 0.05, "train_time": 1.0,
                     "train_loss": 2.0, "train_acc": 0.1,
                     "test_loss": 2.1, "test_acc": 0.1,
                     "down (MiB)": 3, "up (MiB)": 4, "total_time": 5.0})
    tel.nan_abort(nan_round=7, reason="test", cfg=rt.cfg)
    tel.write_summary(aborted=False, n_rounds=3,
                      total_download_mib=1.0, total_upload_mib=2.0,
                      final=tel.last_epoch)
    tel.close()
    path = os.path.join(str(tmp_path), TELEMETRY_BASENAME)
    assert validate_file(path) == []
    events = read_events(path)
    kinds = [e["event"] for e in events]
    assert kinds[0] == "manifest"
    assert kinds[-1] == "summary"
    for needed in ("compile", "memory", "round", "epoch", "nan_abort"):
        assert needed in kinds, kinds
    assert [e["seq"] for e in events] == list(range(len(events)))
    # the manifest records the resolved run
    man = events[0]
    assert man["grad_size"] == rt.cfg.grad_size
    assert man["sketch"]["num_cols"] == rt.cfg.num_cols
    assert man["config"]["mode"] == "sketch"
    assert man["jax_version"] == jax.__version__


def test_validator_rejects_bad_streams():
    ok = json.dumps({"event": "manifest", "t": 0.0, "seq": 0, "schema": 1,
                     "run_type": "t", "jax_version": "x", "backend": "cpu",
                     "device_kind": "cpu", "device_count": 1,
                     "mesh_shape": [], "mesh_axes": [], "grad_size": 1,
                     "sketch": None, "config": {}})
    assert validate_lines([ok]) == []
    # unknown event type
    assert validate_event({"event": "nope", "t": 0.0, "seq": 0})
    # missing required field
    assert validate_event({"event": "round", "t": 0.0, "seq": 0})
    # wrong type
    bad = json.loads(ok)
    bad["grad_size"] = "one"
    assert validate_event(bad)
    # stream-shape checks: first line must be a manifest, seq contiguous
    rnd = json.dumps({"event": "memory", "t": 0.0, "seq": 0, "phase": "p",
                      "devices": [], "host_rss_bytes": None})
    assert any("manifest" in p for _, p in validate_lines([rnd]))
    gap = json.loads(ok)
    gap2 = {"event": "memory", "t": 0.0, "seq": 5, "phase": "p",
            "devices": [], "host_rss_bytes": None}
    probs = validate_lines([json.dumps(gap), json.dumps(gap2)])
    assert any("seq" in p for _, p in probs)
    # not JSON
    assert validate_lines(["{nope"])


def test_check_script_on_runs_tree(tmp_path):
    """The CI lint (scripts/check_telemetry_schema.py) accepts a valid
    stream and fails on a corrupted one."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(os.path.dirname(__file__), os.pardir, "scripts",
                     "check_telemetry_schema.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    tel = RunTelemetry(str(tmp_path / "runA"), "test", cfg=None)
    tel.write_summary(aborted=False, n_rounds=0)
    tel.close()
    assert mod.main([str(tmp_path)]) == 0
    with open(tmp_path / "runA" / TELEMETRY_BASENAME, "a") as f:
        f.write('{"event": "bogus"}\n')
    assert mod.main([str(tmp_path)]) == 1
    assert mod.main([str(tmp_path / "missing")]) == 2


# ------------------------------------------------------------ round records


def test_round_record_contents_under_track_bytes(tmp_path):
    """The driver-side round record must carry the simulated byte
    accounting: upload = 4 bytes x upload_floats x participating clients,
    download per the changed-coordinate rule."""
    from commefficient_tpu.cv_train import train  # noqa: F401 (import check)
    rt, tel, state, metrics = run_instrumented(tmp_path, n_rounds=2)
    up = float(np.asarray(metrics["upload_bytes"]).sum())
    assert up == 4.0 * rt.cfg.upload_floats * W
    res = [np.asarray(r) for r in metrics["results"]]
    nv = np.asarray(metrics["n_valid"])
    tel.round_event(rnd=2, epoch=1, lr=0.05,
                    loss=float((res[0] * nv).sum() / nv.sum()),
                    acc=float((res[1] * nv).sum() / nv.sum()),
                    n_valid=float(nv.sum()),
                    download_bytes=float(
                        np.asarray(metrics["download_bytes"]).sum()),
                    upload_bytes=up,
                    host_s=0.0, dispatch_s=0.0, device_s=0.0)
    tel.close()
    events = read_events(tel.path)
    rec = [e for e in events if e["event"] == "round"][-1]
    assert rec["upload_bytes"] == up
    assert rec["n_valid"] == W * B
    assert np.isfinite(rec["loss"])
    # round 2: every client re-downloads the coordinates round 1 changed
    assert rec["download_bytes"] > 0


class StubDS:
    """Minimal FedDataset stand-in for driving cv_train.train directly:
    train gathers see (W, B) index arrays, val gathers see (B,) — the
    returned leaf shapes mirror the index shape, exactly like a real
    dataset's per-item rows."""

    data_per_client = np.full(8, B)
    num_clients = 8

    def __init__(self, scale: float = 1.0):
        self.scale = scale

    def __len__(self):
        return 8 * B

    def gather(self, idx):
        idx = np.asarray(idx)
        rng = np.random.RandomState(0)
        return {"x": (self.scale
                      * rng.randn(*idx.shape, D_IN).astype(np.float32)),
                "y": rng.randn(*idx.shape, D_OUT).astype(np.float32)}


def test_driver_loop_emits_round_events(tmp_path, capsys):
    """End-to-end through cv_train.train's telemetry wiring: run the real
    train() loop on the quad runtime with a stub dataset."""
    from commefficient_tpu import cv_train

    # dataset_name outside the DeviceStore table => host gather path;
    # telemetry_every=1 pins per-round records (the non-test auto
    # cadence is 64 and this run is 2 rounds long)
    rt = make_runtime(dataset_name="SYNTH", telemetry_every=1)
    tel = RunTelemetry(str(tmp_path), "cv_train", cfg=rt.cfg)
    tel.instrument(rt)
    cfg = rt.cfg.replace(num_epochs=1.0, pivot_epoch=0.5)
    state = rt.init_state()
    ds = StubDS()

    state, summary = cv_train.train(
        cfg, rt, state, ds, ds, loggers=(TableLogger(),), telemetry=tel)
    tel.close()
    assert summary is not None
    assert validate_file(tel.path) == []
    events = read_events(tel.path)
    kinds = [e["event"] for e in events]
    assert kinds.count("round") >= 1
    assert "summary" in kinds and "epoch" in kinds
    rec = [e for e in events if e["event"] == "round"][0]
    for key in ("host_s", "dispatch_s", "device_s",
                "download_bytes", "upload_bytes"):
        assert key in rec
    mem_phases = [e["phase"] for e in events if e["event"] == "memory"]
    assert "round_1" in mem_phases and "epoch_1" in mem_phases


# ------------------------------------------------------------ compile events


def test_compile_events_and_recompile_visibility(tmp_path):
    rt, tel, state, _ = run_instrumented(tmp_path, n_rounds=2)
    events = [e for e in read_events(tel.path) if e["event"] == "compile"]
    assert len(events) == 1, events  # one signature => ONE compile event
    ev = events[0]
    assert ev["name"] == "round_step" and ev["n_compiles"] == 1
    assert ev["flops"] and ev["flops"] > 0
    assert ev["compile_s"] >= 0 and ev["lower_s"] >= 0
    assert ev["fallback"] is False
    # a changed round shape (fewer workers) must surface as a SECOND
    # compile event for the same function, n_compiles == 2
    batch, mask, ids = make_batch()
    half = {k: v[:2] for k, v in batch.items()}
    state, _ = rt.round(state, ids[:2], half, mask[:2], 0.05)
    events = [e for e in read_events(tel.path) if e["event"] == "compile"]
    assert len(events) == 2
    assert events[1]["n_compiles"] == 2
    tel.close()


def test_watched_round_matches_unwatched(tmp_path):
    """Instrumentation must not change numerics: same rounds, same
    weights, watched vs not."""
    rt1 = make_runtime()
    rt2 = make_runtime()
    tel = RunTelemetry(str(tmp_path), "test", cfg=rt2.cfg)
    tel.instrument(rt2)
    batch, mask, ids = make_batch()
    s1, s2 = rt1.init_state(), rt2.init_state()
    for _ in range(3):
        s1, m1 = rt1.round(s1, ids, batch, mask, 0.05)
        s2, m2 = rt2.round(s2, ids, batch, mask, 0.05)
    np.testing.assert_array_equal(np.asarray(s1.ps_weights),
                                  np.asarray(s2.ps_weights))
    tel.close()


# --------------------------------------------------------------- NaN abort


def test_nan_abort_event(tmp_path):
    rt, tel, state, _ = run_instrumented(tmp_path, n_rounds=1)
    tel.nan_abort(nan_round=3,
                  reason="first non-finite update at round 3", cfg=rt.cfg)
    tel.close()
    events = read_events(tel.path)
    ev = [e for e in events if e["event"] == "nan_abort"]
    assert len(ev) == 1
    ev = ev[0]
    assert ev["nan_round"] == 3
    assert ev["mode"] == "sketch"
    assert ev["sketch"]["impl"] == rt.cfg.sketch_impl
    assert ev["max_grad_norm"] is None
    assert validate_file(tel.path) == []


def test_train_loop_nan_abort_emits_event(tmp_path, capsys):
    """Drive the real train() loop into divergence (overflowing inputs)
    and check the structured diagnostic is emitted with the abort
    summary."""
    from commefficient_tpu import cv_train

    rt = make_runtime(dataset_name="SYNTH")
    tel = RunTelemetry(str(tmp_path), "cv_train", cfg=rt.cfg)
    cfg = rt.cfg.replace(num_epochs=1.0, pivot_epoch=0.5, lr_scale=1e30)
    state, summary = cv_train.train(cfg, rt, state=rt.init_state(),
                                    train_ds=StubDS(scale=1e25),
                                    val_ds=StubDS(scale=1e25),
                                    telemetry=tel)
    tel.close()
    assert summary is None  # diverged
    out = capsys.readouterr().out
    assert "TRAINING DIVERGED" in out
    events = read_events(tel.path)
    kinds = [e["event"] for e in events]
    assert "nan_abort" in kinds
    assert events[-1]["event"] == "summary" and events[-1]["aborted"]
    assert validate_file(tel.path) == []


# ---------------------------------------------------------- profiler window


def test_parse_profile_rounds():
    assert parse_profile_rounds("2:4") == (2, 4)
    assert parse_profile_rounds("7") == (7, 7)
    assert parse_profile_rounds(" 1:1 ") == (1, 1)
    for bad in ("", "4:2", "0:3", "a:b", "1:2:3", "-1:4"):
        with pytest.raises(ValueError):
            parse_profile_rounds(bad)
    # config fails fast on a bad window only when profiling is requested
    FedConfig(profile_rounds="nope")
    with pytest.raises(ValueError):
        FedConfig(profile_dir="/tmp/x", profile_rounds="nope")


def test_profiler_window_lifecycle(monkeypatch, tmp_path):
    calls = []
    import jax.profiler as prof_mod
    monkeypatch.setattr(prof_mod, "start_trace",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr(prof_mod, "stop_trace",
                        lambda: calls.append(("stop",)))
    logged = []
    win = ProfilerWindow(str(tmp_path), "2:3", log=logged.append)
    synced = []
    for rnd in range(1, 6):
        win.maybe_start(rnd)
        win.maybe_stop(rnd, lambda: synced.append(rnd))
    assert calls == [("start", str(tmp_path)), ("stop",)]
    assert synced == [3]        # synced exactly once, at the stop round
    assert win.done and not win.active
    assert logged and "profiler trace written" in logged[0]
    # disabled window does nothing
    calls.clear()
    win2 = ProfilerWindow("", "2:3")
    win2.maybe_start(2), win2.maybe_stop(3)
    assert calls == []


def test_telemetry_every_auto_resolution():
    """-1 = auto: per-round under --test, every 64 rounds otherwise;
    explicit values pass through."""
    assert FedConfig().telemetry_round_every == 64
    assert FedConfig(do_test=True).telemetry_round_every == 1
    assert FedConfig(telemetry_every=7).telemetry_round_every == 7
    assert FedConfig(telemetry_every=0, do_test=True).telemetry_round_every \
        == 0


def test_profiler_window_finalize(monkeypatch, tmp_path):
    """A window the run ends inside of (STOP beyond the last round) still
    writes its partial trace and releases the profiler."""
    calls = []
    import jax.profiler as prof_mod
    monkeypatch.setattr(prof_mod, "start_trace",
                        lambda d: calls.append("start"))
    monkeypatch.setattr(prof_mod, "stop_trace",
                        lambda: calls.append("stop"))
    logged = []
    win = ProfilerWindow(str(tmp_path), "2:1000", log=logged.append)
    win.maybe_start(2)
    win.maybe_stop(2)          # stop round never reached
    assert win.active
    synced = []
    win.finalize(lambda: synced.append(True))
    assert calls == ["start", "stop"] and synced == [True]
    assert win.done and not win.active
    assert logged and "closed early" in logged[0]
    win.finalize()             # idempotent
    assert calls == ["start", "stop"]


def test_profiler_window_abort(monkeypatch, tmp_path):
    calls = []
    import jax.profiler as prof_mod
    monkeypatch.setattr(prof_mod, "start_trace",
                        lambda d: calls.append("start"))
    monkeypatch.setattr(prof_mod, "stop_trace",
                        lambda: calls.append("stop"))
    win = ProfilerWindow(str(tmp_path), "1:5", log=lambda *_: None)
    win.maybe_start(1)
    win.abort()
    assert calls == ["start", "stop"]
    # a retried attempt must not re-open the trace
    win.maybe_start(2)
    assert calls == ["start", "stop"]


def test_bench_timed_rounds_with_profiler(monkeypatch, tmp_path):
    """bench_common.timed_rounds drives the profiler over the timed
    rounds and still returns a sane timing."""
    calls = []
    import jax.profiler as prof_mod
    monkeypatch.setattr(prof_mod, "start_trace",
                        lambda d: calls.append("start"))
    monkeypatch.setattr(prof_mod, "stop_trace",
                        lambda: calls.append("stop"))
    import bench_common
    rt = make_runtime()
    batch, mask, ids = make_batch()
    win = ProfilerWindow(str(tmp_path), "1:2", log=lambda *_: None)
    dt, metrics, phases = bench_common.timed_rounds(
        rt, (ids, batch, mask, 0.05), warmup=1, rounds=3, desc="t",
        profiler=win)
    assert dt > 0 and calls == ["start", "stop"]
    # warmup_s (PR 5): the compile+warmup tax, measured OUTSIDE the
    # timed wall so the three timed-phase fractions still sum to dt
    assert set(phases) == {"host_s", "dispatch_s", "device_wait_s",
                           "warmup_s"}
    assert all(v >= 0 for v in phases.values())
    timed = dict(phases)
    warmup_s = timed.pop("warmup_s")
    assert warmup_s > 0
    assert sum(timed.values()) == pytest.approx(dt, abs=1e-3)


# ------------------------------------------------------------ console golden


def test_console_output_unchanged_golden(capsys):
    """The TableLogger/TSVLogger console contract is byte-stable, with
    telemetry attached or not: telemetry writes ONLY to its jsonl (and
    stderr), never stdout."""
    summary = {"epoch": 1, "lr": 0.2, "train_time": 3.5, "train_loss": 2.0,
               "train_acc": 0.5, "test_loss": 1.9, "test_acc": 0.55,
               "down (MiB)": 12, "up (MiB)": 3, "total_time": 7.25}
    golden = (
        "       epoch           lr   train_time   train_loss    train_acc"
        "    test_loss     test_acc   down (MiB)     up (MiB)   total_time\n"
        "           1       0.2000       3.5000       2.0000       0.5000"
        "       1.9000       0.5500           12            3       7.2500\n"
    )
    tl = TableLogger()
    tl.append(summary)
    assert capsys.readouterr().out == golden

    tsv = TSVLogger()
    tsv.append(summary)
    assert str(tsv) == "epoch,hours,top1Accuracy\n1,0.00201389,55.00"

    # identical rows with a telemetry stream attached to the same summary
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        tel = RunTelemetry(d, "test", cfg=None)
        capsys.readouterr()
        tl2 = TableLogger()
        tl2.append(summary)
        tel.epoch_event(summary)
        tel.close()
        assert capsys.readouterr().out == golden


def test_committed_runs_streams_are_valid():
    """CI guard: every telemetry.jsonl committed under runs/ must parse
    against the current schema (none committed yet => trivially green)."""
    repo = os.path.join(os.path.dirname(__file__), os.pardir)
    runs = os.path.join(repo, "runs")
    if not os.path.isdir(runs):
        pytest.skip("no runs/ tree")
    bad = {}
    for dirpath, _, filenames in os.walk(runs):
        for fn in filenames:
            if fn == TELEMETRY_BASENAME:
                path = os.path.join(dirpath, fn)
                problems = validate_file(path)
                if problems:
                    bad[path] = problems[:5]
    assert not bad, bad


def test_non_finite_metrics_serialize_as_null(tmp_path):
    """NaN/inf metric values must land as JSON null (strict parsers
    reject Python's NaN/Infinity tokens), and a non-finite round record
    must not overwrite last_round (nan_abort's last-known-FINITE
    context)."""
    tel = RunTelemetry(str(tmp_path), "test", cfg=None)
    tel.round_event(rnd=1, epoch=1, lr=0.1, loss=1.5, acc=0.5, n_valid=4,
                    download_bytes=None, upload_bytes=1.0,
                    host_s=0, dispatch_s=0, device_s=0)
    tel.round_event(rnd=2, epoch=1, lr=0.1, loss=float("nan"),
                    acc=float("inf"), n_valid=4, download_bytes=None,
                    upload_bytes=1.0, host_s=0, dispatch_s=0, device_s=0)
    assert tel.last_round["round"] == 1  # the finite one
    tel.write_summary(aborted=True, n_rounds=2, final=tel.last_round)
    tel.close()
    raw = open(tel.path).read()
    assert "NaN" not in raw and "Infinity" not in raw
    events = [json.loads(l, parse_constant=lambda c: pytest.fail(
        f"non-strict token {c}")) for l in raw.splitlines()]
    rec2 = [e for e in events if e["event"] == "round"][1]
    assert rec2["loss"] is None and rec2["acc"] is None
    assert validate_file(tel.path) == []


def test_maybe_create_returns_none_on_unwritable_logdir(tmp_path, capsys):
    """A stream that failed to open must not be announced or handed to
    the caller as if it existed."""
    from commefficient_tpu.telemetry import maybe_create
    blocker = tmp_path / "blocker"
    blocker.write_text("a file where the logdir should go")
    cfg = FedConfig()
    assert maybe_create(cfg, "test", logdir=str(blocker)) is None
    assert "telemetry:" not in capsys.readouterr().err
    # and the disabled-config path still returns None
    assert maybe_create(cfg.replace(telemetry=False), "test",
                        logdir=str(tmp_path)) is None


def test_validator_seq_resync_no_cascade():
    """One seq gap (or stray non-object line) is one problem, not a
    mismatch on every following line."""
    def ev(seq, n):
        return json.dumps({"event": "memory", "t": 0.0, "seq": seq,
                           "phase": f"p{n}", "devices": [],
                           "host_rss_bytes": None})
    man = json.dumps({"event": "manifest", "t": 0.0, "seq": 0, "schema": 1,
                      "run_type": "t", "jax_version": "x", "backend": "cpu",
                      "device_kind": "cpu", "device_count": 1,
                      "mesh_shape": [], "mesh_axes": [], "grad_size": 1,
                      "sketch": None, "config": {}})
    # a gap 0 -> 5 flags exactly once; 5,6,7 then validate cleanly
    probs = validate_lines([man, ev(5, 1), ev(6, 2), ev(7, 3)])
    assert len([p for _, p in probs if "seq" in p]) == 1
    # a stray non-object line flags itself; the writer's own seq stream
    # continues undisturbed around it
    probs = validate_lines([man, "[1, 2]", ev(1, 1), ev(2, 2)])
    assert not any("seq" in p for _, p in probs)
    assert any("not an object" in p for _, p in probs)


def test_set_compile_watcher_idempotent(tmp_path):
    """A second instrument() call must not double-wrap (the wrapper needs
    the raw jitted fn's AOT surface) — compile events keep flowing."""
    rt = make_runtime()
    tel = RunTelemetry(str(tmp_path), "test", cfg=rt.cfg)
    tel.instrument(rt)
    tel.instrument(rt)  # no-op, not a re-wrap
    batch, mask, ids = make_batch()
    state = rt.init_state()
    state, _ = rt.round(state, ids, batch, mask, 0.05)
    tel.close()
    comp = [e for e in read_events(tel.path) if e["event"] == "compile"]
    assert len(comp) == 1 and comp[0]["fallback"] is False
