"""Sharded federated round on the virtual 8-device CPU mesh.

Validates the TPU mapping of the reference's distributed stack (SURVEY.md
§2.8): clients sharded over the mesh axis, XLA-inserted collectives for the
gradient sum, and exact equality with the single-device round — sharding
must never change numerics.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.config import FedConfig
from commefficient_tpu.core import FedRuntime
from commefficient_tpu.parallel import FedShardings, make_mesh


def quad_loss(params, batch, mask):
    # simple convex loss: params is a dict pytree
    w = params["w"]
    x, y = batch["x"], batch["y"]
    pred = x @ w
    err = ((pred - y) ** 2).sum(axis=1)
    m = mask.astype(jnp.float32)
    denom = jnp.maximum(m.sum(), 1.0)
    loss = (err * m).sum() / denom
    return loss, (loss,)


def make_cfg(**kw):
    base = dict(mode="uncompressed", error_type="none", local_momentum=0.0,
                virtual_momentum=0.9, weight_decay=0.0, num_workers=8,
                local_batch_size=4, track_bytes=True, num_clients=16)
    base.update(kw)
    return FedConfig(**base)


def make_batch(seed, W=8, B=4, din=6, dout=3):
    rng = np.random.RandomState(seed)
    return (
        {"x": jnp.asarray(rng.randn(W, B, din), jnp.float32),
         "y": jnp.asarray(rng.randn(W, B, dout), jnp.float32)},
        jnp.asarray(rng.rand(W, B) > 0.2),
        jnp.arange(W, dtype=jnp.int32) * 2,
    )


@pytest.mark.parametrize("mode,extra", [
    ("uncompressed", {}),
    ("true_topk", {"error_type": "virtual", "k": 5}),
    ("sketch", {"error_type": "virtual", "k": 5, "num_rows": 3,
                "num_cols": 32, "num_blocks": 2, "sketch_impl": "hash"}),
    # rht: single-device (dense-preimage zeroing) and mesh (table-space
    # subtractive) rules only coincide in the lossless limit — assert the
    # exact-equality contract there (c >= padded d => exact round-trip)
    ("sketch", {"error_type": "virtual", "k": 5, "num_rows": 3,
                "num_cols": 32, "sketch_impl": "rht"}),
    ("local_topk", {"error_type": "local", "k": 5, "local_momentum": 0.9}),
    ("fedavg", {"error_type": "none", "local_batch_size": -1,
                "max_client_batch": 4, "fedavg_batch_size": 2,
                "num_fedavg_epochs": 2}),
])
def test_sharded_round_matches_single_device(mode, extra):
    cfg = make_cfg(mode=mode, **extra)
    params = {"w": jnp.asarray(
        np.random.RandomState(0).randn(6, 3), jnp.float32)}
    mesh = make_mesh((8,), ("clients",))

    rt_single = FedRuntime(cfg, params, quad_loss, num_clients=16)
    rt_shard = FedRuntime(cfg, params, quad_loss, num_clients=16, mesh=mesh)

    s1 = rt_single.init_state()
    s2 = rt_shard.init_state()
    batch, mask, client_ids = make_batch(1)
    lr = 0.1

    for step in range(3):
        s1, m1 = rt_single.round(s1, client_ids, batch, mask, lr)
        s2, m2 = rt_shard.round(s2, client_ids, batch, mask, lr)

    np.testing.assert_allclose(np.asarray(s1.ps_weights),
                               np.asarray(s2.ps_weights),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(m1["results"][0]),
                               np.asarray(m2["results"][0]), rtol=1e-5)
    if cfg.track_bytes:
        np.testing.assert_allclose(np.asarray(m1["download_bytes"]),
                                   np.asarray(m2["download_bytes"]))


def test_sharded_state_layout():
    cfg = make_cfg(mode="local_topk", error_type="local", k=4,
                   local_momentum=0.9)
    params = {"w": jnp.zeros((6, 3), jnp.float32)}
    mesh = make_mesh((8,), ("clients",))
    rt = FedRuntime(cfg, params, quad_loss, num_clients=10, mesh=mesh)
    # client count padded to a multiple of the mesh axis
    assert rt.num_clients == 16
    state = rt.init_state()
    sh = state.client_errors.sharding
    assert sh.is_equivalent_to(
        FedShardings(mesh).client_rows, state.client_errors.ndim)


def test_make_mesh_defaults():
    assert make_mesh((), ("clients",),
                     devices=jax.devices()[:1]) is None
    m = make_mesh((), ("clients",))
    assert m is not None and m.shape["clients"] == 8
    with pytest.raises(ValueError):
        make_mesh((16,), ("clients",))
