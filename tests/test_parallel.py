"""Sharded federated round on the virtual 8-device CPU mesh.

Validates the TPU mapping of the reference's distributed stack (SURVEY.md
§2.8): clients sharded over the mesh axis, XLA-inserted collectives for the
gradient sum, and exact equality with the single-device round — sharding
must never change numerics.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.config import FedConfig
from commefficient_tpu.core import FedRuntime
from commefficient_tpu.parallel import FedShardings, make_mesh


def quad_loss(params, batch, mask):
    # simple convex loss: params is a dict pytree
    w = params["w"]
    x, y = batch["x"], batch["y"]
    pred = x @ w
    err = ((pred - y) ** 2).sum(axis=1)
    m = mask.astype(jnp.float32)
    denom = jnp.maximum(m.sum(), 1.0)
    loss = (err * m).sum() / denom
    return loss, (loss,)


def make_cfg(**kw):
    base = dict(mode="uncompressed", error_type="none", local_momentum=0.0,
                virtual_momentum=0.9, weight_decay=0.0, num_workers=8,
                local_batch_size=4, track_bytes=True, num_clients=16)
    base.update(kw)
    return FedConfig(**base)


def make_batch(seed, W=8, B=4, din=6, dout=3):
    rng = np.random.RandomState(seed)
    return (
        {"x": jnp.asarray(rng.randn(W, B, din), jnp.float32),
         "y": jnp.asarray(rng.randn(W, B, dout), jnp.float32)},
        jnp.asarray(rng.rand(W, B) > 0.2),
        jnp.arange(W, dtype=jnp.int32) * 2,
    )


@pytest.mark.parametrize("mode,extra", [
    ("uncompressed", {}),
    ("true_topk", {"error_type": "virtual", "k": 5}),
    ("sketch", {"error_type": "virtual", "k": 5, "num_rows": 3,
                "num_cols": 32, "num_blocks": 2, "sketch_impl": "hash"}),
    # rht: single-device (dense-preimage zeroing) and mesh (table-space
    # subtractive) rules only coincide in the lossless limit — assert the
    # exact-equality contract there (c >= padded d => exact round-trip)
    ("sketch", {"error_type": "virtual", "k": 5, "num_rows": 3,
                "num_cols": 32, "sketch_impl": "rht"}),
    ("local_topk", {"error_type": "local", "k": 5, "local_momentum": 0.9}),
    ("fedavg", {"error_type": "none", "local_batch_size": -1,
                "max_client_batch": 4, "fedavg_batch_size": 2,
                "num_fedavg_epochs": 2}),
])
def test_sharded_round_matches_single_device(mode, extra):
    cfg = make_cfg(mode=mode, **extra)
    params = {"w": jnp.asarray(
        np.random.RandomState(0).randn(6, 3), jnp.float32)}
    mesh = make_mesh((8,), ("clients",))

    rt_single = FedRuntime(cfg, params, quad_loss, num_clients=16)
    rt_shard = FedRuntime(cfg, params, quad_loss, num_clients=16, mesh=mesh)

    s1 = rt_single.init_state()
    s2 = rt_shard.init_state()
    batch, mask, client_ids = make_batch(1)
    lr = 0.1

    for step in range(3):
        s1, m1 = rt_single.round(s1, client_ids, batch, mask, lr)
        s2, m2 = rt_shard.round(s2, client_ids, batch, mask, lr)

    # mesh state is padded to d_pad (24 here for d=18 on 8 devices) so the
    # server runs sharded; the true coordinates must match the single-device
    # run up to fp32 reduction-order noise (reduce_scatter accumulates in
    # ring order where the single device sums in one pass)
    d = rt_single.cfg.grad_size
    assert rt_shard.d_pad == 24 and s2.ps_weights.shape == (24,)
    np.testing.assert_array_equal(np.asarray(s2.ps_weights[d:]), 0.0)
    np.testing.assert_allclose(np.asarray(s1.ps_weights),
                               np.asarray(s2.ps_weights[:d]),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(m1["results"][0]),
                               np.asarray(m2["results"][0]), rtol=1e-5)
    if cfg.track_bytes:
        np.testing.assert_allclose(np.asarray(m1["download_bytes"]),
                                   np.asarray(m2["download_bytes"]))


def test_sharded_state_layout():
    cfg = make_cfg(mode="local_topk", error_type="local", k=4,
                   local_momentum=0.9)
    params = {"w": jnp.zeros((6, 3), jnp.float32)}
    mesh = make_mesh((8,), ("clients",))
    rt = FedRuntime(cfg, params, quad_loss, num_clients=10, mesh=mesh)
    # client count padded to a multiple of the mesh axis
    assert rt.num_clients == 16
    state = rt.init_state()
    # dense client rows store COLUMN-sharded (home layout: every device
    # owns a d_row_pad/n slice of every row) so the round's gather/scatter
    # by client_ids is local and layout changes are W·d/n all_to_alls
    from jax.sharding import NamedSharding, PartitionSpec as P
    sh = state.client_errors.sharding
    assert sh.is_equivalent_to(
        NamedSharding(mesh, P(None, "clients")), state.client_errors.ndim)
    # dense server state shards over the weight axis even though the true
    # d (18) does not divide the mesh (padded to d_pad=24) — the VERDICT r1
    # replicated-fallback gap
    fs = FedShardings(mesh)
    assert rt.d_pad == 24
    for leaf in (state.ps_weights, state.Vvelocity, state.Verror,
                 state.coord_last_update):
        assert leaf.shape == (24,)
        assert leaf.sharding.is_equivalent_to(fs.dense_vec, leaf.ndim)
    # client rows live at d_row_pad so the column sharding divides evenly
    assert rt.d_row_pad == 24
    assert state.client_errors.shape == (16, 24)


def _collective_shapes(rt, state, batch, mask, client_ids):
    """(kind, n_elements) for every collective in the compiled round
    (tuple-typed combined collectives contribute one entry per element)."""
    from __graft_entry__ import _collective_report
    return _collective_report(rt, state, client_ids, batch, mask)


@pytest.mark.parametrize("mode,extra", [
    ("uncompressed", {}),
    ("true_topk", {"error_type": "virtual", "k": 5}),
    ("local_topk", {"error_type": "local", "k": 5, "local_momentum": 0.9}),
    ("fedavg", {"error_type": "none", "local_batch_size": -1,
                "max_client_batch": 4, "fedavg_batch_size": 2,
                "num_fedavg_epochs": 1}),
    ("sketch", {"error_type": "virtual", "k": 5, "num_rows": 3,
                "num_cols": 32, "num_blocks": 2}),
])
def test_collectives_are_shard_or_table_sized(mode, extra):
    """The round's gradient aggregation must never be a replicated full-d
    all-reduce: dense modes reduce_scatter the d_pad/n gradient shard,
    sketch reduce_scatters the (r, c) table over columns (the compressed
    payload, sharded — PR 11's server tail). The
    only full-length collective allowed is the one all-gather every client
    needs to read the weights (reference: every worker reads g_ps_weights,
    fed_worker.py:41)."""
    cfg = make_cfg(mode=mode, track_bytes=False, **extra)
    params = {"w": jnp.asarray(
        np.random.RandomState(0).randn(6, 3), jnp.float32)}
    mesh = make_mesh((8,), ("clients",))
    rt = FedRuntime(cfg, params, quad_loss, num_clients=16, mesh=mesh)
    state = rt.init_state()
    batch, mask, client_ids = make_batch(1)
    colls = _collective_shapes(rt, state, batch, mask, client_ids)
    assert colls, "expected collectives in the compiled round"
    d_pad = rt.d_pad
    table = cfg.num_rows * cfg.num_cols
    # HARD bound (mirrors __graft_entry__.dryrun_multichip): every
    # non-scalar collective result must be at most a dense shard, the
    # sketch table, or the per-device share of the round's client-state
    # rows (the all_to_all home-shard routing). Only the weight/top-k
    # all-gather may be full-length. The former W·d all-reduce pair for
    # velocity/error write-back (VERDICT r2 item 5) violates this bound.
    row_traffic = (8 * rt.d_row_pad // 8 if (cfg.needs_client_velocities
                                             or cfg.needs_client_errors)
                   else 0)
    # cfg.k covers the top-k select traffic (k ≪ a dense shard at real
    # configs; only this tiny test config has k > d_pad/n)
    bound = max(d_pad // 8, table if mode == "sketch" else 0, row_traffic,
                cfg.k)
    # all-gathers may be weight-sized, or TABLE-sized in sketch mode: the
    # signal diagnostics' row-norm estimates (l2estimate of the
    # column-sharded tables, telemetry/signals.py) gather the compressed
    # payload — bounded by the same table size as the aggregation psum
    gather_bound = max(d_pad, table if mode == "sketch" else 0)
    for kind, n in colls:
        if kind == "all-gather":
            assert n <= gather_bound, (kind, n)
        elif n > 1:
            assert n <= bound, (kind, n)
        if kind == "reduce-scatter":
            if mode == "sketch":
                # the sharded server tail (PR 11): the table aggregation
                # reduce-scatters over COLUMNS — the result is the
                # (r, c/8) shard, never the replicated table
                assert n == table // 8, (kind, n)
            else:
                assert n == d_pad // 8, (kind, n)
    # every mode reduce-scatters its aggregate now: dense modes the
    # d_pad/n gradient shard, sketch the c/n table-column shard
    assert any(k == "reduce-scatter" for k, _ in colls), colls
    if cfg.needs_client_velocities or cfg.needs_client_errors:
        assert any(k == "all-to-all" for k, _ in colls), colls


@pytest.mark.parametrize("mode,extra", [
    ("uncompressed", {}),
    ("true_topk", {"error_type": "virtual", "k": 5}),
    ("sketch", {"error_type": "virtual", "k": 5, "num_rows": 3,
                "num_cols": 32, "num_blocks": 2}),
    # microbatched: 2 microbatches per client — the fused scan must keep
    # per-client results/weighting exact across the client boundary
    ("uncompressed", {"microbatch_size": 2}),
    # bf16 wire: the fused branch's sum-rounding points must agree with
    # the vmap branch's (deferred encode in both)
    ("sketch", {"error_type": "virtual", "k": 5, "num_rows": 3,
                "num_cols": 32, "num_blocks": 2,
                "sketch_dtype": "bfloat16"}),
])
def test_fused_clients_matches_vmap(mode, extra):
    """The jointly-computed round gradient (make_fused_grad, default-on)
    must reproduce the per-client vmap path's trajectory and per-client
    metrics exactly up to summation order — single-device AND mesh."""
    cfg_f = make_cfg(mode=mode, local_momentum=0.0, weight_decay=5e-4,
                     **extra)
    cfg_v = cfg_f.replace(fused_clients=False)
    params = {"w": jnp.asarray(
        np.random.RandomState(0).randn(6, 3), jnp.float32)}
    mesh = make_mesh((8,), ("clients",))
    batch, mask, cids = make_batch(1)

    rt_f = FedRuntime(cfg_f, params, quad_loss, num_clients=16)
    rt_v = FedRuntime(cfg_v, params, quad_loss, num_clients=16)
    assert rt_f._fused and not rt_v._fused
    sf, sv = rt_f.init_state(), rt_v.init_state()
    for _ in range(3):
        sf, mf = rt_f.round(sf, cids, batch, mask, 0.1)
        sv, mv = rt_v.round(sv, cids, batch, mask, 0.1)
    np.testing.assert_allclose(np.asarray(sf.ps_weights),
                               np.asarray(sv.ps_weights),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(mf["results"][0]),
                               np.asarray(mv["results"][0]), rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(mf["n_valid"]),
                                  np.asarray(mv["n_valid"]))

    rt_m = FedRuntime(cfg_f, params, quad_loss, num_clients=16, mesh=mesh)
    assert rt_m._fused
    sm = rt_m.init_state()
    for _ in range(3):
        sm, mm = rt_m.round(sm, cids, batch, mask, 0.1)
    d = rt_f.cfg.grad_size
    # a bf16 WIRE rounds the mesh psum's partial sums where one chip
    # rounds the full sum once — agreement there is only to bf16 epsilon
    wide = extra.get("sketch_dtype") == "bfloat16"
    np.testing.assert_allclose(np.asarray(sf.ps_weights),
                               np.asarray(sm.ps_weights[:d]),
                               rtol=0.02 if wide else 1e-4,
                               atol=1e-3 if wide else 1e-6)
    np.testing.assert_allclose(np.asarray(mf["results"][0]),
                               np.asarray(mm["results"][0]),
                               rtol=5e-3 if wide else 1e-5)


def test_bf16_sketch_tables():
    """--sketch_dtype bfloat16 (VERDICT r3 item 6): the table psum payload
    must compile as a bf16 all-reduce (half the ICI bytes of the
    reference's NCCL reduce, fed_worker.py:138), the round must stay
    close to the fp32-wire round (the only difference is ~2^-8 relative
    cell rounding), and single-device vs mesh must agree — the one-chip
    emulation applies the same wire quantization the psum would."""
    import re

    extra = dict(mode="sketch", error_type="virtual", k=5, num_rows=3,
                 num_cols=32, num_blocks=2, track_bytes=False)
    params = {"w": jnp.asarray(
        np.random.RandomState(0).randn(6, 3), jnp.float32)}
    mesh = make_mesh((8,), ("clients",))
    batch, mask, cids = make_batch(1)

    rt16 = FedRuntime(make_cfg(sketch_dtype="bfloat16", **extra), params,
                      quad_loss, num_clients=16, mesh=mesh)
    # payload dtype pinned in the UNOPTIMIZED lowering: the program hands
    # the collective a bf16 table. (The compiled text cannot be asserted
    # on the CPU backend — its FloatSupport pass legally promotes bf16
    # all-reduces to f32 because CPU lacks bf16 arithmetic; TPU keeps the
    # native bf16 wire.)
    txt = rt16._round.lower(
        rt16.init_state(), cids, batch, mask,
        jnp.asarray(0.1, jnp.float32), rt16.cs, rt16._gid).as_text()
    # the sharded server tail (PR 11) reduce-SCATTERS the table over
    # columns, so the bf16 wire now pins the scattered collective: the
    # payload enters as the full bf16 table and leaves as the (r, c/8)
    # bf16 column shard
    assert re.search(
        r"stablehlo\.reduce_scatter.*?"
        r"\(tensor<3x32xbf16>\) -> tensor<3x4xbf16>", txt, re.S), \
        "expected a bf16 table reduce_scatter in the lowering"

    # numerics: bf16 wire stays near the fp32 wire...
    rt32 = FedRuntime(make_cfg(**extra), params, quad_loss,
                      num_clients=16, mesh=mesh)
    s16, s32 = rt16.init_state(), rt32.init_state()
    for _ in range(3):
        s16, _ = rt16.round(s16, cids, batch, mask, 0.1)
        s32, _ = rt32.round(s32, cids, batch, mask, 0.1)
    assert np.all(np.isfinite(np.asarray(s16.ps_weights)))
    np.testing.assert_allclose(np.asarray(s16.ps_weights),
                               np.asarray(s32.ps_weights),
                               rtol=0.05, atol=1e-3)
    # ...and the single-device emulation matches the mesh wire closely
    # (identical quantization points up to reduction order)
    rt1 = FedRuntime(make_cfg(sketch_dtype="bfloat16", **extra), params,
                     quad_loss, num_clients=16)
    s1 = rt1.init_state()
    for _ in range(3):
        s1, _ = rt1.round(s1, cids, batch, mask, 0.1)
    d = rt1.cfg.grad_size
    np.testing.assert_allclose(np.asarray(s1.ps_weights),
                               np.asarray(s16.ps_weights[:d]),
                               rtol=0.02, atol=1e-3)


def test_sharded_val_matches_dense():
    """Mesh-parallel validation (VERDICT r2 item 6): the val batch shards
    over all devices and the weighted recombination must equal the dense
    single-device evaluation — including a non-mesh-divisible item count
    (padded+masked) and an odd valid-mask."""
    cfg = make_cfg(mode="uncompressed")
    params = {"w": jnp.asarray(
        np.random.RandomState(0).randn(6, 3), jnp.float32)}
    mesh = make_mesh((8,), ("clients",))
    rt_single = FedRuntime(cfg, params, quad_loss, num_clients=16)
    rt_mesh = FedRuntime(cfg, params, quad_loss, num_clients=16, mesh=mesh)
    s1, s2 = rt_single.init_state(), rt_mesh.init_state()

    rng = np.random.RandomState(5)
    for N in (32, 13):  # mesh-divisible and not
        batch = {"x": jnp.asarray(rng.randn(N, 6), jnp.float32),
                 "y": jnp.asarray(rng.randn(N, 3), jnp.float32)}
        mask = jnp.asarray(rng.rand(N) > 0.3)
        r1, n1 = rt_single.val(s1, batch, mask)
        r2, n2 = rt_mesh.val(s2, batch, mask)
        assert float(n1) == float(n2)
        for a, b in zip(r1, r2):
            np.testing.assert_allclose(float(a), float(b), rtol=1e-5)


def test_make_mesh_defaults():
    assert make_mesh((), ("clients",),
                     devices=jax.devices()[:1]) is None
    m = make_mesh((), ("clients",))
    assert m is not None and m.shape["clients"] == 8
    with pytest.raises(ValueError):
        make_mesh((16,), ("clients",))


def test_fedavg_vector_lr_on_mesh():
    """A per-param LR vector (Fixup groups) must work in fedavg mode on a
    mesh with non-divisible d: the server sees it padded, the client step
    true-d."""
    cfg = make_cfg(mode="fedavg", error_type="none", local_momentum=0.0,
                   local_batch_size=-1, max_client_batch=4,
                   fedavg_batch_size=2, num_fedavg_epochs=1)
    params = {"w": jnp.asarray(
        np.random.RandomState(0).randn(6, 3), jnp.float32)}
    mesh = make_mesh((8,), ("clients",))
    rt = FedRuntime(cfg, params, quad_loss, num_clients=16, mesh=mesh)
    assert rt.d_pad != rt.cfg.grad_size
    state = rt.init_state()
    batch, mask, cids = make_batch(1)
    lr_vec = jnp.full((rt.cfg.grad_size,), 0.05, jnp.float32)
    s2, _ = rt.round(state, cids, batch, mask, lr_vec)
    s_ref, _ = rt.round(rt.init_state(), cids, batch, mask, 0.05)
    np.testing.assert_allclose(np.asarray(s2.ps_weights),
                               np.asarray(s_ref.ps_weights), rtol=1e-5)


def test_sketch_vector_lr_on_mesh():
    """Per-param LR vector in sketch mode on a non-divisible-d mesh: the
    padded vector must slice back to true d for the table-space server
    update."""
    cfg = make_cfg(mode="sketch", error_type="virtual", k=5, num_rows=3,
                   num_cols=32, num_blocks=2)
    params = {"w": jnp.asarray(
        np.random.RandomState(0).randn(6, 3), jnp.float32)}
    mesh = make_mesh((8,), ("clients",))
    rt = FedRuntime(cfg, params, quad_loss, num_clients=16, mesh=mesh)
    assert rt.d_pad != rt.cfg.grad_size
    batch, mask, cids = make_batch(1)
    lr_vec = jnp.full((rt.cfg.grad_size,), 0.05, jnp.float32)
    s2, _ = rt.round(rt.init_state(), cids, batch, mask, lr_vec)
    s_ref, _ = rt.round(rt.init_state(), cids, batch, mask, 0.05)
    np.testing.assert_allclose(np.asarray(s2.ps_weights),
                               np.asarray(s_ref.ps_weights), rtol=1e-5)
