"""Unit tests for compression kernels: top-k, clipping, CountSketch.

Property tests follow SURVEY.md §4's implications: sketch linearity,
heavy-hitter recovery, lossless-limit equivalence with exact top-k.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.ops import (
    clip_by_l2_norm,
    make_sketch,
    sketch_decode,
    sketch_encode,
    sketch_l2estimate,
    sketch_unsketch,
    topk,
)


class TestTopk:
    def test_matches_numpy(self):
        rng = np.random.RandomState(0)
        vec = rng.randn(1000).astype(np.float32)
        k = 17
        out = np.asarray(topk(jnp.asarray(vec), k))
        # nonzero exactly at the k largest |v|
        order = np.argsort(vec**2)[::-1][:k]
        expected = np.zeros_like(vec)
        expected[order] = vec[order]
        np.testing.assert_allclose(out, expected)

    def test_2d_rowwise(self):
        rng = np.random.RandomState(1)
        mat = rng.randn(4, 100).astype(np.float32)
        out = np.asarray(topk(jnp.asarray(mat), 5))
        for i in range(4):
            assert (out[i] != 0).sum() == 5
            kept = np.abs(mat[i])[out[i] != 0].min()
            dropped = np.abs(mat[i])[out[i] == 0].max()
            assert kept >= dropped

    def test_jit(self):
        vec = jnp.arange(10.0) - 5.0
        out = jax.jit(lambda v: topk(v, 3))(vec)
        assert int((out != 0).sum()) == 3

    def test_approx_path(self):
        """Pin the --approx_topk plumbing (jit, vmap, 1-D and row-wise 2-D).
        approx_max_k has 0.95 default recall, so compare support overlap
        rather than exact equality."""
        rng = np.random.RandomState(10)
        vec = jnp.asarray(rng.randn(4096).astype(np.float32))
        k = 64
        out = jax.jit(lambda v: topk(v, k, approx=True))(vec)
        assert int((np.asarray(out) != 0).sum()) == k
        exact_support = set(np.nonzero(np.asarray(topk(vec, k)))[0])
        approx_support = set(np.nonzero(np.asarray(out))[0])
        assert len(exact_support & approx_support) >= int(0.9 * k)
        # values at recovered coords are the originals
        idx = sorted(approx_support)
        np.testing.assert_allclose(np.asarray(out)[idx],
                                   np.asarray(vec)[idx])
        mats = jnp.asarray(rng.randn(3, 2048).astype(np.float32))
        out2 = jax.jit(jax.vmap(lambda v: topk(v, 16, approx=True)))(mats)
        assert out2.shape == mats.shape
        assert all(int((r != 0).sum()) == 16 for r in np.asarray(out2))


class TestClip:
    def test_noop_below_threshold(self):
        v = jnp.array([0.3, 0.4])  # norm 0.5
        np.testing.assert_allclose(np.asarray(clip_by_l2_norm(v, 1.0)), [0.3, 0.4])

    def test_scales_above_threshold(self):
        v = jnp.array([3.0, 4.0])  # norm 5
        out = np.asarray(clip_by_l2_norm(v, 1.0))
        np.testing.assert_allclose(np.linalg.norm(out), 1.0, rtol=1e-6)
        np.testing.assert_allclose(out, [0.6, 0.8], rtol=1e-6)

    def test_sketch_table_uses_l2estimate(self):
        """Clipping a sketch table must clip by the median row norm
        (csvec l2estimate semantics, reference utils.py:305-313), not the
        Frobenius norm — the clipped table's estimate equals the threshold."""
        rng = np.random.RandomState(9)
        cs2 = make_sketch(d=D, c=C, r=R, num_blocks=1, seed=13)
        v = jnp.asarray((rng.randn(D) * 3).astype(np.float32))
        table = sketch_encode(cs2, v)
        est_before = float(sketch_l2estimate(cs2, table))
        clip = est_before / 2
        clipped = clip_by_l2_norm(table, clip)
        np.testing.assert_allclose(float(sketch_l2estimate(cs2, clipped)),
                                   clip, rtol=1e-5)


D, C, R = 5000, 2000, 5


@pytest.fixture(scope="module")
def cs():
    return make_sketch(d=D, c=C, r=R, num_blocks=4, seed=7)


class TestSketch:
    def test_linearity(self, cs):
        rng = np.random.RandomState(2)
        a = jnp.asarray(rng.randn(D).astype(np.float32))
        b = jnp.asarray(rng.randn(D).astype(np.float32))
        t = sketch_encode(cs, a) + sketch_encode(cs, b)
        t_sum = sketch_encode(cs, a + b)
        np.testing.assert_allclose(np.asarray(t), np.asarray(t_sum), atol=1e-4)

    def test_block_invariance(self):
        """Table must not depend on num_blocks (it is a memory knob only)."""
        rng = np.random.RandomState(3)
        v = jnp.asarray(rng.randn(D).astype(np.float32))
        t1 = sketch_encode(make_sketch(D, C, R, num_blocks=1, seed=7), v)
        t4 = sketch_encode(make_sketch(D, C, R, num_blocks=4, seed=7), v)
        t7 = sketch_encode(make_sketch(D, C, R, num_blocks=7, seed=7), v)
        np.testing.assert_allclose(np.asarray(t1), np.asarray(t4), atol=1e-4)
        np.testing.assert_allclose(np.asarray(t1), np.asarray(t7), atol=1e-4)

    def test_heavy_hitter_recovery(self, cs):
        """A vector with k big spikes + small noise: unsketch finds the spikes."""
        rng = np.random.RandomState(4)
        k = 10
        v = rng.randn(D).astype(np.float32) * 0.01
        spikes = rng.choice(D, k, replace=False)
        v[spikes] = np.sign(rng.randn(k)) * (10.0 + rng.rand(k))
        table = sketch_encode(cs, jnp.asarray(v))
        rec = np.asarray(sketch_unsketch(cs, table, k))
        assert set(np.nonzero(rec)[0]) == set(spikes)
        np.testing.assert_allclose(rec[spikes], v[spikes], rtol=0.05, atol=0.1)

    def test_lossless_limit_matches_topk(self):
        """With a huge table (c >> d), estimates ≈ exact values, so
        unsketch(k) must equal exact topk(k) (SURVEY.md §4 golden strategy)."""
        d = 200
        cs_big = make_sketch(d=d, c=50_000, r=7, num_blocks=1, seed=11)
        rng = np.random.RandomState(5)
        v = jnp.asarray(rng.randn(d).astype(np.float32))
        table = sketch_encode(cs_big, v)
        est = np.asarray(sketch_decode(cs_big, table))
        np.testing.assert_allclose(est, np.asarray(v), atol=1e-3)
        rec = np.asarray(sketch_unsketch(cs_big, table, 20))
        exact = np.asarray(topk(v, 20))
        np.testing.assert_allclose(rec, exact, atol=1e-3)

    def test_l2_estimate(self, cs):
        rng = np.random.RandomState(6)
        v = jnp.asarray(rng.randn(D).astype(np.float32))
        table = sketch_encode(cs, v)
        est = float(sketch_l2estimate(cs, table))
        true = float(jnp.linalg.norm(v))
        assert abs(est - true) / true < 0.15

    def test_decode_at_matches_decode(self, cs):
        """decode_at(table, idx) == decode(table)[idx] — the contract the
        subtractive error-feedback momentum masking relies on
        (core/server.py)."""
        rng = np.random.RandomState(9)
        v = jnp.asarray(rng.randn(D).astype(np.float32))
        table = sketch_encode(cs, v)
        idx = jnp.asarray(rng.choice(D, 40, replace=False))
        np.testing.assert_allclose(
            np.asarray(cs.decode_at(table, idx)),
            np.asarray(cs.decode(table))[np.asarray(idx)], atol=1e-5)

    def test_encode_jit_and_vmap(self, cs):
        rng = np.random.RandomState(8)
        vs = jnp.asarray(rng.randn(3, D).astype(np.float32))
        tables = jax.jit(jax.vmap(lambda v: sketch_encode(cs, v)))(vs)
        assert tables.shape == (3, R, C)
        # vmapped encode must agree with single encode
        single = sketch_encode(cs, vs[1])
        np.testing.assert_allclose(np.asarray(tables[1]), np.asarray(single),
                                   atol=1e-4)

    def test_sign_balance(self, cs):
        """Hash quality smoke check: bucket histogram ~uniform, signs ~balanced."""
        from commefficient_tpu.ops.sketch import _buckets_signs
        idx = jnp.arange(D, dtype=jnp.uint32)
        buckets, signs = _buckets_signs(cs, idx)
        assert float(jnp.abs(signs.mean())) < 0.05
        counts = np.bincount(np.asarray(buckets[0]), minlength=C)
        # expected D/C per bucket = 2.5; max shouldn't explode
        assert counts.max() < 15


@pytest.fixture(scope="module")
def ccs():
    from commefficient_tpu.ops.circulant import make_circulant_sketch
    return make_circulant_sketch(d=D, c=C, r=R, num_blocks=2, seed=7)


class TestCirculantSketch:
    """Circulant count sketch (ops/circulant.py): same property surface as
    the hash impl — it must be a drop-in (r, c) linear sketch with
    count-sketch estimator guarantees — plus the static-roll layout rules."""

    def test_linearity(self, ccs):
        rng = np.random.RandomState(2)
        a = jnp.asarray(rng.randn(D).astype(np.float32))
        b = jnp.asarray(rng.randn(D).astype(np.float32))
        np.testing.assert_allclose(
            np.asarray(ccs.encode(a) + ccs.encode(b)),
            np.asarray(ccs.encode(a + b)), atol=1e-4)

    def test_block_invariance(self):
        """num_blocks is a decode-memory knob only — table and decode must
        not depend on it."""
        from commefficient_tpu.ops.circulant import make_circulant_sketch
        rng = np.random.RandomState(3)
        v = jnp.asarray(rng.randn(D).astype(np.float32))
        t1 = make_circulant_sketch(D, C, R, num_blocks=1, seed=7)
        t3 = make_circulant_sketch(D, C, R, num_blocks=3, seed=7)
        np.testing.assert_allclose(np.asarray(t1.encode(v)),
                                   np.asarray(t3.encode(v)), atol=1e-4)
        np.testing.assert_allclose(np.asarray(t1.decode(t1.encode(v))),
                                   np.asarray(t3.decode(t3.encode(v))),
                                   atol=1e-4)

    def test_heavy_hitter_recovery(self, ccs):
        rng = np.random.RandomState(4)
        k = 10
        v = rng.randn(D).astype(np.float32) * 0.01
        spikes = rng.choice(D, k, replace=False)
        v[spikes] = np.sign(rng.randn(k)) * (10.0 + rng.rand(k))
        rec = np.asarray(ccs.unsketch(ccs.encode(jnp.asarray(v)), k))
        assert set(np.nonzero(rec)[0]) == set(spikes)
        np.testing.assert_allclose(rec[spikes], v[spikes], rtol=0.05,
                                   atol=0.1)

    def test_lossless_limit_exact(self):
        """c >= d => single block, rolls are invertible: decode is EXACT."""
        from commefficient_tpu.ops.circulant import make_circulant_sketch
        d = 200
        cs_big = make_circulant_sketch(d=d, c=256, r=3, seed=11)
        rng = np.random.RandomState(5)
        v = jnp.asarray(rng.randn(d).astype(np.float32))
        np.testing.assert_allclose(
            np.asarray(cs_big.decode(cs_big.encode(v))), np.asarray(v),
            atol=1e-5)

    def test_encode_at_matches_dense(self, ccs):
        """encode_at on a k-sparse vector == full encode (the server's
        error-feedback re-encode contract, reference
        fed_aggregator.py:593-595)."""
        rng = np.random.RandomState(6)
        idx = jnp.asarray(rng.choice(D, 50, replace=False))
        v = jnp.zeros((D,), jnp.float32).at[idx].set(
            jnp.asarray(rng.randn(50), jnp.float32))
        np.testing.assert_allclose(np.asarray(ccs.encode_at(v, idx)),
                                   np.asarray(ccs.encode(v)), atol=1e-4)

    def test_decode_at_matches_decode(self, ccs):
        """decode_at(table, idx) == decode(table)[idx] for the circulant
        impl (subtractive-EF momentum masking contract, core/server.py)."""
        rng = np.random.RandomState(10)
        v = jnp.asarray(rng.randn(D).astype(np.float32))
        table = ccs.encode(v)
        idx = jnp.asarray(rng.choice(D, 40, replace=False))
        np.testing.assert_allclose(
            np.asarray(ccs.decode_at(table, idx)),
            np.asarray(ccs.decode(table))[np.asarray(idx)], atol=1e-5)

    def test_l2_estimate(self, ccs):
        rng = np.random.RandomState(6)
        v = jnp.asarray(rng.randn(D).astype(np.float32))
        est = float(ccs.l2estimate(ccs.encode(v)))
        true = float(jnp.linalg.norm(v))
        assert abs(est - true) / true < 0.15

    def test_jit_with_sketch_argument(self, ccs):
        """The runtime threads the sketch as a jit ARGUMENT; the static
        shifts live in pytree aux data, so this must trace cleanly."""
        rng = np.random.RandomState(8)
        v = jnp.asarray(rng.randn(D).astype(np.float32))
        t = jax.jit(lambda cs, x: cs.encode(x))(ccs, v)
        np.testing.assert_allclose(np.asarray(t), np.asarray(ccs.encode(v)),
                                   atol=1e-4)

    def test_gather_fallback_matches_unrolled(self, monkeypatch):
        """Extreme d/c ratios (m > _UNROLL_MAX_BLOCKS) switch encode/decode
        to one (m, c) gather per row; results must be identical to the
        static-roll path."""
        from commefficient_tpu.ops import circulant as circ
        cs = circ.make_circulant_sketch(d=119, c=2, r=3, seed=3)  # m=60
        rng = np.random.RandomState(1)
        v = jnp.asarray(rng.randn(119).astype(np.float32))
        t_roll = cs.encode(v)
        dec_roll = cs.decode(t_roll)
        monkeypatch.setattr(circ.CirculantSketch, "_UNROLL_MAX_BLOCKS", 8)
        t_gather = cs.encode(v)
        np.testing.assert_allclose(np.asarray(t_roll),
                                   np.asarray(t_gather), atol=1e-5)
        np.testing.assert_allclose(np.asarray(dec_roll),
                                   np.asarray(cs.decode(t_gather)),
                                   atol=1e-5)

    def test_aligned_shift_granularity(self):
        """c % 1024 == 0 => shifts are multiples of 1024 (the pallas
        no-rotate enabler); unaligned c keeps full-range shifts."""
        from commefficient_tpu.ops import circulant as circ
        cs = circ.make_circulant_sketch(d=9000, c=2048, r=3, seed=5)
        assert all(s % 1024 == 0 for row in cs.shifts for s in row)
        assert any(s != 0 for row in cs.shifts for s in row)
        cs2 = circ.make_circulant_sketch(d=9000, c=500, r=3, seed=5)
        assert any(s % 1024 for row in cs2.shifts for s in row)

    def test_pallas_kernels_match_roll_path(self, monkeypatch):
        """The fused pallas kernels (ops/circulant_pallas.py v4,
        sublane-slice span extraction) must reproduce the roll path
        exactly — validated here in interpret mode (CPU); the TPU decode
        path is on by default when eligible."""
        from commefficient_tpu.ops import circulant as circ
        from commefficient_tpu.ops.circulant_pallas import (pallas_decode,
                                                            pallas_encode)
        cs = circ.make_circulant_sketch(d=9000, c=2048, r=5, num_blocks=3,
                                        seed=7)
        rng = np.random.RandomState(0)
        v = jnp.asarray(rng.randn(9000).astype(np.float32))
        t_roll = cs.encode(v)
        vp = jnp.pad(v, (0, cs.m * cs.c - cs.d))
        shifts = jnp.asarray(cs.shifts, jnp.int32)
        t_pl = pallas_encode(vp, shifts, cs.sign_keys, c=cs.c, r=cs.r,
                             m=cs.m, interpret=True)
        np.testing.assert_allclose(np.asarray(t_pl), np.asarray(t_roll),
                                   atol=1e-4)
        d_pl = pallas_decode(t_roll, shifts, cs.sign_keys, c=cs.c, r=cs.r,
                             m=cs.m, interpret=True)[: cs.d]
        np.testing.assert_allclose(np.asarray(d_pl),
                                   np.asarray(cs.decode(t_roll)), atol=1e-5)

    def test_pallas_multi_lane_tile_matches_roll_path(self, monkeypatch):
        """At real scale (c=524288 > _CT_MAX) the kernels tile the lane
        dimension; spans then cross lane-tile (and mod-c wrap) boundaries
        through the wrap padding. Exercise that path by shrinking _CT_MAX
        so c=2048 splits into 2 tiles of 1024."""
        from commefficient_tpu.ops import circulant as circ
        from commefficient_tpu.ops import circulant_pallas as cp
        monkeypatch.setattr(cp, "_CT_MAX", 1024)
        assert cp._lane_tile(2048) == 1024
        # d chosen so m differs from the test above: pallas_encode is
        # jit-cached on (c, r, m, interpret), and a shape collision would
        # silently reuse the un-monkeypatched single-tile trace
        cs = circ.make_circulant_sketch(d=11000, c=2048, r=5, num_blocks=3,
                                        seed=11)
        rng = np.random.RandomState(2)
        v = jnp.asarray(rng.randn(11000).astype(np.float32))
        t_roll = cs.encode(v)
        vp = jnp.pad(v, (0, cs.m * cs.c - cs.d))
        shifts = jnp.asarray(cs.shifts, jnp.int32)
        t_pl = cp.pallas_encode(vp, shifts, cs.sign_keys, c=cs.c, r=cs.r,
                                m=cs.m, interpret=True)
        np.testing.assert_allclose(np.asarray(t_pl), np.asarray(t_roll),
                                   atol=1e-4)
        d_pl = cp.pallas_decode(t_roll, shifts, cs.sign_keys, c=cs.c,
                                r=cs.r, m=cs.m, interpret=True)[: cs.d]
        np.testing.assert_allclose(np.asarray(d_pl),
                                   np.asarray(cs.decode(t_roll)), atol=1e-5)
