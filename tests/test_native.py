"""Native C++ data-plane: build, determinism, equivalence with numpy path."""

import numpy as np
import pytest

from commefficient_tpu.data import native
from commefficient_tpu.data.transforms import (
    CIFAR10_MEAN,
    CIFAR10_STD,
    CifarEval,
    CifarTrain,
)

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native fedloader not built")


@pytest.fixture(scope="module")
def images():
    rng = np.random.RandomState(0)
    return rng.randint(0, 255, (50, 32, 32, 3), dtype=np.uint8)


def test_gather_normalize_matches_numpy(images):
    idx = np.array([[3, 7], [10, 49]], np.int64)
    out = native.gather_normalize(images, idx, CIFAR10_MEAN, CIFAR10_STD)
    assert out.shape == (2, 2, 32, 32, 3)
    ref = (images[idx].astype(np.float32) / 255.0 - CIFAR10_MEAN) / CIFAR10_STD
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_gather_augment_deterministic(images):
    idx = np.arange(20, dtype=np.int64)
    a = native.gather_augment(images, idx, CIFAR10_MEAN, CIFAR10_STD,
                              pad=4, flip=True, seed=123)
    b = native.gather_augment(images, idx, CIFAR10_MEAN, CIFAR10_STD,
                              pad=4, flip=True, seed=123)
    np.testing.assert_array_equal(a, b)
    c = native.gather_augment(images, idx, CIFAR10_MEAN, CIFAR10_STD,
                              pad=4, flip=True, seed=124)
    assert np.abs(a - c).max() > 0  # different stream


def test_augment_statistics(images):
    """Augmented output must stay in the normalized value range and keep
    per-image content (crop of reflect-padded image)."""
    idx = np.arange(50, dtype=np.int64)
    out = native.gather_augment(images, idx, CIFAR10_MEAN, CIFAR10_STD,
                                pad=4, flip=True, seed=7)
    lo = (0.0 - max(CIFAR10_MEAN)) / min(CIFAR10_STD)
    hi = (1.0 - min(CIFAR10_MEAN)) / min(CIFAR10_STD)
    assert out.min() >= lo - 1e-4 and out.max() <= hi + 1e-4
    # every output pixel value must exist in the source image's value set
    src_vals = ((images[0].astype(np.float32) / 255.0 - CIFAR10_MEAN)
                / CIFAR10_STD)
    assert np.isin(np.round(out[0], 4), np.round(src_vals, 4)).mean() > 0.99


def test_transform_fused_paths(images):
    train = CifarTrain()
    ev = CifarEval()
    idx = np.arange(8, dtype=np.int64)
    ft = train.gather_fused(images, idx)
    fe = ev.gather_fused(images, idx)
    assert ft.shape == fe.shape == (8, 32, 32, 3)
    ref = (images[idx].astype(np.float32) / 255.0 - CIFAR10_MEAN) / CIFAR10_STD
    np.testing.assert_allclose(fe, ref, rtol=1e-5, atol=1e-5)
