"""Property tests for the SRHT sketch (ops/rht.py) — the MXU-native
alternative to the hash count sketch. Mirrors the CSVec-property suite in
test_ops.py::TestSketch: linearity (tables must psum correctly), lossless
exactness, heavy-hitter recovery under compression, norm estimation, and
table clipping."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.ops.rht import make_rht_sketch
from commefficient_tpu.ops.sketch import make_sketch_impl


class TestRHTSketch:
    def test_linearity(self):
        cs = make_rht_sketch(d=1000, c=128, r=3, seed=0)
        rng = np.random.RandomState(0)
        a = jnp.asarray(rng.randn(1000), jnp.float32)
        b = jnp.asarray(rng.randn(1000), jnp.float32)
        np.testing.assert_allclose(
            np.asarray(cs.encode(a) + cs.encode(b)),
            np.asarray(cs.encode(a + b)), rtol=1e-4, atol=1e-4)

    def test_lossless_roundtrip_exact(self):
        """c == padded transform size => S is a permutation and decode is the
        exact inverse (the analogue of a collision-free count sketch)."""
        d = 700
        cs = make_rht_sketch(d=d, c=1024, r=3, seed=1)
        assert cs.dp == 1024
        v = jnp.asarray(np.random.RandomState(1).randn(d), jnp.float32)
        est = cs.decode(cs.encode(v))
        np.testing.assert_allclose(np.asarray(est), np.asarray(v),
                                   rtol=1e-4, atol=1e-4)

    def test_heavy_hitter_recovery(self):
        """A strongly k-sparse signal's support and values survive 8x
        compression through the median-of-r estimates."""
        d, k = 8192, 8
        cs = make_rht_sketch(d=d, c=1024, r=5, seed=2)
        rng = np.random.RandomState(2)
        v = rng.randn(d).astype(np.float32) * 0.1
        idx = rng.choice(d, k, replace=False)
        v[idx] = 50.0 * np.sign(rng.randn(k))
        dense, got_idx = cs.unsketch_with_idx(cs.encode(jnp.asarray(v)), k)
        assert set(np.asarray(got_idx).tolist()) == set(idx.tolist())
        np.testing.assert_allclose(np.asarray(dense)[idx], v[idx], rtol=0.2)

    def test_decode_unbiased(self):
        """Averaged over independent sketches, the estimate of a fixed
        vector converges to the vector (E[est] = v)."""
        d = 512
        v = np.random.RandomState(3).randn(d).astype(np.float32)
        acc = np.zeros(d, np.float64)
        n = 30
        for s in range(n):
            cs = make_rht_sketch(d=d, c=128, r=1, seed=100 + s)
            acc += np.asarray(cs.decode(cs.encode(jnp.asarray(v))))
        err = np.abs(acc / n - v).mean() / np.abs(v).mean()
        assert err < 0.35, err

    def test_l2estimate(self):
        d = 4096
        cs = make_rht_sketch(d=d, c=512, r=5, seed=4)
        v = jnp.asarray(np.random.RandomState(4).randn(d), jnp.float32)
        est = float(cs.l2estimate(cs.encode(v)))
        true = float(jnp.linalg.norm(v))
        assert abs(est - true) / true < 0.15, (est, true)

    def test_clip_scales_to_threshold(self):
        d = 4096
        cs = make_rht_sketch(d=d, c=512, r=5, seed=5)
        v = jnp.asarray(np.random.RandomState(5).randn(d), jnp.float32) * 10
        t = cs.encode(v)
        clipped = cs.clip(t, 1.0)
        assert float(cs.l2estimate(clipped)) <= 1.0 + 1e-4
        # under the threshold => untouched
        np.testing.assert_array_equal(np.asarray(cs.clip(t, 1e9)),
                                      np.asarray(t))

    def test_scan_rows_equivalence(self):
        """Row-at-a-time (large-model memory mode) must match the batched
        transform path exactly — same signs, samples, and math."""
        d, c, r = 3000, 512, 4
        a = make_rht_sketch(d=d, c=c, r=r, seed=7, scan_rows=False)
        b = make_rht_sketch(d=d, c=c, r=r, seed=7, scan_rows=True)
        rng = np.random.RandomState(7)
        v = jnp.asarray(rng.randn(d), jnp.float32)
        ta, tb = a.encode(v), b.encode(v)
        np.testing.assert_allclose(np.asarray(ta), np.asarray(tb),
                                   rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(np.asarray(a.decode(ta)),
                                   np.asarray(b.decode(tb)),
                                   rtol=1e-6, atol=1e-6)
        # batched variants too
        vs = jnp.asarray(rng.randn(2, d), jnp.float32)
        np.testing.assert_allclose(np.asarray(a.encode(vs)),
                                   np.asarray(b.encode(vs)),
                                   rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(np.asarray(a.decode(a.encode(vs))),
                                   np.asarray(b.decode(b.encode(vs))),
                                   rtol=1e-6, atol=1e-6)
        # the on-the-fly sign branch (models past the precompute limit) must
        # agree with the precomputed int8 branch in the batched path:
        # _signs and _signs_row derive from the same mixer
        import dataclasses
        b_fly = dataclasses.replace(b, signs_i8=None)
        a_fly = dataclasses.replace(a, signs_i8=None)
        np.testing.assert_allclose(np.asarray(a_fly.encode(v)),
                                   np.asarray(b_fly.encode(v)),
                                   rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(np.asarray(a_fly.decode(a_fly.encode(v))),
                                   np.asarray(b_fly.decode(b_fly.encode(v))),
                                   rtol=1e-6, atol=1e-6)

    def test_factory_dispatch(self):
        rht = make_sketch_impl("rht", d=100, c=64, r=3)
        hsh = make_sketch_impl("hash", d=100, c=64, r=3)
        assert rht.dense_transform and not hsh.dense_transform
        with pytest.raises(ValueError):
            make_sketch_impl("nope", d=100, c=64, r=3)

    def test_jit_and_native_batching(self):
        cs = make_rht_sketch(d=500, c=128, r=3, seed=6)
        vs = jnp.asarray(np.random.RandomState(6).randn(4, 500), jnp.float32)
        tables = jax.jit(cs.encode)(vs)
        assert tables.shape == (4, 3, 128)
        # batched encode of each == unbatched encode of each
        np.testing.assert_allclose(np.asarray(tables[0]),
                                   np.asarray(cs.encode(vs[0])),
                                   rtol=1e-5, atol=1e-5)
        ests = jax.jit(cs.decode)(tables)
        assert ests.shape == (4, 500)
        np.testing.assert_allclose(np.asarray(ests[2]),
                                   np.asarray(cs.decode(tables[2])),
                                   rtol=1e-5, atol=1e-5)
