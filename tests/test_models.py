"""Model zoo: shapes, init properties, registry surface.

The reference has no model tests at all (SURVEY.md §4); these pin down the
structural contracts: output shapes, Fixup zero-init (residual branches and
classifier start at zero => deterministic initial logits), and the
name-registry the drivers select through.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu import models


def init_and_apply(model, x):
    params = model.init(jax.random.PRNGKey(0), x)
    return params, model.apply(params, x)


def test_registry_has_reference_names():
    # the reference exports these via models/__init__.py:1-7
    for name in ["ResNet9", "FixupResNet9", "ResNet18", "FixupResNet18",
                 "FixupResNet50", "ResNet101LN", "resnet18",
                 "wide_resnet101_2"]:
        assert name in models.MODEL_NAMES
    with pytest.raises(ValueError):
        models.get_model("nope")


@pytest.mark.parametrize("bn", [False, True])
def test_resnet9_shape(bn):
    model = models.ResNet9(do_batchnorm=bn, num_classes=10)
    x = jnp.ones((2, 32, 32, 3))
    _, y = init_and_apply(model, x)
    assert y.shape == (2, 10)
    assert np.all(np.isfinite(y))


def test_resnet9_param_count_matches_reference_scale():
    # reference ResNet9 (no BN) has ~6.57M params; ours must be the same
    # architecture so the same order (exact conv/linear shapes).
    model = models.ResNet9(do_batchnorm=False)
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 32, 32, 3)))
    n = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    assert 6_000_000 < n < 7_000_000, n


def test_fixup_resnet9_initial_logits_finite():
    model = models.FixupResNet9(num_classes=10)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    _, y = init_and_apply(model, x)
    assert y.shape == (2, 10)
    assert np.all(np.isfinite(y))


def test_fixup_resnet18_zero_init_classifier():
    model = models.FixupResNet18(num_classes=10)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    _, y = init_and_apply(model, x)
    # zero-init classifier (reference fixup_resnet18.py:101-103)
    np.testing.assert_allclose(np.asarray(y), 0.0)


def test_resnet18_bn_shape():
    model = models.ResNet18(num_classes=10)
    x = jnp.ones((2, 32, 32, 3))
    _, y = init_and_apply(model, x)
    assert y.shape == (2, 10)


def test_layernorm_resnet18_emnist_shape():
    # 1-channel input is the reference's EMNIST modification (resnets.py:155)
    model = models.resnet18(num_classes=62, norm="layer")
    x = jnp.ones((2, 28, 28, 1))
    _, y = init_and_apply(model, x)
    assert y.shape == (2, 62)


def test_fixup_resnet50_residual_identity_at_init():
    model = models.FixupResNet50(num_classes=7)
    x = jax.random.normal(jax.random.PRNGKey(2), (1, 64, 64, 3))
    _, y = init_and_apply(model, x)
    # zero-init fc => all logits exactly zero at init
    np.testing.assert_allclose(np.asarray(y), 0.0)


def test_model_grads_flow():
    model = models.ResNet9(num_classes=10)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 32, 32, 3))
    params = model.init(jax.random.PRNGKey(0), x)

    def loss(p):
        return model.apply(p, x).sum()

    g = jax.grad(loss)(params)
    norms = [float(jnp.linalg.norm(t)) for t in jax.tree.leaves(g)]
    assert any(n > 0 for n in norms)


def test_attn_impl_resolver_and_cpu_fallback():
    """--attn_impl plumbing: resolve_attn maps names to callables and
    rejects unknowns; off-TPU (this CPU suite) flash_causal_attention
    must fall back to the dense path bit-exactly (the kernel itself is
    parity-checked on hardware by scripts/check_flash_attn.py)."""
    import pytest

    from commefficient_tpu.models.gpt2 import (ATTN_IMPLS,
                                               dense_causal_attention,
                                               flash_causal_attention,
                                               resolve_attn)

    from commefficient_tpu.models.gpt2 import auto_causal_attention

    assert resolve_attn("dense") is dense_causal_attention
    # "flash" resolves to a warn-on-fallback variant of the kernel
    # (ADVICE r4: explicit flash requests must not silently run dense)
    assert resolve_attn("flash").func is flash_causal_attention
    assert resolve_attn("flash").keywords == {"_warn_fallback": True}
    assert resolve_attn("auto") is auto_causal_attention
    with pytest.raises(ValueError, match="unknown attn_impl"):
        resolve_attn("paged")
    q = jax.random.normal(jax.random.PRNGKey(0), (2, 128, 4, 16))
    d = dense_causal_attention(q, q, q)
    f = flash_causal_attention(q, q, q)   # CPU => dense fallback
    a = auto_causal_attention(q, q, q)    # S=128 < 1024 => dense
    np.testing.assert_array_equal(np.asarray(d), np.asarray(f))
    np.testing.assert_array_equal(np.asarray(d), np.asarray(a))
    assert sorted(ATTN_IMPLS) == ["auto", "dense", "flash"]
