"""Preemption-safe rounds (crash recovery, checkpoint integrity, hang
watchdog, fault injection — core/preempt.py, faults.py, the checkpoint
fallback and the telemetry append-resume path).

The contract under test: a run interrupted at ANY round — graceful
SIGTERM drain or hard kill — resumes BIT-identically to the
uninterrupted run (losses and final weights), keeps its host-ledger
state (quarantine bench/eject decisions survive the restart), falls
back a checkpoint generation instead of crashing on a damaged file,
never clobbers a predecessor's telemetry stream, and leaves no .tmp
litter or leaked threads behind. Hard kills (os._exit, skipping every
``finally``) are exercised by the subprocess crash matrix
(scripts/crash_matrix.py, the `slow` test at the bottom); everything
else runs in-process via the deterministic fault hooks."""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu import cv_train, faults
from commefficient_tpu.checkpoint import (CheckpointIntegrityError,
                                          CheckpointManager, load_state,
                                          save_state)
from commefficient_tpu.config import FedConfig
from commefficient_tpu.core import FedRuntime, RoundPipeline
from commefficient_tpu.core.preempt import (PreemptGuard, RoundWatchdog,
                                            collect_ledger_state,
                                            restore_ledger_state,
                                            stall_deadline_s, with_retries)
from commefficient_tpu.core.quarantine import QuarantineLedger
from commefficient_tpu.data.fed_sampler import FedSampler
from commefficient_tpu.telemetry import RunTelemetry, validate_file
from commefficient_tpu.telemetry.clients import ParticipationLedger
from commefficient_tpu.telemetry.health import AnomalyMonitor
from commefficient_tpu.telemetry.schema import validate_event
from commefficient_tpu.utils import TableLogger
from tests.test_telemetry import read_events

W, B, D_IN, D_OUT = 4, 2, 6, 3


def quad_loss(params, batch, mask):
    pred = batch["x"] @ params["w"]
    m = mask.astype(jnp.float32)
    denom = jnp.maximum(m.sum(), 1.0)
    err = ((pred - batch["y"]) ** 2).sum(axis=1)
    loss = (err * m).sum() / denom
    return loss, (loss,)


class FaultDS:
    """8 clients x 8 items (W=4, B=2 => 8 rounds/epoch), INDEX-keyed
    data: a resumed run gathers the exact same per-item rows the
    uninterrupted run would have — the bitwise-resume assertions ride
    on this."""

    data_per_client = np.full(8, 8)
    num_clients = 8
    _rng = np.random.RandomState(0)
    _x = _rng.randn(256, D_IN).astype(np.float32)
    _y = _rng.randn(256, D_OUT).astype(np.float32)

    def __len__(self):
        return 64

    def gather(self, idx):
        idx = np.asarray(idx)
        return {"x": self._x[idx], "y": self._y[idx]}


def make_rt(**kw):
    cfg_kw = dict(mode="sketch", error_type="virtual", local_momentum=0.0,
                  virtual_momentum=0.9, weight_decay=0.0, num_workers=W,
                  local_batch_size=B, track_bytes=True, num_clients=8,
                  num_results_train=2, num_results_val=2, k=5, num_rows=2,
                  num_cols=32, exact_num_cols=True, dataset_name="SYNTH",
                  telemetry_every=1)
    cfg_kw.update(kw)
    params = {"w": jnp.asarray(
        np.random.RandomState(0).randn(D_IN, D_OUT), jnp.float32)}
    return FedRuntime(FedConfig(**cfg_kw), params, quad_loss, num_clients=8)


def run_driver(tmp, *, resume=False, fault=None, num_epochs=2.0,
               telemetry=True, **cfg_kw):
    """One cv_train.train run through the REAL checkpoint/resume wiring
    (setup_checkpointing + RunTelemetry against a FIXED logdir, so a
    resumed run appends to its predecessor's stream)."""
    rt = make_rt(do_resume=resume, checkpoint_every=1,
                 checkpoint_path=str(tmp / "ck"), **cfg_kw)
    cfg = rt.cfg.replace(num_epochs=num_epochs, pivot_epoch=1.0)
    mgr, start_epoch, restored, resume_info = cv_train.setup_checkpointing(
        cfg, rt, "quad")
    state = restored if restored is not None else rt.init_state()
    tel = None
    if telemetry:
        tel = RunTelemetry(
            str(tmp / "logs"), "cv_train", cfg=rt.cfg,
            resume_info=(None if resume_info is None else
                         {"round": resume_info["global_round"],
                          "epoch": start_epoch,
                          "checkpoint": resume_info["checkpoint"]}))
        tel.instrument(rt)
    if fault:
        faults.set_fault(fault)
    try:
        state, summary = cv_train.train(
            cfg, rt, state, FaultDS(), FaultDS(),
            loggers=(TableLogger(),), telemetry=tel, ckpt_mgr=mgr,
            start_epoch=start_epoch, resume_info=resume_info)
    finally:
        faults.set_fault(None)
        if tel is not None:
            tel.close()
    return rt, state, summary, mgr, (tel.path if tel else None)


def round_losses(path):
    return {e["round"]: e["loss"] for e in read_events(path)
            if e["event"] == "round"}


# ----------------------------------------------------- preemption guard


def test_preempt_guard_first_signal_flags_second_forces():
    exits = []
    guard = PreemptGuard(grace_s=5.0, _exit=exits.append)
    old = signal.getsignal(signal.SIGTERM)
    guard.install()
    try:
        assert guard.installed and not guard.requested
        os.kill(os.getpid(), signal.SIGTERM)
        assert guard.requested and guard.signal_name == "SIGTERM"
        assert guard.grace_used_s() is not None
        assert not exits
        os.kill(os.getpid(), signal.SIGTERM)   # second: force-exit path
        assert exits == [128 + int(signal.SIGTERM)]
    finally:
        guard.uninstall()
    assert signal.getsignal(signal.SIGTERM) is old


def test_preempt_guard_rejects_nonpositive_grace():
    with pytest.raises(ValueError, match="grace"):
        PreemptGuard(grace_s=0.0)


def test_grace_budget_is_enforced():
    """force_exit_after is the drain's hard ceiling: it fires when the
    drain wedges past the remaining budget and is cancelled on a
    successful drain."""
    exits = []
    guard = PreemptGuard(grace_s=1.0, _exit=exits.append)
    t = guard.force_exit_after(0.02)
    time.sleep(0.2)
    assert exits == [1]
    exits.clear()
    t2 = guard.force_exit_after(5.0)
    t2.cancel()                       # the successful-drain path
    time.sleep(0.05)
    assert exits == []


def test_config_validates_preempt_and_watchdog():
    with pytest.raises(ValueError, match="preempt_grace"):
        FedConfig(preempt_grace=0.0)
    with pytest.raises(ValueError, match="preempt_grace"):
        FedConfig(preempt_grace=-3.0)
    with pytest.raises(ValueError, match="watchdog_mult"):
        FedConfig(watchdog_mult=0.5)
    FedConfig(watchdog_mult=1.0, preempt_grace=0.1)   # boundaries legal
    # a watchdog without telemetry records could never arm — the
    # silently-ignored-flag contract rejects the combination
    with pytest.raises(ValueError, match="--watchdog requires"):
        FedConfig(watchdog=True, telemetry=False)
    with pytest.raises(ValueError, match="--watchdog requires"):
        FedConfig(watchdog=True, telemetry_every=0)
    FedConfig(watchdog=True)                          # default cadence ok


def test_flight_recorder_state_upgrades_events_only_bundle(tmp_path):
    """A watchdog stall's events-only bundle must not consume the
    one-shot slot for STATE: a later NaN-abort record(state, ...) adds
    state.npz to the existing bundle instead of being swallowed."""
    from commefficient_tpu.telemetry.health import FlightRecorder
    rt = make_rt()
    rec = FlightRecorder(str(tmp_path))
    out = rec.record(None, {"rule": "round_stall", "round": 3})
    assert out is not None
    assert not os.path.exists(os.path.join(out, "state.npz"))
    out2 = rec.record(rt.init_state(), {"rule": "nonfinite_abort",
                                        "round": 9})
    assert out2 == out
    assert os.path.exists(os.path.join(out, "state.npz"))
    # still one-shot for further state records
    meta_before = open(os.path.join(out, "state.meta.json")).read()
    rec.record(rt.init_state(), {"rule": "later", "round": 10})
    assert open(os.path.join(out, "state.meta.json")).read() == meta_before


# ------------------------------------------------- graceful drain path


def test_sigterm_drains_with_round_granular_checkpoint(tmp_path, capsys):
    """The graceful path end to end: a SIGTERM injected at round 5 lets
    round 5 finish, drains at the top of round 6, writes a
    preempt-tagged checkpoint carrying (epoch, round_in_epoch,
    global_round) + the ledger sidecar, emits the `fault` event, and
    returns as an orderly (state, None) exit."""
    rt, state, summary, mgr, stream = run_driver(
        tmp_path, fault="sigterm:pre_round:5", num_epochs=1.0)
    assert summary is None
    out = capsys.readouterr()
    assert "PREEMPT: SIGTERM received" in out.err
    assert "PREEMPT: drained at epoch 0 + 5 round(s)" in out.out
    gens = mgr.generations()
    assert gens and gens[-1][2].endswith("_preempt")
    assert gens[-1][:2] == (0, 5)
    from commefficient_tpu.checkpoint import load_meta
    meta = load_meta(os.path.join(mgr.directory, gens[-1][2]))
    assert meta["epoch"] == 0 and meta["round_in_epoch"] == 5
    assert meta["global_round"] == 5 and meta["tag"] == "preempt"
    assert meta["ledgers"] is not None and "digests" in meta
    events = read_events(stream)
    faults_ev = [e for e in events if e["event"] == "fault"]
    assert len(faults_ev) == 1
    f = faults_ev[0]
    assert f["kind"] == "preempt" and f["signal"] == "SIGTERM"
    assert f["round"] == 5 and f["grace_s"] is not None
    assert f["checkpoint"] and "_preempt" in f["checkpoint"]
    assert events[-1]["event"] == "summary" and events[-1]["aborted"]
    assert validate_file(stream) == []
    # exactly 5 rounds trained before the drain
    assert sorted(round_losses(stream)) == [1, 2, 3, 4, 5]


def test_kill_at_round_k_resume_is_bitwise_identical(tmp_path):
    """THE acceptance property: straight N rounds == preempt-at-5 +
    resume, bit for bit — per-round losses and the final weights. The
    resumed stream appends to the predecessor's with a `resume`
    lineage record and stays schema-valid end to end."""
    straight_dir = tmp_path / "straight"
    straight_dir.mkdir()
    rt_a, state_a, summary_a, _, stream_a = run_driver(
        straight_dir, num_epochs=2.0)
    assert summary_a is not None
    losses_a = round_losses(stream_a)
    # epoch_rounds() is an upper bound: the sampler may strand an
    # underfull tail, so pin only "two epochs of contiguous rounds"
    assert sorted(losses_a) == list(range(1, len(losses_a) + 1))
    assert len(losses_a) >= 10

    killed_dir = tmp_path / "killed"
    killed_dir.mkdir()
    _, _, summary_b, _, _ = run_driver(
        killed_dir, fault="sigterm:pre_round:5", num_epochs=2.0)
    assert summary_b is None
    rt_c, state_c, summary_c, _, stream_c = run_driver(
        killed_dir, resume=True, num_epochs=2.0)
    assert summary_c is not None

    losses_c = round_losses(stream_c)
    assert losses_c == losses_a, "resumed trajectory diverged"
    np.testing.assert_array_equal(
        np.asarray(rt_a.flat_weights(state_a)),
        np.asarray(rt_c.flat_weights(state_c)))
    events = read_events(stream_c)
    kinds = [e["event"] for e in events]
    resumes = [e for e in events if e["event"] == "resume"]
    assert resumes, "no resume lineage record"
    assert resumes[0]["round"] == 5
    assert resumes[0]["checkpoint"] and "_preempt" in resumes[0]["checkpoint"]
    assert resumes[0]["prior_stream"]          # names the dead segment
    assert kinds.count("manifest") == 2        # two segments, one file
    assert validate_file(stream_c) == []


def test_resume_from_epoch_checkpoint_unchanged_semantics(tmp_path):
    """Epoch-granular resume (the pre-existing path) still works through
    the new meta: kill between epochs via a full epoch-1 run, resume
    completes epoch 2 bit-identically to the straight run."""
    straight_dir = tmp_path / "s"
    straight_dir.mkdir()
    rt_a, state_a, _, _, stream_a = run_driver(straight_dir,
                                               num_epochs=2.0)
    part_dir = tmp_path / "p"
    part_dir.mkdir()
    run_driver(part_dir, num_epochs=1.0)
    rt_b, state_b, summary_b, _, stream_b = run_driver(
        part_dir, resume=True, num_epochs=2.0)
    assert summary_b is not None
    assert round_losses(stream_b) == round_losses(stream_a)
    np.testing.assert_array_equal(
        np.asarray(rt_a.flat_weights(state_a)),
        np.asarray(rt_b.flat_weights(state_b)))


# -------------------------------------------- quarantine persistence


def test_quarantine_survives_resume(tmp_path):
    """Satellite: eject a client, resume, assert STILL ejected — an
    epoch-granular restart must not silently re-admit known-bad clients
    (they used to re-strike from zero)."""
    kw = dict(adversary="nan", adversary_frac=0.3, seed=21,
              nonfinite_action="quarantine", quarantine_backoff=50,
              quarantine_strikes=1)
    rt, state, summary, mgr, stream = run_driver(tmp_path, num_epochs=1.0,
                                                 **kw)
    assert summary is not None
    events = read_events(stream)
    ejected = max(e.get("ejected", 0) for e in events
                  if e["event"] == "defense")
    assert ejected >= 1, "no ejection happened in epoch 1 — bad seed?"
    # the epoch-cadence checkpoint carried the ledger sidecar
    from commefficient_tpu.checkpoint import load_meta
    meta = load_meta(os.path.join(mgr.directory, mgr.generations()[-1][2]))
    assert meta["ledgers"]["quarantine"]["ejected"], meta["ledgers"]

    rt2, state2, summary2, _, stream2 = run_driver(
        tmp_path, resume=True, num_epochs=2.0, **kw)
    assert summary2 is not None
    seg2 = [e for e in read_events(stream2)
            if e["event"] == "defense" and e["round"] > 8]
    assert seg2, "resumed epoch emitted no defense events"
    # ejected from the FIRST resumed round: the ledger restored, the
    # client never re-admitted
    assert all(e["ejected"] >= ejected for e in seg2), seg2[:3]


def test_ledger_state_roundtrips():
    q = QuarantineLedger(backoff=3, strikes=2)
    q.observe(1, [4, 5], [True, False])
    q.observe(5, [5], [False])             # 5 ejected
    p = ParticipationLedger(8)
    p.observe(1, [0, 1], [2, 3])
    p.observe(4, [1, 2], [1, 1])
    m = AnomalyMonitor(None, window=8)
    for i in range(10):
        m.observe("round", {"round": i, "loss": 1.0 + 0.01 * i})
    sidecar = collect_ledger_state(qledger=q, participation=p, monitor=m)
    sidecar = json.loads(json.dumps(sidecar))   # must survive JSON
    q2, p2 = QuarantineLedger(backoff=3, strikes=2), ParticipationLedger(8)
    m2 = AnomalyMonitor(None, window=8)
    restore_ledger_state(sidecar, qledger=q2, participation=p2, monitor=m2)
    assert q2.ejected == {5} and q2.blocked(100) == {5}
    assert q2.strikes == q.strikes and q2.total_strikes == 2
    assert p2.snapshot(6) == p.snapshot(6)
    assert m2.state_dict() == m.state_dict()
    # a restored monitor KEEPS its envelope: the next spike fires
    # without re-warming min_points of history
    fired = m2.observe("round", {"round": 7, "loss": 500.0})
    assert any(a["rule"] == "loss_spike" for a in fired)
    # absent/partial sidecars are no-ops
    restore_ledger_state(None, qledger=q2)
    restore_ledger_state({}, qledger=q2)


# ------------------------------------------ checkpoint integrity


def _two_gen_mgr(tmp_path):
    rt = make_rt()
    mgr = CheckpointManager(str(tmp_path / "ck"))
    s = rt.init_state()
    mgr.save(s, epoch=1, meta={"mark": "gen1"})
    # the round DONATES s's buffers: keep host copies for comparisons
    w1 = np.asarray(s.ps_weights).copy()
    batch = {"x": jnp.ones((W, B, D_IN)), "y": jnp.ones((W, B, D_OUT))}
    s2, _ = rt.round(s, jnp.arange(W, dtype=jnp.int32), batch,
                     jnp.ones((W, B), bool), 0.05)
    mgr.save(s2, epoch=2, meta={"mark": "gen2"})
    return rt, mgr, w1, s2


def test_truncated_zip_falls_back_a_generation(tmp_path, capsys):
    rt, mgr, w1, s2 = _two_gen_mgr(tmp_path)
    npz = mgr._path(2) + ".npz"
    raw = open(npz, "rb").read()
    with open(npz, "wb") as f:
        f.write(raw[: len(raw) // 2])      # kill mid-write, no rename
    restored, meta = mgr.restore_latest()
    assert meta["mark"] == "gen1"
    np.testing.assert_array_equal(np.asarray(restored.ps_weights), w1)
    err = capsys.readouterr().err
    assert "unreadable or corrupt" in err
    assert "falling back to the previous generation" in err
    assert len(mgr.restore_fallbacks) == 1
    assert mgr.restore_fallbacks[0]["path"] == mgr._path(2)


def test_bitflip_caught_by_digest_falls_back(tmp_path, capsys):
    """A corrupted array REWRITTEN through np.savez (valid zip, valid
    CRC — only the sha256 digests in the meta sidecar can notice)
    still falls back with the digest explanation."""
    rt, mgr, w1, s2 = _two_gen_mgr(tmp_path)
    npz = mgr._path(2) + ".npz"
    with np.load(npz) as z:
        arrays = {k: np.array(z[k]) for k in z.files}
    arrays["ps_weights"] = arrays["ps_weights"] + 1.0   # silent corruption
    with open(npz, "wb") as f:
        np.savez(f, **arrays)
    restored, meta = mgr.restore_latest()
    assert meta["mark"] == "gen1"
    assert "sha256 digest" in capsys.readouterr().err
    # direct load pins the error class + wording
    gen2_meta = json.load(open(mgr._path(2) + ".meta.json"))
    with pytest.raises(CheckpointIntegrityError, match="sha256 digest"):
        load_state(mgr._path(2), verify_digests=gen2_meta["digests"])


def test_all_generations_corrupt_raises_loudly(tmp_path):
    rt, mgr, _, _ = _two_gen_mgr(tmp_path)
    for e in (1, 2):
        with open(mgr._path(e) + ".npz", "wb") as f:
            f.write(b"junk")
    with pytest.raises(CheckpointIntegrityError,
                       match="every checkpoint generation"):
        mgr.restore_latest()
    assert len(mgr.restore_fallbacks) == 2


def test_corrupt_meta_sidecar_falls_back(tmp_path):
    rt, mgr, w1, _ = _two_gen_mgr(tmp_path)
    with open(mgr._path(2) + ".meta.json", "w") as f:
        f.write("{truncated")
    restored, meta = mgr.restore_latest()
    assert meta["mark"] == "gen1"


def test_semantic_refusals_do_not_fall_back(tmp_path):
    """A fingerprint/marker mismatch is a CONFIG error — falling back a
    generation cannot fix it and must not mask it."""
    rt, mgr, _, _ = _two_gen_mgr(tmp_path)
    # stamp a fingerprint into gen2's meta, then expect a different one
    meta = json.load(open(mgr._path(2) + ".meta.json"))
    meta["params_fingerprint"] = "aaaa"
    json.dump(meta, open(mgr._path(2) + ".meta.json", "w"))
    mgr2 = CheckpointManager(mgr.directory)
    with pytest.raises(ValueError, match="different parameter layout"):
        mgr2.restore_latest(expect_fingerprint="bbbb")
    assert not mgr2.restore_fallbacks


def test_sharded_checkpoint_digests_roundtrip(tmp_path):
    """The streaming (sharded) writer records per-ENTRY digests and the
    host reassembly path verifies them."""
    rt = make_rt()
    s = rt.init_state()
    path = str(tmp_path / "sh")
    save_state(path, s, sharded=True)
    meta = json.load(open(path + ".meta.json"))
    assert any(k.endswith("__shard0") for k in meta["digests"])
    loaded = load_state(path, verify_digests=meta["digests"])
    np.testing.assert_array_equal(np.asarray(loaded.ps_weights),
                                  np.asarray(s.ps_weights))
    # flip one shard entry by rewriting the archive
    import zipfile
    with np.load(path + ".npz") as z:
        entries = {k: np.array(z[k]) for k in z.files}
    entries["ps_weights__shard0"] = entries["ps_weights__shard0"] * 2
    with zipfile.ZipFile(path + ".npz", "w", zipfile.ZIP_STORED) as zf:
        for k, arr in entries.items():
            with zf.open(k + ".npy", "w") as f:
                np.lib.format.write_array(f, arr, allow_pickle=False)
    with pytest.raises(CheckpointIntegrityError, match="sha256 digest"):
        load_state(path, verify_digests=meta["digests"])


def test_stale_tmp_cleanup_on_save(tmp_path):
    rt = make_rt()
    mgr = CheckpointManager(str(tmp_path / "ck"))
    os.makedirs(mgr.directory, exist_ok=True)
    litter = os.path.join(mgr.directory, "xyz123.tmp")
    open(litter, "w").write("leftover from a kill mid-write")
    removed = mgr.clean_stale_tmp()
    assert removed == [litter] and not os.path.exists(litter)
    open(litter, "w").write("again")
    mgr.save(rt.init_state(), epoch=1)     # save() self-heals too
    assert not os.path.exists(litter)
    assert not [fn for fn in os.listdir(mgr.directory)
                if fn.endswith(".tmp")]


def test_preempt_generation_ordering_and_rotation(tmp_path):
    rt = make_rt()
    mgr = CheckpointManager(str(tmp_path / "ck"), keep_last=3)
    s = rt.init_state()
    mgr.save(s, epoch=1)
    mgr.save(s, epoch=1, round_in_epoch=5, tag="preempt")
    mgr.save(s, epoch=2)
    assert [(e, r) for e, r, _ in mgr.generations()] == \
        [(1, 0), (1, 5), (2, 0)]
    assert mgr.epochs() == [1, 2]          # back-compat surface
    # newest preempt generation wins the restore
    mgr.save(s, epoch=2, round_in_epoch=3, tag="preempt")
    _, meta = mgr.restore_latest()
    assert meta["epoch"] == 2 and meta["round_in_epoch"] == 3
    # rotation spans BOTH kinds (keep_last=3 of 4)
    assert len(mgr.generations()) == 3
    assert (1, 0) not in [(e, r) for e, r, _ in mgr.generations()]


# ------------------------------------------- telemetry append-resume


def test_stream_append_preserves_prior_records(tmp_path):
    """Satellite: RunTelemetry must NEVER open an existing events file
    with "w" — the resumed run appends behind a `resume` marker and the
    predecessor's records survive."""
    a = RunTelemetry(str(tmp_path), "cv_train", cfg=make_rt().cfg)
    a.round_event(rnd=1, epoch=1, lr=0.1, loss=1.5, acc=0.5, n_valid=8.0,
                  download_bytes=None, upload_bytes=None, host_s=0.0,
                  dispatch_s=0.0, device_s=0.0)
    a_id = a.stream_id
    a.close()
    a_events = read_events(a.path)
    n_before = len(a_events)

    b = RunTelemetry(str(tmp_path), "cv_train", cfg=make_rt().cfg,
                     resume_info={"round": 2, "epoch": 0,
                                  "checkpoint": "ck/x"})
    b.round_event(rnd=2, epoch=1, lr=0.1, loss=1.4, acc=0.5, n_valid=8.0,
                  download_bytes=None, upload_bytes=None, host_s=0.0,
                  dispatch_s=0.0, device_s=0.0)
    b.write_summary(aborted=False, n_rounds=2)
    b.close()
    events = read_events(b.path)
    assert len(events) > n_before
    assert events[: n_before] == a_events   # predecessor records intact
    kinds = [e["event"] for e in events]
    assert kinds[0] == "manifest" and kinds.count("manifest") == 2
    res = events[n_before]
    assert res["event"] == "resume"
    assert res["prior_stream"] == a_id
    assert res["prior_events"] == n_before
    assert res["round"] == 2 and res["checkpoint"] == "ck/x"
    assert [e["seq"] for e in events] == list(range(len(events)))
    assert validate_file(b.path) == []


def test_stream_append_repairs_truncated_tail(tmp_path):
    a = RunTelemetry(str(tmp_path), "cv_train", cfg=make_rt().cfg)
    a.close()
    with open(a.path, "a") as f:
        f.write('{"event": "round", "t": 1.0, "se')   # died mid-write
    b = RunTelemetry(str(tmp_path), "cv_train", cfg=make_rt().cfg)
    b.close()
    lines = open(b.path).read().splitlines()
    # the fragment occupies its own (invalid) line; everything after
    # parses — teleview reads it, the schema linter flags exactly one
    parsed = []
    for ln in lines:
        try:
            parsed.append(json.loads(ln))
        except ValueError:
            parsed.append(None)
    assert parsed.count(None) == 1
    assert parsed[-1]["event"] == "manifest"
    assert parsed[-2]["event"] == "resume"


def test_fresh_logdir_resume_records_lineage(tmp_path):
    tel = RunTelemetry(str(tmp_path), "cv_train", cfg=make_rt().cfg,
                       resume_info={"round": 9, "epoch": 1,
                                    "checkpoint": "ck/y"})
    tel.close()
    events = read_events(tel.path)
    assert events[0]["event"] == "manifest"
    assert events[0]["stream_id"]
    res = [e for e in events if e["event"] == "resume"]
    assert len(res) == 1 and res[0]["round"] == 9
    assert res[0]["prior_stream"] is None   # no predecessor in THIS file
    assert validate_file(tel.path) == []


def test_fault_and_resume_events_validate():
    ok = {"event": "fault", "t": 0.0, "seq": 0, "round": 5,
          "kind": "preempt", "signal": "SIGTERM", "grace_s": 1.2,
          "detail": None, "checkpoint": "ck/..."}
    assert validate_event(ok) == []
    assert any("kind" in p for p in validate_event(
        {k: v for k, v in ok.items() if k != "kind"}))
    ok2 = {"event": "resume", "t": 0.0, "seq": 1, "round": 5,
           "epoch": 0, "checkpoint": None, "prior_stream": None,
           "prior_events": None}
    assert validate_event(ok2) == []
    # v7 manifests legitimately lack stream_id; v8 ones may not
    man = {"event": "manifest", "t": 0.0, "seq": 0, "schema": 7,
           "run_type": "x", "jax_version": "x", "backend": "cpu",
           "device_kind": "cpu", "device_count": 1, "mesh_shape": [],
           "mesh_axes": [], "grad_size": 0, "sketch": None, "config": {}}
    assert validate_event(man, version=7) == []
    assert any("stream_id" in p for p in validate_event(man, version=8))


# ----------------------------------------------------- hang watchdog


def test_stall_deadline_math():
    assert stall_deadline_s([0.1] * 3, 10.0) is None     # too few points
    d = stall_deadline_s([0.1] * 16, 10.0, floor_s=0.0)
    # constant history: MAD floored at max(2% of median, 50 ms)
    assert d == pytest.approx(10.0 * 0.1 + 6 * 0.05)
    assert stall_deadline_s([0.001] * 16, 1.0, floor_s=2.0) == 2.0
    assert stall_deadline_s([1.0] * 16, 20.0, floor_s=0.0) > \
        stall_deadline_s([1.0] * 16, 10.0, floor_s=0.0)


def test_watchdog_fires_once_per_stalled_round():
    fired = []
    wd = RoundWatchdog(lambda r, el, dl: fired.append((r, el, dl)),
                       mult=1.0, floor_s=0.05, poll_s=0.005)
    try:
        for _ in range(6):                    # warm the history: ~1 ms
            wd.arm(0)
            time.sleep(0.001)
            wd.disarm()
        wd.arm(7)
        time.sleep(0.4)                       # well past the deadline
        assert len(fired) == 1, fired         # once, not once per poll
        assert fired[0][0] == 7 and fired[0][1] >= fired[0][2]
        wd.disarm()
        wd.arm(8)                             # healthy round: no fire
        wd.disarm()
        time.sleep(0.05)
        assert len(fired) == 1
    finally:
        wd.close()
    assert not any(t.name == "round-watchdog" and t.is_alive()
                   for t in threading.enumerate())


def test_watchdog_rejects_sub_one_mult():
    with pytest.raises(ValueError, match="mult"):
        RoundWatchdog(lambda *a: None, mult=0.9)


def test_watchdog_unobserved_disarm_keeps_history_clean():
    """Dispatch-only (non-record) rounds must not feed the deadline
    history: a bimodal fast/slow mix would collapse the median onto the
    async-dispatch mode and false-fire on healthy synced rounds."""
    wd = RoundWatchdog(lambda *a: None, mult=2.0, floor_s=0.01)
    try:
        for _ in range(5):
            wd.arm(1)
            wd.disarm(observe=False)   # async dispatch, never synced
        assert len(wd.history) == 0 and wd.deadline_s() is None
        for _ in range(5):
            wd.arm(2)
            wd.disarm()                # synced round: observed
        assert len(wd.history) == 5 and wd.deadline_s() is not None
    finally:
        wd.close()


def test_with_retries_backoff_and_exhaustion():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    notes = []
    assert with_retries(flaky, attempts=3, base_s=0.001,
                        on_retry=lambda a, e: notes.append(a)) == "ok"
    assert len(calls) == 3 and notes == [1, 2]

    def always():
        raise OSError("dead")

    with pytest.raises(OSError, match="dead"):
        with_retries(always, attempts=2, base_s=0.001)
    with pytest.raises(ValueError, match="attempts"):
        with_retries(lambda: 1, attempts=0)


def test_driver_watchdog_on_is_bit_identical_and_leak_free(tmp_path):
    """--watchdog must observe, never perturb: same losses with it on,
    zero stalls on a healthy run, no leaked thread after train."""
    a_dir, b_dir = tmp_path / "a", tmp_path / "b"
    a_dir.mkdir(), b_dir.mkdir()
    _, _, _, _, stream_a = run_driver(a_dir, num_epochs=1.0)
    _, _, summary_b, _, stream_b = run_driver(b_dir, num_epochs=1.0,
                                              watchdog=True)
    assert summary_b is not None
    assert round_losses(stream_b) == round_losses(stream_a)
    assert not [e for e in read_events(stream_b)
                if e["event"] == "fault"]
    assert not any(t.name == "round-watchdog" and t.is_alive()
                   for t in threading.enumerate())


# ------------------------------------------------ fault spec plumbing


def test_fault_spec_parsing_and_matching():
    faults.set_fault(None)
    assert not faults.faults_enabled()
    faults.maybe_fault("pre_round", 1)          # disarmed: no-op
    faults.set_fault("kill:pre_round:5")
    assert faults.faults_enabled()
    assert not faults.fault_matches("pre_round", 4)
    assert not faults.fault_matches("mid_round", 5)
    assert faults.fault_matches("pre_round", 5)
    faults.set_fault("kill:mid_checkpoint_write")
    assert faults.fault_matches("mid_checkpoint_write")   # first visit
    faults.set_fault(None)
    for bad in ("nope", "kill:bogus_point", "sigsegv:pre_round",
                "kill:pre_round:5:9"):
        with pytest.raises(ValueError):
            faults.set_fault(bad)
    faults.set_fault(None)


def test_pipeline_skip_replays_sampler_tail():
    """RoundPipeline(skip=k) yields exactly the unskipped run's rounds
    k+1.. with identical sampler draws and global numbering — the
    round-granular resume primitive."""
    def rounds():
        return FedSampler(np.full(8, 16), W, B, seed=1234)

    full = list(RoundPipeline(iter(rounds()), lambda r, g: g,
                              start_round=0, enabled=False))
    skipped = list(RoundPipeline(iter(rounds()), lambda r, g: g,
                                 start_round=0, enabled=False, skip=3))
    assert len(skipped) == len(full) - 3
    for a, b in zip(full[3:], skipped):
        assert a.global_round == b.global_round
        np.testing.assert_array_equal(a.rnd.client_ids, b.rnd.client_ids)
        np.testing.assert_array_equal(a.rnd.idx, b.rnd.idx)
    # threaded path too
    threaded = list(RoundPipeline(iter(rounds()), lambda r, g: g,
                                  start_round=0, enabled=True, skip=3))
    assert [t.global_round for t in threaded] == \
        [s.global_round for s in skipped]
    with pytest.raises(ValueError, match="skip"):
        RoundPipeline(iter(()), lambda r, g: g, start_round=0, skip=-1)


# ----------------------------------------------- teleview stitching


def _load_teleview():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "teleview", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts", "teleview.py"))
    tv = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tv)
    return tv


def test_teleview_stitches_lineage_segments(tmp_path, capsys):
    """Satellite: `teleview summarize` reports the stitched segments,
    resume points and faults of an appended stream; `timeline` and
    `alerts` tolerate the new event types (a graceful preempt must NOT
    trip the alerts health gate)."""
    straight = tmp_path
    _, _, _, _, stream = run_driver(straight,
                                    fault="sigterm:pre_round:5",
                                    num_epochs=2.0)
    run_driver(straight, resume=True, num_epochs=2.0)
    tv = _load_teleview()
    events = tv.load_events(stream)
    capsys.readouterr()
    tv.summarize(events, label="stitched")
    out = capsys.readouterr().out
    assert "lineage: 2 segments" in out
    assert "resume at round 5" in out and "continues segment" in out
    assert "fault [preempt]" in out and "SIGTERM" in out
    # alerts: fault records are listed as context but never change the
    # health-gate verdict (a graceful preempt is not a failure — only
    # genuine critical ALERTS/aborts trip the gate, same verdict with
    # the fault events stripped)
    rc_with = tv.alerts(events)
    out = capsys.readouterr().out
    assert "preempt" in out
    rc_without = tv.alerts([e for e in events
                            if e.get("event") != "fault"])
    capsys.readouterr()
    assert rc_with == rc_without
    # timeline: the stitched stream still renders a trace
    trace = tv.build_trace(events)
    assert trace["traceEvents"]


# ------------------------------------------------ subprocess matrix


@pytest.mark.slow
def test_crash_matrix_hard_kill_subprocess():
    """One REAL os._exit(137) kill-point through the subprocess harness
    (scripts/crash_matrix.py): finally-blocks skipped, .tmp litter
    possible, stream truncated — and the resume still reproduces the
    straight run bit for bit. The full matrix runs standalone."""
    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "crash_matrix.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, script, "--points",
         "pre_round,mid_checkpoint_write"],
        env=env, capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "RESULT pre_round: PASS" in proc.stdout
    assert "RESULT mid_checkpoint_write: PASS" in proc.stdout
