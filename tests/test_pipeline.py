"""Round input pipeline (core/pipeline.py): prefetch overlap observed via
spans, rng-order determinism, exception propagation, shutdown without
leaked threads, the --no_pipeline HLO-identity contract, and an
end-to-end pipelined-vs-inline driver-loop equality."""

import threading
import time

import numpy as np
import pytest

from commefficient_tpu.core.pipeline import RoundInput, RoundPipeline
from commefficient_tpu.telemetry import tracing


def _rounds(n):
    """Fake sampler rounds (the pipeline treats them opaquely)."""
    return [{"id": i} for i in range(n)]


def _no_prefetch_threads():
    return all(t.name != "round-prefetch" for t in threading.enumerate())


# ---------------------------------------------------------------- prefetcher


def test_overlap_observed_via_spans():
    """A slow fetch overlaps a slow consumer: total wall well under the
    serial sum, the worker's data_fetch spans carry the true fetch cost,
    and the consumer's data_wait spans collapse after the first round."""
    n, fetch_s, consume_s = 6, 0.05, 0.05

    def fetch(rnd, g):
        time.sleep(fetch_s)
        return {"g": g}

    tracer = tracing.install()
    try:
        t0 = time.perf_counter()
        waits = []
        with RoundPipeline(_rounds(n), fetch, start_round=0,
                           depth=2, enabled=True) as pipe:
            for item in pipe:
                waits.append(item.wait_s)
                time.sleep(consume_s)       # the "device" work
        wall = time.perf_counter() - t0
    finally:
        tracing.uninstall()
    serial = n * (fetch_s + consume_s)
    # ideal pipelined wall ~ fetch_s + n * consume_s (~0.35 s vs 0.6 s
    # serial); generous margin for slow CI
    assert wall < serial * 0.9, (wall, serial)
    spans = tracer.drain()
    fetches = [s for s in spans if s["name"] == "data_fetch"]
    dwaits = [s for s in spans if s["name"] == "data_wait"]
    assert len(fetches) == n
    assert len(dwaits) == n + 1   # + the terminal wait that sees DONE
    assert all(s["dur_s"] >= fetch_s * 0.5 for s in fetches)
    # after round 1 the prefetcher is ahead: waits shrink well below the
    # fetch cost (the whole point)
    assert sum(waits[1:]) < fetch_s * (n - 1) * 0.8, waits
    # worker and consumer recorded under different tracer thread ids —
    # overlap is visible in the teleview timeline
    assert {s["tid"] for s in fetches} != {s["tid"] for s in dwaits}


def test_rng_order_determinism():
    """The worker fetches rounds in sampler order with the same global
    round numbers as the inline path, so index-keyed randomness (and
    per-call host-RNG advancement) is identical pipelined or not."""
    def make_fetch(calls):
        rng = np.random.RandomState(7)   # stateful, advances per call

        def fetch(rnd, g):
            calls.append((rnd["id"], g))
            return {"x": rng.randn(3) + g}
        return fetch

    def run(enabled):
        calls, batches = [], []
        pipe = RoundPipeline(_rounds(5), make_fetch(calls),
                             start_round=10, enabled=enabled)
        with pipe:
            for item in pipe:
                batches.append((item.global_round, item.batch["x"]))
        return calls, batches

    calls_t, batches_t = run(True)
    calls_i, batches_i = run(False)
    assert calls_t == calls_i == [(i, 11 + i) for i in range(5)]
    for (gt, xt), (gi, xi) in zip(batches_t, batches_i):
        assert gt == gi
        np.testing.assert_array_equal(xt, xi)
    assert _no_prefetch_threads()


def test_exception_propagates_and_thread_exits():
    """An exception inside the worker's fetch surfaces on the consumer's
    next(), after the successfully prefetched rounds; the thread dies."""
    def fetch(rnd, g):
        if rnd["id"] == 2:
            raise ValueError("boom in fetch")
        return {"g": g}

    pipe = RoundPipeline(_rounds(5), fetch, start_round=0, depth=1,
                         enabled=True)
    got = []
    with pytest.raises(ValueError, match="boom in fetch"):
        for item in pipe:
            got.append(item.global_round)
    assert got == [1, 2]
    assert pipe._thread is None and _no_prefetch_threads()


def test_early_close_no_leaked_thread():
    """Breaking out mid-epoch (abort paths, --test) reclaims the worker
    even while it is blocked on a full queue."""
    def fetch(rnd, g):
        return {"g": g}

    pipe = RoundPipeline(_rounds(100), fetch, start_round=0, depth=2,
                         enabled=True)
    with pipe:
        for item in pipe:
            break                       # driver break / abort return
    assert pipe._thread is None and _no_prefetch_threads()
    pipe.close()                        # idempotent


def test_max_rounds_cap_and_exhaustion():
    """max_rounds is the fractional-epoch cap: exactly that many rounds
    come out, numbered from start_round + 1."""
    seen = []
    pipe = RoundPipeline(_rounds(10), lambda r, g: g, start_round=4,
                         max_rounds=3, enabled=True)
    with pipe:
        for item in pipe:
            seen.append(item.global_round)
    assert seen == [5, 6, 7]
    # sampler shorter than max_rounds: runs out cleanly
    with RoundPipeline(_rounds(2), lambda r, g: g, start_round=0,
                       max_rounds=8, enabled=True) as p2:
        assert [i.global_round for i in p2] == [1, 2]
    assert _no_prefetch_threads()


def test_inline_mode_runs_no_thread():
    pipe = RoundPipeline(_rounds(3), lambda r, g: {"g": g}, start_round=0,
                         enabled=False)
    assert not pipe.threaded and pipe._thread is None
    items = list(pipe)
    assert [i.global_round for i in items] == [1, 2, 3]
    # inline, the reported wait IS the fetch (host_s keeps its meaning)
    assert all(i.wait_s == i.fetch_s for i in items)
    pipe.close()                        # no-op


def test_invalid_prefetch_depth_raises():
    """depth < 1 with pipelining enabled used to SILENTLY degrade to the
    inline fetch; it must now raise a clear config error — at the
    RoundPipeline layer and at the FedConfig layer (regression test for
    the PR-6 satellite fix). enabled=False still accepts any depth."""
    for depth in (0, -1):
        with pytest.raises(ValueError, match="queue bound"):
            RoundPipeline(_rounds(3), lambda r, g: {"g": g},
                          start_round=0, depth=depth, enabled=True)
    from commefficient_tpu.config import FedConfig
    with pytest.raises(ValueError, match="--prefetch_depth"):
        FedConfig(prefetch_depth=0)
    with pytest.raises(ValueError, match="--prefetch_depth"):
        FedConfig(prefetch_depth=0, pipeline=False)
    # no thread was created by the failed constructions
    assert _no_prefetch_threads()
    # inline mode still accepts any depth >= 1 semantics via enabled=False
    pipe = RoundPipeline(_rounds(2), lambda r, g: {"g": g}, start_round=0,
                         depth=0, enabled=False)
    assert [i.global_round for i in pipe] == [1, 2]


def test_wait_vs_fetch_accounting():
    """Pipelined, wait_s is the consumer's queue wait while fetch_s keeps
    the worker's true cost — input_wait_frac measures starvation, not the
    input path's (now-hidden) work."""
    def fetch(rnd, g):
        time.sleep(0.03)
        return g

    with RoundPipeline(_rounds(4), fetch, start_round=0,
                       enabled=True) as pipe:
        items = []
        for item in pipe:
            items.append(item)
            time.sleep(0.05)            # consumer slower than fetch
    assert all(i.fetch_s >= 0.02 for i in items)
    # steady state: prefetch ready before the consumer asks
    assert all(i.wait_s < 0.02 for i in items[1:]), \
        [i.wait_s for i in items]


# ------------------------------------------------- zero-cost-when-off contract


def test_no_pipeline_hlo_identity():
    """--no_pipeline must leave the jitted round byte-identical: the
    pipeline is a host-side change only (same contract as the
    signals/client_stats gating)."""
    import jax
    import jax.numpy as jnp

    from commefficient_tpu import models
    from commefficient_tpu.config import FedConfig
    from commefficient_tpu.core import FedRuntime
    from commefficient_tpu.losses import make_cv_loss

    model = models.ResNet9(num_classes=10,
                           channels={"prep": 2, "layer1": 2,
                                     "layer2": 2, "layer3": 2})
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 32, 32, 3)))
    loss = make_cv_loss(model, "float32")
    base = dict(mode="sketch", error_type="virtual", local_momentum=0.0,
                virtual_momentum=0.9, num_workers=2, local_batch_size=2,
                k=8, num_rows=2, num_cols=64, num_blocks=2, num_clients=4,
                track_bytes=False, telemetry=False)
    rt_on = FedRuntime(FedConfig(pipeline=True, **base), params, loss,
                       num_clients=4)
    rt_off = FedRuntime(FedConfig(pipeline=False, **base), params, loss,
                        num_clients=4)
    W, B = 2, 2
    batch = {"image": jnp.zeros((W, B, 32, 32, 3)),
             "target": jnp.zeros((W, B), jnp.int32)}
    args = (rt_on.init_state(), jnp.arange(W, dtype=jnp.int32), batch,
            jnp.ones((W, B), bool), jnp.asarray(0.1), rt_on.cs)
    assert rt_on._round.lower(*args).as_text() \
        == rt_off._round.lower(*args).as_text()


# ----------------------------------------------------------- driver end-to-end


def _run_cv_train(tmp_path, pipeline: bool, host_path: bool, monkeypatch):
    """One cv_train.train run over synthetic CIFAR. ``host_path`` forces
    the no-DeviceStore fallback (host gather + stateful CifarTrain RNG on
    the prefetch thread) by stubbing out the store factory; the dataset
    is built FRESH per run so the transform RNG starts identically."""
    import jax
    import jax.numpy as jnp

    from commefficient_tpu import cv_train, models
    from commefficient_tpu.config import FedConfig
    from commefficient_tpu.core import FedRuntime
    from commefficient_tpu.data import FedCIFAR10, transforms_for
    from commefficient_tpu.losses import make_cv_loss

    if host_path:
        monkeypatch.setattr(cv_train, "make_device_store",
                            lambda *a, **k: None)
    ds = FedCIFAR10(str(tmp_path / "d"), synthetic=True,
                    synthetic_per_class=8,
                    transform=transforms_for("CIFAR10", True, seed=0))
    cfg = FedConfig(mode="uncompressed", error_type="none",
                    local_momentum=0.0, virtual_momentum=0.9,
                    num_workers=4, local_batch_size=4,
                    num_clients=ds.num_clients, num_epochs=1.0,
                    track_bytes=False, compute_dtype="float32",
                    telemetry=False, pipeline=pipeline)
    model = models.ResNet9(num_classes=10,
                           channels={"prep": 2, "layer1": 2,
                                     "layer2": 2, "layer3": 2})
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 32, 32, 3)))
    rt = FedRuntime(cfg, params, make_cv_loss(model, "float32"),
                    num_clients=ds.num_clients)
    state, summary = cv_train.train(cfg, rt, rt.init_state(), ds, ds)
    return summary


def test_train_loop_pipelined_matches_inline(tmp_path, monkeypatch):
    """cv_train.train over synthetic CIFAR on the DEVICE-STORE path
    (index-keyed fold_in augmentation) produces bit-identical epoch
    losses pipelined vs inline — the dryrun gate's contract at driver
    level."""
    a = _run_cv_train(tmp_path, True, False, monkeypatch)
    b = _run_cv_train(tmp_path, False, False, monkeypatch)
    assert a["train_loss"] == b["train_loss"]
    assert a["test_loss"] == b["test_loss"]
    assert _no_prefetch_threads()


def test_train_loop_host_path_pipelined_matches_inline(tmp_path,
                                                       monkeypatch):
    """Same contract on the HOST-GATHER fallback path, where the
    augmentation RNG is STATEFUL (CifarTrain advances once per gather)
    and the gathers run on the prefetch thread: per-call advancement in
    round order must reproduce the inline draws exactly."""
    a = _run_cv_train(tmp_path, True, True, monkeypatch)
    b = _run_cv_train(tmp_path, False, True, monkeypatch)
    assert a["train_loss"] == b["train_loss"]
    assert a["test_loss"] == b["test_loss"]
    assert _no_prefetch_threads()
