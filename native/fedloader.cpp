// Native host data-plane for CommEfficient-TPU.
//
// Role: the per-round host work — gathering the sampled client batches out
// of the packed uint8 image store and applying augmentation/normalization —
// is the data-loader hot path. The reference delegates this to torch's
// DataLoader worker processes + PIL (C layers under torchvision transforms,
// reference data_utils/transforms.py + fed_cifar.py). Here it is one
// multithreaded C++ pass: gather + reflect-pad random crop + horizontal
// flip + normalize, uint8 -> float32 NHWC, writing straight into the buffer
// jax.device_put uploads from.
//
// Exposed via a C ABI for ctypes (no pybind11 in this image):
//   fedloader_gather_augment(...)  - full augmentation pipeline (train)
//   fedloader_gather_normalize(...) - gather + normalize only (eval)
//
// Determinism: per-item splitmix64 streams seeded by (seed, item index) —
// bitwise reproducible regardless of thread count.

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <thread>
#include <vector>

namespace {

inline uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ull;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
  return x ^ (x >> 31);
}

// reflect index into [0, n): torchvision "reflect" padding semantics
inline int reflect(int i, int n) {
  if (i < 0) i = -i;
  if (i >= n) i = 2 * n - 2 - i;
  return i;
}

struct AugmentJob {
  const uint8_t* images;   // (num_images, H, W, C) packed
  const int64_t* idx;      // (n,) flat image indices
  float* out;              // (n, H, W, C) float32
  int64_t n;
  int h, w, c;
  int pad;                 // crop shift radius (0 = no crop)
  int flip;                // 1 = random horizontal flip
  const float* mean;       // (C,)
  const float* stdinv;     // (C,) 1/std
  float scale;             // 1/255 for uint8 sources
  uint64_t seed;
};

void augment_range(const AugmentJob& j, int64_t lo, int64_t hi) {
  const int64_t plane = (int64_t)j.h * j.w * j.c;
  for (int64_t i = lo; i < hi; ++i) {
    const uint8_t* src = j.images + j.idx[i] * plane;
    float* dst = j.out + i * plane;
    uint64_t r = splitmix64(j.seed ^ (uint64_t)i * 0x2545F4914F6CDD1Dull);
    int dy = 0, dx = 0, do_flip = 0;
    if (j.pad > 0) {
      dy = (int)(r % (2 * j.pad + 1)) - j.pad;
      r = splitmix64(r);
      dx = (int)(r % (2 * j.pad + 1)) - j.pad;
      r = splitmix64(r);
    }
    if (j.flip) do_flip = (int)(r & 1);

    for (int y = 0; y < j.h; ++y) {
      const int sy = reflect(y + dy, j.h);
      for (int x = 0; x < j.w; ++x) {
        int xx = do_flip ? (j.w - 1 - x) : x;
        const int sx = reflect(xx + dx, j.w);
        const uint8_t* px = src + ((int64_t)sy * j.w + sx) * j.c;
        float* q = dst + ((int64_t)y * j.w + x) * j.c;
        for (int ch = 0; ch < j.c; ++ch) {
          q[ch] = ((float)px[ch] * j.scale - j.mean[ch]) * j.stdinv[ch];
        }
      }
    }
  }
}

void run_threaded(const AugmentJob& j, int num_threads) {
  if (num_threads <= 1 || j.n < 64) {
    augment_range(j, 0, j.n);
    return;
  }
  std::vector<std::thread> ts;
  int64_t chunk = (j.n + num_threads - 1) / num_threads;
  for (int t = 0; t < num_threads; ++t) {
    int64_t lo = t * chunk, hi = std::min<int64_t>(j.n, lo + chunk);
    if (lo >= hi) break;
    ts.emplace_back([&j, lo, hi] { augment_range(j, lo, hi); });
  }
  for (auto& th : ts) th.join();
}

}  // namespace

extern "C" {

void fedloader_gather_augment(const uint8_t* images, const int64_t* idx,
                              float* out, int64_t n, int h, int w, int c,
                              int pad, int flip, const float* mean,
                              const float* std, uint64_t seed,
                              int num_threads) {
  std::vector<float> stdinv(c);
  for (int ch = 0; ch < c; ++ch) stdinv[ch] = 1.0f / std[ch];
  AugmentJob j{images, idx, out, n, h, w, c, pad, flip,
               mean, stdinv.data(), 1.0f / 255.0f, seed};
  run_threaded(j, num_threads);
}

void fedloader_gather_normalize(const uint8_t* images, const int64_t* idx,
                                float* out, int64_t n, int h, int w, int c,
                                const float* mean, const float* std,
                                int num_threads) {
  std::vector<float> stdinv(c);
  for (int ch = 0; ch < c; ++ch) stdinv[ch] = 1.0f / std[ch];
  AugmentJob j{images, idx, out, n, h, w, c, /*pad=*/0, /*flip=*/0,
               mean, stdinv.data(), 1.0f / 255.0f, /*seed=*/0};
  run_threaded(j, num_threads);
}

}  // extern "C"
