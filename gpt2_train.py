#!/usr/bin/env python
"""Entry point kept at the repo root for reference-invocation parity:
``python gpt2_train.py ...`` (reference CommEfficient/gpt2_train.py).
"""

from commefficient_tpu.gpt2_train import main

if __name__ == "__main__":
    main()
