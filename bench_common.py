"""Shared helpers for the driver benchmarks (``bench.py``, ``bench_gpt2.py``).

The key piece is :func:`with_retries`: the experimental axon remote-compile
tunnel has been observed to drop an HTTP body mid-compile (BENCH_r02:
``remote_compile: read body: response body closed``), which previously
killed the whole benchmark artifact. Federated rounds are functional
(state in -> state out), so re-running a failed call with the same inputs
is safe, and the persistent XLA compile cache makes a retried compile
cheap. The benchmark's duty to survive infra flakes mirrors the
reference's treatment of its metric machinery as first-class
(/root/reference/CommEfficient/utils.py:76-85).
"""

from __future__ import annotations

import sys
import time

# peak bf16 FLOP/s and HBM GB/s by generation — single source of truth
# in telemetry/utilization.py (the `utilization` events and the benches
# must agree on the MFU/roofline denominators); re-exported under the
# old name
from commefficient_tpu.telemetry.utilization import (  # noqa: F401
    PEAK_FLOPS_BY_KIND as PEAK_FLOPS,
    peak_flops_for,
    peak_hbm_for,
)


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "")
    peak = peak_flops_for(kind)
    if peak is None:
        log(f"WARNING: unknown device kind {kind!r}; assuming v5e peak")
        return 197e12
    return peak


def peak_hbm_gbps(device) -> float:
    """Roofline bandwidth denominator with the same assume-v5e fallback
    as peak_flops: the bench headline must always carry a number (it is
    labeled with the device kind), unlike the telemetry events whose
    contract is null-never-fake (utilization.peak_hbm_for)."""
    kind = getattr(device, "device_kind", "")
    peak = peak_hbm_for(kind)
    if peak is None:
        log(f"WARNING: unknown device kind {kind!r}; assuming v5e HBM "
            "bandwidth")
        return 819.0
    return peak


# substrings (lower-cased) that mark an infra failure worth retrying, as
# opposed to a real bug in the benchmark; anchored to the observed axon
# tunnel failure messages plus the two gRPC statuses that are transient
# by definition. Deliberately NOT generic markers like "internal:" /
# "timeout" / "eof": a deterministic Mosaic/XLA failure often surfaces as
# INTERNAL, and retrying a 10-20 min GPT-2 compile three times on a real
# bug would waste an hour before reporting it.
_TRANSIENT_MARKERS = (
    # NOT "remote_compile": every error relayed through the tunnel carries
    # the endpoint URL, including deterministic compile failures — the
    # transport-failure texts below already cover the observed flakes
    "read body",
    "response body closed",
    "connection reset",
    "connection refused",
    "connection closed",
    "broken pipe",
    "socket closed",
    "deadline exceeded",
    "deadline_exceeded",
    "unavailable",
)


def is_transient(e: BaseException) -> bool:
    msg = f"{type(e).__name__}: {e}".lower()
    return any(m in msg for m in _TRANSIENT_MARKERS)


def with_retries(fn, *, desc: str, tries: int = 4, base_delay: float = 5.0):
    """Run ``fn()``, retrying transient infra failures with exponential
    backoff. Non-transient exceptions (real bugs) propagate immediately;
    the final attempt's exception propagates regardless so the caller's
    partial-result emission still runs."""
    for attempt in range(1, tries + 1):
        try:
            return fn()
        except Exception as e:
            if attempt == tries or not is_transient(e):
                raise
            delay = base_delay * (2 ** (attempt - 1))
            log(f"transient failure in {desc} (attempt {attempt}/{tries}): "
                f"{type(e).__name__}: {e}")
            log(f"  retrying in {delay:.0f}s...")
            time.sleep(delay)


def timed_rounds(runtime, round_args, *, warmup, rounds, desc: str,
                 profiler=None, round_args_fn=None):
    """Donation-safe, retry-wrapped warmup + timing of federated rounds.

    ``round_args_fn(i)`` (optional) builds round ``i``'s args INSIDE the
    warmup/timed loops instead of reusing the pre-staged ``round_args``
    (pass None for it then) — for benches whose per-round input staging
    is part of what they measure (a per-round host->device batch copy vs
    a device-store gather, scripts/bench_imagenet.py). It must be
    deterministic in ``i`` (retried attempts replay the same rounds);
    its wall time lands in the ``host_s`` phase, i.e. the bench's
    ``input_wait_frac``.

    ``profiler`` (telemetry.ProfilerWindow) places a jax trace over the
    TIMED rounds, numbered 1..rounds — the warmup (and its compile) stays
    out of the trace. Profiling syncs the device inside the loop, so a
    profiled attempt's timing is not a clean throughput number; pass a
    profiler only when the trace is the point of the run.

    The round step DONATES its input state, so a retry must never reuse a
    state object a failed attempt already fed in: the warmup attempt body
    starts from a fresh ``init_state()``, and each timing attempt copies
    the warmed state into fresh buffers. The trailing scalar host fetch is
    the completion barrier — on the experimental axon tunnel backend,
    ``block_until_ready`` has been OBSERVED to return before device work
    completes (chained 512-image rounds "finished" in 0.04 ms).

    The retry snapshot of the warmed state lives on the HOST and the
    device copy is freed between attempts — keeping a second device-side
    copy alive would add a full state (~0.5 GB at GPT-2 scale) to the
    round's peak HBM and has been observed to tip the GPT-2 round into
    RESOURCE_EXHAUSTED.

    Returns ``(dt_seconds, last_metrics, phases)`` for ``rounds`` timed
    rounds. ``phases`` splits the wall clock: ``dispatch_s`` (time inside
    the async round calls), ``device_wait_s`` (the trailing completion
    barrier) and ``host_s`` (everything else — loop overhead and, when
    profiling, the per-round syncs; the batch is pre-staged here so
    there is no data-fetch phase), plus ``warmup_s`` — the compile +
    warmup wall seconds BEFORE the timed window (the cold-vs-warm-start
    number the ``--compile_cache`` flag exists to shrink; callers lift
    it into the BENCH json so the trajectory tracks it). All clocks are
    ``perf_counter`` — an NTP step during a long timing loop must not
    skew the headline.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    def warm():
        s = runtime.init_state()
        for w in range(warmup):
            args = (round_args if round_args_fn is None
                    else round_args_fn(w))
            s, m = runtime.round(s, *args)
        float(s.ps_weights[0])
        return s

    log("compiling + warmup...")
    t0 = time.perf_counter()
    state = with_retries(warm, desc=f"{desc} compile+warmup")
    warmup_s = time.perf_counter() - t0
    log(f"warmup done in {warmup_s:.1f}s")
    host_state = jax.tree.map(np.asarray, state)
    jax.tree.map(lambda x: x.delete(), state)

    def timed():
        # fresh device buffers per attempt (the round donates its input)
        s = jax.tree.map(jnp.asarray, host_state)
        jax.block_until_ready(s)
        t0 = time.perf_counter()
        dispatch_s = 0.0
        try:
            for i in range(rounds):
                if profiler is not None:
                    profiler.maybe_start(i + 1)
                # input staging OUTSIDE the dispatch timer: a per-round
                # batch build/copy shows up as host_s (input wait)
                args = (round_args if round_args_fn is None
                        else round_args_fn(i))
                td = time.perf_counter()
                s, m = runtime.round(s, *args)
                dispatch_s += time.perf_counter() - td
                if profiler is not None:
                    profiler.maybe_stop(
                        i + 1, lambda: jax.block_until_ready(s.ps_weights))
        except BaseException:
            # a retried attempt must not leak an open trace into the
            # profiler's process-global state
            if profiler is not None:
                profiler.abort()
            raise
        if profiler is not None:
            # window STOP beyond the timed round count: keep the partial
            # trace instead of leaking the open profiler
            profiler.finalize(lambda: jax.block_until_ready(s.ps_weights))
        t1 = time.perf_counter()
        float(s.ps_weights[0])
        t2 = time.perf_counter()
        phases = {"host_s": round(t2 - t0 - dispatch_s - (t2 - t1), 6),
                  "dispatch_s": round(dispatch_s, 6),
                  "device_wait_s": round(t2 - t1, 6)}
        return t2 - t0, m, phases

    dt, m, phases = with_retries(timed, desc=f"{desc} timing loop")
    # warmup is OUTSIDE the timed wall (the fractions below stay fractions
    # of the timed window); carried so the BENCH json can track the
    # cold/warm compile tax alongside the throughput it does not affect
    phases["warmup_s"] = round(warmup_s, 3)
    return dt, m, phases
