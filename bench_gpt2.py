#!/usr/bin/env python
"""Secondary benchmark: GPT-2 (124M) sketched federated round throughput
(BASELINE.md config 4: GPT2-small / PersonaChat-shaped batches, FetchSGD
sketch 5x500k). Prints ONE JSON line like bench.py; the driver's headline
metric remains bench.py (CIFAR10 sketch round throughput).

Usage: python bench_gpt2.py  (first compile at this scale takes ~10-20 min
on the axon remote-compile path; subsequent runs hit the compile cache)
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

# PersonaChat-lineage throughput anchor: a V100 runs GPT-2-small fwd+bwd at
# ~4.5k tok/s; the reference publishes no numbers of its own (BASELINE.md)
NOMINAL_SINGLE_GPU_TOK_PER_SEC = 4500.0


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    import jax
    import jax.numpy as jnp

    from commefficient_tpu.config import FedConfig
    from commefficient_tpu.core import FedRuntime
    from commefficient_tpu.losses import make_gpt2_train_loss
    from commefficient_tpu.models.gpt2 import GPT2Config, GPT2DoubleHeads

    log("devices:", jax.devices())
    model = GPT2DoubleHeads(GPT2Config(remat=True))
    W, B, NC, S = 4, 2, 2, 128
    rng = np.random.RandomState(0)
    batch = {
        "input_ids": jnp.asarray(
            rng.randint(0, 50257, (W, B, NC, S)), jnp.int32),
        "mc_token_ids": jnp.asarray(rng.randint(0, S, (W, B, NC)), jnp.int32),
        "lm_labels": jnp.asarray(
            rng.randint(0, 50257, (W, B, NC, S)), jnp.int32),
        "mc_label": jnp.asarray(rng.randint(0, NC, (W, B)), jnp.int32),
        "token_type_ids": jnp.asarray(
            rng.randint(0, 2, (W, B, NC, S)), jnp.int32),
    }
    params = model.init(jax.random.PRNGKey(0),
                        batch["input_ids"][0, :1], batch["mc_token_ids"][0, :1],
                        batch["token_type_ids"][0, :1])

    cfg = FedConfig(mode="sketch", error_type="virtual", local_momentum=0.0,
                    virtual_momentum=0.9, weight_decay=0.0,
                    num_workers=W, local_batch_size=B,
                    k=50_000, num_rows=5, num_cols=500_000,
                    num_clients=100, track_bytes=False, approx_topk=True,
                    sketch_dtype="bfloat16", num_results_train=2)
    runtime = FedRuntime(cfg, params, make_gpt2_train_loss(model),
                         num_clients=cfg.num_clients)
    state = runtime.init_state()
    mask = jnp.ones((W, B), bool)
    ids = jnp.arange(W, dtype=jnp.int32)

    log("compiling + warmup...")
    t0 = time.time()
    state, metrics = runtime.round(state, ids, batch, mask, 0.1)
    float(state.ps_weights[0])
    log(f"warmup done in {time.time() - t0:.1f}s")

    n_rounds = 10
    t0 = time.time()
    for _ in range(n_rounds):
        state, metrics = runtime.round(state, ids, batch, mask, 0.1)
    float(state.ps_weights[0])
    dt = time.time() - t0

    toks = n_rounds * W * B * NC * S
    tps = toks / dt
    loss = float(np.asarray(metrics["results"][0]).mean())
    log(f"{n_rounds} rounds in {dt:.3f}s -> {tps:.0f} tok/s, loss {loss:.3f}")
    print(json.dumps({
        "metric": "gpt2_sketch_round_throughput",
        "value": round(tps, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(tps / NOMINAL_SINGLE_GPU_TOK_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
