#!/usr/bin/env python
"""Secondary benchmark: GPT-2 (124M) sketched federated round throughput
(BASELINE.md config 4: GPT2-small / PersonaChat-shaped batches, FetchSGD
sketch 5x500k, circulant impl). Prints ONE JSON line like bench.py; the
driver's headline metric remains bench.py (CIFAR10 sketch round
throughput), which nests this one under its ``"gpt2"`` key.

Round shape: W=8 clients x B=8 dialogues x C=2 candidates x S=256 tokens
= 32,768 tokens/round (VERDICT r1: the old 2,048-token round amortized the
124M-d sketch over almost nothing), microbatched 8 dialogues at a time
with rematerialized blocks, chunked LM cross-entropy (lm_chunk=128 — the
full fp32 (tokens, vocab) logits used to cap the microbatch at 4), bf16
compute. num_cols=524288 (vs the reference's 500,000): the 1024-aligned
column count enables the fused pallas decode kernel (21 ms vs 129 ms at
d=124M — ops/circulant_pallas.py) at the cost of a 4.9% larger table
upload; measured on one v5e this config lifts the round from ~51.7k
tok/s @ 20.2% MFU to ~67-68k tok/s @ ~26.5% MFU.

MFU is model-FLOPs utilization computed from ANALYTIC fwd+bwd model FLOPs
(gpt2_model_flops below) — not XLA's cost analysis, which counts each
lax.scan body once (no trip-count multiply) and so under-reports the
scanned round by ~10x — divided by wall-clock x the chip's peak bf16
FLOP/s.

All compile/warmup/timing stages run under bench_common.with_retries so a
transient remote-compile tunnel flake (the BENCH_r02 failure mode) cannot
kill the artifact.

Usage: python bench_gpt2.py  (first compile at this scale takes ~10-20 min
on the axon remote-compile path; subsequent runs hit the compile cache)
"""

from __future__ import annotations

import json

import numpy as np

from bench_common import log, peak_flops, timed_rounds, with_retries
# the analytic FLOPs formula moved next to the model so the gpt2_train
# driver's utilization telemetry shares it (models/gpt2.py)
from commefficient_tpu.models.gpt2 import gpt2_model_flops  # noqa: F401

# PersonaChat-lineage throughput anchor (NOMINAL, not measured: a V100
# runs GPT-2-small fwd+bwd at ~4.5k tok/s; the reference publishes no
# numbers of its own — BASELINE.md)
NOMINAL_SINGLE_GPU_TOK_PER_SEC = 4500.0


def run(remat: bool = True, telemetry=None, profiler=None, *,
        remat_policy: str = "", microbatch: int = 8, lm_chunk: int = 128,
        fused_encode: str = "auto", decode_overlap: bool = False,
        n_rounds: int = 8, compile_cache=None,
        wire_dtype: str = "float32", dryrun: bool = False) -> dict:
    """Build, warm up and time the GPT-2 round; returns the result dict.

    ``remat=True`` is the shipping configuration. remat=False spends the
    HBM the fused-clients path freed on saved activations instead of
    backward recompute — measured SLOWER (69.3k vs 76.5k tok/s pre-pallas
    -encode: the saved-activation HBM traffic costs more than the
    recompute FLOPs); kept parameterized so the trade stays measurable.

    ``remat_policy``/``microbatch``/``lm_chunk`` parameterize the MFU
    sweep (scripts/gpt2_mfu_sweep.py): selective-remat policies between
    full remat and none, the microbatch/HBM trade, and the chunked-CE
    granularity — the three knobs runs/BREAKDOWN_gpt2.md names between
    the measured 33% and the 40% target. ``microbatch`` must divide the
    dialogue client batch.

    ``fused_encode`` passes through to --sketch_fused_encode: "auto"
    (the shipping default — the microbatch scan carries the sketch
    table and the dense (d,) gradient never materializes, ~0.5 GB of
    temp at the flagship scale), "off" (the pre-fusion round — the
    A/B arm whose ledger DOCUMENTS the dense materialization), or "on"
    (fail fast if ineligible).

    ``decode_overlap=True`` times the SPLIT round (--decode_overlap,
    core/pipeline.DecodeOverlapRound: cohort + decode executables,
    bit-identical losses) and records BOTH executables' memory ledgers
    — the cohort ledger is where the fused encode's temp win is
    measurable at all (in the monolithic round the server decode's own
    dense (d,) buffers share temp slots with the client scan across
    disjoint lifetimes, so the executable's PEAK barely moves), and
    the decode running while the host stages round t+1 is ROADMAP
    item 1's second half.

    ``dryrun=True`` shrinks the model (GPT2Config.small) and the round
    shape so every arm runs in seconds on the CPU container — the sweep
    mechanics, compiled-executable cost/memory analysis and roofline
    fields stay live while the throughput numbers are explicitly NOT
    the flagship measurement (the result carries ``dryrun: true``)."""
    import jax
    import jax.numpy as jnp

    from commefficient_tpu.config import FedConfig, enable_compilation_cache
    from commefficient_tpu.core import FedRuntime
    from commefficient_tpu.losses import make_gpt2_train_loss
    from commefficient_tpu.models.gpt2 import GPT2Config, GPT2DoubleHeads

    log("devices:", jax.devices())
    if dryrun:
        gcfg = GPT2Config.small(remat=remat, remat_policy=remat_policy)
        W, B, NC, S = 4, 4, 2, 64
    else:
        gcfg = GPT2Config(remat=remat, remat_policy=remat_policy)
        W, B, NC, S = 8, 8, 2, 256
    model = GPT2DoubleHeads(gcfg)
    rng = np.random.RandomState(0)
    V = gcfg.vocab_size
    batch = {
        "input_ids": jnp.asarray(
            rng.randint(0, V, (W, B, NC, S)), jnp.int32),
        "mc_token_ids": jnp.asarray(rng.randint(0, S, (W, B, NC)), jnp.int32),
        "lm_labels": jnp.asarray(
            rng.randint(0, V, (W, B, NC, S)), jnp.int32),
        "mc_label": jnp.asarray(rng.randint(0, NC, (W, B)), jnp.int32),
        "token_type_ids": jnp.asarray(
            rng.randint(0, 2, (W, B, NC, S)), jnp.int32),
    }
    params = model.init(jax.random.PRNGKey(0),
                        batch["input_ids"][0, :1], batch["mc_token_ids"][0, :1],
                        batch["token_type_ids"][0, :1])

    if dryrun:
        # microbatch keeps its RATIO meaning (arms sweep 2/4/8 over the
        # full-scale client batch of 8; the dryrun batch is 4, so
        # mb8 -> 4, mb4 -> 2, mb2 -> 1 — each arm still A/Bs a DISTINCT
        # live-set size; a plain min-clamp would collapse mb8 and mb4
        # into the same configuration) and the sketch shrinks with the
        # model — the arm still exercises the same code paths, just at
        # smoke scale
        microbatch = max(1, (microbatch * B) // 8)
        lm_chunk = min(lm_chunk, S)
        sketch_kw = dict(k=1_000, num_rows=3, num_cols=16_384,
                         num_blocks=2)
    else:
        sketch_kw = dict(k=50_000, num_rows=5, num_cols=524_288,
                         num_blocks=20)
    cfg = FedConfig(mode="sketch", error_type="virtual", local_momentum=0.0,
                    virtual_momentum=0.9, weight_decay=0.0,
                    num_workers=W, local_batch_size=B,
                    microbatch_size=microbatch,
                    num_clients=100, track_bytes=False, approx_topk=True,
                    num_results_train=2, lm_chunk=lm_chunk,
                    sketch_fused_encode=fused_encode,
                    decode_overlap=decode_overlap,
                    wire_dtype=wire_dtype, **sketch_kw)
    if compile_cache is not None:  # "" = disable (true cold start)
        cfg = cfg.replace(compilation_cache_dir=compile_cache)
    enable_compilation_cache(cfg)
    runtime = FedRuntime(cfg, params,
                         make_gpt2_train_loss(model, lm_chunk=cfg.lm_chunk),
                         num_clients=cfg.num_clients)
    if telemetry is not None:
        # the ~10-20 min cold compile of this round becomes a visible
        # compile event (wall time + cost analysis) in the shared stream
        telemetry.instrument(runtime)
        telemetry.memory_event("gpt2_init")
    mask = jnp.ones((W, B), bool)
    ids = jnp.arange(W, dtype=jnp.int32)

    bench_rt = runtime
    if decode_overlap:
        from commefficient_tpu.core import DecodeOverlapRound
        bench_rt = DecodeOverlapRound(runtime)
    dt, metrics, phases = timed_rounds(bench_rt, (ids, batch, mask, 0.1),
                                       warmup=1, rounds=n_rounds, desc="gpt2",
                                       profiler=profiler)
    warmup_s = phases.pop("warmup_s", None)

    toks = n_rounds * W * B * NC * S
    tps = toks / dt
    loss = float(np.asarray(metrics["results"][0]).mean())

    # analytic model FLOPs: the round's scans (microbatch, scan-over-
    # layers) make XLA's cost analysis under-report by the trip counts
    flops = gpt2_model_flops(gcfg, W * B * NC * S, S)
    peak = peak_flops(jax.devices()[0])
    mfu = (flops * n_rounds / dt) / peak
    log(f"{n_rounds} rounds in {dt:.3f}s -> {tps:.0f} tok/s, loss {loss:.3f}")
    log(f"model FLOPs/round {flops:.3e}, peak {peak:.0f}, MFU {mfu:.3f}")

    # roofline attribution of the compiled round: cost-analysis bytes
    # accessed + the memory_analysis ledger (under the fused encode the
    # dense (d,) gradient no longer appears in temp bytes; the
    # fused_encode="off" A/B arm documents what it cost — see
    # telemetry/memory_ledger.py SKETCH_ENCODE_FUSED). With telemetry on
    # the JitWatcher already captured both at the warmup compile (and
    # instrument() replaced runtime._round with the watcher's closure,
    # which has no .lower) — read its channels like bench.py does; only
    # the bare path pays a lower+compile, near-free under the persistent
    # compile cache. NOTE the same scan caveat as flops: XLA's
    # bytes-accessed counts each scan body once, so the measured
    # arithmetic intensity is an UPPER bound for the scanned round.
    nbytes = mledger = decode_ledger = None
    if telemetry is not None:
        w = telemetry.watcher()
        if decode_overlap:
            # headline ledger = the CLIENT executable (where the fused
            # encode's temp win lives); the server half rides alongside
            parts = [w.bytes.get("cohort_step"), w.bytes.get("decode_step")]
            nbytes = sum(p for p in parts if p) or None
            mledger = w.memory.get("cohort_step")
            decode_ledger = w.memory.get("decode_step")
        else:
            nbytes = w.bytes.get("round_step")
            mledger = w.memory.get("round_step")
    else:
        def round_cost():
            from commefficient_tpu.telemetry.memory_ledger import \
                ledger_from_compiled

            def _cost(compiled):
                cost = compiled.cost_analysis()
                if isinstance(cost, (list, tuple)):
                    cost = cost[0] if cost else {}
                return (cost.get("bytes accessed"),
                        ledger_from_compiled(compiled))

            lr = jnp.asarray(0.1, jnp.float32)
            if decode_overlap:
                b1, l1 = _cost(runtime._cohort.lower(
                    runtime.init_state(), ids, batch, mask, lr,
                    runtime.cs).compile())
                # shapes only — this path must stay compile-only (a
                # real cohort execution is the dominant cost of a round)
                s_shape, p_shape = jax.eval_shape(
                    runtime._cohort, runtime.init_state(), ids, batch,
                    mask, lr, runtime.cs)
                b2, l2 = _cost(runtime._decode_jit.lower(
                    s_shape, p_shape["sum"],
                    jax.ShapeDtypeStruct((), jnp.float32),
                    runtime._prep_lr(0.1), runtime.cs).compile())
                return (((b1 or 0) + (b2 or 0)) or None, l1, l2)
            compiled = runtime._round.lower(
                runtime.init_state(), ids, batch, mask, lr,
                runtime.cs, runtime._gid).compile()
            return _cost(compiled) + (None,)

        try:
            nbytes, mledger, decode_ledger = with_retries(
                round_cost, desc="gpt2 round cost")
        except Exception as e:
            log(f"WARNING: round cost/memory analysis unavailable ({e})")
    from commefficient_tpu.telemetry.utilization import roofline_fields
    from bench_common import peak_hbm_gbps as _peak_hbm
    roof = roofline_fields(
        rounds=n_rounds, wall_s=dt, flops_per_round=flops,
        bytes_per_round=(float(nbytes) if nbytes else None),
        bytes_source="cost_analysis",
        peak_flops=peak, peak_hbm_gbps=_peak_hbm(jax.devices()[0]))
    if roof["bound"] is not None:
        log(f"roofline: AI {roof['arithmetic_intensity']:.1f} FLOP/B "
            f"(ridge {roof['ridge_intensity']:.1f}) -> {roof['bound']}-"
            f"bound, bw_frac {roof['bw_frac']}")

    result = {
        "metric": "gpt2_sketch_round_throughput",
        "value": round(tps, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(tps / NOMINAL_SINGLE_GPU_TOK_PER_SEC, 3),
        "mfu": round(mfu, 4) if np.isfinite(mfu) else None,
        "tokens_per_round": W * B * NC * S,
        "timed_rounds": n_rounds,
        # quantized-wire arm identity (schema v9 / ISSUE 14): the table
        # wire dtype and the exact per-round simulated upload payload
        "wire_dtype": cfg.wire_dtype,
        "wire_bytes_per_round": W * cfg.upload_wire_bytes(
            runtime._wire_block or None),
        "warmup_s": warmup_s,
        "phase_split": phases,
        "input_wait_frac": round(phases["host_s"] / dt, 6),
        "roofline": roof,
        "memory_ledger": mledger,
        # present only under decode_overlap: the server half's ledger
        # (the headline memory_ledger is then the COHORT executable)
        "memory_ledger_decode": decode_ledger,
        "dryrun": dryrun,
        # the sweep knobs this arm ran under (scripts/gpt2_mfu_sweep.py)
        "config": {"remat": remat, "remat_policy": remat_policy,
                   "microbatch": microbatch, "lm_chunk": lm_chunk,
                   "fused_encode": fused_encode,
                   "decode_overlap": decode_overlap},
    }
    if telemetry is not None:
        from commefficient_tpu.telemetry.utilization import emit_from_totals
        emit_from_totals(
            telemetry, rnd=n_rounds, rounds=n_rounds, wall_s=dt,
            host_s=phases["host_s"], dispatch_s=phases["dispatch_s"],
            device_s=phases["device_wait_s"],
            flops_per_round=flops, flops_source="analytic",
            device_kind=getattr(jax.devices()[0], "device_kind", "unknown"),
            bytes_per_round=(float(nbytes) if nbytes else None),
            bytes_source="cost_analysis")
        telemetry.bench_event(result["metric"], result,
                              wire_dtype=cfg.wire_dtype)
    return result


def ledger_ab(dryrun: bool = False) -> dict:
    """Compile-only fused-vs-unfused A/B of the split round's COHORT
    executable at a PARAMETER-DOMINATED GPT-2 geometry — the committed
    proof the dense-gradient floor moved (runs/BREAKDOWN_gpt2.md
    §Round 7).

    The throughput sweep's smoke geometry (GPT2Config.small, 4x4x2x64)
    cannot show the win: there d*4 is ~0.5 MB against ~10 MB of
    activation working set, and backward-scheduling noise at that scale
    is larger than the dense gradient itself. This A/B instead uses the
    geometry class the fusion exists for — parameters >> activations
    (the flagship 124M round is d*4 ~0.5 GB against ~tens of MB of
    remat'd activations): ``dryrun=True`` runs a mid-size GPT-2
    (d ~5.6M, one 32-token dialogue, microbatch 1) that compiles in
    ~a minute on the CPU container; ``dryrun=False`` uses the flagship
    config and round shape (TPU: the cohort compile is the same one the
    bench pays, cache-shared). Nothing executes — the ledger reads
    ``memory_analysis()`` off the compiled executables."""
    import jax
    import jax.numpy as jnp
    from jax.flatten_util import ravel_pytree

    from commefficient_tpu.config import FedConfig
    from commefficient_tpu.core import FedRuntime
    from commefficient_tpu.losses import make_gpt2_train_loss
    from commefficient_tpu.models.gpt2 import GPT2Config, GPT2DoubleHeads
    from commefficient_tpu.telemetry.memory_ledger import \
        ledger_from_compiled

    if dryrun:
        gcfg = GPT2Config(vocab_size=8192, n_positions=128, n_embd=256,
                          n_layer=4, n_head=4, remat=True)
        W, B, NC, S, mb = 1, 1, 1, 32, 1
        sketch_kw = dict(k=5_000, num_rows=3, num_cols=262_144,
                         num_blocks=8)
    else:
        gcfg = GPT2Config(remat=True)
        W, B, NC, S, mb = 8, 8, 2, 256, 8
        sketch_kw = dict(k=50_000, num_rows=5, num_cols=524_288,
                         num_blocks=20)
    model = GPT2DoubleHeads(gcfg)
    rng = np.random.RandomState(0)
    V = gcfg.vocab_size
    batch = {
        "input_ids": jnp.asarray(
            rng.randint(0, V, (W, B, NC, S)), jnp.int32),
        "mc_token_ids": jnp.asarray(rng.randint(0, S, (W, B, NC)),
                                    jnp.int32),
        "lm_labels": jnp.asarray(
            rng.randint(0, V, (W, B, NC, S)), jnp.int32),
        "mc_label": jnp.asarray(rng.randint(0, NC, (W, B)), jnp.int32),
        "token_type_ids": jnp.asarray(
            rng.randint(0, 2, (W, B, NC, S)), jnp.int32),
    }
    params = model.init(jax.random.PRNGKey(0), batch["input_ids"][0, :1],
                        batch["mc_token_ids"][0, :1],
                        batch["token_type_ids"][0, :1])
    d = ravel_pytree(params)[0].shape[0]
    mask = jnp.ones((W, B), bool)
    ids = jnp.arange(W, dtype=jnp.int32)
    rec = {"metric": "gpt2_fused_encode_ledger_ab", "d": int(d),
           "dense_grad_bytes": int(d) * 4, "dryrun": dryrun,
           "round_shape": [W, B, NC, S], "microbatch": mb,
           "arms": {}}
    for fe in ("auto", "off"):
        cfg = FedConfig(mode="sketch", error_type="virtual",
                        local_momentum=0.0, virtual_momentum=0.9,
                        weight_decay=0.0, num_workers=W,
                        local_batch_size=B, microbatch_size=mb,
                        num_clients=100, track_bytes=False,
                        approx_topk=True, num_results_train=2,
                        lm_chunk=min(128, S), sketch_fused_encode=fe,
                        decode_overlap=True, telemetry=False, **sketch_kw)
        runtime = FedRuntime(
            cfg, params, make_gpt2_train_loss(model, lm_chunk=cfg.lm_chunk),
            num_clients=cfg.num_clients)

        def compile_arm(runtime=runtime):
            return runtime._cohort.lower(
                runtime.init_state(), ids, batch, mask,
                jnp.asarray(0.1, jnp.float32), runtime.cs).compile()

        compiled = with_retries(compile_arm, desc=f"ledger_ab fe={fe}")
        led = ledger_from_compiled(compiled)
        rec["arms"][fe] = led
        t = (led or {}).get("temp_bytes")
        log(f"ledger_ab fe={fe}: cohort temp {t} "
            f"({t / (d * 4):.2f}x d*4)" if t is not None else
            f"ledger_ab fe={fe}: no ledger")
    a, o = rec["arms"].get("auto") or {}, rec["arms"].get("off") or {}
    if a.get("temp_bytes") is not None and o.get("temp_bytes") is not None:
        rec["temp_drop_bytes"] = o["temp_bytes"] - a["temp_bytes"]
        rec["drop_covers_dense_grad"] = bool(
            rec["temp_drop_bytes"] >= d * 4)
        log(f"ledger_ab: temp drop {rec['temp_drop_bytes']} B vs dense "
            f"grad {d * 4} B -> covers: {rec['drop_covers_dense_grad']}")
    return rec


def main(argv=None):
    import argparse

    from bench import add_bench_args, make_bench_telemetry
    ap = argparse.ArgumentParser(description=__doc__)
    add_bench_args(ap)
    args = ap.parse_args(argv)
    telemetry, profiler = make_bench_telemetry(args, "bench_gpt2")
    result = run(telemetry=telemetry, profiler=profiler,
                 compile_cache=args.compile_cache,
                 wire_dtype=args.wire_dtype)
    if telemetry is not None:
        telemetry.write_summary(aborted=False,
                                n_rounds=result["timed_rounds"],
                                final=result)
        telemetry.close()
    print(json.dumps(result))


if __name__ == "__main__":
    main()
