#!/usr/bin/env python
"""Extract the per-epoch eval TSV from a gpt2_train.py log.

One shared parser (used by scripts/gpt2_convergence.sh and
scripts/gpt2_ef_study.sh) so the TableLogger row format — 10 columns:
epoch lr train_time train_loss train_acc test_loss test_acc down up
total_time — is pinned in exactly one place.

Usage: gpt2log2tsv.py <run.log> <out.tsv>
"""

import math
import re
import sys


def main(log_path: str, tsv_path: str) -> None:
    rows = ["epoch\thours\ttest_nll\tppl\tmc_acc"]
    for line in open(log_path):
        f = line.split()
        if len(f) == 10 and re.fullmatch(r"\d+", f[0]):
            ep, nll, acc, total = (int(f[0]), float(f[5]), float(f[6]),
                                   float(f[9]))
            rows.append(f"{ep}\t{total/3600:.8f}\t{nll:.4f}"
                        f"\t{math.exp(min(nll, 20)):.2f}\t{acc:.4f}")
    with open(tsv_path, "w") as out:
        out.write("\n".join(rows) + "\n")
    print("wrote", tsv_path)


if __name__ == "__main__":
    main(sys.argv[1], sys.argv[2])
