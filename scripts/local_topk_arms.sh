#!/usr/bin/env bash
# local_topk operating-regime confirmation on TPU (VERDICT r4 next-round
# #2): scripts/local_topk_sim.py's CPU sweep of the REFERENCE dynamics
# says local error feedback diverges at real compression unless lr is
# cut far below the dense-stable value, and error_type none tolerates
# ~10x more lr. These arms confirm on the hard-v2 CV regime.
set -euo pipefail
cd "$(dirname "$0")/.."

run() {
    local name=$1; shift
    echo "=== $name ==="
    python cv_train.py --dataset_name CIFAR10 --model ResNet9 --batchnorm \
      --iid --num_clients 40 --num_workers 8 --local_batch_size 64 \
      --num_epochs 24 --synthetic_per_class 400 --synthetic_hard \
      --synthetic_label_noise 0.08 --seed 21 \
      --virtual_momentum 0.9 --mode local_topk --k 50000 --approx_topk \
      "$@" 2>&1 | tee "runs/$name.log"
    { echo "epoch,hours,top1Accuracy";
      grep -E "^[0-9]+,0\.[0-9]+,[0-9.]+$" "runs/$name.log"; } \
      > "runs/$name.tsv"
    tail -1 "runs/$name.tsv"
}

for arm in "$@"; do
  case "$arm" in
    lr01)  run cifar10_hard24v2_local_topk_lr01 \
        --error_type local --local_momentum 0.0 --lr_scale 0.01 ;;
    lr003) run cifar10_hard24v2_local_topk_lr003 \
        --error_type local --local_momentum 0.0 --lr_scale 0.003 ;;
    efnone) run cifar10_hard24v2_local_topk_efnone \
        --error_type none --local_momentum 0.0 --lr_scale 0.1 ;;
    *) echo "unknown arm $arm"; exit 1 ;;
  esac
done
echo LOCAL_TOPK_DONE
