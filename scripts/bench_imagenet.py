#!/usr/bin/env python
"""ImageNet-lineage round throughput: FixupResNet50 @ 224x224, the
reference's only tuned recipe (imagenet.sh: 7 workers x local batch 64,
uncompressed, virtual momentum, iid — SURVEY §6). Measures the full
federated round (fused client gradients + reduce/server update) on one
chip; prints one JSON line like the other benches.

Kept OUT of the driver-run bench.py: a cold FixupResNet50@224 compile is
minutes long and the driver artifact must never hang on it; run this
standalone and the number is recorded in README.md. (Measured scaling
note: doubling the local batch to 128 lifts 2,812 -> 3,211 img/s /
17.6% -> 20.0% MFU — the round is conv-efficiency-bound at 224x224,
not batch-bound like the CIFAR flagship shape.)

Usage: python scripts/bench_imagenet.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench_common import log, peak_flops, timed_rounds
    from commefficient_tpu import models
    from commefficient_tpu.config import FedConfig, enable_compilation_cache
    from commefficient_tpu.core import FedRuntime
    from commefficient_tpu.losses import make_cv_loss

    log("devices:", jax.devices())
    W, B, HW = 7, 64, 224
    cfg = FedConfig(mode="uncompressed", error_type="virtual",
                    local_momentum=0.0, virtual_momentum=0.9,
                    weight_decay=1e-4, num_workers=W, local_batch_size=B,
                    num_clients=7, do_iid=True, track_bytes=False,
                    num_results_train=2)
    enable_compilation_cache(cfg)
    model = models.FixupResNet50(num_classes=1000)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.ones((1, HW, HW, 3), jnp.float32))
    loss_fn = make_cv_loss(model, "bfloat16")
    runtime = FedRuntime(cfg, params, loss_fn, num_clients=cfg.num_clients)
    log(f"grad size {runtime.cfg.grad_size}")

    rng = np.random.RandomState(0)
    batch = {"image": jnp.asarray(rng.randn(W, B, HW, HW, 3), jnp.float32),
             "target": jnp.asarray(rng.randint(0, 1000, (W, B)), jnp.int32)}
    mask = jnp.ones((W, B), bool)
    ids = jnp.arange(W, dtype=jnp.int32)

    n_rounds = 10
    t0 = time.time()
    dt, metrics, _phases = timed_rounds(runtime, (ids, batch, mask, 0.1),
                                        warmup=2, rounds=n_rounds,
                                        desc="imagenet")
    imgs = n_rounds * W * B
    ips = imgs / dt
    loss = float(np.asarray(metrics["results"][0]).mean())
    log(f"{n_rounds} rounds in {dt:.3f}s -> {ips:.1f} img/s, loss {loss:.3f}")

    # analytic model FLOPs: ResNet-50 fwd ~4.1 GFLOP per 224x224 image
    # (standard figure; Fixup changes normalization, not conv shapes),
    # bwd = 2x fwd
    flops = 3 * 4.1e9 * W * B
    peak = peak_flops(jax.devices()[0])
    mfu = (flops * n_rounds / dt) / peak
    log(f"model FLOPs/round {flops:.3e}, MFU {mfu:.3f}")
    print(json.dumps({"metric": "imagenet_fixupresnet50_round_throughput",
                      "value": round(ips, 1), "unit": "images/sec",
                      "mfu": round(mfu, 4),
                      "round_images": W * B,
                      "total_s": round(time.time() - t0, 1)}))


if __name__ == "__main__":
    main()
