#!/usr/bin/env python
"""ImageNet-lineage round throughput: FixupResNet50 @ 224x224, the
reference's only tuned recipe (imagenet.sh: 7 workers x local batch 64,
uncompressed, virtual momentum, iid — SURVEY §6). Measures the full
federated round (fused client gradients + reduce/server update) on one
chip; prints one JSON line like the other benches.

Input layout (the PR-5 fix): the batch is staged PER ROUND, inside the
timed loop, the way a training run actually feeds the chip. Default
``uint8_device``: each round gathers + flips + normalizes on device
from the uint8-resident store ("imagenet_train", data/device_store.py)
— no per-round float32 host input copy exists. ``--layout float_host``
instead device_puts the full float32 batch every round — the old input
path, whose lane-padded (C=3 -> 128, ~42x inflated) transfer the
committed trace attributed 4.8-9.6 ms/round to
(runs/BREAKDOWN_imagenet.md) — so the two arms A/B exactly the input
fix, visible in ``input_wait_frac``/``host_s`` and the throughput.

Kept OUT of the driver-run bench.py: a cold FixupResNet50@224 compile is
minutes long and the driver artifact must never hang on it; run this
standalone and the number is recorded in README.md. (Measured scaling
note: doubling the local batch to 128 lifts 2,812 -> 3,211 img/s /
17.6% -> 20.0% MFU — the round is conv-efficiency-bound at 224x224,
not batch-bound like the CIFAR flagship shape.)

Usage: python scripts/bench_imagenet.py [--layout uint8_device]
           [--telemetry_dir DIR] [--compile_cache DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    from bench import add_bench_args, make_bench_telemetry
    add_bench_args(ap)
    ap.add_argument("--layout", choices=("uint8_device", "float_host"),
                    default="uint8_device",
                    help="per-round batch staging: uint8 device store "
                         "with fused on-device normalize (default), or "
                         "the old per-round float32 host->device copy")
    ap.add_argument("--local_batch", type=int, default=64)
    ap.add_argument("--rounds", type=int, default=10)
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench_common import log, peak_flops, timed_rounds
    from commefficient_tpu import models
    from commefficient_tpu.config import FedConfig, enable_compilation_cache
    from commefficient_tpu.core import FedRuntime
    from commefficient_tpu.data import transforms as T
    from commefficient_tpu.data.device_store import DeviceStore
    from commefficient_tpu.losses import make_cv_loss

    telemetry, profiler = make_bench_telemetry(args, "bench_imagenet")
    log("devices:", jax.devices())
    W, B, HW = 7, args.local_batch, 224
    cfg = FedConfig(mode="uncompressed", error_type="virtual",
                    local_momentum=0.0, virtual_momentum=0.9,
                    weight_decay=1e-4, num_workers=W, local_batch_size=B,
                    num_clients=7, do_iid=True, track_bytes=False,
                    num_results_train=2)
    if args.compile_cache:
        cfg = cfg.replace(compilation_cache_dir=args.compile_cache)
    enable_compilation_cache(cfg)
    model = models.FixupResNet50(num_classes=1000)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.ones((1, HW, HW, 3), jnp.float32))
    loss_fn = make_cv_loss(model, "bfloat16")
    runtime = FedRuntime(cfg, params, loss_fn, num_clients=cfg.num_clients)
    log(f"grad size {runtime.cfg.grad_size}")
    if telemetry is not None:
        telemetry.instrument(runtime)
        telemetry.memory_event("imagenet_init")

    rng = np.random.RandomState(0)
    targets_dev = jnp.asarray(rng.randint(0, 1000, (W, B)), jnp.int32)
    mask = jnp.ones((W, B), bool)
    ids = jnp.arange(W, dtype=jnp.int32)
    if args.layout == "uint8_device":
        # the driver path: raw uint8 resident once; every round's batch
        # is a DEVICE-produced value (gather + flip + normalize in one
        # jit) — the float32 host input copy never exists
        imgs_u8 = rng.randint(0, 255, (W * B, HW, HW, 3), dtype=np.uint8)
        store = DeviceStore({"image": imgs_u8},
                            augment="imagenet_train",
                            mean=T.IMAGENET_MEAN, std=T.IMAGENET_STD)
        log(f"uint8 device store: {store.nbytes / 2**20:.0f} MiB resident")
        idx = np.arange(W * B).reshape(W, B)
        key = jax.random.PRNGKey(1)

        def round_args_fn(i):
            got = store.round_batch(idx, jax.random.fold_in(key, i))
            return (ids, {"image": got["image"], "target": targets_dev},
                    mask, 0.1)
    else:
        # the old input path: the full float32 batch crosses host->device
        # EVERY round (the lane-padded C=3->128 copy in the trace)
        host_imgs = rng.randn(W, B, HW, HW, 3).astype(np.float32)

        def round_args_fn(i):
            return (ids, {"image": jax.device_put(host_imgs),
                          "target": targets_dev}, mask, 0.1)

    n_rounds = args.rounds
    t0 = time.time()
    dt, metrics, phases = timed_rounds(runtime, None,
                                       warmup=2, rounds=n_rounds,
                                       desc="imagenet", profiler=profiler,
                                       round_args_fn=round_args_fn)
    warmup_s = phases.pop("warmup_s", None)
    imgs = n_rounds * W * B
    ips = imgs / dt
    loss = float(np.asarray(metrics["results"][0]).mean())
    log(f"{n_rounds} rounds in {dt:.3f}s -> {ips:.1f} img/s, loss {loss:.3f}")

    # analytic model FLOPs: ResNet-50 fwd ~4.1 GFLOP per 224x224 image
    # (standard figure; Fixup changes normalization, not conv shapes),
    # bwd = 2x fwd
    flops = 3 * 4.1e9 * W * B
    peak = peak_flops(jax.devices()[0])
    mfu = (flops * n_rounds / dt) / peak
    log(f"model FLOPs/round {flops:.3e}, MFU {mfu:.3f}")
    result = {"metric": "imagenet_fixupresnet50_round_throughput",
              "value": round(ips, 1), "unit": "images/sec",
              "mfu": round(mfu, 4),
              "round_images": W * B,
              "timed_rounds": n_rounds,
              "layout": args.layout,
              "warmup_s": warmup_s,
              "phase_split": phases,
              # gateable by `teleview diff --input_wait_rise` on the
              # bench trajectory, like bench.py / bench_gpt2.py
              "input_wait_frac": round(phases["host_s"] / dt, 6),
              "total_s": round(time.time() - t0, 1)}
    if telemetry is not None:
        from commefficient_tpu.telemetry.utilization import emit_from_totals
        emit_from_totals(
            telemetry, rnd=n_rounds, rounds=n_rounds, wall_s=dt,
            host_s=phases["host_s"], dispatch_s=phases["dispatch_s"],
            device_s=phases["device_wait_s"],
            flops_per_round=flops, flops_source="analytic",
            device_kind=getattr(jax.devices()[0], "device_kind", "unknown"))
        telemetry.bench_event(result["metric"], result)
        telemetry.write_summary(aborted=False, n_rounds=n_rounds,
                                final=result)
        telemetry.close()
    print(json.dumps(result))


if __name__ == "__main__":
    main()
