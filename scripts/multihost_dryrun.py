#!/usr/bin/env python
"""Multi-HOST dryrun: two real ``jax.distributed`` processes run ONE
sharded federated round on a global 8-device mesh (4 virtual CPU devices
per process) and must agree with a single-process run of the same mesh.

This executes the path ``parallel/mesh.py:init_distributed`` wraps — the
DCN equivalent of the reference's NCCL world bring-up, which is vestigial
there (hardcoded 127.0.0.1 single node, fed_aggregator.py:161-164). New
scope beyond the reference: the reference never runs multi-node; here the
claim "the same jitted round scales over processes" is executed, not
asserted.

What multi-process changes vs the in-process dryrun (__graft_entry__.py):
- ``jax.devices()`` is the GLOBAL device list; each process addresses
  only its local 4 — inputs must be built as global arrays from
  process-local shards (``jax.make_array_from_callback``), and only
  replicated outputs may be fetched on the host.
- every process executes the same program; the runtime's collectives run
  over the process boundary (gloo/TCP here, DCN on real pods).

Modes:
    python scripts/multihost_dryrun.py            # launcher (spawns all)
    python scripts/multihost_dryrun.py --ref      # single-process golden
    python scripts/multihost_dryrun.py --worker I --port P --nproc N
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys

N_GLOBAL = 8   # global mesh size = nproc * local devices


def _configure(local_devices: int) -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={local_devices}")
    import jax
    # a TPU-plugin sitecustomize may have pinned jax_platforms at the
    # config layer, which overrides the env var (see __graft_entry__.py)
    jax.config.update("jax_platforms", "cpu")


def run_round() -> None:
    """Build the global mesh, run one sketch round, print a checksum line
    ``CHECKSUM <loss> <|w|^2>`` computed from REPLICATED outputs (the only
    thing a process may fetch without owning every shard)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from commefficient_tpu import models
    from commefficient_tpu.config import FedConfig
    from commefficient_tpu.core import FedRuntime
    from commefficient_tpu.losses import make_cv_loss
    from commefficient_tpu.parallel import make_mesh

    devices = jax.devices()
    assert len(devices) == N_GLOBAL, (len(devices), N_GLOBAL)
    mesh = make_mesh((N_GLOBAL,), ("clients",), devices=devices)

    model = models.ResNet9(num_classes=10,
                           channels={"prep": 4, "layer1": 8,
                                     "layer2": 8, "layer3": 8})
    params = model.init(jax.random.PRNGKey(0),
                        jnp.ones((1, 32, 32, 3), jnp.float32))
    cfg = FedConfig(mode="sketch", error_type="virtual", local_momentum=0.0,
                    virtual_momentum=0.9, weight_decay=0.0,
                    num_workers=N_GLOBAL, local_batch_size=2, k=8,
                    num_rows=3, num_cols=64, num_blocks=2,
                    num_clients=2 * N_GLOBAL, track_bytes=False)
    runtime = FedRuntime(cfg, params, make_cv_loss(model, "float32"),
                         num_clients=cfg.num_clients, mesh=mesh)
    state = runtime.init_state()

    # identical full batch on every process; each contributes only the
    # shards its local devices own
    W, B = N_GLOBAL, 2
    rng = np.random.RandomState(0)
    host = {"image": rng.randn(W, B, 32, 32, 3).astype(np.float32),
            "target": rng.randint(0, 10, (W, B)).astype(np.int32)}

    def globalize(x, spec):
        sh = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(x.shape, sh,
                                            lambda idx: x[idx])

    batch = {k: globalize(v, P("clients")) for k, v in host.items()}
    mask = globalize(np.ones((W, B), bool), P("clients"))
    client_ids = globalize(np.arange(W, dtype=np.int32), P("clients"))

    state, metrics = runtime.round(state, client_ids, batch, mask, 0.1)

    # replicate-reduce before fetching: ps_weights is mesh-sharded and a
    # single process cannot materialize it
    @jax.jit
    def summarize(w, losses, n):
        total = jnp.sum(n)
        loss = jnp.sum(losses * n) / jnp.maximum(total, 1.0)
        return jax.lax.with_sharding_constraint(
            jnp.stack([loss, jnp.vdot(w, w)]),
            NamedSharding(mesh, P()))

    out = np.asarray(summarize(state.ps_weights, metrics["results"][0],
                               metrics["n_valid"].sum(axis=-1)))
    assert np.all(np.isfinite(out)), out
    print(f"CHECKSUM {out[0]:.6f} {out[1]:.6f}", flush=True)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--worker", type=int, default=None)
    ap.add_argument("--ref", action="store_true")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--nproc", type=int, default=2)
    args = ap.parse_args()

    if args.ref:
        _configure(N_GLOBAL)
        run_round()
        return 0

    if args.worker is not None:
        _configure(N_GLOBAL // args.nproc)
        from commefficient_tpu.parallel import init_distributed
        init_distributed(coordinator_address=f"127.0.0.1:{args.port}",
                         num_processes=args.nproc, process_id=args.worker)
        import jax
        assert jax.process_count() == args.nproc
        run_round()
        return 0

    # ---------------------------------------------------------- launcher
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    script = os.path.abspath(__file__)

    def spawn(extra):
        return subprocess.Popen([sys.executable, script] + extra, env=env,
                                cwd=repo, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)

    procs = {"ref": spawn(["--ref"])}
    for i in range(2):
        procs[f"worker{i}"] = spawn(["--worker", str(i), "--port",
                                     str(port), "--nproc", "2"])
    sums = {}
    ok = True
    for name, p in procs.items():
        out, _ = p.communicate(timeout=900)
        line = [ln for ln in out.splitlines() if ln.startswith("CHECKSUM")]
        if p.returncode != 0 or not line:
            print(f"{name} FAILED (rc={p.returncode}):\n{out[-3000:]}")
            ok = False
            continue
        sums[name] = [float(x) for x in line[0].split()[1:]]
        print(f"{name}: {line[0]}")
    if not ok:
        return 1
    import numpy as np
    ref = np.asarray(sums["ref"])
    for i in range(2):
        got = np.asarray(sums[f"worker{i}"])
        assert np.allclose(got, ref, rtol=1e-5), (ref, got)
    print("multihost dryrun: 2-process round == single-process round")
    return 0


if __name__ == "__main__":
    sys.exit(main())
