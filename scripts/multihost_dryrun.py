#!/usr/bin/env python
"""Multi-HOST dryrun: two real ``jax.distributed`` processes run ONE
sharded federated round on a global 8-device mesh (4 virtual CPU devices
per process) and must agree with a single-process run of the same mesh.

This executes the path ``parallel/mesh.py:init_distributed`` wraps — the
DCN equivalent of the reference's NCCL world bring-up, which is vestigial
there (hardcoded 127.0.0.1 single node, fed_aggregator.py:161-164). New
scope beyond the reference: the reference never runs multi-node; here the
claim "the same jitted round scales over processes" is executed, not
asserted.

What multi-process changes vs the in-process dryrun (__graft_entry__.py):
- ``jax.devices()`` is the GLOBAL device list; each process addresses
  only its local 4 — inputs must be built as global arrays from
  process-local shards (``jax.make_array_from_callback``), and only
  replicated outputs may be fetched on the host.
- every process executes the same program; the runtime's collectives run
  over the process boundary (gloo/TCP here, DCN on real pods).

Modes:
    python scripts/multihost_dryrun.py            # launcher (spawns all)
    python scripts/multihost_dryrun.py --ref      # single-process golden
    python scripts/multihost_dryrun.py --worker I --port P --nproc N
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_GLOBAL = 8   # global mesh size = nproc * local devices

# the CPU backend of some jax versions (e.g. the container's 0.4.x)
# cannot EXECUTE computations spanning processes ("Multiprocess
# computations aren't implemented on the CPU backend") — the worker
# processes then fail on the first sharded jit regardless of anything
# this script does. Detect that exact signature and fall back to
# ref-only validation (checksum + collective-count assertions still
# run) instead of failing a check the backend cannot host.
_BACKEND_UNSUPPORTED = "Multiprocess computations aren't implemented"


def _configure(local_devices: int) -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={local_devices}")
    import jax
    # a TPU-plugin sitecustomize may have pinned jax_platforms at the
    # config layer, which overrides the env var (see __graft_entry__.py)
    jax.config.update("jax_platforms", "cpu")


def run_round() -> None:
    """Build the global mesh, run one sketch round, print a checksum line
    ``CHECKSUM <loss> <|w|^2>`` computed from REPLICATED outputs (the only
    thing a process may fetch without owning every shard), and a
    ``COLLECTIVES {...}`` line with the compiled round's per-kind launch
    counts. Counts are asserted in EVERY process against the shared
    ceilings (telemetry/collectives.ROUND_COLLECTIVE_LAUNCH_BOUNDS) and
    cross-checked ref vs workers by the launcher — the round-5
    regression class (a layout conversion unrolling into per-row
    collectives, VERDICT weak #2) becomes a hard failure instead of an
    invisible size-preserving count explosion. The line lands in the
    MULTICHIP artifact via the captured output tail."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from commefficient_tpu import models
    from commefficient_tpu.config import FedConfig
    from commefficient_tpu.core import FedRuntime
    from commefficient_tpu.losses import make_cv_loss
    from commefficient_tpu.parallel import make_mesh

    devices = jax.devices()
    assert len(devices) == N_GLOBAL, (len(devices), N_GLOBAL)
    mesh = make_mesh((N_GLOBAL,), ("clients",), devices=devices)

    model = models.ResNet9(num_classes=10,
                           channels={"prep": 4, "layer1": 8,
                                     "layer2": 8, "layer3": 8})
    params = model.init(jax.random.PRNGKey(0),
                        jnp.ones((1, 32, 32, 3), jnp.float32))
    cfg = FedConfig(mode="sketch", error_type="virtual", local_momentum=0.0,
                    virtual_momentum=0.9, weight_decay=0.0,
                    num_workers=N_GLOBAL, local_batch_size=2, k=8,
                    num_rows=3, num_cols=64, num_blocks=2,
                    num_clients=2 * N_GLOBAL, track_bytes=False)
    runtime = FedRuntime(cfg, params, make_cv_loss(model, "float32"),
                         num_clients=cfg.num_clients, mesh=mesh)
    state = runtime.init_state()

    # identical full batch on every process; each contributes only the
    # shards its local devices own
    W, B = N_GLOBAL, 2
    rng = np.random.RandomState(0)
    host = {"image": rng.randn(W, B, 32, 32, 3).astype(np.float32),
            "target": rng.randint(0, 10, (W, B)).astype(np.int32)}

    def globalize(x, spec):
        sh = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(x.shape, sh,
                                            lambda idx: x[idx])

    batch = {k: globalize(v, P("clients")) for k, v in host.items()}
    mask = globalize(np.ones((W, B), bool), P("clients"))
    client_ids = globalize(np.arange(W, dtype=np.int32), P("clients"))

    state, metrics = runtime.round(state, client_ids, batch, mask, 0.1)

    # collective ledger of the compiled round (telemetry/collectives.py):
    # assert launch COUNTS, not just sizes — weak #2's regression class.
    # Post-round state is shape/sharding-identical to the input, so the
    # lowering is the same program (lower() reads avals, not values).
    import json
    from commefficient_tpu.telemetry.collectives import (
        ROUND_COLLECTIVE_LAUNCH_BOUNDS, round_ledger, summarize_ledger)
    counts = summarize_ledger(
        round_ledger(runtime, state, client_ids, batch, mask))["counts"]
    for kind, limit in ROUND_COLLECTIVE_LAUNCH_BOUNDS.items():
        assert counts.get(kind, 0) <= limit, (
            f"{counts.get(kind)} {kind} launches per round (bound "
            f"{limit}): a collective got unrolled — the round-5 per-row "
            "all_to_all regression class")
    # sharded-server kinds (PR 11): the sketch round's table psum is a
    # reduce-scatter now, and the shard-local top-k adds the ~n*k*8-byte
    # candidate all-gathers — every process (ref AND workers) must
    # compile them, and the launcher's dict cross-check below then
    # verifies ref == workers over the NEW kinds exactly like the old
    # ones. A sketch round with no reduce-scatter means the replicated
    # tail silently came back.
    assert counts.get("reduce-scatter", 0) >= 1, (
        f"sketch round compiled without the reduce-scattered table "
        f"aggregation (sharded server regressed): {counts}")
    n_gathers = counts.get("all-gather", 0)
    assert n_gathers >= 3, (
        f"sketch round compiled only {n_gathers} all-gathers — the "
        "sharded tail's table re-gather + candidate gathers are missing")
    print(f"COLLECTIVES {json.dumps(counts, sort_keys=True)}", flush=True)

    # replicate-reduce before fetching: ps_weights is mesh-sharded and a
    # single process cannot materialize it
    @jax.jit
    def summarize(w, losses, n):
        total = jnp.sum(n)
        loss = jnp.sum(losses * n) / jnp.maximum(total, 1.0)
        return jax.lax.with_sharding_constraint(
            jnp.stack([loss, jnp.vdot(w, w)]),
            NamedSharding(mesh, P()))

    out = np.asarray(summarize(state.ps_weights, metrics["results"][0],
                               metrics["n_valid"].sum(axis=-1)))
    assert np.all(np.isfinite(out)), out
    print(f"CHECKSUM {out[0]:.6f} {out[1]:.6f}", flush=True)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--worker", type=int, default=None)
    ap.add_argument("--ref", action="store_true")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--nproc", type=int, default=2)
    args = ap.parse_args()

    if args.ref:
        _configure(N_GLOBAL)
        run_round()
        return 0

    if args.worker is not None:
        _configure(N_GLOBAL // args.nproc)
        from commefficient_tpu.parallel import init_distributed
        init_distributed(coordinator_address=f"127.0.0.1:{args.port}",
                         num_processes=args.nproc, process_id=args.worker)
        import jax
        assert jax.process_count() == args.nproc
        run_round()
        return 0

    # ---------------------------------------------------------- launcher
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    script = os.path.abspath(__file__)

    def spawn(extra):
        return subprocess.Popen([sys.executable, script] + extra, env=env,
                                cwd=repo, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)

    procs = {"ref": spawn(["--ref"])}
    for i in range(2):
        procs[f"worker{i}"] = spawn(["--worker", str(i), "--port",
                                     str(port), "--nproc", "2"])
    import json
    sums = {}
    colls = {}
    ok = True
    backend_unsupported = False
    for name, p in procs.items():
        out, _ = p.communicate(timeout=900)
        line = [ln for ln in out.splitlines() if ln.startswith("CHECKSUM")]
        cline = [ln for ln in out.splitlines()
                 if ln.startswith("COLLECTIVES")]
        if p.returncode != 0 or not line or not cline:
            if name != "ref" and _BACKEND_UNSUPPORTED in out:
                print(f"{name} SKIPPED: this backend cannot execute "
                      "multiprocess computations (CPU backend of this "
                      "jax); ref-only validation")
                backend_unsupported = True
                continue
            print(f"{name} FAILED (rc={p.returncode}):\n{out[-3000:]}")
            ok = False
            continue
        sums[name] = [float(x) for x in line[0].split()[1:]]
        colls[name] = json.loads(cline[0].split(None, 1)[1])
        print(f"{name}: {line[0]}")
        print(f"{name}: {cline[0]}")
    if not ok or "ref" not in sums:
        return 1
    import numpy as np
    ref = np.asarray(sums["ref"])
    for i in range(2):
        if f"worker{i}" not in sums:
            continue
        got = np.asarray(sums[f"worker{i}"])
        assert np.allclose(got, ref, rtol=1e-5), (ref, got)
        # the distributed processes must compile the same collective
        # program as the single-process golden — a per-process count
        # drift is exactly the class of silent divergence weak #2 names
        assert colls[f"worker{i}"] == colls["ref"], (
            "collective counts diverged between single-process and "
            f"distributed compilation: ref={colls['ref']} "
            f"worker{i}={colls[f'worker{i}']}")
    if backend_unsupported:
        print("multihost dryrun: DEGRADED (ref-only — backend cannot run "
              "multiprocess); collective counts "
              f"{json.dumps(colls['ref'], sort_keys=True)}")
    else:
        print("multihost dryrun: 2-process round == single-process round; "
              f"collective counts {json.dumps(colls['ref'], sort_keys=True)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
