#!/usr/bin/env python
"""Attribute the ImageNet-recipe round's time op-by-op (VERDICT r4 weak #4).

Same method as scripts/profile_gpt2_round.py (jax.profiler xplane trace,
shared parser): FixupResNet50 @ 224x224, the reference's only tuned
recipe (imagenet.sh: 7 workers x local batch 64, uncompressed, virtual
momentum, iid). The committed narrative lives in
runs/BREAKDOWN_imagenet.md; the binary trace dir is gitignored.

Usage: python scripts/profile_imagenet_round.py [outdir] [--batch N]
"""

from __future__ import annotations

import collections
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from profile_gpt2_round import group_of, parse_xplane  # noqa: E402


def build_round(local_batch: int = 64):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from commefficient_tpu import models
    from commefficient_tpu.config import FedConfig, enable_compilation_cache
    from commefficient_tpu.core import FedRuntime
    from commefficient_tpu.losses import make_cv_loss

    W, B, HW = 7, local_batch, 224
    cfg = FedConfig(mode="uncompressed", error_type="virtual",
                    local_momentum=0.0, virtual_momentum=0.9,
                    weight_decay=1e-4, num_workers=W, local_batch_size=B,
                    num_clients=7, do_iid=True, track_bytes=False,
                    num_results_train=2)
    enable_compilation_cache(cfg)
    model = models.FixupResNet50(num_classes=1000)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.ones((1, HW, HW, 3), jnp.float32))
    runtime = FedRuntime(cfg, params, make_cv_loss(model, "bfloat16"),
                         num_clients=cfg.num_clients)
    rng = np.random.RandomState(0)
    batch = {"image": jnp.asarray(rng.randn(W, B, HW, HW, 3), jnp.float32),
             "target": jnp.asarray(rng.randint(0, 1000, (W, B)), jnp.int32)}
    args = (jnp.arange(W, dtype=jnp.int32), batch, jnp.ones((W, B), bool),
            0.1)
    return runtime, args, W * B


def main():
    argv = [a for a in sys.argv[1:]]
    local_batch = 64
    if "--batch" in argv:
        i = argv.index("--batch")
        local_batch = int(argv[i + 1])
        del argv[i:i + 2]
    outdir = argv[0] if argv else "runs/profile_imagenet"
    os.makedirs(outdir, exist_ok=True)
    import jax

    runtime, args, imgs = build_round(local_batch)
    state = runtime.init_state()
    print("compiling + warmup...", flush=True)
    t0 = time.time()
    state, _ = runtime.round(state, *args)
    jax.block_until_ready(state.ps_weights)
    print(f"warmup {time.time() - t0:.1f}s", flush=True)

    t0 = time.time()
    with jax.profiler.trace(outdir):
        for _ in range(3):
            state, metrics = runtime.round(state, *args)
        jax.block_until_ready(state.ps_weights)
    wall = (time.time() - t0) / 3
    print(f"traced 3 rounds, {wall * 1e3:.1f} ms/round wall "
          f"({imgs / wall:.0f} img/s)", flush=True)

    ops, span = parse_xplane(outdir)
    if ops is None:
        print("NO DEVICE TRACE CAPTURED — fall back to component ablation")
        return
    total = sum(ms for _, ms in ops)
    print(f"\ndevice busy time {total / 3:.1f} ms/round "
          f"(span {span / 3:.1f} ms/round)\n")
    by_group = collections.Counter()
    for name, ms in ops:
        by_group[group_of(name)] += ms
    print(f"{'group':28s} {'ms/round':>9s}  share")
    for g, ms in by_group.most_common():
        print(f"{g:28s} {ms / 3:9.2f}  {ms / total:6.1%}")
    print("\ntop 40 ops (ms/round):")
    for name, ms in ops[:40]:
        print(f"  {ms / 3:8.2f}  {name[:110]}")


if __name__ == "__main__":
    main()
