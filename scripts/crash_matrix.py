#!/usr/bin/env python
"""Crash/kill fault-injection matrix: prove the resume story against
REAL process death.

For each kill-point (commefficient_tpu/faults.py) the harness runs a
small deterministic federated training child three ways:

1. **straight** — no fault, the bit-exact baseline (per-round losses
   read back from its telemetry stream);
2. **faulted** — the same child with ``COMMEFFICIENT_FAULT`` armed:
   ``kill`` points die via ``os._exit(137)`` exactly there (no
   ``finally``, no flush — the SIGKILL-alike), ``sigterm`` points
   self-signal and exercise the graceful --preempt_grace drain;
3. **resumed** — ``--resume`` against the same checkpoint dir and the
   same logdir, so the telemetry stream APPENDS behind a `resume`
   lineage record.

Asserted per point: the resume exits 0; the union of round records is
BIT-identical to the straight baseline (same loss float per global
round — JSON round-trips floats exactly); no ``*.tmp`` litter survives
in the checkpoint dir; the stitched stream carries the lineage
(`resume` event, and a `fault` event for the graceful points); and the
child leaves no threads behind (its clean exit is the proof).

Usage::

    python scripts/crash_matrix.py                  # full matrix
    python scripts/crash_matrix.py --points pre_round,mid_round
    python scripts/crash_matrix.py --keep           # keep the scratch dirs

Exit status: 0 = every point passed, 1 = any failure.

The child is this same file with ``--child`` (a quad-model
cv_train.train run — the tier-1 driver-test harness in subprocess
form), so the matrix needs no dataset downloads and runs on the CPU
backend in seconds per arm.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

KILL_EXIT = 137          # faults.KILL_EXIT_CODE (keep jax out of parent)

# (label, COMMEFFICIENT_FAULT spec, async child?, expected exit codes).
# The child runs 2 epochs x 8 rounds with a checkpoint at each epoch
# boundary: kills at round 12 land mid-epoch-2, so the resume restores
# the epoch-1 generation and REPLAYS rounds 9.. (their re-emitted
# records must agree bit-for-bit with the faulted run's); the
# mid-checkpoint kill dies during the FIRST save (tmp litter, no
# generation yet); the graceful arm self-SIGTERMs at round 5 and
# resumes from the round-granular preempt checkpoint.
MATRIX = (
    ("pre_round", "kill:pre_round:12", False, (KILL_EXIT,)),
    ("mid_round", "kill:mid_round:12", False, (KILL_EXIT,)),
    ("mid_checkpoint_write", "kill:mid_checkpoint_write", False,
     (KILL_EXIT,)),
    ("mid_telemetry_flush", "kill:mid_telemetry_flush:40", False,
     (KILL_EXIT,)),
    ("async_pool", "kill:async_pool:12", True, (KILL_EXIT,)),
    ("graceful_preempt", "sigterm:pre_round:5", False, (0,)),
)


# ---------------------------------------------------------------- child


def run_child(args) -> int:
    """One deterministic training run: quad model, 8 clients x 16 items,
    W=4 B=2 => 8 rounds/epoch x 2 epochs, checkpoint every epoch,
    per-round telemetry into a FIXED logdir (the resume appends)."""
    import jax.numpy as jnp
    import numpy as np

    from commefficient_tpu import cv_train
    from commefficient_tpu.config import FedConfig
    from commefficient_tpu.core import FedRuntime
    from commefficient_tpu.telemetry import RunTelemetry
    from commefficient_tpu.utils import TableLogger

    D_IN, D_OUT = 6, 3

    def loss_fn(params, batch, mask):
        pred = batch["x"] @ params["w"]
        m = mask.astype(jnp.float32)
        denom = jnp.maximum(m.sum(), 1.0)
        err = ((pred - batch["y"]) ** 2).sum(axis=1)
        loss = (err * m).sum() / denom
        return loss, (loss,)

    class DS:
        data_per_client = np.full(8, 8)   # W=4 x B=2 => 8 rounds/epoch
        num_clients = 8
        _rng = np.random.RandomState(0)
        _x = _rng.randn(256, D_IN).astype(np.float32)
        _y = _rng.randn(256, D_OUT).astype(np.float32)

        def __len__(self):
            return 64

        def gather(self, idx):
            idx = np.asarray(idx)
            return {"x": self._x[idx], "y": self._y[idx]}

    cfg = FedConfig(
        mode="sketch", error_type="virtual", local_momentum=0.0,
        virtual_momentum=0.9, weight_decay=0.0, num_workers=4,
        local_batch_size=2, track_bytes=True, num_clients=8,
        num_results_train=2, num_results_val=2, k=5, num_rows=2,
        num_cols=32, exact_num_cols=True, dataset_name="SYNTH",
        telemetry_every=1, num_epochs=2.0, pivot_epoch=1.0,
        checkpoint_every=1, checkpoint_path=args.ckpt,
        do_resume=args.resume, preempt_grace=20.0,
        async_agg=args.async_agg,
        max_inflight=2 if args.async_agg else 4,
        buffer_goal=2 if args.async_agg else 1)
    params = {"w": jnp.asarray(
        np.random.RandomState(0).randn(D_IN, D_OUT), jnp.float32)}
    rt = FedRuntime(cfg, params, loss_fn, num_clients=8)
    mgr, start_epoch, restored, resume_info = cv_train.setup_checkpointing(
        cfg, rt, "quad")
    state = restored if restored is not None else rt.init_state()
    tel = RunTelemetry(
        args.logdir, "cv_train", cfg=rt.cfg,
        resume_info=(None if resume_info is None else
                     {"round": resume_info["global_round"],
                      "epoch": start_epoch,
                      "checkpoint": resume_info["checkpoint"]}))
    tel.instrument(rt)
    try:
        state, summary = cv_train.train(
            cfg, rt, state, DS(), DS(), loggers=(TableLogger(),),
            telemetry=tel, ckpt_mgr=mgr, start_epoch=start_epoch,
            resume_info=resume_info)
    finally:
        tel.close()
    # final weights fingerprint, for the parent's bitwise comparison
    w = np.asarray(rt.flat_weights(state)).tobytes()
    import hashlib
    print("CHILD_WEIGHTS " + hashlib.sha256(w).hexdigest())
    return 0


# --------------------------------------------------------------- parent


def _read_rounds(logdir: str):
    """{global round -> loss} from a (possibly stitched) stream; a later
    segment's record for the same round must agree with the earlier one
    (replayed rounds are bit-identical by contract)."""
    out, conflicts = {}, []
    path = os.path.join(logdir, "telemetry.jsonl")
    if not os.path.exists(path):
        return out, conflicts, []
    kinds = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                e = json.loads(line)
            except ValueError:
                continue        # the truncated kill-mid-flush fragment
            kinds.append(e.get("event"))
            if e.get("event") == "round":
                r, loss = e["round"], e["loss"]
                if r in out and out[r] != loss:
                    conflicts.append((r, out[r], loss))
                out[r] = loss
    return out, conflicts, kinds


def _spawn(args, extra_env, workdir, label):
    env = dict(os.environ, JAX_PLATFORMS="cpu", **extra_env)
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child"] + args,
        env=env, cwd=workdir, capture_output=True, text=True)
    sys.stdout.write(f"    [{label}] exit {proc.returncode}\n")
    return proc


def run_matrix(points, keep: bool) -> int:
    failures = []
    scratch = tempfile.mkdtemp(prefix="crash_matrix_")
    try:
        baselines = {}
        for is_async in sorted({a for _, _, a, _ in points}):
            base_dir = os.path.join(scratch, f"base_{int(is_async)}")
            args = ["--ckpt", os.path.join(base_dir, "ck"),
                    "--logdir", os.path.join(base_dir, "logs")]
            if is_async:
                args.append("--async_agg")
            proc = _spawn(args, {}, scratch, "baseline")
            rounds, conflicts, _ = _read_rounds(
                os.path.join(base_dir, "logs"))
            if proc.returncode != 0 or not rounds or conflicts:
                print(proc.stdout[-2000:], proc.stderr[-2000:])
                print("FATAL: baseline run failed")
                return 1
            weights = [ln for ln in proc.stdout.splitlines()
                       if ln.startswith("CHILD_WEIGHTS")]
            baselines[is_async] = (rounds, weights[-1])
        for label, spec, is_async, ok_exits in points:
            print(f"== {label} ({spec})")
            d = os.path.join(scratch, label)
            args = ["--ckpt", os.path.join(d, "ck"),
                    "--logdir", os.path.join(d, "logs")]
            if is_async:
                args.append("--async_agg")
            bad = []
            faulted = _spawn(args, {"COMMEFFICIENT_FAULT": spec}, scratch,
                             "faulted")
            if faulted.returncode not in ok_exits:
                bad.append(f"faulted exit {faulted.returncode} not in "
                           f"{ok_exits}")
            resumed = _spawn(args + ["--resume"], {}, scratch, "resumed")
            if resumed.returncode != 0:
                bad.append(f"resume exit {resumed.returncode}")
                print(resumed.stdout[-2000:], resumed.stderr[-2000:])
            rounds, conflicts, kinds = _read_rounds(
                os.path.join(d, "logs"))
            base_rounds, base_weights = baselines[is_async]
            if conflicts:
                bad.append(f"replayed rounds disagree: {conflicts[:3]}")
            if rounds != base_rounds:
                missing = sorted(set(base_rounds) - set(rounds))
                diff = [r for r in rounds
                        if base_rounds.get(r) != rounds[r]]
                bad.append(f"round/loss map != baseline (missing "
                           f"{missing[:5]}, diverged {diff[:5]})")
            weights = [ln for ln in resumed.stdout.splitlines()
                       if ln.startswith("CHILD_WEIGHTS")]
            if not weights or weights[-1] != base_weights:
                bad.append("final weights differ from the straight run")
            ck_dir = os.path.join(d, "ck", "quad")
            litter = [fn for fn in os.listdir(ck_dir)
                      if fn.endswith(".tmp")] if os.path.isdir(ck_dir) \
                else []
            if litter:
                bad.append(f".tmp litter survived the resume: {litter}")
            if "resume" not in kinds:
                bad.append("no `resume` lineage record in the stream")
            if spec.startswith("sigterm") and "fault" not in kinds:
                bad.append("graceful preempt left no `fault` event")
            status = "PASS" if not bad else "FAIL: " + "; ".join(bad)
            print(f"RESULT {label}: {status}")
            if bad:
                failures.append(label)
        return 1 if failures else 0
    finally:
        if keep:
            print(f"scratch kept at {scratch}")
        else:
            shutil.rmtree(scratch, ignore_errors=True)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--child", action="store_true")
    p.add_argument("--resume", action="store_true")
    p.add_argument("--async_agg", action="store_true")
    p.add_argument("--ckpt", type=str, default="")
    p.add_argument("--logdir", type=str, default="")
    p.add_argument("--points", type=str, default="",
                   help="comma-separated kill-point labels (default all)")
    p.add_argument("--keep", action="store_true")
    args = p.parse_args(argv)
    if args.child:
        return run_child(args)
    wanted = set(filter(None, args.points.split(",")))
    points = [m for m in MATRIX if not wanted or m[0] in wanted]
    if not points:
        print(f"no kill-points match {sorted(wanted)}; known: "
              f"{[m[0] for m in MATRIX]}")
        return 2
    return run_matrix(points, args.keep)


if __name__ == "__main__":
    sys.exit(main())
