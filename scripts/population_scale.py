#!/usr/bin/env python
"""Population-scale observability evidence (PR 16): memory and estimate
accuracy of the sketch-backed ledger (telemetry/population.py) against
the exact ledger, swept over population sizes 10^4 -> 10^6 on the SAME
deterministic zipf-skewed sampler stream shape the dryrun gate uses.

For every population size it records the sketch ledger's measured
footprint (the documented ``memory_bytes`` accounting AND the
serialized checkpoint-sidecar bytes — the number the PR-13 size guard
caps) plus, where an exact control is feasible, the realized estimator
errors next to their documented bounds: count-min max/mean overcount
vs eps*N, KMV distinct relative error vs ~1/sqrt(S), the coverage gap,
and heavy-hitter recall for every id above the N/K guarantee line.

Also writes a schema-v11 telemetry stream carrying ``population``
events from BOTH ledger modes over the 10^4 arm, so the committed
artifact exercises `teleview population` and the `diff
--coverage_stall` gate end to end. Host-only numpy — no jax, no
devices; results land in runs/population/.

    python scripts/population_scale.py [--out runs/population]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from commefficient_tpu.telemetry.clients import ParticipationLedger  # noqa: E402
from commefficient_tpu.telemetry.population import (  # noqa: E402
    MEMORY_BUDGET_BYTES, PopulationLedger)

ROUNDS, SLOTS, SEED = 300, 512, 0xB16
EXACT_CEILING = 200_000  # above this the exact control itself is the liability


def stream(rs, num_clients):
    hot = rs.zipf(1.5, SLOTS // 2) % num_clients
    cold = rs.randint(0, num_clients, SLOTS - SLOTS // 2)
    ids = np.concatenate([hot, cold]).astype(np.int64)
    return ids, rs.randint(1, 9, SLOTS).astype(np.int64)


def sweep_one(num_clients):
    rs = np.random.RandomState(SEED)
    sk = PopulationLedger(num_clients, seed=7)
    exact = num_clients <= EXACT_CEILING
    true = np.zeros(num_clients, np.float64) if exact else None
    t0 = time.perf_counter()
    for rnd in range(1, ROUNDS + 1):
        ids, w = stream(rs, num_clients)
        sk.observe(rnd, ids, w)
        sk.observe_loss_argmax(int(ids[0]))
        if true is not None:
            np.add.at(true, ids, w.astype(np.float64))
    ingest_s = time.perf_counter() - t0
    sidecar = json.dumps(sk.state_dict()).encode()
    snap = sk.population_snapshot(ROUNDS)
    row = {
        "num_clients": num_clients,
        "rounds": ROUNDS,
        "slots": SLOTS,
        "ingest_s": round(ingest_s, 3),
        "memory_bytes": sk.memory_bytes(),
        "sidecar_bytes": len(sidecar),
        "budget_bytes": MEMORY_BUDGET_BYTES,
        "distinct_est": snap["distinct"],
        "coverage_est": snap["coverage"],
        "counts_p50_est": snap["counts_p50"],
        "cm_epsilon": snap["cm_epsilon"],
        "cm_delta": snap["cm_delta"],
    }
    if true is not None:
        n = float(true.sum())
        est = sk.participation_count(np.arange(num_clients, dtype=np.int64))
        over = est - true
        assert np.all(over >= -1e-9), "count-min undercounted"
        floor = n / sk._hh_sampled.k
        heavy = np.nonzero(true > floor)[0]
        held = sum(int(c) in sk._hh_sampled._counts for c in heavy.tolist())
        exact_distinct = int(np.count_nonzero(true))
        # the exact ledger's sidecar at the same population: the number
        # the PR-13 guard compares against its cap
        ex = ParticipationLedger(num_clients)
        rs2 = np.random.RandomState(SEED)
        for rnd in range(1, ROUNDS + 1):
            ids, w = stream(rs2, num_clients)
            ex.observe(rnd, ids, w)
        ex_sidecar = len(json.dumps(ex.state_dict()).encode())
        esnap = ex.population_snapshot(ROUNDS)
        row.update({
            "n_total": n,
            "cm_bound": sk._cm.epsilon * n,
            "cm_overcount_max": float(over.max()),
            "cm_overcount_mean": float(over.mean()),
            "cm_within_bound_frac": float(
                np.mean(over <= sk._cm.epsilon * n)),
            "distinct_exact": exact_distinct,
            "distinct_rel_err": abs(snap["distinct"] - exact_distinct)
            / max(exact_distinct, 1),
            "coverage_exact": esnap["coverage"],
            "counts_p50_exact": esnap["counts_p50"],
            "staleness_p50_est": snap["staleness_p50"],
            "staleness_p50_exact": esnap["staleness_p50"],
            "hh_guaranteed": int(heavy.size),
            "hh_held": held,
            "exact_sidecar_bytes": ex_sidecar,
            "exact_memory_bytes": ex.memory_bytes(),
        })
    return row, sk


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join("runs", "population"))
    ap.add_argument("--sizes", type=int, nargs="*",
                    default=[10_000, 100_000, 1_000_000])
    args = ap.parse_args(argv)
    os.makedirs(args.out, exist_ok=True)
    rows = []
    for n in args.sizes:
        row, sk = sweep_one(n)
        rows.append(row)
        print(json.dumps(row))
        assert row["memory_bytes"] <= MEMORY_BUDGET_BYTES
        assert row["sidecar_bytes"] <= MEMORY_BUDGET_BYTES
    with open(os.path.join(args.out, "population_scale.jsonl"), "w") as f:
        f.writelines(json.dumps(r) + "\n" for r in rows)

    # the committed v11 stream: both ledger modes over the 10^4 arm
    from commefficient_tpu.telemetry.run import RunTelemetry
    from commefficient_tpu.telemetry.schema import validate_file
    tel = RunTelemetry(args.out, "population_scale", cfg=None)
    for mode, cls in (("sketch", PopulationLedger),
                      ("exact", ParticipationLedger)):
        led = (cls(10_000, seed=7) if cls is PopulationLedger
               else cls(10_000))
        rs = np.random.RandomState(SEED)
        for rnd in range(1, ROUNDS + 1):
            ids, w = stream(rs, 10_000)
            led.observe(rnd, ids, w)
            if rnd % 50 == 0:
                tel.population_event(
                    snapshot=led.population_snapshot(rnd))
    tel.close()
    problems = validate_file(tel.path)
    assert problems == [], problems
    print(f"wrote {args.out}/population_scale.jsonl "
          f"({len(rows)} arms) and a schema-valid v11 stream "
          f"({tel.path})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
