#!/usr/bin/env bash
# Layer-wise compression attribution evidence runs (ISSUE 15): the
# hard-v2 sketch recipe at the flagship 2.6x compression and at the
# ~10x ROADMAP target, with the schema-v10 layer_signals stream on
# (--signals_exact + --sketch_fused_encode off keep the dense capture
# alive so grad_mass and the per-group heavy-hitter overlap are live —
# the starvation rule measures against gradient mass, never guesses).
#
# CPU-scale arms: the FLAGSHIP sketch geometry is kept exactly
# (d = 6.57M ResNet9+BN, r = 5, k = 50k, c = 500k -> 2.63x; the 10x arm
# narrows c to 131072 -> 10.0x) — only the schedule is cut to CPU size
# (local_batch_size 32, 2 epochs of the 4k-image synthetic-hard set vs
# the committed 48-epoch TPU runs), so the per-group attribution
# describes the real flagship channel, not a toy. The committed
# streams + runs/BREAKDOWN_layers.md are the analysis artifact.
#
# Usage: scripts/layer_attribution.sh [c26x] [c10x]
set -euo pipefail
cd "$(dirname "$0")/.."

run() {
    local name=$1; shift
    echo "=== $name ==="
    rm -rf "runs/layer_attrib/$name"
    python cv_train.py --dataset_name CIFAR10 --model ResNet9 --batchnorm \
      --iid --num_clients 40 --num_workers 8 --local_batch_size 32 \
      --num_epochs 2 --synthetic_per_class 400 --synthetic_hard \
      --synthetic_label_noise 0.08 --lr_scale 0.1 --seed 21 \
      --local_momentum 0.0 --virtual_momentum 0.9 \
      --mode sketch --error_type virtual \
      --k 50000 --num_rows 5 --num_blocks 20 --approx_topk \
      --exact_num_cols --signals_exact --sketch_fused_encode off \
      --telemetry_every 1 --logdir "runs/layer_attrib/$name" \
      "$@" 2>&1 | tail -5
    python scripts/teleview.py layers "runs/layer_attrib/$name"
}

[ $# -eq 0 ] && set -- c26x c10x
for arm in "$@"; do
  case "$arm" in
    c26x) run c26x --num_cols 500000 ;;   # flagship: d/(r*c) = 2.63x
    c10x) run c10x --num_cols 131072 ;;   # ROADMAP target: 10.0x
    *) echo "unknown arm $arm"; exit 1 ;;
  esac
done
