#!/usr/bin/env python
"""Long-context attention scaling on one chip: dense vs flash
(--attn_impl) for GPT-2 fwd+bwd at growing sequence length, constant
token budget per step.

Why this exists: at the flagship federated round's S=256 the flash
kernel LOSES to dense attention (grid overhead > what fusing a 256x256
softmax saves — runs/BREAKDOWN_gpt2.md). Attention cost scales O(S^2)
while everything else is O(S), so the crossover and the memory wall both
live at longer S — this script measures both. The dense path
materializes (B, H, S, S) logits; at S=4096 that is 1.6 GiB bf16 per
microbatch PER LAYER in the backward's saved activations, which is the
wall flash's O(S) memory removes. (Multi-chip long-context uses ring
attention over a "seq" mesh axis — parallel/ring.py — which composes
with the same federated round; this script is the single-chip half of
the story.)

Timing: chained lax.scan over grad steps (the axon tunnel poisons any
per-call host timing). MFU from the analytic FLOP model (bench_gpt2).

Usage: python scripts/bench_longctx.py [reps=4]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    reps = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench_common import peak_flops
    from bench_gpt2 import gpt2_model_flops
    from commefficient_tpu.models.gpt2 import (GPT2Config, GPT2LMHead,
                                               resolve_attn)
    from commefficient_tpu.ops import ravel_params

    TOKENS = 16384  # per step, constant across S
    peak = peak_flops(jax.devices()[0])
    print(f"{'S':>6s} {'B':>3s} {'attn':>6s} {'ms/step':>9s} "
          f"{'tok/s':>9s} {'MFU':>6s}")
    for S in (1024, 2048, 4096):
        B = TOKENS // S
        for attn in ("dense", "flash"):
            gcfg = GPT2Config(n_positions=S, remat=True)
            model = GPT2LMHead(gcfg, attn_impl=resolve_attn(attn))
            rng = np.random.RandomState(0)
            ids = jnp.asarray(rng.randint(0, 50257, (B, S)), jnp.int32)
            labels = jnp.asarray(rng.randint(0, 50257, (B, S)), jnp.int32)
            params = model.init(jax.random.PRNGKey(0), ids[:1])
            vec, unravel = ravel_params(params)

            def loss_fn(v):
                logits = model.apply(unravel(v), ids)
                lp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
                tgt = labels[:, 1:]
                nll = -jnp.take_along_axis(lp, tgt[..., None], -1)
                return nll.mean()

            grad = jax.value_and_grad(loss_fn)

            def chain(v, n):
                def body(carry, _):
                    l, g = grad(carry)
                    return carry - 1e-12 * g, l
                v_out, ls = jax.lax.scan(body, v, None, length=n)
                return v_out[0] + ls[-1]

            run = jax.jit(chain, static_argnums=1)
            try:
                float(run(vec, reps))          # compile + warmup
                t0 = time.time()
                float(run(vec, reps))
                dt = (time.time() - t0) / reps
            except Exception as e:
                print(f"{S:6d} {B:3d} {attn:>6s}    FAILED "
                      f"{type(e).__name__}: {str(e).splitlines()[0][:60]}",
                      flush=True)
                continue
            flops = gpt2_model_flops(gcfg, B * S, S)
            print(f"{S:6d} {B:3d} {attn:>6s} {dt * 1e3:9.1f} "
                  f"{B * S / dt:9.0f} {flops / dt / peak:6.1%}", flush=True)


if __name__ == "__main__":
    main()
