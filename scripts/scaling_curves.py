#!/usr/bin/env python
"""Weak/strong scaling curves for the sharded sketch round.

ROADMAP item 2's committed evidence harness: run the SAME sharded
sketch federated round over meshes of growing device count and record
throughput, per-chip throughput, the compiled round's collective
inventory and schema-v7 ``utilization`` events — then gate the weak
curve's per-chip throughput with ``teleview diff --perchip_drop``.

Arms (each a SUBPROCESS, because the virtual device count must be
pinned in ``XLA_FLAGS`` before jax initializes — the exact flags a real
multi-chip slice run drops in favor of its physical topology):

- **weak scaling**: clients grow with the mesh (W = 2n, fixed
  per-client batch) — per-chip work constant, so per-chip throughput
  staying flat is the "added chips add capacity" contract;
- **strong scaling**: a fixed client population (W = 8) sharded over
  1..n devices — total work constant, wall time should fall.

On this container the "chips" are ``--xla_force_host_platform_device_
count`` virtual CPU devices sharing one socket, so the committed curve
validates the HARNESS — arm mechanics, collective shapes (the
reduce-scattered table + candidate gathers land in every arm's
ledger), schema-v7 per-chip fields, the teleview gate wiring — and
bounds scheduling overhead, NOT ICI bandwidth. A real v5e slice runs
the identical script with no XLA_FLAGS override; the gate threshold
then tightens from the virtual-device default (see --perchip_drop).

Usage:
    python scripts/scaling_curves.py --out runs/scaling_dryrun.jsonl
    python scripts/scaling_curves.py --arm weak --n 4 --stream DIR  # internal

The launcher writes one JSONL line per arm plus a final ``gate`` line
recording the teleview verdict; ``__graft_entry__.dryrun_multichip``
asserts the committed artifact carries a weak curve whose gate passed.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_DEVICES = (1, 2, 4, 8)
STRONG_WORKERS = 8      # fixed population for the strong arms
WEAK_PER_DEVICE = 2     # clients per device for the weak arms
BATCH = 8
# per-chip drop tolerance for the committed VIRTUAL-device weak curve:
# the 2->8 device arms share one CPU socket, so the gate bounds
# harness/scheduling overhead, not ICI (measured headroom over the
# observed drop; a real slice passes a far tighter threshold — see the
# module docstring and runs/BREAKDOWN_scaling.md)
DRYRUN_PERCHIP_DROP = 0.55
# int8 table-reduce wire-byte ceiling vs the f32 arm (scales included):
# the ISSUE-14 contract, shared with __graft_entry__._wire_gate
WIRE_BYTES_CEILING = 0.30


def _configure(n: int) -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")


def run_arm(scaling: str, n: int, stream_dir: str, rounds: int,
            warmup: int, wire_dtype: str = "float32",
            compile_cache: str = "") -> None:
    """One arm: n-device mesh, the sharded sketch round, telemetry +
    timing; prints a ``RESULT {...}`` line the launcher collects."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from commefficient_tpu import models
    from commefficient_tpu.config import (FedConfig,
                                          enable_compilation_cache_dir)
    from commefficient_tpu.core import FedRuntime
    from commefficient_tpu.losses import make_cv_loss
    from commefficient_tpu.parallel import make_mesh
    from commefficient_tpu.telemetry import RunTelemetry, UtilizationTracker
    from commefficient_tpu.telemetry.schema import validate_file

    assert len(jax.devices()) == n, (len(jax.devices()), n)
    # persistent XLA compile cache: without it EVERY subprocess arm pays
    # the cold round compile (BENCH r05 measured it at 77 s on the
    # flagship round) — the launcher threads --compile_cache through so
    # repeat sweeps start warm; warmup_s below records what was paid
    if compile_cache:
        enable_compilation_cache_dir(compile_cache)
    mesh = make_mesh((n,), ("clients",)) if n > 1 else None

    W = WEAK_PER_DEVICE * n if scaling == "weak" else STRONG_WORKERS
    model = models.ResNet9(num_classes=10,
                           channels={"prep": 4, "layer1": 8,
                                     "layer2": 8, "layer3": 8})
    params = model.init(jax.random.PRNGKey(0),
                        jnp.ones((1, 32, 32, 3), jnp.float32))
    cfg = FedConfig(mode="sketch", error_type="virtual",
                    local_momentum=0.0, virtual_momentum=0.9,
                    weight_decay=0.0, num_workers=W, local_batch_size=BATCH,
                    k=8, num_rows=3, num_cols=512, num_blocks=2,
                    num_clients=2 * W, track_bytes=False,
                    wire_dtype=wire_dtype)
    runtime = FedRuntime(cfg, params, make_cv_loss(model, "float32"),
                         num_clients=cfg.num_clients, mesh=mesh)
    state = runtime.init_state()

    tel = RunTelemetry(stream_dir, "scaling_arm", cfg=runtime.cfg)
    tel.instrument(runtime)
    util = UtilizationTracker(tel, peak_flops=1e12, peak_hbm_gbps=100.0,
                              watcher=tel.watcher(), n_devices=n,
                              mesh_shape=[n])

    key = jax.random.PRNGKey(0x5CA1)

    def batch_for(g):
        k1, k2 = jax.random.split(jax.random.fold_in(key, g))
        return {"image": jax.random.normal(k1, (W, BATCH, 32, 32, 3),
                                           jnp.float32),
                "target": jax.random.randint(k2, (W, BATCH), 0, 10,
                                             jnp.int32)}

    ids = jnp.arange(W, dtype=jnp.int32)
    mask = jnp.ones((W, BATCH), bool)

    tw = time.perf_counter()
    for g in range(1, warmup + 1):          # compile + cache warm
        state, m = runtime.round(state, ids, batch_for(g), mask, 0.1)
    jax.block_until_ready(m["results"][0])
    # compile + warmup wall seconds BEFORE the timed window — the
    # number --compile_cache exists to shrink (tracked per arm so the
    # cold-compile tax of a sweep is visible in the committed artifact)
    warmup_s = time.perf_counter() - tw

    t0 = time.perf_counter()
    for g in range(warmup + 1, warmup + rounds + 1):
        r0 = time.perf_counter()
        state, m = runtime.round(state, ids, batch_for(g), mask, 0.1)
        r1 = time.perf_counter()
        jax.block_until_ready(m["results"][0])
        util.observe_round(host_s=0.0, dispatch_s=r1 - r0,
                           device_s=time.perf_counter() - r1)
    wall = time.perf_counter() - t0
    util.emit(warmup + rounds)

    losses = np.asarray(m["results"][0])
    assert np.all(np.isfinite(losses)), losses
    items = W * BATCH * rounds
    result = {
        "scaling": scaling,
        "devices": n,
        "num_workers": W,
        "batch": BATCH,
        "rounds": rounds,
        "wire_dtype": wire_dtype,
        "warmup_s": round(warmup_s, 3),
        "wall_s": round(wall, 6),
        "items_per_s": round(items / wall, 3),
        "per_chip_items_per_s": round(items / wall / n, 3),
        "round_ms": round(1e3 * wall / rounds, 3),
        "sharded_server": bool(runtime._sharded_server),
        "d": int(cfg.grad_size),
        "final_loss": float(losses.mean()),
    }
    # collective inventory of the compiled round: the JitWatcher parsed
    # it at the warmup compile and emitted it into the arm's own stream
    # (instrument() swapped _round for its closure, so a fresh .lower()
    # is unavailable — the PR-8 bench_gpt2 lesson; the stream IS the
    # record)
    counts = {}
    table_reduce_bytes = None
    with open(tel.path) as f:
        for ln in f:
            e = json.loads(ln)
            if (e.get("event") == "collectives"
                    and e.get("name") == "round_step"):
                counts = e.get("counts") or {}
                table_reduce_bytes = e.get("table_reduce_bytes")
    result["collectives"] = counts
    result["table_reduce_bytes"] = table_reduce_bytes
    if mesh is not None:
        assert runtime._sharded_server, "sharded server lost eligibility"
        if wire_dtype == "int8":
            # the quantized wire REPLACES the reduce-scatter with the
            # int8 all_to_all pair — a reduce-scatter here means the
            # f32 reduce silently came back
            assert counts.get("all-to-all", 0) >= 2, (
                "the int8 arm compiled without the quantized all_to_all "
                f"reduce: {counts}")
            assert counts.get("reduce-scatter", 0) == 0, (
                "the int8 arm still compiled the f32 reduce-scatter — "
                f"the quantized wire is not engaged: {counts}")
        else:
            assert counts.get("reduce-scatter", 0) >= 1, (
                "the sharded sketch round compiled without its "
                f"reduce-scattered table aggregation: {counts}")
    tel.bench_event("scaling_arm", result)
    tel.write_summary(aborted=False, n_rounds=warmup + rounds)
    tel.close()
    assert validate_file(tel.path) == [], "arm stream schema-invalid"
    print("RESULT " + json.dumps(result), flush=True)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--arm", choices=("weak", "strong"), default=None,
                    help="internal: run one arm in THIS process")
    ap.add_argument("--n", type=int, default=1)
    ap.add_argument("--stream", default=None,
                    help="internal: arm telemetry directory")
    ap.add_argument("--out", default="runs/scaling_dryrun.jsonl")
    ap.add_argument("--devices", default=",".join(map(str,
                                                      DEFAULT_DEVICES)))
    ap.add_argument("--rounds", type=int, default=8)
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--wire_dtype", default="float32",
                    help="comma list of table wire dtypes to sweep "
                         "(float32,bfloat16,int8); non-f32 dtypes run "
                         "the WEAK arms only (the per-chip contract is "
                         "the weak curve; int8's own gate compares its "
                         "table-reduce wire bytes against the f32 arm)")
    ap.add_argument("--compile_cache",
                    default="~/.cache/commefficient_tpu_xla",
                    help="persistent XLA compile cache DIR threaded "
                         "into every subprocess arm (empty string "
                         "disables — each arm then pays the cold round "
                         "compile recorded as its warmup_s)")
    ap.add_argument("--workdir", default=None,
                    help="keep arm telemetry streams here; without it "
                         "the streams live in a temp dir that is "
                         "deleted after the gate runs (the JSONL is "
                         "the committed record)")
    ap.add_argument("--perchip_drop", type=float,
                    default=DRYRUN_PERCHIP_DROP)
    args = ap.parse_args()

    if args.arm is not None:
        _configure(args.n)
        run_arm(args.arm, args.n, args.stream or tempfile.mkdtemp(),
                args.rounds, args.warmup,
                wire_dtype=args.wire_dtype.split(",")[0],
                compile_cache=args.compile_cache)
        return 0

    # ------------------------------------------------------- launcher
    devices = [int(x) for x in args.devices.split(",") if x]
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    script = os.path.abspath(__file__)

    wire_dtypes = [w for w in args.wire_dtype.split(",") if w]
    workdir = args.workdir or tempfile.mkdtemp(prefix="scaling_")
    os.makedirs(workdir, exist_ok=True)
    lines = []
    streams = {}
    for wire in wire_dtypes:
        for scaling in ("weak", "strong"):
            if scaling == "strong" and wire != "float32":
                # non-f32 wires sweep the weak arms only: the per-chip
                # contract is the weak curve, and the int8 wire gate
                # below compares against the f32 weak arm directly
                continue
            for n in devices:
                if scaling == "strong" and STRONG_WORKERS % n:
                    print(f"skip strong n={n}: {STRONG_WORKERS} clients "
                          "not divisible")
                    continue
                sdir = os.path.join(workdir, f"{scaling}_{wire}_n{n}")
                os.makedirs(sdir, exist_ok=True)
                cmd = [sys.executable, script, "--arm", scaling,
                       "--n", str(n), "--stream", sdir,
                       "--rounds", str(args.rounds),
                       "--warmup", str(args.warmup),
                       "--wire_dtype", wire,
                       "--compile_cache", args.compile_cache]
                t0 = time.perf_counter()
                p = subprocess.run(cmd, env=env, cwd=repo,
                                   capture_output=True,
                                   text=True, timeout=1200)
                if p.returncode != 0:
                    print(p.stdout[-3000:])
                    print(p.stderr[-3000:])
                    print(f"{scaling} {wire} n={n} FAILED "
                          f"(rc={p.returncode})")
                    return 1
                rline = [ln for ln in p.stdout.splitlines()
                         if ln.startswith("RESULT ")]
                assert rline, p.stdout[-2000:]
                rec = json.loads(rline[0][len("RESULT "):])
                rec["kind"] = "arm"
                rec["dryrun"] = True
                rec["backend"] = "cpu-virtual"
                rec["arm_wall_s"] = round(time.perf_counter() - t0, 3)
                lines.append(rec)
                streams[(scaling, wire, n)] = os.path.join(
                    sdir, "telemetry.jsonl")
                print(f"{scaling:6s} {wire:8s} n={n}: "
                      f"{rec['items_per_s']:9.1f} img/s "
                      f"({rec['per_chip_items_per_s']:8.1f}/chip), "
                      f"round {rec['round_ms']:.1f} ms, "
                      f"warmup {rec['warmup_s']:.1f} s, "
                      f"collectives {rec['collectives']}")

    # ---- the weak-scaling per-chip gate: teleview diff between the
    # smallest MULTI-device weak arm (same compiled program family —
    # n=1 compiles no collectives, so its ledger diff would be
    # vacuously different) and the largest. Every other diff gate is
    # slackened wide: arms at different scales legitimately differ in
    # norms/MFU/bytes, and the per-chip contract is what this
    # comparison is FOR.
    multi = sorted(n for s, w, n in streams
                   if s == "weak" and w == "float32" and n > 1)
    rc = None
    if len(multi) >= 2:
        base_n, cand_n = multi[0], multi[-1]
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "teleview", os.path.join(repo, "scripts", "teleview.py"))
        tv = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(tv)
        rc = tv.main(["diff", streams[("weak", "float32", base_n)],
                      streams[("weak", "float32", cand_n)],
                      "--perchip_drop", str(args.perchip_drop),
                      "--mfu_drop", "0.95", "--signal_ratio", "1000",
                      "--loss_ratio", "1000", "--bytes_ratio", "1000",
                      "--temp_bytes_growth", "1000",
                      "--wire_bytes_growth", "1000",
                      "--count_slack", "0"])
        lines.append({"kind": "gate", "gate": "teleview_diff_perchip",
                      "scaling": "weak", "baseline_devices": base_n,
                      "candidate_devices": cand_n,
                      "perchip_drop": args.perchip_drop,
                      "rc": rc, "passed": rc == 0})
        print(f"weak-scaling per-chip gate (n={base_n} -> n={cand_n}, "
              f"drop <= {args.perchip_drop:.0%}): "
              f"{'PASS' if rc == 0 else 'FAIL'}")

    # ---- the int8 wire gate: at the largest shared weak-arm device
    # count, the int8 arm's ledger-measured table-reduce wire bytes
    # must sit at <= WIRE_BYTES_CEILING of the f32 arm's (scales
    # included) — the committed form of ISSUE-14's dryrun gate
    wire_rc = None
    if "int8" in wire_dtypes:
        shared = sorted(n for s, w, n in streams
                        if s == "weak" and w == "int8" and n > 1
                        and ("weak", "float32", n) in streams)
        if shared:
            n = shared[-1]
            by_arm = {}
            for w in ("float32", "int8"):
                rec = next(ln for ln in lines
                           if ln.get("kind") == "arm"
                           and ln.get("scaling") == "weak"
                           and ln.get("wire_dtype") == w
                           and ln.get("devices") == n)
                by_arm[w] = rec.get("table_reduce_bytes")
            ok = (by_arm["float32"] and by_arm["int8"]
                  and by_arm["int8"] <= WIRE_BYTES_CEILING
                  * by_arm["float32"])
            wire_rc = 0 if ok else 1
            lines.append({"kind": "gate", "gate": "wire_bytes_int8",
                          "devices": n,
                          "ceiling": WIRE_BYTES_CEILING,
                          "f32_table_reduce_bytes": by_arm["float32"],
                          "int8_table_reduce_bytes": by_arm["int8"],
                          "rc": wire_rc, "passed": ok})
            print(f"int8 wire gate (n={n}): table-reduce "
                  f"{by_arm['int8']} B vs f32 {by_arm['float32']} B "
                  f"(ceiling {WIRE_BYTES_CEILING:.2f}x): "
                  f"{'PASS' if ok else 'FAIL'}")

    with open(args.out, "w") as f:
        for rec in lines:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
            f.flush()
            os.fsync(f.fileno())
    if args.workdir is None:
        # the JSONL is the committed record; unrequested stream dirs
        # must not accumulate in /tmp across runs
        import shutil
        shutil.rmtree(workdir, ignore_errors=True)
        where = "(streams deleted; pass --workdir to keep them)"
    else:
        where = f"arm streams in {workdir}"
    print(f"wrote {args.out} ({len(lines)} lines); {where}")
    if wire_rc not in (0, None):
        return 1
    return 1 if rc not in (0, None) else 0


if __name__ == "__main__":
    sys.exit(main())
