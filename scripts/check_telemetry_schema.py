#!/usr/bin/env python
"""Lint committed telemetry streams against the schema.

Validates every ``telemetry.jsonl`` under the given roots (default:
``runs/``) with ``commefficient_tpu.telemetry.schema`` — the same code
the writers and the tier-1 tests run, so a committed artifact that
drifts from the documented schema fails CI instead of silently rotting.

Usage:
    python scripts/check_telemetry_schema.py [root ...]
    python scripts/check_telemetry_schema.py path/to/telemetry.jsonl
    python scripts/check_telemetry_schema.py --selftest

``--selftest`` generates a sample stream containing one event of EVERY
schema type (signals, collectives, span and utilization included) and
validates it — the cheap CI proof that the generator vocabulary and the
validator vocabulary have not drifted apart.

Exit status: 0 when every stream found is valid (or none exist),
1 when any stream has problems, 2 on usage errors.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from commefficient_tpu.telemetry.schema import (EVENT_FIELDS,  # noqa: E402
                                                SCHEMA_VERSION,
                                                TELEMETRY_BASENAME,
                                                validate_file,
                                                validate_lines)

# minimal valid value per predicate-shaped field, keyed by the exact
# field name where a generic fill would be wrong
_SAMPLE_OVERRIDES = {
    "schema": SCHEMA_VERSION,
    "devices": [{"id": 0, "kind": "cpu", "stats": None}],
    "ops": [{"kind": "all-reduce", "n_elements": 192, "dtype": "f32",
             "bytes": 768, "combined_in": 0}],
    "counts": {"all-reduce": 1},
    # schema-v9 quantized-wire fields (collectives/signals/bench): one
    # realistic int8 arm — the table-reduce wire at ~0.27x of f32
    "wire_dtype": "int8",
    "table_reduce_bytes": 1428.0,
    "client_download_bytes": [4.0],
    "client_upload_bytes": [4.0],
    # schema-v10 layer_signals: one realistic coarse attribution — a
    # norm-bias group holding gradient mass but winning none of k (the
    # starvation signature), hh_overlap null where no winner landed
    "signal_groups": "coarse",
    "groups": ["embed", "h0/attn", "h0/norm-bias", "head"],
    "sizes": [16704, 12288, 384, 650],
    "grad_mass": [3.1, 5.4, 0.9, 1.2],
    "update_mass": [1.0, 2.4, 0.0, 0.4],
    "topk_count": [2.0, 5.0, 0.0, 1.0],
    "error_mass": [0.4, 0.9, 2.8, 0.2],
    "hh_overlap": [1.0, 0.8, None, 1.0],
    "spans": [{"name": "data_fetch", "ts": 0.0, "dur_s": 0.01,
               "tid": 0, "depth": 0},
              {"name": "round_dispatch", "ts": 0.01, "dur_s": 0.02,
               "tid": 0, "depth": 1}],
    "flops_source": "cost_analysis",
    # schema-v6 roofline enrichment of the utilization event: one
    # realistic bandwidth-bound window (AI below the v5e ridge)
    "bytes_source": "cost_analysis",
    "bound": "bandwidth",
    "peak_hbm_gbps": 819.0,
    "bytes_per_round": 4.0e9,
    "arithmetic_intensity": 55.0,
    "ridge_intensity": 240.5,
    "achieved_gbps": 500.0,
    "bw_frac": 0.61,
    "expected_round_s": 0.0049,
    # schema-v7 mesh-topology fields of the utilization event (the
    # scaling-curve harness's per-chip normalization inputs)
    "n_devices": 8,
    "mesh_shape": [8],
    # schema-v6 residency enrichment of the memory event (a healthy
    # snapshot with headroom) — null on CPU streams, see memory_ledger
    "live_bytes": 9.0e9,
    "peak_bytes": 1.1e10,
    "delta_peak_bytes": 2.0e8,
    "fragmentation_bytes": 2.0e9,
    "limit_bytes": 1.6e10,
    "headroom_frac": 0.3125,
    # memory_ledger: one realistic executable inventory (temp carrying
    # a dense-gradient-sized buffer, the committed sketch-round shape)
    "temp_bytes": 2.9e9,
    "argument_bytes": 1.2e9,
    "output_bytes": 1.2e9,
    "alias_bytes": 1.1e9,
    "generated_code_bytes": 4.0e6,
    "total_bytes": 5.3e9,
    # client_stats: one realistic per-stat quantile record (ordered
    # quantiles, a null not-applicable stat) + participation fields
    "quantiles": {
        "loss": {"p5": 0.5, "p25": 0.8, "p50": 1.0, "p75": 1.3,
                 "p95": 1.9, "max": 2.0, "mean": 1.1,
                 "argmax_client": 3},
        "grad_norm_pre": {"p5": None, "p25": None, "p50": None,
                          "p75": None, "p95": None, "max": None,
                          "mean": None, "argmax_client": None},
    },
    "coverage": 0.5,
    "distinct_clients": 4,
    "counts_p50": 8.0,
    "counts_max": 16.0,
    "staleness_p50": 1.0,
    "staleness_max": 3.0,
    # async_round: one realistic schema-v4 commit (two merged cohorts,
    # one of them a commit stale, poly-discounted; device fields set as
    # a record-cadence event would carry them)
    "cohorts": [11, 12],
    "staleness_mean": 0.5,
    "staleness_max": 1.0,
    "discount_mean": 0.9,
    "discount_min": 0.8165,
    "buffer_n": 14.0,
    "partial": False,
    "update_norm": 0.25,
    "error_norm": 1.5,
    "velocity_norm": 0.75,
    # defense: one schema-v5 robustness record (a normclip run absorbing
    # a scale attack, one client benched)
    "defense": "normclip",
    "adversary": "scale",
    "nonfinite_action": "quarantine",
    "clip_frac": 0.25,
    "clip_thresh": 42.0,
    "clipped_mass": 1043.0,
    "trim_frac": None,
    "nonfinite_clients": 1.0,
    "quarantined": 1,
    "ejected": 0,
    "quarantine_ids_digest": "1:c1dfd96eea8c",
    "injected": {"scale": 1},
    # manifest: schema-v8 segment id (crash-recovery lineage)
    "stream_id": "cv_train-1234-18c2a9f0e01",
    # fault/resume: one realistic graceful-preemption record + the
    # resumed segment's lineage (schema v8, core/preempt.py)
    "kind": "preempt",
    "signal": "SIGTERM",
    "grace_s": 4.2,
    "detail": None,
    "checkpoint": "./checkpoint/ResNet9/ckpt_000002_r000005_preempt",
    "prior_stream": "cv_train-1200-18c2a9e77b3",
    "prior_events": 412,
    # population (schema v11): one realistic sketch-estimated summary —
    # half the registered fleet seen, the three heavy-hitter tables as
    # [id, count] pairs, the count-min (eps, delta) the counts carry
    # (telemetry/population.py; `estimated` also rides client_stats)
    "estimated": True,
    "registered": 16,
    "distinct": 8.0,
    "counts_p95": 14.0,
    "staleness_p95": 2.0,
    "obs_count_p50": 8.0,
    "obs_count_p95": 12.0,
    "gap_p50": 2.0,
    "gap_p95": 4.0,
    "top_sampled": [[3, 9], [7, 8]],
    "top_loss": [[3, 4]],
    "top_strikes": [],
    "memory_bytes": 3468800.0,
    "cm_epsilon": 4.15e-05,
    "cm_delta": 0.0183,
    "hh_k": 256,
    "sample_size": 4096,
    # alert: a fired statistical rule
    "rule": "loss_spike",
    "severity": "warn",
    "metric": "round.loss",
    "zscore": 8.5,
    "median": 1.0,
    "mad": 0.1,
    "window": 32,
    "action": "log",
}


def _sample_value(field, pred):
    if field in _SAMPLE_OVERRIDES:
        return _SAMPLE_OVERRIDES[field]
    name = pred.__name__
    return {"_int": 1, "_num": 1.0, "_opt_num": 1.0, "_str": "x",
            "_bool": False, "_dict": {}, "_opt_dict": None,
            "_list": [], "_opt_list": []}.get(name, None)


def sample_stream():
    """One well-formed JSONL line per schema event type, manifest first,
    summary last, contiguous seq — a synthetic but schema-complete run."""
    order = (["manifest"]
             + [k for k in EVENT_FIELDS if k not in ("manifest", "summary")]
             + ["summary"])
    lines = []
    for seq, kind in enumerate(order):
        ev = {"event": kind, "t": float(seq), "seq": seq}
        for field, pred in EVENT_FIELDS[kind].items():
            ev[field] = _sample_value(field, pred)
        lines.append(json.dumps(ev))
    return lines


def find_streams(roots):
    for root in roots:
        if os.path.isfile(root):
            yield root
            continue
        for dirpath, _, filenames in os.walk(root):
            for fn in filenames:
                if fn == TELEMETRY_BASENAME:
                    yield os.path.join(dirpath, fn)


def main(argv=None) -> int:
    args = list(argv if argv is not None else sys.argv[1:])
    selftest = "--selftest" in args
    if selftest:
        # the flag composes with roots in any order; run it first and
        # keep linting whatever paths remain
        args = [a for a in args if a != "--selftest"]
        problems = validate_lines(sample_stream())
        for lineno, problem in problems:
            print(f"selftest line {lineno}: {problem}")
        print(f"selftest: {len(EVENT_FIELDS)} event types "
              f"{'INVALID' if problems else 'ok'}")
        if problems:
            return 1
        if not args:
            return 0
    roots = args or ["runs"]
    for root in roots:
        if not os.path.exists(root):
            print(f"check_telemetry_schema: {root} does not exist",
                  file=sys.stderr)
            return 2
    n_checked = n_bad = 0
    for path in sorted(find_streams(roots)):
        n_checked += 1
        problems = validate_file(path)
        if problems:
            n_bad += 1
            print(f"INVALID {path}:")
            for lineno, problem in problems[:20]:
                print(f"  line {lineno}: {problem}")
            if len(problems) > 20:
                print(f"  ... and {len(problems) - 20} more")
        else:
            print(f"ok      {path}")
    print(f"{n_checked} stream(s) checked, {n_bad} invalid")
    return 1 if n_bad else 0


if __name__ == "__main__":
    sys.exit(main())
