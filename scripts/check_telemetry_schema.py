#!/usr/bin/env python
"""Lint committed telemetry streams against the schema.

Validates every ``telemetry.jsonl`` under the given roots (default:
``runs/``) with ``commefficient_tpu.telemetry.schema`` — the same code
the writers and the tier-1 tests run, so a committed artifact that
drifts from the documented schema fails CI instead of silently rotting.

Usage:
    python scripts/check_telemetry_schema.py [root ...]
    python scripts/check_telemetry_schema.py path/to/telemetry.jsonl

Exit status: 0 when every stream found is valid (or none exist),
1 when any stream has problems, 2 on usage errors.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from commefficient_tpu.telemetry.schema import (TELEMETRY_BASENAME,  # noqa: E402
                                                validate_file)


def find_streams(roots):
    for root in roots:
        if os.path.isfile(root):
            yield root
            continue
        for dirpath, _, filenames in os.walk(root):
            for fn in filenames:
                if fn == TELEMETRY_BASENAME:
                    yield os.path.join(dirpath, fn)


def main(argv=None) -> int:
    roots = (argv if argv is not None else sys.argv[1:]) or ["runs"]
    for root in roots:
        if not os.path.exists(root):
            print(f"check_telemetry_schema: {root} does not exist",
                  file=sys.stderr)
            return 2
    n_checked = n_bad = 0
    for path in sorted(find_streams(roots)):
        n_checked += 1
        problems = validate_file(path)
        if problems:
            n_bad += 1
            print(f"INVALID {path}:")
            for lineno, problem in problems[:20]:
                print(f"  line {lineno}: {problem}")
            if len(problems) > 20:
                print(f"  ... and {len(problems) - 20} more")
        else:
            print(f"ok      {path}")
    print(f"{n_checked} stream(s) checked, {n_bad} invalid")
    return 1 if n_bad else 0


if __name__ == "__main__":
    sys.exit(main())
