import os, sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp, numpy as np, time
from commefficient_tpu.models.gpt2 import (dense_causal_attention,
                                           flash_causal_attention)
rng = np.random.RandomState(0)
for shape in [(2, 256, 12, 64), (2, 2, 256, 12, 64)]:
    q, k, v = (jnp.asarray(rng.randn(*shape), jnp.bfloat16) for _ in range(3))
    d = jax.jit(dense_causal_attention)(q, k, v)
    f = jax.jit(flash_causal_attention)(q, k, v)
    err = float(jnp.max(jnp.abs(d.astype(jnp.float32) - f.astype(jnp.float32))))
    print(shape, "fwd max err", err)
    # grad parity through a scalar loss
    def loss(fn, q, k, v):
        return (fn(q, k, v).astype(jnp.float32) ** 2).mean()
    gd = jax.jit(jax.grad(lambda q: loss(dense_causal_attention, q, k, v)))(q)
    gf = jax.jit(jax.grad(lambda q: loss(flash_causal_attention, q, k, v)))(q)
    gerr = float(jnp.max(jnp.abs(gd.astype(jnp.float32) - gf.astype(jnp.float32))))
    gscale = float(jnp.max(jnp.abs(gd.astype(jnp.float32))))
    print(shape, "grad max err", gerr, "grad scale", gscale)
print("FLASH PARITY OK")
