#!/usr/bin/env bash
# Round-5 second TPU work queue: local_topk operating-regime arms, the
# hard-v2 accuracy-vs-compression curve, the ImageNet round profile
# (two shapes), and the CIFAR round-shape grid — chained so the chip
# never idles between studies.
set -uo pipefail
cd "$(dirname "$0")/.."

bash scripts/local_topk_arms.sh lr01 efnone lr003 \
    2>&1 | tee runs/local_topk_arms.out
bash scripts/hardv2_curve.sh c1m c2m c4m c8m c2m_sub \
    2>&1 | tee runs/hardv2_curve.out
python scripts/profile_imagenet_round.py runs/profile_imagenet \
    2>&1 | tee runs/profile_imagenet_b64.out
python scripts/profile_imagenet_round.py runs/profile_imagenet_b256 \
    --batch 256 2>&1 | tee runs/profile_imagenet_b256.out
python scripts/round_shape_grid.py 2>&1 | tee runs/round_shape_grid.out
echo QUEUE2_DONE
