set -euo pipefail
cd /root/repo
# Preemption-safe resume demo: kill a run at round k with a real
# SIGTERM (delivered deterministically via the fault-injection hook at
# a chosen round), let the graceful drain write a round-granular
# `preempt`-tagged checkpoint, resume, and verify the stitched
# trajectory is BIT-identical to an uninterrupted run of the same
# config. Epoch-granular resume (the pre-PR-13 path) falls out as the
# tag-less case; the full hard-kill matrix (os._exit at
# mid-checkpoint-write / mid-telemetry-flush / inside the async pool)
# is scripts/crash_matrix.py.
OUT=runs/gpt2_conv
CK=/tmp/resume_ck
LOGS=/tmp/resume_logs
rm -rf "$CK" "$LOGS"
mkdir -p "$OUT"
COMMON=(--mode sketch --error_type virtual --num_cols 524288 --num_rows 5
        --k 50000 --approx_topk --num_workers 8 --local_batch_size 8
        --microbatch_size 8 --max_seq_len 64 --valid_batch_size 64
        --weight_decay 0 --local_momentum 0 --virtual_momentum 0.9
        --dataset_dir "$OUT/data" --seed 21 --num_epochs 12
        --checkpoint_path "$CK" --checkpoint_every 3 --telemetry_every 1)
# 1) uninterrupted 12-epoch run — the bitwise reference trajectory
python gpt2_train.py "${COMMON[@]}" --logdir "$LOGS/straight" \
    2>&1 | tee "$OUT/resume_full12.log"
# 2) the same run preempted at global round 20: the injected SIGTERM
#    triggers the graceful drain (finish the in-flight round, flush,
#    write ckpt_*_r*_preempt with round-granular meta + ledger sidecar,
#    emit the `fault` event, exit 0)
rm -rf "$CK"
COMMEFFICIENT_FAULT=sigterm:pre_round:20 \
python gpt2_train.py "${COMMON[@]}" --logdir "$LOGS/killed" \
    2>&1 | tee "$OUT/resume_killed.log"
ls -l "$CK/gpt2_doubleheads/" | tee -a "$OUT/resume_killed.log"
# 3) resume: rebuilds the (seed, epoch) sampler, skips the 20 trained
#    rounds, continues — and APPENDS to the killed run's telemetry
#    stream behind a `resume` lineage record (same --logdir)
python gpt2_train.py "${COMMON[@]}" --resume --logdir "$LOGS/killed" \
    2>&1 | tee "$OUT/resume_from_kill.log"
# 4) bitwise gate: every round record in the stitched killed+resumed
#    stream must carry EXACTLY the loss the uninterrupted run recorded
python - "$LOGS/straight" "$LOGS/killed" <<'PYEOF'
import json, sys

def rounds(d):
    out = {}
    for line in open(d + "/telemetry.jsonl"):
        try:
            e = json.loads(line)
        except ValueError:
            continue
        if e.get("event") == "round":
            prev = out.get(e["round"])
            assert prev is None or prev == e["loss"], \
                f"replayed round {e['round']} diverged: {prev} vs {e['loss']}"
            out[e["round"]] = e["loss"]
    return out

a, b = rounds(sys.argv[1]), rounds(sys.argv[2])
assert a == b, ("killed+resumed trajectory != uninterrupted run: "
                f"{sorted(set(a) ^ set(b))[:5]} ...")
print(f"BITWISE OK: {len(a)} rounds identical across the kill+resume")
PYEOF
python scripts/teleview.py summarize "$LOGS/killed" \
    | tee "$OUT/resume_lineage.log"
echo RESUME DEMO DONE
