set -euo pipefail
cd /root/repo
OUT=runs/gpt2_conv
CK=/tmp/resume_ck
rm -rf "$CK"
COMMON=(--mode sketch --error_type virtual --num_cols 524288 --num_rows 5
        --k 50000 --approx_topk --num_workers 8 --local_batch_size 8
        --microbatch_size 8 --max_seq_len 64 --valid_batch_size 64
        --weight_decay 0 --local_momentum 0 --virtual_momentum 0.9
        --dataset_dir "$OUT/data" --seed 21 --num_epochs 12
        --checkpoint_path "$CK")
# uninterrupted 12-epoch run (checkpoints every 3 so the interrupted
# variant can resume from epoch 6)
python gpt2_train.py "${COMMON[@]}" --checkpoint_every 3 \
    2>&1 | tee "$OUT/resume_full12.log"
# wipe later checkpoints so the resume starts at epoch 6, then resume
python - "$CK" <<'PYEOF'
import glob, os, sys
for fn in glob.glob(os.path.join(sys.argv[1], "gpt2_doubleheads", "*")):
    base = os.path.basename(fn)
    if any(f"_{ep:06d}" in base or f"{ep}" == base.split("_")[-1].split(".")[0]
           for ep in (9, 12)):
        os.remove(fn)
        print("removed", base)
PYEOF
python gpt2_train.py "${COMMON[@]}" --resume \
    2>&1 | tee "$OUT/resume_from6.log"
echo RESUME DEMO DONE
