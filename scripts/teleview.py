#!/usr/bin/env python
"""teleview: offline analyzer for ``telemetry.jsonl`` streams.

BENCH/MULTICHIP comparisons have been manual JSON spelunking — ``jq``
one-liners against artifacts whose schema only the writers knew. This
CLI reads one stream (``summarize``) or two (``diff``) and turns them
into the three tables that actually answer "did this run regress":

    python scripts/teleview.py summarize runs/x/telemetry.jsonl
    python scripts/teleview.py diff old/telemetry.jsonl new/telemetry.jsonl

``summarize`` prints the manifest header, compile/collective inventory
(per watched executable: launch counts by kind, payload bytes), a
sampled round table, per-signal trends (first/last/min/max of every
signals.py key) and the epoch table.

``diff`` compares two runs and EXITS NONZERO on regression:
- any collective launch-count increase for a watched executable (the
  round-5 32x all_to_all unroll class — count growth is never benign),
  or payload-byte growth beyond ``--bytes_ratio``;
- a final signal norm (error/velocity/update/grad) growing beyond
  ``--signal_ratio``x (sketch-EF divergence shows here rounds before
  the loss goes non-finite), or topk_overlap dropping by more than
  ``--overlap_drop``;
- the final round/epoch loss growing beyond ``--loss_ratio``x.

Dependency-free (json + argparse), validates nothing itself — run
``scripts/check_telemetry_schema.py`` for schema enforcement.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

try:
    # single source of truth when the package is importable...
    from commefficient_tpu.telemetry.schema import TELEMETRY_BASENAME
    from commefficient_tpu.telemetry.signals import SIGNAL_KEYS
except ImportError:
    # ...but the analyzer must work on a machine WITHOUT jax (analyzing
    # a downloaded artifact is the whole point of an offline tool, and
    # the telemetry package import pulls jax in transitively). These
    # literals mirror the canonical values; tests/test_signals.py pins
    # them against the package.
    TELEMETRY_BASENAME = "telemetry.jsonl"
    SIGNAL_KEYS = (
        "grad_norm", "grad_true_norm", "grad_l2estimate",
        "velocity_norm", "error_norm", "error_l2estimate",
        "update_norm", "support_density", "topk_overlap",
    )

NORM_KEYS = ("grad_norm", "grad_true_norm", "grad_l2estimate",
             "velocity_norm", "error_norm", "error_l2estimate",
             "update_norm")


def load_events(path: str) -> List[Dict[str, Any]]:
    if os.path.isdir(path):
        path = os.path.join(path, TELEMETRY_BASENAME)
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue  # check_telemetry_schema flags these; keep reading
            if isinstance(obj, dict):
                events.append(obj)
    return events


def by_kind(events, kind):
    return [e for e in events if e.get("event") == kind]


def latest_collectives(events) -> Dict[str, Dict[str, Any]]:
    """name -> the LAST collectives event per watched executable (a
    recompile re-emits; the last one is the executable that ran)."""
    out: Dict[str, Dict[str, Any]] = {}
    for e in by_kind(events, "collectives"):
        out[str(e.get("name"))] = e
    return out


def _fin(v) -> Optional[float]:
    return float(v) if isinstance(v, (int, float)) else None


# ------------------------------------------------------------------ summarize


def summarize(events: List[Dict[str, Any]], label: str = "") -> None:
    man = next(iter(by_kind(events, "manifest")), {})
    cfgd = man.get("config") or {}
    print(f"== {label or 'run'}: {man.get('run_type', '?')} on "
          f"{man.get('device_count', '?')}x {man.get('device_kind', '?')} "
          f"({man.get('backend', '?')}, jax {man.get('jax_version', '?')})")
    sk = man.get("sketch")
    print(f"   mode={cfgd.get('mode', '?')} grad_size={man.get('grad_size')}"
          + (f" sketch={sk['impl']} {sk['num_rows']}x{sk['num_cols']} "
             f"k={sk['k']} ef={sk['ef']}" if sk else ""))

    comps = by_kind(events, "compile")
    if comps:
        print("-- compiles")
        for e in comps:
            print(f"   {e['name']}: #{e['n_compiles']} "
                  f"lower {e['lower_s']:.2f}s compile {e['compile_s']:.2f}s"
                  + (f" flops {e['flops']:.3g}" if e.get("flops") else "")
                  + (" FALLBACK" if e.get("fallback") else ""))

    colls = latest_collectives(events)
    if colls:
        print("-- collectives (per compiled executable)")
        for name, e in sorted(colls.items()):
            counts = e.get("counts") or {}
            inv = " ".join(f"{k}x{v}" for k, v in sorted(counts.items()))
            print(f"   {name}: {e.get('n_collectives', 0)} launches"
                  f" [{inv or 'none'}] payload "
                  f"{(e.get('total_bytes') or 0) / 1024:.1f} KiB")

    rounds = by_kind(events, "round")
    if rounds:
        losses = [_fin(e.get("loss")) for e in rounds]
        fin = [l for l in losses if l is not None]
        print(f"-- rounds: {len(rounds)} records, loss "
              f"first {fin[0]:.4f} last {fin[-1]:.4f} min {min(fin):.4f}"
              if fin else f"-- rounds: {len(rounds)} records (no finite loss)")
        step = max(1, len(rounds) // 8)
        for e in rounds[::step]:
            print(f"   r{e['round']:>6} ep{e['epoch']:>3} "
                  f"lr {e['lr']:.4f} loss "
                  + (f"{e['loss']:.4f}" if _fin(e.get("loss")) is not None
                     else "NaN")
                  + f" host {e['host_s']*1e3:.0f}ms dev "
                    f"{e['device_s']*1e3:.0f}ms")

    sigs = by_kind(events, "signals")
    if sigs:
        print(f"-- signals: {len(sigs)} records")
        for key in SIGNAL_KEYS:
            vals = [_fin(e.get(key)) for e in sigs]
            vals = [v for v in vals if v is not None]
            if not vals:
                continue
            print(f"   {key:18s} first {vals[0]:11.5g} last {vals[-1]:11.5g}"
                  f" min {min(vals):11.5g} max {max(vals):11.5g}")

    epochs = by_kind(events, "epoch")
    if epochs:
        print("-- epochs")

        def fmt(v, spec=".4f"):
            # loss/acc fields are nullable (non-finite serializes as null)
            return format(v, spec) if _fin(v) is not None else "NaN"

        for e in epochs:
            print(f"   ep{e['epoch']:>3} train {fmt(e['train_loss'])}/"
                  f"{fmt(e['train_acc'])} test {fmt(e['test_loss'])}/"
                  f"{fmt(e['test_acc'])} up {fmt(e['upload_mib'], '.0f')}"
                  " MiB")

    summ = next(iter(by_kind(events, "summary")), None)
    if summ is None:
        print("-- NO summary footer: the run DIED before finishing")
    else:
        print(f"-- summary: {'ABORTED' if summ['aborted'] else 'ok'}, "
              f"{summ['n_rounds']} rounds, {summ['wall_time_s']:.1f}s wall")
    for e in by_kind(events, "nan_abort"):
        print(f"   nan_abort at round {e['nan_round']}: {e['reason']}")


# ----------------------------------------------------------------------- diff


def diff(a: List[Dict[str, Any]], b: List[Dict[str, Any]],
         args) -> List[str]:
    """Regressions of run B against baseline A (empty list = clean)."""
    problems: List[str] = []

    ca, cb = latest_collectives(a), latest_collectives(b)
    for name in sorted(set(ca) & set(cb)):
        counts_a = ca[name].get("counts") or {}
        counts_b = cb[name].get("counts") or {}
        for kind in sorted(set(counts_a) | set(counts_b)):
            na, nb = counts_a.get(kind, 0), counts_b.get(kind, 0)
            if nb > na + args.count_slack:
                problems.append(
                    f"collectives[{name}]: {kind} launch count {na} -> {nb}"
                    " (count growth is the 32x-unroll regression class)")
        ba = ca[name].get("total_bytes") or 0
        bb = cb[name].get("total_bytes") or 0
        if ba > 0 and bb > ba * args.bytes_ratio:
            problems.append(
                f"collectives[{name}]: payload bytes {ba} -> {bb} "
                f"(> {args.bytes_ratio:.2f}x)")

    sa, sb = by_kind(a, "signals"), by_kind(b, "signals")
    if sa and sb:
        for key in NORM_KEYS:
            va, vb = _fin(sa[-1].get(key)), _fin(sb[-1].get(key))
            if va is not None and vb is not None and va > 0 \
                    and vb > va * args.signal_ratio:
                problems.append(
                    f"signals: final {key} {va:.5g} -> {vb:.5g} "
                    f"(> {args.signal_ratio:.2f}x — EF-divergence class)")
        oa, ob = (_fin(sa[-1].get("topk_overlap")),
                  _fin(sb[-1].get("topk_overlap")))
        if oa is not None and ob is not None \
                and ob < oa - args.overlap_drop:
            problems.append(
                f"signals: topk_overlap {oa:.3f} -> {ob:.3f} "
                f"(drop > {args.overlap_drop:.2f} — recovery degraded)")

    def final_loss(events):
        eps = by_kind(events, "epoch")
        if eps:
            return _fin(eps[-1].get("test_loss"))
        rnds = [_fin(e.get("loss")) for e in by_kind(events, "round")]
        rnds = [v for v in rnds if v is not None]
        return rnds[-1] if rnds else None

    la, lb = final_loss(a), final_loss(b)
    if la is not None:
        if lb is None:
            problems.append("loss: baseline finite, new run has no finite "
                            "loss (diverged?)")
        elif la > 0 and lb > la * args.loss_ratio:
            problems.append(f"loss: final {la:.4f} -> {lb:.4f} "
                            f"(> {args.loss_ratio:.2f}x)")
    for e in by_kind(b, "nan_abort"):
        if not by_kind(a, "nan_abort"):
            problems.append(f"new run aborted non-finite at round "
                            f"{e['nan_round']} (baseline did not)")
    return problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="teleview")
    sub = ap.add_subparsers(dest="cmd")
    s = sub.add_parser("summarize", help="one-stream report")
    s.add_argument("path")
    d = sub.add_parser("diff", help="regression check: B against baseline A")
    d.add_argument("baseline")
    d.add_argument("candidate")
    d.add_argument("--count_slack", type=int, default=0,
                   help="collective launch-count growth tolerated (default "
                        "0: any increase fails)")
    d.add_argument("--bytes_ratio", type=float, default=1.05,
                   help="max collective payload-byte growth factor")
    d.add_argument("--signal_ratio", type=float, default=2.0,
                   help="max final signal-norm growth factor")
    d.add_argument("--overlap_drop", type=float, default=0.2,
                   help="max topk_overlap absolute drop")
    d.add_argument("--loss_ratio", type=float, default=1.05,
                   help="max final loss growth factor")
    args = ap.parse_args(argv)
    if args.cmd == "summarize":
        summarize(load_events(args.path), label=args.path)
        return 0
    if args.cmd == "diff":
        a, b = load_events(args.baseline), load_events(args.candidate)
        summarize(a, label=f"A (baseline) {args.baseline}")
        summarize(b, label=f"B (candidate) {args.candidate}")
        problems = diff(a, b, args)
        if problems:
            print("== REGRESSIONS")
            for p in problems:
                print(f"   {p}")
            return 1
        print("== no regressions beyond thresholds")
        return 0
    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
