#!/usr/bin/env python
"""teleview: offline analyzer for ``telemetry.jsonl`` streams.

BENCH/MULTICHIP comparisons have been manual JSON spelunking — ``jq``
one-liners against artifacts whose schema only the writers knew. This
CLI reads one stream (``summarize``/``alerts``/``clients``/
``layers``), two (``diff``), or renders one into a timeline
(``timeline``):

    python scripts/teleview.py summarize runs/x/telemetry.jsonl
    python scripts/teleview.py alerts runs/x/telemetry.jsonl
    python scripts/teleview.py clients runs/x/telemetry.jsonl
    python scripts/teleview.py population runs/x/telemetry.jsonl
    python scripts/teleview.py layers runs/x/telemetry.jsonl
    python scripts/teleview.py memory runs/x/telemetry.jsonl
    python scripts/teleview.py trend .
    python scripts/teleview.py diff old/telemetry.jsonl new/telemetry.jsonl
    python scripts/teleview.py timeline runs/x/telemetry.jsonl -o trace.json

``population`` (schema v11) renders the population-scale participation
stream (``population`` events, telemetry/population.py): the coverage/
distinct trajectory, sample-count and staleness quantiles, the three
heavy-hitter tables, the ledger's memory footprint and — on
sketch-estimated streams — the count-min (eps, delta) bounds the
estimates carry. ``trend`` tabulates the repo's ``BENCH_r*.json``
benchmark checkpoints (img/s, MFU, the saturated and gpt2 arms, wire
bytes, warmup seconds), tolerating every vintage's missing fields.

``layers`` (schema v10) renders the layer-wise compression attribution
stream (``layer_signals`` events, telemetry/layer_signals.py): the
per-group table — coordinate/gradient/update/EF mass shares, top-k win
share, heavy-hitter overlap, STARVED verdicts at the monitor rule's
thresholds — and the per-group win-share trend.

``memory`` (schema v6) renders the per-executable byte inventory
(``memory_ledger`` events), the residency timeline (enriched ``memory``
events: live/peak/fragmentation/headroom per phase) and the roofline
table (arithmetic intensity, ridge, compute-vs-bandwidth bound verdict
per ``utilization`` window); ``timeline`` adds hbm_live/peak_gib
counter tracks from the same snapshots.

``summarize`` prints the manifest header, compile/collective inventory
(per watched executable: launch counts by kind, payload bytes), a
sampled round table, per-signal trends (first/last/min/max of every
signals.py key), the MFU/starvation line from the ``utilization``
events, alert/abort lines, and the epoch table.

``alerts`` lists every ``alert`` event (rule, severity, metric, value,
robust z) plus the nan_abort, and exits 1 when any critical alert (or
abort) is present — the postmortem triage entry point for a stream a
crashed run left behind. ``clients`` renders the ``client_stats``
population trends: per-stat p50/p95/max first->last, participation
coverage/staleness, and the clients that most often owned the round
maximum. Both run jax-free, and both tolerate the truncated trailing
line a crashed writer leaves (see ``load_events``).

``timeline`` renders the ``span`` event stream (telemetry/tracing.py)
into a perfetto / chrome-tracing ``trace.json`` — complete ("X") slice
events per span, plus counter ("C") tracks for MFU, input-wait
fraction, round loss, and (schema v9) the per-executable table-reduce
wire: modeled ICI bytes (``table_reduce_bytes:<name>``) and the wire
dtype's bytes/cell (``wire_dtype_bytes:<name>``) — a quantized wire
silently re-widening shows as a step in the timeline. Open it at
https://ui.perfetto.dev or chrome://tracing.

``diff`` compares two runs and EXITS NONZERO on regression:
- any collective launch-count increase for a watched executable (the
  round-5 32x all_to_all unroll class — count growth is never benign),
  or payload-byte growth beyond ``--bytes_ratio``;
- a final signal norm (error/velocity/update/grad) growing beyond
  ``--signal_ratio``x (sketch-EF divergence shows here rounds before
  the loss goes non-finite), or topk_overlap dropping by more than
  ``--overlap_drop``;
- the final round/epoch loss growing beyond ``--loss_ratio``x;
- MFU dropping more than ``--mfu_drop`` (relative) or the input-wait
  starvation fraction rising more than ``--input_wait_rise`` (absolute),
  from the last ``utilization`` event of each run — the round-pipeline
  regression gate, exercised with its default threshold by
  ``__graft_entry__.dryrun_multichip``;
- on schema-v10 streams, the LAYER starvation gap (max over groups
  above the grad-mass floor of mass share minus top-k win share, final
  ``layer_signals`` event) rising more than ``--starvation_rise``
  (absolute) — a parameter group losing the top-k race it used to win
  (pre-v10 that spelling aliased ``--input_wait_rise``);
- on async buffered-aggregation streams (schema v4), the final
  ``async_round`` staleness_mean rising more than ``--staleness_rise``
  (absolute, commits-stale units), or its post-commit error_norm
  growing beyond ``--signal_ratio``x (staleness-induced EF divergence);
- on schema-v6 streams, a watched executable's ``memory_ledger`` temp
  bytes growing beyond ``--temp_bytes_growth``x (the de-fusion /
  re-materialization regression class), or the final ``utilization``
  ``bw_frac`` dropping more than ``--bw_frac_drop`` (absolute);
- on schema-v11 streams, the final ``population`` coverage dropping
  more than ``--coverage_stall`` (absolute), or the candidate stream
  ending in a distinct-coverage stall (no new distinct participants for
  COVERAGE_STALL_WINDOW records below saturation) the baseline does not
  show — the sampler-reach regression class;
- PER-CHIP throughput (the weak-scaling contract,
  scripts/scaling_curves.py): the last ``bench`` event carrying
  ``result.per_chip_items_per_s`` dropping more than ``--perchip_drop``
  (relative) against the baseline stream — on a weak-scaling sweep the
  baseline is the smallest mesh's arm, so a sharding regression that
  taxes every added chip fails the diff.

Dependency-free (json + argparse), validates nothing itself — run
``scripts/check_telemetry_schema.py`` for schema enforcement.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

try:
    # single source of truth when the package is importable...
    from commefficient_tpu.telemetry.clients import CLIENT_STAT_KEYS
    from commefficient_tpu.telemetry.health import COVERAGE_STALL_WINDOW
    from commefficient_tpu.telemetry.layer_signals import (
        LAYER_SIGNAL_KEYS, STARVATION_MASS_SHARE, STARVATION_WIN_SHARE,
        starved_groups)
    from commefficient_tpu.telemetry.memory_ledger import (
        MEMORY_KEYS, MEMORY_LEDGER_KEYS)
    from commefficient_tpu.telemetry.population import POPULATION_KEYS
    from commefficient_tpu.telemetry.schema import TELEMETRY_BASENAME
    from commefficient_tpu.telemetry.signals import SIGNAL_KEYS
    from commefficient_tpu.telemetry.utilization import ROOFLINE_KEYS
except ImportError:
    # ...but the analyzer must work on a machine WITHOUT jax (analyzing
    # a downloaded artifact is the whole point of an offline tool, and
    # the telemetry package import pulls jax in transitively). These
    # literals mirror the canonical values; tests/test_signals.py,
    # tests/test_clients.py and tests/test_memory.py pin them against
    # the package.
    TELEMETRY_BASENAME = "telemetry.jsonl"
    SIGNAL_KEYS = (
        "grad_norm", "grad_true_norm", "grad_l2estimate",
        "velocity_norm", "error_norm", "error_l2estimate",
        "update_norm", "support_density", "topk_overlap",
    )
    CLIENT_STAT_KEYS = (
        "loss", "grad_norm_pre", "grad_norm_post", "clip_frac",
        "tx_norm", "upload_bytes", "download_bytes",
    )
    MEMORY_KEYS = (
        "live_bytes", "peak_bytes", "delta_peak_bytes",
        "fragmentation_bytes", "limit_bytes", "headroom_frac",
    )
    MEMORY_LEDGER_KEYS = (
        "temp_bytes", "argument_bytes", "output_bytes", "alias_bytes",
        "generated_code_bytes", "total_bytes",
    )
    ROOFLINE_KEYS = (
        "peak_hbm_gbps", "bytes_per_round", "bytes_source",
        "arithmetic_intensity", "ridge_intensity", "bound",
        "achieved_gbps", "bw_frac", "expected_round_s",
    )
    LAYER_SIGNAL_KEYS = (
        "grad_mass", "update_mass", "topk_count", "error_mass",
        "hh_overlap",
    )
    STARVATION_MASS_SHARE = 0.05
    STARVATION_WIN_SHARE = 0.02
    # population event fields (schema v11, telemetry/population.py) and
    # the coverage-stall window the monitor rule fires on — literal
    # twins pinned against the package by tests/test_population.py
    POPULATION_KEYS = (
        "round", "estimated", "registered", "distinct", "coverage",
        "counts_p50", "counts_p95", "counts_max",
        "staleness_p50", "staleness_p95", "staleness_max",
        "obs_count_p50", "obs_count_p95", "gap_p50", "gap_p95",
        "top_sampled", "top_loss", "top_strikes",
        "memory_bytes", "cm_epsilon", "cm_delta", "hh_k", "sample_size",
    )
    COVERAGE_STALL_WINDOW = 5

    def starved_groups(groups, grad_mass, topk_count,
                       mass_share=STARVATION_MASS_SHARE,
                       win_share=STARVATION_WIN_SHARE):
        # literal twin of layer_signals.starved_groups (pinned against
        # the package by tests/test_layer_signals.py): groups holding
        # > mass_share of the gradient energy but winning < win_share
        # of the top-k coordinates. Empty when grad_mass is null.
        if not grad_mass or not topk_count:
            return []
        gm = [v if isinstance(v, (int, float)) else 0.0
              for v in grad_mass]
        tc = [v if isinstance(v, (int, float)) else 0.0
              for v in topk_count]
        tm, tk = sum(gm), sum(tc)
        if tm <= 0 or tk <= 0:
            return []
        return [(str(g), gm[i] / tm, tc[i] / tk)
                for i, g in enumerate(groups)
                if gm[i] / tm > mass_share and tc[i] / tk < win_share]

NORM_KEYS = ("grad_norm", "grad_true_norm", "grad_l2estimate",
             "velocity_norm", "error_norm", "error_l2estimate",
             "update_norm")

# async_round fields the analyzer reads (schema v4, core/async_agg.py).
# Literal on purpose — this tool must run jax-free; tests/test_async_agg
# pins these names against telemetry/schema.EVENT_FIELDS["async_round"].
ASYNC_ROUND_KEYS = ("staleness_mean", "staleness_max", "discount_mean",
                    "discount_min", "error_norm", "loss", "n_cohorts",
                    "partial")

# defense fields the analyzer reads (schema v5, core/runtime.py +
# core/quarantine.py). Same jax-free literal pattern; tests/
# test_defense.py pins these names against
# telemetry/schema.EVENT_FIELDS["defense"].
DEFENSE_KEYS = ("clip_frac", "clip_thresh", "clipped_mass", "trim_frac",
                "nonfinite_clients", "quarantined", "ejected")


def load_events(path: str) -> List[Dict[str, Any]]:
    if os.path.isdir(path):
        path = os.path.join(path, TELEMETRY_BASENAME)
    events = []
    with open(path) as f:
        lines = [ln.strip() for ln in f]
    while lines and not lines[-1]:
        lines.pop()
    for i, line in enumerate(lines):
        if not line:
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            # a crashed run's stream legitimately ends mid-write: the
            # analyzer's whole job is reading exactly those streams, so
            # a truncated TRAILING line is a note, not an error (a bad
            # line mid-stream is still skipped — the schema linter
            # flags it; keep reading either way)
            if i == len(lines) - 1:
                print(f"note: {os.path.basename(path)} ends in a "
                      "truncated line (crashed run?) — ignored",
                      file=sys.stderr)
            continue
        if isinstance(obj, dict):
            events.append(obj)
    return events


def by_kind(events, kind):
    return [e for e in events if e.get("event") == kind]


def latest_collectives(events) -> Dict[str, Dict[str, Any]]:
    """name -> the LAST collectives event per watched executable (a
    recompile re-emits; the last one is the executable that ran)."""
    out: Dict[str, Dict[str, Any]] = {}
    for e in by_kind(events, "collectives"):
        out[str(e.get("name"))] = e
    return out


def latest_memory_ledgers(events) -> Dict[str, Dict[str, Any]]:
    """name -> the LAST memory_ledger event per watched executable
    (schema v6) — same recompile-overwrites semantics as collectives."""
    out: Dict[str, Dict[str, Any]] = {}
    for e in by_kind(events, "memory_ledger"):
        out[str(e.get("name"))] = e
    return out


def _gib(v) -> str:
    return f"{v / 2**30:.3f} GiB" if isinstance(v, (int, float)) else "-"


def _fin(v) -> Optional[float]:
    return float(v) if isinstance(v, (int, float)) else None


# ------------------------------------------------------------------ summarize


def summarize(events: List[Dict[str, Any]], label: str = "") -> None:
    man = next(iter(by_kind(events, "manifest")), {})
    cfgd = man.get("config") or {}
    print(f"== {label or 'run'}: {man.get('run_type', '?')} on "
          f"{man.get('device_count', '?')}x {man.get('device_kind', '?')} "
          f"({man.get('backend', '?')}, jax {man.get('jax_version', '?')})")
    sk = man.get("sketch")
    print(f"   mode={cfgd.get('mode', '?')} grad_size={man.get('grad_size')}"
          + (f" sketch={sk['impl']} {sk['num_rows']}x{sk['num_cols']} "
             f"k={sk['k']} ef={sk['ef']}"
             + (f" wire={sk['wire_dtype']}"
                if sk.get("wire_dtype") not in (None, "float32") else "")
             if sk else ""))

    comps = by_kind(events, "compile")
    if comps:
        print("-- compiles")
        for e in comps:
            print(f"   {e['name']}: #{e['n_compiles']} "
                  f"lower {e['lower_s']:.2f}s compile {e['compile_s']:.2f}s"
                  + (f" flops {e['flops']:.3g}" if e.get("flops") else "")
                  + (" FALLBACK" if e.get("fallback") else ""))

    colls = latest_collectives(events)
    if colls:
        print("-- collectives (per compiled executable)")
        for name, e in sorted(colls.items()):
            counts = e.get("counts") or {}
            inv = " ".join(f"{k}x{v}" for k, v in sorted(counts.items()))
            print(f"   {name}: {e.get('n_collectives', 0)} launches"
                  f" [{inv or 'none'}] payload "
                  f"{(e.get('total_bytes') or 0) / 1024:.1f} KiB")

    rounds = by_kind(events, "round")
    if rounds:
        losses = [_fin(e.get("loss")) for e in rounds]
        fin = [l for l in losses if l is not None]
        print(f"-- rounds: {len(rounds)} records, loss "
              f"first {fin[0]:.4f} last {fin[-1]:.4f} min {min(fin):.4f}"
              if fin else f"-- rounds: {len(rounds)} records (no finite loss)")
        step = max(1, len(rounds) // 8)
        for e in rounds[::step]:
            print(f"   r{e['round']:>6} ep{e['epoch']:>3} "
                  f"lr {e['lr']:.4f} loss "
                  + (f"{e['loss']:.4f}" if _fin(e.get("loss")) is not None
                     else "NaN")
                  + f" host {e['host_s']*1e3:.0f}ms dev "
                    f"{e['device_s']*1e3:.0f}ms")

    utils = by_kind(events, "utilization")
    if utils:
        u = utils[-1]
        mfu = _fin(u.get("mfu"))
        ach = _fin(u.get("achieved_flops"))
        peak = _fin(u.get("peak_flops"))
        wait = _fin(u.get("input_wait_frac"))
        spread = _fin(u.get("straggler_spread"))
        line = (f"-- utilization ({len(utils)} windows, last: "
                f"{u.get('rounds', '?')} rounds on "
                f"{u.get('device_kind', '?')}): ")
        line += f"mfu {mfu:.3g}" if mfu is not None else "mfu n/a"
        if ach is not None:
            line += f", {ach / 1e12:.2f} TFLOP/s"
            if peak:
                line += f" of {peak / 1e12:.0f} peak"
        if wait is not None:
            line += f", input wait {wait * 100:.1f}%"
        if spread is not None:
            line += f", straggler spread {spread:.3f}"
        bound = u.get("bound")
        bw = _fin(u.get("bw_frac"))
        if bound is not None:
            line += f", {bound}-bound"
            if bw is not None:
                line += f" (bw {bw * 100:.1f}% of peak)"
        print(line)

    sigs = by_kind(events, "signals")
    if sigs:
        print(f"-- signals: {len(sigs)} records")
        for key in SIGNAL_KEYS:
            vals = [_fin(e.get(key)) for e in sigs]
            vals = [v for v in vals if v is not None]
            if not vals:
                continue
            print(f"   {key:18s} first {vals[0]:11.5g} last {vals[-1]:11.5g}"
                  f" min {min(vals):11.5g} max {max(vals):11.5g}")

    lsigs = by_kind(events, "layer_signals")
    if lsigs:
        last = lsigs[-1]
        groups = last.get("groups") or []
        sv = starved_groups(groups, last.get("grad_mass"),
                            last.get("topk_count"))
        print(f"-- layers: {len(lsigs)} records, {len(groups)} "
              f"{last.get('signal_groups', '?')} groups"
              + (f"; STARVED last round: "
                 + " ".join(f"{g}({ms * 100:.1f}% mass, "
                            f"{ws * 100:.2f}% of k)" for g, ms, ws in sv)
                 if sv else "; no starved group last round")
              + " (run `teleview layers` for the table)")

    asyncs = by_kind(events, "async_round")
    if asyncs:
        # the staleness line: commits, merged-cohort staleness trend,
        # discount floor, partial flushes — the async-aggregation health
        # summary (schema v4, core/async_agg.py)
        sm = [_fin(e.get("staleness_mean")) for e in asyncs]
        sm = [v for v in sm if v is not None]
        smax = max((_fin(e.get("staleness_max")) or 0.0) for e in asyncs)
        dmin = min((_fin(e.get("discount_min")) or 1.0) for e in asyncs)
        n_partial = sum(1 for e in asyncs if e.get("partial"))
        errs = [_fin(e.get("error_norm")) for e in asyncs]
        errs = [v for v in errs if v is not None]
        line = (f"-- async: {len(asyncs)} commits, staleness mean "
                f"{sm[0]:.2f} -> {sm[-1]:.2f} (max {smax:.0f}), "
                f"discount floor {dmin:.3f}, {n_partial} partial flush"
                + ("es" if n_partial != 1 else ""))
        if errs:
            line += f"; error_norm first {errs[0]:.5g} last {errs[-1]:.5g}"
        print(line)

    defs = by_kind(events, "defense")
    if defs:
        # robustness line: what the defense did over the run (schema v5)
        last = defs[-1]
        cf = [_fin(e.get("clip_frac")) for e in defs]
        cf = [v for v in cf if v is not None]
        nfc = sum(_fin(e.get("nonfinite_clients")) or 0.0 for e in defs)
        line = (f"-- defense: {len(defs)} records, "
                f"{last.get('defense', '?')}"
                + (f" vs adversary={last.get('adversary')}"
                   if last.get("adversary") not in (None, "none") else ""))
        if cf:
            line += (f", clip_frac mean {sum(cf) / len(cf):.3f} "
                     f"max {max(cf):.3f}")
        line += (f"; nonfinite clients {nfc:.0f} total, "
                 f"quarantined {last.get('quarantined', 0)} "
                 f"ejected {last.get('ejected', 0)}")
        print(line)

    epochs = by_kind(events, "epoch")
    if epochs:
        print("-- epochs")

        def fmt(v, spec=".4f"):
            # loss/acc fields are nullable (non-finite serializes as null)
            return format(v, spec) if _fin(v) is not None else "NaN"

        for e in epochs:
            print(f"   ep{e['epoch']:>3} train {fmt(e['train_loss'])}/"
                  f"{fmt(e['train_acc'])} test {fmt(e['test_loss'])}/"
                  f"{fmt(e['test_acc'])} up {fmt(e['upload_mib'], '.0f')}"
                  " MiB")

    cstats = by_kind(events, "client_stats")
    if cstats:
        c = cstats[-1]
        q = (c.get("quantiles") or {}).get("loss") or {}
        spread = (q["p95"] - q["p5"]
                  if _fin(q.get("p95")) is not None
                  and _fin(q.get("p5")) is not None else None)
        print(f"-- clients: {len(cstats)} records, coverage "
              f"{c.get('coverage', 0) * 100:.1f}% "
              f"({c.get('distinct_clients', '?')} seen), last loss "
              f"p50 {q.get('p50')} spread(p95-p5) "
              + (f"{spread:.4g}" if spread is not None else "n/a"))

    pops = by_kind(events, "population")
    if pops:
        p = pops[-1]
        dist = _fin(p.get("distinct"))
        mem = _fin(p.get("memory_bytes"))
        print(f"-- population: {len(pops)} records, "
              f"{'SKETCH' if p.get('estimated') else 'exact'} ledger, "
              f"coverage {(_fin(p.get('coverage')) or 0) * 100:.1f}%"
              + (f" ({dist:.0f} distinct~)" if dist is not None else "")
              + (f", {mem / 2**20:.1f} MiB" if mem is not None else "")
              + " (run `teleview population` for the tables)")

    als = by_kind(events, "alert")
    if als:
        worst = max(als, key=lambda e: ("info", "warn", "critical").index(
            e.get("severity", "info")))
        print(f"-- alerts: {len(als)} fired, worst "
              f"[{worst.get('severity')}] {worst.get('rule')} at round "
              f"{worst.get('round')} (run `teleview alerts` for the list)")

    # crash-recovery lineage (schema v8): a resumed run APPENDS to its
    # predecessor's stream — each manifest opens a segment, each
    # `resume` names the segment it continues and the checkpoint/round
    # it restored, each `fault` names what interrupted a segment
    mans = by_kind(events, "manifest")
    resumes = by_kind(events, "resume")
    fts = by_kind(events, "fault")
    if len(mans) > 1 or resumes or fts:
        seg = f"{len(mans)} segment" + ("s" if len(mans) != 1 else "")
        print(f"-- lineage: {seg} stitched in one stream")
        for e in resumes:
            src = e.get("checkpoint") or "no checkpoint (stream only)"
            print(f"   resume at round {e.get('round')}"
                  + (f" epoch {e.get('epoch')}"
                     if e.get("epoch") is not None else "")
                  + f" from {src}"
                  + (f" (continues segment {e['prior_stream']}, "
                     f"{e.get('prior_events')} events)"
                     if e.get("prior_stream") else ""))
        for e in fts:
            print(f"   fault [{e.get('kind')}] at round {e.get('round')}"
                  + (f" signal {e['signal']}" if e.get("signal") else "")
                  + (f" grace {e['grace_s']}s"
                     if e.get("grace_s") is not None else "")
                  + (f": {e['detail']}" if e.get("detail") else ""))

    # the LAST summary is the lineage's final verdict (earlier segments
    # that drained gracefully wrote their own aborted footers)
    summs = by_kind(events, "summary")
    summ = summs[-1] if summs else None
    if summ is None:
        print("-- NO summary footer: the run DIED before finishing")
    else:
        print(f"-- summary: {'ABORTED' if summ['aborted'] else 'ok'}, "
              f"{summ['n_rounds']} rounds, {summ['wall_time_s']:.1f}s wall")
    for e in by_kind(events, "nan_abort"):
        print(f"   nan_abort at round {e['nan_round']}: {e['reason']}")


# -------------------------------------------------------------------- alerts


def alerts(events: List[Dict[str, Any]]) -> int:
    """Postmortem triage: every alert in firing order, rule counts, the
    nan_abort line. Exit 1 when anything critical (or an abort) fired —
    scriptable as a health gate over a finished run's stream."""
    als = by_kind(events, "alert")
    aborts = by_kind(events, "nan_abort")
    fts = by_kind(events, "fault")
    for e in fts:
        # faults are context, not verdicts: a graceful preempt or a
        # recovered fetch retry must not trip the health gate (a
        # round_stall also fired its own critical alert, counted below)
        print(f"   r{e.get('round', '?'):>6} [fault   ] "
              f"{e.get('kind', '?'):24s}"
              + (f" signal={e['signal']}" if e.get("signal") else "")
              + (f" {e['detail']}" if e.get("detail") else ""))
    if not als and not aborts:
        print("no alerts (and no nan_abort) in the stream"
              + (f" ({len(fts)} fault record(s) above)" if fts else ""))
        return 0
    counts: Dict[str, int] = {}
    for e in als:
        counts[str(e.get("rule"))] = counts.get(str(e.get("rule")), 0) + 1
        z = _fin(e.get("zscore"))
        print(f"   r{e.get('round', '?'):>6} [{e.get('severity', '?'):8s}] "
              f"{e.get('rule', '?'):24s} {e.get('metric', '?')}"
              f"={e.get('value')}"
              + (f" z {z:+.1f}" if z is not None else "")
              + f" action={e.get('action', '?')}")
    for e in aborts:
        print(f"   nan_abort at round {e['nan_round']}: {e['reason']}")
    if counts:
        print("-- rule counts: "
              + " ".join(f"{k}x{v}" for k, v in sorted(counts.items())))
    critical = (any(e.get("severity") == "critical" for e in als)
                or bool(aborts))
    print(f"-- {'CRITICAL' if critical else 'no critical alerts'}")
    return 1 if critical else 0


# ------------------------------------------------------------------- clients


def clients(events: List[Dict[str, Any]]) -> int:
    """Per-client population trends from the ``client_stats`` stream:
    p50/p95/max of every stat first->last, the participation ledger
    trajectory, and the most frequent argmax (round-maximum) clients."""
    cstats = by_kind(events, "client_stats")
    if not cstats:
        print("no client_stats events (pre-PR-4 stream, or "
              "--no_client_stats)")
        return 0
    first, last = cstats[0], cstats[-1]
    print(f"== client population: {len(cstats)} records, "
          f"{last.get('n_participants', '?')} participants/round")
    print(f"-- coverage {first.get('coverage', 0) * 100:.1f}% -> "
          f"{last.get('coverage', 0) * 100:.1f}% "
          f"({last.get('distinct_clients', '?')} distinct); staleness "
          f"p50 {last.get('staleness_p50')} max {last.get('staleness_max')}"
          f"; samples/client p50 {last.get('counts_p50')} "
          f"max {last.get('counts_max')}")
    print("-- per-client stat quantiles (first -> last)")
    for key in CLIENT_STAT_KEYS:
        qf = (first.get("quantiles") or {}).get(key) or {}
        ql = (last.get("quantiles") or {}).get(key) or {}
        if _fin(ql.get("p50")) is None and _fin(qf.get("p50")) is None:
            continue

        def fmt(q):
            vals = [q.get(f) for f in ("p50", "p95", "max")]
            return "/".join(f"{v:.4g}" if _fin(v) is not None else "-"
                            for v in vals)

        print(f"   {key:18s} p50/p95/max {fmt(qf)} -> {fmt(ql)}")
    owners: Dict[int, int] = {}
    for e in cstats:
        c = ((e.get("quantiles") or {}).get("loss") or {}).get(
            "argmax_client")
        if isinstance(c, int):
            owners[c] = owners.get(c, 0) + 1
    if owners:
        top = sorted(owners.items(), key=lambda kv: -kv[1])[:5]
        print("-- clients most often owning the round's max loss: "
              + " ".join(f"#{c}x{n}" for c, n in top))
    return 0


# ---------------------------------------------------------------- population


def _stall_streak(pops: List[Dict[str, Any]]) -> int:
    """Terminal distinct-coverage stall streak of a ``population``
    stream — the jax-free twin of the monitor's ``coverage_stall``
    bookkeeping (telemetry/health.py): consecutive records where the
    round advanced but the distinct-participant estimate did not grow
    while coverage sat below saturation (0.999). The monitor fires at
    ``COVERAGE_STALL_WINDOW``; ``diff --coverage_stall`` reuses this."""
    streak = 0
    prev: Optional[Dict[str, Any]] = None
    for e in pops:
        cov = _fin(e.get("coverage"))
        dist = _fin(e.get("distinct"))
        rnd = _fin(e.get("round"))
        if prev is not None:
            advanced = (rnd is None or _fin(prev.get("round")) is None
                        or rnd > _fin(prev.get("round")))
            grew = (dist is not None
                    and _fin(prev.get("distinct")) is not None
                    and dist > _fin(prev.get("distinct")))
            if cov is not None and cov >= 0.999:
                streak = 0
            elif not advanced:
                pass
            elif grew:
                streak = 0
            else:
                streak += 1
        prev = e
    return streak


def population(events: List[Dict[str, Any]]) -> int:
    """Population-scale participation report (schema-v11 ``population``
    events, telemetry/population.py): coverage/distinct trajectory,
    sample-count and staleness quantiles, the three heavy-hitter tables
    (most-sampled, loss-argmax, quarantine strikes), the ledger's memory
    footprint, and — on sketch-estimated streams — the documented
    count-min (eps, delta) error bounds. Works on exact streams too;
    the ``estimated`` flag says which ledger wrote the numbers."""
    pops = by_kind(events, "population")
    if not pops:
        print("no population events (pre-v11 stream, or "
              "--no_client_stats)")
        return 0
    first, last = pops[0], pops[-1]
    est = bool(last.get("estimated"))
    print(f"== population: {len(pops)} records, "
          f"{last.get('registered', '?')} registered clients, "
          + ("SKETCH-ESTIMATED" if est else "exact") + " ledger")
    dist = _fin(last.get("distinct"))
    reg = _fin(last.get("registered"))
    print(f"-- coverage {(_fin(first.get('coverage')) or 0) * 100:.1f}% -> "
          f"{(_fin(last.get('coverage')) or 0) * 100:.1f}% "
          f"({dist:.0f} of {reg:.0f} distinct"
          + ("~" if est else "") + ")"
          if dist is not None and reg is not None else
          "-- coverage trajectory unavailable (empty ledger)")
    streak = _stall_streak(pops)
    if streak >= COVERAGE_STALL_WINDOW:
        print(f"-- COVERAGE STALL: distinct flat for the last {streak} "
              f"records (monitor fires at {COVERAGE_STALL_WINDOW})")

    def q3(p50, p95, mx):
        vals = [last.get(p50), last.get(p95), last.get(mx)]
        return "/".join(f"{v:.4g}" if _fin(v) is not None else "-"
                        for v in vals)

    print(f"-- samples/client p50/p95/max {q3('counts_p50', 'counts_p95', 'counts_max')}"
          f"; staleness p50/p95/max "
          f"{q3('staleness_p50', 'staleness_p95', 'staleness_max')}")
    oc50, oc95 = _fin(last.get("obs_count_p50")), _fin(last.get("obs_count_p95"))
    g50, g95 = _fin(last.get("gap_p50")), _fin(last.get("gap_p95"))
    if oc50 is not None or g50 is not None:
        print("-- per-participation streams (P2 running quantiles): "
              "samples/slot p50/p95 "
              + "/".join(f"{v:.4g}" if v is not None else "-"
                         for v in (oc50, oc95))
              + ", revisit gap p50/p95 "
              + "/".join(f"{v:.4g}" if v is not None else "-"
                         for v in (g50, g95)))
    for key, label in (("top_sampled", "most-sampled clients"),
                       ("top_loss", "loss-argmax owners"),
                       ("top_strikes", "quarantine strikes")):
        top = last.get(key) or []
        pairs = [(p[0], p[1]) for p in top
                 if isinstance(p, (list, tuple)) and len(p) >= 2]
        if pairs:
            print(f"-- {label}: "
                  + " ".join(f"#{int(c)}x{n:.0f}" for c, n in pairs[:10])
                  + (" (counts are upper bounds)" if est else ""))
    mem = _fin(last.get("memory_bytes"))
    line = (f"-- ledger memory {mem / 2**20:.2f} MiB"
            if mem is not None else "-- ledger memory n/a")
    eps, delta = _fin(last.get("cm_epsilon")), _fin(last.get("cm_delta"))
    if eps is not None and delta is not None:
        line += (f"; count-min bound: overcount <= {eps:.3g}*N "
                 f"w.p. >= {1 - delta:.3g}"
                 f" (hh_k {last.get('hh_k')}, "
                 f"sample {last.get('sample_size')})")
    print(line)
    return 0


# --------------------------------------------------------------------- trend


def trend(path: str) -> int:
    """Benchmark trajectory across the repo's ``BENCH_r*.json``
    checkpoints: one row per file — cifar round throughput (img/s) and
    MFU, the saturated-batch arm, the gpt2 arm (tok/s, MFU), the modeled
    wire bytes when the vintage carries them, and the slowest warmup
    parsed from the captured tail. Every column is vintage-tolerant:
    r01 predates mfu, r02's bench crashed (parsed null), the saturated
    and gpt2 arms appear mid-history, and no vintage so far emits wire
    bytes — absent is '-', never a guess."""
    import glob
    import re
    if os.path.isdir(path):
        files = sorted(glob.glob(os.path.join(path, "BENCH_*.json")))
    else:
        files = sorted(glob.glob(path))
    if not files:
        print(f"no BENCH_*.json under {path}")
        return 1

    def num(v, spec=".4g"):
        return format(v, spec) if isinstance(v, (int, float)) else "-"

    print("   file            img/s     mfu  sat img/s  sat mfu  "
          "gpt2 tok/s  gpt2 mfu  wire MiB  warmup_s")
    for f in files:
        name = os.path.basename(f)
        try:
            with open(f) as fh:
                d = json.load(fh)
        except (OSError, ValueError):
            print(f"   {name:14s} unreadable")
            continue
        parsed = d.get("parsed") if isinstance(d, dict) else None
        parsed = parsed if isinstance(parsed, dict) else {}
        sat = parsed.get("cifar_saturated") or {}
        gpt2 = parsed.get("gpt2") or {}
        # wire bytes: no committed vintage emits these yet; accept the
        # names a future bench would naturally use, render '-' otherwise
        wire = None
        for k in ("wire_mib", "wire_bytes", "table_reduce_bytes"):
            w = _fin(parsed.get(k))
            if w is not None:
                wire = w / 2**20 if k != "wire_mib" else w
                break
        warm = re.findall(r"warmup done in (\d+\.?\d*)s",
                          str(d.get("tail") or ""))
        warm_s = max((float(w) for w in warm), default=None)
        row = (f"   {name:14s} {num(parsed.get('value'), '8.5g'):>8} "
               f"{num(parsed.get('mfu')):>7} "
               f"{num(sat.get('value'), '9.5g'):>9} "
               f"{num(sat.get('mfu')):>8} "
               f"{num(gpt2.get('value'), '10.6g'):>10} "
               f"{num(gpt2.get('mfu')):>9} "
               f"{num(wire, '.3g'):>9} "
               f"{num(warm_s, '.1f'):>9}")
        if not parsed:
            row += f"   (rc={d.get('rc')}: bench produced no parse)"
        print(row)
    return 0


# ------------------------------------------------------------------- defense


def defense(events: List[Dict[str, Any]]) -> int:
    """Robustness report from the schema-v5 ``defense`` stream: the
    configured attack/defense/action, clip and trim activity trends,
    per-round nonfinite counts, the quarantine ledger trajectory and
    injected-fate totals. Exits 1 when any client was permanently
    EJECTED — a fleet losing clients for good is worth a red exit in a
    health-gate pipeline even if the run itself finished."""
    defs = by_kind(events, "defense")
    if not defs:
        print("no defense events (pre-v5 stream, or the robustness "
              "subsystem — --adversary/--defense/--nonfinite_action "
              "quarantine — was not configured)")
        return 0
    first, last = defs[0], defs[-1]
    print(f"== defense: {len(defs)} records, defense="
          f"{last.get('defense', '?')} adversary="
          f"{last.get('adversary', '?')} nonfinite_action="
          f"{last.get('nonfinite_action', '?')}")
    for key in DEFENSE_KEYS:
        vals = [_fin(e.get(key)) for e in defs]
        vals = [v for v in vals if v is not None]
        if not vals:
            continue
        print(f"   {key:18s} first {vals[0]:9.4g} last {vals[-1]:9.4g} "
              f"min {min(vals):9.4g} max {max(vals):9.4g}")
    inj: Dict[str, float] = {}
    for e in defs:
        for kind, n in (e.get("injected") or {}).items():
            if isinstance(n, (int, float)):
                inj[str(kind)] = inj.get(str(kind), 0.0) + float(n)
    if inj:
        print("-- injected slots (sum over records): "
              + " ".join(f"{k}x{v:.0f}" for k, v in sorted(inj.items())))
    digest = last.get("quarantine_ids_digest")
    if digest:
        print(f"-- quarantine ids digest (last): {digest}")
    ejected = int(last.get("ejected") or 0)
    print(f"-- {'EJECTIONS: ' + str(ejected) if ejected else 'no ejections'}"
          f" (quarantined now: {last.get('quarantined', 0)})")
    return 1 if ejected else 0


# -------------------------------------------------------------------- layers


def _shares(vals) -> Optional[List[Optional[float]]]:
    """Per-entry share of a per-group mass/count list (None-safe);
    None when the field is null or carries no mass."""
    if not vals:
        return None
    nums = [v if isinstance(v, (int, float)) else 0.0 for v in vals]
    total = sum(nums)
    if total <= 0:
        return None
    return [v / total for v in nums]


def layers(events: List[Dict[str, Any]]) -> int:
    """Layer-wise compression attribution report (schema-v10
    ``layer_signals`` events): the per-group table of the LAST record —
    coordinate share, dense-gradient mass share, recovered-update mass
    share, top-k win share, EF mass share, heavy-hitter overlap, and a
    STARVED verdict (> {mass}% of gradient mass, < {win}% of k — the
    same thresholds the ``group_starvation`` monitor rule fires on) —
    plus the first->last win-share trend per group, which is the
    mechanism trace the adaptive-compression controller consumes."""
    lsigs = by_kind(events, "layer_signals")
    if not lsigs:
        print("no layer_signals events (pre-v10 stream, or "
              "--signal_groups off / --no_signals)")
        return 0
    first, last = lsigs[0], lsigs[-1]
    groups = [str(g) for g in (last.get("groups") or [])]
    sizes = last.get("sizes") or []
    print(f"== layers: {len(lsigs)} records, {len(groups)} "
          f"{last.get('signal_groups', '?')} groups, mode "
          f"{last.get('mode', '?')}")
    d = sum(v for v in sizes if isinstance(v, (int, float))) or 1
    gshare = _shares(last.get("grad_mass"))
    ushare = _shares(last.get("update_mass"))
    kshare = _shares(last.get("topk_count"))
    eshare = _shares(last.get("error_mass"))
    hh = last.get("hh_overlap")
    starved = {g for g, _, _ in starved_groups(
        groups, last.get("grad_mass"), last.get("topk_count"))}

    def pct(shares, i):
        if shares is None or i >= len(shares) or shares[i] is None:
            return "     -"
        return f"{shares[i] * 100:5.1f}%"

    cshare = [(s / d if isinstance(s, (int, float)) else None)
              for s in sizes]
    print("   group                 coords   grad    upd    k-win"
          "   err     hh")
    for i, g in enumerate(groups):
        h = (hh[i] if hh and i < len(hh) else None)
        print(f"   {g:20s} {pct(cshare, i)}"
              f" {pct(gshare, i)} {pct(ushare, i)} {pct(kshare, i)}"
              f" {pct(eshare, i)}"
              + (f"  {h:5.2f}" if isinstance(h, (int, float)) else "      -")
              + ("   STARVED" if g in starved else ""))
    kf, kl = _shares(first.get("topk_count")), kshare
    if kf and kl and first is not last:
        print(f"-- k-win share trend (r{first.get('round', '?')} -> "
              f"r{last.get('round', '?')})")
        for i, g in enumerate(groups):
            if i < len(kf) and kf[i] is not None and kl[i] is not None:
                print(f"   {g:20s} {kf[i] * 100:5.1f}% -> "
                      f"{kl[i] * 100:5.1f}%")
    if starved:
        print(f"-- STARVED groups (> {STARVATION_MASS_SHARE * 100:.0f}% "
              f"gradient mass, < {STARVATION_WIN_SHARE * 100:.0f}% of k): "
              + " ".join(sorted(starved)))
    else:
        print("-- no starved group in the last record"
              + ("" if gshare is not None else
                 " (grad_mass is null — starvation is measured against "
                 "gradient mass, unavailable on this round's topology)"))
    return 0


# -------------------------------------------------------------------- memory


def memory(events: List[Dict[str, Any]]) -> int:
    """Memory report from the schema-v6 streams: the per-executable
    byte inventory (``memory_ledger`` events — where a compiled round's
    bytes STATICALLY go), the residency timeline (enriched ``memory``
    events — what the allocator DYNAMICALLY held per phase, and which
    phase grew the high-water), and the roofline table (``utilization``
    events — whether each window was compute- or bandwidth-bound)."""
    ledgers = latest_memory_ledgers(events)
    mems = by_kind(events, "memory")
    utils = by_kind(events, "utilization")
    if not ledgers and not mems and not utils:
        print("no memory_ledger/memory/utilization events (pre-v6 "
              "stream, or --no_telemetry)")
        return 0
    if ledgers:
        print("== per-executable byte inventory (memory_analysis, last "
              "compile each)")
        for name, e in sorted(ledgers.items()):
            print(f"   {name}: temp {_gib(e.get('temp_bytes'))}, "
                  f"args {_gib(e.get('argument_bytes'))}, "
                  f"out {_gib(e.get('output_bytes'))}, "
                  f"alias {_gib(e.get('alias_bytes'))}, "
                  f"total {_gib(e.get('total_bytes'))}")
    if mems:
        any_resident = any(_fin(e.get("peak_bytes")) is not None
                           for e in mems)
        print(f"== residency timeline ({len(mems)} snapshots"
              + ("" if any_resident
                 else "; allocator stats unavailable on this backend — "
                      "null means not measurable, not zero") + ")")
        for e in mems:
            delta = _fin(e.get("delta_peak_bytes"))
            head = _fin(e.get("headroom_frac"))
            print(f"   {str(e.get('phase', '?')):24s} "
                  f"live {_gib(e.get('live_bytes'))} "
                  f"peak {_gib(e.get('peak_bytes'))}"
                  + (f" (+{_gib(delta)})" if delta is not None and delta > 0
                     else "")
                  + f" frag {_gib(e.get('fragmentation_bytes'))}"
                  + (f" headroom {head * 100:.1f}%"
                     if head is not None else ""))
    if utils:
        rows = [u for u in utils
                if _fin(u.get("arithmetic_intensity")) is not None]
        if rows:
            print("== roofline (utilization windows with byte counts)")
            for u in rows:
                ai = _fin(u.get("arithmetic_intensity"))
                ridge = _fin(u.get("ridge_intensity"))
                bw = _fin(u.get("bw_frac"))
                mfu = _fin(u.get("mfu"))
                print(f"   r{u.get('round', '?'):>6}: AI {ai:.2f} FLOP/B"
                      + (f" (ridge {ridge:.2f})" if ridge is not None
                         else "")
                      + f" -> {u.get('bound') or 'n/a'}"
                      + (f", bw {bw * 100:.1f}%" if bw is not None else "")
                      + (f", mfu {mfu:.3g}" if mfu is not None else ""))
        else:
            print("== roofline: utilization events carry no byte counts "
                  "(no cost-analysis bytes, or pre-v6 stream)")
    return 0


# ------------------------------------------------------------------- timeline


def build_trace(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Chrome-tracing / perfetto JSON from the span + utilization +
    round event stream. Span events carry spans as (monotonic) seconds
    since their tracer's epoch plus a ``t0_wall`` unix anchor; counter
    tracks use the events' absolute ``t``. All timestamps shift to start
    at 0 and are emitted in MICROseconds (the trace-event format's
    unit), sorted ascending."""
    slices = []   # (abs_start_s, dur_s, name, tid, depth)
    for e in by_kind(events, "span"):
        t0w = _fin(e.get("t0_wall")) or 0.0
        for s in e.get("spans") or []:
            if not isinstance(s, dict):
                continue
            ts, dur = _fin(s.get("ts")), _fin(s.get("dur_s"))
            if ts is None or dur is None:
                continue
            slices.append((t0w + ts, max(dur, 0.0),
                           str(s.get("name", "?")),
                           int(s.get("tid") or 0),
                           int(s.get("depth") or 0)))
    counters = []  # (abs_t_s, track_name, value)
    # wire-width tracks (schema v9, `collectives` events): the modeled
    # per-device table-reduce ICI bytes and the wire dtype's bytes/cell
    # per watched executable — a quantized wire silently re-widening is
    # visible as a step in the timeline, not only in `diff`
    wire_cell_bytes = {"float32": 4.0, "bfloat16": 2.0, "int8": 1.0}
    for e in by_kind(events, "collectives"):
        t = _fin(e.get("t"))
        if t is None:
            continue
        name = str(e.get("name", "?"))
        trb = _fin(e.get("table_reduce_bytes"))
        if trb is not None:
            counters.append((t, f"table_reduce_bytes:{name}", trb))
        w = wire_cell_bytes.get(str(e.get("wire_dtype")))
        if w is not None:
            counters.append((t, f"wire_dtype_bytes:{name}", w))
    for e in by_kind(events, "utilization"):
        t = _fin(e.get("t"))
        if t is None:
            continue
        if _fin(e.get("mfu")) is not None:
            counters.append((t, "MFU", e["mfu"]))
        if _fin(e.get("input_wait_frac")) is not None:
            counters.append((t, "input_wait_frac", e["input_wait_frac"]))
    for e in by_kind(events, "round"):
        t, loss = _fin(e.get("t")), _fin(e.get("loss"))
        if t is not None and loss is not None:
            counters.append((t, "loss", loss))
    for e in by_kind(events, "memory"):
        # HBM counter track (schema v6): live + allocator-peak bytes in
        # GiB per residency snapshot — the memory timeline next to the
        # span slices, so an OOM trace shows WHEN the bytes arrived
        t = _fin(e.get("t"))
        if t is None:
            continue
        if _fin(e.get("live_bytes")) is not None:
            counters.append((t, "hbm_live_gib", e["live_bytes"] / 2**30))
        if _fin(e.get("peak_bytes")) is not None:
            counters.append((t, "hbm_peak_gib", e["peak_bytes"] / 2**30))

    starts = [s[0] for s in slices] + [c[0] for c in counters]
    base = min(starts) if starts else 0.0
    trace = []
    for start, dur, name, tid, depth in slices:
        trace.append({"name": name, "ph": "X", "pid": 0, "tid": tid,
                      "ts": (start - base) * 1e6, "dur": dur * 1e6,
                      "args": {"depth": depth}})
    for t, name, value in counters:
        trace.append({"name": name, "ph": "C", "pid": 0,
                      "ts": (t - base) * 1e6, "args": {name: value}})
    trace.sort(key=lambda e: e["ts"])
    man = next(iter(by_kind(events, "manifest")), {})
    meta = [{"name": "process_name", "ph": "M", "pid": 0, "ts": 0,
             "args": {"name": str(man.get("run_type", "run"))}}]
    return {"traceEvents": meta + trace, "displayTimeUnit": "ms"}


def timeline(events: List[Dict[str, Any]], out_path: str) -> int:
    trace = build_trace(events)
    n_slices = sum(1 for e in trace["traceEvents"] if e.get("ph") == "X")
    if n_slices == 0:
        print("WARNING: no span events in the stream (pre-v2 telemetry, "
              "or the run never hit the record cadence) — the trace "
              "holds counter tracks only", file=sys.stderr)
    with open(out_path, "w") as f:
        json.dump(trace, f)
    print(f"wrote {out_path}: {n_slices} spans, "
          f"{len(trace['traceEvents']) - n_slices - 1} counter samples "
          "(open at https://ui.perfetto.dev or chrome://tracing)")
    return 0


# ----------------------------------------------------------------------- diff


def diff(a: List[Dict[str, Any]], b: List[Dict[str, Any]],
         args) -> List[str]:
    """Regressions of run B against baseline A (empty list = clean)."""
    problems: List[str] = []

    ca, cb = latest_collectives(a), latest_collectives(b)
    for name in sorted(set(ca) & set(cb)):
        counts_a = ca[name].get("counts") or {}
        counts_b = cb[name].get("counts") or {}
        for kind in sorted(set(counts_a) | set(counts_b)):
            na, nb = counts_a.get(kind, 0), counts_b.get(kind, 0)
            if nb > na + args.count_slack:
                problems.append(
                    f"collectives[{name}]: {kind} launch count {na} -> {nb}"
                    " (count growth is the 32x-unroll regression class)")
        ba = ca[name].get("total_bytes") or 0
        bb = cb[name].get("total_bytes") or 0
        if ba > 0 and bb > ba * args.bytes_ratio:
            problems.append(
                f"collectives[{name}]: payload bytes {ba} -> {bb} "
                f"(> {args.bytes_ratio:.2f}x)")
        # schema-v9 quantized-wire gate: the modeled table-reduce ICI
        # bytes regressing past threshold means the wire silently
        # re-widened (an int8 arm compiling the f32 reduce, a barrier
        # lost to a jax upgrade) — the exact regression class
        # --wire_dtype int8 exists to prevent
        wa = _fin(ca[name].get("table_reduce_bytes"))
        wb = _fin(cb[name].get("table_reduce_bytes"))
        if wa is not None and wb is not None and wa > 0 \
                and wb > wa * args.wire_bytes_growth:
            problems.append(
                f"collectives[{name}]: table-reduce wire bytes "
                f"{wa:.0f} -> {wb:.0f} "
                f"(> {args.wire_bytes_growth:.2f}x — the quantized "
                "wire re-widened)")

    ma, mb = latest_memory_ledgers(a), latest_memory_ledgers(b)
    for name in sorted(set(ma) & set(mb)):
        # schema-v6 memory gate: temp-buffer growth is the de-fusion /
        # re-materialization regression class (a per-client (W, d)
        # gradient reappearing multiplies temp by the client count)
        ta = _fin(ma[name].get("temp_bytes"))
        tb = _fin(mb[name].get("temp_bytes"))
        if ta is not None and tb is not None and ta > 0 \
                and tb > ta * args.temp_bytes_growth:
            problems.append(
                f"memory_ledger[{name}]: temp bytes {ta:.0f} -> {tb:.0f} "
                f"(> {args.temp_bytes_growth:.2f}x — a working-set "
                "regression: something re-materialized)")

    sa, sb = by_kind(a, "signals"), by_kind(b, "signals")
    if sa and sb:
        for key in NORM_KEYS:
            va, vb = _fin(sa[-1].get(key)), _fin(sb[-1].get(key))
            if va is not None and vb is not None and va > 0 \
                    and vb > va * args.signal_ratio:
                problems.append(
                    f"signals: final {key} {va:.5g} -> {vb:.5g} "
                    f"(> {args.signal_ratio:.2f}x — EF-divergence class)")
        oa, ob = (_fin(sa[-1].get("topk_overlap")),
                  _fin(sb[-1].get("topk_overlap")))
        if oa is not None and ob is not None \
                and ob < oa - args.overlap_drop:
            problems.append(
                f"signals: topk_overlap {oa:.3f} -> {ob:.3f} "
                f"(drop > {args.overlap_drop:.2f} — recovery degraded)")

    ua, ub = by_kind(a, "utilization"), by_kind(b, "utilization")
    if ua and ub:
        ma, mb = _fin(ua[-1].get("mfu")), _fin(ub[-1].get("mfu"))
        if ma is not None and mb is not None and ma > 0 \
                and mb < ma * (1 - args.mfu_drop):
            problems.append(
                f"utilization: final mfu {ma:.4f} -> {mb:.4f} "
                f"(> {args.mfu_drop:.0%} relative drop)")
        wa = _fin(ua[-1].get("input_wait_frac"))
        wb = _fin(ub[-1].get("input_wait_frac"))
        if wa is not None and wb is not None \
                and wb > wa + args.input_wait_rise:
            problems.append(
                f"utilization: input_wait_frac {wa:.3f} -> {wb:.3f} "
                f"(rise > {args.input_wait_rise:.2f} — the input "
                "pipeline started starving the chip)")
        fa = _fin(ua[-1].get("bw_frac"))
        fb = _fin(ub[-1].get("bw_frac"))
        if fa is not None and fb is not None \
                and fb < fa - args.bw_frac_drop:
            problems.append(
                f"utilization: final bw_frac {fa:.3f} -> {fb:.3f} "
                f"(drop > {args.bw_frac_drop:.2f} — achieved HBM "
                "bandwidth regressed against the same peak)")

    def per_chip(events):
        # last bench event carrying the per-chip throughput (the
        # scaling-curve arms emit it; ordinary runs have none and the
        # gate is vacuous-by-absence, like every other diff gate)
        for e in reversed(by_kind(events, "bench")):
            v = _fin((e.get("result") or {}).get("per_chip_items_per_s"))
            if v is not None:
                return v
        return None

    pa, pb = per_chip(a), per_chip(b)
    if pa is not None and pb is not None and pa > 0 \
            and pb < pa * (1 - args.perchip_drop):
        problems.append(
            f"bench: per_chip_items_per_s {pa:.5g} -> {pb:.5g} "
            f"(> {args.perchip_drop:.0%} relative drop — per-chip "
            "throughput regressed; on a weak-scaling sweep this means "
            "added chips are being taxed instead of adding capacity)")

    def starvation_gap(events):
        # max per-group starvation gap (mass share minus k-win share,
        # over groups above the mass floor) of the final layer_signals
        # event; None when the stream has none or grad_mass is null —
        # the gate is vacuous-by-absence like every other diff gate
        ls = by_kind(events, "layer_signals")
        if not ls:
            return None
        e = ls[-1]
        gm = _shares(e.get("grad_mass"))
        tc = _shares(e.get("topk_count"))
        if gm is None or tc is None:
            return None
        gaps = [m - w for m, w in zip(gm, tc)
                if m is not None and w is not None
                and m > STARVATION_MASS_SHARE]
        return max(gaps) if gaps else 0.0

    ga, gb = starvation_gap(a), starvation_gap(b)
    if ga is not None and gb is not None \
            and gb > ga + args.starvation_rise:
        problems.append(
            f"layer_signals: starvation gap (max grad-mass share minus "
            f"k-win share) {ga:.3f} -> {gb:.3f} (rise > "
            f"{args.starvation_rise:.2f} — a parameter group is losing "
            "the top-k race it used to win; the layer-wise compression "
            "allocation regressed)")

    aa, ab = by_kind(a, "async_round"), by_kind(b, "async_round")
    if aa and ab:
        za = _fin(aa[-1].get("staleness_mean"))
        zb = _fin(ab[-1].get("staleness_mean"))
        if za is not None and zb is not None \
                and zb > za + args.staleness_rise:
            problems.append(
                f"async_round: final staleness_mean {za:.2f} -> {zb:.2f} "
                f"(rise > {args.staleness_rise:.2f} — cohorts are landing "
                "later relative to commits; the in-flight pool or the "
                "buffer goal regressed)")
        ea = _fin(aa[-1].get("error_norm"))
        eb = _fin(ab[-1].get("error_norm"))
        if ea is not None and eb is not None and ea > 0 \
                and eb > ea * args.signal_ratio:
            problems.append(
                f"async_round: final error_norm {ea:.5g} -> {eb:.5g} "
                f"(> {args.signal_ratio:.2f}x — staleness-induced EF "
                "divergence class)")

    da, db = by_kind(a, "defense"), by_kind(b, "defense")
    if da and db:
        # schema-v5 robustness gates: a defended run whose clip fraction
        # rises sharply is absorbing a new attack (or clipping honest
        # clients); growth of the bench/eject counts is a fleet-health
        # regression in its own right
        fa = _fin(da[-1].get("clip_frac"))
        fb = _fin(db[-1].get("clip_frac"))
        if fa is not None and fb is not None \
                and fb > fa + args.clip_frac_rise:
            problems.append(
                f"defense: final clip_frac {fa:.3f} -> {fb:.3f} "
                f"(rise > {args.clip_frac_rise:.2f} — the norm clip is "
                "binding on far more clients than the baseline)")
        qa = (_fin(da[-1].get("quarantined")) or 0) + \
            (_fin(da[-1].get("ejected")) or 0)
        qb = (_fin(db[-1].get("quarantined")) or 0) + \
            (_fin(db[-1].get("ejected")) or 0)
        if qb > qa + args.quarantine_growth:
            problems.append(
                f"defense: quarantined+ejected {qa:.0f} -> {qb:.0f} "
                f"(growth > {args.quarantine_growth} — more clients are "
                "producing nonfinite uploads than the baseline)")

    def final_loss(events):
        eps = by_kind(events, "epoch")
        if eps:
            return _fin(eps[-1].get("test_loss"))
        rnds = [_fin(e.get("loss")) for e in by_kind(events, "round")]
        rnds = [v for v in rnds if v is not None]
        return rnds[-1] if rnds else None

    la, lb = final_loss(a), final_loss(b)
    if la is not None:
        if lb is None:
            problems.append("loss: baseline finite, new run has no finite "
                            "loss (diverged?)")
        elif la > 0 and lb > la * args.loss_ratio:
            problems.append(f"loss: final {la:.4f} -> {lb:.4f} "
                            f"(> {args.loss_ratio:.2f}x)")

    def loss_spread(events):
        cs = by_kind(events, "client_stats")
        if not cs:
            return None
        q = (cs[-1].get("quantiles") or {}).get("loss") or {}
        hi, lo = _fin(q.get("p95")), _fin(q.get("p5"))
        return hi - lo if hi is not None and lo is not None else None

    pa, pb = loss_spread(a), loss_spread(b)
    if pa is not None and pb is not None and pa > 0 \
            and pb > pa * args.client_spread_ratio:
        problems.append(
            f"client_stats: final loss spread (p95-p5) {pa:.4g} -> "
            f"{pb:.4g} (> {args.client_spread_ratio:.2f}x — the client "
            "population is diverging)")

    # schema-v11 population gates: final coverage dropping more than
    # --coverage_stall (absolute) against the baseline, or the
    # candidate's stream ENDING in a distinct-coverage stall (streak >=
    # COVERAGE_STALL_WINDOW, the monitor rule's window) the baseline
    # does not show — the sampler stopped reaching new clients
    pop_a, pop_b = by_kind(a, "population"), by_kind(b, "population")
    if pop_a and pop_b:
        va = _fin(pop_a[-1].get("coverage"))
        vb = _fin(pop_b[-1].get("coverage"))
        if va is not None and vb is not None \
                and vb < va - args.coverage_stall:
            problems.append(
                f"population: final coverage {va:.3f} -> {vb:.3f} "
                f"(drop > {args.coverage_stall:.2f} — the candidate is "
                "reaching a smaller slice of the client population)")
        sa, sb_ = _stall_streak(pop_a), _stall_streak(pop_b)
        if sb_ >= COVERAGE_STALL_WINDOW > sa:
            problems.append(
                f"population: candidate ends in a {sb_}-record "
                f"distinct-coverage stall (window "
                f"{COVERAGE_STALL_WINDOW}) the baseline does not — "
                "the client sampler stopped reaching new clients")

    def crit_alerts(events):
        return [e for e in by_kind(events, "alert")
                if e.get("severity") == "critical"]

    na, nb = len(crit_alerts(a)), len(crit_alerts(b))
    if nb > na + args.alert_slack:
        problems.append(
            f"alerts: critical count {na} -> {nb} (the monitor fired on "
            "the candidate where the baseline stayed quiet)")
    for e in by_kind(b, "nan_abort"):
        if not by_kind(a, "nan_abort"):
            problems.append(f"new run aborted non-finite at round "
                            f"{e['nan_round']} (baseline did not)")
    return problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="teleview")
    sub = ap.add_subparsers(dest="cmd")
    s = sub.add_parser("summarize", help="one-stream report")
    s.add_argument("path")
    d = sub.add_parser("diff", help="regression check: B against baseline A")
    d.add_argument("baseline")
    d.add_argument("candidate")
    d.add_argument("--count_slack", type=int, default=0,
                   help="collective launch-count growth tolerated (default "
                        "0: any increase fails)")
    d.add_argument("--wire_bytes_growth", type=float, default=1.05,
                   help="max growth of the modeled table-reduce ICI "
                        "bytes (collectives.table_reduce_bytes, schema "
                        "v9) before the diff fails — catches a "
                        "quantized wire silently re-widening to f32")
    d.add_argument("--bytes_ratio", type=float, default=1.05,
                   help="max collective payload-byte growth factor")
    d.add_argument("--signal_ratio", type=float, default=2.0,
                   help="max final signal-norm growth factor")
    d.add_argument("--overlap_drop", type=float, default=0.2,
                   help="max topk_overlap absolute drop")
    d.add_argument("--loss_ratio", type=float, default=1.05,
                   help="max final loss growth factor")
    d.add_argument("--mfu_drop", type=float, default=0.15,
                   help="max RELATIVE drop of the final mfu (0.15 = "
                        "15%% slower per peak-FLOP fails)")
    d.add_argument("--input_wait_rise", dest="input_wait_rise",
                   type=float, default=0.10,
                   help="max ABSOLUTE rise of the final input_wait_frac "
                        "(the round-pipeline starvation gate). "
                        "dryrun_multichip wires the default against its "
                        "pipelined-vs-inline streams. (--starvation_rise "
                        "was an alias of this flag before schema v10; it "
                        "now gates LAYER starvation — see below)")
    d.add_argument("--starvation_rise", type=float, default=0.15,
                   help="max ABSOLUTE rise of the layer-starvation gap "
                        "(schema-v10 layer_signals streams: max over "
                        "groups above the grad-mass floor of mass share "
                        "minus k-win share, from the final record) — a "
                        "group losing the top-k race it used to win. "
                        "Pre-v10 this spelling aliased "
                        "--input_wait_rise; the input-wait gate keeps "
                        "its primary spelling")
    d.add_argument("--staleness_rise", type=float, default=1.0,
                   help="max ABSOLUTE rise of the final async_round "
                        "staleness_mean (async buffered-aggregation "
                        "runs; commits-stale units)")
    d.add_argument("--temp_bytes_growth", type=float, default=1.10,
                   help="max growth factor of a watched executable's "
                        "memory_ledger temp bytes (schema-v6 streams; "
                        "the de-fusion/re-materialization regression "
                        "class)")
    d.add_argument("--bw_frac_drop", type=float, default=0.10,
                   help="max ABSOLUTE drop of the final utilization "
                        "bw_frac (achieved HBM bandwidth as a fraction "
                        "of peak; schema-v6 streams)")
    d.add_argument("--perchip_drop", type=float, default=0.30,
                   help="fail if the last bench event's "
                        "per_chip_items_per_s drops more than this "
                        "relative fraction vs baseline (the weak-"
                        "scaling gate; scripts/scaling_curves.py "
                        "passes its own threshold for virtual-device "
                        "dryruns)")
    d.add_argument("--clip_frac_rise", type=float, default=0.25,
                   help="max ABSOLUTE rise of the final defense "
                        "clip_frac (schema-v5 defense streams)")
    d.add_argument("--quarantine_growth", type=int, default=0,
                   help="quarantined+ejected client-count growth "
                        "tolerated (default 0: any new benched/ejected "
                        "client fails)")
    d.add_argument("--client_spread_ratio", type=float, default=2.0,
                   help="max growth factor of the final per-client loss "
                        "spread (p95-p5) — population divergence")
    d.add_argument("--alert_slack", type=int, default=0,
                   help="critical-alert count growth tolerated (default "
                        "0: any new critical alert fails)")
    d.add_argument("--coverage_stall", type=float, default=0.05,
                   help="max ABSOLUTE drop of the final population "
                        "coverage (schema-v11 population streams); the "
                        "diff also fails when the candidate stream ends "
                        "in a >= COVERAGE_STALL_WINDOW-record distinct-"
                        "coverage stall the baseline does not show — "
                        "the sampler-reach regression gate")
    al = sub.add_parser("alerts", help="postmortem alert triage "
                                       "(exit 1 on critical)")
    al.add_argument("path")
    cl = sub.add_parser("clients",
                        help="per-client population trends from the "
                             "client_stats stream")
    cl.add_argument("path")
    po = sub.add_parser("population",
                        help="population-scale participation report "
                             "from the schema-v11 population stream "
                             "(sketch-estimated or exact)")
    po.add_argument("path")
    tr = sub.add_parser("trend",
                        help="benchmark trajectory across BENCH_r*.json "
                             "checkpoints (img/s, mfu, gpt2 tok/s, wire "
                             "bytes, warmup; vintage-tolerant)")
    tr.add_argument("path", nargs="?", default=".",
                    help="directory holding BENCH_*.json (or a glob); "
                         "default: current directory")
    ly = sub.add_parser("layers",
                        help="layer-wise compression attribution table "
                             "and per-group win-share trend from the "
                             "schema-v10 layer_signals stream")
    ly.add_argument("path")
    de = sub.add_parser("defense",
                        help="robustness report from the schema-v5 "
                             "defense stream (exit 1 on ejections)")
    de.add_argument("path")
    me = sub.add_parser("memory",
                        help="per-executable byte inventory, residency "
                             "timeline and roofline table from the "
                             "schema-v6 memory/memory_ledger/"
                             "utilization streams")
    me.add_argument("path")
    t = sub.add_parser("timeline",
                       help="render the span stream into a perfetto/"
                            "chrome-tracing trace.json")
    t.add_argument("path")
    t.add_argument("-o", "--out", default="trace.json",
                   help="output trace file (default: trace.json)")
    args = ap.parse_args(argv)
    if args.cmd == "summarize":
        summarize(load_events(args.path), label=args.path)
        return 0
    if args.cmd == "alerts":
        return alerts(load_events(args.path))
    if args.cmd == "clients":
        return clients(load_events(args.path))
    if args.cmd == "population":
        return population(load_events(args.path))
    if args.cmd == "trend":
        return trend(args.path)
    if args.cmd == "layers":
        return layers(load_events(args.path))
    if args.cmd == "defense":
        return defense(load_events(args.path))
    if args.cmd == "memory":
        return memory(load_events(args.path))
    if args.cmd == "timeline":
        return timeline(load_events(args.path), args.out)
    if args.cmd == "diff":
        a, b = load_events(args.baseline), load_events(args.candidate)
        summarize(a, label=f"A (baseline) {args.baseline}")
        summarize(b, label=f"B (candidate) {args.candidate}")
        problems = diff(a, b, args)
        if problems:
            print("== REGRESSIONS")
            for p in problems:
                print(f"   {p}")
            return 1
        print("== no regressions beyond thresholds")
        return 0
    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
