#!/usr/bin/env python
"""Round-shape tuning grid for the CIFAR flagship sketch round (VERDICT
r4 weak #3 / next-round #8): MFU and throughput over a
(clients-per-round W x local-batch B) grid with the same machinery as
bench.py, so the batch-starved 18.7%-MFU parity headline gets a
shape-vs-MFU story instead of a caveat sentence.

Prints a table + one JSON line; the committed narrative lives in
runs/ROUND_SHAPE.md.

Usage: python scripts/round_shape_grid.py
"""

from __future__ import annotations

import itertools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def measure(W: int, B: int, n_rounds: int = 10):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench_common import peak_flops, timed_rounds
    from commefficient_tpu import models
    from commefficient_tpu.config import FedConfig, enable_compilation_cache
    from commefficient_tpu.core import FedRuntime
    from commefficient_tpu.losses import make_cv_loss

    cfg = FedConfig(
        mode="sketch", error_type="virtual", local_momentum=0.0,
        virtual_momentum=0.9, weight_decay=5e-4,
        num_workers=W, local_batch_size=B,
        k=50_000, num_rows=5, num_cols=500_000, num_blocks=20,
        num_clients=max(100, W), track_bytes=False, approx_topk=True)
    enable_compilation_cache(cfg)
    model = models.ResNet9(num_classes=10)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.ones((1, 32, 32, 3), jnp.float32))
    loss_fn = make_cv_loss(model, "bfloat16")
    runtime = FedRuntime(cfg, params, loss_fn, num_clients=cfg.num_clients)
    rng = np.random.RandomState(0)
    batch = {"image": jnp.asarray(rng.randn(W, B, 32, 32, 3), jnp.float32),
             "target": jnp.asarray(rng.randint(0, 10, (W, B)), jnp.int32)}
    args = (jnp.arange(W, dtype=jnp.int32), batch, jnp.ones((W, B), bool),
            0.1)
    dt, _, _ = timed_rounds(runtime, args, warmup=2, rounds=n_rounds,
                            desc=f"W{W}xB{B}")
    ips = n_rounds * W * B / dt
    peak = peak_flops(jax.devices()[0])
    return ips, peak, runtime, params, loss_fn, batch


def flops_per_image():
    """One XLA cost analysis of the bare value_and_grad (per image)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from commefficient_tpu import models
    from commefficient_tpu.losses import make_cv_loss

    model = models.ResNet9(num_classes=10)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.ones((1, 32, 32, 3), jnp.float32))
    loss_fn = make_cv_loss(model, "bfloat16")
    N = 512
    rng = np.random.RandomState(0)
    batch = {"image": jnp.asarray(rng.randn(N, 32, 32, 3), jnp.float32),
             "target": jnp.asarray(rng.randint(0, 10, (N,)), jnp.int32)}
    mask = jnp.ones((N,), bool)
    g = jax.jit(jax.value_and_grad(lambda p: loss_fn(p, batch, mask)[0]))
    cost = g.lower(params).compile().cost_analysis()
    if isinstance(cost, list):
        cost = cost[0]
    return float(cost["flops"]) / N


def main():
    fpi = flops_per_image()
    print(f"model FLOPs/image {fpi:.3e}", flush=True)
    rows = []
    for W, B in itertools.product((8, 16, 32), (64, 256, 512)):
        try:
            ips, peak, *_ = measure(W, B)
        except Exception as e:  # OOM at the big corner etc.
            print(f"W={W:3d} B={B:4d}: FAILED ({type(e).__name__})",
                  flush=True)
            rows.append({"W": W, "B": B, "error": type(e).__name__})
            continue
        mfu = ips * fpi / peak
        print(f"W={W:3d} B={B:4d} round={W*B:6d} img: "
              f"{ips:9.0f} img/s  MFU {mfu:6.1%}", flush=True)
        rows.append({"W": W, "B": B, "img_per_s": round(ips),
                     "mfu": round(mfu, 4)})
    print(json.dumps({"metric": "cifar_round_shape_grid", "rows": rows,
                      "flops_per_image": fpi}))


if __name__ == "__main__":
    main()
