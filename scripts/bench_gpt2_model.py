#!/usr/bin/env python
"""Isolated GPT-2 MODEL throughput (no federation): vmap-8-clients,
microbatched value_and_grad — the round's compute core, measured alone so
the federated overhead and the model ceiling can be attributed separately
(VERDICT r3 items 2-3).

Variants: remat on/off x attention dense/flash. Round-3 finding: with
dense attention, remat=False cannot even compile at this scale (the
(B, H, S, S) logits tensors of 12 layers x 8 microbatches overflow HBM);
flash attention removes those tensors, which is what makes the no-remat
(no-recompute) configuration reachable at all.

Timing is CHAINED on-device (lax.scan over grad steps, each step's params
perturbed by the previous gradient) — the only methodology the axon
tunnel's noisy transfers don't poison. MFU uses the same analytic FLOP
model as bench_gpt2.py (cost_analysis undercounts scanned bodies).

Usage: python scripts/bench_gpt2_model.py [reps=6]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    reps = int(sys.argv[1]) if len(sys.argv) > 1 else 6
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench_gpt2 import gpt2_model_flops
    from bench_common import peak_flops
    from commefficient_tpu.config import FedConfig, enable_compilation_cache
    from commefficient_tpu.core.client import make_forward_grad
    from commefficient_tpu.losses import make_gpt2_train_loss
    from commefficient_tpu.models.gpt2 import (GPT2Config, GPT2DoubleHeads,
                                               resolve_attn)
    from commefficient_tpu.ops import ravel_params

    W, B, NC, S = 8, 8, 2, 256
    rng = np.random.RandomState(0)
    batch = {
        "input_ids": jnp.asarray(
            rng.randint(0, 50257, (W, B, NC, S)), jnp.int32),
        "mc_token_ids": jnp.asarray(rng.randint(0, S, (W, B, NC)), jnp.int32),
        "lm_labels": jnp.asarray(
            rng.randint(0, 50257, (W, B, NC, S)), jnp.int32),
        "mc_label": jnp.asarray(rng.randint(0, NC, (W, B)), jnp.int32),
        "token_type_ids": jnp.asarray(
            rng.randint(0, 2, (W, B, NC, S)), jnp.int32),
    }
    mask = jnp.ones((W, B), bool)
    peak = peak_flops(jax.devices()[0])
    enable_compilation_cache(FedConfig())

    for label, remat, attn in (
            ("remat + dense (r3 baseline)", True, "dense"),
            ("remat + flash", True, "flash"),
            ("NO remat + flash", False, "flash"),
            ("NO remat + dense (expected OOM)", False, "dense")):
        gcfg = GPT2Config(remat=remat)
        model = GPT2DoubleHeads(gcfg, attn_impl=resolve_attn(attn))
        params = model.init(jax.random.PRNGKey(0), batch["input_ids"][0, :1],
                            batch["mc_token_ids"][0, :1],
                            batch["token_type_ids"][0, :1])
        vec, unravel = ravel_params(params)
        cfg = FedConfig(mode="uncompressed", error_type="none",
                        local_momentum=0.0, virtual_momentum=0.9,
                        weight_decay=0.0, num_workers=W, local_batch_size=B,
                        microbatch_size=8, num_clients=100,
                        track_bytes=False, num_results_train=2, lm_chunk=128)
        fwd = make_forward_grad(
            cfg, make_gpt2_train_loss(model, lm_chunk=cfg.lm_chunk),
            unravel, B)
        vfwd = jax.vmap(fwd, in_axes=(None, 0, 0, 0))
        rngs = jax.random.split(jax.random.PRNGKey(1), W)

        def chain(p, n):
            def body(carry, _):
                g, res, nv, _ = vfwd(carry, batch, mask, rngs)
                # serialize: next step's params depend on this gradient
                return carry - 1e-12 * g.sum(axis=0), res[0].mean()
            p_out, losses = jax.lax.scan(body, p, None, length=n)
            return p_out[0] + losses[-1]

        run = jax.jit(chain, static_argnums=1)
        try:
            t0 = time.time()
            float(run(vec, 1))       # compile the body + 1 step
            compile_s = time.time() - t0
            float(run(vec, reps))    # warmup: n=reps is its own program
            t0 = time.time()
            float(run(vec, reps))    # steady-state chained timing
            dt = (time.time() - t0) / reps
        except Exception as e:
            print(f"{label:34s}: FAILED {type(e).__name__}: "
                  f"{str(e).splitlines()[0][:90]}")
            continue
        toks = W * B * NC * S
        flops = gpt2_model_flops(gcfg, toks, S)
        mfu = flops / dt / peak
        print(f"{label:34s}: {dt * 1e3:7.1f} ms/step  "
              f"{toks / dt:9.0f} tok/s  MFU {mfu:.3f}  "
              f"(compile {compile_s:.0f}s)", flush=True)


if __name__ == "__main__":
    main()
