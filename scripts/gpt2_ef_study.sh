#!/usr/bin/env bash
# Round-5 sketch-space stabilization study (VERDICT r4 next-round #1):
# subtractive error feedback on the gpt2_conv regime, clipped and
# unclipped arms. Same corpus/recipe as scripts/gpt2_convergence.sh.
set -euo pipefail
cd "$(dirname "$0")/.."
OUT=runs/gpt2_conv
mkdir -p "$OUT"
[ -f "$OUT/data/personachat_self_original.json" ] || \
    python scripts/make_persona_corpus.py "$OUT/data"

COMMON=(--num_epochs 24 --num_workers 8 --local_batch_size 8
        --microbatch_size 8 --max_seq_len 64 --valid_batch_size 64
        --weight_decay 0 --local_momentum 0 --virtual_momentum 0.9
        --eval_before_start --dataset_dir "$OUT/data" --seed 21)

run() {
    local name=$1; shift
    echo "=== $name ==="
    python gpt2_train.py "$@" "${COMMON[@]}" 2>&1 | tee "$OUT/$name.log"
    python scripts/gpt2log2tsv.py "$OUT/$name.log" "$OUT/$name.tsv"
}

for arm in "$@"; do
  case "$arm" in
    sub_clip1) run gpt2_sketch24_sub_clip1 --mode sketch --error_type virtual \
        --num_cols 524288 --num_rows 5 --k 50000 --approx_topk \
        --sketch_ef subtract --max_grad_norm 1 ;;
    sub) run gpt2_sketch24_sub --mode sketch --error_type virtual \
        --num_cols 524288 --num_rows 5 --k 50000 --approx_topk \
        --sketch_ef subtract ;;
    sub_clip1_k200k) run gpt2_sketch24_sub_clip1_k200k --mode sketch \
        --error_type virtual --num_cols 524288 --num_rows 5 --k 200000 \
        --approx_topk --sketch_ef subtract --max_grad_norm 1 ;;
    clip1_decay95) run gpt2_sketch24_clip1_decay95 --mode sketch \
        --error_type virtual --num_cols 524288 --num_rows 5 --k 50000 \
        --approx_topk --max_grad_norm 1 --error_decay 0.95 ;;
    sub_clip1_decay90) run gpt2_sketch24_sub_clip1_decay90 --mode sketch \
        --error_type virtual --num_cols 524288 --num_rows 5 --k 50000 \
        --approx_topk --sketch_ef subtract --max_grad_norm 1 \
        --error_decay 0.90 ;;
    clip1_k200k) run gpt2_sketch24_clip1_k200k --mode sketch \
        --error_type virtual --num_cols 524288 --num_rows 5 --k 200000 \
        --approx_topk --max_grad_norm 1 ;;
    sub_clip1_r9) run gpt2_sketch24_sub_clip1_r9 --mode sketch \
        --error_type virtual --num_cols 524288 --num_rows 9 --k 50000 \
        --approx_topk --sketch_ef subtract --max_grad_norm 1 ;;
    densestate_clip1) run gpt2_sketch24_densestate_clip1 --mode sketch \
        --error_type virtual --num_cols 524288 --num_rows 5 --k 50000 \
        --approx_topk --sketch_server_state dense \
        --sketch_dense_clip --max_grad_norm 1 ;;
    densestate) run gpt2_sketch24_densestate --mode sketch \
        --error_type virtual --num_cols 524288 --num_rows 5 --k 50000 \
        --approx_topk --sketch_server_state dense ;;
    sub_clip1_c1p8m) run gpt2_sketch24_sub_clip1_c1p8m --mode sketch \
        --error_type virtual --num_cols 1835008 --num_rows 5 --k 50000 \
        --approx_topk --sketch_ef subtract --max_grad_norm 1 ;;
    clip1_c1p8m) run gpt2_sketch24_clip1_c1p8m --mode sketch \
        --error_type virtual --num_cols 1835008 --num_rows 5 --k 50000 \
        --approx_topk --max_grad_norm 1 ;;
    clip1_c4m) run gpt2_sketch24_clip1_c4m --mode sketch \
        --error_type virtual --num_cols 4194304 --num_rows 5 --k 50000 \
        --approx_topk --max_grad_norm 1 ;;
    clip1_c8m) run gpt2_sketch24_clip1_c8m --mode sketch \
        --error_type virtual --num_cols 8388608 --num_rows 5 --k 50000 \
        --approx_topk --max_grad_norm 1 ;;
    clip1_r9) run gpt2_sketch24_clip1_r9 --mode sketch \
        --error_type virtual --num_cols 524288 --num_rows 9 --k 50000 \
        --approx_topk --max_grad_norm 1 ;;
    clip1_r2_c4p6m) run gpt2_sketch24_clip1_r2_c4p6m --mode sketch \
        --error_type virtual --num_cols 4603904 --num_rows 2 --k 50000 \
        --approx_topk --max_grad_norm 1 ;;
    warmup) run gpt2_sketch24_warmup --mode sketch \
        --error_type virtual --num_cols 524288 --num_rows 5 --k 50000 \
        --approx_topk --lr_warmup --pivot_epoch 3 ;;
    uncompressed_warmup) run gpt2_uncompressed24_warmup \
        --mode uncompressed --error_type none --lr_warmup --pivot_epoch 3 ;;
    densestate_clip1_decay95) run gpt2_sketch24_densestate_clip1_decay95 \
        --mode sketch --error_type virtual --num_cols 524288 --num_rows 5 \
        --k 50000 --approx_topk --sketch_server_state dense \
        --sketch_dense_clip --max_grad_norm 1 --error_decay 0.95 ;;
    *) echo "unknown arm $arm"; exit 1 ;;
  esac
done
echo STUDY_DONE
