#!/usr/bin/env bash
# FixupResNet50 / federated ImageNet recipe — the reference's only tuned
# large-scale config (imagenet.sh:2-21), with its stale flags (--mixup,
# --supervised) dropped and the process-placement flags replaced by the TPU
# mesh. Expects the ImageNet train/ tree (or synthetic fallback) under
# $DATASET_DIR.
set -e
DATASET_DIR=${DATASET_DIR:-./dataset/imagenet}
MESH=${MESH:-8}

python cv_train.py \
    --dataset_name ImageNet \
    --model FixupResNet50 \
    --mode uncompressed \
    --error_type virtual \
    --virtual_momentum 0.9 \
    --local_momentum 0 \
    --weight_decay 1e-4 \
    --num_epochs 24 \
    --pivot_epoch 2 \
    --lr_scale 0.4 \
    --num_workers 7 \
    --num_clients 7 \
    --iid \
    --local_batch_size 64 \
    --valid_batch_size 64 \
    --dataset_dir "$DATASET_DIR" \
    --mesh_shape "$MESH" \
    --checkpoint --checkpoint_every 1 \
    "$@"
