#!/usr/bin/env python
"""GPT-2 round MFU sweep: remat policy x microbatch x lm_chunk.

The committed sweep behind VERDICT round-5 "Next round" item 4: the
flagship GPT-2 sketched round sits at 33% MFU (BENCH_r05, flat since
r04), and runs/BREAKDOWN_gpt2.md attributes the gap to the model side —
the bare fwd+bwd at the same config measures ~31% MFU under full remat
(scripts/bench_gpt2_model.py), so the target MFU >= 0.40 is reachable
ONLY by cutting backward recompute (remat policy) or reshaping the
microbatch scan, not by shaving the ~75 ms of federated slices. The two
endpoints are already measured and committed:

- remat=False: compiles post-fused-clients but is SLOWER (69.3k vs
  76.5k tok/s) — saved-activation HBM traffic beats the recompute FLOPs;
- dots_with_no_batch_dims_saveable: catastrophic under the fused round
  (3.1k tok/s, r4) — excluded from the default arm set on purpose.

What was NEVER measured is the middle ground this sweep covers:
``dots_saveable`` (save matmul outputs, recompute elementwise),
microbatch 2/4 (smaller live set => more savable activations per step),
and the chunked-CE granularity 64/256 (chunk loop count vs live logits).
Each arm is one `bench_gpt2.run(...)` — same round, same analytic-FLOPs
MFU definition, retry-wrapped — and lands as one JSON line in the
output file as it finishes (a dead arm costs itself, not the sweep).

Run on the TPU runtime (each arm recompiles; the persistent compile
cache makes repeats cheap):

    python scripts/gpt2_mfu_sweep.py --out runs/gpt2_mfu_sweep.jsonl
    python scripts/gpt2_mfu_sweep.py --arms base,mb4,policy_dots

The verdict rule the sweep encodes: if no arm reaches MFU >= 0.40, the
best arm + the committed endpoint measurements above constitute the
trace-level ceiling proof (the remat recompute is the floor, and every
policy between full remat and none loses more to HBM traffic than it
saves in FLOPs) — recorded in runs/BREAKDOWN_gpt2.md either way.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# arm name -> bench_gpt2.run keyword overrides (base = shipping config:
# full remat, microbatch 8, lm_chunk 128)
ARMS = {
    "base": {},
    # the PR-9 A/B: base now runs the FUSED sketch encode (the
    # microbatch scan carries the table; --sketch_fused_encode auto);
    # this arm forces the pre-fusion round whose ledger documents the
    # dense (d,) gradient materialization — the temp_bytes delta
    # between the two is the committed proof the floor moved
    # (runs/BREAKDOWN_gpt2.md §Round 7)
    "unfused_encode": {"fused_encode": "off"},
    # split-round arms (--decode_overlap): the decode of round t runs
    # while round t+1 stages, and the COHORT executable's ledger
    # isolates the client block — the granularity where the fused
    # encode's temp drop is measurable at all (the monolithic round's
    # peak is shared with the server decode's own dense buffers)
    "overlap": {"decode_overlap": True},
    "overlap_unfused": {"decode_overlap": True, "fused_encode": "off"},
    "no_remat": {"remat": False},
    "policy_dots": {"remat_policy": "dots_saveable"},
    "mb4": {"microbatch": 4},
    "mb2": {"microbatch": 2},
    "chunk64": {"lm_chunk": 64},
    "chunk256": {"lm_chunk": 256},
    "mb4_chunk256": {"microbatch": 4, "lm_chunk": 256},
    "policy_dots_mb4": {"remat_policy": "dots_saveable", "microbatch": 4},
    # the measured-catastrophic policy (3.1k tok/s at r4) — opt-in only,
    # kept so the endpoint stays reproducible: --arms +policy_nobatch
    "policy_nobatch": {"remat_policy": "dots_with_no_batch_dims_saveable"},
}
DEFAULT_ARMS = [a for a in ARMS if a != "policy_nobatch"]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="runs/gpt2_mfu_sweep.jsonl",
                    help="JSONL output, one line per arm as it finishes")
    ap.add_argument("--arms", default="",
                    help="comma-separated arm names (default: all except "
                         "policy_nobatch); prefix an arm with + to ADD it "
                         "to the default set")
    ap.add_argument("--rounds", type=int, default=8,
                    help="timed rounds per arm")
    ap.add_argument("--compile_cache", default=None,
                    help="persistent XLA compile cache DIR (unset: the "
                         "config default — strongly recommended, every "
                         "arm recompiles the round; empty string "
                         "disables)")
    ap.add_argument("--dryrun", action="store_true",
                    help="run every arm at smoke scale (GPT2Config.small"
                         ", tiny round) so the sweep completes on the "
                         "CPU container: exercises the sweep mechanics "
                         "and records live roofline/memory-ledger "
                         "fields per arm, but the throughput numbers "
                         "are NOT the flagship measurement — each line "
                         "carries dryrun: true")
    ap.add_argument("--ledger_ab", action="store_true",
                    help="append the compile-only fused-vs-unfused "
                         "cohort-ledger A/B at a parameter-dominated "
                         "GPT-2 geometry (bench_gpt2.ledger_ab) — the "
                         "committed dense-gradient-floor proof for "
                         "runs/BREAKDOWN_gpt2.md §Round 7; honors "
                         "--dryrun")
    args = ap.parse_args(argv)

    import bench_gpt2
    from bench_common import log

    names = list(DEFAULT_ARMS)
    if args.arms:
        adds = [a[1:] for a in args.arms.split(",") if a.startswith("+")]
        picks = [a for a in args.arms.split(",") if not a.startswith("+")]
        if picks:
            names = picks
        names += [a for a in adds if a not in names]
    unknown = [a for a in names if a not in ARMS]
    if unknown:
        ap.error(f"unknown arms {unknown}; known: {sorted(ARMS)}")

    os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
    results = []
    with open(args.out, "a") as f:
        for name in names:
            log(f"=== arm {name}: {ARMS[name] or 'shipping config'}")
            rec = {"arm": name, **{"overrides": ARMS[name]}}
            if args.dryrun:
                rec["dryrun"] = True
            try:
                rec["result"] = bench_gpt2.run(
                    n_rounds=args.rounds, dryrun=args.dryrun,
                    compile_cache=args.compile_cache, **ARMS[name])
            except Exception as e:
                log(traceback.format_exc())
                rec["error"] = f"{type(e).__name__}: {e}"
            # one fsync'd line per arm: a crash mid-sweep keeps every
            # finished measurement (the bench resilience contract)
            f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())
            results.append(rec)
        if args.ledger_ab:
            log("=== ledger_ab: compile-only fused-vs-unfused cohort "
                "ledgers (parameter-dominated geometry)")
            rec = {"arm": "ledger_ab"}
            if args.dryrun:
                rec["dryrun"] = True
            try:
                rec["result"] = bench_gpt2.ledger_ab(dryrun=args.dryrun)
            except Exception as e:
                log(traceback.format_exc())
                rec["error"] = f"{type(e).__name__}: {e}"
            f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())

    ok = [r for r in results if r.get("result", {}).get("mfu") is not None]
    if ok:
        best = max(ok, key=lambda r: r["result"]["mfu"])
        print(json.dumps({
            "metric": "gpt2_mfu_sweep_best",
            "arm": best["arm"],
            "mfu": best["result"]["mfu"],
            "tok_per_s": best["result"]["value"],
            "target_0.40_met": best["result"]["mfu"] >= 0.40,
            "arms_run": len(results),
        }))
        return 0
    print(json.dumps({"metric": "gpt2_mfu_sweep_best", "error":
                      "no arm produced an MFU", "arms_run": len(results)}))
    return 1


if __name__ == "__main__":
    sys.exit(main())
