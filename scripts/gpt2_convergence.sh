#!/usr/bin/env bash
# GPT-2 federated convergence artifact (VERDICT r3 item 1): from-scratch
# GPT-2 (12L/768, vocab = offline HashTokenizer) on the structured
# synthetic PersonaChat corpus (scripts/make_persona_corpus.py — real
# personachat_self_original.json format, 256 personality clients), three
# complete 24-epoch runs on one TPU chip: flagship sketch (5x524288,
# k=50k, d=92.1M — 35x compression) vs true_topk vs uncompressed.
# Reference lineage: gpt2_train.py:115-149 (train loop), 55-86 (eval).
set -euo pipefail
cd "$(dirname "$0")/.."
OUT=runs/gpt2_conv
mkdir -p "$OUT"
[ -f "$OUT/data/personachat_self_original.json" ] || \
    python scripts/make_persona_corpus.py "$OUT/data"

COMMON=(--num_epochs 24 --num_workers 8 --local_batch_size 8
        --microbatch_size 8 --max_seq_len 64 --valid_batch_size 64
        --weight_decay 0 --local_momentum 0 --virtual_momentum 0.9
        --eval_before_start --dataset_dir "$OUT/data" --seed 21)

run() {
    local name=$1; shift
    echo "=== $name ==="
    python gpt2_train.py "$@" "${COMMON[@]}" 2>&1 | tee "$OUT/$name.log"
    # per-epoch TSV artifact: epoch, hours, test NLL, ppl, MC accuracy
    python scripts/gpt2log2tsv.py "$OUT/$name.log" "$OUT/$name.tsv"
}

run gpt2_sketch24 --mode sketch --error_type virtual \
    --num_cols 524288 --num_rows 5 --k 50000 --approx_topk
run gpt2_true_topk24 --mode true_topk --error_type virtual \
    --k 50000 --approx_topk
run gpt2_uncompressed24 --mode uncompressed --error_type none
echo "ALL DONE"
