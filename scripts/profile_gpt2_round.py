#!/usr/bin/env python
"""Attribute the flagship GPT-2 federated round's time op-by-op.

VERDICT r3 item 2: the round measured ~495 ms of which ~430 was model and
~65 federated overhead, with encode 26 + decode 21 + topk ~10 accounted
and ~50 ms UNATTRIBUTED by component ablation. This script captures a
real device trace of the round (jax.profiler) and aggregates per-op time
from the xplane proto, so every >=1 ms slice gets a name — the committed
breakdown lives in runs/profile_gpt2/BREAKDOWN.md.

Usage: python scripts/profile_gpt2_round.py [outdir=runs/profile_gpt2]
"""

from __future__ import annotations

import collections
import glob
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_round():
    """Flagship bench config (bench_gpt2.py): 124M GPT-2, 8x8x2x256 round,
    sketch 5x524288, microbatch 8, chunked CE, remat."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from commefficient_tpu.config import FedConfig, enable_compilation_cache
    from commefficient_tpu.core import FedRuntime
    from commefficient_tpu.losses import make_gpt2_train_loss
    from commefficient_tpu.models.gpt2 import GPT2Config, GPT2DoubleHeads

    gcfg = GPT2Config(remat=True)
    model = GPT2DoubleHeads(gcfg)
    W, B, NC, S = 8, 8, 2, 256
    rng = np.random.RandomState(0)
    batch = {
        "input_ids": jnp.asarray(
            rng.randint(0, 50257, (W, B, NC, S)), jnp.int32),
        "mc_token_ids": jnp.asarray(rng.randint(0, S, (W, B, NC)), jnp.int32),
        "lm_labels": jnp.asarray(
            rng.randint(0, 50257, (W, B, NC, S)), jnp.int32),
        "mc_label": jnp.asarray(rng.randint(0, NC, (W, B)), jnp.int32),
        "token_type_ids": jnp.asarray(
            rng.randint(0, 2, (W, B, NC, S)), jnp.int32),
    }
    params = model.init(jax.random.PRNGKey(0), batch["input_ids"][0, :1],
                        batch["mc_token_ids"][0, :1],
                        batch["token_type_ids"][0, :1])
    cfg = FedConfig(mode="sketch", error_type="virtual", local_momentum=0.0,
                    virtual_momentum=0.9, weight_decay=0.0,
                    num_workers=W, local_batch_size=B, microbatch_size=8,
                    k=50_000, num_rows=5, num_cols=524_288, num_blocks=20,
                    num_clients=100, track_bytes=False, approx_topk=True,
                    num_results_train=2, lm_chunk=128)
    enable_compilation_cache(cfg)
    runtime = FedRuntime(cfg, params,
                         make_gpt2_train_loss(model, lm_chunk=cfg.lm_chunk),
                         num_clients=cfg.num_clients)
    args = (jnp.arange(W, dtype=jnp.int32), batch,
            jnp.ones((W, B), bool), 0.1)
    return runtime, args


def parse_xplane(outdir: str):
    """Aggregate device-side op durations from the newest xplane.pb.
    Returns [(name, total_ms)] sorted descending, plus the wall span."""
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except ImportError:   # older TF ships it under tensorflow.core
        from tensorflow.core.profiler.protobuf import xplane_pb2

    files = sorted(glob.glob(os.path.join(
        outdir, "plugins/profile/*/*.xplane.pb")), key=os.path.getmtime)
    if not files:
        return None, 0.0
    xspace = xplane_pb2.XSpace()
    with open(files[-1], "rb") as f:
        xspace.ParseFromString(f.read())
    per_op = collections.Counter()
    span = 0.0
    for plane in xspace.planes:
        # device planes: "/device:TPU:0" / "TPU:0" — skip host threads
        if "TPU" not in plane.name and "device" not in plane.name.lower():
            continue
        ev_meta = {m.id: m.name for m in plane.event_metadata.values()}
        for line in plane.lines:
            t0, t1 = None, None
            for ev in line.events:
                name = ev_meta.get(ev.metadata_id, "?")
                dur = ev.duration_ps / 1e9  # ms
                per_op[name] += dur
                s = ev.offset_ps / 1e9
                t0 = s if t0 is None else min(t0, s)
                t1 = s + dur if t1 is None else max(t1, s + dur)
            if t0 is not None:
                span = max(span, t1 - t0)
    return per_op.most_common(), span


GROUPS = (
    # (label, name substrings) — first match wins; only UNAMBIGUOUS keys
    # (pallas kernel names, collective/top-k HLO opcodes, matmul opcodes).
    # Everything else lands in coarse buckets — the authoritative
    # attribution is the top-op list below, read against the op names'
    # jax scope metadata; generic substrings like "concatenate"/"sort"
    # appear all over the model's backward and must NOT be claimed by a
    # sketch/topk group.
    ("pallas decode kernel", ("decode_kernel", "pallas_decode")),
    ("topk/approx_max_k", ("approx-top-k", "partialreduce",
                           "partial-reduce")),
    ("collectives", ("all-reduce", "all-gather", "reduce-scatter",
                     "all-to-all")),
    ("matmul/MXU", ("dot", "convolution")),
    ("copies", ("copy",)),
    ("fusions (model + sketch elementwise)", ("fusion",)),
)


def group_of(name: str) -> str:
    low = name.lower()
    for label, keys in GROUPS:
        if any(k in low for k in keys):
            return label
    return "other"


def main():
    outdir = sys.argv[1] if len(sys.argv) > 1 else "runs/profile_gpt2"
    os.makedirs(outdir, exist_ok=True)
    import time

    import jax

    runtime, args = build_round()
    state = runtime.init_state()
    print("compiling + warmup...", flush=True)
    t0 = time.time()
    state, _ = runtime.round(state, *args)
    jax.block_until_ready(state.ps_weights)
    print(f"warmup {time.time() - t0:.1f}s", flush=True)

    t0 = time.time()
    with jax.profiler.trace(outdir):
        for _ in range(3):
            state, metrics = runtime.round(state, *args)
        jax.block_until_ready(state.ps_weights)
    wall = (time.time() - t0) / 3
    print(f"traced 3 rounds, {wall * 1e3:.1f} ms/round wall", flush=True)

    ops, span = parse_xplane(outdir)
    if ops is None:
        print("NO DEVICE TRACE CAPTURED (remote-backend limitation?) — "
              "fall back to component ablation timings")
        return
    total = sum(ms for _, ms in ops)
    print(f"\ndevice busy time {total / 3:.1f} ms/round "
          f"(span {span / 3:.1f} ms/round)\n")
    by_group = collections.Counter()
    for name, ms in ops:
        by_group[group_of(name)] += ms
    print(f"{'group':28s} {'ms/round':>9s}  share")
    for g, ms in by_group.most_common():
        print(f"{g:28s} {ms / 3:9.2f}  {ms / total:6.1%}")
    print(f"\ntop 40 ops (ms/round):")
    for name, ms in ops[:40]:
        print(f"  {ms / 3:8.2f}  {name[:110]}")


if __name__ == "__main__":
    main()
