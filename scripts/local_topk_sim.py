#!/usr/bin/env python
"""local_topk golden-case study (VERDICT r4 next-round #2).

Two jobs:

1. ``--check``: a straight numpy transcription of the REFERENCE's
   local_topk dynamics — client pipeline fed_worker.py:184-230 (g scaled
   by batch size, local momentum, local error accumulation, top-k with
   error feedback + momentum factor masking at the transmitted coords)
   and server rule fed_aggregator.py:544-566 (momentum accumulate onto
   the summed sparse top-k, no virtual error) — run trajectory-identical
   against THIS framework's FedRuntime on the same tiny problem. Any
   local_topk behavior measured on this stack is therefore the
   reference algorithm's behavior, not a port artifact.

2. ``--sweep``: a cheap CPU sweep of (k/d, lr, local_momentum,
   error_type) on a small least-squares problem to locate (or rule out)
   an operating regime where local_topk actually learns, before spending
   TPU budget on full CV runs. The mechanism under test: each client's
   error accumulator keeps the un-transmitted (1 - k/d) of every round's
   gradient; by the time those stale coordinates win the local top-k the
   weights have moved, so the transmitted mass is misaligned gradient —
   noise whose magnitude grows with lr and shrinks with k/d.
"""

from __future__ import annotations

import argparse

import numpy as np

D_FEAT = 24
NUM_CLIENTS = 10
W = 4
B = 8


def make_problem(seed=1):
    rng = np.random.RandomState(seed)
    w_true = rng.randn(D_FEAT).astype(np.float32)
    xs = rng.randn(NUM_CLIENTS, B, D_FEAT).astype(np.float32)
    ys = xs @ w_true + 0.01 * rng.randn(NUM_CLIENTS, B).astype(np.float32)
    return xs, ys


def topk(v, k):
    out = np.zeros_like(v)
    if k >= v.size:
        return v.copy()
    idx = np.argpartition(np.abs(v), -k)[-k:]
    out[idx] = v[idx]
    return out


def reference_local_topk(n_rounds, k, lr, local_momentum=0.0,
                         error_type="local", rho=0.9, seed=3,
                         w0_seed=0, loss_every=None):
    """Numpy transcription of the reference dynamics (see module doc).
    Returns (weight trajectory, loss history)."""
    rng = np.random.RandomState(w0_seed)
    # weight layout mirrors tests/test_core.py: ravel_pytree orders dict
    # keys alphabetically (b then w)
    w = np.concatenate([[0.0], rng.randn(D_FEAT)]).astype(np.float32)
    xs, ys = make_problem()
    round_rng = np.random.RandomState(seed)
    vels = np.zeros((NUM_CLIENTS, w.size), np.float32)
    errs = np.zeros((NUM_CLIENTS, w.size), np.float32)
    Vvel = np.zeros_like(w)
    traj, losses = [], []
    for _ in range(n_rounds):
        ids = round_rng.choice(NUM_CLIENTS, W, replace=False)
        agg = np.zeros_like(w)
        n_total = 0.0
        round_loss = 0.0
        for c in ids:
            x, y = xs[c], ys[c]
            pred = x @ w[1:] + w[0]
            err = pred - y
            round_loss += float((err ** 2).mean())
            gw = 2 * (x * err[:, None]).mean(0)
            gb = 2 * err.mean()
            g = np.concatenate([[gb], gw]).astype(np.float32)
            # fed_worker.py:190 — g scaled by the client's datum count
            g = g * B
            # fed_worker.py:193-200
            if local_momentum > 0:
                vels[c] = local_momentum * vels[c] + g
                base = vels[c]
            else:
                base = g
            if error_type == "local":
                errs[c] = errs[c] + base
                to_send = errs[c]
            else:
                to_send = base
            # fed_worker.py:204-216
            t = topk(to_send, k)
            nz = t != 0
            if error_type == "local":
                errs[c] = np.where(nz, 0.0, errs[c])
            if local_momentum > 0:
                vels[c] = np.where(nz, 0.0, vels[c])
            agg += t
            n_total += B
        agg /= n_total                      # fed_aggregator.py:332
        Vvel = agg + rho * Vvel             # fed_aggregator.py:544-566
        w = w - lr * Vvel
        traj.append(w.copy())
        losses.append(round_loss / W)
    return traj, losses


def check_against_runtime(n_rounds=6, k=5):
    """Trajectory identity vs FedRuntime (CPU, same seeds/data)."""
    import os
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import jax.numpy as jnp

    from commefficient_tpu.config import FedConfig
    from commefficient_tpu.core import FedRuntime

    def loss_fn(params, batch, mask):
        x, y = batch["x"], batch["y"]
        pred = x @ params["w"] + params["b"]
        m = mask.astype(jnp.float32)
        denom = jnp.maximum(m.sum(), 1.0)
        e = pred - y
        return ((e ** 2) * m).sum() / denom, \
            ((jnp.abs(e) * m).sum() / denom,)

    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(D_FEAT).astype(np.float32)),
              "b": jnp.zeros(())}
    xs, ys = make_problem()
    cfg = FedConfig(mode="local_topk", error_type="local",
                    local_momentum=0.0, virtual_momentum=0.9,
                    weight_decay=0.0, k=k, local_batch_size=B,
                    num_workers=W, num_clients=NUM_CLIENTS,
                    num_results_train=2, track_bytes=False)
    rt = FedRuntime(cfg, params, loss_fn, num_clients=NUM_CLIENTS)
    state = rt.init_state()
    round_rng = np.random.RandomState(3)
    ours = []
    for _ in range(n_rounds):
        ids = round_rng.choice(NUM_CLIENTS, W, replace=False).astype(np.int32)
        batch = {"x": jnp.asarray(xs[ids]), "y": jnp.asarray(ys[ids])}
        state, _ = rt.round(state, ids, batch, np.ones((W, B)), 0.05)
        ours.append(np.asarray(rt.flat_weights(state)))
    ref, _ = reference_local_topk(n_rounds, k=k, lr=0.05, seed=3)
    worst = max(float(np.abs(a - b).max()) for a, b in zip(ours, ref))
    print(f"trajectory identity over {n_rounds} rounds, k={k}: "
          f"max |delta| = {worst:.2e}")
    assert worst < 1e-4, "our local_topk does NOT match the reference sim"
    print("OK: framework local_topk == reference dynamics")


def sweep():
    d = D_FEAT + 1
    print(f"d={d}; final-vs-initial loss ratio after 120 rounds "
          "(<1 learns, >=1 fails); uncompressed anchor k=d")
    header = f"{'k/d':>6} {'lr':>6} {'mom':>4} {'err':>6} | ratio"
    print(header)
    for err in ("local", "none"):
        for mom in (0.0, 0.9):
            for kfrac in (1.0, 0.2, 0.08):
                k = max(1, int(kfrac * d))
                for lr in (0.1, 0.05, 0.02, 0.005):
                    _, losses = reference_local_topk(
                        120, k=k, lr=lr, local_momentum=mom,
                        error_type=err)
                    ratio = losses[-1] / losses[0]
                    print(f"{kfrac:>6} {lr:>6} {mom:>4} {err:>6} | "
                          f"{ratio:8.3f}"
                          + ("   LEARNS" if ratio < 0.5 else ""))


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--check", action="store_true")
    ap.add_argument("--sweep", action="store_true")
    a = ap.parse_args()
    if a.check:
        check_against_runtime()
    if a.sweep or not a.check:
        sweep()
