#!/usr/bin/env python
"""Generate a structured synthetic PersonaChat corpus in the REAL release
format (``personachat_self_original.json``: {"train": [...], "valid": [...]},
entries with "personality" + "utterances"/"history"/"candidates", gold last —
reference CommEfficient/data_utils/fed_persona.py:95-123 consumes exactly
this shape), sized for multi-hundred-round federated convergence runs.

The environment has no network, so the real 17,568-personality corpus can't
be downloaded; this stands in with a corpus that is *learnable*, not random:

- each personality draws a topic; its persona sentences and its gold replies
  share that topic's noun pool, while distractor candidates come from a
  different topic — so both the LM loss (topical word prediction) and the
  dialogue structure carry signal a model can descend on, and the
  sketched-vs-uncompressed gap is measured against a nontrivial objective;
- sentences come from a small template grammar over ~300 distinct words
  (near-injective under the offline HashTokenizer's 8192 crc32 buckets).

Usage: python scripts/make_persona_corpus.py OUT_DIR [--n_train 256]
           [--n_valid 32] [--seed 17]
"""

from __future__ import annotations

import argparse
import json
import os

import numpy as np

TOPIC_NOUNS = {
    "cooking": ["pasta", "bread", "soup", "spices", "recipes", "baking",
                "pancakes", "stew", "salad", "curry", "noodles", "pie"],
    "hiking": ["trails", "mountains", "forests", "boots", "summit", "maps",
               "rivers", "valleys", "campfires", "tents", "ridges", "peaks"],
    "music": ["guitar", "piano", "drums", "concerts", "melodies", "bands",
              "violin", "songs", "chords", "albums", "jazz", "opera"],
    "gardening": ["roses", "tomatoes", "soil", "seeds", "tulips", "herbs",
                  "compost", "orchids", "pumpkins", "ferns", "ivy", "moss"],
    "astronomy": ["stars", "planets", "telescopes", "comets", "galaxies",
                  "nebulae", "orbits", "moons", "eclipses", "meteors",
                  "constellations", "satellites"],
    "painting": ["canvas", "brushes", "watercolors", "portraits", "easels",
                 "sketches", "murals", "pigments", "landscapes", "ink",
                 "charcoal", "frames"],
    "fishing": ["trout", "rods", "lakes", "bait", "salmon", "reels",
                "docks", "lures", "ponds", "bass", "nets", "streams"],
    "chess": ["openings", "endgames", "knights", "bishops", "gambits",
              "tournaments", "checkmate", "pawns", "rooks", "tactics",
              "puzzles", "clocks"],
    "cycling": ["wheels", "pedals", "helmets", "races", "gears", "roads",
                "sprints", "tires", "descents", "climbs", "routes",
                "saddles"],
    "pottery": ["clay", "glazes", "kilns", "bowls", "vases", "wheels",
                "mugs", "plates", "sculptures", "slips", "molds", "tiles"],
    "sailing": ["sails", "knots", "harbors", "winds", "anchors", "decks",
                "masts", "tides", "buoys", "regattas", "hulls", "charts"],
    "baking": ["cookies", "cakes", "muffins", "dough", "frosting", "ovens",
               "croissants", "tarts", "scones", "yeast", "sugar", "flour"],
    "photography": ["cameras", "lenses", "portraits", "sunsets", "film",
                    "tripods", "shadows", "exposures", "prints", "studios",
                    "flashes", "angles"],
    "skiing": ["slopes", "powder", "lifts", "lodges", "moguls", "poles",
               "goggles", "glaciers", "chalets", "bindings", "runs",
               "drifts"],
    "birdwatching": ["owls", "herons", "finches", "binoculars", "nests",
                     "warblers", "hawks", "feathers", "migrations",
                     "sparrows", "cranes", "eagles"],
    "woodworking": ["oak", "chisels", "joints", "planes", "sawdust",
                    "lathes", "walnut", "cabinets", "dovetails", "maple",
                    "benches", "carvings"],
}

PERSONA_TEMPLATES = [
    "i really love {n}",
    "my favorite thing is {n}",
    "i spend weekends with {n}",
    "i think about {n} daily",
]
STATEMENT_TEMPLATES = [
    "the {n} were wonderful today",
    "i found some great {n} yesterday",
    "tell me about your {n}",
    "my {n} keep getting better",
    "we should talk about {n}",
    "have you tried new {n} lately",
]
REPLY_TEMPLATES = [
    "yes i adore {n} and {m}",
    "honestly {n} beat {m} every time",
    "my {n} pair nicely with {m}",
    "i learned about {n} from {m}",
]


def _sent(rng, templates, nouns):
    t = templates[rng.randint(len(templates))]
    picks = rng.choice(nouns, size=2, replace=False)
    return t.format(n=picks[0], m=picks[1])


def make_personality(rng, topic, n_utterances=6, num_candidates=2):
    nouns = TOPIC_NOUNS[topic]
    other_topics = [t for t in TOPIC_NOUNS if t != topic]
    personality = [_sent(rng, PERSONA_TEMPLATES, nouns)
                   for _ in range(4)]
    utterances = []
    history = []
    for _ in range(n_utterances):
        history = history + [_sent(rng, STATEMENT_TEMPLATES, nouns)]
        distractors = [
            _sent(rng, REPLY_TEMPLATES,
                  TOPIC_NOUNS[other_topics[rng.randint(len(other_topics))]])
            for _ in range(num_candidates - 1)]
        gold = _sent(rng, REPLY_TEMPLATES, nouns)
        utterances.append({"history": list(history),
                           "candidates": distractors + [gold]})
        history = history + [gold]
    return {"personality": personality, "utterances": utterances}


def make_corpus(n_train=256, n_valid=32, seed=17):
    rng = np.random.RandomState(seed)
    topics = list(TOPIC_NOUNS)
    blob = {}
    for split, n in (("train", n_train), ("valid", n_valid)):
        blob[split] = [make_personality(rng, topics[i % len(topics)])
                       for i in range(n)]
    return blob


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("out_dir")
    ap.add_argument("--n_train", type=int, default=256)
    ap.add_argument("--n_valid", type=int, default=32)
    ap.add_argument("--seed", type=int, default=17)
    args = ap.parse_args()
    os.makedirs(args.out_dir, exist_ok=True)
    blob = make_corpus(args.n_train, args.n_valid, args.seed)
    fn = os.path.join(args.out_dir, "personachat_self_original.json")
    with open(fn, "w") as f:
        json.dump(blob, f)
    n_ut = sum(len(p["utterances"]) for p in blob["train"])
    print(f"wrote {fn}: {len(blob['train'])} train personalities "
          f"({n_ut} utterances), {len(blob['valid'])} valid")


if __name__ == "__main__":
    main()
