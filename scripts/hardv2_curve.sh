#!/usr/bin/env bash
# Accuracy-vs-compression curve at a fixed 48-epoch budget on the
# hard-v2 regime (VERDICT r4 next-round #6: the BASELINE.json metric is
# time-to-accuracy vs grad-compression ratio, and the tree had single
# points, no curve). Sketch width sweep c in {0.5M, 1M, 2M, 4M, 8M}
# (d = 6.57M, r = 5 => compression 2.6x..0.16x of d at the wide end)
# under the reference zero-EF rule, plus the round-5 subtract-EF rule at
# the flagship width, against the committed anchors
# (cifar10_hard48v2_{uncompressed,true_topk,sketch}.tsv).
set -euo pipefail
cd "$(dirname "$0")/.."

run() {
    local name=$1; shift
    echo "=== $name ==="
    python cv_train.py --dataset_name CIFAR10 --model ResNet9 --batchnorm \
      --iid --num_clients 40 --num_workers 8 --local_batch_size 64 \
      --num_epochs 48 --synthetic_per_class 400 --synthetic_hard \
      --synthetic_label_noise 0.08 --lr_scale 0.1 --seed 21 \
      --local_momentum 0.0 --virtual_momentum 0.9 \
      --mode sketch --error_type virtual \
      --k 50000 --num_rows 5 --num_blocks 20 --approx_topk --exact_num_cols \
      "$@" 2>&1 | tee "runs/$name.log"
    { echo "epoch,hours,top1Accuracy";
      grep -E "^[0-9]+,0\.[0-9]+,[0-9.]+$" "runs/$name.log"; } \
      > "runs/$name.tsv"
    tail -1 "runs/$name.tsv"
}

for arm in "$@"; do
  case "$arm" in
    c1m)  run cifar10_hard48v2_sketch_c1m  --num_cols 1000000 ;;
    c2m)  run cifar10_hard48v2_sketch_c2m  --num_cols 2000000 ;;
    c4m)  run cifar10_hard48v2_sketch_c4m  --num_cols 4000000 ;;
    c8m)  run cifar10_hard48v2_sketch_c8m  --num_cols 8000000 ;;
    c2m_sub) run cifar10_hard48v2_sketch_c2m_sub --num_cols 2000000 \
        --sketch_ef subtract ;;
    *) echo "unknown arm $arm"; exit 1 ;;
  esac
done
echo CURVE_DONE
