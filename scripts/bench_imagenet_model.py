#!/usr/bin/env python
"""Bare-model FixupResNet50 @ 224x224 fwd+bwd MFU probe (VERDICT r4 weak
#4): isolates the MODEL's conv efficiency from the federated round so
the round's MFU gap decomposes into (model ceiling) + (federated
overhead). Also profiles per-op so the stem/input-layout cost is named.

Usage: python scripts/bench_imagenet_model.py [--batch N] [--s2d]
"""

from __future__ import annotations

import collections
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from profile_gpt2_round import parse_xplane  # noqa: E402


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench_common import peak_flops
    from commefficient_tpu import models
    from commefficient_tpu.losses import make_cv_loss

    B = 64
    if "--batch" in sys.argv:
        B = int(sys.argv[sys.argv.index("--batch") + 1])
    use_s2d = "--s2d" in sys.argv

    model = models.FixupResNet50(num_classes=1000, space_to_depth=use_s2d) \
        if use_s2d else models.FixupResNet50(num_classes=1000)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.ones((1, 224, 224, 3), jnp.float32))
    loss_fn = make_cv_loss(model, "bfloat16")
    rng = np.random.RandomState(0)
    batch = {"image": jnp.asarray(rng.randn(B, 224, 224, 3), jnp.float32),
             "target": jnp.asarray(rng.randint(0, 1000, (B,)), jnp.int32)}
    mask = jnp.ones((B,), bool)

    g = jax.jit(jax.value_and_grad(lambda p: loss_fn(p, batch, mask)[0]))
    print("compiling...", flush=True)
    out = g(params)
    jax.block_until_ready(out)
    n = 10
    t0 = time.time()
    for _ in range(n):
        out = g(params)
    jax.block_until_ready(out)
    dt = (time.time() - t0) / n
    flops = 3 * 4.1e9 * B
    peak = peak_flops(jax.devices()[0])
    print(f"batch {B}{' s2d' if use_s2d else ''}: {dt*1e3:.1f} ms/step, "
          f"{B/dt:.0f} img/s, MFU {flops/dt/peak:.1%}", flush=True)

    outdir = "/tmp/profile_imagenet_model"
    with jax.profiler.trace(outdir):
        for _ in range(3):
            out = g(params)
        jax.block_until_ready(out)
    ops, span = parse_xplane(outdir)
    if ops:
        print(f"span {span/3:.1f} ms/step; top 25 ops (ms/step):")
        for name, ms in ops[:25]:
            print(f"  {ms/3:8.2f}  {name[:110]}")


if __name__ == "__main__":
    main()
