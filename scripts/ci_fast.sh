#!/usr/bin/env bash
# The single cheap green signal: schema selftest (generator and
# validator vocabularies agree, incl. the v3 client_stats/alert types),
# committed-artifact schema lint, a fast-fail pass over the round-
# pipeline tests (an input-pipeline regression — leaked thread, broken
# determinism — fails in seconds, before the full suite), then the
# tier-1 suite exactly as ROADMAP.md specifies it (CPU backend, slow
# tests deselected).
#
# Usage: scripts/ci_fast.sh [extra pytest args...]
set -euo pipefail
cd "$(dirname "$0")/.."

python scripts/check_telemetry_schema.py --selftest runs

env JAX_PLATFORMS=cpu python -m pytest tests/test_pipeline.py -q \
    -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly

# async buffered aggregation + scenario engine: a regression here
# (broken sync-equivalence, unsound merge, scenario nondeterminism)
# fails in seconds, before the full suite
env JAX_PLATFORMS=cpu python -m pytest tests/test_async_agg.py \
    tests/test_scenarios.py -q \
    -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly

# adversary injection + robust aggregation + quarantine: a regression
# here (broken HLO identity with defenses off, unsound clip/trim math,
# quarantine semantics drift) fails in seconds, before the full suite
env JAX_PLATFORMS=cpu python -m pytest tests/test_defense.py \
    tests/test_quarantine.py -q \
    -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly

# memory ledger + roofline attribution: a regression here (broken
# ledger parse, roofline math drift, ceiling-gate or residency-
# degradation semantics) fails in seconds, before the full suite
env JAX_PLATFORMS=cpu python -m pytest tests/test_memory.py -q \
    -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly

# fused sketch encode + decode overlap: a regression here (broken
# sketch linearity in the table-carry scan, streaming_grad drift vs
# jax.grad, lost decode-overlap bit-identity, soundness guards) fails
# in seconds, before the full suite
env JAX_PLATFORMS=cpu python -m pytest tests/test_fused_encode.py -q \
    -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly

# int8 quantized wire: a regression here (quantizer drifting from its
# numpy reference, lost rounding determinism/resume replay, broken
# byte accounting, a v9 schema/teleview gate drift) fails in seconds,
# before the full suite
env JAX_PLATFORMS=cpu python -m pytest tests/test_wire.py -q \
    -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly

# sharded sketch server: a regression here (lost sharded==replicated
# round parity, a drifting range decode or top-k merge, a table-sized
# all-reduce sneaking back, broken eligibility fail-fasts, the teleview
# per-chip gate) fails in seconds, before the full suite
env JAX_PLATFORMS=cpu python -m pytest tests/test_sharded_server.py -q \
    -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly

# layer-wise compression attribution: a regression here (a broken
# group partition / conservation law, a per-group collective unroll,
# lost HLO identity with --signal_groups off, starvation-rule or
# teleview-fallback drift) fails in seconds, before the full suite
env JAX_PLATFORMS=cpu python -m pytest tests/test_layer_signals.py -q \
    -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly

# preemption-safe rounds: a regression here (lost bitwise crash-resume,
# checkpoint-integrity fallback drift, telemetry stream clobbering,
# quarantine state dropped on restart, a leaked watchdog thread) fails
# in seconds, before the full suite; the REAL-kill subprocess matrix is
# scripts/crash_matrix.py (slow-marked here)
env JAX_PLATFORMS=cpu python -m pytest tests/test_faults.py -q \
    -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly

# population-scale observability: a regression here (a drifted count-min
# or heavy-hitter bound, broken sketch/exact snapshot parity, a
# non-deterministic sidecar that loses bitwise crash-resume, the sidecar
# size guard or the teleview literal fallbacks drifting) fails in
# seconds, before the full suite
env JAX_PLATFORMS=cpu python -m pytest tests/test_population.py -q \
    -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly

set -o pipefail
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly "$@" 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)"
exit "$rc"
