#!/usr/bin/env python
"""Calibrate the CIFAR hard-regime knobs (VERDICT r3 weak #4): find
(_HARD_FRAC, _HARD_DELTA, _HARD_NOISE) where even UNCOMPRESSED training
lands below 100% val accuracy at epoch 24 — so the three-way comparison
measures compression cost against a nontrivial ceiling instead of a
saturated one. Monkeypatches the knobs (the synth marker carries them,
so each setting re-prepares its own arrays) and runs the runs/README.md
recipe's uncompressed arm.

Usage: python scripts/calibrate_hard.py "frac,delta,noise" [...]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_setting(frac: float, delta: int, noise: int, mode: str,
                out_dir: str, epochs: int = 24):
    from commefficient_tpu import cv_train
    from commefficient_tpu.data import fed_cifar

    fed_cifar._HARD_FRAC = frac
    fed_cifar._HARD_DELTA = delta
    fed_cifar._HARD_NOISE = noise
    os.makedirs(out_dir, exist_ok=True)
    argv = ["--dataset_name", "CIFAR10", "--model", "ResNet9",
            "--batchnorm", "--iid", "--num_clients", "40",
            "--num_workers", "8", "--local_batch_size", "64",
            "--num_epochs", str(epochs), "--synthetic_per_class", "400",
            "--synthetic_hard", "--synthetic_label_noise", "0.08",
            "--lr_scale", "0.1", "--seed", "21",
            "--local_momentum", "0.0", "--virtual_momentum", "0.9",
            "--dataset_dir", out_dir]
    if mode == "sketch":
        argv += ["--mode", "sketch", "--error_type", "virtual",
                 "--k", "50000", "--num_rows", "5", "--num_cols", "500000",
                 "--num_blocks", "20", "--approx_topk"]
    elif mode == "true_topk":
        argv += ["--mode", "true_topk", "--error_type", "virtual",
                 "--k", "50000", "--approx_topk"]
    else:
        argv += ["--mode", "uncompressed", "--error_type", "none"]
    print(f"=== frac={frac} delta={delta} noise={noise} mode={mode}",
          flush=True)
    summary = cv_train.main(argv)
    print(f"=== RESULT frac={frac} delta={delta} noise={noise} "
          f"mode={mode}: "
          + (f"val acc {summary['test_acc']:.4f}" if summary else "DIVERGED"),
          flush=True)
    return summary


def main():
    for spec in sys.argv[1:]:
        frac, delta, noise = (float(x) for x in spec.split(","))
        run_setting(frac, int(delta), int(noise), "uncompressed",
                    f"/tmp/hardcal_{spec.replace(',', '_').replace('.', '')}")


if __name__ == "__main__":
    main()
