#!/usr/bin/env python
"""Headline benchmark: federated-round throughput, ResNet-9/CIFAR10-shape,
FetchSGD sketch compression (the reference's flagship config,
``cv_train.py --mode sketch``).

Measures end-to-end rounds of the jitted federated step — per-client
forward/backward, count-sketch encode, aggregation, server unsketch/top-k
update — and reports images/second. ``vs_baseline`` is the ratio against a
2000 img/s nominal single-GPU figure (cifar10_fast lineage trains CIFAR10 in
~24 epochs x ~25 s on one V100; the reference publishes no numbers of its
own — BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

NOMINAL_SINGLE_GPU_IMG_PER_SEC = 2000.0


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    import jax
    import jax.numpy as jnp

    from commefficient_tpu import models
    from commefficient_tpu.config import FedConfig
    from commefficient_tpu.core import FedRuntime
    from commefficient_tpu.losses import make_cv_loss

    log("devices:", jax.devices())

    W, B = 8, 64  # 8 simulated clients/round x 64 images
    cfg = FedConfig(
        mode="sketch", error_type="virtual", local_momentum=0.0,
        virtual_momentum=0.9, weight_decay=5e-4,
        num_workers=W, local_batch_size=B,
        k=50_000, num_rows=5, num_cols=500_000, num_blocks=20,
        num_clients=100, track_bytes=False,
        # TPU-tuned selects: approx_max_k (0.95 recall) for the top-k
        # sparsification — itself an approximation — instead of a 20x
        # slower exact sort-based select; bf16 sketch transform (noise
        # ~1e-3, far under the sketch's own estimation error at this c/d)
        approx_topk=True, sketch_dtype="bfloat16",
    )

    model = models.ResNet9(num_classes=10)
    x0 = jnp.ones((1, 32, 32, 3), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x0)
    loss_fn = make_cv_loss(model, "bfloat16")

    runtime = FedRuntime(cfg, params, loss_fn, num_clients=cfg.num_clients)
    state = runtime.init_state()

    rng = np.random.RandomState(0)
    batch = {
        "image": jnp.asarray(rng.randn(W, B, 32, 32, 3), jnp.float32),
        "target": jnp.asarray(rng.randint(0, 10, (W, B)), jnp.int32),
    }
    mask = jnp.ones((W, B), bool)
    client_ids = jnp.arange(W, dtype=jnp.int32)
    lr = 0.1

    log("compiling + warmup...")
    t0 = time.time()
    for _ in range(2):
        state, metrics = runtime.round(state, client_ids, batch, mask, lr)
    # completion barrier: on the experimental axon tunnel backend,
    # block_until_ready has been OBSERVED to return before device work
    # completes (chained 512-image rounds "finished" in 0.04 ms); a scalar
    # host fetch forces real completion on every backend
    float(state.ps_weights[0])
    log(f"warmup done in {time.time() - t0:.1f}s")

    n_rounds = 20
    t0 = time.time()
    for _ in range(n_rounds):
        state, metrics = runtime.round(state, client_ids, batch, mask, lr)
    float(state.ps_weights[0])
    dt = time.time() - t0

    images = n_rounds * W * B
    ips = images / dt
    log(f"{n_rounds} rounds in {dt:.3f}s -> {ips:.1f} img/s")
    loss = float(np.asarray(metrics["results"][0]).mean())
    log(f"final mean client loss {loss:.4f}")

    print(json.dumps({
        "metric": "cifar10_sketch_round_throughput",
        "value": round(ips, 1),
        "unit": "images/sec",
        "vs_baseline": round(ips / NOMINAL_SINGLE_GPU_IMG_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
