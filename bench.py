#!/usr/bin/env python
"""Headline benchmark: federated-round throughput, ResNet-9/CIFAR10-shape,
FetchSGD sketch compression (the reference's flagship config,
``cv_train.py --mode sketch``), plus the GPT-2 (124M) sketched round as a
nested secondary metric so one driver run records both flagship configs.

Measures end-to-end rounds of the jitted federated step — per-client
forward/backward, count-sketch encode, aggregation, server unsketch/top-k
update — and reports images/second. ``vs_baseline`` is the ratio against a
2000 img/s nominal single-GPU figure (cifar10_fast lineage trains CIFAR10 in
~24 epochs x ~25 s on one V100; the reference publishes no numbers of its
own — BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "mfu",
"gpt2": {...}}. ``vs_baseline`` divides by a NOMINAL (not measured)
single-GPU anchor; ``mfu`` is the measured model-FLOPs utilization — the
MODEL's fwd+bwd FLOPs for the round's images (XLA cost analysis of the bare
value_and_grad; the sketch/server ops the round also executes are real
work but not model FLOPs) over wall-clock x peak bf16 FLOP/s — and is
the number to trust.

Resilience contract (BENCH_r02 post-mortem): every compile/warmup/timing
stage runs under bench_common.with_retries, and the JSON line is printed
even if a late stage dies — a transient tunnel flake may cost one metric,
never the artifact.
"""

from __future__ import annotations

import argparse
import json
import sys
import traceback

import numpy as np

from bench_common import log, peak_flops, timed_rounds, with_retries

NOMINAL_SINGLE_GPU_IMG_PER_SEC = 2000.0


def run_cifar(result: dict, W: int = 8, B: int = 64,
              n_rounds: int = 20, telemetry=None, profiler=None,
              compile_cache=None, wire_dtype: str = "float32") -> None:
    """Fill ``result`` in place so partial progress survives a crash.

    Default (W=8, B=64) is the flagship-parity round shape — 512
    images/round, which a v5e finishes in ~0.5 ms of model time per
    client: the round is BATCH-bound there (model isolated ~51% MFU, the
    round ~17%). The saturating point below (B=512) exists to show the
    framework's ceiling when the round actually feeds the chip."""
    import jax
    import jax.numpy as jnp

    from commefficient_tpu import models
    from commefficient_tpu.config import FedConfig, enable_compilation_cache
    from commefficient_tpu.core import FedRuntime
    from commefficient_tpu.losses import make_cv_loss

    log("devices:", jax.devices())
    cfg = FedConfig(
        mode="sketch", error_type="virtual", local_momentum=0.0,
        virtual_momentum=0.9, weight_decay=5e-4,
        num_workers=W, local_batch_size=B,
        k=50_000, num_rows=5, num_cols=500_000, num_blocks=20,
        num_clients=100, track_bytes=False,
        # TPU-tuned select: approx_max_k (0.95 recall) for the top-k
        # sparsification — itself an approximation — instead of a 20x
        # slower exact sort-based select. Sketch: the default circulant
        # impl (fp32 tables); --wire_dtype selects the table wire
        # (f32 / bf16 / int8-quantized — ops/wire.py).
        approx_topk=True,
        wire_dtype=wire_dtype,
    )
    # persistent compile cache: retried compiles and the cost-analysis
    # lower+compile after the timing loop become near-free; --compile_cache
    # overrides the default per-machine directory (empty string = disable,
    # for true cold-start warmup_s measurements; None = keep the default)
    if compile_cache is not None:
        cfg = cfg.replace(compilation_cache_dir=compile_cache)
    enable_compilation_cache(cfg)

    model = models.ResNet9(num_classes=10)
    x0 = jnp.ones((1, 32, 32, 3), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x0)
    loss_fn = make_cv_loss(model, "bfloat16")

    runtime = FedRuntime(cfg, params, loss_fn, num_clients=cfg.num_clients)
    if telemetry is not None:
        # compile events (lower/compile wall time + cost-analysis FLOPs)
        # for the warmup's compiles land in the shared stream
        telemetry.instrument(runtime)
        telemetry.memory_event(f"cifar_w{W}_b{B}_init")

    rng = np.random.RandomState(0)
    batch = {
        "image": jnp.asarray(rng.randn(W, B, 32, 32, 3), jnp.float32),
        "target": jnp.asarray(rng.randint(0, 10, (W, B)), jnp.int32),
    }
    mask = jnp.ones((W, B), bool)
    client_ids = jnp.arange(W, dtype=jnp.int32)
    lr = 0.1

    dt, metrics, phases = timed_rounds(runtime, (client_ids, batch, mask, lr),
                                       warmup=2, rounds=n_rounds, desc="cifar",
                                       profiler=profiler)

    images = n_rounds * W * B
    ips = images / dt
    log(f"{n_rounds} rounds in {dt:.3f}s -> {ips:.1f} img/s")
    loss = float(np.asarray(metrics["results"][0]).mean())
    log(f"final mean client loss {loss:.4f}")

    result["value"] = round(ips, 1)
    result["vs_baseline"] = round(ips / NOMINAL_SINGLE_GPU_IMG_PER_SEC, 3)
    result["timed_rounds"] = n_rounds
    # quantized-wire arm identity (schema v9 / ISSUE 14): which table
    # wire this arm ran, and the exact simulated per-round upload
    # payload (W clients x the wire-dtype cell cost incl. int8 scales)
    # — what lets BENCH_r* trajectory files distinguish wire arms
    result["wire_dtype"] = cfg.wire_dtype
    result["wire_bytes_per_round"] = W * cfg.upload_wire_bytes(
        runtime._wire_block or None)
    # compile+warmup wall seconds BEFORE the timed window — the number
    # --compile_cache exists to shrink (cold ~77 s for this driver run,
    # warm-start target < 10 s); tracked in the BENCH trajectory
    result["warmup_s"] = phases.pop("warmup_s", None)
    # where the timed wall clock went: dispatch (async round calls),
    # device_wait (trailing completion barrier), host (loop remainder)
    result["phase_split"] = phases
    # headline starvation fraction, gateable by `teleview diff
    # --input_wait_rise` on the bench trajectory (not just run streams)
    result["input_wait_frac"] = round(phases["host_s"] / dt, 6)

    # MFU numerator = MODEL FLOPs (the ResNet-9 fwd+bwd for the round's
    # W*B images, from XLA's cost analysis of the bare value_and_grad — no
    # scans there, so the count is trustworthy), consistent with
    # bench_gpt2's analytic model-FLOPs definition. The sketch/server ops
    # the round also executes are real work but not "model FLOPs".
    def model_flops():
        flat = {k: v.reshape((-1,) + v.shape[2:]) for k, v in batch.items()}
        fmask = mask.reshape(-1)
        g = jax.jit(jax.value_and_grad(lambda p: loss_fn(p, flat, fmask)[0]))
        cost = g.lower(params).compile().cost_analysis()
        if isinstance(cost, list):
            cost = cost[0]
        return float(cost["flops"])

    try:
        flops = with_retries(model_flops, desc="cifar cost analysis")
    except Exception as e:
        log(f"WARNING: cost analysis unavailable ({e})")
        flops = float("nan")
    peak = peak_flops(jax.devices()[0])
    mfu = (flops * n_rounds / dt) / peak
    log(f"model FLOPs/round {flops:.3e}, peak {peak:.0f}, MFU {mfu:.3f}")
    result["mfu"] = round(mfu, 4) if np.isfinite(mfu) else None
    if telemetry is not None:
        # schema-validated utilization event in the shared stream: the
        # same MFU the JSON line carries, plus the starvation fractions
        # and (v6) the roofline fields — the round executable's bytes
        # accessed come from the JitWatcher's cost analysis (the warmup
        # compiled through it), so AI/bound ride the same stream
        from commefficient_tpu.telemetry.utilization import emit_from_totals
        round_bytes = telemetry.watcher().bytes.get("round_step")
        ufields = emit_from_totals(
            telemetry, rnd=n_rounds, rounds=n_rounds, wall_s=dt,
            host_s=phases["host_s"], dispatch_s=phases["dispatch_s"],
            device_s=phases["device_wait_s"],
            flops_per_round=(flops if np.isfinite(flops) else None),
            flops_source="cost_analysis",
            device_kind=getattr(jax.devices()[0], "device_kind", "unknown"),
            bytes_per_round=(float(round_bytes) if round_bytes else None),
            bytes_source="cost_analysis")
        result["roofline"] = {
            k: ufields[k] for k in ("bytes_per_round",
                                    "arithmetic_intensity", "bound",
                                    "bw_frac")}
        telemetry.bench_event(result["metric"], result,
                              wire_dtype=cfg.wire_dtype)


def make_bench_telemetry(args, run_type: str):
    """Shared bench CLI: ``--telemetry_dir`` opens the same JSONL stream
    the drivers write (telemetry/schema.py); ``--profile_dir``/
    ``--profile_rounds`` place a jax trace over the timed rounds."""
    from commefficient_tpu.telemetry import ProfilerWindow, RunTelemetry
    telemetry = None
    if args.telemetry_dir:
        telemetry = RunTelemetry(args.telemetry_dir, run_type)
        if telemetry.active:
            log(f"telemetry: {telemetry.path}")
        else:
            telemetry = None  # constructor warned; no stream to feed
    profiler = (ProfilerWindow(args.profile_dir, args.profile_rounds,
                               log=log)
                if args.profile_dir else None)
    return telemetry, profiler


def add_bench_args(ap: argparse.ArgumentParser) -> None:
    ap.add_argument("--telemetry_dir", default="",
                    help="write a telemetry.jsonl event stream here "
                         "(same schema as the drivers')")
    ap.add_argument("--profile_dir", default="",
                    help="write a jax profiler trace of the timed rounds")
    ap.add_argument("--profile_rounds", default="2:4",
                    help="1-based inclusive timed-round window for the "
                         "trace, START:STOP")
    ap.add_argument("--compile_cache", default=None,
                    help="persistent XLA compile cache DIR (unset: the "
                         "config default, ~/.cache/commefficient_tpu_xla; "
                         "pass an empty string to DISABLE and measure a "
                         "true cold start); warm starts skip the cold "
                         "compile tax recorded as warmup_s in the JSON")
    ap.add_argument("--wire_dtype",
                    choices=("float32", "bfloat16", "int8"),
                    default="float32",
                    help="sketch-table wire dtype for the benched round "
                         "(int8 = block-quantized wire, ops/wire.py); "
                         "recorded in the headline JSON so BENCH "
                         "trajectory arms stay distinguishable")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    add_bench_args(ap)
    args = ap.parse_args(argv)
    telemetry, profiler = make_bench_telemetry(args, "bench")
    result = {
        "metric": "cifar10_sketch_round_throughput",
        "value": None,
        "unit": "images/sec",
        "vs_baseline": None,
        "mfu": None,
    }
    try:
        run_cifar(result, telemetry=telemetry, profiler=profiler,
                  compile_cache=args.compile_cache,
                  wire_dtype=args.wire_dtype)
    except Exception as e:
        log(traceback.format_exc())
        result["error"] = f"{type(e).__name__}: {e}"
    # insurance: the measured headline lands in the stderr tail NOW, so a
    # kill/hang during the (long-compiling) GPT-2 stage cannot lose it
    log("headline:", json.dumps(result))
    # second CIFAR point at a round size that FEEDS the chip (VERDICT r3
    # item 4): same model/sketch config, 32 clients x 512 images — the
    # top of the measured round-shape grid (runs/ROUND_SHAPE.md: both
    # clients-per-round and local batch amortize launch cost, composing
    # to 61.5% MFU where 8x512 stops at 53%). The flagship-parity
    # headline above is deliberately batch-starved (its round shape
    # matches the reference experiment, not the hardware); this point
    # records what the same machinery does when the round is
    # compute-bound.
    try:
        sat = {"metric": "cifar10_sketch_round_throughput_saturated",
               "value": None, "unit": "images/sec", "vs_baseline": None,
               "mfu": None, "round_images": 32 * 512}
        run_cifar(sat, W=32, B=512, n_rounds=10, telemetry=telemetry,
                  compile_cache=args.compile_cache,
                  wire_dtype=args.wire_dtype)
        result["cifar_saturated"] = sat
        log("saturated:", json.dumps(sat))
    except Exception as e:
        log(traceback.format_exc())
        log(f"WARNING: saturated CIFAR bench failed ({e})")
        result["cifar_saturated"] = {"error": f"{type(e).__name__}: {e}"}
    # secondary metric: the GPT-2 (124M) sketched round, so the driver's
    # BENCH record captures both benchmarks (best-effort — the headline
    # CIFAR metric must survive a GPT-2 failure, e.g. an OOM on a small
    # chip, and vice versa)
    try:
        import bench_gpt2
        result["gpt2"] = bench_gpt2.run(telemetry=telemetry,
                                        compile_cache=args.compile_cache,
                                        wire_dtype=args.wire_dtype)
    except Exception as e:
        log(traceback.format_exc())
        log(f"WARNING: GPT-2 bench failed ({e})")
        result["gpt2"] = {"error": f"{type(e).__name__}: {e}"}
    if telemetry is not None:
        # total timed rounds across the stages that actually ran
        n_rounds = sum(
            stage.get("timed_rounds", 0)
            for stage in (result, result.get("cifar_saturated") or {},
                          result.get("gpt2") or {}))
        telemetry.write_summary(aborted="error" in result,
                                n_rounds=n_rounds, final=result)
        telemetry.close()
    print(json.dumps(result))
    # rc=0 iff the headline number exists; partial JSON is emitted either way
    sys.exit(0 if result["value"] is not None else 1)


if __name__ == "__main__":
    main()
