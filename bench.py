#!/usr/bin/env python
"""Headline benchmark: federated-round throughput, ResNet-9/CIFAR10-shape,
FetchSGD sketch compression (the reference's flagship config,
``cv_train.py --mode sketch``).

Measures end-to-end rounds of the jitted federated step — per-client
forward/backward, count-sketch encode, aggregation, server unsketch/top-k
update — and reports images/second. ``vs_baseline`` is the ratio against a
2000 img/s nominal single-GPU figure (cifar10_fast lineage trains CIFAR10 in
~24 epochs x ~25 s on one V100; the reference publishes no numbers of its
own — BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "mfu"}.
``vs_baseline`` divides by a NOMINAL (not measured) single-GPU anchor;
``mfu`` is the measured model-FLOPs utilization — the MODEL's fwd+bwd
FLOPs for the round's images (XLA cost analysis of the bare
value_and_grad; the sketch/server ops the round also executes are real
work but not model FLOPs) over wall-clock x peak bf16 FLOP/s — and is
the number to trust.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

from bench_gpt2 import log, peak_flops

NOMINAL_SINGLE_GPU_IMG_PER_SEC = 2000.0


def main():
    import jax
    import jax.numpy as jnp

    from commefficient_tpu import models
    from commefficient_tpu.config import FedConfig
    from commefficient_tpu.core import FedRuntime
    from commefficient_tpu.losses import make_cv_loss

    log("devices:", jax.devices())

    W, B = 8, 64  # 8 simulated clients/round x 64 images
    cfg = FedConfig(
        mode="sketch", error_type="virtual", local_momentum=0.0,
        virtual_momentum=0.9, weight_decay=5e-4,
        num_workers=W, local_batch_size=B,
        k=50_000, num_rows=5, num_cols=500_000, num_blocks=20,
        num_clients=100, track_bytes=False,
        # TPU-tuned select: approx_max_k (0.95 recall) for the top-k
        # sparsification — itself an approximation — instead of a 20x
        # slower exact sort-based select. Sketch: the default circulant
        # impl (fp32 tables).
        approx_topk=True,
    )
    # persistent compile cache: the cost-analysis lower+compile after the
    # timing loop would otherwise pay a full second compilation
    from commefficient_tpu.config import enable_compilation_cache
    enable_compilation_cache(cfg)

    model = models.ResNet9(num_classes=10)
    x0 = jnp.ones((1, 32, 32, 3), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x0)
    loss_fn = make_cv_loss(model, "bfloat16")

    runtime = FedRuntime(cfg, params, loss_fn, num_clients=cfg.num_clients)
    state = runtime.init_state()

    rng = np.random.RandomState(0)
    batch = {
        "image": jnp.asarray(rng.randn(W, B, 32, 32, 3), jnp.float32),
        "target": jnp.asarray(rng.randint(0, 10, (W, B)), jnp.int32),
    }
    mask = jnp.ones((W, B), bool)
    client_ids = jnp.arange(W, dtype=jnp.int32)
    lr = 0.1

    log("compiling + warmup...")
    t0 = time.time()
    for _ in range(2):
        state, metrics = runtime.round(state, client_ids, batch, mask, lr)
    # completion barrier: on the experimental axon tunnel backend,
    # block_until_ready has been OBSERVED to return before device work
    # completes (chained 512-image rounds "finished" in 0.04 ms); a scalar
    # host fetch forces real completion on every backend
    float(state.ps_weights[0])
    log(f"warmup done in {time.time() - t0:.1f}s")

    n_rounds = 20
    t0 = time.time()
    for _ in range(n_rounds):
        state, metrics = runtime.round(state, client_ids, batch, mask, lr)
    float(state.ps_weights[0])
    dt = time.time() - t0

    images = n_rounds * W * B
    ips = images / dt
    log(f"{n_rounds} rounds in {dt:.3f}s -> {ips:.1f} img/s")
    loss = float(np.asarray(metrics["results"][0]).mean())
    log(f"final mean client loss {loss:.4f}")

    # MFU numerator = MODEL FLOPs (the ResNet-9 fwd+bwd for the round's 512
    # images, from XLA's cost analysis of the bare value_and_grad — no
    # scans there, so the count is trustworthy), consistent with
    # bench_gpt2's analytic model-FLOPs definition. The sketch/server ops
    # the round also executes are real work but not "model FLOPs".
    def model_flops():
        flat = {k: v.reshape((-1,) + v.shape[2:]) for k, v in batch.items()}
        fmask = mask.reshape(-1)
        g = jax.jit(jax.value_and_grad(lambda p: loss_fn(p, flat, fmask)[0]))
        cost = g.lower(params).compile().cost_analysis()
        if isinstance(cost, list):
            cost = cost[0]
        return float(cost["flops"])

    try:
        flops = model_flops()
    except Exception as e:  # pragma: no cover
        log(f"WARNING: cost analysis unavailable ({e})")
        flops = float("nan")
    peak = peak_flops(jax.devices()[0])
    mfu = (flops * n_rounds / dt) / peak
    log(f"model FLOPs/round {flops:.3e}, peak {peak:.0f}, MFU {mfu:.3f}")
    result = {
        "metric": "cifar10_sketch_round_throughput",
        "value": round(ips, 1),
        "unit": "images/sec",
        "vs_baseline": round(ips / NOMINAL_SINGLE_GPU_IMG_PER_SEC, 3),
        "mfu": round(mfu, 4) if np.isfinite(mfu) else None,
    }
    # insurance: the measured headline lands in the stderr tail NOW, so a
    # kill/hang during the (long-compiling) GPT-2 stage cannot lose it
    log("headline:", json.dumps(result))
    # secondary metric: the GPT-2 (124M) sketched round, so the driver's
    # BENCH record captures both benchmarks (best-effort — the headline
    # CIFAR metric must survive a GPT-2 failure, e.g. an OOM on a small
    # chip)
    try:
        import bench_gpt2
        result["gpt2"] = bench_gpt2.run()
    except Exception as e:  # pragma: no cover
        log(f"WARNING: GPT-2 bench failed ({e})")
    print(json.dumps(result))


if __name__ == "__main__":
    main()
