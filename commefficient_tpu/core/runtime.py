"""FedRuntime: the single-program federated round.

This is the TPU-native collapse of the reference's entire process
architecture (SURVEY.md §2.8): the parameter-server process
(fed_aggregator.py), the per-GPU worker processes (fed_worker.py), the
batch/result multiprocessing queues, the /dev/shm shared-memory tensors and
the NCCL reduce all become ONE jitted function

    round_step(state: FedState, client_ids, batch, mask, lr)
        -> (state', metrics)

in which the round's clients are a leading array axis. Per-client gradients
are computed under ``vmap`` (single device) or ``shard_map`` over the
``clients`` mesh axis with a ``psum`` aggregation (see parallel/), which is
the ICI equivalent of the reference's ``torch.distributed.reduce(sum_g, 0)``
(fed_worker.py:138, fed_aggregator.py:329).

State stays on device between rounds; the only host traffic is the incoming
batch and the outgoing scalar metrics — the reference instead bounces the
full weight vector host<->device every round (fed_worker.py:41,
fed_aggregator.py:455).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from commefficient_tpu.config import FedConfig
from commefficient_tpu.core import client as client_lib
from commefficient_tpu.core.server import (robust_aggregate,
                                           server_update,
                                           sharded_sketch_server_update,
                                           validate_defense_combo,
                                           validate_mode_combo,
                                           validate_regimes)
from commefficient_tpu.core.state import FedState
from commefficient_tpu.ops import ravel_params
from commefficient_tpu.ops.sketch import make_sketch_impl
from commefficient_tpu.telemetry import tracing
from commefficient_tpu.telemetry.clients import (CLIENT_GRAD_KEYS,
                                                 summarize_per_client)
from commefficient_tpu.telemetry.signals import round_signals
from commefficient_tpu.utils.jax_compat import shard_map


class FedRuntime:
    """Owns the jitted round/val steps and the state layout for a model.

    Parameters
    ----------
    cfg : FedConfig (grad_size is filled in here, like fed_aggregator.py:88)
    params : the model parameter pytree (initial weights)
    loss_fn_train / loss_fn_val : see core.client loss contract
    batch_size : static per-client batch (local_batch_size, or
        max_client_batch when local_batch_size == -1)
    num_clients : total simulated clients
    mesh : optional jax.sharding.Mesh; when given, the round is pjit-sharded
        per parallel.mesh.FedShardings (clients over the mesh axis, dense
        federated vectors sharded, XLA inserts the ICI collectives)
    """

    def __init__(self, cfg: FedConfig, params: Any,
                 loss_fn_train: Callable,
                 loss_fn_val: Optional[Callable] = None,
                 num_clients: Optional[int] = None,
                 mesh=None,
                 seq_spec: Optional[Dict[str, int]] = None):
        flat, unravel = ravel_params(params)
        cfg = cfg.replace(grad_size=int(flat.size))
        if (cfg.mode == "sketch" and cfg.sketch_impl == "circ"
                and not cfg.exact_num_cols):
            # TPU-efficient sketch width (config.auto_num_cols): align to
            # the Pallas kernels and keep static rolls out of the gather
            # cliff. Replaced BEFORE the sketch is built so upload byte
            # accounting (cfg.upload_floats) reflects the real table.
            from commefficient_tpu.config import auto_num_cols
            c = auto_num_cols(cfg.num_cols)
            if c != cfg.num_cols:
                print(f"auto-sized sketch num_cols {cfg.num_cols} -> {c} "
                      "(1024-aligned for the Pallas kernels; "
                      "--exact_num_cols pins the original)")
                cfg = cfg.replace(num_cols=c)
        validate_mode_combo(cfg)
        # measured-divergence guardrails (VERDICT r5 weak #3): warn — or
        # fail under --strict_regimes — on configs round 5 measured
        # divergent; runs here (not parse time) because the collision
        # load needs the resolved grad_size/num_cols
        validate_regimes(cfg)
        self.cfg = cfg
        self.unravel = unravel
        self.initial_weights = flat
        self.mesh = mesh
        # sequence/context parallelism: a mesh with a "seq" axis runs every
        # client's model seq-sharded (ring attention; see parallel/ring.py
        # and the seq_axis machinery in models/gpt2.py + losses.py).
        # ``seq_spec`` maps batch leaf names -> the index of their sequence
        # dimension (leaves absent from it replicate over the seq axis).
        self._seq_axis = ("seq" if (mesh is not None
                                    and "seq" in mesh.axis_names) else None)
        self._seq_shards = (mesh.shape["seq"] if self._seq_axis else 1)
        if self._seq_shards == 1:
            # a size-1 seq axis is a degenerate layout, not sequence
            # parallelism — treat it as absent (no seq_spec required, no
            # mode restrictions, no gradient rescale)
            self._seq_axis = None
        self._seq_spec = seq_spec or {}
        if self._seq_axis:
            if not self._seq_spec:
                raise ValueError(
                    "the mesh has a 'seq' axis but no seq_spec was given: "
                    "without one the batch replicates over seq and every "
                    "shard silently duplicates the full forward/backward. "
                    "Pass seq_spec (and a seq-sharded loss/model, see "
                    "gpt2_train.py), or drop the seq axis from mesh_axes.")
            # the per-shard client pipeline must be LINEAR in the gradient
            # (shards sum): modes with per-client nonlinearities are out
            if cfg.mode not in ("uncompressed", "true_topk", "sketch"):
                raise ValueError(
                    f"mode={cfg.mode} is incompatible with a seq mesh axis "
                    "(per-client nonlinear pipeline; use uncompressed/"
                    "true_topk/sketch)")
            if (cfg.do_topk_down or cfg.do_dp
                    or cfg.needs_client_velocities
                    or cfg.needs_client_errors):
                raise ValueError(
                    "topk_down / DP / local client state are not supported "
                    "with a seq mesh axis")
            if cfg.max_grad_norm is not None:
                raise ValueError(
                    "max_grad_norm is unsupported with a seq mesh axis: "
                    "clipping needs the norm of the client's SUMMED "
                    "gradient, which per-shard partial norms cannot "
                    "provide (and the sketch table clip is per-client "
                    "nonlinear)")
        # measured (not assumed) autodiff scale of the seq-axis psum
        # transpose — see the rescale site in _round_step
        self._seq_grad_scale = (self._probe_seq_grad_scale()
                                if self._seq_axis else 1.0)
        self.num_clients = (num_clients if num_clients is not None
                            else cfg.default_num_clients())
        if mesh is not None:
            # pad the client-state row count up to a mesh-divisible size
            from commefficient_tpu.parallel.mesh import FedShardings
            self.shardings = FedShardings(mesh)
            n_dev = mesh.shape[self.shardings.axis]
            self.num_clients = -(-self.num_clients // n_dev) * n_dev
            n_dense = mesh.size  # dense vectors shard over ALL mesh axes
            # pad the dense federated vector too, so the SERVER state
            # (ps_weights, dense Vvelocity/Verror, coord_last_update) always
            # shards evenly over the mesh: the dense-mode client sum arrives
            # by reduce_scatter (each device owns d_pad/n coordinates of the
            # summed gradient), the elementwise server math runs sharded,
            # and XLA all-gathers only where globality is required (the
            # top-k select, and the per-round weight broadcast every client
            # needs anyway). Without this, any d not divisible by the mesh
            # fell back to a fully-replicated (d,) all-reduce — at GPT-2
            # scale a 500 MB collective where a shard-sized one suffices
            # (ref aggregation: fed_aggregator.py:326-332, 446-458).
            self.d_pad = -(-cfg.grad_size // n_dense) * n_dense
            # Dense per-client rows (velocity/error) store COLUMN-sharded
            # — (num_clients, d_row_pad) with the row length sharded over
            # the clients axis — so the round's gather/scatter by
            # client_ids is device-local and the layout change to/from
            # per-client full rows is one all_to_all of W·d/n elements
            # (parallel/mesh.py FedShardings.for_state; replaces the W·d
            # all-reduce pair of the row-sharded layout — the reference
            # analogue is zero-traffic /dev/shm rows,
            # fed_aggregator.py:119-129). Sketch-mode table rows stay in
            # the row layout.
            self.d_row_pad = -(-cfg.grad_size // n_dev) * n_dev
            self._rows_cols = (cfg.mode not in ("sketch", "fedavg")
                               and (cfg.needs_client_velocities
                                    or cfg.needs_client_errors))
        else:
            self.shardings = None
            self.d_pad = cfg.grad_size
            self.d_row_pad = cfg.grad_size
            self._rows_cols = False
        self._axis = self.shardings.axis if self.shardings else None
        # --- robustness subsystem (adversary injection / robust
        # aggregation / nonfinite quarantine). Everything below is gated
        # at TRACE time on config flags that default off, so the round's
        # HLO stays byte-identical to the pre-defense round when unused
        # (identity-tested, same discipline as signals/client_stats).
        validate_defense_combo(cfg, mesh=mesh, seq_axis=self._seq_axis)
        self._adversary = cfg.adversary != "none"
        # update-space kinds act on per-client transmitted quantities
        # (vmap path); labelflip acts on the batch and stays
        # fused-compatible
        self._adv_inject = cfg.adversary in ("signflip", "scale",
                                             "noise", "nan")
        self._labelflip = cfg.adversary == "labelflip"
        self._quarantine = cfg.nonfinite_action == "quarantine"
        self._defense_ring = cfg.defense == "normclip"
        self.adversary_plan = None
        self._adv_universe = None
        self._flip_classes = 0
        if self._adversary:
            from commefficient_tpu.data.scenarios import make_adversary
            self.adversary_plan = make_adversary(cfg)
            # the per-client assignment over the whole universe, baked
            # into the jitted round as a tiny boolean constant — the
            # device and the host (telemetry counts, the scenario
            # engine's CohortFate.adversary) read the SAME draw
            self._adv_universe = jnp.asarray(
                self.adversary_plan.universe_mask(self.num_clients))
            if self._labelflip:
                from commefficient_tpu.config import num_classes_of_dataset
                # validate_defense_combo already rejected non-classifiable
                # datasets; resolve the flip arity here
                self._flip_classes = num_classes_of_dataset(
                    cfg.dataset_name)
        self.batch_size = (cfg.local_batch_size if cfg.local_batch_size > 0
                           else cfg.max_client_batch)
        self.cs = None
        if cfg.mode == "sketch":
            self.cs = make_sketch_impl(
                cfg.sketch_impl, cfg.grad_size, cfg.num_cols, cfg.num_rows,
                cfg.num_blocks, seed=cfg.sketch_seed, dtype=cfg.sketch_dtype,
                scan_rows=cfg.sketch_scan_rows, pallas=cfg.pallas)
        # sketch-table wire dtype (--sketch_dtype): uploads/psum payloads
        # travel rounded to this dtype; all server math stays fp32
        self._table_dtype = (jnp.dtype(cfg.sketch_dtype)
                             if cfg.mode == "sketch" else jnp.float32)
        # Sketch linearity: sum-of-client-sketches == sketch-of-summed-grads,
        # so the O(d·r) encode can run once per round instead of once per
        # client — unless a per-client nonlinearity (table clip) intervenes.
        # (The reference necessarily encodes per worker because aggregation
        # happens across processes via NCCL, fed_worker.py:312-320.)
        # On a mesh the deferral is per-SHARD: each device sums its local
        # clients' dense gradients and encodes once, then the (r, c) tables
        # psum over ICI — encode work drops from per-client to per-device
        # and the collective stays table-sized (the TPU analogue of the
        # reference's encode-before-NCCL-reduce).
        # (the post-encode TABLE clip is per-client and kills deferral;
        # the pre-encode dense clip preserves sketch linearity — the sum
        # of clipped dense gradients encodes once)
        self._defer_encode = (cfg.mode == "sketch"
                              and (cfg.max_grad_norm is None
                                   or cfg.sketch_dense_clip))
        # With deferred encode on a single device, the server can keep
        # momentum/error as dense (d,) PRE-IMAGES instead of (r, c) tables:
        # one enc+dec round-trip of the error per round injects the sketch's
        # compression noise (that round-trip IS what the server sees through
        # the compressed channel), and the reference's error-feedback /
        # momentum-masking zeroing applies EXACTLY at the update support —
        # the true_topk rule structure with the sketch round-trip inserted.
        # See core/server.py dense_preimage branch; reduces to both the
        # table-space rule and true_topk in the lossless limit.
        # Single-device ONLY: on a mesh the pre-image trick would turn the
        # table-sized psum back into a d-sized dense psum — there the
        # per-shard encode + table-space subtractive rule applies instead.
        # Always on for the SRHT transform (its dense transform admits no
        # cell rule); opt-in for circ/hash via --sketch_server_state dense
        # (round-5 study: the table-space rules either leak accumulated
        # error [zero] or amplify decode noise [subtract] at GPT-2-scale
        # collision load — the dense pre-image is leak-free AND stable,
        # at O(d) server memory the reference's PS already spends on every
        # dense mode).
        self._dense_preimage = (self._defer_encode and mesh is None
                                and (getattr(self.cs, "dense_transform",
                                             False)
                                     or cfg.sketch_server_state == "dense"))
        if (cfg.mode == "sketch" and cfg.sketch_server_state == "dense"
                and not self._dense_preimage):
            raise ValueError(
                "--sketch_server_state dense requires a single device "
                "(no mesh) and deferred encode (no per-client table "
                "clip — use --sketch_dense_clip to clip)")
        # ---- sharded sketch SERVER tail (core/server.py
        # sharded_sketch_server_update): replace the replicated table
        # psum with a psum_scatter over table columns, run momentum+EF
        # on the column shards, decode only this device's d_pad/n
        # coordinate range, and merge an (n, k) candidate all-gather
        # into the global top-k — no device ever materializes the dense
        # (d,) estimates. Eligibility decided ONCE here (the
        # fused-encode pattern): "auto" silently falls back to the
        # replicated tail (the fallback IS the pre-sharding round —
        # numerics never change silently), "on" fails fast listing
        # every blocker.
        ss_problems = []
        if cfg.mode == "sketch":
            if mesh is None:
                ss_problems.append(
                    "no mesh: there is nothing to shard the server tail "
                    "over (the single-device round already holds the "
                    "whole table)")
            else:
                if self._dense_preimage:
                    ss_problems.append(
                        "the dense-preimage server state has no table "
                        "to reduce-scatter")
                if self._seq_axis is not None:
                    ss_problems.append(
                        "a seq mesh axis: the table's column shards "
                        "live on the clients axis only (the state's "
                        "sketch_table layout), which a seq-sharded "
                        "aggregation cannot feed without a reshard "
                        "every round")
                if (getattr(self.cs, "dense_transform", False)
                        or not hasattr(self.cs, "decode_range")):
                    ss_problems.append(
                        f"sketch_impl={cfg.sketch_impl} has a dense "
                        "transform (no cell-addressable table, no "
                        "range-restricted decode, and an estimate-"
                        "space EF rule); use circ or hash")
                n_dev = mesh.shape[self._axis]
                if cfg.num_cols % max(n_dev, 1) != 0:
                    ss_problems.append(
                        f"num_cols={cfg.num_cols} is not divisible by "
                        f"the clients mesh axis ({n_dev} devices): the "
                        "reduce-scattered column shards must tile "
                        "evenly (pick --num_cols as a multiple of the "
                        "device count; the circ auto-sizing's 1024-"
                        "aligned widths already are for meshes up to "
                        "1024 chips)")
        self._sharded_server = (cfg.mode == "sketch"
                                and cfg.sketch_sharded_server != "off"
                                and not ss_problems)
        if cfg.sketch_sharded_server == "on" and not self._sharded_server:
            raise ValueError(
                "--sketch_sharded_server on: the sharded server tail is "
                "unavailable for this configuration (use auto to fall "
                "back to the replicated tail instead):\n  "
                + "\n  ".join(ss_problems))
        # --decode_overlap composition: the table reduce itself MOVES
        # into the decode executable — the cohort ends at each device's
        # LOCAL partial table, so the round's metrics sync completes
        # without waiting any ICI collective and the reduce-scatter +
        # sharded decode both run while the host stages round t+1 (see
        # _decode_step / _reduce_partials; bit-identity dryrun-gated).
        self._reduce_in_decode = (self._sharded_server
                                  and cfg.decode_overlap)
        # ---- int8 quantized wire (--wire_dtype int8; ops/wire.py):
        # clients quantize their table contribution with per-column-
        # block abs-max scales + stochastic rounding (draws keyed off
        # (seed, global_round, device/slot, cell) — deterministic,
        # replay/resume-safe), the mesh table reduce becomes an
        # all_to_all of int8 column shards + f32 scales with
        # shard-local dequantize-accumulate in f32 (int8 summation over
        # W clients would overflow; f32 local accumulation keeps the
        # server momentum/EF numerics untouched), and the rounding
        # residual lands in the aggregate where the server error
        # feedback absorbs it. int8 is an EXPLICIT request, so every
        # blocker is a hard error (no silent auto-fallback — a
        # compression study must never silently measure the f32 wire);
        # config.__post_init__ already rejected the topology-free
        # blockers (non-sketch mode, rht, dense server state).
        self._int8_wire = False
        self._wire_block = 0
        if cfg.mode == "sketch" and cfg.wire_dtype == "int8":
            problems = []
            if self._dense_preimage:
                problems.append(
                    "the dense-preimage server state consumes the dense "
                    "aggregated gradient — no table crosses the wire")
            if mesh is not None and not self._sharded_server:
                problems.append(
                    "a mesh without the sharded server tail: the "
                    "quantized reduce is an all_to_all of int8 COLUMN "
                    "SHARDS, which only the reduce-scattered tail "
                    "consumes (sharded-server blockers:\n    "
                    + "\n    ".join(ss_problems or ["(disabled by flag)"])
                    + ")")
            n_dev = mesh.shape[self._axis] if mesh is not None else 1
            shard_c = cfg.num_cols // max(n_dev, 1)
            blk = min(cfg.wire_block, shard_c)
            if shard_c == 0 or shard_c % max(blk, 1):
                problems.append(
                    f"--wire_block {cfg.wire_block} does not tile the "
                    f"per-device column shard ({shard_c} cols on "
                    f"{n_dev} devices): pick a --wire_block dividing "
                    "num_cols / n_devices")
            if problems:
                raise ValueError(
                    "--wire_dtype int8 is unavailable for this "
                    "configuration:\n  " + "\n  ".join(problems))
            self._int8_wire = True
            self._wire_block = blk
        # exact per-client simulated upload bytes under the wire dtype
        # (4 * upload_floats for the f32 wire — the pre-wire constant,
        # so the f32 round's HLO stays byte-identical)
        self._upload_bytes = cfg.upload_wire_bytes(self._wire_block
                                                   or None)
        # compression-signal health diagnostics (telemetry/signals.py):
        # cheap on-device reductions appended to the round's metrics.
        # Gated on telemetry too: with --no_telemetry nothing ever reads
        # them, and in sketch mode on a mesh the l2estimate diagnostics
        # cost two table-sized all-gathers per round — never pay a hot-
        # path collective for a stream nobody consumes. Async buffered
        # aggregation (core/async_agg.py) splits the round around the
        # signal computation sites (the signals compare the round's agg
        # against the SAME round's server update, which async decouples),
        # so signals are off there — loudly, not silently: the
        # async_round event's EF norms are the async health channel.
        self._signals = (cfg.signals and cfg.telemetry
                         and not cfg.async_agg and not cfg.decode_overlap)
        if cfg.signals and cfg.telemetry and cfg.async_agg:
            import sys
            print("NOTE: --async_agg disables the per-round `signals` "
                  "diagnostics (they compare a round's aggregate against "
                  "the same round's server update, which buffered "
                  "aggregation decouples); commit-granularity EF norms "
                  "are emitted on the `async_round` events instead. Pass "
                  "--no_signals to silence this note.", file=sys.stderr)
        if cfg.signals and cfg.telemetry and cfg.decode_overlap:
            import sys
            print("NOTE: --decode_overlap disables the per-round `signals` "
                  "diagnostics: the split round's client block finishes "
                  "before the server decode it would be compared against "
                  "(that early finish is the point of the split). Pass "
                  "--no_signals to silence this note.", file=sys.stderr)
        # the dense pre-encode aggregate exists only where the deferred
        # encode runs once on one device — capture it there so sketch
        # mode gets grad_true_norm (the collision-noise reference); on a
        # mesh each shard encodes its own partial sum and the global
        # dense aggregate never materializes (by design — restoring it
        # would cost the d-sized collective the encode deferral removes)
        self._signals_dense_cap = (self._signals and cfg.mode == "sketch"
                                   and self._defer_encode
                                   and not self._dense_preimage
                                   and mesh is None)
        # --signals_exact on TABLE-state sketch additionally threads a
        # dense shadow EF accumulator pair through FedState (see
        # signals.py round_signals) — same availability condition
        self._signals_shadow = (self._signals_dense_cap
                                and cfg.signals_exact)
        # per-client population stats (telemetry/clients.py): quantile
        # summaries of per-client loss / grad norms / clip saturation /
        # contribution norm / bytes, reduced on device along the client
        # axis. Gated exactly like signals — with --no_telemetry (or
        # --no_client_stats) nothing ever reads them, so the per-client
        # reductions are compiled out of the round entirely.
        self._client_stats = cfg.client_stats and cfg.telemetry
        # defense-event scalars (clip fraction/mass, trim fraction,
        # nonfinite count): tiny extra reductions, but still only
        # computed when a telemetry stream exists to read them — the
        # defense ARITHMETIC itself (clip/trim/zeroing) is never gated
        # on telemetry, only its observability is
        self._defense_stats = cfg.telemetry and (
            cfg.defense != "none" or self._adversary or self._quarantine)

        loss_fn_val = loss_fn_val if loss_fn_val is not None else loss_fn_train
        # Fused client gradients: when nothing nonlinear happens per client
        # (no local momentum/error rows, no per-client clip/table-op/DP
        # noise, no per-client weights, no seq sharding), the round's
        # aggregate sum_c n_c*g_c is linear in the microbatch gradients and
        # can be computed by ONE scan into ONE (d,) buffer instead of
        # vmap's per-client (W, d) gradient — see make_fused_grad. Exact
        # (up to summation order); --no_fused_clients forces the vmap path.
        n_iters, mb = client_lib._num_microbatches(cfg, self.batch_size)
        self._fused = (
            cfg.fused_clients
            and cfg.mode in ("sketch", "true_topk", "uncompressed")
            and cfg.local_momentum == 0 and cfg.error_type != "local"
            and not cfg.do_dp and cfg.max_grad_norm is None
            and not cfg.do_topk_down
            and self._seq_axis is None
            # update-space injection, robust aggregation and per-client
            # nonfinite flags all need the per-client transmitted
            # quantities the fused accumulator sums away; labelflip is
            # data-space and stays fused-eligible
            and not self._adv_inject and cfg.defense == "none"
            and not self._quarantine
            and n_iters * mb == self.batch_size)
        self._fused_fn = None
        # per-client GRADIENT stats only exist where a per-client
        # gradient does (the vmap path and fedavg). The fused path sums
        # every client's microbatches into ONE (d,) buffer by design —
        # disabling it to observe would cost the measured ~15% hot-path
        # win, so its grad-stat quantiles come out NaN instead while the
        # loss/bytes population stats stay live (see _round_step tail).
        # Seq-sharded rounds are excluded for CORRECTNESS, not cost:
        # inside the shard_map each shard holds only its PARTIAL
        # gradient, whose norm is not the client's norm (partials are
        # not orthogonal — the same reason max_grad_norm is forbidden
        # with a seq axis), so a per-shard norm replicated out as the
        # client stat would be fabricated data.
        self._client_grad_stats = (self._client_stats and not self._fused
                                   and self._seq_axis is None)
        # ---- fused sketch encode (ROADMAP item 1; core/client.py
        # make_forward_grad / make_fused_grad): the microbatch scan
        # carries the (r, c) sketch TABLE instead of the dense (d,)
        # gradient sum, so the dense gradient never materializes in HBM
        # (telemetry/memory_ledger.py SKETCH_ENCODE_FUSED is the
        # committed acceptance gate). Eligibility is decided ONCE here
        # from config + topology: "auto" silently falls back to the
        # unfused round (the fallback IS the pre-fusion path — numerics
        # never change silently), "on" fails fast listing every blocker.
        fe_problems = client_lib.fused_encode_blockers(
            cfg, signals=self._signals)
        if cfg.mode == "sketch":
            if self._dense_preimage:
                fe_problems.append(
                    "the dense-preimage server state (sketch_impl=rht / "
                    "--sketch_server_state dense) consumes the dense "
                    "aggregated gradient — there is no table to "
                    "accumulate into")
            elif (getattr(self.cs, "dense_transform", False)
                    or not hasattr(self.cs, "encode_accum")):
                fe_problems.append(
                    f"sketch_impl={cfg.sketch_impl} has a dense transform "
                    "(no streaming range encode); use circ or hash")
            if cfg.defense != "none" and self._defer_encode:
                fe_problems.append(
                    f"--defense {cfg.defense} measures per-client norms on "
                    "the dense deferred-encode uploads; fusing would move "
                    "the defense to table-Frobenius space and silently "
                    "change its clipping/trimming numerics")
            if self._client_grad_stats:
                fe_problems.append(
                    "per-client grad-norm stats (telemetry/clients.py) "
                    "measure dense gradient norms on the vmap path; pass "
                    "--no_client_stats (or --no_telemetry)")
        self._fused_encode = (cfg.mode == "sketch"
                              and cfg.sketch_fused_encode != "off"
                              and not fe_problems)
        if cfg.sketch_fused_encode == "on" and not self._fused_encode:
            raise ValueError(
                "--sketch_fused_encode on: the fused sketch encode is "
                "unsound for this configuration (use auto to fall back "
                "to the unfused round instead):\n  "
                + "\n  ".join(fe_problems))
        if self._fused_encode and self._signals_dense_cap:
            import sys
            print("NOTE: the fused sketch encode removes the dense "
                  "aggregated gradient the sketch-mode signals capture "
                  "(grad_true_norm and the collision-noise reference go "
                  "null). Pass --sketch_fused_encode off to keep them at "
                  "the cost of the dense (d,) materialization.",
                  file=sys.stderr)
            self._signals_dense_cap = False
        # ---- layer-wise compression attribution (telemetry/
        # layer_signals.py): named parameter groups over the ravel-order
        # coordinate line, reduced per group inside the jitted round
        # (ops/segments.py scatter-adds keyed by a precomputed int32
        # group-id map). Gated exactly like the scalar signals — off,
        # the group machinery is compiled out entirely (HLO identity-
        # tested); on, the gid map rides as a CALL-TIME jit argument
        # (like cs: a (d_pad,) int32 constant baked into the HLO would
        # ship ~d*4 bytes to the compiler at GPT-2 scale), sharded like
        # the dense federated vectors so each device reduces its own
        # coordinate shard and ONE small (G,) psum recombines — never a
        # per-group collective unroll (dryrun-ledger-gated).
        self._layer_signals = (self._signals
                               and cfg.signal_groups != "off")
        # the per-group DENSE gradient mass needs a dense aggregated
        # gradient in the round: dense modes have it as the transmitted
        # quantity itself; sketch only via the dense-preimage state or
        # the single-device deferred-encode capture. Fused-encode and
        # mesh sketch rounds emit it null — never fake zero — because
        # restoring the dense gradient would cost exactly the (d,)
        # buffer / collective those paths exist to remove (the PR-4
        # client-stats NaN contract, applied to groups).
        self._layer_grad_mass = (self._layer_signals
                                 and (cfg.mode != "sketch"
                                      or self._dense_preimage
                                      or self._signals_dense_cap))
        self.group_spec = None
        self._gid = None
        if self._layer_signals:
            from commefficient_tpu.telemetry.layer_signals import \
                make_group_spec
            self.group_spec = make_group_spec(params, cfg.signal_groups)
            assert self.group_spec.d == cfg.grad_size, (
                self.group_spec.d, cfg.grad_size)
            gid_np = self.group_spec.gid(self.d_pad)
            if mesh is not None:
                self._gid = jax.device_put(jnp.asarray(gid_np),
                                           self.shardings.dense_vec)
            else:
                self._gid = jnp.asarray(gid_np)
        if cfg.mode == "fedavg":
            self._client_fn = client_lib.make_fedavg_client(
                cfg, loss_fn_train, unravel, self.batch_size,
                with_stats=self._client_grad_stats)
        elif self._fused:
            self._fused_fn = client_lib.make_fused_grad(
                cfg, loss_fn_train, unravel, self.batch_size,
                fused_encode=self._fused_encode)
            self._client_fn = None
        else:
            self._client_fn = client_lib.make_client_step(
                cfg, loss_fn_train, unravel, self.batch_size,
                defer_encode=self._defer_encode,
                with_stats=self._client_grad_stats,
                fused_encode=self._fused_encode)
        self._val_fn_inner = client_lib.make_val_step(cfg, loss_fn_val, unravel)

        if self.shardings is not None:
            sh = self.shardings
            state_sh = sh.for_state(cfg, self._state_template())
            batch_sh = self.batch_sharding()
            cs_sh = jax.tree.map(lambda _: sh.replicated, self.cs)
            self._round = jax.jit(
                self._round_step,
                donate_argnums=(0,),
                in_shardings=(state_sh, sh.round_axis, batch_sh,
                              sh.round_axis, None, cs_sh,
                              # gid: inferred from the argument's
                              # committed layout (device_put dense_vec
                              # in __init__) — a pinned entry here would
                              # reject the legacy 6-argument lowerings
                              # that omit it (see _round_step's
                              # constant fallback)
                              None),
                out_shardings=(state_sh, None),
            )
            self._state_sharding = state_sh
        else:
            self._round = jax.jit(self._round_step, donate_argnums=(0,))
            self._state_sharding = None
        if self.mesh is not None:
            # mesh-parallel validation: val items are independent, so the
            # batch shards over EVERY mesh axis (flattened) and each device
            # evaluates its slice; per-shard means recombine as
            # datum-weighted sums under two scalar psums. The reference
            # instead runs val through the worker queues with no reduce
            # (fed_aggregator.py:337-364) — here an n-device mesh evaluates
            # n× faster instead of idling n-1 devices.
            self._val = jax.jit(self._val_step_sharded)
        else:
            self._val = jax.jit(self._val_step)

        # async buffered aggregation (core/async_agg.py): the round splits
        # into a client-compute cohort step (dispatch time) and a server
        # commit step (buffer-goal time), plus a trivial merge. Built only
        # under --async_agg — the synchronous path compiles nothing new.
        # --decode_overlap reuses the SAME cohort step (the client half)
        # plus a buffer-free decode step (core/pipeline.DecodeOverlapRound
        # drives them): the server decode of round t runs as its own
        # executable, so a metrics sync completes when the client half
        # finishes and the host stages round t+1 under the decode.
        self._cohort = self._commit_jit = self._merge_jit = None
        self._decode_jit = None
        if cfg.async_agg or cfg.decode_overlap:
            from commefficient_tpu.core.async_agg import (
                validate_async_combo, validate_overlap_combo)
            if cfg.async_agg:
                validate_async_combo(cfg)
            else:
                validate_overlap_combo(cfg)
            if self.shardings is not None:
                sh = self.shardings
                cs_sh = jax.tree.map(lambda _: sh.replicated, self.cs)
                self._cohort = jax.jit(
                    self._cohort_step, donate_argnums=(0,),
                    in_shardings=(self._state_sharding, sh.round_axis,
                                  self.batch_sharding(), sh.round_axis,
                                  None, cs_sh),
                    out_shardings=(self._state_sharding, None))
                if cfg.async_agg:
                    self._commit_jit = jax.jit(
                        self._commit_step, donate_argnums=(0,),
                        in_shardings=(self._state_sharding, None, cs_sh),
                        out_shardings=(self._state_sharding, None))
                    self._merge_jit = jax.jit(
                        self._merge_step, donate_argnums=(0,),
                        in_shardings=(self._state_sharding, None, None,
                                      None),
                        out_shardings=self._state_sharding)
                else:
                    self._decode_jit = jax.jit(
                        self._decode_step, donate_argnums=(0,),
                        in_shardings=(self._state_sharding, None, None,
                                      None, cs_sh),
                        out_shardings=self._state_sharding)
            else:
                self._cohort = jax.jit(self._cohort_step,
                                       donate_argnums=(0,))
                if cfg.async_agg:
                    self._commit_jit = jax.jit(self._commit_step,
                                               donate_argnums=(0,))
                    self._merge_jit = jax.jit(self._merge_step,
                                              donate_argnums=(0,))
                else:
                    self._decode_jit = jax.jit(self._decode_step,
                                               donate_argnums=(0,))

    def set_compile_watcher(self, watcher) -> None:
        """Compile observability hook (telemetry.JitWatcher): wraps the
        jitted round/val steps so every lowering+compile — including
        recompiles from shape changes or donation misses — is timed,
        cost-analyzed and logged instead of stalling silently. Call
        before the first round. A repeat call is a no-op: the wrapper
        needs the raw jitted functions' AOT surface, so double-wrapping
        would silently break the observation it exists to provide."""
        if getattr(self, "_compile_watched", False):
            return
        self._compile_watched = True
        self._round = watcher.wrap("round_step", self._round)
        self._val = watcher.wrap("val_step", self._val)
        if self._cohort is not None:
            self._cohort = watcher.wrap("cohort_step", self._cohort)
        if self._commit_jit is not None:
            self._commit_jit = watcher.wrap("commit_step", self._commit_jit)
        if self._decode_jit is not None:
            self._decode_jit = watcher.wrap("decode_step", self._decode_jit)

    def _probe_seq_grad_scale(self) -> float:
        """Measure how the round's cross-seq-shard gradient sum over-counts
        a replicated parameter's gradient on THIS mesh under THIS jax.

        Mirrors the round's exact structure: jax.grad INSIDE a
        shard_map(check_vma=False) block of a loss whose differentiable
        path crosses exactly one seq-axis psum (the seq-sharded token
        mean), followed by the seq-axis psum the aggregation applies.
        True d(loss)/d(w) of the probe function is 1, so the returned
        value IS the over-count factor (seq_shards under jax 0.9's
        psum->psum transpose with vma checking off; 1 if a future jax
        emits per-shard partial gradients). De-fangs the jax-version
        landmine flagged in VERDICT r4 weak #5 — the constant is probed,
        not assumed."""
        ax = self._seq_axis
        n = self._seq_shards

        def blk(w):
            g = jax.grad(lambda w: lax.psum(w, ax) / n)(w)
            return lax.psum(g, ax)

        out = shard_map(blk, mesh=self.mesh, in_specs=P(),
                        out_specs=P(), check_vma=False)(
                            jnp.asarray(1.0, jnp.float32))
        scale = float(out)
        assert scale > 0, scale
        return scale

    def _batch_pspec(self, seq_dim: Optional[int]) -> P:
        """PartitionSpec for one batch leaf: clients on dim 0, and (when
        seq-sharded) the seq axis at ``seq_dim``."""
        ax = self.shardings.axis
        if self._seq_axis is None or seq_dim is None:
            return P(ax)
        return P(*([ax] + [None] * (seq_dim - 1) + [self._seq_axis]))

    def batch_sharding(self):
        """Per-leaf NamedShardings for the batch jit argument — the layout
        any batch producer (e.g. a DeviceStore) must emit on a mesh.
        Without a seq axis every leaf shards on its leading (client) dim;
        with one, ``seq_spec`` must name every batch leaf (value = its
        sequence dim index, or None to replicate over seq)."""
        if self._seq_axis is None or not self._seq_spec:
            return self.shardings.round_axis
        return {k: NamedSharding(self.mesh, self._batch_pspec(sd))
                for k, sd in self._seq_spec.items()}

    # ------------------------------------------------------------------ state

    def _state_template(self):
        """Structure-only FedState (no allocation) for sharding layout."""
        return jax.eval_shape(self._make_state, 0, self.initial_weights)

    def init_state(self, seed: Optional[int] = None) -> FedState:
        seed = self.cfg.seed if seed is None else seed
        if self._state_sharding is not None:
            # create the state directly in its sharded layout — no single
            # device ever holds the full per-client arrays. The weights are
            # a jit ARGUMENT: as a closure constant they would be serialized
            # into the HLO shipped to the compiler (0.5 GB at GPT-2 scale)
            return jax.jit(self._make_state,
                           out_shardings=self._state_sharding)(
                               seed, self.initial_weights)
        return self._make_state(seed, self.initial_weights)

    def _make_state(self, seed, initial_weights) -> FedState:
        cfg = self.cfg
        # Server-side transmitted-space state lives at the mesh-padded
        # length so it shards evenly (see __init__). Per-client dense rows
        # are at true d single-device; on a mesh they live at d_row_pad in
        # the COLUMN-sharded home layout (see __init__ / parallel.mesh).
        # Sketch-table shapes are unaffected. Dense pre-image states for
        # the single-device SRHT path (see __init__) are dense too.
        dense = self._dense_preimage or cfg.mode != "sketch"
        server_tx = (self.d_pad,) if dense else cfg.transmitted_shape
        # dense client rows live at d_row_pad on a mesh (column-sharded
        # home layout, see __init__) and at true d single-device
        client_tx = ((self.d_row_pad,) if self._rows_cols
                     else (cfg.grad_size,) if dense
                     else cfg.transmitted_shape)
        d = cfg.grad_size
        n = self.num_clients
        zeros_tx = jnp.zeros(server_tx, jnp.float32)

        def maybe(shape, cond):
            return jnp.zeros(shape, jnp.float32) if cond else None

        return FedState(
            # copy: the round step donates its input state, and the shared
            # self.initial_weights buffer must survive repeated init_state()
            ps_weights=jnp.pad(jnp.asarray(initial_weights),
                               (0, self.d_pad - d)),
            Vvelocity=zeros_tx,
            Verror=jnp.zeros_like(zeros_tx),
            step=jnp.zeros((), jnp.int32),
            rng=jax.random.PRNGKey(seed),
            client_velocities=maybe((n,) + client_tx,
                                    cfg.needs_client_velocities),
            client_errors=maybe((n,) + client_tx, cfg.needs_client_errors),
            # every client starts with the initial PS weights
            # (reference fed_aggregator.py:105-111)
            client_weights=(jnp.broadcast_to(initial_weights, (n, d))
                            if cfg.do_topk_down else None),
            coord_last_update=(jnp.full((self.d_pad,), -1, jnp.int32)
                               if cfg.track_bytes else None),
            client_last_round=(jnp.zeros((n,), jnp.int32)
                               if cfg.track_bytes else None),
            nan_round=jnp.full((), -1, jnp.int32),
            sig_Vvelocity=maybe((d,), self._signals_shadow),
            sig_Verror=maybe((d,), self._signals_shadow),
            # async buffered aggregation (core/async_agg.py): the merge
            # buffer lives in FedState so it shards/checkpoints exactly
            # like the server EF state it feeds
            async_buffer=maybe(server_tx, cfg.async_agg),
            async_buffer_n=maybe((), cfg.async_agg),
            # normclip rolling reference: NaN = "round not yet seen"
            # (nanmedian ignores it) — a zero-init would anchor the
            # threshold at zero and clip everything on round 2
            defense_ref=(jnp.full((cfg.defense_window,), jnp.nan,
                                  jnp.float32)
                         if self._defense_ring else None),
        )

    # ------------------------------------------------- robustness tail

    def _transmit_tail(self, tx, out, adv, ref, client_rngs, step=None):
        """Shared per-client transmitted-space tail of the sync round's
        and async cohort's client blocks: adversarial injection ->
        nonfinite quarantine -> wire rounding -> robust (or plain-sum)
        aggregation. MUST stay one function: the async K=1/M=1
        bit-identity claim rides on both paths tracing exactly these
        ops. ``tx`` is None on the fused path (the aggregate is already
        accumulated; the robustness flags that need per-client uploads
        force the vmap path) — then agg comes back None and the caller
        keeps its own. Everything is compiled out at the flag defaults.
        Returns ``(agg_or_None, results, n_valid, stats, client_finite,
        defense_stats, cur_med)``."""
        cfg = self.cfg
        results, n_valid, stats = out.results, out.n_valid, out.stats
        client_finite = cur_med = defense_stats = agg = None
        if tx is not None:
            if self._adv_inject:
                tx = client_lib.inject_adversary(cfg, tx, adv,
                                                 client_rngs,
                                                 n_valid=n_valid)
                if stats is not None:
                    # the population stats must describe what each
                    # client actually UPLOADED: recomputing tx_norm on
                    # the post-injection transmit is what lets the
                    # update_norm_outlier monitor rule see a boosted
                    # client at all (the client step measured the
                    # honest pre-injection value)
                    flat = tx.reshape(tx.shape[0], -1)
                    stats = {**stats, "tx_norm": jnp.sqrt(
                        (flat * flat).sum(axis=1)).astype(jnp.float32)}
            if self._quarantine:
                tx, n_valid, results, client_finite = \
                    client_lib.quarantine_zero(tx, n_valid, results)
            td = self._table_dtype
            wire = (td != jnp.float32 and not self._dense_preimage
                    and cfg.mode == "sketch")
            if wire and not self._defer_encode and tx.ndim == 3:
                tx = tx.astype(td).astype(jnp.float32)
            elif (self._int8_wire and not self._defer_encode
                  and tx.ndim == 3):
                # per-client int8 uploads (the non-deferred path keeps
                # per-client tables — table clip): each slot quantizes
                # with its GLOBAL slot index as salt so draws stay
                # independent across mesh shards, and the server sums
                # the dequantized f32 reconstructions
                tx = client_lib.int8_wire_uploads(
                    cfg, tx, step, self._wire_block,
                    slot0=(lax.axis_index(self._axis) * tx.shape[0]
                           if self._axis is not None else 0))
            if cfg.defense != "none":
                agg, cur_med, defense_stats = robust_aggregate(
                    cfg, tx, n_valid, ref_thresh=ref,
                    axis_name=self._axis)
            else:
                agg = tx.sum(axis=0)
        return agg, results, n_valid, stats, client_finite, \
            defense_stats, cur_med

    def _defense_scalars(self, defense_stats, client_finite):
        """The ``metrics['defense']`` dict (schema-v5 scalars; NaN = not
        applicable for the configured defense/action, serialized null),
        or None when the robustness observability is off."""
        if not self._defense_stats:
            return None
        nan = jnp.full((), jnp.nan, jnp.float32)
        d = (dict(defense_stats) if defense_stats is not None
             else {"clip_frac": nan, "clip_thresh": nan,
                   "clipped_mass": nan, "trim_frac": nan})
        d["nonfinite_clients"] = (
            (~client_finite).sum().astype(jnp.float32)
            if client_finite is not None else nan)
        return d

    def _download_coord_counts(self, coord_last_update: jax.Array,
                               thresholds: jax.Array) -> jax.Array:
        """Per-client count of coordinates updated at-or-after the
        client's last download (the download-byte accounting): counts[w]
        = |{i : coord_last_update[i] >= thresholds[w]}|.

        Single device this streams BLOCK by block through a lax.scan —
        the obvious fused broadcast-compare-reduce materializes its
        converted (W, d) s32 intermediate on CPU and TPU (measured: the
        largest temp buffer of the fused-encode cohort, 2x the dense
        gradient this PR's encode fusion removes; ~4 GB at GPT-2 124M
        with 8 clients), so the accounting would single-handedly fail
        the dryrun's temp < d*4 gate. Peak temp here is O(W * block).
        On a mesh the broadcast form stays: the d axis is sharded, so
        each device holds only a (W, d/n) slice, and a host-chosen block
        split would fight the partitioner's own sharding of d."""
        if self._axis is not None:
            return (coord_last_update[None, :]
                    >= thresholds[:, None]).sum(axis=1)
        d = coord_last_update.shape[0]
        blk = max(512, min(65536, d // 16))
        nb = -(-d // blk)
        pad = nb * blk - d
        if pad:
            # padding must never satisfy ``>= threshold`` for any real
            # threshold (round indices) — int32 min is below them all
            coord_last_update = jnp.pad(
                coord_last_update, (0, pad),
                constant_values=jnp.iinfo(jnp.int32).min)
        blocks = coord_last_update.reshape(nb, blk)

        def body(acc, b):
            return acc + (b[None, :] >= thresholds[:, None]).sum(axis=1), None

        counts, _ = lax.scan(
            body, jnp.zeros(thresholds.shape, jnp.int32), blocks)
        return counts

    # ------------------------------------------------------- server dispatch

    def _apply_server_update(self, state: FedState, agg: jax.Array,
                             lr: jax.Array, server_rng: jax.Array, cs=None):
        """Mode + topology dispatch of the server update rule — ONE
        implementation consumed by the sync round step AND the split/
        async server tails (the ``_transmit_tail`` discipline: the
        sharded-vs-replicated parity gate rides on every path tracing
        exactly the same ops). Returns ``(update, Vvel, Verr,
        sup_mask)``; the sharded tail's update is a mesh-padded
        (d_pad,) sharded vector, the replicated sketch decode's a
        true-d one (the caller's padding block handles both)."""
        cfg = self.cfg
        server_lr = jnp.asarray(1.0) if cfg.mode == "fedavg" else lr
        if (cfg.mode == "sketch" and not self._dense_preimage
                and server_lr.ndim == 1 and not self._sharded_server):
            # the replicated sketch branch multiplies lr against the
            # TRUE-d decoded update (its state is the table, not a
            # padded dense vector); the sharded tail multiplies
            # per-shard against d_pad-length update shards, so there
            # the vector stays mesh-padded (padding coords get
            # multiplier 1 against an identically-zero update)
            server_lr = server_lr[: cfg.grad_size]
        if self._sharded_server:
            update, Vvel, Verr = self._sharded_server_apply(
                agg, state.Vvelocity, state.Verror, server_lr, cs)
            return update, Vvel, Verr, None
        return server_update(cfg, agg, state.Vvelocity, state.Verror,
                             server_lr, cs=cs, dp_rng=server_rng,
                             dense_preimage=self._dense_preimage)

    def _sharded_server_apply(self, agg: jax.Array, Vvel_prev: jax.Array,
                              Verr_prev: jax.Array, server_lr: jax.Array,
                              cs=None):
        """shard_map wrapper of core/server.sharded_sketch_server_update:
        the reduce-scattered aggregate and the column-sharded
        momentum/EF tables enter in the state's sketch_table layout
        (P(None, clients) — no reshard), the update leaves as the
        dense-vector layout's (d_pad,) coordinate shards (P(clients) —
        matching ps_weights, so the weight apply runs sharded with no
        further collective)."""
        ax = self._axis
        tab = P(None, ax)
        n_dev = self.mesh.shape[ax]
        lr_vec = server_lr.ndim == 1

        def blk(agg, vvel, verr, lr, cs):
            return sharded_sketch_server_update(
                self.cfg, agg, vvel, verr, lr, cs, axis=ax,
                n_shards=n_dev, d_pad=self.d_pad)

        fn = shard_map(blk, mesh=self.mesh,
                       in_specs=(tab, tab, tab,
                                 P(ax) if lr_vec else P(),
                                 jax.tree.map(lambda _: P(), cs)),
                       out_specs=(P(ax), tab, tab), check_vma=False)
        return fn(agg, Vvel_prev, Verr_prev, server_lr, cs)

    def _int8_reduce_scatter(self, agg: jax.Array,
                             step: jax.Array) -> jax.Array:
        """The quantized table reduce (called INSIDE the round's
        shard_map): per-device int8 quantization of the local partial
        table, an all_to_all of int8 column shards + f32 scales, and a
        shard-local f32 dequantize-accumulate — returning the same
        (r, c/n) column-shard layout the psum_scatter produced, so the
        sharded server tail consumes it unchanged (ops/wire.py
        int8_reduce_scatter owns the arithmetic)."""
        from commefficient_tpu.ops.wire import int8_reduce_scatter
        return int8_reduce_scatter(
            agg, axis=self._axis, n_shards=self.mesh.shape[self._axis],
            block=self._wire_block, seed=self.cfg.seed, round_idx=step)

    def _reduce_partials(self, partials: jax.Array,
                         step=None) -> jax.Array:
        """--decode_overlap + sharded server: the cohort left each
        device's LOCAL partial table stacked on the clients axis
        ((n, r, c), device i owning slot i) — run the deferred
        reduce-scatter here, in the DECODE executable, so the cohort's
        metrics sync waits out no ICI collective and the reduce runs
        under the next round's staging. Bitwise the sync round's
        collective: same per-device partials, same op, same bf16 wire
        rounding (the collective IS the wire)."""
        ax = self._axis
        td = self._table_dtype

        if self._int8_wire:
            # the int8 wire travels WITH the deferred collective exactly
            # like the bf16 rounding: quantization draws key off the
            # SAME state.step the monolithic round would use (the server
            # tail has not advanced it yet), so the split round stays
            # bitwise identical to the monolithic one
            def blk8(part, step):
                return self._int8_reduce_scatter(part[0], step)

            return shard_map(blk8, mesh=self.mesh,
                             in_specs=(P(ax, None, None), P()),
                             out_specs=P(None, ax),
                             check_vma=False)(partials, step)

        def blk(part):
            p = part[0]
            if td != jnp.float32:
                red = lax.optimization_barrier(lax.psum_scatter(
                    p.astype(td), ax, scatter_dimension=1, tiled=True))
                return red.astype(jnp.float32)
            return lax.psum_scatter(p, ax, scatter_dimension=1,
                                    tiled=True)

        return shard_map(blk, mesh=self.mesh,
                         in_specs=P(ax, None, None),
                         out_specs=P(None, ax),
                         check_vma=False)(partials)

    # ------------------------------------------------------------- round step

    def _round_step(self, state: FedState, client_ids: jax.Array,
                    batch: Any, mask: jax.Array, lr: jax.Array, cs=None,
                    gid=None):
        cfg = self.cfg
        if gid is None and self._layer_signals:
            # legacy 6-argument lowerings (tests/benches that lower the
            # round directly) omit the group-id map: fall back to the
            # runtime's copy as a trace-time constant. The REAL round
            # (self.round) always passes it as an argument — a constant
            # would serialize d_pad*4 bytes into the HLO shipped to the
            # compiler at GPT-2 scale, the same reason cs is an argument
            gid = self._gid
        num_workers = client_ids.shape[0]
        keys = jax.random.split(state.rng, num_workers + 2)
        rng, server_rng, client_rngs = keys[0], keys[1], keys[2:]

        # ---- download byte accounting, before this round's update
        # (re-design of reference fed_aggregator.py:239-289; see state.py)
        download_bytes = upload_bytes = None
        down_slot = up_slot = None
        client_last_round = state.client_last_round
        if cfg.track_bytes:
            thresholds = state.client_last_round[client_ids]
            counts = self._download_coord_counts(state.coord_last_update,
                                                 thresholds)
            # per-SLOT byte vectors kept alive for the client_stats
            # quantiles (telemetry/clients.py) — the scatter below is the
            # same data keyed by client id over the whole universe
            down_slot = 4.0 * counts.astype(jnp.float32)
            # exact wire-dtype payload (cfg.upload_wire_bytes): the f32
            # wire keeps the pre-wire 4*upload_floats constant
            up_slot = jnp.full((num_workers,), self._upload_bytes,
                               jnp.float32)
            download_bytes = jnp.zeros(self.num_clients, jnp.float32).at[
                client_ids].set(down_slot)
            upload_bytes = jnp.zeros(self.num_clients, jnp.float32).at[
                client_ids].set(up_slot)
            client_last_round = state.client_last_round.at[client_ids].set(
                state.step)

        # ---- per-client weights (download path)
        client_weights = state.client_weights
        if cfg.do_topk_down:
            stale = state.client_weights[client_ids]
            ps_true = state.ps_weights[: cfg.grad_size]
            used_weights = jax.vmap(
                lambda w: client_lib.topk_down_weights(
                    cfg, ps_true, w))(stale)
            client_weights = state.client_weights.at[client_ids].set(
                used_weights)
            params_axis = 0
        else:
            # all clients read the current PS weights
            # (reference fed_worker.py:159)
            used_weights = state.ps_weights
            params_axis = None

        # ---- per-client persistent rows
        vel_rows = (state.client_velocities[client_ids]
                    if state.client_velocities is not None else None)
        err_rows = (state.client_errors[client_ids]
                    if state.client_errors is not None else None)

        # ---- client compute + aggregation
        # (reference fed_worker.py:131,138 + fed_aggregator.py:329-332)
        # vmapped over the round's client axis; on a mesh the block below is
        # shard_mapped so each device sums (and, deferred, sketch-encodes)
        # its local clients before ONE explicit psum over ICI — the direct
        # analogue of the reference's per-worker compute + NCCL reduce.
        has_vel = vel_rows is not None
        has_err = err_rows is not None

        # ---- robustness inputs: per-slot adversary assignment (the
        # baked universe constant indexed by this round's client ids)
        # and the normclip rolling-median reference (NaN while the ring
        # is cold — robust_aggregate falls back to the round's own
        # median). Both None (and compiled out) when the flags are off.
        adv_slot = (self._adv_universe[client_ids]
                    if self._adversary else None)
        ref_thresh = (jnp.nanmedian(state.defense_ref)
                      if self._defense_ring else None)

        def client_block(used_weights, batch, mask, vel_rows, err_rows,
                         client_rngs, lr, adv, ref, step, cs):
            if self._rows_cols and self._axis is not None:
                # home->compute layout: each device holds a (W, d_row_pad/n)
                # column slice of all round rows; ONE all_to_all turns it
                # into the (W/n, d_row_pad) full rows of its local clients
                def rows_to_compute(x):
                    full = lax.all_to_all(x, self._axis, split_axis=0,
                                          concat_axis=1, tiled=True)
                    return full[:, : cfg.grad_size]
                if vel_rows is not None:
                    vel_rows = rows_to_compute(vel_rows)
                if err_rows is not None:
                    err_rows = rows_to_compute(err_rows)
            if params_axis is None:
                # clients read the (padded, possibly sharded) PS weights;
                # the slice back to true d happens here, inside the block,
                # where the weights are already a full local copy
                used = used_weights[: cfg.grad_size]
            else:
                used = used_weights
            if self._labelflip:
                # data-space injection: adversarial clients train on
                # flipped labels (core/client.flip_labels) — applied on
                # the whole (W, B) batch so every client path (vmap,
                # fused, fedavg) sees it identically
                batch = client_lib.flip_labels(batch, adv,
                                               self._flip_classes)
            # --sketch_dtype bfloat16 wire (see config.py): per-client
            # table uploads round to bf16 before the server's accumulation
            # (non-deferred encode only — deferred encode has no
            # per-client table), and the cross-device SUM rounds once — by
            # the bf16 psum on a mesh, explicitly here on a single device
            # (quantization points matched up to psum partial-sum order).
            td = self._table_dtype
            wire = (td != jnp.float32 and not self._dense_preimage
                    and cfg.mode == "sketch")
            tx = None
            if cfg.mode == "fedavg":
                # fedavg applies the LR on the CLIENT against true-d
                # weights; a per-param vector arrives mesh-padded for the
                # server consumers, so slice it back here
                lr_c = lr[: cfg.grad_size] if lr.ndim == 1 else lr
                out = jax.vmap(
                    self._client_fn,
                    in_axes=(params_axis, 0, 0, None, 0))(
                        used, batch, mask, lr_c, client_rngs)
                tx = out.transmit
            elif self._fused:
                # jointly-computed round gradient (make_fused_grad): ONE
                # (d,) accumulator over all local clients' microbatches —
                # no per-client (W, d) gradient materialization (the
                # robustness flags that need per-client uploads force
                # the vmap path, see __init__). Under the fused sketch
                # encode the accumulator is the (r, c) table itself.
                agg, f_results, f_nvalid = self._fused_fn(used, batch,
                                                          mask, cs)
                out = client_lib.ClientOut(None, None, None, f_results,
                                           f_nvalid)
            else:
                out = jax.vmap(
                    self._client_fn,
                    in_axes=(params_axis, 0, 0,
                             0 if has_vel else None,
                             0 if has_err else None, 0, None))(
                        used, batch, mask, vel_rows, err_rows,
                        client_rngs, cs)
                tx = out.transmit
            # ---- shared per-client transmitted-space tail (injection
            # -> quarantine -> wire -> robust aggregation); compiled out
            # entirely at the flag defaults — the off-path ops and their
            # order stay byte-identical to the pre-defense round
            t_agg, results, n_valid, stats, client_finite, \
                defense_stats, cur_med = self._transmit_tail(
                    tx, out, adv, ref, client_rngs, step)
            if t_agg is not None:
                agg = t_agg
            sig_dense = None
            if (self._defer_encode and not self._dense_preimage
                    and not self._fused_encode):
                # fused-encode: the clients already accumulated in table
                # space, so the deferred encode-once is a no-op (its
                # degenerate case) and no dense aggregate exists to
                # capture (_signals_dense_cap was cleared in __init__)
                if self._signals_dense_cap:
                    # keep the dense summed gradient alive for the signal
                    # norms/shadow (single device only — the buffer
                    # already exists here, this just extends its lifetime
                    # to the round step's tail)
                    sig_dense = agg
                agg = cs.encode(agg)
            if wire and self._axis is None and agg.ndim == 2:
                agg = agg.astype(td).astype(jnp.float32)
            elif (self._int8_wire and self._axis is None
                  and agg.ndim == 2 and self._defer_encode):
                # single-device deferred/fused encode: one table crosses
                # the simulated wire (the per-device-partial analogue of
                # the mesh quantize; per-client tables were already
                # quantized in _transmit_tail on the non-deferred path)
                from commefficient_tpu.ops.wire import wire_round_trip
                agg = wire_round_trip(agg, self._wire_block,
                                      seed=cfg.seed, round_idx=step,
                                      salt=0)
            n_total = n_valid.sum()
            if self._axis is not None:
                # the aggregation spans every mesh axis: clients sum across
                # the clients axis, and (in seq mode) each client's partial
                # per-shard gradients sum across the seq axis — one fused
                # collective either way
                all_axes = tuple(self.mesh.axis_names)
                if agg.ndim == 1:
                    # dense modes: reduce_scatter the client sum so each
                    # device receives only its d_pad/n shard of the summed
                    # gradient — the server update then runs fully sharded.
                    # (The ICI analogue of encode-before-reduce for dense
                    # payloads; reference reduce: fed_aggregator.py:326-332)
                    agg = lax.psum_scatter(
                        jnp.pad(agg, (0, self.d_pad - cfg.grad_size)),
                        all_axes, scatter_dimension=0, tiled=True)
                elif self._sharded_server:
                    # sharded server tail: reduce-SCATTER over table
                    # columns replaces the replicated table psum (the
                    # dense-mode analogue above) — each device receives
                    # only its c/n column shard of the summed table, the
                    # (r, c) replicated result never exists, and the
                    # momentum/EF tail runs on the shards
                    # (core/server.sharded_sketch_server_update). The
                    # bfloat16 wire covers this collective exactly like
                    # the psum it replaces (the barrier pins the payload
                    # dtype against XLA hoisting the f32 convert back
                    # through the reduce); the int8 wire replaces the
                    # reduce itself with the quantized all_to_all +
                    # shard-local dequantize-accumulate.
                    if self._int8_wire:
                        agg = self._int8_reduce_scatter(agg, step)
                    elif td != jnp.float32:
                        agg = lax.optimization_barrier(lax.psum_scatter(
                            agg.astype(td), self._axis,
                            scatter_dimension=1, tiled=True))
                        agg = agg.astype(jnp.float32)
                    else:
                        agg = lax.psum_scatter(agg, self._axis,
                                               scatter_dimension=1,
                                               tiled=True)
                else:
                    # sketch tables are already the compressed payload: one
                    # table-sized psum (analogue of encode-before-NCCL);
                    # --sketch_dtype bfloat16 halves this payload — the
                    # multichip bandwidth lever (accumulation inside the
                    # collective is then bf16 too; measured impact in
                    # tests/test_parallel.py + README)
                    if td != jnp.float32 and agg.ndim == 2:
                        # the barrier pins the collective's payload dtype:
                        # without it XLA hoists the f32 convert back
                        # through the all-reduce and the wire stays f32
                        agg = lax.optimization_barrier(
                            lax.psum(agg.astype(td), all_axes))
                        agg = agg.astype(jnp.float32)
                    else:
                        agg = lax.psum(agg, all_axes)
                if self._seq_axis is not None:
                    # shard_map autodiff with vma checking off transposes
                    # psum to psum, so each seq shard's gradient comes out
                    # scaled (every differentiable path in the seq-sharded
                    # loss crosses exactly ONE psum — the LM token mean or
                    # the MC logit reduction; verified uniform by
                    # tests/test_seqparallel.py's round equivalence). The
                    # cross-shard sum above therefore over-counts by a
                    # factor that DEPENDS ON THE JAX VERSION's transpose
                    # rule (as of jax 0.9 with check_vma=False it is
                    # seq_shards; with vma checking on it would be 1).
                    # Rather than hard-code a jax internal, the factor is
                    # MEASURED at runtime init by differentiating a known
                    # seq-sharded function on this mesh under the same
                    # check_vma setting (_probe_seq_grad_scale) — a jax
                    # upgrade that changes the transpose changes the probe
                    # identically. tests/test_seqparallel.py::
                    # test_seq_sharded_round_matches_dense stays as the
                    # end-to-end guard.
                    agg = agg / self._seq_grad_scale
                # datum counts are identical on every seq shard (the mask
                # replicates over seq) — sum over clients only
                n_total = lax.psum(n_total, self._axis)
            vel_out, err_out = out.velocity, out.error
            if client_finite is not None:
                # a struck client's persistent local rows must not absorb
                # its nonfinite round — keep the previous rows (still in
                # the compute layout here, matching vel_out/err_out)
                if vel_out is not None:
                    finb = client_finite.reshape(
                        (-1,) + (1,) * (vel_out.ndim - 1))
                    vel_out = jnp.where(finb, vel_out, vel_rows)
                if err_out is not None:
                    finb = client_finite.reshape(
                        (-1,) + (1,) * (err_out.ndim - 1))
                    err_out = jnp.where(finb, err_out, err_rows)
            if self._rows_cols and self._axis is not None:
                # compute->home layout: the reverse all_to_all routes each
                # updated row's columns back to their owning shards
                def rows_to_home(x):
                    xp = jnp.pad(
                        x, ((0, 0), (0, self.d_row_pad - cfg.grad_size)))
                    return lax.all_to_all(xp, self._axis, split_axis=1,
                                          concat_axis=0, tiled=True)
                if vel_out is not None:
                    vel_out = rows_to_home(vel_out)
                if err_out is not None:
                    err_out = rows_to_home(err_out)
            return agg, n_total, vel_out, err_out, results, \
                n_valid, sig_dense, stats, client_finite, \
                defense_stats, cur_med

        if self._axis is not None:
            ax = self._axis
            row = P(ax)
            if self._seq_axis and self._seq_spec:
                batch_specs = {k: self._batch_pspec(sd)
                               for k, sd in self._seq_spec.items()}
            else:
                batch_specs = jax.tree.map(lambda _: row, batch)
            # dense client rows arrive/leave in the column-sharded home
            # layout (see __init__); sketch table rows keep the row layout
            row_spec = P(None, ax) if self._rows_cols else row
            in_specs = (
                row if params_axis == 0 else P(),
                batch_specs,
                row,
                row_spec if has_vel else None,
                row_spec if has_err else None,
                row,
                P(),
                row if self._adversary else None,      # adv slot mask
                P() if self._defense_ring else None,   # normclip reference
                P() if self._int8_wire else None,      # wire round key
                jax.tree.map(lambda _: P(), cs),
            )
            # dense modes leave the block as a reduce_scattered shard of
            # the summed gradient (over ALL axes); sketch leaves as a
            # COLUMN-sharded reduce-scattered table under the sharded
            # server tail (the state tables' home layout, so the tail
            # consumes it in place), or a replicated (psum'd) table on
            # the replicated path
            dense_agg_spec = P(tuple(self.mesh.axis_names))
            if cfg.mode != "sketch":
                agg_spec = dense_agg_spec
            elif self._sharded_server:
                agg_spec = P(None, ax)
            else:
                agg_spec = P()
            out_specs = (
                agg_spec,
                P(),
                row_spec if (cfg.mode != "fedavg" and has_vel) else None,
                row_spec if (cfg.mode != "fedavg" and has_err) else None,
                tuple(row for _ in range(cfg.num_results_train)),
                row,
                None,   # sig_dense: never captured on a mesh (see __init__)
                # per-client stat scalars shard like every other
                # per-client quantity (telemetry/clients.py)
                ({k: row for k in CLIENT_GRAD_KEYS}
                 if self._client_grad_stats else None),
                # per-client finite flags (quarantine)
                row if self._quarantine else None,
                # defense scalars leave the block psum'd/replicated
                ({k: P() for k in ("clip_frac", "clip_thresh",
                                   "clipped_mass", "trim_frac")}
                 if cfg.defense != "none" else None),
                P() if self._defense_ring else None,   # cur_med
            )
            # check_vma off: the client step's scan carries start as
            # replicated zeros and become device-varying on the first
            # iteration, which the strict varying-axis checker rejects
            client_block = shard_map(client_block, mesh=self.mesh,
                                     in_specs=in_specs, out_specs=out_specs,
                                     check_vma=False)

        step_arg = state.step if self._int8_wire else None
        agg, n_total, vel_new, err_new, results, n_valid, sig_dense, \
            client_grad_stats, client_finite, defense_stats, cur_med = \
            client_block(used_weights, batch, mask, vel_rows, err_rows,
                         client_rngs, lr, adv_slot, ref_thresh, step_arg,
                         cs)
        out = client_lib.ClientOut(None, vel_new, err_new, results, n_valid,
                                   client_grad_stats)
        total = jnp.maximum(n_total, 1.0)
        agg = agg / total
        if sig_dense is not None:
            # same normalization as agg: the signals compare like with like
            sig_dense = sig_dense / total

        # ---- server update (mode + topology dispatch: the sharded
        # sketch tail on an eligible mesh, core/server.py's replicated
        # rules otherwise — ONE implementation shared with the split/
        # async server tails, see _apply_server_update)
        update, Vvel, Verr, sup_mask = self._apply_server_update(
            state, agg, lr, server_rng, cs)

        # ---- compression-signal health (telemetry/signals.py): on-device
        # scalars fetched asynchronously alongside the loss — computed
        # BEFORE the update is padded so true-d slicing stays uniform
        signals = None
        sig_vel_new, sig_err_new = state.sig_Vvelocity, state.sig_Verror
        if self._signals:
            signals, sig_vel_new, sig_err_new = round_signals(
                cfg, agg=agg, update=update,
                Vvel_prev=state.Vvelocity, Verr_prev=state.Verror,
                Vvel_new=Vvel, Verr_new=Verr, cs=cs,
                dense_agg=sig_dense,
                sig_vel=state.sig_Vvelocity, sig_err=state.sig_Verror)

        # ---- layer-wise attribution (telemetry/layer_signals.py):
        # per-group reductions of the same pre-padding quantities the
        # scalar signals just measured — the conservation laws (group
        # masses sum to the whole-vector norms squared, support counts
        # sum to nnz) are dryrun-gated against exactly that pairing
        layer_signals = None
        if self._layer_signals:
            from commefficient_tpu.telemetry.layer_signals import \
                layer_group_signals
            # dense gradient / dense EF sources, where the round holds
            # them (see __init__._layer_grad_mass; None -> null fields)
            dense = cfg.mode != "sketch" or self._dense_preimage
            grad_dense = (agg if dense
                          else sig_dense if self._layer_grad_mass
                          else None)
            err_dense = (Verr if dense
                         else sig_err_new if sig_err_new is not None
                         else None)
            err_pre = None
            if cfg.signals_exact:
                # the SAME dense pre-feedback error round_signals'
                # topk_overlap selects against (signals.py documents
                # the two availability paths) — recomputed here from
                # the pre-update state so the modules stay decoupled
                rho = cfg.virtual_momentum
                if state.sig_Verror is not None and sig_dense is not None:
                    err_pre = (state.sig_Verror + sig_dense
                               + rho * state.sig_Vvelocity)
                elif cfg.mode == "true_topk" or (cfg.mode == "sketch"
                                                 and dense):
                    err_pre = (state.Verror + agg
                               + rho * state.Vvelocity)[: cfg.grad_size]
            layer_signals = layer_group_signals(
                cfg, gid=gid, n_groups=self.group_spec.n_groups,
                update=update, grad_dense=grad_dense,
                err_dense=err_dense, err_pre=err_pre)

        # ---- per-client population stats (telemetry/clients.py): quantile
        # summaries along the client axis, riding the same async metrics
        # fetch as the loss — per-client vectors never leave the device
        client_stats = None
        if self._client_stats:
            per_client = {"loss": out.results[0]}
            if out.stats is not None:
                per_client.update(out.stats)
            else:
                # fused path: no per-client gradient exists (see __init__
                # _client_grad_stats) — NaN quantiles, never fake zeros
                nan_w = jnp.full((num_workers,), jnp.nan, jnp.float32)
                per_client.update({k: nan_w for k in CLIENT_GRAD_KEYS})
            if cfg.track_bytes:
                per_client["upload_bytes"] = up_slot
                per_client["download_bytes"] = down_slot
            rep = None
            if self.mesh is not None:
                # one W-sized all-gather for the WHOLE summary: without
                # the replication constraint every per-key quantile
                # lowers to its own tiny collectives (launch-count
                # pathology, see summarize_per_client)
                rep_sh = NamedSharding(self.mesh, P())

                def rep(x, _sh=rep_sh):
                    return lax.with_sharding_constraint(x, _sh)
            client_stats = summarize_per_client(per_client, out.n_valid,
                                                replicate_fn=rep)

        if self.d_pad != cfg.grad_size:
            if update.shape[0] == cfg.grad_size:
                # sketch decode produces a true-d update; pad to the
                # server's sharded length
                update = jnp.pad(update, (0, self.d_pad - cfg.grad_size))
            else:
                # keep the padding coordinates exactly zero (server-side DP
                # noise would otherwise drift them and pollute the
                # changed-coordinate byte accounting)
                update = jnp.where(
                    jnp.arange(self.d_pad) < cfg.grad_size, update, 0.0)
        ps_weights = state.ps_weights - update

        # ---- write back per-client rows
        client_velocities = state.client_velocities
        if out.velocity is not None and client_velocities is not None:
            new_rows = out.velocity
            if cfg.mode == "true_topk" and sup_mask is not None:
                # momentum factor masking on participating clients' local
                # velocities (intended behavior of fed_aggregator.py:528-533)
                # — the server mask is in padded space; rows are at true d
                # single-device, at d_row_pad in the mesh home layout
                # (padding coords are identically 0 and where() keeps them 0)
                sm = sup_mask[: cfg.grad_size]
                if self._rows_cols:
                    sm = jnp.pad(sm, (0, self.d_row_pad - cfg.grad_size))
                new_rows = jnp.where(sm[None, :], 0.0, new_rows)
            client_velocities = client_velocities.at[client_ids].set(new_rows)
        client_errors = state.client_errors
        if out.error is not None and client_errors is not None:
            client_errors = client_errors.at[client_ids].set(out.error)

        # ---- byte accounting: record which coordinates changed this round
        coord_last_update = state.coord_last_update
        if cfg.track_bytes:
            coord_last_update = jnp.where(
                update != 0, state.step, state.coord_last_update)

        # device-side divergence detection: record the FIRST round where a
        # client loss, the aggregated gradient, or the weight update went
        # non-finite (fused isfinite+reduce; a NaN gradient does not always
        # survive the top-k select into the update, and the reference's
        # host check is on the loss, cv_train.py:222-224)
        bad = ~jnp.isfinite(update).all() | ~jnp.isfinite(agg).all()
        if self._quarantine:
            # per-client nonfinites were zeroed OUT of the aggregate in
            # the client block (their losses too) — only a round with no
            # finite DATA-CARRYING client left, or nonfinite SERVER
            # state, still aborts. A nonfinite flag can only come from a
            # live slot (benched/masked placeholders upload finite
            # zeros), so "fully-nonfinite round" == some client went
            # nonfinite AND no finite client with data remains
            # (n_valid is post-zeroing: > 0 iff live AND finite)
            bad = bad | ((~client_finite).any()
                         & ~(out.n_valid > 0).any())
        else:
            bad = bad | ~jnp.isfinite(out.results[0]).all()
        nan_round = jnp.where((state.nan_round < 0) & bad, state.step,
                              state.nan_round)

        # normclip rolling reference: this round's median per-datum norm
        # enters the ring AFTER the round used the PAST medians — the
        # attack round cannot vouch for its own normality
        defense_ref = state.defense_ref
        if self._defense_ring:
            defense_ref = state.defense_ref.at[
                jnp.mod(state.step, cfg.defense_window)].set(cur_med)

        defense = self._defense_scalars(defense_stats, client_finite)

        new_state = FedState(
            ps_weights=ps_weights,
            Vvelocity=Vvel,
            Verror=Verr,
            step=state.step + 1,
            rng=rng,
            client_velocities=client_velocities,
            client_errors=client_errors,
            client_weights=client_weights,
            coord_last_update=coord_last_update,
            client_last_round=client_last_round,
            nan_round=nan_round,
            sig_Vvelocity=sig_vel_new,
            sig_Verror=sig_err_new,
            # pass-through: the synchronous round never touches the async
            # buffer (the two paths are mutually exclusive per config)
            async_buffer=state.async_buffer,
            async_buffer_n=state.async_buffer_n,
            defense_ref=defense_ref,
        )
        metrics = {
            "results": out.results,          # tuple of (num_workers,) arrays
            "n_valid": out.n_valid,
            "download_bytes": download_bytes,
            "upload_bytes": upload_bytes,
            "signals": signals,              # dict of scalars, or None
            # dict of (G,) per-group vectors, or None (layer_signals.py)
            "layer_signals": layer_signals,
            "client_stats": client_stats,    # quantile summaries, or None
            "defense": defense,              # dict of scalars, or None
            # (W,) bool, quarantine mode only: the host-side ledger's
            # per-round feed (False = zeroed out of this aggregate)
            "client_finite": client_finite,
        }
        return new_state, metrics

    def _val_step(self, ps_weights: jax.Array, batch: Any, mask: jax.Array):
        return self._val_fn_inner(ps_weights[: self.cfg.grad_size], batch,
                                  mask)

    def _val_step_sharded(self, ps_weights: jax.Array, batch: Any,
                          mask: jax.Array):
        """Mesh-parallel val: batch items shard over every mesh axis; each
        device evaluates its slice with the full (all-gathered) weights;
        per-shard means recombine as valid-ITEM-weighted sums — exactly
        the convention the host loops already use ACROSS batches
        (run_validation / compat._call_val accumulate results*n_valid).
        For per-item losses (CV) this equals the dense whole-batch step
        up to fp32 reduction order (asserted by tests/test_parallel.py::
        test_sharded_val_matches_dense). For metrics whose within-shard
        mean is over a different unit (GPT-2's per-TOKEN lm NLL), the
        item weighting is an approximation of the whole-batch token mean
        — the same approximation the cross-batch accumulation already
        makes, just at shard granularity."""
        axes = tuple(self.mesh.axis_names)
        nres = self.cfg.num_results_val

        def block(w_shard, batch, mask):
            w = lax.all_gather(w_shard, axes, tiled=True)
            res, n = self._val_fn_inner(w[: self.cfg.grad_size], batch, mask)
            num = lax.psum(jnp.stack([r * n for r in res]), axes)
            den = lax.psum(n, axes)
            safe = jnp.maximum(den, 1.0)
            return tuple(num[i] / safe for i in range(len(res))), den

        item = P(axes)
        return shard_map(
            block, mesh=self.mesh,
            in_specs=(P(axes), jax.tree.map(lambda _: item, batch), item),
            out_specs=(tuple(P() for _ in range(nres)), P()),
            check_vma=False)(ps_weights, batch, mask)

    # -------------------------------------------- async buffered aggregation
    #
    # The synchronous _round_step fuses client compute and the server
    # update into one program; async buffered aggregation (FedBuff-style,
    # core/async_agg.py) needs them apart: cohort gradients are computed
    # against the weights AT DISPATCH, land out of order, merge into the
    # FedState buffer by (staleness-weighted) addition, and the server
    # momentum+EF step runs only when the buffer goal is reached. The
    # three pieces below mirror the sync step's code EXACTLY over the
    # combinations validate_async_combo admits (no per-client persistent
    # rows, no topk_down) — with max_inflight=1, buffer_goal=1 and no
    # scenario latency the composition is bit-identical to _round_step
    # (asserted per mode by __graft_entry__.dryrun_multichip).

    def _cohort_step(self, state: FedState, client_ids: jax.Array,
                     batch: Any, mask: jax.Array, lr: jax.Array, cs=None):
        """Client half of the round: the same client block as
        _round_step, stopping BEFORE the datum normalization and server
        update. Advances only the dispatch-time state (rng, download
        byte accounting, nan flag) and returns the cohort payload: the
        UNNORMALIZED transmitted-space sum, its datum count, per-client
        results/stats, and the round's exact byte costs."""
        cfg = self.cfg
        num_workers = client_ids.shape[0]
        keys = jax.random.split(state.rng, num_workers + 1)
        rng, client_rngs = keys[0], keys[1:]

        # download byte accounting at DISPATCH: the client reads the
        # weights of server version ``state.step`` (in async mode the
        # step counter advances per COMMIT — the server version)
        download_bytes = upload_bytes = None
        down_slot = up_slot = None
        client_last_round = state.client_last_round
        if cfg.track_bytes:
            thresholds = state.client_last_round[client_ids]
            counts = self._download_coord_counts(state.coord_last_update,
                                                 thresholds)
            down_slot = 4.0 * counts.astype(jnp.float32)
            up_slot = jnp.full((num_workers,), self._upload_bytes,
                               jnp.float32)
            download_bytes = jnp.zeros(self.num_clients, jnp.float32).at[
                client_ids].set(down_slot)
            upload_bytes = jnp.zeros(self.num_clients, jnp.float32).at[
                client_ids].set(up_slot)
            client_last_round = state.client_last_round.at[client_ids].set(
                state.step)

        adv_slot = (self._adv_universe[client_ids]
                    if self._adversary else None)
        ref_thresh = (jnp.nanmedian(state.defense_ref)
                      if self._defense_ring else None)

        def client_block(used_weights, batch, mask, client_rngs, lr, adv,
                         ref, step, cs):
            # validate_async_combo guarantees no vel/err rows and no
            # topk_down here — otherwise byte-for-byte the sync block
            used = used_weights[: cfg.grad_size]
            if self._labelflip:
                batch = client_lib.flip_labels(batch, adv,
                                               self._flip_classes)
            td = self._table_dtype
            wire = (td != jnp.float32 and not self._dense_preimage
                    and cfg.mode == "sketch")
            tx = None
            if cfg.mode == "fedavg":
                lr_c = lr[: cfg.grad_size] if lr.ndim == 1 else lr
                out = jax.vmap(
                    self._client_fn, in_axes=(None, 0, 0, None, 0))(
                        used, batch, mask, lr_c, client_rngs)
                tx = out.transmit
            elif self._fused:
                agg, f_results, f_nvalid = self._fused_fn(used, batch,
                                                          mask, cs)
                out = client_lib.ClientOut(None, None, None, f_results,
                                           f_nvalid)
            else:
                out = jax.vmap(
                    self._client_fn,
                    in_axes=(None, 0, 0, None, None, 0, None))(
                        used, batch, mask, None, None, client_rngs, cs)
                tx = out.transmit
            # the SAME transmitted-space tail as the sync block
            # (adversarial fates act at COHORT COMPUTE, which both paths
            # share — the reason injection works with and without
            # --async_agg)
            t_agg, results, n_valid, stats, client_finite, \
                defense_stats, cur_med = self._transmit_tail(
                    tx, out, adv, ref, client_rngs, step)
            if t_agg is not None:
                agg = t_agg
            if (self._defer_encode and not self._dense_preimage
                    and not self._fused_encode):
                agg = cs.encode(agg)
            if wire and self._axis is None and agg.ndim == 2:
                agg = agg.astype(td).astype(jnp.float32)
            elif (self._int8_wire and self._axis is None
                  and agg.ndim == 2 and self._defer_encode):
                # same single-device simulated wire as the sync round —
                # the async K=1/M=1 bit-identity rides on it
                from commefficient_tpu.ops.wire import wire_round_trip
                agg = wire_round_trip(agg, self._wire_block,
                                      seed=cfg.seed, round_idx=step,
                                      salt=0)
            n_total = n_valid.sum()
            if self._axis is not None:
                all_axes = tuple(self.mesh.axis_names)
                if agg.ndim == 1:
                    agg = lax.psum_scatter(
                        jnp.pad(agg, (0, self.d_pad - cfg.grad_size)),
                        all_axes, scatter_dimension=0, tiled=True)
                elif self._reduce_in_decode:
                    # --decode_overlap + sharded server: the table
                    # reduce MOVES into the decode executable — the
                    # cohort ends at this device's LOCAL partial table
                    # (stacked on the clients axis, zero wire traffic),
                    # so the metrics sync completes without waiting any
                    # ICI collective and the reduce-scatter runs under
                    # round t+1's staging (see _reduce_partials; the
                    # bf16 wire rounding travels WITH the collective)
                    agg = agg[None]
                elif self._sharded_server:
                    # same reduce-scattered table collective as the
                    # sync round's client block (bf16 barrier-pinned;
                    # int8 = the quantized all_to_all reduce)
                    if self._int8_wire:
                        agg = self._int8_reduce_scatter(agg, step)
                    elif td != jnp.float32:
                        agg = lax.optimization_barrier(lax.psum_scatter(
                            agg.astype(td), self._axis,
                            scatter_dimension=1, tiled=True))
                        agg = agg.astype(jnp.float32)
                    else:
                        agg = lax.psum_scatter(agg, self._axis,
                                               scatter_dimension=1,
                                               tiled=True)
                else:
                    if td != jnp.float32 and agg.ndim == 2:
                        agg = lax.optimization_barrier(
                            lax.psum(agg.astype(td), all_axes))
                        agg = agg.astype(jnp.float32)
                    else:
                        agg = lax.psum(agg, all_axes)
                if self._seq_axis is not None:
                    agg = agg / self._seq_grad_scale
                n_total = lax.psum(n_total, self._axis)
            return agg, n_total, results, n_valid, stats, \
                client_finite, defense_stats, cur_med

        if self._axis is not None:
            ax = self._axis
            row = P(ax)
            if self._seq_axis and self._seq_spec:
                batch_specs = {k: self._batch_pspec(sd)
                               for k, sd in self._seq_spec.items()}
            else:
                batch_specs = jax.tree.map(lambda _: row, batch)
            in_specs = (P(), batch_specs, row, row, P(),
                        row if self._adversary else None,
                        P() if self._defense_ring else None,
                        P() if self._int8_wire else None,
                        jax.tree.map(lambda _: P(), cs))
            dense_agg_spec = P(tuple(self.mesh.axis_names))
            if cfg.mode != "sketch":
                agg_spec = dense_agg_spec
            elif self._reduce_in_decode:
                # stacked per-device partial tables (see client_block)
                agg_spec = P(ax, None, None)
            elif self._sharded_server:
                agg_spec = P(None, ax)
            else:
                agg_spec = P()
            out_specs = (
                agg_spec,
                P(),
                tuple(row for _ in range(cfg.num_results_train)),
                row,
                ({k: row for k in CLIENT_GRAD_KEYS}
                 if self._client_grad_stats else None),
                row if self._quarantine else None,
                ({k: P() for k in ("clip_frac", "clip_thresh",
                                   "clipped_mass", "trim_frac")}
                 if cfg.defense != "none" else None),
                P() if self._defense_ring else None,
            )
            client_block = shard_map(client_block, mesh=self.mesh,
                                     in_specs=in_specs, out_specs=out_specs,
                                     check_vma=False)

        agg, n_total, results, n_valid, grad_stats, client_finite, \
            defense_stats, cur_med = client_block(
                state.ps_weights, batch, mask, client_rngs, lr, adv_slot,
                ref_thresh, state.step if self._int8_wire else None, cs)

        client_stats = None
        if self._client_stats:
            per_client = {"loss": results[0]}
            if grad_stats is not None:
                per_client.update(grad_stats)
            else:
                nan_w = jnp.full((num_workers,), jnp.nan, jnp.float32)
                per_client.update({k: nan_w for k in CLIENT_GRAD_KEYS})
            if cfg.track_bytes:
                per_client["upload_bytes"] = up_slot
                per_client["download_bytes"] = down_slot
            rep = None
            if self.mesh is not None:
                rep_sh = NamedSharding(self.mesh, P())

                def rep(x, _sh=rep_sh):
                    return lax.with_sharding_constraint(x, _sh)
            client_stats = summarize_per_client(per_client, n_valid,
                                                replicate_fn=rep)

        # dispatch-side divergence detection: a poisoned cohort sum must
        # be flagged before it can merge into the buffer
        bad = ~jnp.isfinite(agg).all()
        if self._quarantine:
            # same "fully-nonfinite" semantics as the sync round: a
            # benched/masked placeholder slot never vouches for a cohort
            # whose every live upload diverged
            bad = bad | ((~client_finite).any() & ~(n_valid > 0).any())
        else:
            bad = bad | ~jnp.isfinite(results[0]).all()
        nan_round = jnp.where((state.nan_round < 0) & bad, state.step,
                              state.nan_round)

        defense_ref = state.defense_ref
        if self._defense_ring:
            # at cohort (dispatch) granularity the ring keys off the
            # server version — commits between dispatches share a slot,
            # which only shortens the effective window, never corrupts it
            defense_ref = state.defense_ref.at[
                jnp.mod(state.step, cfg.defense_window)].set(cur_med)

        defense = self._defense_scalars(defense_stats, client_finite)

        new_state = state.replace(rng=rng, client_last_round=client_last_round,
                                  nan_round=nan_round,
                                  defense_ref=defense_ref)
        payload = {
            "sum": agg,                  # UNNORMALIZED weighted client sum
            "n_total": n_total,          # datum count of this cohort
            "results": results,
            "n_valid": n_valid,
            "download_bytes": download_bytes,
            "upload_bytes": upload_bytes,
            "client_stats": client_stats,
            "defense": defense,
            "client_finite": client_finite,
        }
        return new_state, payload

    def _merge_step(self, state: FedState, cohort_sum: jax.Array,
                    n_total: jax.Array, weight: jax.Array) -> FedState:
        """Fold one landed cohort into the buffer: pure weighted addition
        (the merge soundness condition — sketch tables and dense sums are
        both linear in the uploads). The datum count accumulates RAW,
        not discounted: the commit divides the weighted sum by the true
        datum total (FedBuff's divide-by-K), so a stale cohort's
        contribution is genuinely attenuated by its weight instead of
        the discount cancelling between numerator and denominator."""
        return state.replace(
            async_buffer=state.async_buffer + weight * cohort_sum,
            async_buffer_n=state.async_buffer_n + n_total)

    def _server_tail_fields(self, state: FedState, agg: jax.Array,
                            lr: jax.Array, server_rng: jax.Array, cs=None):
        """The split round's shared server tail (normalize happened at
        the caller): the mode's momentum+EF ``server_update``, the
        weight apply, and the byte/nan bookkeeping — ONE implementation
        consumed by both the async commit and the decode-overlap decode
        (the ``_transmit_tail`` lesson applied to the server half: the
        bit-identity contracts ride on these paths never drifting
        apart). Returns ``(replace_fields, update, Vvel, Verr)``; the
        caller owns ``rng`` advancement and any buffer handling."""
        cfg = self.cfg
        update, Vvel, Verr, _sup_mask = self._apply_server_update(
            state, agg, lr, server_rng, cs)

        if self.d_pad != cfg.grad_size:
            if update.shape[0] == cfg.grad_size:
                update = jnp.pad(update, (0, self.d_pad - cfg.grad_size))
            else:
                update = jnp.where(
                    jnp.arange(self.d_pad) < cfg.grad_size, update, 0.0)
        ps_weights = state.ps_weights - update

        coord_last_update = state.coord_last_update
        if cfg.track_bytes:
            coord_last_update = jnp.where(
                update != 0, state.step, state.coord_last_update)

        bad = ~jnp.isfinite(update).all() | ~jnp.isfinite(agg).all()
        nan_round = jnp.where((state.nan_round < 0) & bad, state.step,
                              state.nan_round)
        fields = dict(
            ps_weights=ps_weights,
            Vvelocity=Vvel,
            Verror=Verr,
            step=state.step + 1,
            coord_last_update=coord_last_update,
            nan_round=nan_round,
        )
        return fields, update, Vvel, Verr

    def _commit_step(self, state: FedState, lr: jax.Array, cs=None):
        """Server half of the round: normalize the buffered aggregate,
        run the mode's momentum+EF update (core/server.py — identical
        code to the sync round), apply it to the weights, and reset the
        buffer. ``step`` advances here: it is the server version."""
        rng, server_rng = jax.random.split(state.rng)
        total = jnp.maximum(state.async_buffer_n, 1.0)
        agg = state.async_buffer / total
        fields, update, Vvel, Verr = self._server_tail_fields(
            state, agg, lr, server_rng, cs)
        new_state = state.replace(
            rng=rng,
            async_buffer=jnp.zeros_like(state.async_buffer),
            async_buffer_n=jnp.zeros_like(state.async_buffer_n),
            **fields)
        # commit health scalars for the async_round telemetry event: the
        # post-commit EF-accumulator norms are the staleness-divergence
        # signal telemetry/health.py watches
        metrics = {
            "update_norm": jnp.linalg.norm(update),
            "error_norm": jnp.linalg.norm(Verr),
            "velocity_norm": jnp.linalg.norm(Vvel),
            "buffer_n": state.async_buffer_n,
        }
        return new_state, metrics

    def _decode_step(self, state: FedState, cohort_sum: jax.Array,
                     n_total: jax.Array, lr: jax.Array, cs=None
                     ) -> FedState:
        """Server half of the --decode_overlap split round: the commit
        step WITHOUT the async buffer — the cohort's unnormalized sum
        arrives as an argument (the buffer at K=1/M=1 is a pure pytree
        swap, so skipping it changes nothing; FedState keeps its sync
        template and checkpoints stay vintage-compatible). Dispatched as
        its own executable so the decode/top-k uncompress of round t
        runs while the host stages round t+1's client block, and a
        metrics sync on the cohort outputs returns without waiting the
        decode out. Numerically the sync round's server tail verbatim
        (losses bit-identical — dryrun-asserted, the PR-5 gate
        pattern). Returns ONLY the new state: with the per-round
        signals off under the split, nothing reads post-decode norms —
        emitting them as executable outputs would force a (d,)-sized
        reduction per round that XLA cannot DCE."""
        rng, server_rng = jax.random.split(state.rng)
        if self._reduce_in_decode:
            # the cohort deferred the table reduce to THIS executable
            # (stacked per-device partials): run the reduce-scatter
            # first, then normalize — the sync round's exact order.
            # state.step has not advanced yet, so the int8 wire's
            # quantization draws match the monolithic round's bitwise.
            cohort_sum = self._reduce_partials(
                cohort_sum, state.step if self._int8_wire else None)
        agg = cohort_sum / jnp.maximum(n_total, 1.0)
        fields, _update, _Vvel, _Verr = self._server_tail_fields(
            state, agg, lr, server_rng, cs)
        return state.replace(rng=rng, **fields)

    def _prep_lr(self, lr) -> jax.Array:
        lr = jnp.asarray(lr, jnp.float32)
        if lr.ndim == 1 and lr.shape[0] != self.d_pad:
            lr = jnp.pad(lr, (0, self.d_pad - lr.shape[0]),
                         constant_values=1.0)
        return lr

    def cohort(self, state: FedState, client_ids, batch, mask, lr
               ) -> Tuple[FedState, Dict]:
        """Dispatch one cohort's client compute (async or decode-overlap
        mode). Same argument contract as :meth:`round`; returns (state',
        payload) where payload carries the unnormalized transmitted-space
        sum the AsyncAggregator merges (or :meth:`decode` consumes)."""
        assert self._cohort is not None, \
            "neither --async_agg nor --decode_overlap is on"
        with tracing.span("cohort_dispatch"):
            return self._cohort(state, jnp.asarray(client_ids, jnp.int32),
                                batch, jnp.asarray(mask),
                                self._prep_lr(lr), self.cs)

    def merge(self, state: FedState, cohort_sum, n_total,
              weight: float) -> FedState:
        """Merge a landed cohort into the buffer with its staleness
        weight. ``weight == 1.0`` into an EMPTY buffer swaps the arrays
        in directly — bitwise-exact, the sync-equivalence path (the
        generic path computes ``buffer + w*sum``, and 0 + x flips the
        sign of -0.0 coordinates)."""
        return self._merge_jit(state, cohort_sum,
                               jnp.asarray(n_total, jnp.float32),
                               jnp.asarray(weight, jnp.float32))

    def merge_first(self, state: FedState, cohort_sum,
                    n_total) -> FedState:
        """Weight-1.0 merge into an empty buffer: a pytree swap, no
        arithmetic (see :meth:`merge`). On a mesh the cohort sum is
        re-laid-out to the buffer's canonical state sharding first — a
        pure layout copy, bitwise identical — so the commit/cohort jits'
        pinned in_shardings keep matching."""
        n_total = jnp.asarray(n_total, jnp.float32)
        if self._state_sharding is not None:
            cohort_sum = jax.device_put(cohort_sum,
                                        self._state_sharding.async_buffer)
            n_total = jax.device_put(n_total,
                                     self._state_sharding.async_buffer_n)
        return state.replace(async_buffer=cohort_sum,
                             async_buffer_n=n_total)

    def commit(self, state: FedState, lr) -> Tuple[FedState, Dict]:
        """Commit the buffered aggregate through the server step."""
        assert self._commit_jit is not None, "--async_agg is off"
        with tracing.span("commit_dispatch"):
            return self._commit_jit(state, self._prep_lr(lr), self.cs)

    def decode(self, state: FedState, cohort_sum, n_total, lr
               ) -> FedState:
        """Run the --decode_overlap server half on one cohort payload
        (core/pipeline.DecodeOverlapRound). Returns the new state."""
        assert self._decode_jit is not None, "--decode_overlap is off"
        with tracing.span("decode_dispatch"):
            return self._decode_jit(state, cohort_sum,
                                    jnp.asarray(n_total, jnp.float32),
                                    self._prep_lr(lr), self.cs)

    # -------------------------------------------------------------- user API

    def round(self, state: FedState, client_ids, batch, mask, lr
              ) -> Tuple[FedState, Dict]:
        """Run one federated round. ``client_ids``: (num_workers,) int32;
        ``batch``: pytree with leaves (num_workers, batch_size, ...);
        ``mask``: (num_workers, batch_size); ``lr``: scalar or (d,) vector."""
        lr = jnp.asarray(lr, jnp.float32)
        if lr.ndim == 1 and lr.shape[0] != self.d_pad:
            # per-param LR vector (Fixup groups): pad to the server's
            # mesh-padded length (padding coords get multiplier 1; their
            # update is identically 0)
            lr = jnp.pad(lr, (0, self.d_pad - lr.shape[0]),
                         constant_values=1.0)
        # span = the async dispatch (argument staging + jit call return);
        # device completion lands in the caller's "device_wait" span. A
        # compile shows up here as a multi-second dispatch — cross-check
        # with the `compile` event the JitWatcher emits for the same round
        with tracing.span("round_dispatch"):
            return self._round(state, jnp.asarray(client_ids, jnp.int32),
                               batch, jnp.asarray(mask), lr, self.cs,
                               self._gid)

    def val(self, state: FedState, batch, mask):
        """Masked evaluation on the current PS weights; returns
        (results_tuple, n_valid). On a mesh the batch pads up to a
        mesh-divisible item count (padding items are masked out) and
        shards over all devices — see _val_step_sharded."""
        with tracing.span("val_dispatch"):
            mask = jnp.asarray(mask)
            if self.mesh is not None:
                n = self.mesh.size
                N = mask.shape[0]
                Np = -(-N // n) * n
                if Np != N:
                    batch = jax.tree.map(
                        lambda t: jnp.pad(
                            t, [(0, Np - N)] + [(0, 0)] * (t.ndim - 1)),
                        batch)
                    mask = jnp.pad(mask, (0, Np - N))
            return self._val(state.ps_weights, batch, mask)

    def flat_weights(self, state: FedState) -> jax.Array:
        """The true-d flat weight vector (mesh padding sliced off) — the
        ONE accessor every consumer of ``state.ps_weights`` outside the
        round step must use; a padded vector does not unravel."""
        return state.ps_weights[: self.cfg.grad_size]

    def get_params(self, state: FedState):
        """Materialize the model parameter pytree from the flat PS weights
        (reference __getattr__ trick, fed_aggregator.py:372-376)."""
        return self.unravel(self.flat_weights(state))
