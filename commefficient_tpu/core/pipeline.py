"""Round input pipeline: prefetch round t+1's host work while round t runs.

Why this exists
---------------
The shared driver loop (cv_train.train) was fully synchronous per round:
assemble the batch (host gather or DeviceStore dispatch), dispatch the
round, and — at the record cadence — block on the metrics. The span/
utilization telemetry built in PRs 3-4 measured the consequence: on any
config whose input path does real host work (ImageNet's host gather, the
PERSONA pack, any no-DeviceStore fallback), ``input_wait_frac`` charges
the whole fetch to the round's critical path even though the device is
idle-waiting the entire time. The fix is the classic input pipeline: a
background thread runs ahead of the compute loop by ``depth`` rounds, so
round t+1's gather/``device_put`` overlaps round t's device execution and
the consumer's wait collapses to (ideally) zero.

Determinism contract
--------------------
Pipelining MUST NOT change what trains — ``__graft_entry__.
dryrun_multichip`` asserts bit-identical per-round losses pipelined vs
not. That holds because nothing the worker does depends on *when* it
runs:

- the sampler is iterated only by the worker (or only inline), in round
  order, so its RandomState draws are identical either way;
- per-round data augmentation randomness derives from the round index
  (``jax.random.fold_in(data_key, global_round)`` — split ahead of time,
  stateless), never from shared mutable RNG touched by two threads;
- host-transform RNGs (e.g. CifarTrain's) advance once per gather in
  round order on a single thread, exactly like the inline path;
- the jitted round consumes the same arrays in the same order — the
  pipeline never reorders or drops rounds.

``enabled=False`` (the ``--no_pipeline`` escape hatch) runs the same
fetch inline on the caller's thread: one code path builds the
:class:`RoundInput`, so the two modes differ only in *where* the fetch
runs. The jitted round step itself never sees the flag — the compiled
HLO is identical either way (pinned by tests/test_pipeline.py, the same
zero-cost-when-off contract as signals/client_stats).

Failure semantics
-----------------
An exception inside the worker's fetch is captured and re-raised on the
consumer's next ``__next__`` — the driver's existing abort/cleanup paths
fire exactly as if the fetch had been inline. ``close()`` (idempotent;
also the context-manager exit) stops the worker, drains the queue so a
blocked put wakes, and joins the thread — no leaked threads, asserted by
the tests. The worker is a daemon as a last-ditch guard: a fetch hung in
foreign code cannot wedge interpreter shutdown.

Span accounting
---------------
The worker wraps each fetch in the existing ``data_fetch`` span (the
true cost of the input path, now off the critical path); the consumer's
queue wait is the new ``data_wait`` span and is what the driver reports
as the round's ``host_s`` — so ``utilization.input_wait_frac`` measures
what the loop actually *waited*, while the ``data_fetch`` spans keep the
input path's real cost visible in the teleview timeline. Overlap shows
up as data_fetch spans (worker tid) running under round dispatch spans
(main tid).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator, NamedTuple, Optional

from commefficient_tpu.telemetry import tracing

# queue message kinds (worker -> consumer)
_ITEM, _DONE, _ERR = "item", "done", "err"


class RoundInput(NamedTuple):
    """One prefetched round, as the driver loop consumes it."""

    rnd: Any            # the sampler's Round (client_ids, idx, mask)
    global_round: int   # 1-based global round index (rng/schedule key)
    batch: Any          # batch pytree (device arrays once dispatched)
    wait_s: float       # seconds the CONSUMER waited for this input —
                        # the round's true input-starvation time
    fetch_s: float      # seconds the fetch itself took (worker wall)


class RoundPipeline:
    """Iterator of :class:`RoundInput` over one epoch's sampler.

    Parameters
    ----------
    rounds : iterable of sampler rounds (consumed on the worker thread
        when enabled, inline otherwise — never both).
    fetch : ``fetch(rnd, global_round) -> batch``. Must derive any
        randomness from ``global_round`` (or advance a private RNG once
        per call) — see the module determinism contract.
    start_round : global round counter BEFORE this epoch; yielded rounds
        are numbered ``start_round + 1 ...``.
    max_rounds : stop after this many rounds (the fractional-epoch cap);
        None = run the sampler out.
    depth : prefetch queue bound. ``depth=2`` double-buffers: one batch
        in flight to the device, one staged behind it.
    enabled : False = inline fetch on the caller's thread (identical
        outputs, zero threads — the ``--no_pipeline`` path).
    skip : consume (but never fetch) the first ``skip`` sampler rounds —
        the round-granular resume path: a run checkpointed ``skip``
        rounds into an epoch rebuilds the SAME ``(seed, epoch)`` sampler
        and fast-forwards past the rounds it already trained. The
        sampler's RandomState draws replay identically (it is iterated
        in order either way) and index-keyed fetch randomness is
        untouched, so the first yielded round is bit-identical to what
        the uninterrupted run would have trained next. Counted against
        ``max_rounds`` (the cap is the epoch's ABSOLUTE round index).
    """

    def __init__(self, rounds: Iterable, fetch: Callable[[Any, int], Any],
                 *, start_round: int, max_rounds: Optional[int] = None,
                 depth: int = 2, enabled: bool = True, skip: int = 0):
        self._rounds = iter(rounds)
        self._fetch = fetch
        self._start = int(start_round)
        self._max = max_rounds if max_rounds is None else int(max_rounds)
        if skip < 0:
            raise ValueError(f"skip must be >= 0, got {skip}")
        self._skip = int(skip)
        if enabled and depth < 1:
            # this used to silently degrade to the inline fetch — a
            # caller asking for prefetch got none and no message. The
            # config layer rejects it too (FedConfig.__post_init__);
            # this guard covers direct constructions.
            raise ValueError(
                f"RoundPipeline(depth={depth}) with enabled=True: the "
                "prefetcher needs a queue bound >= 1 (2 = double-"
                "buffered). Pass depth >= 1, or enabled=False for the "
                "inline fetch.")
        self.threaded = bool(enabled)
        self._exhausted = False
        self._thread: Optional[threading.Thread] = None
        if self.threaded:
            self._q: queue.Queue = queue.Queue(maxsize=max(int(depth), 1))
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._worker, name="round-prefetch", daemon=True)
            self._thread.start()
        else:
            self._inline = self._inline_iter()

    # ------------------------------------------------------------ iteration

    def __iter__(self) -> Iterator[RoundInput]:
        return self

    def __next__(self) -> RoundInput:
        if not self.threaded:
            return next(self._inline)
        if self._exhausted:
            raise StopIteration
        t0 = time.perf_counter()
        with tracing.span("data_wait"):
            kind, payload = self._q.get()
        wait = time.perf_counter() - t0
        if kind is _ERR:
            self._exhausted = True
            self.close()
            raise payload
        if kind is _DONE:
            self._exhausted = True
            self.close()
            raise StopIteration
        return payload._replace(wait_s=wait)

    def _inline_iter(self) -> Iterator[RoundInput]:
        for i, rnd in enumerate(self._rounds):
            if self._max is not None and i >= self._max:
                return
            if i < self._skip:
                continue          # already-trained round: advance the
            g = self._start + i + 1  # sampler, fetch nothing
            t0 = time.perf_counter()
            with tracing.span("data_fetch"):
                batch = self._fetch(rnd, g)
            dt = time.perf_counter() - t0
            # inline, the wait IS the fetch — host_s keeps its pre-
            # pipeline meaning on the --no_pipeline path
            yield RoundInput(rnd, g, batch, dt, dt)

    # --------------------------------------------------------------- worker

    def _worker(self) -> None:
        try:
            for i, rnd in enumerate(self._rounds):
                if self._max is not None and i >= self._max:
                    break
                if self._stop.is_set():
                    return
                if i < self._skip:
                    continue      # resume fast-forward (see class doc)
                g = self._start + i + 1
                t0 = time.perf_counter()
                with tracing.span("data_fetch"):
                    batch = self._fetch(rnd, g)
                item = RoundInput(rnd, g, batch, 0.0,
                                  time.perf_counter() - t0)
                if not self._put((_ITEM, item)):
                    return          # close() requested mid-epoch
        except BaseException as e:   # noqa: BLE001 — relayed, not swallowed
            self._put((_ERR, e))
            return
        self._put((_DONE, None))

    def _put(self, msg) -> bool:
        """Bounded put that a concurrent close() can always unwedge."""
        while not self._stop.is_set():
            try:
                self._q.put(msg, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    # ------------------------------------------------------------- shutdown

    def close(self, join_timeout: float = 30.0) -> None:
        """Stop the worker and reclaim the thread. Idempotent; safe from
        any driver exit path (normal exhaustion, break, abort return,
        exception unwind). Prefetched-but-unconsumed batches are simply
        dropped. NOTE: fetching them may already have advanced a
        STATEFUL host-transform RNG past the consumed prefix (index-
        keyed randomness is unaffected) — harmless for the driver, which
        only closes early on paths that stop training (abort, --test) or
        at the epoch boundary after consuming every round; do not close
        a pipeline mid-stream and keep fetching from the same dataset
        expecting inline-identical augmentation draws."""
        if not self.threaded or self._thread is None:
            return
        self._stop.set()
        # drain so a worker blocked in put() observes the stop event
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=join_timeout)
        if self._thread.is_alive():  # pragma: no cover — hung foreign fetch
            import sys
            print("WARNING: round-prefetch thread did not join within "
                  f"{join_timeout}s (fetch hung?); left as daemon",
                  file=sys.stderr)
        self._thread = None

    def __enter__(self) -> "RoundPipeline":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


class DecodeOverlapRound:
    """--decode_overlap driver adapter: one federated round as TWO
    dispatched executables instead of one (the server-side twin of this
    module's input prefetch — ROADMAP item 1's second half).

    The monolithic ``FedRuntime.round`` fuses client compute and the
    server decode/top-k uncompress into one program, so a record-cadence
    metrics sync (and the profiler's device window) waits out the decode
    even though the metrics are client-block outputs. Here round t is
    dispatched as ``cohort`` (the client half — identical code to the
    sync round's client block) immediately followed by ``decode`` (the
    server half — the sync round's server tail verbatim, see
    FedRuntime._decode_step): jax's async dispatch returns both at once,
    a ``block_until_ready`` on the returned metrics completes when the
    CLIENT executable finishes, and the decode executes while this loop
    (and the RoundPipeline prefetcher above) stages round t+1's input.
    Losses are bit-identical to the monolithic round for every
    configuration that consumes no per-round randomness (no DP — the
    split advances ``state.rng`` by a W+1 split then a 2-split instead
    of one W+2 split, the async_agg K=1/M=1 caveat verbatim); asserted
    by ``__graft_entry__.dryrun_multichip`` the same way PR 5 gated the
    input pipeline.

    The returned metrics dict matches ``FedRuntime.round``'s contract
    (``signals`` is None — the split decouples the quantities the
    signal diagnostics compare; the runtime prints the NOTE once).
    """

    def __init__(self, runtime):
        if not runtime.cfg.decode_overlap:
            raise ValueError(
                "DecodeOverlapRound needs a runtime built with "
                "cfg.decode_overlap=True (the cohort/decode executables "
                "are only jitted then)")
        self.runtime = runtime

    def init_state(self):
        """Delegates to the runtime — the adapter is drop-in for the
        driver/bench loops that build their state through the object
        they call ``round`` on (bench_common.timed_rounds)."""
        return self.runtime.init_state()

    def round(self, state, client_ids, batch, mask, lr):
        """Same contract as ``FedRuntime.round`` (state', metrics)."""
        state, payload = self.runtime.cohort(state, client_ids, batch,
                                             mask, lr)
        state = self.runtime.decode(state, payload["sum"],
                                    payload["n_total"], lr)
        metrics = {
            "results": payload["results"],
            "n_valid": payload["n_valid"],
            "download_bytes": payload["download_bytes"],
            "upload_bytes": payload["upload_bytes"],
            "signals": None,
            "layer_signals": None,
            "client_stats": payload["client_stats"],
            "defense": payload["defense"],
            "client_finite": payload["client_finite"],
        }
        return state, metrics
